#!/usr/bin/env python
"""Headline benchmark: EM iters/sec on the 10k-series x 500-step 10-factor DFM.

This is the BASELINE.json:2 metric.  Prints exactly ONE JSON line to stdout:

    {"metric": ..., "value": N, "unit": "iters/sec", "vs_baseline": N}

``vs_baseline`` is the speedup over the single-threaded NumPy float64 CPU
reference running the SAME information-form algorithm (the dense O(N^3)
filter is infeasible at N=10k, and an O(N k^2) CPU baseline is the honest
comparison — BASELINE.json:5 targets >=50x vs single-threaded CPU).
Diagnostics go to stderr.  Shapes can be overridden for smoke tests via
DFM_BENCH_N / DFM_BENCH_T / DFM_BENCH_K / DFM_BENCH_ITERS.
"""

import json
import os
import sys
import time

# Pin the CPU baseline to one thread BEFORE numpy/BLAS load.
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")

import numpy as np  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def measure_riccati_mixing(p, tol=1e-12, max_steps=512) -> int:
    """Steps until the predicted-covariance recursion stops moving (host)."""
    Lam = np.asarray(p.Lam, np.float64)
    A = np.asarray(p.A, np.float64)
    Q = np.asarray(p.Q, np.float64)
    C = (Lam / np.asarray(p.R, np.float64)[:, None]).T @ Lam
    k = A.shape[0]
    P = np.asarray(p.P0, np.float64)
    for t in range(1, max_steps + 1):
        Pf = np.linalg.solve(np.eye(k) + P @ C, P)
        Pn = A @ (0.5 * (Pf + Pf.T)) @ A.T + Q
        if np.max(np.abs(Pn - P)) <= tol * max(np.max(np.abs(Pn)), 1e-30):
            return t
        P = Pn
    return max_steps


def main():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))
    # 50 fused iterations ~= one realistic fit-to-convergence call; the
    # axon tunnel adds a large fixed per-invocation cost (~60-100 ms
    # measured), so short programs mis-state the sustained rate.
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 50))
    cpu_iters = max(2, min(3, n_iters))

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp

    rng = np.random.default_rng(0)
    log(f"simulating {N}x{T}, k={k} ...")
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    log("PCA init ...")
    p0 = cpu_ref.pca_init(Y, k)

    # --- single-threaded CPU baseline (info-form NumPy) ---
    log(f"CPU baseline: {cpu_iters} info-form EM iters, 1 thread ...")
    p = p0.copy()
    t0 = time.perf_counter()
    for _ in range(cpu_iters):
        p, ll_cpu, _ = cpu_ref.em_step(Y, p, filter="info")
    cpu_secs = (time.perf_counter() - t0) / cpu_iters
    log(f"CPU: {cpu_secs:.3f} s/iter ({1.0 / cpu_secs:.4f} iters/sec), "
        f"loglik {ll_cpu:.2f}")

    # --- TPU/JAX path: fused scan over EM iterations ---
    import jax
    import jax.numpy as jnp
    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.ssm.params import SSMParams as JP

    dev = jax.devices()[0]
    log(f"JAX device: {dev.platform} ({dev.device_kind})")
    dtype = jnp.float32
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)
    # Steady-state accelerated E-step (exact-to-tolerance; see ssm/steady.py),
    # overridable for A/B runs via DFM_BENCH_FILTER=info|pit|ss.  tau comes
    # from measuring the actual covariance-recursion convergence at the init
    # params on host (k x k per step — microseconds), with a 2x margin for
    # parameter drift across EM iterations.
    tau = 2 * measure_riccati_mixing(p0)
    tau = int(np.clip(tau, 16, 192))
    log(f"steady-state tau={tau}")
    cfg = EMConfig(filter=os.environ.get("DFM_BENCH_FILTER", "ss"), tau=tau)

    # NOTE: jax.block_until_ready is a no-op on the axon PJRT plugin
    # (measured: returns in 0.1 ms while the program is still running);
    # a device->host transfer is the only reliable execution barrier here.
    def timed_run(Yj):
        t0 = time.perf_counter()
        _, lls, _ = em_fit_scan(Yj, pj, n_iters, cfg=cfg)
        lls = np.asarray(lls)  # forces completion
        return time.perf_counter() - t0, lls

    with jax.default_matmul_precision("highest"):
        log(f"compiling fused {n_iters}-iter EM scan ...")
        t0 = time.perf_counter()
        compile_secs, lls = timed_run(Yj)
        log(f"first call (compile+run): {compile_secs:.2f} s")
        reps = [timed_run(Yj)[0] for _ in range(3)]
        log(f"reps: {[f'{r:.3f}' for r in reps]} s")
        run_secs = min(reps)
    tpu_secs = run_secs / n_iters
    ll_tpu = float(lls[min(cpu_iters, n_iters) - 1])
    log(f"TPU: {tpu_secs * 1e3:.1f} ms/iter ({1.0 / tpu_secs:.2f} iters/sec)")
    rel = abs(ll_tpu - ll_cpu) / abs(ll_cpu)
    log(f"loglik check at iter {cpu_iters}: cpu={ll_cpu:.2f} "
        f"tpu={ll_tpu:.2f} rel={rel:.2e}")

    value = 1.0 / tpu_secs
    print(json.dumps({
        "metric": f"em_iters_per_sec_{N}x{T}_k{k}",
        "value": round(value, 4),
        "unit": "iters/sec",
        "vs_baseline": round(value * cpu_secs, 2),
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Headline benchmark: EM iters/sec AND loglik evals/sec on the
10k-series x 500-step 10-factor DFM (both halves of the BASELINE.json:2
metric).  Prints exactly ONE JSON line to stdout:

    {"metric": ..., "value": N, "unit": "iters/sec", "vs_baseline": N,
     "loglik_evals_per_sec": N, "loglik_vs_baseline": N,
     "loglik_rel_err_iter3": x, "loglik_rel_err_iter50": x,     # precise
     "loglik_rel_err_fast_iter3": x, "loglik_rel_err_fast_iter50": x,
     "accuracy_ok": bool}

The 1e-5 contract (BASELINE.json:5) is checked with the reporting-grade
f64-on-device evaluator (``ssm.info_filter.loglik_eval``) at the f32
trajectory's params — measuring whether the TPU TRAJECTORY drifted
(~1e-10 measured).  The f32 in-loop loglik's own evaluation noise
(~1e-5 at the headline shape, a cancellation artifact — see
loglik_from_terms) is reported alongside as the ``fast`` figures.

``vs_baseline`` is the speedup over the single-threaded NumPy float64 CPU
reference running the SAME information-form algorithm (the dense O(N^3)
filter is infeasible at N=10k, and an O(N k^2) CPU baseline is the honest
comparison — BASELINE.json:5 targets >=50x vs single-threaded CPU).

Measurement hardening (VERDICT r2 "what's weak" 1/2; r4 item 1):
  - the CPU baseline is the MEDIAN of several timed passes (each restarted
    from the PCA init), not one 3-iteration sample — round-to-round the old
    single sample swung +/-25%, turning the >=50x contract into a coin flip;
  - the 1e-5 loglik contract (BASELINE.json:5) is checked at iteration 3
    AND at iteration 50, where float32 drift across the fused scan peaks;
  - ``value`` is the SUSTAINED device rate from a two-point measurement
    (fused scans at n/3 and n iterations; the slope isolates per-iteration
    device time from the ~60-100 ms/program tunnel dispatch, which the CPU
    baseline does not pay).  The dispatch-inclusive total/n rate at
    DFM_BENCH_ITERS=150 — the r1-r4 headline figure — is reported alongside
    as ``iters_per_sec_with_dispatch``, and the dispatch cost itself as
    ``dispatch_ms_per_program``.

Diagnostics go to stderr.  Shapes/lengths can be overridden for smoke tests
via DFM_BENCH_N / DFM_BENCH_T / DFM_BENCH_K / DFM_BENCH_ITERS /
DFM_BENCH_CPU_TIMING_ITERS / DFM_BENCH_CPU_CHECK_ITERS.
"""

import json
import os
import sys
import time

# Pin the CPU baseline to one thread BEFORE numpy/BLAS load.
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")

import numpy as np  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))
    # 150 fused iterations amortize the fixed per-dispatch cost of the
    # tunneled device (~60-100 ms measured) to well under the per-iteration
    # compute, so the reported rate is the SUSTAINED rate of a long fit.
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 150))
    # CPU timing: median over `passes` restarts of `timing_iters` iters.
    cpu_timing_iters = int(os.environ.get("DFM_BENCH_CPU_TIMING_ITERS", 5))
    cpu_passes = 3
    # CPU accuracy chain: run to iteration 50 (or n_iters if smaller) once.
    cpu_check_iters = int(os.environ.get(
        "DFM_BENCH_CPU_CHECK_ITERS", min(50, n_iters)))

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp

    rng = np.random.default_rng(0)
    log(f"simulating {N}x{T}, k={k} ...")
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    log("PCA init ...")
    p0 = cpu_ref.pca_init(Y, k)

    # --- single-threaded CPU baselines (info-form NumPy, f64) ---
    log(f"CPU EM baseline: {cpu_passes} passes x {cpu_timing_iters} "
        "info-form EM iters, 1 thread ...")
    pass_secs = []
    for _ in range(cpu_passes):
        p = p0.copy()
        t0 = time.perf_counter()
        for _ in range(cpu_timing_iters):
            p, _, _ = cpu_ref.em_step(Y, p, filter="info")
        pass_secs.append((time.perf_counter() - t0) / cpu_timing_iters)
    cpu_secs = float(np.median(pass_secs))
    log(f"CPU EM: {cpu_secs:.3f} s/iter ({1.0 / cpu_secs:.4f} iters/sec); "
        f"passes {[f'{s:.3f}' for s in pass_secs]}")

    log(f"CPU loglik-eval baseline: {cpu_passes} passes x 2 filter passes ...")
    eval_secs = []
    for _ in range(cpu_passes):
        t0 = time.perf_counter()
        for _ in range(2):
            kf = cpu_ref.kalman_filter_info(Y, p0)
        eval_secs.append((time.perf_counter() - t0) / 2)
    cpu_eval_secs = float(np.median(eval_secs))
    log(f"CPU loglik eval: {cpu_eval_secs:.3f} s "
        f"({1.0 / cpu_eval_secs:.4f} evals/sec)")

    # --- CPU accuracy chain: logliks at iterations 1..cpu_check_iters ---
    log(f"CPU accuracy chain: {cpu_check_iters} iters ...")
    p = p0.copy()
    cpu_lls = []
    for _ in range(cpu_check_iters):
        p, ll, _ = cpu_ref.em_step(Y, p, filter="info")
        cpu_lls.append(ll)

    # --- TPU/JAX path: fused scan over EM iterations ---
    import jax
    # x64 ON: data/params stay explicitly float32 (the MXU path), but the
    # small (T,)-sized loglik assembly upgrades to f64 (see
    # info_filter.loglik_from_terms) — the pieces cancel ~100x, so f32
    # assembly alone costs ~6e-6 of the 1e-5 contract.  This is also the
    # dtype regime the test suite runs under (tests/conftest.py).
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from functools import partial
    from jax import lax
    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer, shape_key
    from dfm_tpu.ssm.info_filter import info_filter
    from dfm_tpu.ssm.steady import ss_filter
    from dfm_tpu.ssm.params import SSMParams as JP

    # Persistent compile cache: CLI entry points opt into the default
    # .dfm_cache/ dir (DFM_COMPILE_CACHE overrides; "" disables) so a
    # fresh bench process re-running the same shapes skips XLA compiles —
    # the warm/cold gap shows up in compile_proxy_s and the e2e warm fit.
    from dfm_tpu.pipeline import setup_compile_cache
    cache_dir = setup_compile_cache()
    log(f"compile cache: {cache_dir or 'disabled'}")

    dev = jax.devices()[0]
    log(f"JAX device: {dev.platform} ({dev.device_kind})")
    dtype = jnp.float32
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)
    # Steady-state accelerated E-step (exact-to-tolerance; see ssm/steady.py),
    # overridable for A/B runs via DFM_BENCH_FILTER=info|pit|ss.  tau comes
    # from measuring the actual covariance-recursion convergence at the init
    # params on host (``ssm.steady.auto_tau``: k x k per step — microseconds
    # — with a 2x margin for parameter drift across EM iterations); the
    # precise-loglik contract checks below validate the choice end to end.
    from dfm_tpu.ssm.steady import auto_tau
    tau = int(os.environ.get("DFM_BENCH_TAU", auto_tau(p0)))
    log(f"steady-state tau={tau}")
    filt = os.environ.get("DFM_BENCH_FILTER", "ss")
    cfg = EMConfig(filter=filt, tau=tau)

    # Pure loglik evaluations, fused: n back-to-back filter passes in one
    # program.  The params are re-derived each step through a multiply by
    # (1 + 0*prev_loglik) — bitwise identity, but a loop-carried data
    # dependency XLA cannot simplify away (x*0 is unsafe for floats), so
    # neither CSE nor loop-invariant code motion can hoist the filter.
    from dfm_tpu.ssm.parallel_filter import pit_filter
    filter_fn = {"ss": partial(ss_filter, tau=tau),
                 "pit": pit_filter}.get(filt, info_filter)
    log(f"loglik-eval filter: {getattr(filter_fn, 'func', filter_fn).__name__}")

    # Telemetry: DFM_TRACE=<path> seeds an ambient file tracer (the same
    # one the instrumented library code picks up); without it, a fresh
    # in-memory tracer still counts dispatches/recompiles for the JSON
    # line.  Event emission is list-append + clock read — no host syncs —
    # and the per-dispatch cost is fixed, so the two-point slope (the
    # headline `value`) is unaffected either way.
    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    @partial(jax.jit, static_argnames=("n_evals",))
    def loglik_scan(Yj, pj, n_evals):
        def body(p_c, _):
            ll = filter_fn(Yj, p_c).loglik
            eps = jnp.zeros((), Yj.dtype) * ll.astype(Yj.dtype)
            p_next = jax.tree.map(lambda a: a * (1.0 + eps), p_c)
            return p_next, ll
        _, lls = lax.scan(body, pj, None, length=n_evals)
        return lls

    # NOTE: jax.block_until_ready is a no-op on the axon PJRT plugin
    # (measured: returns in 0.1 ms while the program is still running);
    # a device->host transfer is the only reliable execution barrier here.
    #
    # Two-point measurement (docs/PERF.md "fixed vs marginal"): every
    # program execution pays a ~60-100 ms FIXED dispatch/transfer cost
    # through the tunnel regardless of content, so a single total/n
    # division reports mostly tunnel latency at small n.  Timing the SAME
    # fused scan at n_iters and 3*n_iters separates the two:
    #     sustained rate = (n_hi - n_lo) / (t_hi - t_lo)
    # — the device rate a fit-to-convergence call sustains once the per-
    # chunk dispatch is amortized (the CPU baseline has no analogous fixed
    # cost, so this is also the apples-to-apples comparison); the
    # dispatch-inclusive rate at n_iters is reported alongside.  The hi/lo
    # executions are INTERLEAVED and the slope is the median of per-pair
    # slopes: run-to-run drift on this tunnel (+/-30% across a few seconds,
    # docs/PERF.md item 6) would otherwise swamp the difference.
    n_lo = n_iters
    n_hi = 3 * n_iters

    def timed_em(n):
        t0 = time.perf_counter()
        with tracer.dispatch("em_fit_scan",
                             shape_key(Yj, cfg.filter, f"iters{n}"),
                             barrier=True, n_iters=n):
            _, lls, _ = em_fit_scan(Yj, pj, n, cfg=cfg)
            lls = np.asarray(lls)  # forces completion
        return time.perf_counter() - t0, lls

    def timed_eval(n):
        t0 = time.perf_counter()
        with tracer.dispatch("loglik_scan",
                             shape_key(Yj, filt, f"evals{n}"),
                             barrier=True, n_iters=n):
            lls = np.asarray(loglik_scan(Yj, pj, n))
        return time.perf_counter() - t0, lls

    def two_point(timed, label):
        log(f"compiling fused {label} scans ({n_lo} and {n_hi}) ...")
        t0 = time.perf_counter()
        _, lls = timed(n_lo)
        log(f"first call (compile+run): {time.perf_counter() - t0:.2f} s")
        timed(n_hi)  # compile the long program too
        pairs = [(timed(n_hi)[0], timed(n_lo)[0]) for _ in range(5)]
        log(f"{label} (hi, lo) pairs: "
            f"{[(f'{a:.3f}', f'{b:.3f}') for a, b in pairs]} s")
        slopes = [(a - b) / (n_hi - n_lo) for a, b in pairs]
        med = float(np.median(slopes))
        t_lo = float(np.median([b for _, b in pairs]))
        slope_ok = med > 0
        if not slope_ok:
            # Jitter swamped the hi-lo signal (possible in smoke-size runs
            # where the whole program is a few ms): fall back to the
            # dispatch-inclusive figure instead of reporting a fantasy rate.
            log(f"WARNING: {label} two-point slope non-positive "
                f"({med:.2e}); falling back to total/n for the sustained "
                "figure")
            med = t_lo / n_lo
        dispatch_ms = max(t_lo - n_lo * med, 0.0) * 1e3
        return t_lo / n_lo, med, dispatch_ms, slope_ok, lls

    with activate(tracer), jax.default_matmul_precision("highest"):
        (tpu_secs_e2e, tpu_secs, em_dispatch_ms, em_slope_ok,
         lls) = two_point(timed_em, "EM")
        (tpu_eval_secs_e2e, tpu_eval_secs, ev_dispatch_ms, ev_slope_ok,
         eval_lls) = two_point(timed_eval, "loglik-eval")

    log(f"TPU EM: {tpu_secs * 1e3:.3f} ms/iter sustained "
        f"({1.0 / tpu_secs:.1f} iters/sec); with dispatch at {n_iters} "
        f"iters: {tpu_secs_e2e * 1e3:.3f} ms/iter "
        f"({1.0 / tpu_secs_e2e:.1f}/sec); "
        f"dispatch ~{em_dispatch_ms:.0f} ms/program")
    log(f"TPU loglik eval: {tpu_eval_secs * 1e3:.3f} ms/eval sustained "
        f"({1.0 / tpu_eval_secs:.1f} evals/sec); with dispatch "
        f"{tpu_eval_secs_e2e * 1e3:.3f} ms/eval")
    # Fused-eval self-consistency: every eval is at the same params.
    ev_spread = float(np.max(eval_lls) - np.min(eval_lls))
    log(f"eval loglik spread across fused repeats: {ev_spread:.3g}")

    # --- accuracy: the 1e-5 contract at iteration 3 AND iteration 50 ---
    # Two numbers per checkpoint (see ssm.info_filter.loglik_eval):
    #   fast    — the f32 in-loop loglik the fused scan emitted (what EM's
    #             convergence check consumes; carries the f32 evaluation
    #             noise floor, ~1e-5 at this shape);
    #   precise — the reporting-grade f64-on-device evaluation at the SAME
    #             f32-trajectory params (this is the contract check: it
    #             measures whether the TPU trajectory itself drifted).
    from dfm_tpu.ssm.info_filter import loglik_eval

    def rel_fast(it):
        if it > min(len(cpu_lls), len(lls)):
            return None
        return float(abs(lls[it - 1] - cpu_lls[it - 1])
                     / abs(cpu_lls[it - 1]))

    def rel_precise(it):
        # cpu_lls[it-1] is the loglik at the params AFTER it-1 EM updates.
        if it > len(cpu_lls):
            return None
        with jax.default_matmul_precision("highest"):
            p_it = (pj if it == 1
                    else em_fit_scan(Yj, pj, it - 1, cfg=cfg)[0])
            ll = loglik_eval(Y, p_it.to_numpy())
        return float(abs(ll - cpu_lls[it - 1]) / abs(cpu_lls[it - 1]))

    last = min(50, cpu_check_iters)
    rel3_f, rel50_f = rel_fast(3), rel_fast(last)
    log("precise (f64-on-device) loglik checks ...")
    rel3_p, rel50_p = rel_precise(3), rel_precise(last)

    def fmt(r):
        return "n/a" if r is None else f"{r:.2e}"  # None: run too short

    for name, rf, rp in (("iter3", rel3_f, rel3_p),
                         (f"iter{last}", rel50_f, rel50_p)):
        log(f"loglik rel err at {name}: fast={fmt(rf)} precise={fmt(rp)}")
    checks = [r for r in (rel3_p, rel50_p) if r is not None]
    accuracy_ok = bool(checks) and all(r < 1e-5 for r in checks)
    if not accuracy_ok:
        log("WARNING: 1e-5 loglik contract NOT met (BASELINE.json:5)"
            if checks else
            "WARNING: run too short to check the loglik contract")

    # --- end-to-end warm fit through the pipelined dispatch driver ---
    # Cold pass compiles the chunk program (or loads it from the compile
    # cache); the warm pass is the figure: full fit() wall including the
    # host driver, with depth-2 speculative chunk issue hiding the tunnel
    # latency.  tol=0 pins the iteration count so the rate is stable.
    from dfm_tpu.api import DynamicFactorModel, fit as api_fit
    e2e_iters = int(os.environ.get("DFM_BENCH_E2E_ITERS", min(30, n_iters)))
    e2e_model = DynamicFactorModel(n_factors=k, standardize=False)

    def timed_fit():
        # Internal timing probe: keep it out of the run registry (DFM_RUNS)
        # — the bench appends its own headline RunRecord below.
        runs_env = os.environ.pop("DFM_RUNS", None)
        try:
            t0 = time.perf_counter()
            r = api_fit(e2e_model, Y, max_iters=e2e_iters, tol=0.0, init=p0,
                        pipeline=2, telemetry=True)
            return time.perf_counter() - t0, r
        finally:
            if runs_env is not None:
                os.environ["DFM_RUNS"] = runs_env
    log(f"e2e fit ({e2e_iters} iters, pipeline depth 2): cold pass ...")
    t_cold, _ = timed_fit()
    t_warm, e2e_res = timed_fit()
    e2e_tel = e2e_res.telemetry or {}
    blocking = e2e_tel.get("blocking_transfers")
    log(f"e2e fit: cold {t_cold:.2f} s, warm {t_warm:.2f} s "
        f"({e2e_res.n_iters / t_warm:.2f} iters/sec end to end); "
        f"{blocking} blocking transfers")

    # --- dispatch-free fused fit: whole fit->smooth->forecast in ONE
    # program (estim.fused).  One backend INSTANCE across cold/warm so the
    # warm refit (warm_start=cold result, same panel object) re-enters the
    # donated executable with zero h2d re-upload — the serving-path figure.
    from dfm_tpu.api import TPUBackend
    fused_b = TPUBackend()

    def timed_fused(warm=None):
        runs_env = os.environ.pop("DFM_RUNS", None)
        try:
            t0 = time.perf_counter()
            r = api_fit(e2e_model, Y, max_iters=e2e_iters, tol=0.0,
                        init=p0 if warm is None else None, warm_start=warm,
                        fused=True, backend=fused_b, telemetry=True)
            return time.perf_counter() - t0, r
        finally:
            if runs_env is not None:
                os.environ["DFM_RUNS"] = runs_env
    log(f"fused e2e fit ({e2e_iters} iters, one program): cold pass ...")
    t_fcold, fused_cold = timed_fused()
    t_fwarm, fused_res = timed_fused(warm=fused_cold)
    fused_tel = fused_res.telemetry or {}
    dispatches_per_fit = fused_tel.get("dispatches")
    log(f"fused e2e fit: cold {t_fcold:.2f} s, warm {t_fwarm:.2f} s "
        f"({fused_res.n_iters / t_fwarm:.2f} iters/sec end to end); "
        f"{dispatches_per_fit} dispatches, "
        f"{fused_tel.get('blocking_transfers')} blocking transfers")

    # --- auto-tuning advisor: seed ProfileRecords from the measurements
    # this run already made (no extra profiling fits), then close the loop
    # with one fit(auto=True) probe — its predicted-vs-realized wall is
    # the advice_rel_err model-drift metric obs.regress gates.
    from dfm_tpu.obs import store as obs_store
    advice = None
    runs_d = obs_store.runs_dir()
    if runs_d is not None:
        from dfm_tpu.obs.profile import profile_record
        devstr = f"{dev.platform} ({dev.device_kind})"
        reg = obs_store.RunStore(runs_d)
        for rec in (
            # Coefficients only (no warm_wall_s anchor): the two-point
            # sustained rate + per-program dispatch cost.
            profile_record(
                "chunked", N, T, k, iters=n_iters, chunk=8,
                metrics={"sustained_ms_per_iter": 1e3 * tpu_secs,
                         "dispatch_ms_per_program": em_dispatch_ms},
                device=devstr),
            profile_record(
                "pipelined", N, T, k, iters=e2e_iters, chunk=8, depth=2,
                metrics={"warm_wall_s": t_warm, "cold_wall_s": t_cold,
                         "ms_per_iter_warm": 1e3 * t_warm / e2e_iters},
                device=devstr),
            profile_record(
                "fused", N, T, k, iters=e2e_iters, chunk=8,
                metrics={"warm_wall_s": t_fwarm, "cold_wall_s": t_fcold,
                         "ms_per_iter_warm": 1e3 * t_fwarm / e2e_iters},
                device=devstr),
        ):
            reg.append(rec)
        log("advisor: 3 profiles recorded; fit(auto=True) probe ...")
        t0 = time.perf_counter()
        r_auto = api_fit(e2e_model, Y, max_iters=e2e_iters, tol=0.0,
                         init=p0, backend=fused_b, auto=True,
                         telemetry=True)
        t_auto = time.perf_counter() - t0
        advice = r_auto.advice or {}
        log(f"advisor: plan={advice.get('engine')} "
            f"predicted {advice.get('predicted_wall_s', 0.0):.2f} s, "
            f"realized {t_auto:.2f} s "
            f"(rel err {advice.get('rel_err', float('nan')):.2f})")
    else:
        log("advisor: run registry disabled (DFM_RUNS=\"\"), skipping")

    # Telemetry roll-up (events flush eagerly, so no close needed before
    # process exit — and the ambient tracer may outlive this function).
    ts = tracer.summary()
    log(f"telemetry: {ts['dispatches']} dispatches, "
        f"{ts['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    value = 1.0 / tpu_secs
    from dfm_tpu.obs.store import new_run_id
    payload = {
        # Round 5 renamed the metric: `value` is now the SUSTAINED device
        # rate (two-point slope — the dispatch-free figure the CPU baseline
        # is actually comparable to); the r1-r4 dispatch-inclusive total/n
        # figure continues under `iters_per_sec_with_dispatch`.  The metric
        # string carries the definition so longitudinal consumers cannot
        # silently mix the two.
        "metric": f"em_iters_per_sec_sustained_{N}x{T}_k{k}",
        "value": round(value, 4),
        "unit": "iters/sec",
        "value_definition": ("sustained device rate, per-program dispatch "
                             "excluded (see docs/PERF.md round-5 metric "
                             "note)" if em_slope_ok else
                             "FALLBACK total/n (two-point slope was "
                             "jitter-dominated)"),
        "sustained_measurement_ok": bool(em_slope_ok and ev_slope_ok),
        "vs_baseline": round(value * cpu_secs, 2),
        "iters_per_sec_with_dispatch": round(1.0 / tpu_secs_e2e, 4),
        "dispatch_ms_per_program": round(em_dispatch_ms, 1),
        "n_iters_fused": n_iters,
        "loglik_evals_per_sec": round(1.0 / tpu_eval_secs, 4),
        "loglik_vs_baseline": round(cpu_eval_secs / tpu_eval_secs, 2),
        "loglik_evals_per_sec_with_dispatch": round(
            1.0 / tpu_eval_secs_e2e, 4),
        "loglik_rel_err_iter3": rel3_p,
        "loglik_rel_err_iter50": rel50_p,
        "loglik_rel_err_fast_iter3": rel3_f,
        "loglik_rel_err_fast_iter50": rel50_f,
        "accuracy_ok": accuracy_ok,
        # End-to-end warm fit() wall rate (host driver + pipelined
        # dispatch; depth 2) and the host-barrier count it paid — the
        # pipelining win is blocking_transfers ~halving vs chunk count.
        "e2e_warm_fit_iters_per_sec": round(
            float(e2e_res.n_iters) / t_warm, 4),
        "blocking_transfers": blocking,
        # Dispatch-free serving path: warm fused-program refit rate and
        # how many programs that one fit() dispatched (target <= 2).
        "e2e_fused_fit_iters_per_sec": round(
            float(fused_res.n_iters) / t_fwarm, 4),
        "dispatches_per_fit": dispatches_per_fit,
        # Latency percentiles over this run's timed dispatch spans, and
        # the advisor's prediction error (None when DFM_RUNS="" disabled
        # the registry and no plan could be calibrated).
        "p99_dispatch_ms": (round(ts["dispatch_percentiles_ms"]["p99"], 3)
                            if ts.get("dispatch_percentiles_ms") else None),
        "advice_rel_err": (round(float(advice["rel_err"]), 4)
                           if advice and advice.get("rel_err") is not None
                           else None),
        "advice_engine": advice.get("engine") if advice else None,
        # Distinct fused lengths are distinct XLA programs, so the two-point
        # protocol itself compiles several: recompiles > 0 here is expected
        # and truthful (see obs/trace.py shape_key).
        "dispatches": ts["dispatches"],
        "recompiles": ts["recompiles"],
        # Registry identity: obs.regress addresses this exact run by id.
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    _record_run(payload, dev)


def _record_run(payload, dev):
    """Append this run to the perf-observatory registry (obs.store).
    Default dir .dfm_runs/; DFM_RUNS overrides, DFM_RUNS="" disables.
    Diagnostics only ever go to stderr — the one-JSON-line stdout
    contract stays intact."""
    from dfm_tpu.obs import store as obs_store
    d = obs_store.runs_dir()
    if d is None:
        return
    try:
        rec = obs_store.record_from_bench_json(
            payload, device=f"{dev.platform} ({dev.device_kind})")
        obs_store.RunStore(d).append(rec)
        log(f"run {payload['run_id']} recorded in {d}/ "
            "(diff: python -m dfm_tpu.obs.regress)")
    except Exception as e:  # registry failure must not fail the bench
        log(f"WARNING: run registry append failed: {e}")


if __name__ == "__main__":
    main()

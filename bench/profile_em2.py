#!/usr/bin/env python
"""Fixed-vs-marginal cost of the fused EM scan: time at several n_iters and
fit a line.  The slope is the TRUE per-iteration device cost; the intercept
is the per-dispatch overhead (tunnel + program launch) that ``bench.py``
amortizes over its 150 fused iterations.  Also slopes for the isolated
components of ``bench.profile_em``.  Run: ``python -m bench.profile_em2``."""

import os
import sys
import time
from functools import partial

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.ssm.params import SSMParams as JP
    from dfm_tpu.ssm import steady
    from dfm_tpu.ssm.info_filter import obs_stats, loglik_terms_local
    from dfm_tpu.ops.scan import blocked_scan

    rng = np.random.default_rng(0)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Y, k)
    dtype = jnp.float32
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)

    def timed(fn, *args):
        np.asarray(jax.tree.leaves(fn(*args))[0])
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(jax.tree.leaves(fn(*args))[0])
            reps.append(time.perf_counter() - t0)
        return min(reps)

    def chain(x, scalar):
        return x * (1.0 + jnp.zeros((), x.dtype) * scalar.astype(x.dtype))

    @partial(jax.jit, static_argnames=("n",))
    def trivial_scan(p, n):
        def body(carry, _):
            out = jnp.sum(p.A @ (p.A * (1.0 + 0.0 * carry)))
            return out, out
        return lax.scan(body, jnp.zeros((), p.A.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def panel_scan(Yj, p, n):
        def body(carry, _):
            Lam, R = chain(p.Lam, carry), p.R
            stats = obs_stats(Yj, Lam, R)
            x_fake = stats.b @ jnp.linalg.inv(stats.C)
            quad_R, U = loglik_terms_local(Yj, Lam, R, x_fake, None)
            S_yf = Yj.T @ x_fake
            Ysq = jnp.einsum("ti,ti->i", Yj, Yj)
            out = (jnp.sum(quad_R) + jnp.sum(U) + jnp.sum(S_yf)
                   + jnp.sum(Ysq) + jnp.sum(stats.b)).astype(Yj.dtype)
            return out, out
        return lax.scan(body, jnp.zeros((), Yj.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n", "tau"))
    def cov_scan(p, C, n, tau):
        def body(carry, _):
            Cc = chain(C, carry)
            Pp, Pf, M, ldG, delta = steady._cov_path(
                Cc, p.A, p.Q, p.P0, tau, dtype)
            out = (jnp.sum(Pp[-1]) + jnp.sum(Pf[-1]) + jnp.sum(M[-1])
                   + jnp.sum(ldG) + delta)
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_scan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = blocked_scan(steady._affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            Jr, cr = blocked_scan(
                lambda late, early: steady._affine_combine(late, early),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_ascan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = lax.associative_scan(
                lambda a, bb_: steady._affine_combine(a, bb_),
                (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            Jr, cr = lax.associative_scan(
                lambda a, bb_: steady._affine_combine(a, bb_),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    C0 = np.asarray((p0.Lam / p0.R[:, None]).T @ p0.Lam, np.float32)
    Cj = jnp.asarray(C0)
    b0 = jnp.asarray(rng.standard_normal((T, k)), dtype)
    M0 = jnp.asarray(
        np.broadcast_to(np.asarray(p0.A, np.float32) * 0.5, (T, k, k)))
    Pf0 = jnp.asarray(np.broadcast_to(np.eye(k, dtype=np.float32) * 0.3,
                                      (T, k, k)))

    ns = (50, 150, 300, 600)
    with jax.default_matmul_precision("highest"):
        def slope(name, f):
            ts = [timed(f, n) for n in ns]
            A = np.vstack([np.ones(len(ns)), np.asarray(ns)]).T
            (fixed, marg), *_ = np.linalg.lstsq(A, np.asarray(ts),
                                                rcond=None)
            print(f"{name:34s} fixed {fixed * 1e3:7.1f} ms   "
                  f"marginal {marg * 1e3:7.3f} ms/iter   "
                  f"({[f'{t:.3f}' for t in ts]})")
            return fixed, marg

        slope("means", lambda n: means_scan(b0, M0, Pf0, n))
        slope("means assoc", lambda n: means_ascan(b0, M0, Pf0, n))
        for tau in (8, 16):
            slope(f"cov tau={tau}",
                  lambda n, tau=tau: cov_scan(pj, Cj, n, tau))
        for tau in (8, 16):
            cfg = EMConfig(filter="ss", tau=tau)
            slope(f"FULL em tau={tau}",
                  lambda n, cfg=cfg: em_fit_scan(Yj, pj, n, cfg=cfg)[1])


if __name__ == "__main__":
    main()

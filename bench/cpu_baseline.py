#!/usr/bin/env python
"""Single-threaded CPU baseline for one BASELINE.json config.

Run as a SUBPROCESS by ``bench.all`` (or by hand):

    taskset -c 0 python -m bench.cpu_baseline --config s3

and prints ONE summary JSON line (the same shape ``bench.run`` emits).  The
comparison class per family (BASELINE.json:5 "vs single-threaded CPU
backend", same algorithm class):

  s1/s2/headline  NumPy f64 info-form EM (``CPUBackend(filter="info")`` for
                  N >= 32, dense below) through the same ``bench.run`` path
  s3 (MF)         the SAME constrained-EM code on the XLA CPU backend in
                  f64 (no NumPy twin exists; CPU x64 IS the oracle dtype
                  regime the tests golden against)
  s4 (TVL)        likewise (dual-Kalman rounds on CPU f64)
  s5 (SV)         RBPF filter-pass rate on CPU f64, timed on a T-prefix
                  (DFM_SV_CPU_T_PREFIX, default 100) and extrapolated
                  linearly — the pass cost is linear in T and a full
                  10k x 1000 x 256-particle pass on one core is minutes

Thread pinning: the parent sets OMP/MKL/OPENBLAS_NUM_THREADS=1 in the
subprocess environment (before numpy loads) and prepends ``taskset -c 0``
where available, which bounds XLA's own thread pool to one core as well.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="s1")
    args = ap.parse_args(argv)

    import jax
    # jax is already imported at interpreter startup on this machine
    # (sitecustomize registers the TPU plugin), so the platform must be
    # forced via config, not env (see tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import numpy as np
    from .configs import get
    from . import run as bench_run

    cfg = get(args.config)

    if cfg.kind == "sv":
        # Filter-pass rate only (the metric BENCH_ALL records for s5),
        # timed on a T-prefix and extrapolated linearly in T.
        from dfm_tpu.models.sv import sv_filter
        from dfm_tpu.ssm.params import SSMParams as JP
        from dfm_tpu.backends import cpu_ref
        from dfm_tpu.utils.data import standardize as _std
        import jax.numpy as jnp

        T_pre = int(os.environ.get("DFM_SV_CPU_T_PREFIX", 100))
        Y, mask, _ = bench_run.make_data(cfg)
        Yz, _ = _std(np.asarray(Y, np.float64))
        Ypre = Yz[:T_pre]
        # Params from a cheap PCA init on the prefix: the pass cost is
        # parameter-independent (same op count), this just keeps R sane.
        p0 = cpu_ref.pca_init(Ypre, cfg.k)
        spec = bench_run.sv_bench_spec(cfg)
        Yj = jnp.asarray(Ypre, jnp.float64)
        pj = JP.from_numpy(p0, dtype=jnp.float64)
        key = jax.random.PRNGKey(bench_run.SV_BENCH_SEED)

        def one_pass():
            t0 = time.perf_counter()
            r = sv_filter(Yj, pj, spec, key=key, store_paths=False)
            float(r.loglik)
            return time.perf_counter() - t0

        one_pass()                                   # compile
        pass_pre = min(one_pass() for _ in range(2))
        pass_secs = pass_pre * (cfg.T / T_pre)
        summary = {
            "config": cfg.name, "backend": "cpu-1thread",
            "N": cfg.N, "T": cfg.T, "k": cfg.k,
            "sv_filter_pass_secs": pass_secs,
            "sv_filter_passes_per_sec": 1.0 / pass_secs,
            "n_particles": spec.n_particles,
            "extrapolated_from_T": T_pre,
        }
        print(json.dumps(summary))
        return summary

    # Everything else: the regular bench.run timing path on the CPU device.
    # Plain configs go through CPUBackend (NumPy f64; info form at scale);
    # MF/TVL run their own fit drivers, which land on the CPU XLA device.
    if cfg.kind in ("plain", "missing"):
        from dfm_tpu.api import CPUBackend, register_backend

        class _CPUInfo(CPUBackend):
            def __init__(self):
                super().__init__(filter="info" if cfg.N >= 32 else "dense")

        register_backend("cpu-baseline", _CPUInfo)
        backend = "cpu-baseline"
    else:
        backend = "cpu"  # ignored by the MF/TVL paths; device is CPU here
    summary = bench_run.main(["--config", args.config, "--backend", backend,
                              "--quiet"])
    summary["backend"] = "cpu-1thread"
    print(json.dumps(summary))   # last stdout line = the record (parent
    return summary               # parses it; bench_run printed its own too)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Fleet serving benchmark: aggregate query throughput of a
``dfm_tpu.open_fleet`` under Poisson mixed-tenant load (ONE fused batched
``serve_update`` dispatch per bucket per tick answers every queued
tenant's query) vs the loop-over-lone-sessions baseline (one
``open_session`` per tenant, one dispatch PER query — the only option
before ``fleet/``).  Prints exactly ONE JSON line to stdout:

    {"metric": ..., "value": N, "unit": "queries/sec",
     "fleet_qps": N, "fleet_p99_ms": N, "fleet_pad_waste_frac": N, ...}

``value`` is the fleet's aggregate warm queries/sec (total queries
served / drain wall, d2h barriers included).  ``fleet_p99_ms`` is the
p99 per-query latency (each query completes with its tick).  The load is
Poisson: each round every tenant independently queues a query with a
ragged row count, so ticks carry a realistic mixed active set.

Run on the real chip: ``python -m bench.fleet``.  Smoke-size via
DFM_BENCH_FLEET_MIX ("N,T,KxC;..." tenant shapes, default 2 groups x 4 =
8 tenants), DFM_BENCH_ROUNDS (load rounds, default 6), DFM_BENCH_ROWS
(max rows/query, default 2), DFM_BENCH_SERVE_ITERS (EM iters/query,
default 5), DFM_BENCH_ITERS (cold-fit budget, default 30),
DFM_BENCH_MAX_CLASSES, DFM_BENCH_FLEET_BACKEND (tpu|sharded),
DFM_BENCH_FLEET_WIDEK_MIX / DFM_BENCH_WIDEK_ROUNDS /
DFM_BENCH_WIDEK_RANK (wide-k engine leg: a lowrank-routed fleet vs a
forced-info twin at k=50 — ``fleet_widek_speedup``).
The live plane's SLO is armed for the run (DFM_BENCH_SLO_P99_MS,
default 60000) so the line carries ``fleet_slo_burn_rate`` /
``flight_dumps`` (~0 healthy).  Diagnostics on stderr.
"""

import json
import os
import time
import warnings

import numpy as np

from bench._common import log, parse_mix, pct as _pct, record_run


def main():
    mix = os.environ.get("DFM_BENCH_FLEET_MIX", "16,56,2x4;24,72,2x4")
    rounds = int(os.environ.get("DFM_BENCH_ROUNDS", 6))
    r_max = int(os.environ.get("DFM_BENCH_ROWS", 2))
    serve_iters = int(os.environ.get("DFM_BENCH_SERVE_ITERS", 5))
    cold_iters = int(os.environ.get("DFM_BENCH_ITERS", 30))
    max_classes = int(os.environ.get("DFM_BENCH_MAX_CLASSES", 2))
    backend = os.environ.get("DFM_BENCH_FLEET_BACKEND", "tpu")
    shapes = parse_mix(mix)
    B = len(shapes)

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly

    from dfm_tpu import (DynamicFactorModel, TPUBackend, fit, open_fleet,
                         open_session)
    from dfm_tpu.obs.live import plane, set_slo
    from dfm_tpu.obs.slo import SLOConfig
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer
    from dfm_tpu.utils import dgp

    # Arm the live plane's SLO with a generous default so the bench line
    # always carries a burn-rate reading (~0 on a healthy run; a tunnel
    # stall or divergence storm shows up as burn > 0 + flight dumps).
    slo_p99 = float(os.environ.get("DFM_BENCH_SLO_P99_MS", 60000.0))
    set_slo(SLOConfig(p99_ms=slo_p99,
                      error_rate=float(os.environ.get(
                          "DFM_BENCH_SLO_ERROR_RATE", 0.05))))

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); {B} tenants "
        f"[{mix}], {rounds} Poisson rounds, <= {r_max} rows/query, "
        f"{serve_iters} EM iters/query, backend={backend}")

    # Per-tenant fitted models + a Poisson query schedule.  The headline
    # leg pins the info engine on both sides so fleet and lone baseline
    # run identical per-query program semantics (the engine-routing win
    # is measured separately in the wide-k leg below).
    be = TPUBackend(filter="info")
    rng = np.random.default_rng(123)
    schedule = []       # [round][tenant] -> n_rows (0 = no query)
    for _ in range(rounds):
        lam_rows = 1 + rng.poisson(0.6, size=B)
        arrive = rng.random(B) < 0.75
        schedule.append([int(min(r_max, lam_rows[i])) if arrive[i] else 0
                         for i in range(B)])
    n_total = [1 * r_max + sum(s[i] for s in schedule)
               for i in range(B)]    # warmup round + load

    model_of, ress, Ys, streams = [], [], [], []
    with jax.default_matmul_precision("highest"):
        for i, (N, T, k) in enumerate(shapes):
            rngi = np.random.default_rng(3000 + i)
            p_true = dgp.dfm_params(N, k, rngi)
            Y_all, _ = dgp.simulate(p_true, T + n_total[i], rngi)
            m = DynamicFactorModel(n_factors=k)
            model_of.append(m)
            ress.append(fit(m, Y_all[:T], max_iters=cold_iters,
                            backend=be, telemetry=False))
            Ys.append(Y_all[:T])
            streams.append(Y_all[T:])

    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    caps = [Ys[i].shape[0] + n_total[i] + r_max for i in range(B)]
    with activate(tracer), jax.default_matmul_precision("highest"):
        fleet = open_fleet(ress, Ys, capacity=caps,
                           max_update_rows=r_max, max_iters=serve_iters,
                           tol=0.0, backend=backend if backend != "tpu"
                           else be, max_classes=max_classes)
        names = fleet.tenants
        cursor = [0] * B
        # Warmup tick: every tenant active (compiles the one executable
        # per bucket; later ticks reuse it for every active set).
        for i, t in enumerate(names):
            fleet.submit(t, streams[i][:r_max])
            cursor[i] = r_max
        fleet.drain()
        base = tracer.summary()
        base_ticks = fleet._n_ticks

        walls, q_lat = [], []
        t_load0 = time.perf_counter()
        n_queries = 0
        for rnd in schedule:
            for i, t in enumerate(names):
                if rnd[i]:
                    fleet.submit(t, streams[i][cursor[i]:cursor[i]
                                               + rnd[i]])
                    cursor[i] += rnd[i]
                    n_queries += 1
            t0 = time.perf_counter()
            out = fleet.drain()
            w = time.perf_counter() - t0
            walls.append(w)
            for t, ups in out.items():
                q_lat.extend([u.wall_s for u in ups])
        fleet_wall = time.perf_counter() - t_load0
        warm = tracer.summary()
        n_ticks = fleet._n_ticks - base_ticks
        qps = n_queries / fleet_wall
        p50_ms = 1e3 * _pct(q_lat, 50)
        p99_ms = 1e3 * _pct(q_lat, 99)
        blocking = (warm["blocking_transfers"] - base["blocking_transfers"])
        per_tick = blocking / max(n_ticks, 1)
        recomp = (warm["programs"].get("serve_update", {})
                  .get("recompiles", 0)
                  - base["programs"].get("serve_update", {})
                  .get("recompiles", 0))
        log(f"fleet: {n_queries} queries in {fleet_wall:.3f} s "
            f"({qps:.1f} q/s) over {n_ticks} ticks "
            f"({n_queries / max(n_ticks, 1):.2f} queries/dispatch); "
            f"query p50 {p50_ms:.1f} ms p99 {p99_ms:.1f} ms; "
            f"{per_tick:.2f} blocking transfers/tick, {recomp} recompiles "
            f"after warmup; pad waste {100 * fleet.pad_waste_frac:.1f}%")

        # Baseline: one lone session per tenant serving the SAME
        # schedule (state ends identical) — one dispatch per query.
        sessions = [open_session(ress[i], Ys[i], capacity=caps[i],
                                 max_update_rows=r_max,
                                 max_iters=serve_iters, tol=0.0,
                                 backend=be) for i in range(B)]
        cursor = [0] * B
        for i, s in enumerate(sessions):      # warmup (compile) query
            s.update(streams[i][:r_max])
            cursor[i] = r_max
        t0 = time.perf_counter()
        for rnd in schedule:
            for i, s in enumerate(sessions):
                if rnd[i]:
                    s.update(streams[i][cursor[i]:cursor[i] + rnd[i]])
                    cursor[i] += rnd[i]
        lone_wall = time.perf_counter() - t0
        lone_qps = n_queries / lone_wall
        log(f"lone sessions: {lone_wall:.3f} s ({lone_qps:.1f} q/s); "
            f"fleet speedup {lone_wall / fleet_wall:.2f}x")

    # -- wide-k leg: lowrank-routed fleet vs forced-info twin -----------
    # Engine-complete serving: at k ~ 50 the info engine's k x k linalg
    # dominates every tick; routing the bucket through the rank-r
    # downdate engine must carry the bench.kscale win through the full
    # fleet path (admission, ragged appends, d2h) — same tenants, same
    # schedule, same container, only the engine differs.
    # Default matches bench.kscale's measured point (N=120, T=200, k=50,
    # rank 8) so the fleet-path win is directly comparable to the lone
    # fit-path win in docs/PERF.md.
    widek_mix = os.environ.get("DFM_BENCH_FLEET_WIDEK_MIX", "120,200,50x2")
    widek_rounds = int(os.environ.get("DFM_BENCH_WIDEK_ROUNDS", 3))
    widek_rank = int(os.environ.get("DFM_BENCH_WIDEK_RANK", 8))
    wshapes = parse_mix(widek_mix)
    wB = len(wshapes)
    blr = TPUBackend(filter="lowrank", rank=widek_rank)
    with jax.default_matmul_precision("highest"):
        wress, wYs, wstreams = [], [], []
        n_w = (widek_rounds + 1) * r_max
        for i, (N, T, k) in enumerate(wshapes):
            rngi = np.random.default_rng(4000 + i)
            p_true = dgp.dfm_params(N, k, rngi)
            Y_all, _ = dgp.simulate(p_true, T + n_w, rngi)
            wress.append(fit(DynamicFactorModel(n_factors=k), Y_all[:T],
                             max_iters=max(4, cold_iters // 6),
                             backend=blr, telemetry=False))
            wYs.append(Y_all[:T])
            wstreams.append(Y_all[T:])
        wcaps = [wYs[i].shape[0] + n_w + r_max for i in range(wB)]
        eng_wall = {}
        # The rank-r E-step is approximate, so warm EM at tol=0.0 can
        # dip the loglik past the guard's floor — the in-graph rollback
        # (a masked update in the SAME executable) is the designed
        # sail-through and keeps the twin walls program-fair; the
        # per-tenant RuntimeWarning is expected here, not a fault.
        for eng, rk in (("info", 0), ("lowrank", widek_rank)):
            flw = open_fleet(wress, wYs, capacity=wcaps,
                             max_update_rows=r_max, max_iters=serve_iters,
                             tol=0.0, backend=blr, max_classes=1,
                             filter=eng, rank=rk)
            wcur = [0] * wB
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                for i, t in enumerate(flw.tenants):  # warmup/compile tick
                    flw.submit(t, wstreams[i][:r_max])
                    wcur[i] = r_max
                flw.drain()
                t0 = time.perf_counter()
                for _ in range(widek_rounds):
                    for i, t in enumerate(flw.tenants):
                        flw.submit(t, wstreams[i][wcur[i]:wcur[i] + r_max])
                        wcur[i] += r_max
                    flw.drain()
                eng_wall[eng] = time.perf_counter() - t0
            flw.close()
    widek_speedup = (eng_wall["info"] / eng_wall["lowrank"]
                     if eng_wall["lowrank"] > 0 else 0.0)
    log(f"wide-k leg [{widek_mix}] rank={widek_rank}: lowrank fleet "
        f"{eng_wall['lowrank']:.3f} s vs info twin "
        f"{eng_wall['info']:.3f} s — {widek_speedup:.2f}x")

    ts_sum = tracer.summary()
    log(f"telemetry: {ts_sum['dispatches']} dispatches, "
        f"{ts_sum['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"fleet_qps_{B}tenants",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "value_definition": ("aggregate warm fleet throughput under "
                             "Poisson mixed-tenant load: queries served "
                             "per second of drain wall (one fused "
                             "batched serve_update dispatch per bucket "
                             "per tick, d2h barriers included)"),
        "fleet_qps": round(qps, 2),
        "fleet_p99_ms": round(p99_ms, 2),
        "fleet_pad_waste_frac": round(float(fleet.pad_waste_frac), 4),
        "fleet_p50_ms": round(p50_ms, 2),
        "fleet_blocking_transfers_per_tick": round(per_tick, 3),
        "queries_per_dispatch": round(n_queries / max(n_ticks, 1), 3),
        "recompiles_after_warmup": int(recomp),
        "speedup_vs_lone_sessions": round(lone_wall / fleet_wall, 2),
        "lone_sessions_qps": round(lone_qps, 2),
        "n_tenants": B,
        "n_queries": n_queries,
        "n_ticks": n_ticks,
        "n_classes": fleet.n_buckets,
        "serve_iters": serve_iters,
        "mix": mix,
        "fleet_backend": backend,
        "dispatches": ts_sum["dispatches"],
        "recompiles": ts_sum["recompiles"],
        "fleet_widek_speedup": round(widek_speedup, 3),
        "fleet_widek_lowrank_s": round(eng_wall["lowrank"], 3),
        "fleet_widek_info_s": round(eng_wall["info"], 3),
        "fleet_widek_mix": widek_mix,
        "fleet_widek_rank": widek_rank,
        "fleet_slo_burn_rate": round(float(
            plane().slo.status().get("burn_rate_max") or 0.0), 4),
        "flight_dumps": int(plane().flight_dumps),
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_fleet")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Mixed-shape multi-tenant benchmark: aggregate EM iters/sec for a
heterogeneous (N, T, k) job mix through the shape-bucketed scheduler
(``dfm_tpu.fit_jobs`` — one fused batched program per bucket) vs the
loop-over-fits baseline (one ``api.fit`` per job — the only option before
``sched/``, paying the ~60-100 ms tunnel dispatch stream PER job).
Prints exactly ONE JSON line to stdout:

    {"metric": ..., "value": N, "unit": "iters/sec",
     "aggregate_mixed_iters_per_sec": N, "pad_waste_frac": N,
     "scheduler_overhead_ms": N, "speedup_vs_looped": N, ...}

``value`` is the scheduler's DISPATCH-INCLUSIVE aggregate rate (total
EM iterations across all jobs / wall) — dispatch amortization across
tenants is exactly what the scheduler buys.  ``pad_waste_frac`` is the
bucket plan's padded-flop waste, ``scheduler_overhead_ms`` the host-side
plan+pack+slice cost (wall minus in-bucket compute).

Run on the real chip: ``python -m bench.mixed``.  Smoke-size via
DFM_BENCH_MIX ("N,T,KxC;..." job groups, default 3 shape groups x 4),
DFM_BENCH_ITERS, DFM_BENCH_SCHED_BACKEND (tpu|sharded), DFM_BENCH_CHUNK.
Diagnostics on stderr.
"""

import json
import os

import numpy as np

from bench._common import (log, parse_mix as _parse_mix, record_run,
                           timed)


def main():
    mix = os.environ.get("DFM_BENCH_MIX",
                         "20,64,2x12;14,40,1x12;26,96,2x12")
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 20))
    backend = os.environ.get("DFM_BENCH_SCHED_BACKEND", "tpu")
    chunk = int(os.environ.get("DFM_BENCH_CHUNK", n_iters))
    max_buckets = int(os.environ.get("DFM_BENCH_MAX_BUCKETS", 3))
    shapes = _parse_mix(mix)
    n_jobs = len(shapes)

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly
    import jax.numpy as jnp

    from dfm_tpu import DynamicFactorModel, Job, TPUBackend, fit, fit_jobs
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); {n_jobs} jobs "
        f"[{mix}], {n_iters} iters each, backend={backend}, chunk={chunk}")

    # One DGP panel per job at its own shape; tol=0 pins every tenant to
    # exactly n_iters EM iterations, so both sides time identical work.
    dtype = jnp.float32
    jobs = []
    for i, (N, T, k) in enumerate(shapes):
        rng = np.random.default_rng(2000 + i)
        p_true = dgp.dfm_params(N, k, rng)
        Y, _ = dgp.simulate(p_true, T, rng)
        jobs.append(Job(Y=Y, model=DynamicFactorModel(n_factors=k),
                        tenant=f"job{i}", max_iters=n_iters, tol=0.0))
    total_iters = n_jobs * n_iters

    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    last_stats = {}

    def run_sched():
        last_stats.clear()
        fit_jobs(jobs, backend=backend, max_buckets=max_buckets,
                 dtype=dtype, fused_chunk=chunk, stats=last_stats)

    # Baseline: one api.fit per job — a shared backend instance so the
    # loop reuses compiled programs across same-shaped jobs (the best a
    # non-batched caller can do).  filter="info" matches the scheduler's
    # engine; telemetry hard-off keeps the loop lean.
    be = TPUBackend(dtype=dtype, filter="info", fused_chunk=chunk)

    def run_looped():
        for job in jobs:
            fit(job.model, job.Y, backend=be, max_iters=n_iters, tol=0.0,
                telemetry=False)

    with activate(tracer), jax.default_matmul_precision("highest"):
        t_s = timed(run_sched)
        agg = total_iters / t_s
        waste = float(last_stats.get("pad_waste_frac", 0.0))
        overhead_ms = 1e3 * max(t_s - float(last_stats.get("compute_s",
                                                           0.0)), 0.0)
        log(f"scheduler: {t_s:.3f} s ({agg:.1f} agg iters/sec, "
            f"{last_stats.get('n_buckets')} buckets "
            f"{last_stats.get('bucket_dims')}, pad waste "
            f"{100 * waste:.1f}%, overhead {overhead_ms:.1f} ms)")

        t_l = timed(run_looped, reps=2)
        agg_l = total_iters / t_l
        log(f"looped:    {t_l:.3f} s ({agg_l:.1f} agg iters/sec); "
            f"speedup {t_l / t_s:.2f}x")

    ts_sum = tracer.summary()
    log(f"telemetry: {ts_sum['dispatches']} dispatches, "
        f"{ts_sum['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"mixed_sched_agg_iters_per_sec_{n_jobs}jobs",
        "value": round(agg, 2),
        "unit": "iters/sec",
        "value_definition": ("aggregate dispatch-inclusive EM iterations "
                             "per second across a mixed-shape job mix "
                             "(total iters / scheduler wall), one fused "
                             "batched program per shape bucket"),
        "aggregate_mixed_iters_per_sec": round(agg, 2),
        "pad_waste_frac": round(waste, 4),
        "scheduler_overhead_ms": round(overhead_ms, 2),
        "speedup_vs_looped": round(t_l / t_s, 2),
        "looped_agg_iters_per_sec": round(agg_l, 2),
        "n_jobs": n_jobs,
        "n_iters": n_iters,
        "n_buckets": last_stats.get("n_buckets"),
        "mix": mix,
        "sched_backend": backend,
        "dispatches": ts_sum["dispatches"],
        "recompiles": ts_sum["recompiles"],
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_mixed")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Differentiable tuning vs the G-fit grid sweep it replaces.

Measures the gradient-descent Q/R search (``estim.tune``, method="grad":
the whole search — inner fixed-iteration EM, in-graph held-out scoring,
Adam over log hypers — as ONE jitted program with ONE blocking d2h)
against the pre-tune baseline: a loop of G lone fused fits, one per grid
point of the default (q_scale, r_scale) grid, each scored held-out at
the same budget on the same backend.  Equal-quality: the grad search's
final held-out MSE must match or beat the grid's best point (recorded as
``tune_quality_vs_grid`` = grid best / grad best, >= 1 means the
gradient search found an as-good-or-better point).

Prints exactly ONE JSON line to stdout:

    {"metric": "tune_speedup_vs_grid", "value": N, "unit": "x",
     "tune_speedup_vs_grid": N, "tune_heldout_gain": N,
     "tune_dispatches": N, ...}

``tune_heldout_gain`` is the relative held-out one-step MSE improvement
of the tuned point over the untuned (q=r=1) fit at the same EM budget —
deterministic given the panel.  ``tune_dispatches`` is the search's
blocking-d2h count (the dispatch-budget contract; 1 for the grad
search vs 2G for the grid loop).

Run on the real chip: ``python -m bench.tune``.  Smoke-size via
DFM_BENCH_N/T/K, DFM_BENCH_TUNE_STEPS (Adam steps, default 12),
DFM_BENCH_TUNE_EM_ITERS (inner EM budget, default 5),
DFM_BENCH_TUNE_HOLDOUT (held-out rows, default 8), DFM_BENCH_REPS
(best-of-N, default 3).  Diagnostics on stderr.
"""

import json
import os

from bench._common import log, record_run, timed


def main():
    N = int(os.environ.get("DFM_BENCH_N", 24))
    T = int(os.environ.get("DFM_BENCH_T", 120))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    steps = int(os.environ.get("DFM_BENCH_TUNE_STEPS", 12))
    em_iters = int(os.environ.get("DFM_BENCH_TUNE_EM_ITERS", 5))
    holdout = int(os.environ.get("DFM_BENCH_TUNE_HOLDOUT", 8))
    reps = int(os.environ.get("DFM_BENCH_REPS", 3))

    import numpy as np

    import jax

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.estim.em import EMConfig
    from dfm_tpu.estim.tune import DEFAULT_GRID, TuneOptions, tune_fit
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} T={T} k={k}, "
        f"{steps} grad steps x {em_iters} EM iters, holdout {holdout}, "
        f"grid {len(DEFAULT_GRID)} points, best of {reps}")

    rng = np.random.default_rng(77)
    Y_raw, _ = dgp.simulate(dgp.dfm_params(N, k, rng), T, rng)
    Y = (Y_raw - Y_raw.mean(0)) / Y_raw.std(0)
    W = dgp.random_mask(T, N, rng, 0.1)      # masked panel: the tune
    p0 = cpu_ref.pca_init(Y * W, k)          # objective's natural habitat
    cfg = EMConfig(filter="info")

    # --- grad leg: the whole search is ONE jitted program, ONE d2h ----
    o_grad = TuneOptions(method="grad", steps=steps, em_iters=em_iters,
                         holdout_rows=holdout)
    rec = tune_fit(Y, W, p0, cfg, o_grad)
    wall_grad = timed(lambda: tune_fit(Y, W, p0, cfg, o_grad), reps)
    log(f"grad: q={rec['q_scale']:.3g} r={rec['r_scale']:.3g}, held-out "
        f"{rec['heldout_before']:.4g} -> {rec['heldout_after']:.4g}, "
        f"{rec['dispatches']} d2h, {1e3 * wall_grad:.1f} ms warm")

    # --- grid leg: G lone fits, one per candidate point ---------------
    # What the same search costs without tune: per point, a full lone
    # ``fit()`` on the training window at the same EM budget (its own
    # fused program + result d2h) scored held-out by the NumPy f64
    # oracle — exactly the pre-tune recipe ``fleet/maintenance``'s
    # quality gate uses.  Through the axon tunnel that is >= 2G blocking
    # round-trips vs the grad search's one; the candidate hypers ride
    # the backend's tuned-cfg seam so both legs fit the identical
    # hyper-scaled EM.
    from dfm_tpu import DynamicFactorModel, TPUBackend, fit
    from dfm_tpu.estim.score import heldout_mse_np

    model = DynamicFactorModel(n_factors=k, standardize=False)
    Wtr = W.copy()
    Wtr[T - holdout:] = 0.0
    Ytr = np.where(Wtr > 0, Y, np.nan)   # holdout + mask -> missing
    be = TPUBackend()

    def grid_loop():
        best = float("inf")
        for g in DEFAULT_GRID:
            be._tune_hypers = g
            r1 = fit(model, Ytr, max_iters=em_iters, tol=0.0, init=p0,
                     backend=be)
            s = heldout_mse_np(Y, W, r1.params, holdout)
            if np.isfinite(s):
                best = min(best, s)
        be._tune_hypers = None
        return best

    grid_best = grid_loop()
    wall_grid = timed(grid_loop, reps)
    log(f"grid: best held-out {grid_best:.4g} over {len(DEFAULT_GRID)} "
        f"lone fits ({2 * len(DEFAULT_GRID)} d2h), "
        f"{1e3 * wall_grid:.1f} ms warm")

    speedup = wall_grid / wall_grad
    before = rec["heldout_before"]
    gain = ((before - rec["heldout_after"]) / before
            if np.isfinite(before) and before > 0 else float("nan"))
    quality = (grid_best / rec["heldout_after"]
               if np.isfinite(grid_best) and rec["heldout_after"] > 0
               else float("nan"))
    log(f"speedup {speedup:.2f}x, held-out gain {100 * gain:.1f}%, "
        f"quality vs grid {quality:.3f} (>=1 means grad as good or "
        f"better)")

    payload = {
        "metric": "tune_speedup_vs_grid",
        "value": round(speedup, 3),
        "unit": "x",
        "value_definition": ("warm wall of the G-lone-fit grid sweep "
                             "divided by the warm wall of the one-program "
                             "gradient search at the same shape, budget "
                             "and backend"),
        "tune_speedup_vs_grid": round(speedup, 3),
        "tune_heldout_gain": round(gain, 6),
        "tune_dispatches": int(rec["dispatches"]),
        "tune_quality_vs_grid": round(quality, 4),
        "heldout_before": rec["heldout_before"],
        "heldout_after": rec["heldout_after"],
        "grid_best_heldout": grid_best,
        "q_scale": rec["q_scale"],
        "r_scale": rec["r_scale"],
        "grad_steps": steps,
        "grid_points": len(DEFAULT_GRID),
        "em_iters": em_iters,
        "holdout_rows": holdout,
        "shape_N_T_k": [N, T, k],
    }
    from dfm_tpu.obs.store import new_run_id
    payload["run_id"] = new_run_id()
    print(json.dumps(payload))
    record_run(payload, dev, "bench_tune")


if __name__ == "__main__":
    main()

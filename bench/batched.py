#!/usr/bin/env python
"""Batched multi-fit benchmark: aggregate EM iters/sec for B independent
S1-shaped problems (N=50, T=200, k=2, static) fused into ONE program
(``estim.batched.run_batched_em``) vs the B-looped driver (one fused
``em_fit_scan`` program PER problem — the best non-batched alternative,
paying the ~60-100 ms tunnel dispatch B times).  Prints exactly ONE JSON
line to stdout:

    {"metric": ..., "value": N, "unit": "iters/sec",
     "speedup_vs_looped": N, "sweep": {B: {...}}, ...}

``value`` is the DISPATCH-INCLUSIVE aggregate rate (B * n_iters / wall)
at the largest B — dispatch amortization is exactly what the batched
engine buys, so the headline keeps it in.  The sustained (two-point
slope, interleaved hi/lo median — same hardening as bench.py) rate is
reported alongside per B, isolating the marginal device cost per
batched iteration.

Run on the real chip: ``python -m bench.batched``.  Smoke-size via
DFM_BENCH_B (comma list, default "1,8,32") / DFM_BENCH_N / DFM_BENCH_T /
DFM_BENCH_K / DFM_BENCH_ITERS.  Diagnostics on stderr.
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    Bs = sorted({int(b) for b in
                 os.environ.get("DFM_BENCH_B", "1,8,32").split(",")})
    N = int(os.environ.get("DFM_BENCH_N", 50))
    T = int(os.environ.get("DFM_BENCH_T", 200))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 20))
    dynamics = os.environ.get("DFM_BENCH_DYNAMICS", "static")
    B_max = Bs[-1]

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly
    import jax.numpy as jnp

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.estim.batched import run_batched_em, stack_params
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer
    from dfm_tpu.ssm.params import SSMParams as JP

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"B sweep {Bs}, shape {N}x{T} k={k} {dynamics}, {n_iters} iters")

    # B_max independent same-shaped problems (fresh DGP draw each).
    static = dynamics == "static"
    panels, inits = [], []
    for b in range(B_max):
        rng = np.random.default_rng(1000 + b)
        p_true = dgp.dfm_params(N, k, rng)
        Y, _ = dgp.simulate(p_true, T, rng)
        Y = (Y - Y.mean(0)) / Y.std(0)
        panels.append(Y)
        inits.append(cpu_ref.pca_init(Y, k, static=static))
    Y_all = np.stack(panels)                       # (B_max, T, N)

    dtype = jnp.float32
    cfg = EMConfig(estimate_A=not static, estimate_Q=not static,
                   filter="info")
    Yj_all = jax.device_put(jnp.asarray(Y_all, dtype))
    pj_each = [JP.from_numpy(p, dtype=dtype) for p in inits]

    def run_batched(B, n):
        # tol=0: no convergence exit — every problem runs all n iterations
        # in ONE dispatch (fused_chunk=n), so timed work is deterministic.
        _, lls_list, _, _, _ = run_batched_em(
            Yj_all[:B], stack_params(inits[:B], dtype), cfg,
            max_iters=n, tol=0.0, fused_chunk=n)
        return lls_list  # driver's np.asarray on the carry is the barrier

    def timed(f, *args, reps=3):
        f(*args)  # warm-up / compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            f(*args)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    # Telemetry: DFM_TRACE=<path> seeds the ambient file tracer; without it
    # a fresh in-memory one still counts dispatches/recompiles for the JSON
    # line.  Both benched drivers (run_batched_em, em_fit_scan) carry their
    # own dispatch spans, so activation alone instruments everything.
    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    sweep = {}
    with activate(tracer), jax.default_matmul_precision("highest"):
        # Looped driver: one fused em_fit_scan program per problem (same
        # compiled program for every b — identical shapes), B dispatches.
        def run_looped(B, n):
            for b in range(B):
                _, lls, _ = em_fit_scan(Yj_all[b], pj_each[b], n, cfg=cfg)
                np.asarray(lls)  # per-problem barrier, as a real loop pays

        for B in Bs:
            log(f"--- B={B} ---")
            t_b = timed(run_batched, B, n_iters)
            agg = B * n_iters / t_b
            log(f"batched: {t_b:.3f} s  ({agg:.1f} agg iters/sec "
                "dispatch-inclusive)")

            # Two-point sustained: interleaved hi/lo, median slope.
            n_lo, n_hi = n_iters, 3 * n_iters
            run_batched(B, n_hi)  # compile the long program
            pairs = [(timed(run_batched, B, n_hi, reps=1),
                      timed(run_batched, B, n_lo, reps=1))
                     for _ in range(3)]
            slopes = [(a - b) / (n_hi - n_lo) for a, b in pairs]
            slope = float(np.median(slopes))
            if slope <= 0:  # jitter swamped the signal (smoke sizes)
                log("WARNING: non-positive two-point slope; falling back "
                    "to total/n")
                slope = t_b / n_iters
            sus = B / slope
            log(f"batched sustained: {slope * 1e3:.3f} ms/iter "
                f"({sus:.1f} agg iters/sec)")

            t_l = timed(run_looped, B, n_iters, reps=2)
            agg_l = B * n_iters / t_l
            log(f"looped:  {t_l:.3f} s  ({agg_l:.1f} agg iters/sec); "
                f"speedup {t_l / t_b:.2f}x")
            sweep[str(B)] = {
                "batched_secs": round(t_b, 4),
                "agg_iters_per_sec": round(agg, 2),
                "sustained_agg_iters_per_sec": round(sus, 2),
                "looped_secs": round(t_l, 4),
                "looped_agg_iters_per_sec": round(agg_l, 2),
                "speedup_vs_looped": round(t_l / t_b, 2),
            }

    ts = tracer.summary()
    log(f"telemetry: {ts['dispatches']} dispatches, "
        f"{ts['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    from dfm_tpu.obs.store import new_run_id
    head = sweep[str(B_max)]
    payload = {
        "metric": (f"batched_em_agg_iters_per_sec_B{B_max}_"
                   f"{N}x{T}_k{k}_{dynamics}"),
        "value": head["agg_iters_per_sec"],
        "unit": "iters/sec",
        "value_definition": ("aggregate dispatch-inclusive EM iterations "
                             "per second across the batch (B * n_iters / "
                             "wall), one fused program per chunk"),
        "speedup_vs_looped": head["speedup_vs_looped"],
        "n_iters": n_iters,
        "shape": {"N": N, "T": T, "k": k, "dynamics": dynamics},
        "sweep": sweep,
        # Per-B fused lengths are distinct programs: recompiles > 0 is
        # the expected, truthful count for a sweep (obs/trace.py).
        "dispatches": ts["dispatches"],
        "recompiles": ts["recompiles"],
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    _record_run(payload, dev)


def _record_run(payload, dev):
    """Append this run to the perf-observatory registry (obs.store);
    stderr-only diagnostics, same contract as bench.py."""
    from dfm_tpu.obs import store as obs_store
    d = obs_store.runs_dir()
    if d is None:
        return
    try:
        rec = obs_store.record_from_bench_json(
            payload, device=f"{dev.platform} ({dev.device_kind})",
            kind="bench_batched")
        obs_store.RunStore(d).append(rec)
        log(f"run {payload['run_id']} recorded in {d}/")
    except Exception as e:  # registry failure must not fail the bench
        log(f"WARNING: run registry append failed: {e}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Unbounded-stream serving benchmark: ring-buffer sessions + fleet
snapshot tiering (ISSUE 14).

Leg 1 soaks a ``ring=True`` nowcast session far past its capacity — the
panel starts FULL, so every query rolls the oldest rows off in-graph
while appending the new ones: constant memory, one executable, and the
same ≤1-blocking-d2h budget as the fixed-capacity session it is raced
against.  Leg 2 opens a fleet with more registered tenants than resident
HBM lanes (``resident=``) and round-robins queries so every submit pages
a warm tenant into a hot lane; the paging walls are the re-admission
price the cost model trades against lane rent.  Prints exactly ONE JSON
line to stdout:

    {"metric": ..., "value": N, "unit": "queries/sec",
     "stream_qps": N, "stream_p99_ms": N,
     "evictions_per_query": N, "readmission_ms": N, ...}

``value`` is the warm ring-session query throughput (host-observed,
d2h barrier included).  ``recompiles_after_warmup`` must stay 0 — the
traced eviction count rides the SAME executable as a non-ring session.

Leg 1b races a ``filter="pit_qr"`` ring session against a forced-info
twin on the same long trailing window (``stream_pit_speedup`` — the
engine win must survive the full serving path).  Run on the real chip:
``python -m bench.stream``.  Smoke-size via
DFM_BENCH_N/K, DFM_BENCH_STREAM_CAPACITY (ring window, default 160),
DFM_BENCH_QUERIES (warm queries, default 50), DFM_BENCH_ROWS (rows per
query, default 2), DFM_BENCH_SERVE_ITERS (EM iters/update, default 5),
DFM_BENCH_ITERS (cold-fit budget, default 50),
DFM_BENCH_STREAM_PIT_CAPACITY / DFM_BENCH_STREAM_PIT_QUERIES (pit_qr
leg window, default 600 / half the warm queries),
DFM_BENCH_STREAM_TENANTS / DFM_BENCH_STREAM_RESIDENT (fleet tiering
leg, default 8 tenants on 2 lanes).  Diagnostics on stderr.
"""

import json
import os
import time

import numpy as np

from bench._common import log, pct as _pct, record_run


def main():
    N = int(os.environ.get("DFM_BENCH_N", 24))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    cap = int(os.environ.get("DFM_BENCH_STREAM_CAPACITY", 160))
    n_queries = int(os.environ.get("DFM_BENCH_QUERIES", 50))
    rows = int(os.environ.get("DFM_BENCH_ROWS", 2))
    serve_iters = int(os.environ.get("DFM_BENCH_SERVE_ITERS", 5))
    cold_iters = int(os.environ.get("DFM_BENCH_ITERS", 50))
    n_tenants = int(os.environ.get("DFM_BENCH_STREAM_TENANTS", 8))
    resident = int(os.environ.get("DFM_BENCH_STREAM_RESIDENT", 2))

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly
    from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    n_stream = (n_queries + 1) * rows
    log(f"device: {dev.platform} ({dev.device_kind}); ring window "
        f"({N}, {cap}) k={k}, {n_queries} warm queries x {rows} rows "
        f"past capacity, {serve_iters} EM iters/update; tiering leg "
        f"{n_tenants} tenants / {resident} lanes")

    rng = np.random.default_rng(177)
    p_true = dgp.dfm_params(N, k, rng)
    Y_all, _ = dgp.simulate(p_true, cap + n_stream, rng)
    Y0, Y_stream = Y_all[:cap], Y_all[cap:]

    model = DynamicFactorModel(n_factors=k)
    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    with activate(tracer), jax.default_matmul_precision("highest"):
        res = fit(model, Y0, max_iters=cold_iters, fused=True)

        # Fixed-capacity yardstick: the PR 9 session with room for the
        # whole stream (no eviction ever fires).  Ring p99 must sit
        # within noise of this — the eviction roll is in-graph and free.
        fixed = open_session(res, Y0, capacity=cap + n_stream,
                             max_update_rows=rows, max_iters=serve_iters,
                             tol=0.0)
        fixed.update(Y_stream[:rows])       # compile + warm
        fixed_walls = []
        for i in range(1, n_queries + 1):
            t0 = time.perf_counter()
            fixed.update(Y_stream[i * rows:(i + 1) * rows])
            fixed_walls.append(time.perf_counter() - t0)
        fixed.close()
        fixed_p50 = 1e3 * _pct(fixed_walls, 50)
        fixed_p99 = 1e3 * _pct(fixed_walls, 99)
        log(f"fixed-capacity session: p50 {fixed_p50:.1f} ms, "
            f"p99 {fixed_p99:.1f} ms")

        # The soak: the ring panel starts FULL, so EVERY query evicts
        # exactly `rows` oldest rows in-graph while appending.
        sess = open_session(res, Y0, capacity=cap, max_update_rows=rows,
                            max_iters=serve_iters, tol=0.0, ring=True)
        sess.update(Y_stream[:rows])        # compile + warm
        base = tracer.summary()
        walls = []
        for i in range(1, n_queries + 1):
            t0 = time.perf_counter()
            sess.update(Y_stream[i * rows:(i + 1) * rows])
            walls.append(time.perf_counter() - t0)
        warm = tracer.summary()
        n_evicted = sess.n_evicted
        assert sess.t == cap, "ring session must hold exactly capacity"
        sess.close()

    p50_ms = 1e3 * _pct(walls, 50)
    p99_ms = 1e3 * _pct(walls, 99)
    qps = n_queries / sum(walls)
    blocking = warm["blocking_transfers"] - base["blocking_transfers"]
    per_query = blocking / n_queries
    recomp = (warm["programs"].get("serve_update", {}).get("recompiles", 0)
              - base["programs"].get("serve_update", {}).get("recompiles",
                                                             0))
    evictions_per_query = n_evicted / ((n_queries + 1))
    log(f"ring soak: p50 {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms "
        f"({qps:.1f} queries/sec), {evictions_per_query:.2f} rows "
        f"evicted/query, {per_query:.2f} blocking transfers/query, "
        f"{recomp} recompiles after warmup; p99 {p99_ms / fixed_p99:.2f}x "
        "the fixed-capacity session's")

    # -- leg 1b: long-window ring, pit_qr vs forced-info twin -----------
    # Engine-complete serving: the SAME ring executable budget, but the
    # in-update EM/smooth runs the square-root parallel-in-time engine.
    # At long trailing windows the sequential scan dominates the query
    # wall, so the pit_qr session's win must SURVIVE the serving path
    # (ragged append + warm EM + forecasts, d2h included).
    pit_cap = int(os.environ.get("DFM_BENCH_STREAM_PIT_CAPACITY", 600))
    pit_queries = int(os.environ.get("DFM_BENCH_STREAM_PIT_QUERIES",
                                     max(6, n_queries // 2)))
    from dfm_tpu import TPUBackend
    rng_p = np.random.default_rng(179)
    pp = dgp.dfm_params(N, k, rng_p)
    n_pstream = (pit_queries + 1) * rows
    Yp_all, _ = dgp.simulate(pp, pit_cap + n_pstream, rng_p)
    Yp0, Yp_stream = Yp_all[:pit_cap], Yp_all[pit_cap:]
    bq = TPUBackend(filter="pit_qr")
    with jax.default_matmul_precision("highest"):
        res_p = fit(DynamicFactorModel(n_factors=k), Yp0, backend=bq,
                    max_iters=max(8, cold_iters // 4), fused=True,
                    telemetry=False)
        eng_walls = {}
        for eng in ("info", "pit_qr"):
            s = open_session(res_p, Yp0, backend=bq, capacity=pit_cap,
                             max_update_rows=rows, max_iters=serve_iters,
                             tol=0.0, ring=True, filter=eng)
            s.update(Yp_stream[:rows])      # compile + warm
            ws = []
            for i in range(1, pit_queries + 1):
                t0 = time.perf_counter()
                s.update(Yp_stream[i * rows:(i + 1) * rows])
                ws.append(time.perf_counter() - t0)
            s.close()
            eng_walls[eng] = ws
    pit_p50 = 1e3 * _pct(eng_walls["pit_qr"], 50)
    info_p50 = 1e3 * _pct(eng_walls["info"], 50)
    pit_speedup = (sum(eng_walls["info"]) / sum(eng_walls["pit_qr"])
                   if sum(eng_walls["pit_qr"]) > 0 else 0.0)
    log(f"pit_qr ring leg (window {pit_cap}): p50 {pit_p50:.1f} ms vs "
        f"info twin {info_p50:.1f} ms — {pit_speedup:.2f}x")

    # -- leg 2: fleet tiering (more tenants than lanes) -----------------
    n_t0 = 40
    rng2 = np.random.default_rng(178)
    tn = max(2, n_tenants)
    resident = max(1, min(resident, tn - 1))
    with jax.default_matmul_precision("highest"):
        tenants, panels, streams = [], [], []
        for i in range(tn):
            pt = dgp.dfm_params(10, 2, rng2)
            Yt, _ = dgp.simulate(pt, n_t0 + 8, rng2)
            r = fit(DynamicFactorModel(n_factors=2), Yt[:n_t0],
                    max_iters=8, telemetry=False)
            tenants.append(r)
            panels.append(Yt[:n_t0])
            streams.append(Yt[n_t0:])
        tr2 = Tracer()
        with activate(tr2):
            fl = open_fleet(tenants, panels, capacity=n_t0 + 8,
                            max_update_rows=2, max_iters=3, tol=0.0,
                            resident=resident, max_classes=1)
            # Round-robin queries: with resident < tenants every submit
            # beyond the hot set pages a warm tenant in (and demotes the
            # LRU hot one) — the admit walls ARE the re-admission price.
            for rnd in range(2):
                for i in range(tn):
                    fl.submit(f"t{i}", streams[i][2 * rnd:2 * rnd + 2])
                    fl.drain()
            fl.close()
        admit_walls = [e["wall"] for e in tr2.events
                       if e.get("kind") == "page"
                       and e.get("action") == "admit"]
    readmission_ms = (1e3 * _pct(admit_walls, 50)) if admit_walls else 0.0
    log(f"tiering: {tn} tenants on {resident} lanes, "
        f"{len(admit_walls)} page-ins, readmission p50 "
        f"{readmission_ms:.1f} ms")

    ts_sum = tracer.summary()
    log(f"telemetry: {ts_sum['dispatches']} dispatches, "
        f"{ts_sum['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"stream_qps_{N}x{cap}",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "value_definition": ("warm ring-session query throughput at a "
                             "FULL panel: every query evicts the oldest "
                             "rows in-graph and appends new ones (one "
                             "fused dispatch, d2h barrier included)"),
        "stream_qps": round(qps, 2),
        "stream_p50_ms": round(p50_ms, 2),
        "stream_p99_ms": round(p99_ms, 2),
        "stream_fixed_p99_ms": round(fixed_p99, 2),
        "evictions_per_query": round(evictions_per_query, 3),
        "readmission_ms": round(readmission_ms, 2),
        "stream_pit_speedup": round(pit_speedup, 3),
        "stream_pit_p50_ms": round(pit_p50, 2),
        "stream_pit_info_p50_ms": round(info_p50, 2),
        "stream_pit_capacity": pit_cap,
        "stream_pit_queries": pit_queries,
        "stream_blocking_transfers_per_query": round(per_query, 3),
        "recompiles_after_warmup": int(recomp),
        "rows_evicted": int(n_evicted),
        "n_queries": n_queries,
        "rows_per_query": rows,
        "serve_iters": serve_iters,
        "tiering_tenants": tn,
        "tiering_resident_lanes": resident,
        "tiering_page_ins": len(admit_walls),
        "shape": [N, cap, k],
        "dispatches": ts_sum["dispatches"],
        "recompiles": ts_sum["recompiles"],
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_stream")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Within-process ablation of the steady-state EM iteration.

Replicates the ss EM body (e_step + M-step, unmasked) with switchable
pieces, scans it 300x fused, and times every variant in ONE process (the
between-process variance on this tunnel is +/-50%, so cross-process
comparisons lie; within-process ones are stable).  full - variant = the
ablated piece's marginal cost.  Run: ``python -m bench.profile_em3``."""

import os
import sys
import time
from functools import partial

import numpy as np


def main():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))
    tau = int(os.environ.get("DFM_BENCH_TAU", 8))
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 300))

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.estim.em import (EMConfig, _m_step, moment_sums,
                                  mstep_rows, mstep_dynamics_sums)
    from dfm_tpu.ssm.params import SSMParams as JP, SmootherResult
    from dfm_tpu.ssm import steady
    from dfm_tpu.ssm.steady import _cov_path, _freeze, _affine_combine
    from dfm_tpu.ssm.info_filter import (obs_stats, quad_local, u_from_stats,
                                         loglik_from_terms)
    from dfm_tpu.ops.linalg import sym, psd_cholesky, chol_solve
    from dfm_tpu.ops.scan import blocked_scan

    rng = np.random.default_rng(0)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Y, k)
    dtype = jnp.float32
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)

    # Ablation switches (static): each removes ONE piece, replacing its
    # output with a cheap same-shaped fake that keeps upstream alive.
    PIECES = ("covpath", "fwdmeans", "smcov", "jpath", "revmeans",
              "quad", "syf", "bpass", "moments")

    def em_body(Y, p, cfg, skip: frozenset, Ysq):
        T_, k_ = Y.shape[0], p.A.shape[0]
        I_k = jnp.eye(k_, dtype=Y.dtype)
        if "bpass" in skip:
            G = p.Lam[:64] / p.R[:64, None]
            b = Y[:, :64] @ G                       # 64-series stand-in
            C = p.Lam.T @ (p.Lam / p.R[:, None])
            from dfm_tpu.ssm.info_filter import ObsStats
            from dfm_tpu.ops.precision import accum_dtype
            acc = accum_dtype(Y.dtype)
            stats = ObsStats(b, C, jnp.full((T_,), float(N), Y.dtype),
                             jnp.full((T_,), 1.0).astype(acc))
        else:
            stats = obs_stats(Y, p.Lam, p.R)
        C = stats.C

        if "covpath" in skip:
            P1 = sym(p.P0 * 0.5)
            Pp_ex = jnp.broadcast_to(P1, (tau, k_, k_))
            Pf_ex = jnp.broadcast_to(P1 * 0.3, (tau, k_, k_))
            M_ex = jnp.broadcast_to(p.A * 0.5, (tau, k_, k_))
            ldG_ex = jnp.ones((tau,), Y.dtype)
            delta = jnp.zeros((), Y.dtype)
        else:
            Pp_ex, Pf_ex, M_ex, ldG_ex, delta = _cov_path(
                C, p.A, p.Q, p.P0, tau, Y.dtype)
        P_pred = _freeze(Pp_ex, T_, tau)
        P_filt = _freeze(Pf_ex, T_, tau)
        M_path = _freeze(M_ex, T_, tau)
        logdetG = _freeze(ldG_ex, T_, tau)

        b = stats.b
        x0 = p.mu0 + Pf_ex[0] @ (b[0] - C @ p.mu0)
        if "fwdmeans" in skip:
            x_filt = jnp.einsum("tkl,tl->tk", P_filt, b)
        else:
            d = jnp.einsum("tkl,tl->tk", P_filt[1:], b[1:])
            Mpref, dpref = blocked_scan(_affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mpref, x0) + dpref
            x_filt = jnp.concatenate([x0[None], x_tail], axis=0)
        x_pred = jnp.concatenate([p.mu0[None], x_filt[:-1] @ p.A.T], axis=0)

        if "jpath" in skip:
            J = jnp.broadcast_to(p.A * 0.4, (T_ - 1, k_, k_))
            J_ss = p.A * 0.4
        else:
            Lp_ex = psd_cholesky(Pp_ex[1:])
            APf_ex = jnp.einsum("ij,tjk->tik", p.A, Pf_ex[:-1])
            J_ex = jnp.swapaxes(jax.vmap(chol_solve)(Lp_ex, APf_ex), -1, -2)
            Lp_ss = psd_cholesky(Pp_ex[-1])
            J_ss = chol_solve(Lp_ss, p.A @ Pf_ex[-1]).T
            J = jnp.concatenate(
                [J_ex, jnp.broadcast_to(J_ss, (T_ - tau, k_, k_))], axis=0)

        Pp_ss, Pf_ss = Pp_ex[-1], Pf_ex[-1]
        if "smcov" in skip:
            P_sm = P_filt
        else:
            def bstep_ss(Ps, _):
                Ps_new = sym(Pf_ss + J_ss @ (Ps - Pp_ss) @ J_ss.T)
                return Ps_new, Ps_new

            Ps_mid, Psm_end_rev = lax.scan(bstep_ss, Pf_ss, None, length=tau)
            Psm_end = jnp.flip(Psm_end_rev, axis=0)

            def bstep_ex(Ps, inp):
                P_f_t, P_p_next, J_t = inp
                Ps_new = sym(P_f_t + J_t @ (Ps - P_p_next) @ J_t.T)
                return Ps_new, Ps_new

            Pp_next_ex = jnp.concatenate([Pp_ex[1:], Pp_ex[-1:]], axis=0)
            _, Psm_front_rev = lax.scan(
                bstep_ex, Ps_mid, (Pf_ex, Pp_next_ex, J[:tau]), reverse=True)
            n_mid = T_ - 1 - 2 * tau
            P_sm = jnp.concatenate([
                Psm_front_rev,
                jnp.broadcast_to(Ps_mid, (n_mid, k_, k_)),
                Psm_end,
                Pf_ss[None],
            ], axis=0)

        if "revmeans" in skip:
            x_sm = x_filt
        else:
            c = x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, x_pred[1:])
            Jr, cr = blocked_scan(
                lambda late, early: _affine_combine(late, early),
                (J, c), reverse=True)
            x_head = jnp.einsum("tkl,l->tk", Jr, x_filt[-1]) + cr
            x_sm = jnp.concatenate([x_head, x_filt[-1:]], axis=0)

        P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
        P_lag = jnp.concatenate([jnp.zeros((1, k_, k_), Y.dtype),
                                 P_lag_tail], axis=0)
        sm = SmootherResult(x_sm, P_sm, P_lag)

        if "quad" in skip:
            quad_R = stats.n
        else:
            quad_R, _ = quad_local(Y, p.Lam, p.R, x_pred, None)
        ll = loglik_from_terms(stats, logdetG, P_filt, quad_R,
                               u_from_stats(stats, x_pred))

        # ----- M-step -----
        if "moments" in skip:
            S_ff = C * 0.1 + I_k * float(T_)
            S_lag = S_cur = S_ff
            S_cross = S_ff * 0.5
        else:
            S_ff, S_lag, S_cur, S_cross = moment_sums(sm)
        if "syf" in skip:
            Lam, R = p.Lam, p.R
        else:
            Lam, R = mstep_rows(Y, None, sm.x_sm, None, None, S_ff,
                                1e-6, Ysq=Ysq)
        A, Q, mu0, P0 = mstep_dynamics_sums(sm, S_lag, S_cur, S_cross,
                                            p, EMConfig())
        return JP(Lam, A, Q, R, mu0, P0), (ll, delta)

    @partial(jax.jit, static_argnames=("skip", "n"))
    def em_scan(Y, p, skip, n):
        Ysq = jnp.einsum("ti,ti->i", Y, Y)

        def body(p_c, _):
            return em_body(Y, p_c, None, skip, Ysq)

        return lax.scan(body, p, None, length=n)[1]

    def timed(skip):
        f = lambda: em_scan(Yj, pj, skip, n_iters)
        np.asarray(f()[0])
        reps = []
        for _ in range(4):
            t0 = time.perf_counter()
            np.asarray(f()[0])
            reps.append(time.perf_counter() - t0)
        return min(reps)

    with jax.default_matmul_precision("highest"):
        full = timed(frozenset())
        print(f"{'FULL replica':12s} {full / n_iters * 1e3:7.3f} ms/iter "
              f"(tau={tau}, {n_iters} fused)")
        for piece in PIECES:
            t = timed(frozenset([piece]))
            print(f"-{piece:11s} {t / n_iters * 1e3:7.3f} ms/iter   "
                  f"piece costs {(full - t) / n_iters * 1e3:+7.3f}")
        t = timed(frozenset(PIECES))
        print(f"-ALL         {t / n_iters * 1e3:7.3f} ms/iter (skeleton)")
        # real em_fit_scan for cross-check, same process
        from dfm_tpu.estim.em import em_fit_scan
        cfg = EMConfig(filter="ss", tau=tau)
        np.asarray(em_fit_scan(Yj, pj, n_iters, cfg=cfg)[1])
        reps = []
        for _ in range(4):
            t0 = time.perf_counter()
            np.asarray(em_fit_scan(Yj, pj, n_iters, cfg=cfg)[1])
            reps.append(time.perf_counter() - t0)
        print(f"real em_fit_scan {min(reps) / n_iters * 1e3:7.3f} ms/iter")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Streaming nowcast-session benchmark: warm per-query latency of
``dfm_tpu.open_session`` updates (ONE fused program per query, panel and
params device-resident) vs the cold baseline (a full ``fit(fused=True)``
on the extended panel every time new rows arrive).  Prints exactly ONE
JSON line to stdout:

    {"metric": ..., "value": N, "unit": "ms",
     "serve_p50_ms": N, "serve_p99_ms": N,
     "serve_blocking_transfers_per_query": N, ...}

``value`` is the warm p50 query wall in milliseconds (host-observed,
d2h barrier included — the serving-latency view).  The first query
compiles the session executable and is excluded from the percentiles;
``recompiles_after_warmup`` must stay 0 (shape-stable ragged updates
reuse ONE executable).

Run on the real chip: ``python -m bench.serve``.  Smoke-size via
DFM_BENCH_N/T/K, DFM_BENCH_QUERIES (warm queries, default 20),
DFM_BENCH_ROWS (rows per query, default 2), DFM_BENCH_SERVE_ITERS
(EM iterations per update, default 5), DFM_BENCH_ITERS (cold-fit EM
budget, default 50).  Diagnostics on stderr.
"""

import json
import os
import time

import numpy as np

from bench._common import log, pct as _pct, record_run


def main():
    N = int(os.environ.get("DFM_BENCH_N", 30))
    T = int(os.environ.get("DFM_BENCH_T", 120))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    n_queries = int(os.environ.get("DFM_BENCH_QUERIES", 20))
    rows = int(os.environ.get("DFM_BENCH_ROWS", 2))
    serve_iters = int(os.environ.get("DFM_BENCH_SERVE_ITERS", 5))
    cold_iters = int(os.environ.get("DFM_BENCH_ITERS", 50))

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly
    import jax.numpy as jnp

    from dfm_tpu import DynamicFactorModel, fit, open_session
    from dfm_tpu.obs.trace import Tracer, activate, current_tracer
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    # warm-up + traced leg + untraced (tracing-overhead) leg
    n_stream = (2 * n_queries + 1) * rows
    log(f"device: {dev.platform} ({dev.device_kind}); panel ({N}, {T}) "
        f"k={k}, {n_queries} warm queries x {rows} rows, "
        f"{serve_iters} EM iters/update")

    rng = np.random.default_rng(77)
    p_true = dgp.dfm_params(N, k, rng)
    Y_all, _ = dgp.simulate(p_true, T + n_stream, rng)
    Y0, Y_stream = Y_all[:T], Y_all[T:]

    model = DynamicFactorModel(n_factors=k)
    tracer = current_tracer()
    if tracer is None:
        tracer = Tracer()

    with activate(tracer), jax.default_matmul_precision("highest"):
        res = fit(model, Y0, max_iters=cold_iters, fused=True)
        # Cold baseline: what a caller pays today per new-data arrival —
        # a budget-matched rolling-window fused refit (same EM iteration
        # count, one dispatch, warm params), but a full-panel host prep +
        # h2d upload per query: the content changed, so the fused panel
        # cache can't help.  First refit compiles the serve-budget
        # program and is excluded.
        p = res.params
        cold_walls = []
        for i in range(min(5, n_queries) + 1):
            lo = (i + 1) * rows
            Y_roll = np.ascontiguousarray(Y_all[lo:lo + T])
            t0 = time.perf_counter()
            r = fit(model, Y_roll, max_iters=serve_iters, tol=0.0,
                    fused=True, init=p)
            if i > 0:   # skip the compile call
                cold_walls.append(time.perf_counter() - t0)
            p = r.params
        cold_ms = 1e3 * _pct(cold_walls, 50)
        log(f"cold rolling refit ({T} rows, {serve_iters} iters, upload "
            f"per query): p50 {cold_ms:.1f} ms")
        # Semantically-equivalent cold baseline: a fused refit of the
        # GROWING concatenated panel — exactly what update() is pinned
        # against numerically.  Every arrival changes T, so XLA builds a
        # new executable per query; that recompile stream is the dominant
        # cost the session's capacity padding exists to remove.
        ext_walls = []
        p = res.params
        for i in range(3):
            Y_ext = Y_all[:T + (i + 1) * rows]
            t0 = time.perf_counter()
            r = fit(model, Y_ext, max_iters=serve_iters, tol=0.0,
                    fused=True, init=p)
            ext_walls.append(time.perf_counter() - t0)
            p = r.params
        ext_ms = 1e3 * _pct(ext_walls, 50)
        log(f"cold growing-panel refit (recompile per query): "
            f"p50 {ext_ms:.1f} ms")

        sess = open_session(res, Y0, capacity=T + n_stream,
                            max_update_rows=rows, max_iters=serve_iters,
                            tol=0.0)
        sess.update(Y_stream[:rows])  # compile + warm the one executable

        base = tracer.summary()
        walls = []
        for i in range(1, n_queries + 1):
            t0 = time.perf_counter()
            sess.update(Y_stream[i * rows:(i + 1) * rows])
            walls.append(time.perf_counter() - t0)
        warm = tracer.summary()

        # Tracing-overhead leg: the same warm queries with the tracer
        # masked (activate(None) — no spans, no request waterfalls, zero
        # clock reads).  Best-of-N on both sides isolates the span
        # plumbing's tax from scheduler noise.
        untraced_walls = []
        with activate(None):
            for i in range(n_queries + 1, 2 * n_queries + 1):
                t0 = time.perf_counter()
                sess.update(Y_stream[i * rows:(i + 1) * rows])
                untraced_walls.append(time.perf_counter() - t0)
    trace_overhead_pct = (100.0 * (min(walls) - min(untraced_walls))
                          / min(untraced_walls))
    log(f"tracing overhead: traced best {1e3 * min(walls):.2f} ms vs "
        f"untraced best {1e3 * min(untraced_walls):.2f} ms "
        f"({trace_overhead_pct:+.1f}%)")

    p50_ms = 1e3 * _pct(walls, 50)
    p99_ms = 1e3 * _pct(walls, 99)
    blocking = warm["blocking_transfers"] - base["blocking_transfers"]
    per_query = blocking / n_queries
    recomp = (warm["programs"].get("serve_update", {}).get("recompiles", 0)
              - base["programs"].get("serve_update", {}).get("recompiles",
                                                             0))
    # Queries answered in degraded mode (divergence retry / repair): 0 on
    # a healthy bench; recorded + gated exactly (no noise floor) so a
    # serving regression that silently leans on the repair ladder trips.
    degraded = (warm.get("robustness", {}).get("degraded_queries", 0)
                - base.get("robustness", {}).get("degraded_queries", 0))
    log(f"warm queries: p50 {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms, "
        f"{per_query:.2f} blocking transfers/query, "
        f"{recomp} recompiles after warmup; {ext_ms / p50_ms:.1f}x vs the "
        f"growing-panel refit, {cold_ms / p50_ms:.2f}x vs rolling")

    ts_sum = tracer.summary()
    log(f"telemetry: {ts_sum['dispatches']} dispatches, "
        f"{ts_sum['recompiles']} recompiles"
        + (f" -> {tracer.path}" if tracer.path else ""))

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"serve_warm_query_p50_ms_{N}x{T}",
        "value": round(p50_ms, 2),
        "unit": "ms",
        "value_definition": ("host-observed wall of one warm streaming "
                             "nowcast query (ragged row append + EM "
                             "warm iterations + smooth + forecasts, one "
                             "fused dispatch, d2h barrier included)"),
        "serve_p50_ms": round(p50_ms, 2),
        "serve_p99_ms": round(p99_ms, 2),
        "serve_blocking_transfers_per_query": round(per_query, 3),
        "serve_degraded_queries": int(degraded),
        "trace_overhead_pct": round(trace_overhead_pct, 2),
        "cold_extend_refit_ms": round(ext_ms, 2),
        "cold_rolling_refit_ms": round(cold_ms, 2),
        "speedup_vs_cold_refit": round(ext_ms / p50_ms, 2),
        "recompiles_after_warmup": int(recomp),
        "n_queries": n_queries,
        "rows_per_query": rows,
        "serve_iters": serve_iters,
        "shape": [N, T, k],
        "dispatches": ts_sum["dispatches"],
        "recompiles": ts_sum["recompiles"],
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_serve")


if __name__ == "__main__":
    main()

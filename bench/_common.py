"""Shared bench-CLI plumbing: logging, percentiles, timing, run records.

Every bench module (``bench.py``, ``bench.serve``, ``bench.mixed``,
``bench.fleet``, ``bench.all``) shares the same contract — exactly ONE
JSON line on stdout, diagnostics on stderr, and an optional RunRecord
appended to the perf-observatory registry.  This module owns the pieces
they all duplicate; the contracts themselves are unchanged.
"""

import sys
import time

__all__ = ["log", "pct", "timed", "parse_mix", "record_run"]


def log(*a):
    """stderr-only diagnostics (stdout is reserved for the ONE JSON line)."""
    print(*a, file=sys.stderr, flush=True)


def pct(xs, q):
    """Nearest-rank percentile (same convention as obs.report)."""
    ys = sorted(xs)
    return ys[min(int(round(q / 100.0 * (len(ys) - 1))), len(ys) - 1)]


def timed(f, reps=3):
    """Best-of-N wall of ``f()`` after one warm-up/compile call."""
    f()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def parse_mix(spec):
    """Same grammar as ``obs.advise --jobs``: N,T,K[xC] joined by ';'."""
    shapes = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        mult = 1
        if "x" in part.rsplit(",", 1)[-1]:
            part, m = part.rsplit("x", 1)
            mult = int(m)
        N, T, k = (int(x) for x in part.split(","))
        shapes.extend([(N, T, k)] * mult)
    return shapes


def record_run(payload, dev, kind):
    """Append this run to the perf-observatory registry (obs.store);
    stderr-only diagnostics, same contract as bench.py."""
    from dfm_tpu.obs import store as obs_store
    d = obs_store.runs_dir()
    if d is None:
        return
    try:
        rec = obs_store.record_from_bench_json(
            payload, device=f"{dev.platform} ({dev.device_kind})",
            kind=kind)
        obs_store.RunStore(d).append(rec)
        log(f"run {payload['run_id']} recorded in {d}/")
    except Exception as e:  # registry failure must not fail the bench
        log(f"WARNING: run registry append failed: {e}")

"""Shared bench-CLI plumbing: logging, percentiles, timing, run records.

Every bench module (``bench.py``, ``bench.serve``, ``bench.mixed``,
``bench.fleet``, ``bench.all``) shares the same contract — exactly ONE
JSON line on stdout, diagnostics on stderr, and an optional RunRecord
appended to the perf-observatory registry.  This module owns the pieces
they all duplicate; the contracts themselves are unchanged.
"""

import sys
import time

__all__ = ["log", "pct", "timed", "parse_mix", "record_run",
           "engine_sweep_point"]


def log(*a):
    """stderr-only diagnostics (stdout is reserved for the ONE JSON line)."""
    print(*a, file=sys.stderr, flush=True)


def pct(xs, q):
    """Nearest-rank percentile (same convention as obs.report)."""
    ys = sorted(xs)
    return ys[min(int(round(q / 100.0 * (len(ys) - 1))), len(ys) - 1)]


def timed(f, reps=3):
    """Best-of-N wall of ``f()`` after one warm-up/compile call."""
    f()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def parse_mix(spec):
    """Same grammar as ``obs.advise --jobs``: N,T,K[xC] joined by ';'."""
    shapes = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        mult = 1
        if "x" in part.rsplit(",", 1)[-1]:
            part, m = part.rsplit("x", 1)
            mult = int(m)
        N, T, k = (int(x) for x in part.split(","))
        shapes.extend([(N, T, k)] * mult)
    return shapes


def engine_sweep_point(model, N, T, k, *, backends, iters, reps, seed,
                       baseline):
    """One engine-comparison sweep point (shared by bench.longt /
    bench.kscale — the sweep-loop scaffolding they would otherwise each
    copy).

    Builds the panel (DGP -> standardize -> PCA init), fits an f64
    sequential-info reference at the same budget, then for each entry of
    ``backends`` (name -> zero-arg TPUBackend factory) fits once for the
    f32 final-loglik error and times the warm chunked fit best-of-``reps``
    (the fit's own d2h read is the execution barrier — CLAUDE.md).

    Returns {"walls", "errs" (relative final-loglik error vs the f64
    reference), "speedup" (wall of ``baseline`` over each engine),
    "ll_ref", "panel": (Y standardized, Y raw, F true factors, p_true,
    p0)} so callers can run engine-specific extra legs (noise ratios,
    calibration) without rebuilding the panel.
    """
    import numpy as np

    import jax.numpy as jnp

    from dfm_tpu import TPUBackend, fit
    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp

    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y_raw, F = dgp.simulate(p_true, T, rng)
    Y = (Y_raw - Y_raw.mean(0)) / Y_raw.std(0)
    p0 = cpu_ref.pca_init(Y, k)

    # f64 sequential reference loglik at the same budget: the yardstick
    # every f32 engine's final-loglik error divides against.
    ref = fit(model, Y, max_iters=iters, tol=0.0, init=p0,
              backend=TPUBackend(dtype=jnp.float64, filter="info"))
    ll_ref = float(ref.logliks[-1])

    walls, errs = {}, {}
    for name, make in backends.items():
        b = make()
        r = fit(model, Y, max_iters=iters, tol=0.0, init=p0, backend=b)
        errs[name] = abs(float(r.logliks[-1]) - ll_ref) / abs(ll_ref)
        walls[name] = timed(
            lambda b=b: fit(model, Y, max_iters=iters, tol=0.0,
                            init=p0, backend=b), reps)
    speedup = {name: walls[baseline] / walls[name] for name in walls}
    return {"walls": walls, "errs": errs, "speedup": speedup,
            "ll_ref": ll_ref, "panel": (Y, Y_raw, F, p_true, p0)}


def record_run(payload, dev, kind):
    """Append this run to the perf-observatory registry (obs.store);
    stderr-only diagnostics, same contract as bench.py."""
    from dfm_tpu.obs import store as obs_store
    d = obs_store.runs_dir()
    if d is None:
        return
    try:
        rec = obs_store.record_from_bench_json(
            payload, device=f"{dev.platform} ({dev.device_kind})",
            kind=kind)
        obs_store.RunStore(d).append(rec)
        log(f"run {payload['run_id']} recorded in {d}/")
    except Exception as e:  # registry failure must not fail the bench
        log(f"WARNING: run registry append failed: {e}")

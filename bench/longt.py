#!/usr/bin/env python
"""Long-T time-scan sweep: sequential vs parallel-in-time filtering.

Sweeps the EM fit over panel lengths (default T in {300, 1000, 4000})
under three in-loop time-scan engines at the same shape, budget, and
f32 dtype — ``filter="info"`` (the sequential scan), ``filter="pit"``
(the legacy covariance-form parallel scan), and ``filter="pit_qr"``
(the square-root QR-factor parallel scan) — and prints exactly ONE JSON
line to stdout:

    {"metric": ..., "value": N, "unit": "x",
     "pit_qr_speedup_t300": N, "pit_qr_speedup_t1000": N,
     "pit_qr_speedup_t4000": N, "pit_qr_noise_ratio": N, ...}

``value`` is the pit_qr speedup over the sequential scan at the largest
sweep point (wall of the same warm chunked fit, best-of-N with the d2h
read as the barrier).  ``pit_qr_noise_ratio`` compares the f32 final
loglik error of pit_qr against the sequential scan's, both measured
against the f64 sequential fit at the same budget (ratio <= ~1 means
the square-root combine holds the sequential noise level — the
"matched numerics" half of the long-T contract).

Run on the real chip: ``python -m bench.longt``.  Smoke-size via
DFM_BENCH_N/K, DFM_BENCH_TSWEEP (comma list, default "300,1000,4000"),
DFM_BENCH_ITERS (EM budget per fit, default 16), DFM_BENCH_REPS
(best-of-N, default 3).  Diagnostics on stderr.
"""

import json
import os

import numpy as np

from bench._common import log, record_run, timed


def main():
    N = int(os.environ.get("DFM_BENCH_N", 24))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    sweep = [int(t) for t in os.environ.get(
        "DFM_BENCH_TSWEEP", "300,1000,4000").split(",") if t]
    iters = int(os.environ.get("DFM_BENCH_ITERS", 16))
    reps = int(os.environ.get("DFM_BENCH_REPS", 3))

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 reference fits
    import jax.numpy as jnp

    from dfm_tpu import DynamicFactorModel, TPUBackend, fit
    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} k={k} "
        f"T sweep {sweep}, {iters} EM iters/fit, best of {reps}")

    model = DynamicFactorModel(n_factors=k, standardize=False)
    engines = ("info", "pit", "pit_qr")
    payload = {}
    results = []
    with jax.default_matmul_precision("highest"):
        for T in sweep:
            rng = np.random.default_rng(1000 + T)
            p_true = dgp.dfm_params(N, k, rng)
            Y, _ = dgp.simulate(p_true, T, rng)
            Y = (Y - Y.mean(0)) / Y.std(0)
            p0 = cpu_ref.pca_init(Y, k)

            # f64 sequential reference loglik at the same budget: the
            # yardstick both f32 engines' final-loglik errors divide
            # against.
            ref = fit(model, Y, max_iters=iters, tol=0.0, init=p0,
                      backend=TPUBackend(dtype=jnp.float64, filter="info"))
            ll_ref = float(ref.logliks[-1])

            walls, errs = {}, {}
            for eng in engines:
                b = TPUBackend(dtype=jnp.float32, filter=eng)
                r = fit(model, Y, max_iters=iters, tol=0.0, init=p0,
                        backend=b)
                errs[eng] = abs(float(r.logliks[-1]) - ll_ref) / abs(ll_ref)
                walls[eng] = timed(
                    lambda b=b: fit(model, Y, max_iters=iters, tol=0.0,
                                    init=p0, backend=b), reps)
            spd = {e: walls["info"] / walls[e] for e in engines}
            log(f"T={T}: seq {1e3 * walls['info']:.1f} ms"
                + "".join(f", {e} {1e3 * walls[e]:.1f} ms "
                          f"({spd[e]:.2f}x, f32 err {errs[e]:.2e})"
                          for e in ("pit", "pit_qr")))
            payload[f"pit_qr_speedup_t{T}"] = round(spd["pit_qr"], 3)
            payload[f"pit_speedup_t{T}"] = round(spd["pit"], 3)
            payload[f"seq_iters_per_sec_t{T}"] = round(
                iters / walls["info"], 2)
            results.append((T, spd["pit_qr"], errs))

    # Noise ratio at the largest sweep point: eps*N*T noise is worst
    # there, so it is the binding comparison.
    T_max, spd_max, errs_max = results[-1]
    noise_ratio = errs_max["pit_qr"] / max(errs_max["info"], 1e-7)
    payload.update({
        "metric": f"longt_pit_qr_speedup_T{T_max}",
        "value": round(spd_max, 3),
        "unit": "x",
        "value_definition": ("warm chunked-fit wall of the sequential "
                            "info scan divided by the pit_qr scan at the "
                            "largest sweep T (same shape, budget, f32)"),
        "pit_qr_noise_ratio": round(noise_ratio, 3),
        "f32_loglik_rel_err_seq": errs_max["info"],
        "f32_loglik_rel_err_pit_qr": errs_max["pit_qr"],
        "sweep_T": sweep,
        "shape_N_k": [N, k],
        "em_iters": iters,
    })
    from dfm_tpu.obs.store import new_run_id
    payload["run_id"] = new_run_id()
    print(json.dumps(payload))
    record_run(payload, dev, "bench_longt")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Long-T time-scan sweep: sequential vs parallel-in-time filtering.

Sweeps the EM fit over panel lengths (default T in {300, 1000, 4000})
under three in-loop time-scan engines at the same shape, budget, and
f32 dtype — ``filter="info"`` (the sequential scan), ``filter="pit"``
(the legacy covariance-form parallel scan), and ``filter="pit_qr"``
(the square-root QR-factor parallel scan) — and prints exactly ONE JSON
line to stdout:

    {"metric": ..., "value": N, "unit": "x",
     "pit_qr_speedup_t300": N, "pit_qr_speedup_t1000": N,
     "pit_qr_speedup_t4000": N, "pit_qr_noise_ratio": N, ...}

``value`` is the pit_qr speedup over the sequential scan at the largest
sweep point (wall of the same warm chunked fit, best-of-N with the d2h
read as the barrier).  ``pit_qr_noise_ratio`` compares the f32 final
loglik error of pit_qr against the sequential scan's, both measured
against the f64 sequential fit at the same budget (ratio <= ~1 means
the square-root combine holds the sequential noise level — the
"matched numerics" half of the long-T contract).

Run on the real chip: ``python -m bench.longt``.  Smoke-size via
DFM_BENCH_N/K, DFM_BENCH_TSWEEP (comma list, default "300,1000,4000"),
DFM_BENCH_ITERS (EM budget per fit, default 16), DFM_BENCH_REPS
(best-of-N, default 3).  Diagnostics on stderr.
"""

import json
import os

from bench._common import engine_sweep_point, log, record_run


def main():
    N = int(os.environ.get("DFM_BENCH_N", 24))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    sweep = [int(t) for t in os.environ.get(
        "DFM_BENCH_TSWEEP", "300,1000,4000").split(",") if t]
    iters = int(os.environ.get("DFM_BENCH_ITERS", 16))
    reps = int(os.environ.get("DFM_BENCH_REPS", 3))

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 reference fits
    import jax.numpy as jnp

    from dfm_tpu import DynamicFactorModel, TPUBackend

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} k={k} "
        f"T sweep {sweep}, {iters} EM iters/fit, best of {reps}")

    model = DynamicFactorModel(n_factors=k, standardize=False)
    engines = ("info", "pit", "pit_qr")
    payload = {}
    results = []
    with jax.default_matmul_precision("highest"):
        for T in sweep:
            res = engine_sweep_point(
                model, N, T, k,
                backends={e: (lambda e=e: TPUBackend(dtype=jnp.float32,
                                                     filter=e))
                          for e in engines},
                iters=iters, reps=reps, seed=1000 + T, baseline="info")
            walls, errs, spd = res["walls"], res["errs"], res["speedup"]
            log(f"T={T}: seq {1e3 * walls['info']:.1f} ms"
                + "".join(f", {e} {1e3 * walls[e]:.1f} ms "
                          f"({spd[e]:.2f}x, f32 err {errs[e]:.2e})"
                          for e in ("pit", "pit_qr")))
            payload[f"pit_qr_speedup_t{T}"] = round(spd["pit_qr"], 3)
            payload[f"pit_speedup_t{T}"] = round(spd["pit"], 3)
            payload[f"seq_iters_per_sec_t{T}"] = round(
                iters / walls["info"], 2)
            results.append((T, spd["pit_qr"], errs))

    # Noise ratio at the largest sweep point: eps*N*T noise is worst
    # there, so it is the binding comparison.
    T_max, spd_max, errs_max = results[-1]
    noise_ratio = errs_max["pit_qr"] / max(errs_max["info"], 1e-7)
    payload.update({
        "metric": f"longt_pit_qr_speedup_T{T_max}",
        "value": round(spd_max, 3),
        "unit": "x",
        "value_definition": ("warm chunked-fit wall of the sequential "
                            "info scan divided by the pit_qr scan at the "
                            "largest sweep T (same shape, budget, f32)"),
        "pit_qr_noise_ratio": round(noise_ratio, 3),
        "f32_loglik_rel_err_seq": errs_max["info"],
        "f32_loglik_rel_err_pit_qr": errs_max["pit_qr"],
        "sweep_T": sweep,
        "shape_N_k": [N, k],
        "em_iters": iters,
    })
    from dfm_tpu.obs.store import new_run_id
    payload["run_id"] = new_run_id()
    print(json.dumps(payload))
    record_run(payload, dev, "bench_longt")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Decompose the headline EM iteration cost on the real chip.

Times fused scans (150 reps, data-dependency-chained so XLA cannot hoist)
of each piece of the steady-state EM iteration separately:

  panel   the three (T,N) MXU passes (b = Y G, the residual quad pass,
          S_yf = Y' Ef) plus the k-sized M-step algebra
  cov     the tau-step sequential covariance path (``steady._cov_path``)
  means   the blocked affine scans (filtered + smoothed means)
  smcov   the smoother covariance fixed point + front boundary
  full    the whole ``em_fit_scan`` iteration

and prints per-iteration milliseconds for each, at several tau values.
This is the measurement behind docs/PERF.md's roofline table.  Run on the
real chip: ``python -m bench.profile_em``.  Shapes via DFM_BENCH_N/T/K.
"""

import os
import sys
import time
from functools import partial

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 150))

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.ssm.params import SSMParams as JP
    from dfm_tpu.ssm import steady
    from dfm_tpu.ssm.info_filter import obs_stats, loglik_terms_local
    from dfm_tpu.ops.scan import blocked_scan
    from dfm_tpu.ssm.steady import riccati_mixing_steps

    rng = np.random.default_rng(0)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Y, k)
    mix = riccati_mixing_steps(p0)
    log(f"shape {N}x{T} k={k}; riccati mixing {mix} steps")

    dtype = jnp.float32
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)

    def timed(fn, *args):
        # warm-up (compile) + best-of-3; transfer is the only barrier on axon
        np.asarray(jax.tree.leaves(fn(*args))[0])
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(jax.tree.leaves(fn(*args))[0])
            reps.append(time.perf_counter() - t0)
        return min(reps)

    # Chain trick: eps = 0 * (scalar from prev iter) keeps a loop-carried
    # data dependency so neither CSE nor LICM can collapse the scan body.
    def chain(x, scalar):
        return x * (1.0 + jnp.zeros((), x.dtype) * scalar.astype(x.dtype))

    @partial(jax.jit, static_argnames=("n",))
    def panel_scan(Yj, p, n):
        def body(carry, _):
            Lam, R = chain(p.Lam, carry), p.R
            stats = obs_stats(Yj, Lam, R)
            x_fake = stats.b @ jnp.linalg.inv(stats.C)        # (T, k)
            quad_R, U = loglik_terms_local(Yj, Lam, R, x_fake, None)
            S_yf = Yj.T @ x_fake
            Ysq = jnp.einsum("ti,ti->i", Yj, Yj)
            out = (jnp.sum(quad_R) + jnp.sum(U) + jnp.sum(S_yf)
                   + jnp.sum(Ysq) + jnp.sum(stats.b)).astype(Yj.dtype)
            return out, out
        return lax.scan(body, jnp.zeros((), Yj.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n", "tau"))
    def cov_scan(p, C, n, tau):
        def body(carry, _):
            Cc = chain(C, carry)
            Pp, Pf, M, ldG, delta = steady._cov_path(
                Cc, p.A, p.Q, p.P0, tau, dtype)
            out = (jnp.sum(Pp[-1]) + jnp.sum(Pf[-1]) + jnp.sum(M[-1])
                   + jnp.sum(ldG) + delta)
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_scan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = blocked_scan(steady._affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            # reverse smoothed-mean-style scan
            Jr, cr = blocked_scan(
                lambda late, early: steady._affine_combine(late, early),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n", "tau"))
    def smcov_scan(p, C, n, tau):
        # smoother covariance fixed point + front boundary, at fixed inputs
        from dfm_tpu.ops.linalg import sym, psd_cholesky, chol_solve
        Pp_ex, Pf_ex, M_ex, ldG_ex, _ = steady._cov_path(
            C, p.A, p.Q, p.P0, tau, dtype)
        Lp_ss = psd_cholesky(Pp_ex[-1])
        J_ss = chol_solve(Lp_ss, p.A @ Pf_ex[-1]).T
        Pp_ss, Pf_ss = Pp_ex[-1], Pf_ex[-1]

        def body(carry, _):
            Pf_c = chain(Pf_ss, carry)

            def bstep_ss(Ps, _):
                Ps_new = sym(Pf_c + J_ss @ (Ps - Pp_ss) @ J_ss.T)
                return Ps_new, Ps_new

            Ps_mid, rev = lax.scan(bstep_ss, Pf_c, None, length=tau)

            def bstep_ex(Ps, inp):
                P_f_t, P_p_next, J_t = inp
                Ps_new = sym(P_f_t + J_t @ (Ps - P_p_next) @ J_t.T)
                return Ps_new, Ps_new

            Pp_next_ex = jnp.concatenate([Pp_ex[1:], Pp_ex[-1:]], axis=0)
            Lp_ex = psd_cholesky(Pp_ex[1:])
            APf_ex = jnp.einsum("ij,tjk->tik", p.A, Pf_ex[:-1])
            J_ex = jnp.swapaxes(jax.vmap(chol_solve)(Lp_ex, APf_ex), -1, -2)
            J_front = jnp.concatenate([J_ex, J_ss[None]], axis=0)
            _, front = lax.scan(bstep_ex, Ps_mid,
                                (Pf_ex, Pp_next_ex, J_front), reverse=True)
            out = jnp.sum(rev[-1]) + jnp.sum(front[0])
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    with jax.default_matmul_precision("highest"):
        C0 = np.asarray((p0.Lam / p0.R[:, None]).T @ p0.Lam, np.float32)
        Cj = jnp.asarray(C0)
        b0 = jnp.asarray(rng.standard_normal((T, k)), dtype)
        M0 = jnp.asarray(
            np.broadcast_to(np.asarray(p0.A, np.float32) * 0.5, (T, k, k)))
        Pf0 = jnp.asarray(np.broadcast_to(np.eye(k, dtype=np.float32) * 0.3,
                                          (T, k, k)))

        rows = []
        t = timed(panel_scan, Yj, pj, n_iters)
        rows.append(("panel (3 MXU passes + k-alg)", "-", t))
        t = timed(means_scan, b0, M0, Pf0, n_iters)
        rows.append(("means (2 blocked affine scans)", "-", t))
        for tau in (16, 32, 64, 96):
            t = timed(cov_scan, pj, Cj, n_iters, tau)
            rows.append(("cov path", tau, t))
            t = timed(smcov_scan, pj, Cj, n_iters, tau)
            rows.append(("smoother cov (fp + front)", tau, t))
            cfg = EMConfig(filter="ss", tau=tau)
            out = em_fit_scan(Yj, pj, n_iters, cfg=cfg)
            np.asarray(out[1])
            reps = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(em_fit_scan(Yj, pj, n_iters, cfg=cfg)[1])
                reps.append(time.perf_counter() - t0)
            rows.append(("FULL em_fit_scan", tau, min(reps)))

    print(f"\n{'component':36s} {'tau':>4s} {'ms/iter':>9s}")
    for name, tau, secs in rows:
        print(f"{name:36s} {str(tau):>4s} {secs / n_iters * 1e3:9.3f}")


if __name__ == "__main__":
    main()

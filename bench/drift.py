#!/usr/bin/env python
"""Closed-loop maintenance soak: drifting panel, managed vs frozen twin
(ISSUE 18).

Simulates a regime break mid-stream — the serving panel switches to a
fresh DGP draw (new loadings, new dynamics, hotter scale) while two ring
fleets serve the IDENTICAL update stream in one interleaved loop (paired
design: host-state disturbances hit both twins).  BOTH twins serve at
the fleet's minimal per-query warm-EM budget (1 iteration — the serving
floor, ``fleet/driver.py`` clamps ``max_iters`` to >= 1), so the query
paths are the same executable and the comparison isolates the closed
loop.  The *frozen* twin never retrains beyond that floor; the
*managed* twin additionally runs the drift detector (``obs/drift.py``)
on the query signals every update
already emits, and on each detector FIRING runs one
``fleet.run_maintenance`` pass between queries: background warm-started
refit on the current ring window, held-out quality gate, in-place hot
swap.  Prints exactly ONE JSON line:

    {"metric": ..., "value": N, "unit": "heldout_mse_gain",
     "drift_detection_lag_updates": N, "managed_vs_frozen_heldout_gain": N,
     "drift_swaps_total": N, "drift_false_positive_rate": N,
     "drift_p99_ratio": N, ...}

``value`` is ``managed_vs_frozen_heldout_gain``: frozen minus managed
held-out one-step MSE (standardized units), AVERAGED over every
post-break update — the regret a floor-budget serving twin keeps paying
after the regime turns and the drift->refit->swap loop removes.
Positive means the loop bought real forecast quality.  ``drift_p99_ratio`` is the managed twin's serving p99 over
the frozen twin's (maintenance passes and scoring run BETWEEN timed
queries): the acceptance bound is <= 1.05 — the loop must not tax the
serving path.  ``recompiles_after_warmup`` must stay 0 through every
refit + swap.  Smoke-size via DFM_BENCH_N/K,
DFM_BENCH_DRIFT_T0 (ring window, default 80), DFM_BENCH_DRIFT_PRE /
DFM_BENCH_DRIFT_POST (updates before/after the break, default 20/30),
DFM_BENCH_ROWS (rows/update, default 2), DFM_BENCH_SERVE_ITERS (EM
iters/update, default 1 = the serving floor), DFM_BENCH_ITERS (cold-fit budget, default
30), DFM_BENCH_DRIFT_REFIT_ITERS (background refit budget, default 40),
DFM_BENCH_DRIFT_MAX_SWAPS (maintenance-pass cap, default 3).
Diagnostics on stderr.
"""

import dataclasses
import gc
import json
import os
import time

import numpy as np

from bench._common import log, pct as _pct, record_run


def main():
    N = int(os.environ.get("DFM_BENCH_N", 12))
    k = int(os.environ.get("DFM_BENCH_K", 2))
    T0 = int(os.environ.get("DFM_BENCH_DRIFT_T0", 80))
    n_pre = int(os.environ.get("DFM_BENCH_DRIFT_PRE", 20))
    n_post = int(os.environ.get("DFM_BENCH_DRIFT_POST", 30))
    rows = int(os.environ.get("DFM_BENCH_ROWS", 2))
    serve_iters = int(os.environ.get("DFM_BENCH_SERVE_ITERS", 1))
    cold_iters = int(os.environ.get("DFM_BENCH_ITERS", 30))
    refit_iters = int(os.environ.get("DFM_BENCH_DRIFT_REFIT_ITERS", 40))
    max_swaps = int(os.environ.get("DFM_BENCH_DRIFT_MAX_SWAPS", 3))

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 loglik assembly
    from dfm_tpu import DynamicFactorModel, fit, open_fleet
    from dfm_tpu.fleet import MaintenancePolicy, heldout_score, \
        run_maintenance
    from dfm_tpu.obs import live
    from dfm_tpu.obs.drift import DriftConfig
    from dfm_tpu.obs.trace import Tracer, activate
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    n_updates = n_pre + n_post
    holdout = max(4, min(16, n_post * rows // 2))
    log(f"device: {dev.platform} ({dev.device_kind}); panel ({T0}, {N}) "
        f"k={k}, break after {n_pre} updates, {n_post} post-break "
        f"updates x {rows} rows, {serve_iters} EM iters/update, "
        f"refit budget {refit_iters}")

    # Two regimes from independent DGP draws: stale params are genuinely
    # wrong post-break (loadings AND dynamics change), and regime B runs
    # hotter so standardized innovations shift in location too.
    # Seed choice matters at this panel scale: a draw whose healthy
    # stretch contains a factor excursion reads as drift to ANY
    # sensitive detector (seed 181 does, max healthy score 2.7).  These
    # seeds give a typical healthy regime (max score ~0.3 across a
    # 6-seed sweep) so the fp metric measures the detector, not one
    # unlucky draw.
    rng_a = np.random.default_rng(300)
    rng_b = np.random.default_rng(301)
    p_a = dgp.dfm_params(N, k, rng_a)
    p_b = dgp.dfm_params(N, k, rng_b)
    Y_pre_all, _ = dgp.simulate(p_a, T0 + n_pre * rows, rng_a)
    Y_post, _ = dgp.simulate(p_b, n_post * rows, rng_b)
    Y0 = Y_pre_all[:T0]
    stream = np.concatenate([Y_pre_all[T0:], 1.5 * Y_post], axis=0)

    model = DynamicFactorModel(n_factors=k)
    cfg = DriftConfig()
    live.set_drift(cfg)
    policy = MaintenancePolicy(holdout_rows=holdout,
                               max_iters=refit_iters)

    def score_of(fl, name):
        """Held-out one-step MSE of the twin's CURRENT params on its
        CURRENT trailing panel (standardized units, masked)."""
        _, slot = fl._slot_of[name]
        Yz = slot.std.transform(np.asarray(slot.Y_orig, np.float64))
        W = np.asarray(slot.W_orig, np.float64)
        Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
        p = fl._slot_params_np(*fl._slot_of[name])
        return heldout_score(Yz, W, p, holdout)

    tracer = Tracer()
    walls = {"frozen": [], "managed": []}
    scores = {"frozen": [], "managed": []}
    swaps, lag, pre_fired, seen_fires = 0, None, 0, 0
    with activate(tracer), jax.default_matmul_precision("highest"):
        res = fit(model, Y0, max_iters=cold_iters, fused=True,
                  telemetry=False)
        # Ring window = T0 rows: the healthy regime is stationary so the
        # steady pre-break eviction is inert to the detector's baseline,
        # and post-break the window turns over to the new regime at
        # rows/update — each successive refit trains on an increasingly
        # post-break panel, as it would on a real turned series.
        fleets = {
            name: open_fleet([res], [Y0], tenants=[name],
                             capacity=T0,
                             max_update_rows=rows, max_iters=serve_iters,
                             tol=0.0, ring=True)
            for name in ("frozen", "managed")}
        for name, fl in fleets.items():
            fl.submit(name, stream[:rows])
            fl.drain()                       # compile + warm
        # Warm the background-refit program too (min_gain=inf -> the
        # quality gate always skips, params untouched): the first
        # in-loop firing must pay dispatch walls, not XLA compilation.
        run_maintenance(fleets["managed"], ["managed"],
                        policy=dataclasses.replace(
                            policy, min_gain=float("inf")))
        gc.collect()
        base = tracer.summary()
        # p99 at soak sizes is the max wall: keep GC pauses off the
        # timed region entirely (collect UNTIMED each iteration, both
        # twins see the same allocator state).
        gc.disable()
        for i in range(1, n_updates):
            gc.collect()
            # Alternate twin order so box-level drift (cache state, GC
            # debt) averages out of the paired percentiles.
            order = (("frozen", "managed") if i % 2
                     else ("managed", "frozen"))
            for name in order:
                fl = fleets[name]
                t0 = time.perf_counter()
                fl.submit(name, stream[i * rows:(i + 1) * rows])
                fl.drain()
                walls[name].append(time.perf_counter() - t0)
            st = live.drift_status()["per_tenant"].get("managed", {})
            if i == n_pre - 1:
                # Firings before the break are false positives by
                # construction (healthy regime).
                pre_fired = int(st.get("n_fired", 0))
            fired = int(st.get("n_fired", 0))
            if fired > seen_fires and swaps < max_swaps:
                # One maintenance pass per detector FIRING (not per
                # breached update — a skip verdict stands until the
                # detector re-fires on fresh evidence).
                seen_fires = fired
                if lag is None and i >= n_pre:
                    lag = i - n_pre + 1
                recs = run_maintenance(fleets["managed"], ["managed"],
                                       policy=policy)
                swaps += sum(r.action == "swap" for r in recs)
                log(f"  update {i}: drift fired "
                    f"(score {st.get('drift_score', 0.0):.2f}) -> "
                    f"{recs[0].action} "
                    f"(delta {recs[0].quality_delta:+.4g})")
                # Refit garbage must not tax the next serving wall.
                gc.collect()
                seen_fires = int(live.drift_status()["per_tenant"]
                                 .get("managed", {}).get("n_fired", 0))
            if i >= n_pre:
                # Post-break transient regret (untimed, both twins).
                for name in ("frozen", "managed"):
                    scores[name].append(score_of(fleets[name], name))
        gc.enable()
        warm = tracer.summary()
        final = {name: score_of(fleets[name], name)
                 for name in ("frozen", "managed")}
        for fl in fleets.values():
            fl.close()

    recomp = (warm["programs"].get("serve_update", {})
              .get("recompiles", 0)
              - base["programs"].get("serve_update", {})
              .get("recompiles", 0))
    # Nearest-rank p99 over ~40 walls is the MAX: a single host
    # scheduler stall (tens of ms on the 1-core fallback box, landing
    # on either twin at random) would decide the ratio.  Reject
    # outliers SYMMETRICALLY — one cut from the pooled walls of both
    # twins — so isolated stalls drop out while any systematic
    # maintenance tax (which shifts the managed twin's walls
    # consistently, and is also guarded by recompiles_after_warmup==0)
    # survives.  Trimmed counts are logged, never silent.
    pooled = np.asarray(walls["frozen"] + walls["managed"])
    med = float(np.median(pooled))
    mad = float(np.median(np.abs(pooled - med)))
    cut = med + 10.0 * max(mad, 1e-9)
    kept = {name: [w for w in walls[name] if w <= cut] or walls[name]
            for name in walls}
    n_trim = {name: len(walls[name]) - len(kept[name]) for name in walls}
    if any(n_trim.values()):
        log(f"trimmed scheduler-stall walls above {1e3 * cut:.2f} ms: "
            f"{n_trim['frozen']} frozen, {n_trim['managed']} managed")
    frozen_p99 = 1e3 * _pct(kept["frozen"], 99)
    managed_p99 = 1e3 * _pct(kept["managed"], 99)
    p99_ratio = managed_p99 / frozen_p99 if frozen_p99 > 0 else 1.0
    mean_f = float(np.mean(scores["frozen"]))
    mean_m = float(np.mean(scores["managed"]))
    gain = mean_f - mean_m
    lag = lag if lag is not None else n_post
    n_scored_pre = max(1, n_pre - cfg.baseline_n - cfg.min_updates)
    fp_rate = pre_fired / n_scored_pre

    log(f"frozen twin: post-break heldout MSE {mean_f:.4g} (final "
        f"{final['frozen']:.4g}), p99 {frozen_p99:.2f} ms")
    log(f"managed twin: post-break heldout MSE {mean_m:.4g} (final "
        f"{final['managed']:.4g}), p99 {managed_p99:.2f} ms, "
        f"{swaps} swaps, detection lag {lag} updates, {recomp} "
        f"serve_update recompiles after warmup")
    log(f"heldout gain {gain:+.4g} (positive = maintenance helped), "
        f"serving p99 ratio {p99_ratio:.3f}, false-positive rate "
        f"{fp_rate:.3f}")

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"drift_soak_{N}x{T0}",
        "value": round(gain, 6),
        "unit": "heldout_mse_gain",
        "value_definition": ("frozen-twin minus managed-twin held-out "
                             "one-step MSE (standardized units), "
                             "averaged over every post-break update of "
                             "an identical simulated regime break; both "
                             "twins serve at the 1-iter warm-EM floor — "
                             "the regret the drift->refit->swap loop "
                             "removes"),
        "managed_vs_frozen_heldout_gain": round(gain, 6),
        "drift_detection_lag_updates": int(lag),
        "drift_swaps_total": int(swaps),
        "drift_false_positive_rate": round(fp_rate, 4),
        "drift_p99_ratio": round(p99_ratio, 4),
        "managed_heldout_mse": round(mean_m, 6),
        "frozen_heldout_mse": round(mean_f, 6),
        "managed_final_heldout_mse": round(final["managed"], 6),
        "frozen_final_heldout_mse": round(final["frozen"], 6),
        "managed_p99_ms": round(managed_p99, 2),
        "frozen_p99_ms": round(frozen_p99, 2),
        "stall_walls_trimmed": int(sum(n_trim.values())),
        "recompiles_after_warmup": int(recomp),
        "n_updates": n_updates,
        "break_after_updates": n_pre,
        "rows_per_update": rows,
        "serve_iters": serve_iters,
        "refit_iters": refit_iters,
        "holdout_rows": holdout,
        "shape": [N, T0, k],
        "dispatches": warm["dispatches"],
        "recompiles": warm["recompiles"],
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_drift")


if __name__ == "__main__":
    main()

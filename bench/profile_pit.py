#!/usr/bin/env python
"""Settle the parallel-in-time filter's promised win (VERDICT r4 item 8).

Times one fused log-likelihood evaluation (filter only) for the
sequential info-form scan vs the associative-scan PIT filter vs the
steady-state engine, across T, at small N/k (the long-context regime the
PIT filter exists for).  Run on the current device:

    python -m bench.profile_pit                 # real TPU
    JAX_PLATFORMS='' python -m bench.profile_pit --cpu   # multi-core CPU

(--cpu forces the multithreaded XLA CPU backend in-process; the
sequential scan cannot use extra cores, the PIT combines can.)
"""

import argparse
import sys
import time
from functools import partial

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--N", type=int, default=32)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--Ts", default="2048,8192,32768")
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.ssm.info_filter import info_filter
    from dfm_tpu.ssm.parallel_filter import pit_filter
    from dfm_tpu.ssm.steady import ss_filter
    from dfm_tpu.ssm.params import SSMParams as JP

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr)
    dtype = jnp.float32 if dev.platform == "tpu" else jnp.float64

    rng = np.random.default_rng(0)
    N, k = args.N, args.k
    p_true = dgp.dfm_params(N, k, rng)

    @partial(jax.jit, static_argnames=("which",))
    def ll(Y, p, which):
        f = {"info": info_filter, "pit": pit_filter,
             "ss": partial(ss_filter, tau=16)}[which]
        return f(Y, p).loglik

    def timed(Y, p, which):
        np.asarray(ll(Y, p, which))
        reps = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(ll(Y, p, which))
            reps.append(time.perf_counter() - t0)
        return min(reps)

    print(f"{'T':>7s} {'info ms':>9s} {'pit ms':>9s} {'ss ms':>9s} "
          f"{'pit speedup':>12s}")
    with jax.default_matmul_precision("highest"):
        for T in (int(t) for t in args.Ts.split(",")):
            Y, _ = dgp.simulate(p_true, T, rng)
            Y = (Y - Y.mean(0)) / Y.std(0)
            Yj = jnp.asarray(Y, dtype)
            pj = JP.from_numpy(cpu_ref.pca_init(Y, k), dtype=dtype)
            ti = timed(Yj, pj, "info")
            tp = timed(Yj, pj, "pit")
            ts = timed(Yj, pj, "ss")
            print(f"{T:7d} {ti * 1e3:9.1f} {tp * 1e3:9.1f} {ts * 1e3:9.1f} "
                  f"{ti / tp:11.2f}x")


if __name__ == "__main__":
    main()

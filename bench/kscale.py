#!/usr/bin/env python
"""Wide-k state-axis sweep: exact vs rank-r computation-aware filtering.

Sweeps the EM fit over state dimensions (default k in {10, 25, 50, 100})
under the exact information-form scan (``filter="info"``) and the rank-r
downdate engine (``filter="lowrank"``, arXiv 2405.08971) at the same
shape, budget, and f32 dtype, and prints exactly ONE JSON line to stdout:

    {"metric": "kscale_speedup_k50", "value": N, "unit": "x",
     "kscale_speedup_k10": N, ..., "kscale_calib_err": N,
     "kscale_mf_m25_wall_s": N, ...}

``value`` is the rank-r speedup over the exact scan at k = 50 (warm
chunked fit wall, best-of-N with the d2h read as the barrier — the
acceptance headline).  Two extra legs ride along:

- calibration: at the largest sweep k the exact and rank-r smoothers run
  at the TRUE DGP params on a fresh unstandardized panel (fixed params —
  no EM, so the latent factors are identified and coverage against the
  simulated truth is meaningful).  ``kscale_calib_err`` is
  |coverage - 0.90| of the rank-r smoother's 90% bands; the downdate is
  conservative (covariance >= exact in the PSD order) so honest bands
  can only match or widen — exact-smoother coverage is reported next to
  it as the yardstick.
- MF m~25: the mixed-frequency augmented shape the axon compiler
  SIGABRTs on under the exact masked scan (CLAUDE.md) completes a
  rank-r fit; its wall is recorded (``kscale_mf_m25_wall_s``).  Only
  the lowrank leg runs — the bench must not trip the documented crash.

Run on the real chip: ``python -m bench.kscale``.  Smoke-size via
DFM_BENCH_N/T, DFM_BENCH_KSWEEP (comma list, default "10,25,50,100"),
DFM_BENCH_RANK (downdate rank, default 0 = auto min(k, 8)),
DFM_BENCH_ITERS (EM budget per fit, default 12), DFM_BENCH_REPS
(best-of-N, default 3), DFM_BENCH_MF_T (MF leg length, default 60;
empty/0 skips).  Diagnostics on stderr.
"""

import json
import os

from bench._common import engine_sweep_point, log, record_run, timed


def main():
    N = int(os.environ.get("DFM_BENCH_N", 120))
    T = int(os.environ.get("DFM_BENCH_T", 200))
    sweep = [int(x) for x in os.environ.get(
        "DFM_BENCH_KSWEEP", "10,25,50,100").split(",") if x]
    rank = int(os.environ.get("DFM_BENCH_RANK", 0))
    iters = int(os.environ.get("DFM_BENCH_ITERS", 12))
    reps = int(os.environ.get("DFM_BENCH_REPS", 3))
    mf_T = int(os.environ.get("DFM_BENCH_MF_T", "60") or 0)

    import numpy as np

    import jax
    jax.config.update("jax_enable_x64", True)  # f64 reference/calib legs
    import jax.numpy as jnp

    from dfm_tpu import DynamicFactorModel, TPUBackend
    from dfm_tpu.ssm.lowrank_filter import (lowrank_filter_smoother,
                                            resolve_rank, state_coverage)

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} T={T} "
        f"k sweep {sweep}, rank={rank or 'auto'}, {iters} EM iters/fit, "
        f"best of {reps}")

    payload = {}
    results = []
    with jax.default_matmul_precision("highest"):
        for k in sweep:
            model = DynamicFactorModel(n_factors=k, standardize=False)
            res = engine_sweep_point(
                model, N, T, k,
                backends={
                    "info": lambda: TPUBackend(dtype=jnp.float32,
                                               filter="info"),
                    "lowrank": lambda: TPUBackend(dtype=jnp.float32,
                                                  filter="lowrank",
                                                  rank=rank),
                },
                iters=iters, reps=reps, seed=3000 + k, baseline="info")
            walls, errs, spd = res["walls"], res["errs"], res["speedup"]
            r_eff = resolve_rank(k, rank)
            log(f"k={k} (r={r_eff}): exact {1e3 * walls['info']:.1f} ms, "
                f"lowrank {1e3 * walls['lowrank']:.1f} ms "
                f"({spd['lowrank']:.2f}x; ll drift vs f64 exact "
                f"{errs['lowrank']:.2e} — approximation, not noise, "
                f"when r < k)")
            payload[f"kscale_speedup_k{k}"] = round(spd["lowrank"], 3)
            payload[f"kscale_exact_iters_per_sec_k{k}"] = round(
                iters / walls["info"], 2)
            results.append((k, spd["lowrank"], res))

        # --- calibration leg: fixed TRUE params at the largest sweep k ---
        # Identified factors (no EM rotation), f64, raw panel: coverage of
        # the simulated truth by the 90% smoother bands.
        k_cal, _, res_cal = results[-1]
        _, Y_raw, F_true, p_true, _ = res_cal["panel"]
        from dfm_tpu.ssm.kalman import rts_smoother
        from dfm_tpu.ssm.info_filter import info_filter
        from dfm_tpu.ssm.params import SSMParams as JP
        pj = JP.from_numpy(p_true, dtype=jnp.float64)
        Yj = jnp.asarray(Y_raw, jnp.float64)
        kf_ex = info_filter(Yj, pj)
        sm_ex = rts_smoother(kf_ex, pj)
        _, sm_lr = lowrank_filter_smoother(Yj, pj, rank=rank)
        cov_ex = state_coverage(sm_ex.x_sm, sm_ex.P_sm, F_true)
        cov_lr = state_coverage(sm_lr.x_sm, sm_lr.P_sm, F_true)
        calib_err = abs(cov_lr - 0.90)
        log(f"calibration @ k={k_cal}: exact coverage {cov_ex:.3f}, "
            f"lowrank coverage {cov_lr:.3f} (|err| {calib_err:.3f})")
        payload.update({
            "kscale_calib_err": round(calib_err, 4),
            "kscale_coverage_lowrank": round(cov_lr, 4),
            "kscale_coverage_exact": round(cov_ex, 4),
        })

        # --- MF m~25 leg: the previously-uncompilable augmented shape ---
        if mf_T > 0:
            from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
            from dfm_tpu.utils import dgp as _dgp
            rng = np.random.default_rng(77)
            Ym, maskm, _, _ = _dgp.simulate_mixed_freq(
                n_monthly=30, n_quarterly=8, T=mf_T, k=5, rng=rng)
            spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=5,
                                 time_scan="lowrank", rank=rank)
            m_aug = spec.state_dim
            mf_wall = timed(lambda: mf_fit(Ym, spec, mask=maskm,
                                           max_iters=4, tol=0.0), reps)
            log(f"MF m={m_aug} lowrank fit: {1e3 * mf_wall:.1f} ms "
                f"(exact path documented to SIGABRT on axon)")
            payload["kscale_mf_m25_wall_s"] = round(mf_wall, 4)
            payload["kscale_mf_state_dim"] = m_aug

    # Headline: the k=50 acceptance point when swept, else the largest k.
    spd_by_k = {k: s for k, s, _ in results}
    head_k = 50 if 50 in spd_by_k else results[-1][0]
    payload.update({
        "metric": f"kscale_speedup_k{head_k}",
        "value": round(spd_by_k[head_k], 3),
        "unit": "x",
        "value_definition": ("warm chunked-fit wall of the exact info "
                            "scan divided by the rank-r lowrank scan at "
                            f"k={head_k} (same shape, budget, f32)"),
        "sweep_k": sweep,
        "rank": rank,
        "shape_N_T": [N, T],
        "em_iters": iters,
    })
    from dfm_tpu.obs.store import new_run_id
    payload["run_id"] = new_run_id()
    print(json.dumps(payload))
    record_run(payload, dev, "bench_kscale")


if __name__ == "__main__":
    main()

"""The five benchmark configs of BASELINE.json:6-12 as named presets.

SURVEY.md section 5 (config/flag system row) prescribes these be checked in;
``bench.run`` and the root-level ``bench.py`` harness consume them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class BenchConfig:
    name: str
    description: str
    N: int                      # series
    T: int                      # time steps
    k: int                      # factors
    dynamics: str = "ar1"       # model.dynamics
    em_iters: int = 20
    kind: str = "plain"         # plain | missing | mixed_freq | tvl | sv
    frac_missing: float = 0.0
    n_quarterly: int = 0
    seed: int = 0


CONFIGS = {
    # BASELINE.json:7 — the CPU-reference config.
    "s1": BenchConfig("s1", "2-factor static DFM, 50x200, PCA init + 20 EM "
                            "iters (CPU ref)",
                      N=50, T=200, k=2, dynamics="static", em_iters=20),
    # BASELINE.json:8
    "s2": BenchConfig("s2", "10-factor AR(1) DFM, 1000x500",
                      N=1000, T=500, k=10, em_iters=20),
    # BASELINE.json:9
    "s3": BenchConfig("s3", "Mixed-frequency nowcasting DFM, 2000 series, "
                            "missing obs",
                      N=2000, T=300, k=5, em_iters=10, kind="mixed_freq",
                      frac_missing=0.1, n_quarterly=400),
    # BASELINE.json:10
    "s4": BenchConfig("s4", "Time-varying-loadings DFM, 5000 series",
                      N=5000, T=300, k=4, em_iters=5, kind="tvl"),
    # BASELINE.json:11
    "s5": BenchConfig("s5", "SV-DFM via particle Kalman filter, 10000x1000",
                      N=10000, T=1000, k=5, em_iters=1, kind="sv"),
    # BASELINE.json:2 — the headline metric shape.
    "headline": BenchConfig("headline", "EM iters/sec, 10000x500, 10 factors",
                            N=10000, T=500, k=10, em_iters=10),
}


def get(name: str) -> BenchConfig:
    try:
        return CONFIGS[name]
    except KeyError:
        raise SystemExit(f"unknown config {name!r}; have {sorted(CONFIGS)}")

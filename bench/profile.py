#!/usr/bin/env python
"""Profiling harness for the EM iteration and the filters (one script,
subcommands — consolidates the former profile_em{,2,3}.py / profile_pit.py).

  components  per-piece ms/iter of the steady-state EM iteration (the
              measurement behind docs/PERF.md's roofline table)
  slope       fixed-vs-marginal cost: time the fused scan at several
              n_iters and fit a line; slope = true per-iteration device
              cost, intercept = per-dispatch overhead
  ablate      within-process ablation of the ss EM body (between-process
              variance on this tunnel is +/-50%; within-process deltas
              are stable — full - variant = that piece's marginal cost)
  pit         sequential info-form vs associative-scan PIT filter vs ss
              engine, one fused loglik pass across T (VERDICT r4 item 8)

Run on the real chip: ``python -m bench.profile <subcommand>``.
Shapes via DFM_BENCH_N/T/K (and DFM_BENCH_TAU/ITERS for ablate);
``pit`` takes --N/--k/--Ts/--cpu flags instead (small-N long-T regime).
All diagnostics go to stdout as tables — this is NOT the one-JSON-line
bench contract (that is bench.py / bench/batched.py).
"""

import argparse
import os
import sys
import time
from functools import partial

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _env_shapes():
    N = int(os.environ.get("DFM_BENCH_N", 10_000))
    T = int(os.environ.get("DFM_BENCH_T", 500))
    k = int(os.environ.get("DFM_BENCH_K", 10))
    return N, T, k


def _panel(N, T, k, dtype):
    """Standardized simulated panel + PCA init on device (f32)."""
    import jax
    import jax.numpy as jnp
    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.ssm.params import SSMParams as JP

    rng = np.random.default_rng(0)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    Y = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Y, k)
    Yj = jax.device_put(jnp.asarray(Y, dtype))
    pj = JP.from_numpy(p0, dtype=dtype)
    return rng, Y, p0, Yj, pj


def _timed(fn, *args, reps=3):
    """Warm-up (compile) + best-of-N; transfer is the only barrier on axon."""
    import jax
    np.asarray(jax.tree.leaves(fn(*args))[0])
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.tree.leaves(fn(*args))[0])
        ts.append(time.perf_counter() - t0)
    return min(ts)


# ---------------------------------------------------------------------------
# components — per-piece ms/iter (formerly profile_em.py)
# ---------------------------------------------------------------------------

def cmd_components(args):
    N, T, k = _env_shapes()
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 150))

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.ssm import steady
    from dfm_tpu.ssm.info_filter import obs_stats, loglik_terms_local
    from dfm_tpu.ops.scan import blocked_scan
    from dfm_tpu.ssm.steady import riccati_mixing_steps

    dtype = jnp.float32
    rng, Y, p0, Yj, pj = _panel(N, T, k, dtype)
    mix = riccati_mixing_steps(p0)
    log(f"shape {N}x{T} k={k}; riccati mixing {mix} steps")

    # Chain trick: eps = 0 * (scalar from prev iter) keeps a loop-carried
    # data dependency so neither CSE nor LICM can collapse the scan body.
    def chain(x, scalar):
        return x * (1.0 + jnp.zeros((), x.dtype) * scalar.astype(x.dtype))

    @partial(jax.jit, static_argnames=("n",))
    def panel_scan(Yj, p, n):
        def body(carry, _):
            Lam, R = chain(p.Lam, carry), p.R
            stats = obs_stats(Yj, Lam, R)
            x_fake = stats.b @ jnp.linalg.inv(stats.C)        # (T, k)
            quad_R, U = loglik_terms_local(Yj, Lam, R, x_fake, None)
            S_yf = Yj.T @ x_fake
            Ysq = jnp.einsum("ti,ti->i", Yj, Yj)
            out = (jnp.sum(quad_R) + jnp.sum(U) + jnp.sum(S_yf)
                   + jnp.sum(Ysq) + jnp.sum(stats.b)).astype(Yj.dtype)
            return out, out
        return lax.scan(body, jnp.zeros((), Yj.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n", "tau"))
    def cov_scan(p, C, n, tau):
        def body(carry, _):
            Cc = chain(C, carry)
            Pp, Pf, M, ldG, delta = steady._cov_path(
                Cc, p.A, p.Q, p.P0, tau, dtype)
            out = (jnp.sum(Pp[-1]) + jnp.sum(Pf[-1]) + jnp.sum(M[-1])
                   + jnp.sum(ldG) + delta)
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_scan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = blocked_scan(steady._affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            # reverse smoothed-mean-style scan
            Jr, cr = blocked_scan(
                lambda late, early: steady._affine_combine(late, early),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n", "tau"))
    def smcov_scan(p, C, n, tau):
        # smoother covariance fixed point + front boundary, at fixed inputs
        from dfm_tpu.ops.linalg import sym, psd_cholesky, chol_solve
        Pp_ex, Pf_ex, M_ex, ldG_ex, _ = steady._cov_path(
            C, p.A, p.Q, p.P0, tau, dtype)
        Lp_ss = psd_cholesky(Pp_ex[-1])
        J_ss = chol_solve(Lp_ss, p.A @ Pf_ex[-1]).T
        Pp_ss, Pf_ss = Pp_ex[-1], Pf_ex[-1]

        def body(carry, _):
            Pf_c = chain(Pf_ss, carry)

            def bstep_ss(Ps, _):
                Ps_new = sym(Pf_c + J_ss @ (Ps - Pp_ss) @ J_ss.T)
                return Ps_new, Ps_new

            Ps_mid, rev = lax.scan(bstep_ss, Pf_c, None, length=tau)

            def bstep_ex(Ps, inp):
                P_f_t, P_p_next, J_t = inp
                Ps_new = sym(P_f_t + J_t @ (Ps - P_p_next) @ J_t.T)
                return Ps_new, Ps_new

            Pp_next_ex = jnp.concatenate([Pp_ex[1:], Pp_ex[-1:]], axis=0)
            Lp_ex = psd_cholesky(Pp_ex[1:])
            APf_ex = jnp.einsum("ij,tjk->tik", p.A, Pf_ex[:-1])
            J_ex = jnp.swapaxes(jax.vmap(chol_solve)(Lp_ex, APf_ex), -1, -2)
            J_front = jnp.concatenate([J_ex, J_ss[None]], axis=0)
            _, front = lax.scan(bstep_ex, Ps_mid,
                                (Pf_ex, Pp_next_ex, J_front), reverse=True)
            out = jnp.sum(rev[-1]) + jnp.sum(front[0])
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    with jax.default_matmul_precision("highest"):
        C0 = np.asarray((p0.Lam / p0.R[:, None]).T @ p0.Lam, np.float32)
        Cj = jnp.asarray(C0)
        b0 = jnp.asarray(rng.standard_normal((T, k)), dtype)
        M0 = jnp.asarray(
            np.broadcast_to(np.asarray(p0.A, np.float32) * 0.5, (T, k, k)))
        Pf0 = jnp.asarray(np.broadcast_to(np.eye(k, dtype=np.float32) * 0.3,
                                          (T, k, k)))

        rows = []
        t = _timed(panel_scan, Yj, pj, n_iters)
        rows.append(("panel (3 MXU passes + k-alg)", "-", t))
        t = _timed(means_scan, b0, M0, Pf0, n_iters)
        rows.append(("means (2 blocked affine scans)", "-", t))
        for tau in (16, 32, 64, 96):
            t = _timed(cov_scan, pj, Cj, n_iters, tau)
            rows.append(("cov path", tau, t))
            t = _timed(smcov_scan, pj, Cj, n_iters, tau)
            rows.append(("smoother cov (fp + front)", tau, t))
            cfg = EMConfig(filter="ss", tau=tau)
            t = _timed(lambda: em_fit_scan(Yj, pj, n_iters, cfg=cfg)[1])
            rows.append(("FULL em_fit_scan", tau, t))

    print(f"\n{'component':36s} {'tau':>4s} {'ms/iter':>9s}")
    for name, tau, secs in rows:
        print(f"{name:36s} {str(tau):>4s} {secs / n_iters * 1e3:9.3f}")


# ---------------------------------------------------------------------------
# slope — fixed vs marginal via line fit (formerly profile_em2.py)
# ---------------------------------------------------------------------------

def cmd_slope(args):
    N, T, k = _env_shapes()

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.estim.em import EMConfig, em_fit_scan
    from dfm_tpu.ssm import steady
    from dfm_tpu.ops.scan import blocked_scan

    dtype = jnp.float32
    rng, Y, p0, Yj, pj = _panel(N, T, k, dtype)

    def chain(x, scalar):
        return x * (1.0 + jnp.zeros((), x.dtype) * scalar.astype(x.dtype))

    @partial(jax.jit, static_argnames=("n", "tau"))
    def cov_scan(p, C, n, tau):
        def body(carry, _):
            Cc = chain(C, carry)
            Pp, Pf, M, ldG, delta = steady._cov_path(
                Cc, p.A, p.Q, p.P0, tau, dtype)
            out = (jnp.sum(Pp[-1]) + jnp.sum(Pf[-1]) + jnp.sum(M[-1])
                   + jnp.sum(ldG) + delta)
            return out, out
        return lax.scan(body, jnp.zeros((), dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_scan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = blocked_scan(steady._affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            Jr, cr = blocked_scan(
                lambda late, early: steady._affine_combine(late, early),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    @partial(jax.jit, static_argnames=("n",))
    def means_ascan(b, M_path, Pfilt, n):
        def body(carry, _):
            bb = chain(b, carry)
            d = jnp.einsum("tkl,tl->tk", Pfilt[1:], bb[1:])
            Mp, dp = lax.associative_scan(
                lambda a, bb_: steady._affine_combine(a, bb_),
                (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mp, bb[0]) + dp
            Jr, cr = lax.associative_scan(
                lambda a, bb_: steady._affine_combine(a, bb_),
                (M_path[1:], d), reverse=True)
            out = jnp.sum(x_tail) + jnp.sum(Jr[0]) + jnp.sum(cr)
            return out, out
        return lax.scan(body, jnp.zeros((), b.dtype), None, length=n)[1]

    C0 = np.asarray((p0.Lam / p0.R[:, None]).T @ p0.Lam, np.float32)
    Cj = jnp.asarray(C0)
    b0 = jnp.asarray(rng.standard_normal((T, k)), dtype)
    M0 = jnp.asarray(
        np.broadcast_to(np.asarray(p0.A, np.float32) * 0.5, (T, k, k)))
    Pf0 = jnp.asarray(np.broadcast_to(np.eye(k, dtype=np.float32) * 0.3,
                                      (T, k, k)))

    ns = (50, 150, 300, 600)
    with jax.default_matmul_precision("highest"):
        def slope(name, f):
            ts = [_timed(f, n) for n in ns]
            A = np.vstack([np.ones(len(ns)), np.asarray(ns)]).T
            (fixed, marg), *_ = np.linalg.lstsq(A, np.asarray(ts),
                                                rcond=None)
            print(f"{name:34s} fixed {fixed * 1e3:7.1f} ms   "
                  f"marginal {marg * 1e3:7.3f} ms/iter   "
                  f"({[f'{t:.3f}' for t in ts]})")
            return fixed, marg

        slope("means", lambda n: means_scan(b0, M0, Pf0, n))
        slope("means assoc", lambda n: means_ascan(b0, M0, Pf0, n))
        for tau in (8, 16):
            slope(f"cov tau={tau}",
                  lambda n, tau=tau: cov_scan(pj, Cj, n, tau))
        for tau in (8, 16):
            cfg = EMConfig(filter="ss", tau=tau)
            slope(f"FULL em tau={tau}",
                  lambda n, cfg=cfg: em_fit_scan(Yj, pj, n, cfg=cfg)[1])


# ---------------------------------------------------------------------------
# ablate — within-process ablation of the ss EM body (formerly profile_em3)
# ---------------------------------------------------------------------------

def cmd_ablate(args):
    N, T, k = _env_shapes()
    tau = int(os.environ.get("DFM_BENCH_TAU", 8))
    n_iters = int(os.environ.get("DFM_BENCH_ITERS", 300))

    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    from dfm_tpu.estim.em import (EMConfig, moment_sums,
                                  mstep_rows, mstep_dynamics_sums)
    from dfm_tpu.ssm.params import SSMParams as JP, SmootherResult
    from dfm_tpu.ssm.steady import _cov_path, _freeze, _affine_combine
    from dfm_tpu.ssm.info_filter import (obs_stats, quad_local, u_from_stats,
                                         loglik_from_terms)
    from dfm_tpu.ops.linalg import sym, psd_cholesky, chol_solve
    from dfm_tpu.ops.scan import blocked_scan

    dtype = jnp.float32
    rng, Y, p0, Yj, pj = _panel(N, T, k, dtype)

    # Ablation switches (static): each removes ONE piece, replacing its
    # output with a cheap same-shaped fake that keeps upstream alive.
    PIECES = ("covpath", "fwdmeans", "smcov", "jpath", "revmeans",
              "quad", "syf", "bpass", "moments")

    def em_body(Y, p, cfg, skip: frozenset, Ysq):
        T_, k_ = Y.shape[0], p.A.shape[0]
        I_k = jnp.eye(k_, dtype=Y.dtype)
        if "bpass" in skip:
            G = p.Lam[:64] / p.R[:64, None]
            b = Y[:, :64] @ G                       # 64-series stand-in
            C = p.Lam.T @ (p.Lam / p.R[:, None])
            from dfm_tpu.ssm.info_filter import ObsStats
            from dfm_tpu.ops.precision import accum_dtype
            acc = accum_dtype(Y.dtype)
            stats = ObsStats(b, C, jnp.full((T_,), float(N), Y.dtype),
                             jnp.full((T_,), 1.0).astype(acc))
        else:
            stats = obs_stats(Y, p.Lam, p.R)
        C = stats.C

        if "covpath" in skip:
            P1 = sym(p.P0 * 0.5)
            Pp_ex = jnp.broadcast_to(P1, (tau, k_, k_))
            Pf_ex = jnp.broadcast_to(P1 * 0.3, (tau, k_, k_))
            M_ex = jnp.broadcast_to(p.A * 0.5, (tau, k_, k_))
            ldG_ex = jnp.ones((tau,), Y.dtype)
            delta = jnp.zeros((), Y.dtype)
        else:
            Pp_ex, Pf_ex, M_ex, ldG_ex, delta = _cov_path(
                C, p.A, p.Q, p.P0, tau, Y.dtype)
        P_pred = _freeze(Pp_ex, T_, tau)
        P_filt = _freeze(Pf_ex, T_, tau)
        M_path = _freeze(M_ex, T_, tau)
        logdetG = _freeze(ldG_ex, T_, tau)

        b = stats.b
        x0 = p.mu0 + Pf_ex[0] @ (b[0] - C @ p.mu0)
        if "fwdmeans" in skip:
            x_filt = jnp.einsum("tkl,tl->tk", P_filt, b)
        else:
            d = jnp.einsum("tkl,tl->tk", P_filt[1:], b[1:])
            Mpref, dpref = blocked_scan(_affine_combine, (M_path[1:], d))
            x_tail = jnp.einsum("tkl,l->tk", Mpref, x0) + dpref
            x_filt = jnp.concatenate([x0[None], x_tail], axis=0)
        x_pred = jnp.concatenate([p.mu0[None], x_filt[:-1] @ p.A.T], axis=0)

        if "jpath" in skip:
            J = jnp.broadcast_to(p.A * 0.4, (T_ - 1, k_, k_))
            J_ss = p.A * 0.4
        else:
            Lp_ex = psd_cholesky(Pp_ex[1:])
            APf_ex = jnp.einsum("ij,tjk->tik", p.A, Pf_ex[:-1])
            J_ex = jnp.swapaxes(jax.vmap(chol_solve)(Lp_ex, APf_ex), -1, -2)
            Lp_ss = psd_cholesky(Pp_ex[-1])
            J_ss = chol_solve(Lp_ss, p.A @ Pf_ex[-1]).T
            J = jnp.concatenate(
                [J_ex, jnp.broadcast_to(J_ss, (T_ - tau, k_, k_))], axis=0)

        Pp_ss, Pf_ss = Pp_ex[-1], Pf_ex[-1]
        if "smcov" in skip:
            P_sm = P_filt
        else:
            def bstep_ss(Ps, _):
                Ps_new = sym(Pf_ss + J_ss @ (Ps - Pp_ss) @ J_ss.T)
                return Ps_new, Ps_new

            Ps_mid, Psm_end_rev = lax.scan(bstep_ss, Pf_ss, None, length=tau)
            Psm_end = jnp.flip(Psm_end_rev, axis=0)

            def bstep_ex(Ps, inp):
                P_f_t, P_p_next, J_t = inp
                Ps_new = sym(P_f_t + J_t @ (Ps - P_p_next) @ J_t.T)
                return Ps_new, Ps_new

            Pp_next_ex = jnp.concatenate([Pp_ex[1:], Pp_ex[-1:]], axis=0)
            _, Psm_front_rev = lax.scan(
                bstep_ex, Ps_mid, (Pf_ex, Pp_next_ex, J[:tau]), reverse=True)
            n_mid = T_ - 1 - 2 * tau
            P_sm = jnp.concatenate([
                Psm_front_rev,
                jnp.broadcast_to(Ps_mid, (n_mid, k_, k_)),
                Psm_end,
                Pf_ss[None],
            ], axis=0)

        if "revmeans" in skip:
            x_sm = x_filt
        else:
            c = x_filt[:-1] - jnp.einsum("tkl,tl->tk", J, x_pred[1:])
            Jr, cr = blocked_scan(
                lambda late, early: _affine_combine(late, early),
                (J, c), reverse=True)
            x_head = jnp.einsum("tkl,l->tk", Jr, x_filt[-1]) + cr
            x_sm = jnp.concatenate([x_head, x_filt[-1:]], axis=0)

        P_lag_tail = jnp.einsum("tij,tkj->tik", P_sm[1:], J)
        P_lag = jnp.concatenate([jnp.zeros((1, k_, k_), Y.dtype),
                                 P_lag_tail], axis=0)
        sm = SmootherResult(x_sm, P_sm, P_lag)

        if "quad" in skip:
            quad_R = stats.n
        else:
            quad_R, _ = quad_local(Y, p.Lam, p.R, x_pred, None)
        ll = loglik_from_terms(stats, logdetG, P_filt, quad_R,
                               u_from_stats(stats, x_pred))

        # ----- M-step -----
        if "moments" in skip:
            S_ff = C * 0.1 + I_k * float(T_)
            S_lag = S_cur = S_ff
            S_cross = S_ff * 0.5
        else:
            S_ff, S_lag, S_cur, S_cross = moment_sums(sm)
        if "syf" in skip:
            Lam, R = p.Lam, p.R
        else:
            Lam, R = mstep_rows(Y, None, sm.x_sm, None, None, S_ff,
                                1e-6, Ysq=Ysq)
        A, Q, mu0, P0 = mstep_dynamics_sums(sm, S_lag, S_cur, S_cross,
                                            p, EMConfig())
        return JP(Lam, A, Q, R, mu0, P0), (ll, delta)

    @partial(jax.jit, static_argnames=("skip", "n"))
    def em_scan(Y, p, skip, n):
        Ysq = jnp.einsum("ti,ti->i", Y, Y)

        def body(p_c, _):
            return em_body(Y, p_c, None, skip, Ysq)

        return lax.scan(body, p, None, length=n)[1]

    def timed(skip):
        return _timed(lambda: em_scan(Yj, pj, skip, n_iters), reps=4)

    with jax.default_matmul_precision("highest"):
        full = timed(frozenset())
        print(f"{'FULL replica':12s} {full / n_iters * 1e3:7.3f} ms/iter "
              f"(tau={tau}, {n_iters} fused)")
        for piece in PIECES:
            t = timed(frozenset([piece]))
            print(f"-{piece:11s} {t / n_iters * 1e3:7.3f} ms/iter   "
                  f"piece costs {(full - t) / n_iters * 1e3:+7.3f}")
        t = timed(frozenset(PIECES))
        print(f"-ALL         {t / n_iters * 1e3:7.3f} ms/iter (skeleton)")
        # real em_fit_scan for cross-check, same process
        from dfm_tpu.estim.em import em_fit_scan
        cfg = EMConfig(filter="ss", tau=tau)
        t = _timed(lambda: em_fit_scan(Yj, pj, n_iters, cfg=cfg)[1], reps=4)
        print(f"real em_fit_scan {t / n_iters * 1e3:7.3f} ms/iter")


# ---------------------------------------------------------------------------
# pit — sequential vs parallel-in-time filter (formerly profile_pit.py)
# ---------------------------------------------------------------------------

def cmd_pit(args):
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dfm_tpu.backends import cpu_ref
    from dfm_tpu.utils import dgp
    from dfm_tpu.ssm.info_filter import info_filter
    from dfm_tpu.ssm.parallel_filter import pit_filter
    from dfm_tpu.ssm.steady import ss_filter
    from dfm_tpu.ssm.params import SSMParams as JP

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")
    dtype = jnp.float32 if dev.platform == "tpu" else jnp.float64

    rng = np.random.default_rng(0)
    N, k = args.N, args.k
    p_true = dgp.dfm_params(N, k, rng)

    @partial(jax.jit, static_argnames=("which",))
    def ll(Y, p, which):
        f = {"info": info_filter, "pit": pit_filter,
             "ss": partial(ss_filter, tau=16)}[which]
        return f(Y, p).loglik

    print(f"{'T':>7s} {'info ms':>9s} {'pit ms':>9s} {'ss ms':>9s} "
          f"{'pit speedup':>12s}")
    with jax.default_matmul_precision("highest"):
        for T in (int(t) for t in args.Ts.split(",")):
            Y, _ = dgp.simulate(p_true, T, rng)
            Y = (Y - Y.mean(0)) / Y.std(0)
            Yj = jnp.asarray(Y, dtype)
            pj = JP.from_numpy(cpu_ref.pca_init(Y, k), dtype=dtype)
            ti = _timed(ll, Yj, pj, "info")
            tp = _timed(ll, Yj, pj, "pit")
            ts = _timed(ll, Yj, pj, "ss")
            print(f"{T:7d} {ti * 1e3:9.1f} {tp * 1e3:9.1f} {ts * 1e3:9.1f} "
                  f"{ti / tp:11.2f}x")


# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m bench.profile",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("components", help="per-piece ms/iter of the ss EM body")
    sub.add_parser("slope", help="fixed vs marginal cost via n_iters fit")
    sub.add_parser("ablate", help="within-process ablation of the ss EM body")
    p_pit = sub.add_parser("pit", help="sequential vs PIT filter across T")
    p_pit.add_argument("--cpu", action="store_true")
    p_pit.add_argument("--N", type=int, default=32)
    p_pit.add_argument("--k", type=int, default=4)
    p_pit.add_argument("--Ts", default="2048,8192,32768")
    args = ap.parse_args(argv)
    {"components": cmd_components, "slope": cmd_slope,
     "ablate": cmd_ablate, "pit": cmd_pit}[args.cmd](args)


if __name__ == "__main__":
    main()

"""Run EVERY BASELINE.json config on the current device and record the
results: ``python -m bench.all [--out BENCH_ALL.json]``.

One artifact with on-device numbers for S1-S5 at spec shape plus the
headline 10k x 500 metric (VERDICT r2 item 4) — iters/sec for the EM
configs, rounds/sec for TVL, filter-pass/sec for SV.  Each config runs in
this process sequentially; the device stays warm between configs but every
config's own warm pass is what its metric comes from (see bench.run).

Every config also gets a SINGLE-THREADED CPU baseline (VERDICT r4 item 3
— BASELINE.json:5 defines the target *vs single-threaded CPU*): a pinned
subprocess runs ``bench.cpu_baseline`` (same algorithm class per family)
and ``vs_cpu`` records rate_tpu / rate_cpu per config.  Disable with
``--no-cpu`` for a quick device-only sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time


def _rate(rec):
    """The config's headline rate (iters/sec or filter-passes/sec)."""
    if not isinstance(rec, dict):
        return None
    return rec.get("sv_filter_passes_per_sec") or rec.get("em_iters_per_sec")


def cpu_baseline(name: str, timeout: float = 3600.0):
    """Run ``bench.cpu_baseline --config name`` pinned to one core."""
    env = dict(os.environ,
               OMP_NUM_THREADS="1", OPENBLAS_NUM_THREADS="1",
               MKL_NUM_THREADS="1")
    cmd = [sys.executable, "-m", "bench.cpu_baseline", "--config", name]
    if shutil.which("taskset"):
        cmd = ["taskset", "-c", "0"] + cmd
    out = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=timeout, cwd=os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__))))
    if out.returncode != 0:
        raise RuntimeError(f"cpu baseline rc={out.returncode}: "
                           f"{out.stderr.strip()[-400:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="BENCH_ALL.json")
    ap.add_argument("--configs",
                    default="s1,s2,s3,s4,s5,s3@sharded,s4@sharded,"
                            "s5@sharded,headline",
                    help="comma list; a 'name@backend' entry runs that "
                         "config on a non-default backend (no CPU rerun)")
    ap.add_argument("--no-cpu", action="store_true",
                    help="skip the single-threaded CPU baselines")
    args = ap.parse_args(argv)

    import jax
    from . import run as bench_run

    dev = jax.devices()[0]
    results = {}
    t_start = time.time()
    for name in args.configs.split(","):
        name = name.strip()
        cfg_name, _, backend = name.partition("@")
        run_args = ["--config", cfg_name, "--quiet"]
        if backend:
            run_args += ["--backend", backend]
        print(f"=== {name} ===", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        try:
            results[name] = bench_run.main(run_args)
        # SystemExit included: configs raise it for unknown names/kinds, and
        # one bad config must not discard the sweep's earlier device time.
        except (Exception, SystemExit) as e:
            results[name] = {"config": name,
                             "error": f"{type(e).__name__}: {e}"}
            print(f"{name} FAILED: {e}", file=sys.stderr, flush=True)
        results[name]["total_secs"] = time.perf_counter() - t0
        if args.no_cpu or backend or "error" in results[name]:
            continue   # name@backend variants share the base config's CPU
        print(f"=== {name} cpu baseline ===", file=sys.stderr, flush=True)
        try:
            cpu = cpu_baseline(cfg_name)
            results[name]["cpu"] = cpu
            r_tpu, r_cpu = _rate(results[name]), _rate(cpu)
            if r_tpu and r_cpu:
                results[name]["vs_cpu"] = round(r_tpu / r_cpu, 2)
            # Same-basis marginal-rate comparison (dispatch/init-free on
            # both sides — what BASELINE.json:2/5 actually define; the
            # end-to-end vs_cpu above is fixed-cost-bound at these short
            # fit lengths on BOTH device classes, see docs/PERF.md).
            s_tpu = results[name].get("em_iters_per_sec_sustained")
            s_cpu = cpu.get("em_iters_per_sec_sustained")
            if s_tpu and s_cpu:
                results[name]["vs_cpu_sustained"] = round(s_tpu / s_cpu, 2)
        except Exception as e:
            results[name]["cpu"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"{name} cpu baseline FAILED: {e}", file=sys.stderr,
                  flush=True)

    out = {
        "device": f"{dev.platform} ({dev.device_kind})",
        "recorded_unix": t_start,
        "results": results,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: {kk: vv for kk, vv in v.items()
                          if kk in ("em_iters_per_sec",
                                    "em_iters_per_sec_sustained",
                                    "sv_filter_passes_per_sec", "loglik",
                                    "vs_cpu", "vs_cpu_sustained", "error")}
                      for k, v in results.items()}))
    print(f"wrote {args.out}", file=sys.stderr)
    _record_runs(out)


def _record_runs(out):
    """Append one registry record per swept config (obs.store; default
    .dfm_runs/, DFM_RUNS overrides, DFM_RUNS="" disables)."""
    from dfm_tpu.obs import store as obs_store
    d = obs_store.runs_dir()
    if d is None:
        return
    try:
        store = obs_store.RunStore(d)
        n = 0
        for name, res in out["results"].items():
            rec = obs_store.record_from_bench_all_entry(
                name, res, device=out["device"],
                t_unix=out["recorded_unix"])
            if rec is not None:
                store.append(rec)
                n += 1
        if n:
            print(f"recorded {n} run(s) in {d}/", file=sys.stderr)
    except Exception as e:  # registry failure must not fail the sweep
        print(f"WARNING: run registry append failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""Run EVERY BASELINE.json config on the current device and record the
results: ``python -m bench.all [--out BENCH_ALL.json]``.

One artifact with on-device numbers for S1-S5 at spec shape plus the
headline 10k x 500 metric (VERDICT r2 item 4) — iters/sec for the EM
configs, rounds/sec for TVL, filter-pass/sec for SV.  Each config runs in
this process sequentially; the device stays warm between configs but every
config's own warm pass is what its metric comes from (see bench.run).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="BENCH_ALL.json")
    ap.add_argument("--configs", default="s1,s2,s3,s4,s5,headline")
    args = ap.parse_args(argv)

    import jax
    from . import run as bench_run

    dev = jax.devices()[0]
    results = {}
    t_start = time.time()
    for name in args.configs.split(","):
        name = name.strip()
        print(f"=== {name} ===", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        try:
            results[name] = bench_run.main(["--config", name, "--quiet"])
        # SystemExit included: configs raise it for unknown names/kinds, and
        # one bad config must not discard the sweep's earlier device time.
        except (Exception, SystemExit) as e:
            results[name] = {"config": name,
                             "error": f"{type(e).__name__}: {e}"}
            print(f"{name} FAILED: {e}", file=sys.stderr, flush=True)
        results[name]["total_secs"] = time.perf_counter() - t0

    out = {
        "device": f"{dev.platform} ({dev.device_kind})",
        "recorded_unix": t_start,
        "results": results,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: {kk: vv for kk, vv in v.items()
                          if kk in ("em_iters_per_sec",
                                    "sv_filter_passes_per_sec", "loglik",
                                    "error")}
                      for k, v in results.items()}))
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()

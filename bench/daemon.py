#!/usr/bin/env python
"""Serving-daemon soak: socket-level throughput/latency of the
``dfm_tpu.daemon`` front door over a restored fleet, plus the two
robustness contracts the daemon exists for — overload protection (the
SLO-burn shed path actually sheds, deterministically, and records it)
and zero-downtime handoff (a mid-soak blue/green swap drops ZERO
queries).  Prints exactly ONE JSON line to stdout:

    {"metric": ..., "value": N, "unit": "queries/sec",
     "daemon_qps": N, "daemon_p99_ms": N, "daemon_shed_rate": N,
     "daemon_handoff_gap_ms": N, "daemon_dropped_queries": 0, ...}

``value`` is warm client-observed queries/sec through the socket
(connect + JSON round-trip + fused fleet tick + d2h, per query).  The
overload leg arms a deliberately-unmeetable SLO so the burn signal
fires, then bursts a low-priority tenant: ``daemon_shed_rate`` is the
fraction of the burst shed (the leg MEANS to shed; zero would be the
bug).  The handoff leg runs a same-process blue/green swap while a
client hammers submits: ``daemon_handoff_gap_ms`` is the successor-ready
gap and ``daemon_dropped_queries`` counts client requests that got no
answer (the zero-downtime contract: always 0).

Run on the real chip: ``python -m bench.daemon``.  Smoke-size via
DFM_BENCH_DAEMON_MIX ("N,T,KxC;...", default 2 shapes x 2 = 4 tenants),
DFM_BENCH_QUERIES (load-leg queries, default 24), DFM_BENCH_ROWS
(rows/query, default 2), DFM_BENCH_SERVE_ITERS (EM iters/query, default
5), DFM_BENCH_ITERS (cold-fit budget, default 30),
DFM_BENCH_DAEMON_BURST (overload burst size, default 12).
Diagnostics on stderr.
"""

import json
import os
import shutil
import tempfile
import threading
import time

import numpy as np

from bench._common import log, parse_mix, pct as _pct, record_run


def main():
    mix = os.environ.get("DFM_BENCH_DAEMON_MIX", "12,48,2x2;16,56,2x2")
    n_queries = int(os.environ.get("DFM_BENCH_QUERIES", 24))
    r_max = int(os.environ.get("DFM_BENCH_ROWS", 2))
    serve_iters = int(os.environ.get("DFM_BENCH_SERVE_ITERS", 5))
    cold_iters = int(os.environ.get("DFM_BENCH_ITERS", 30))
    burst = int(os.environ.get("DFM_BENCH_DAEMON_BURST", 12))
    shapes = parse_mix(mix)
    B = len(shapes)

    import jax
    jax.config.update("jax_enable_x64", True)

    from dfm_tpu import DynamicFactorModel, TPUBackend, fit, open_fleet
    from dfm_tpu.daemon import (DaemonClient, DaemonConfig, DFMDaemon,
                                make_listener)
    from dfm_tpu.obs.live import set_slo
    from dfm_tpu.obs.slo import SLOConfig
    from dfm_tpu.utils import dgp

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); {B} tenants "
        f"[{mix}], {n_queries} load queries, {burst} overload burst, "
        f"{serve_iters} EM iters/query")

    work = tempfile.mkdtemp(prefix="dfm_bench_daemon_")
    snap = os.path.join(work, "snap")
    journal = os.path.join(work, "journal.jsonl")
    addr = os.path.join(work, "daemon.sock")

    be = TPUBackend(filter="info")
    # Per-tenant fitted models + held-out rows for the query stream.
    total = n_queries + burst + 64
    ress, Ys, streams = [], [], []
    with jax.default_matmul_precision("highest"):
        for i, (N, T, k) in enumerate(shapes):
            rngi = np.random.default_rng(5000 + i)
            p_true = dgp.dfm_params(N, k, rngi)
            Y_all, _ = dgp.simulate(p_true, T + total * r_max, rngi)
            ress.append(fit(DynamicFactorModel(n_factors=k), Y_all[:T],
                            max_iters=cold_iters, backend=be,
                            telemetry=False))
            Ys.append(Y_all[:T])
            streams.append(Y_all[T:])

    # Bootstrap: snapshot a fresh fleet, then run the daemon from the
    # RECOVERED state — the bench soaks the restore path too.
    caps = [Ys[i].shape[0] + (total + 2) * r_max for i in range(B)]
    with jax.default_matmul_precision("highest"):
        boot = open_fleet(ress, Ys, capacity=caps, max_update_rows=r_max,
                          max_iters=serve_iters, tol=0.0, backend=be)
        names = list(boot.tenants)
        boot.snapshot_all(snap)
        boot.close()

        # Tenant 0 is high-priority; everyone else is the shed class.
        cfg = DaemonConfig(queue_max=max(16, 2 * B),
                           priority={names[0]: 1})
        daemon = DFMDaemon.recover(snap, journal, backend=be, config=cfg)
        listener = make_listener(addr)
        th = threading.Thread(target=daemon.serve_forever,
                              args=(listener,), daemon=True)
        th.start()

        cli = DaemonClient(addr, timeout=600.0)
        cursor = [0] * B

        def rows_for(i):
            r = streams[i][cursor[i]:cursor[i] + r_max]
            cursor[i] += r_max
            return r

        # Warmup: one query per tenant compiles each bucket's executable.
        for i, t in enumerate(names):
            r = cli.submit(t, rows_for(i), wait=True)
            assert r.get("ok"), r

        # -- load leg: warm socket-level throughput + latency ----------
        lat = []
        t0 = time.perf_counter()
        for q in range(n_queries):
            i = q % B
            tq = time.perf_counter()
            r = cli.submit(names[i], rows_for(i), wait=True)
            lat.append(time.perf_counter() - tq)
            assert r.get("ok"), r
        wall = time.perf_counter() - t0
        qps = n_queries / wall
        p50_ms = 1e3 * _pct(lat, 50)
        p99_ms = 1e3 * _pct(lat, 99)
        log(f"load: {n_queries} queries in {wall:.3f} s ({qps:.1f} q/s); "
            f"p50 {p50_ms:.1f} ms p99 {p99_ms:.1f} ms")

        # -- tracing-overhead leg: traced vs untraced warm round-trips -
        # The load leg above ran untraced; a short traced window (ambient
        # tracer — the daemon's pump thread is not this thread, so
        # activate() would never reach it) measures the request-waterfall
        # plumbing's socket-to-socket tax.  Best-of-N on both sides.
        from dfm_tpu.obs.trace import Tracer, set_ambient
        n_ov = max(4, min(8, n_queries))
        un_walls = []
        for q in range(n_ov):
            i = q % B
            tq = time.perf_counter()
            r = cli.submit(names[i], rows_for(i), wait=True)
            un_walls.append(time.perf_counter() - tq)
            assert r.get("ok"), r
        ov_tracer = Tracer()
        prev_amb = set_ambient(ov_tracer)
        try:
            tr_walls = []
            for q in range(n_ov):
                i = q % B
                tq = time.perf_counter()
                r = cli.submit(names[i], rows_for(i), wait=True)
                tr_walls.append(time.perf_counter() - tq)
                assert r.get("ok"), r
        finally:
            set_ambient(prev_amb)
        trace_overhead_pct = (100.0 * (min(tr_walls) - min(un_walls))
                              / min(un_walls))
        n_waterfalls = sum(1 for e in ov_tracer.events
                           if e.get("kind") == "request")
        log(f"tracing overhead: traced best {1e3 * min(tr_walls):.2f} ms "
            f"vs untraced best {1e3 * min(un_walls):.2f} ms "
            f"({trace_overhead_pct:+.1f}%); {n_waterfalls} waterfalls "
            f"captured")

        # -- overload leg: burn the SLO, burst the shed class ----------
        # An unmeetable latency objective makes every served query a
        # budget violation; after min_events the burn fires and the
        # daemon sheds the low-priority class deterministically.
        set_slo(SLOConfig(p99_ms=1e-6, min_events=5, window=3600.0))
        for _ in range(6):           # feed the monitor until it fires
            cli.submit(names[0], rows_for(0), wait=True)
        n_shed = 0
        for q in range(burst):
            i = 1 % B                # lowest-priority tenant
            r = cli.submit(names[i], rows_for(i))
            if r.get("shed"):
                n_shed += 1
        shed_rate = n_shed / burst if burst else 0.0
        set_slo(None)                # disarm: clears the breach latch
        log(f"overload: {n_shed}/{burst} burst queries shed "
            f"(rate {shed_rate:.2f}) under forced SLO burn")

        # -- handoff leg: blue/green swap under live load --------------
        stop = threading.Event()
        served_during = [0]
        dropped_box = [0]

        def hammer():
            hc = DaemonClient(addr, timeout=600.0)
            while not stop.is_set():
                try:
                    r = hc.submit(names[0], None, wait=True)
                    if r.get("ok"):
                        served_during[0] += 1
                except ConnectionError:
                    dropped_box[0] += 1
                time.sleep(0.02)

        hth = threading.Thread(target=hammer, daemon=True)
        hth.start()
        succ, lst2, gap_ms = DFMDaemon.takeover(
            addr, snap, journal, backend=be, config=cfg)
        th.join(timeout=60)
        th2 = threading.Thread(target=succ.serve_forever, args=(lst2,),
                               daemon=True)
        th2.start()
        # A few post-swap queries prove the successor serves.
        for i, t in enumerate(names):
            r = cli.submit(t, rows_for(i), wait=True)
            assert r.get("ok"), r
        stop.set()
        hth.join(timeout=60)
        dropped = dropped_box[0]
        log(f"handoff: gap {gap_ms:.1f} ms, {served_during[0]} queries "
            f"served during swap, {dropped} dropped")

        st = succ.status()
        dr = st.get("drift") or {}
        log(f"drift plane: armed={dr.get('armed')}, "
            f"{len(dr.get('per_tenant', {}))} tenants scored, "
            f"last swap seq {dr.get('last_swap_seq')}")
        cli.shutdown()
        th2.join(timeout=60)
        daemon.close()
        succ.close()

    shutil.rmtree(work, ignore_errors=True)

    from dfm_tpu.obs.store import new_run_id
    payload = {
        "metric": f"daemon_qps_{B}tenants",
        "value": round(qps, 2),
        "unit": "queries/sec",
        "value_definition": ("warm client-observed daemon throughput: "
                             "queries/sec through the socket front door "
                             "(connect + JSON round-trip + fused fleet "
                             "tick + d2h per query)"),
        "daemon_qps": round(qps, 2),
        "daemon_p99_ms": round(p99_ms, 2),
        "daemon_p50_ms": round(p50_ms, 2),
        "daemon_shed_rate": round(shed_rate, 3),
        "daemon_handoff_gap_ms": round(gap_ms, 2),
        "daemon_dropped_queries": int(dropped),
        "daemon_queries_during_handoff": int(served_during[0]),
        "trace_overhead_pct": round(trace_overhead_pct, 2),
        "trace_waterfalls": int(n_waterfalls),
        "daemon_dedup_hits": int(st.get("dedup_hits", 0)),
        "n_tenants": B,
        "n_queries": n_queries,
        "overload_burst": burst,
        "n_backpressure": int(st["n_backpressure"]),
        "n_snapshots": int(st["n_snapshots"]),
        "journal_seq": int(st["journal_seq"]),
        # Model-quality trail (status "drift" section): per-tenant drift
        # scores + the journal seq of the latest hot swap, if any.
        "drift_armed": bool(dr.get("armed")),
        "drift_scores": {t: v.get("drift_score")
                         for t, v in dr.get("per_tenant", {}).items()},
        "last_swap_seq": dr.get("last_swap_seq"),
        "serve_iters": serve_iters,
        "mix": mix,
        "run_id": new_run_id(),
    }
    print(json.dumps(payload))
    record_run(payload, dev, "bench_daemon")


if __name__ == "__main__":
    main()

"""Benchmark/CLI runner: ``python -m bench.run --config s1 --backend tpu``.

The minimum-slice command of SURVEY.md section 7.3: simulate the named config,
fit with the chosen backend, print per-iteration loglik/timing records (JSONL,
the observability sink of SURVEY.md section 5) and a one-line JSON summary
with the BASELINE.json:2 metrics.

Timing method: the fit runs TWICE — a cold pass (records, compile) and a warm
pass (same iteration count, caches hot) whose wall time yields
``em_iters_per_sec``.  Per-callback timing would misattribute work under the
fused-chunk drivers (a whole chunk completes before its callbacks fire), and
the warm wall also charges each iteration its share of dispatch overhead —
the number a user actually experiences.

S5 (SV-DFM) runs REAL estimation — EM pre-fit + particle EM for the vol-walk
scale with the cancellation-free residual weights — and additionally times
pure RBPF filter passes (the "filter-pass/sec" figure BASELINE.json:11's
10k x 1000 stress config is judged by).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax

# Same dtype regime as bench.py and the test suite: data/params stay
# explicitly float32 on accelerators, but the small high-sensitivity pieces
# (loglik assembly, the MF augmented-state scans) upgrade to f64 — see
# info_filter.loglik_from_terms and mixed_freq.mf_em_core.
jax.config.update("jax_enable_x64", True)

import numpy as np

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.utils import dgp
from .configs import get


def make_data(cfg):
    rng = np.random.default_rng(cfg.seed)
    if cfg.kind in ("plain", "missing"):
        p_true = dgp.dfm_params(cfg.N, cfg.k, rng,
                                static=(cfg.dynamics == "static"))
        Y, F = dgp.simulate(p_true, cfg.T, rng)
        mask = None
        if cfg.kind == "missing" or cfg.frac_missing > 0:
            mask = dgp.random_mask(cfg.T, cfg.N, rng, cfg.frac_missing)
        return Y, mask, F
    if cfg.kind == "mixed_freq":
        Y, mask, F, _ = dgp.simulate_mixed_freq(
            cfg.N - cfg.n_quarterly, cfg.n_quarterly, cfg.T, cfg.k, rng)
        if cfg.frac_missing > 0:
            ragged = dgp.random_mask(cfg.T, cfg.N, rng, cfg.frac_missing)
            mask = mask * ragged
            Y = np.where(mask > 0, Y, np.nan)
        return Y, mask, F
    if cfg.kind == "tvl":
        Y, F, _, _, _ = dgp.simulate_tv_loadings(cfg.N, cfg.T, cfg.k, rng,
                                                 walk_scale=0.05)
        return Y, None, F
    if cfg.kind == "sv":
        Y, F, _, _ = dgp.simulate_sv(cfg.N, cfg.T, cfg.k, rng)
        return Y, None, F
    raise SystemExit(f"config kind {cfg.kind!r} not yet runnable")


# S5 filter-pass benchmark particle system: ONE definition shared by the
# timed pass (_run_sv), the matched-seed accuracy artifact
# (accuracy_fields), and the CPU baseline (bench.cpu_baseline) — the
# "matched-seed" claim is only true while all three use the same spec/key.
SV_BENCH_PARTICLES = 256
SV_BENCH_SEED = 1


def sv_bench_spec(cfg):
    from dfm_tpu.models.sv import SVSpec
    return SVSpec(n_factors=cfg.k, n_particles=SV_BENCH_PARTICLES)


def _run_sv(cfg, Y, iters, backend, cb):
    """S5: real SV estimation + pure filter-pass timing.

    ``--backend sharded`` runs the WHOLE pipeline multi-device (VERDICT r4
    item 9): the EM pre-fit through ``ShardedBackend``, and every RBPF pass
    — particle-EM E-steps and the timed filter passes — through the
    series-sharded filter over ``make_mesh()`` (a 1-shard mesh on a single
    chip; the fake 8-device mesh in CPU test runs).
    """
    from functools import partial
    from dfm_tpu.models.sv import SVSpec, SVFit, sv_filter, sv_fit
    from dfm_tpu.ssm.params import SSMParams as JP
    import jax
    import jax.numpy as jnp

    mesh = None
    if backend == "sharded":
        from dfm_tpu.parallel.mesh import make_mesh
        mesh = make_mesh()
    spec = sv_bench_spec(cfg)                         # residual weights
    t0 = time.perf_counter()
    svr = sv_fit(Y, spec, em_iters=10, backend=backend,
                 sv_iters=max(iters, 1), mesh=mesh)
    fit_wall = time.perf_counter() - t0
    for i, ll in enumerate(np.atleast_1d(svr.logliks)):
        cb(i, float(ll), None)

    # Pure RBPF filter passes at the estimated parameters (no particle
    # history emission — the timing mode; see models.sv).  Standardize with
    # the SAME convention sv_fit estimated the params under (observed-entry
    # ddof-1 — utils.data.standardize), not an ad-hoc reimplementation.
    from dfm_tpu.utils.data import standardize as _std
    Yz, _ = _std(np.asarray(Y, np.float64))
    from dfm_tpu.ops.precision import default_compute_dtype
    dtype = default_compute_dtype()
    Yj = jnp.asarray(Yz, dtype)
    pj = JP.from_numpy(svr.params, dtype=dtype)
    key = jax.random.PRNGKey(SV_BENCH_SEED)
    filt = sv_filter
    if mesh is not None:
        from dfm_tpu.parallel.sharded_sv import sharded_sv_filter
        filt = partial(sharded_sv_filter, mesh=mesh)

    def one_pass():
        t0 = time.perf_counter()
        r = filt(Yj, pj, spec, key=key, sigma_h=svr.sigma_h,
                 h_center=svr.h_center, store_paths=False)
        float(r.loglik)   # host assembly forces completion
        return time.perf_counter() - t0

    one_pass()                                  # warm/compile
    pass_secs = min(one_pass() for _ in range(3))
    return svr, fit_wall, pass_secs


def accuracy_fields(cfg, res, Y, mask, svr=None):
    """Contract-grade accuracy artifact per family (VERDICT r4 item 4).

    Evaluates the final params' log-likelihood twice — float32 fast path
    and the family's reporting-grade f64 evaluator — and records the
    relative difference plus the evaluator's semantics:

      plain/missing  exact marginal loglik (``ssm.info_filter.loglik_eval``)
      mixed_freq     exact marginal loglik of the augmented model
                     (``models.mixed_freq.mf_loglik_eval``)
      tvl            loglik CONDITIONAL on the smoothed loading paths (the
                     dual-estimation monitor; exact joint is intractable)
      sv             matched-seed RBPF Monte-Carlo estimate re-evaluated in
                     f64 (same particle system up to resampling-threshold
                     rounding; the estimator itself carries MC noise)
    """
    import jax
    import numpy as np
    from dfm_tpu.utils.data import build_mask

    with jax.default_matmul_precision("highest"):
        if cfg.kind in ("plain", "missing"):
            from dfm_tpu.ssm.info_filter import loglik_eval
            W = build_mask(Y, mask)
            missing = bool((W == 0).any())
            std = res.standardizer
            Yz = std.transform(Y) if std is not None else np.asarray(Y)
            Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
            Wm = W if missing else None
            ll64 = loglik_eval(Yz, res.params, mask=Wm)
            ll32 = loglik_eval(np.asarray(Yz, np.float32), res.params,
                               mask=Wm, precise=False)
            sem = "exact"
        elif cfg.kind == "mixed_freq":
            from dfm_tpu.models.mixed_freq import mf_loglik_eval
            W = build_mask(Y, mask)
            std = res.standardizer
            Yz = std.transform(Y) if std is not None else np.asarray(Y)
            Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
            ll64 = mf_loglik_eval(Yz, W, res.params, res.spec)
            ll32 = mf_loglik_eval(np.asarray(Yz, np.float32), W,
                                  res.params, res.spec, precise=False)
            sem = "exact (augmented state)"
        elif cfg.kind == "tvl":
            from dfm_tpu.models.tv_loadings import tvl_loglik_eval
            W = build_mask(Y, mask)
            missing = bool((W == 0).any())
            Yz = np.where(W > 0, np.nan_to_num(np.asarray(Y)), 0.0)
            Wm = W if missing else None
            ll64 = tvl_loglik_eval(Yz, res.loadings, res.params, mask=Wm)
            ll32 = tvl_loglik_eval(np.asarray(Yz, np.float32), res.loadings,
                                   res.params, mask=Wm, precise=False)
            sem = "conditional on smoothed loading paths"
        elif cfg.kind == "sv":
            import jax.numpy as jnp
            from dfm_tpu.models.sv import sv_filter
            from dfm_tpu.ssm.params import SSMParams as JP
            from dfm_tpu.utils.data import standardize as _std
            Yz, _ = _std(np.asarray(Y, np.float64))
            spec = sv_bench_spec(cfg)
            key = jax.random.PRNGKey(SV_BENCH_SEED)
            kw = dict(key=key, sigma_h=svr.sigma_h, h_center=svr.h_center,
                      store_paths=False)
            ll32 = float(sv_filter(jnp.asarray(Yz, jnp.float32),
                                   JP.from_numpy(svr.params, jnp.float32),
                                   spec, **kw).loglik)
            if jax.config.jax_enable_x64:
                ll64 = float(sv_filter(jnp.asarray(Yz, jnp.float64),
                                       JP.from_numpy(svr.params,
                                                     jnp.float64),
                                       spec, **kw).loglik)
            else:
                ll64 = ll32
            sem = "matched-seed RBPF MC estimate"
        else:
            return {}
    return {
        "loglik_f64_at_final": float(ll64),
        "loglik_f32_at_final": float(ll32),
        "loglik_rel_err_f32": abs(float(ll32) - float(ll64))
        / max(abs(float(ll64)), 1e-12),
        "accuracy_semantics": sem,
    }


def _two_point_rate(run_n, n_lo: int, n_hi: int, reps: int = 3):
    """Median per-pair slope of ``run_n`` walls at n_lo/n_hi (interleaved —
    the bench.py measurement pattern: run-to-run drift through the tunnel
    would swamp a non-interleaved difference).  Returns (units/sec, ok);
    falls back to total/n when jitter dominates the slope."""
    run_n(n_lo)                       # compile both program sizes
    run_n(n_hi)
    pairs = [(run_n(n_hi), run_n(n_lo)) for _ in range(reps)]
    slopes = [(a - b) / (n_hi - n_lo) for a, b in pairs]
    med = float(np.median(slopes))
    if med <= 0:
        return n_lo / float(np.median([b for _, b in pairs])), False
    return 1.0 / med, True


def sustained_fields(cfg, res, Y, mask):
    """Per-config SUSTAINED rate: the marginal per-iteration (per-round for
    TVL) device cost at the fitted params, fused-program two-point slope —
    dispatch/init-free on both device classes, so ``vs_cpu_sustained`` in
    ``bench.all`` compares the same thing ``bench.py``'s headline metric
    does (VERDICT r4 item 3: the end-to-end short-fit walls are fixed-cost-
    bound on BOTH sides and say nothing about the EM rate).  On the CPU
    baseline process the same code lands on the XLA CPU device (MF/TVL) or
    the NumPy reference loop (plain — the comparison class of
    BASELINE.json:5).
    """
    import os
    if os.environ.get("DFM_BENCH_SUSTAINED", "1") == "0":
        return {}
    import jax.numpy as jnp
    from dfm_tpu.ops.precision import default_compute_dtype
    from dfm_tpu.utils.data import build_mask

    is_cpu = jax.devices()[0].platform == "cpu"
    dt = default_compute_dtype()
    out = {}
    with jax.default_matmul_precision("highest"):
        if cfg.kind in ("plain", "missing") and mask is None:
            p_final = res.params
            ar1 = cfg.dynamics == "ar1"
            std = res.standardizer
            Yz = std.transform(np.asarray(Y, np.float64)) \
                if std is not None else np.asarray(Y, np.float64)
            if is_cpu:
                # The plain-family CPU baseline class is the NumPy f64
                # reference (what bench.py times), not XLA-on-CPU.
                from dfm_tpu.backends import cpu_ref
                flt = "info" if cfg.N >= 32 else "dense"

                def run_n(n):
                    p = p_final
                    t0 = time.perf_counter()
                    for _ in range(n):
                        p, _, _ = cpu_ref.em_step(Yz, p, filter=flt,
                                                  estimate_A=ar1,
                                                  estimate_Q=ar1)
                    return time.perf_counter() - t0

                rate, ok = _two_point_rate(run_n, 2, 6)
            else:
                from dfm_tpu.estim.em import EMConfig, em_fit_scan
                from dfm_tpu.ssm.params import SSMParams as JP
                from dfm_tpu.ssm.steady import auto_tau
                flt = ("ss" if cfg.N >= 512 else
                       "info" if cfg.N >= 32 else "dense")
                emc = EMConfig(filter=flt, estimate_A=ar1, estimate_Q=ar1,
                               tau=auto_tau(p_final) if flt == "ss" else 8)
                Yj = jnp.asarray(Yz, dt)
                pj = JP.from_numpy(p_final, dtype=dt)

                def run_n(n):
                    t0 = time.perf_counter()
                    np.asarray(em_fit_scan(Yj, pj, n, cfg=emc)[1])
                    return time.perf_counter() - t0

                # Wide two-point window for the fast ss engine (its
                # per-iteration cost is ~0.1-0.3 ms, so a 100-iteration
                # delta would drown in dispatch jitter; bench.py uses the
                # same 150/450 pair).
                n_pts = (150, 450) if flt == "ss" else (50, 150)
                rate, ok = _two_point_rate(run_n, *n_pts)
            out = {"em_iters_per_sec_sustained": rate,
                   "sustained_filter": flt}
        elif cfg.kind == "mixed_freq":
            from dfm_tpu.models.mixed_freq import mf_em_scan
            W = build_mask(Y, mask)
            std = res.standardizer
            Yz = std.transform(np.asarray(Y, np.float64)) \
                if std is not None else np.asarray(Y, np.float64)
            Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
            Yj = jnp.asarray(Yz, dt)
            mj = jnp.asarray(W, dt)
            pj = res.params.astype(dt)
            scan = jax.jit(mf_em_scan, static_argnames=("spec", "n_iters"))

            def run_n(n):
                t0 = time.perf_counter()
                np.asarray(scan(Yj, mj, pj, res.spec, n)[1])
                return time.perf_counter() - t0

            rate, ok = _two_point_rate(run_n, *((2, 6) if is_cpu
                                                else (10, 30)))
            out = {"em_iters_per_sec_sustained": rate}
        elif cfg.kind == "tvl":
            from dfm_tpu.models.tv_loadings import tvl_round_scan
            W = build_mask(Y, mask)
            missing = bool((W == 0).any())
            Yz = np.where(W > 0, np.nan_to_num(np.asarray(Y)), 0.0)
            Yj = jnp.asarray(Yz, dt)
            mj = jnp.asarray(W, dt) if missing else None
            Lj = jnp.asarray(res.loadings, dt)
            pj = res.params.astype(dt)
            scan = jax.jit(tvl_round_scan,
                           static_argnames=("spec", "has_mask", "n_rounds"))

            def run_n(n):
                t0 = time.perf_counter()
                np.asarray(scan(Yj, mj if missing else Yj, Lj, pj,
                                res.spec, missing, n)[1])
                return time.perf_counter() - t0

            rate, ok = _two_point_rate(run_n, *((1, 3) if is_cpu
                                                else (2, 6)), reps=2)
            out = {"rounds_per_sec_sustained": rate,
                   "em_iters_per_sec_sustained": rate}
        else:
            return {}
    out["sustained_ok"] = bool(ok)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="s1")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--iters", type=int, default=None,
                    help="override the config's EM iteration count")
    ap.add_argument("--tol", type=float, default=0.0,
                    help="EM convergence tol (0 = run all iters)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-iteration JSONL records")
    args = ap.parse_args(argv)

    cfg = get(args.config)
    # CLI entry points opt into the persistent compile cache (default
    # .dfm_cache/; DFM_COMPILE_CACHE overrides, "" disables) — a re-run at
    # the same shapes skips XLA compiles entirely.
    from dfm_tpu.pipeline import setup_compile_cache
    setup_compile_cache()
    Y, mask, _ = make_data(cfg)
    iters = args.iters if args.iters is not None else cfg.em_iters

    records = []
    t_prev = time.perf_counter()

    def cb(it, ll, p):
        nonlocal t_prev
        now = time.perf_counter()
        rec = {"iter": it, "loglik": float(ll), "secs": now - t_prev}
        t_prev = now
        records.append(rec)
        if not args.quiet:
            print(json.dumps(rec), file=sys.stderr)

    extra = {}
    sharded = args.backend == "sharded"
    t0 = time.perf_counter()
    if cfg.kind == "mixed_freq":
        from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
        spec = MixedFreqSpec(n_monthly=cfg.N - cfg.n_quarterly,
                             n_quarterly=cfg.n_quarterly, n_factors=cfg.k)
        if sharded:
            from functools import partial
            from dfm_tpu.parallel.mesh import make_mesh
            from dfm_tpu.parallel.sharded_mf import sharded_mf_fit
            fit_fn = partial(sharded_mf_fit, mesh=make_mesh())
        else:
            fit_fn = mf_fit
        res = fit_fn(Y, spec, mask=mask, max_iters=iters, tol=args.tol,
                     callback=cb)
        wall_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        fit_fn(Y, spec, mask=mask, max_iters=iters, tol=args.tol)
        wall_warm = time.perf_counter() - t0
        res_backend = "sharded" if sharded else "tpu"
    elif cfg.kind == "tvl":
        from dfm_tpu.models.tv_loadings import TVLSpec, tvl_fit
        tvl_spec = TVLSpec(n_factors=cfg.k, n_rounds=iters, tol=args.tol)
        if sharded:
            from functools import partial
            from dfm_tpu.parallel.mesh import make_mesh
            from dfm_tpu.parallel.sharded_tvl import sharded_tvl_fit
            fit_fn = partial(sharded_tvl_fit, mesh=make_mesh())
        else:
            fit_fn = tvl_fit
        res = fit_fn(Y, tvl_spec, mask=mask, callback=cb)
        wall_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        fit_fn(Y, tvl_spec, mask=mask)
        wall_warm = time.perf_counter() - t0
        res_backend = "sharded" if sharded else "tpu"
    elif cfg.kind == "sv":
        res, wall_cold, pass_secs = _run_sv(cfg, Y, iters, args.backend, cb)
        wall_warm = None
        extra = {"sv_filter_pass_secs": pass_secs,
                 "sv_filter_passes_per_sec": 1.0 / pass_secs,
                 "n_particles": SV_BENCH_PARTICLES}
        extra.update(accuracy_fields(cfg, res, Y, mask, svr=res))
        res_backend = args.backend
    else:
        res = fit(DynamicFactorModel(n_factors=cfg.k, dynamics=cfg.dynamics),
                  Y, mask=mask, backend=args.backend, max_iters=iters,
                  tol=args.tol, callback=cb)
        wall_cold = time.perf_counter() - t0
        # Warm pass through the pipelined dispatch driver (depth 2): the
        # chunk programs are hot, speculative issue hides the per-dispatch
        # tunnel latency, and the telemetry summary reports how many host
        # barriers the fit actually paid (``blocking_transfers``).
        # Internal timing probe: keep it out of the run registry — the
        # bench records its own RunRecord for the config.
        runs_env = os.environ.pop("DFM_RUNS", None)
        try:
            t0 = time.perf_counter()
            res_w = fit(DynamicFactorModel(n_factors=cfg.k,
                                           dynamics=cfg.dynamics),
                        Y, mask=mask, backend=args.backend, max_iters=iters,
                        tol=args.tol, pipeline=2, telemetry=True)
            wall_warm = time.perf_counter() - t0
        finally:
            if runs_env is not None:
                os.environ["DFM_RUNS"] = runs_env
        tele_w = res_w.telemetry or {}
        extra["e2e_warm_fit_iters_per_sec"] = (
            float(res_w.n_iters) / wall_warm if wall_warm else None)
        if tele_w.get("blocking_transfers") is not None:
            extra["blocking_transfers"] = tele_w["blocking_transfers"]
        res_backend = res.backend
    if cfg.kind != "sv":
        extra.update(accuracy_fields(cfg, res, Y, mask))
        if not sharded:
            extra.update(sustained_fields(cfg, res, Y, mask))
    summary = {
        "config": cfg.name,
        "backend": res_backend,
        "N": cfg.N, "T": cfg.T, "k": cfg.k,
        "n_iters": len(records),
        "converged": bool(getattr(res, "converged", True)),
        "loglik": float(res.loglik),
        "wall_secs_cold": wall_cold,
        "wall_secs_warm": wall_warm,
        "em_iters_per_sec": (len(records) / wall_warm
                             if wall_warm else None),
        **extra,
    }
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()

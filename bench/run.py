"""Benchmark/CLI runner: ``python -m bench.run --config s1 --backend tpu``.

The minimum-slice command of SURVEY.md section 7.3: simulate the named config,
fit with the chosen backend, print per-iteration loglik/timing records (JSONL,
the observability sink of SURVEY.md section 5) and a one-line JSON summary
with the BASELINE.json:2 metrics (EM iters/sec, loglik evals/sec).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.utils import dgp
from .configs import get


def make_data(cfg):
    rng = np.random.default_rng(cfg.seed)
    if cfg.kind in ("plain", "missing"):
        p_true = dgp.dfm_params(cfg.N, cfg.k, rng,
                                static=(cfg.dynamics == "static"))
        Y, F = dgp.simulate(p_true, cfg.T, rng)
        mask = None
        if cfg.kind == "missing" or cfg.frac_missing > 0:
            mask = dgp.random_mask(cfg.T, cfg.N, rng, cfg.frac_missing)
        return Y, mask, F
    if cfg.kind == "mixed_freq":
        Y, mask, F, _ = dgp.simulate_mixed_freq(
            cfg.N - cfg.n_quarterly, cfg.n_quarterly, cfg.T, cfg.k, rng)
        if cfg.frac_missing > 0:
            ragged = dgp.random_mask(cfg.T, cfg.N, rng, cfg.frac_missing)
            mask = mask * ragged
            Y = np.where(mask > 0, Y, np.nan)
        return Y, mask, F
    if cfg.kind == "tvl":
        Y, F, _, _, _ = dgp.simulate_tv_loadings(cfg.N, cfg.T, cfg.k, rng,
                                                 walk_scale=0.05)
        return Y, None, F
    if cfg.kind == "sv":
        Y, F, _, _ = dgp.simulate_sv(cfg.N, cfg.T, cfg.k, rng)
        return Y, None, F
    raise SystemExit(f"config kind {cfg.kind!r} not yet runnable")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="s1")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--iters", type=int, default=None,
                    help="override the config's EM iteration count")
    ap.add_argument("--tol", type=float, default=0.0,
                    help="EM convergence tol (0 = run all iters)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-iteration JSONL records")
    args = ap.parse_args(argv)

    cfg = get(args.config)
    Y, mask, _ = make_data(cfg)
    iters = args.iters if args.iters is not None else cfg.em_iters

    records = []
    t_prev = time.perf_counter()

    def cb(it, ll, p):
        nonlocal t_prev
        now = time.perf_counter()
        rec = {"iter": it, "loglik": float(ll), "secs": now - t_prev}
        t_prev = now
        records.append(rec)
        if not args.quiet:
            print(json.dumps(rec), file=sys.stderr)

    t0 = time.perf_counter()
    if cfg.kind == "mixed_freq":
        from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
        spec = MixedFreqSpec(n_monthly=cfg.N - cfg.n_quarterly,
                             n_quarterly=cfg.n_quarterly, n_factors=cfg.k)
        res = mf_fit(Y, spec, mask=mask, max_iters=iters, tol=args.tol,
                     callback=cb)
        res_backend, history = "tpu", records
    elif cfg.kind == "tvl":
        from dfm_tpu.models.tv_loadings import TVLSpec, tvl_fit
        res = tvl_fit(Y, TVLSpec(n_factors=cfg.k, n_rounds=iters,
                                 tol=args.tol), mask=mask, callback=cb)
        res_backend, history = "tpu", records
    elif cfg.kind == "sv":
        from dfm_tpu.models.sv import SVSpec, sv_fit
        t_pf = time.perf_counter()
        # Timing workload: one RBPF pass (no particle-EM refinement) with
        # the fast expanded quadratic — see sv.py module docstring.
        svr = sv_fit(Y, SVSpec(n_factors=cfg.k, n_particles=256,
                               quad_form="expanded"),
                     em_iters=max(iters, 2), backend=args.backend,
                     estimate_sv=False)
        cb(0, svr.loglik, None)

        class _R:  # summary-shape shim
            loglik = svr.loglik
            converged = True
        res = _R()
        res_backend, history = args.backend, records
    else:
        res = fit(DynamicFactorModel(n_factors=cfg.k, dynamics=cfg.dynamics),
                  Y, mask=mask, backend=args.backend, max_iters=iters,
                  tol=args.tol, callback=cb)
        res_backend, history = res.backend, res.history
    wall = time.perf_counter() - t0
    # Per-iteration seconds from the fit history (first iter includes compile).
    secs = [h["secs"] for h in history]
    steady = secs[1:] if len(secs) > 1 else secs
    summary = {
        "config": cfg.name,
        "backend": res_backend,
        "N": cfg.N, "T": cfg.T, "k": cfg.k,
        "n_iters": len(records),
        "converged": res.converged,
        "loglik": res.loglik,
        "wall_secs": wall,
        "em_iters_per_sec": (len(steady) / sum(steady)) if steady else None,
        "first_iter_secs": secs[0] if secs else None,
    }
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()

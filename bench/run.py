"""Benchmark/CLI runner: ``python -m bench.run --config s1 --backend tpu``.

The minimum-slice command of SURVEY.md section 7.3: simulate the named config,
fit with the chosen backend, print per-iteration loglik/timing records (JSONL,
the observability sink of SURVEY.md section 5) and a one-line JSON summary
with the BASELINE.json:2 metrics (EM iters/sec, loglik evals/sec).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.utils import dgp
from .configs import get


def make_data(cfg):
    rng = np.random.default_rng(cfg.seed)
    if cfg.kind in ("plain", "missing", "mixed_freq"):
        p_true = dgp.dfm_params(cfg.N, cfg.k, rng,
                                static=(cfg.dynamics == "static"))
        Y, F = dgp.simulate(p_true, cfg.T, rng)
        mask = None
        if cfg.kind == "missing" or cfg.frac_missing > 0:
            mask = dgp.random_mask(cfg.T, cfg.N, rng, cfg.frac_missing)
        if cfg.kind == "mixed_freq":
            mf = dgp.mixed_freq_mask(cfg.T, cfg.N, cfg.n_quarterly)
            mask = mf if mask is None else mask * mf
        return Y, mask, F
    if cfg.kind == "tvl":
        Y, F, _, _, _ = dgp.simulate_tv_loadings(cfg.N, cfg.T, cfg.k, rng)
        return Y, None, F
    if cfg.kind == "sv":
        Y, F, _, _ = dgp.simulate_sv(cfg.N, cfg.T, cfg.k, rng)
        return Y, None, F
    raise SystemExit(f"config kind {cfg.kind!r} not yet runnable")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default="s1")
    ap.add_argument("--backend", default="tpu")
    ap.add_argument("--iters", type=int, default=None,
                    help="override the config's EM iteration count")
    ap.add_argument("--tol", type=float, default=0.0,
                    help="EM convergence tol (0 = run all iters)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-iteration JSONL records")
    args = ap.parse_args(argv)

    cfg = get(args.config)
    Y, mask, _ = make_data(cfg)
    model = DynamicFactorModel(n_factors=cfg.k, dynamics=cfg.dynamics)
    iters = args.iters if args.iters is not None else cfg.em_iters

    records = []

    def cb(it, ll, p):
        rec = {"iter": it, "loglik": float(ll)}
        records.append(rec)
        if not args.quiet:
            print(json.dumps(rec), file=sys.stderr)

    t0 = time.perf_counter()
    res = fit(model, Y, mask=mask, backend=args.backend, max_iters=iters,
              tol=args.tol, callback=cb)
    wall = time.perf_counter() - t0
    # Per-iteration seconds from the fit history (first iter includes compile).
    secs = [h["secs"] for h in res.history]
    steady = secs[1:] if len(secs) > 1 else secs
    summary = {
        "config": cfg.name,
        "backend": res.backend,
        "N": cfg.N, "T": cfg.T, "k": cfg.k,
        "n_iters": res.n_iters,
        "converged": res.converged,
        "loglik": res.loglik,
        "wall_secs": wall,
        "em_iters_per_sec": (len(steady) / sum(steady)) if steady else None,
        "first_iter_secs": secs[0] if secs else None,
    }
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()

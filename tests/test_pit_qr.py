"""Square-root (QR-factor) parallel-in-time filter/smoother == sequential
(ISSUE 13 tentpole; arXiv 2502.11686's orthogonal-transformation elements).

Covers x64-exact and f32-tolerance equivalence vs the sequential info
scan (masked/unmasked, divisible/non-divisible T), EM-through-pit_qr
(chunked AND fused drivers), the mixed-frequency augmented E-step, the
f32 noise contract (pit_qr no noisier than the sequential scan — the
reason the square-root rebuild exists), and the fit()-level plumbing
(FitResult.filter stamp + trace event, advisor plan application).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.estim.em import EMConfig, em_fit
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.parallel_filter import (pit_qr_filter,
                                         pit_qr_filter_smoother,
                                         pit_qr_smoother)
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(61)
    p = dgp.dfm_params(33, 3, rng)
    Y, _ = dgp.simulate(p, 90, rng)
    return p, Y


@pytest.mark.parametrize("impl", ["blocked", "associative"])
@pytest.mark.parametrize("masked", [False, True])
def test_pit_qr_filter_matches_sequential(setup, impl, masked):
    p, Y = setup
    pj = JP.from_numpy(p, jnp.float64)
    mask = None
    if masked:
        rng = np.random.default_rng(62)
        W = dgp.random_mask(*Y.shape, rng, 0.3)
        W[5] = 0.0          # a fully-missing step (C_t = 0 element)
        mask = jnp.asarray(W)
    kf_s = info_filter(jnp.asarray(Y), pj, mask=mask)
    kf_q = pit_qr_filter(jnp.asarray(Y), pj, mask=mask, scan_impl=impl)
    assert abs(float(kf_q.loglik) - float(kf_s.loglik)) < 1e-7 * abs(
        float(kf_s.loglik))
    np.testing.assert_allclose(np.asarray(kf_q.x_filt),
                               np.asarray(kf_s.x_filt), atol=1e-9)
    np.testing.assert_allclose(np.asarray(kf_q.P_filt),
                               np.asarray(kf_s.P_filt), atol=1e-9)
    np.testing.assert_allclose(np.asarray(kf_q.x_pred),
                               np.asarray(kf_s.x_pred), atol=1e-9)
    sm_s = rts_smoother(kf_s, pj)
    sm_q = pit_qr_smoother(kf_q, pj, scan_impl=impl)
    np.testing.assert_allclose(np.asarray(sm_q.x_sm),
                               np.asarray(sm_s.x_sm), atol=1e-8)
    np.testing.assert_allclose(np.asarray(sm_q.P_sm),
                               np.asarray(sm_s.P_sm), atol=1e-8)
    np.testing.assert_allclose(np.asarray(sm_q.P_lag),
                               np.asarray(sm_s.P_lag), atol=1e-8)


def test_pit_qr_non_divisible_lengths(setup):
    p, _ = setup
    rng = np.random.default_rng(63)
    for T in (7, 29, 97):
        Y, _ = dgp.simulate(p, T, rng)
        pj = JP.from_numpy(p, jnp.float64)
        kf_s = info_filter(jnp.asarray(Y), pj)
        kf_q, sm_q = pit_qr_filter_smoother(jnp.asarray(Y), pj)
        assert abs(float(kf_q.loglik) - float(kf_s.loglik)) < 1e-9 * abs(
            float(kf_s.loglik)), T
        sm_s = rts_smoother(kf_s, pj)
        np.testing.assert_allclose(np.asarray(sm_q.x_sm),
                                   np.asarray(sm_s.x_sm), atol=1e-8)


def test_pit_qr_f32_noise_no_worse_than_sequential(setup):
    """The matched-numerics half of the long-T contract: at f32 the
    square-root combine must hold the sequential scan's noise level
    (the covariance-form pit combine historically did not — that
    instability is WHY the QR-factor rebuild exists)."""
    p, _ = setup
    rng = np.random.default_rng(64)
    Y, _ = dgp.simulate(p, 400, rng)
    p64 = JP.from_numpy(p, jnp.float64)
    p32 = JP.from_numpy(p, jnp.float32)
    Y64, Y32 = jnp.asarray(Y), jnp.asarray(Y, jnp.float32)
    ll_ref = float(info_filter(Y64, p64).loglik)
    err_seq = abs(float(info_filter(Y32, p32).loglik) - ll_ref)
    err_qr = abs(float(pit_qr_filter(Y32, p32).loglik) - ll_ref)
    # Both sit near eps*N*T; pit_qr must not blow past the sequential
    # level (3x headroom over run-to-run wobble).
    assert err_qr <= 3.0 * max(err_seq, 1e-7 * abs(ll_ref))


def test_em_with_pit_qr_matches_info(setup):
    p, Y = setup
    from dfm_tpu.backends import cpu_ref
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 3)
    pj = JP.from_numpy(p0, jnp.float64)
    _, lls_i, _, _ = em_fit(jnp.asarray(Yz), pj, max_iters=5,
                            cfg=EMConfig(filter="info"))
    _, lls_q, _, _ = em_fit(jnp.asarray(Yz), pj, max_iters=5,
                            cfg=EMConfig(filter="pit_qr"))
    np.testing.assert_allclose(np.asarray(lls_q), np.asarray(lls_i),
                               rtol=1e-9)


def test_fused_fit_with_pit_qr_matches_chunked(setup):
    """filter="pit_qr" routes through the fused while-loop driver too
    (the in-loop E-step is the same _em_chunk_body)."""
    from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
    p, Y = setup
    model = DynamicFactorModel(n_factors=3)
    kw = dict(max_iters=6, tol=0.0)
    r_ch = fit(model, Y, backend=TPUBackend(dtype=jnp.float64,
                                            filter="pit_qr"), **kw)
    r_fu = fit(model, Y, backend=TPUBackend(dtype=jnp.float64,
                                            filter="pit_qr"), fused=True,
               **kw)
    np.testing.assert_allclose(np.asarray(r_fu.logliks),
                               np.asarray(r_ch.logliks), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(r_fu.params.Lam),
                               np.asarray(r_ch.params.Lam), atol=1e-9)


def test_mf_time_scan_pit_qr_matches_seq():
    """MixedFreqSpec(time_scan="pit_qr") reproduces the sequential
    augmented E-step (small state: the statically-unrolled QR kernels)."""
    from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
    rng = np.random.default_rng(65)
    Y, mask, _, _ = dgp.simulate_mixed_freq(
        n_monthly=12, n_quarterly=3, T=36, k=1, rng=rng)
    spec = MixedFreqSpec(n_monthly=12, n_quarterly=3, n_factors=1)
    r_seq = mf_fit(Y, spec, mask=mask, max_iters=4, tol=0.0)
    r_qr = mf_fit(Y, dataclasses.replace(spec, time_scan="pit_qr"),
                  mask=mask, max_iters=4, tol=0.0)
    np.testing.assert_allclose(np.asarray(r_qr.logliks),
                               np.asarray(r_seq.logliks), rtol=1e-7)
    with pytest.raises(ValueError):
        MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2,
                      time_scan="qr")


def test_fit_stamps_resolved_filter(setup):
    """FitResult.filter carries the resolved in-loop engine; the traced
    fit event and summarize()/obs.report surface it."""
    from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
    from dfm_tpu.obs import Tracer
    p, Y = setup
    model = DynamicFactorModel(n_factors=3)
    tr = Tracer()
    res = fit(model, Y, backend=TPUBackend(dtype=jnp.float64,
                                           filter="pit_qr"),
              max_iters=3, tol=0.0, telemetry=tr)
    assert res.filter == "pit_qr"
    fit_evs = [e for e in tr.events if e.get("kind") == "fit"]
    assert fit_evs and fit_evs[0]["filter"] == "pit_qr"
    assert tr.summary()["fits"][0]["filter"] == "pit_qr"
    # Backends without the filter knob leave the stamp unset.
    assert fit(model, Y, backend="cpu", max_iters=2).filter is None


def test_backend_rejects_unknown_filter():
    from dfm_tpu.api import TPUBackend
    with pytest.raises(ValueError):
        TPUBackend(filter="qr")

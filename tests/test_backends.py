"""Backend-dispatch seam tests (SURVEY.md section 4.2.7 test_backends):
cpu and tpu backends interchangeable behind fit(), loglik agreement to the
BASELINE.json:5 bound, NaN handling, validation errors.
"""

import numpy as np
import pytest

import dfm_tpu
from dfm_tpu import DynamicFactorModel, fit, forecast
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(21)
    p = dgp.dfm_params(N=20, k=2, rng=rng)
    Y, F = dgp.simulate(p, T=60, rng=rng)
    return Y


def test_cpu_tpu_loglik_agree(panel):
    m = DynamicFactorModel(n_factors=2)
    r_cpu = fit(m, panel, backend="cpu", max_iters=10, tol=0.0)
    r_tpu = fit(m, panel, backend="tpu", max_iters=10, tol=0.0)
    # x64 on fake-CPU jax -> near-exact; the 1e-5 spec bound is generous here.
    np.testing.assert_allclose(r_tpu.logliks, r_cpu.logliks, rtol=1e-7)
    np.testing.assert_allclose(r_tpu.factors, r_cpu.factors, atol=1e-6)


def test_static_model(panel):
    m = DynamicFactorModel(n_factors=2, dynamics="static")
    r = fit(m, panel, backend="tpu", max_iters=8, tol=0.0)
    assert np.allclose(r.params.A, 0.0)
    assert np.allclose(r.params.Q, np.eye(2))
    assert np.all(np.diff(r.logliks) >= -1e-7)


def test_nan_panel_auto_mask(panel):
    Yn = panel.copy()
    rng = np.random.default_rng(22)
    miss = rng.random(Yn.shape) < 0.15
    Yn[miss] = np.nan
    m = DynamicFactorModel(n_factors=2)
    r_cpu = fit(m, Yn, backend="cpu", max_iters=6, tol=0.0)
    r_tpu = fit(m, Yn, backend="tpu", max_iters=6, tol=0.0)
    assert np.isfinite(r_cpu.logliks).all()
    np.testing.assert_allclose(r_tpu.logliks, r_cpu.logliks, rtol=1e-7)


def test_monotone_loglik_through_api(panel):
    m = DynamicFactorModel(n_factors=3)
    r = fit(m, panel, backend="tpu", max_iters=15, tol=0.0)
    assert np.all(np.diff(r.logliks) >= -1e-7)
    assert r.n_iters == 15
    assert len(r.history) == 15
    assert all("secs" in h for h in r.history)


def test_forecast_destandardized(panel):
    m = DynamicFactorModel(n_factors=2)
    r = fit(m, panel, backend="cpu", max_iters=5)
    y, f = forecast(r, horizon=4)
    assert y.shape == (4, panel.shape[1])
    # De-standardized forecasts live on the data scale.
    assert np.all(np.abs(y.mean(0) - panel.mean(0)) < 5 * panel.std(0))


def test_validation_errors(panel):
    with pytest.raises(ValueError, match="dynamics"):
        DynamicFactorModel(n_factors=2, dynamics="arma")
    with pytest.raises(ValueError, match="n_factors"):
        DynamicFactorModel(n_factors=0)
    with pytest.raises(ValueError, match="exceeds"):
        fit(DynamicFactorModel(n_factors=200), panel)
    with pytest.raises(ValueError, match="unknown backend"):
        fit(DynamicFactorModel(n_factors=2), panel, backend="cuda")
    with pytest.raises(ValueError, match="must be"):
        fit(DynamicFactorModel(n_factors=2), panel[:, 0])


def test_backend_registry_plugin():
    from dfm_tpu.api import _BACKENDS

    class MyBackend(dfm_tpu.CPUBackend):
        name = "mine"

    try:
        dfm_tpu.register_backend("mine", MyBackend)
        assert isinstance(dfm_tpu.get_backend("mine"), MyBackend)
        # Instances pass through the seam untouched.
        inst = MyBackend()
        assert dfm_tpu.get_backend(inst) is inst
    finally:
        _BACKENDS.pop("mine", None)


def test_convergence_flag(panel):
    m = DynamicFactorModel(n_factors=2)
    r = fit(m, panel, backend="cpu", max_iters=200, tol=1e-5)
    assert r.converged
    assert r.n_iters < 200


# ---------------------------------------------------------------------------
# Fused-chunk stop/replay semantics (code-review r4): drive _run_em_chunked
# with a scripted loglik sequence, representing "params" as the integer
# number of EM updates they embody — the scan stub advances the counter and
# serves the scripted logliks, so each replay branch's arithmetic is checked
# exactly against the per-iteration drivers' contracts.
# ---------------------------------------------------------------------------

def _run_scripted_chunked(lls_script, fused_chunk, max_iters=None, tol=1e-6):
    import jax.numpy as jnp
    from dfm_tpu.api import TPUBackend
    from dfm_tpu.estim.em import EMConfig

    def scan_fn(Yj, p, n, mask=None, cfg=None):
        return p + n, jnp.asarray(lls_script[p:p + n]), jnp.zeros((n,))

    b = TPUBackend(fused_chunk=fused_chunk)
    return b._run_em_chunked(
        jnp.zeros((2,), jnp.float64), None, 0, EMConfig(filter="info"),
        max_iters if max_iters is not None else len(lls_script),
        tol, None, scan_fn)[:4]    # [:4]: drop the smooth cell


def test_chunked_replay_converged_mid_chunk():
    # Convergence detected at index 4 (|rel change| < tol): params must
    # embody 5 updates, not the chunk's 8.
    lls = [-100.0, -50.0, -30.0, -20.0, -20.0 + 1e-9, -19.0, -18.0, -17.0]
    p, out_lls, converged, p_iters = _run_scripted_chunked(lls, fused_chunk=8)
    assert converged and p == 5 and p_iters == 5 and len(out_lls) == 5


def test_chunked_replay_diverged_mid_chunk():
    # Drop at index 4 -> params entering iteration 3 (= 3 updates), the
    # em_fit divergence contract.
    lls = [-100.0, -50.0, -30.0, -20.0, -40.0, -10.0, -9.0, -8.0]
    p, out_lls, converged, p_iters = _run_scripted_chunked(lls, fused_chunk=8)
    assert not converged and p == 3 and p_iters == 3 and len(out_lls) == 5


def test_chunked_replay_drop_at_chunk_start():
    # fused_chunk=3: drop at global index 3 = first loglik of chunk 2, which
    # blames chunk 1's last update -> target 2 sits BEFORE the current
    # chunk entry (3), forcing the p_entry_prev replay branch.
    lls = [-100.0, -50.0, -30.0, -60.0, -10.0, -9.0]
    p, out_lls, converged, p_iters = _run_scripted_chunked(lls, fused_chunk=3)
    assert not converged and p == 2 and p_iters == 2 and len(out_lls) == 4


def test_chunked_converged_at_chunk_boundary_no_replay():
    # Convergence exactly at the chunk's last index: chunk-end params already
    # embody the target; p must be the unreplayed chunk end (4 updates).
    lls = [-100.0, -50.0, -30.0, -30.0 + 1e-9, -20.0, -19.0]
    p, out_lls, converged, p_iters = _run_scripted_chunked(lls, fused_chunk=4)
    assert converged and p == 4 and p_iters == 4 and len(out_lls) == 4


def test_chunked_maxiter_no_stop():
    lls = [-100.0, -50.0, -30.0, -20.0, -15.0, -12.0]
    p, out_lls, converged, p_iters = _run_scripted_chunked(
        lls, fused_chunk=4, tol=0.0)
    assert not converged and p == 6 and p_iters == 6 and len(out_lls) == 6


def test_fused_smooth_cache_matches_separate_smooth(panel):
    """The chunked driver's in-program final smooth (consumed by smooth()
    via the identity-keyed cache) must equal the standalone smooth path
    (fused_chunk=1 driver — no cache), factors included (VERDICT r4 item 5
    fused-final-smooth)."""
    import jax.numpy as jnp
    from dfm_tpu.api import TPUBackend
    model = DynamicFactorModel(n_factors=3)
    b8 = TPUBackend(dtype=jnp.float64, fused_chunk=8)
    r8 = fit(model, panel, backend=b8, max_iters=6, tol=0.0)
    assert b8._smooth_cache is None     # consumed exactly once
    r1 = fit(model, panel, backend=TPUBackend(dtype=jnp.float64,
                                              fused_chunk=1),
             max_iters=6, tol=0.0)
    np.testing.assert_allclose(r8.logliks, r1.logliks, rtol=1e-12)
    np.testing.assert_allclose(r8.factors, r1.factors, atol=1e-10)
    np.testing.assert_allclose(r8.factor_cov, r1.factor_cov, atol=1e-10)


def test_fused_smooth_cache_correct_after_divergence_replay(panel):
    """After a mid-chunk stop the returned params come from a REPLAY
    program; the smooth cache must match those params (or be bypassed),
    never the overshot chunk's."""
    import jax.numpy as jnp
    from dfm_tpu.api import TPUBackend
    model = DynamicFactorModel(n_factors=3)
    # tol large enough to converge mid-chunk quickly
    b = TPUBackend(dtype=jnp.float64, fused_chunk=5)
    r = fit(model, panel, backend=b, max_iters=20, tol=1e-3)
    assert r.converged and r.n_iters < 20
    # reference: smooth computed independently at the returned params
    from dfm_tpu.backends import cpu_ref
    Yz = r.standardizer.transform(panel)
    kf = cpu_ref.kalman_filter(Yz, r.params)
    sm = cpu_ref.rts_smoother(kf, r.params)
    np.testing.assert_allclose(r.factors, sm.x_sm, atol=1e-8)


def test_device_init_auto_threshold():
    """device_init='auto' switches on only for large panels."""
    from dfm_tpu.api import TPUBackend
    b = TPUBackend()
    assert b.device_init == "auto"
    small = np.zeros((100, 50))
    big = np.zeros((500, 10_000))
    assert not b._use_device_init(small)
    assert b._use_device_init(big)
    assert TPUBackend(device_init=False)._use_device_init(big) is False
    assert TPUBackend(device_init=True)._use_device_init(small) is True

"""Model-quality drift detection + closed-loop maintenance (ISSUE 18).

The operative contracts, on the fake 8-device CPU mesh (conftest):

- DETECTOR: the jax-free CUSUM detector (``obs/drift.py``) fires on a
  sustained shift of any query signal, clears with hysteresis, treats
  the ll-per-row LEVEL as nonstationary (only its first difference is
  tracked — a trending panel loglik never reads as drift), never fires
  on over-coverage, and its state round-trips exactly.
- OFF-PATH INERTNESS: the SAME serving workload with drift detection
  disarmed and armed produces bit-identical numbers and the same
  dispatch count — the detector is host arithmetic on signals the
  query path already emits.
- HOT SWAP: ``fleet.swap_params`` serves exactly what a fleet opened
  cold on the swapped params serves; swapping unchanged params is a
  bit-identical no-op; a swap mid-ring-stream leaves the eviction
  ledger intact.
- MAINTENANCE: ``run_maintenance`` refits in the background (serving
  executable untouched), gates the swap on held-out quality, resets the
  swapped tenant's detector, and leaves params untouched on a skip.
- PERSISTENCE: detector state rides session/fleet snapshots.
- TRAIL: ``summarize`` always carries a stable-keyed ``maintenance``
  section; trigger/refit/swap events land as per-tenant rows.
"""

import dataclasses
import json

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.api import TPUBackend
from dfm_tpu.fleet import MaintenancePolicy, heldout_score, run_maintenance
from dfm_tpu.obs import live as live_mod
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.drift import DriftConfig, DriftDetector, drift_from_env
from dfm_tpu.obs.report import summarize
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

BE = TPUBackend(filter="info")
MODEL = DynamicFactorModel(n_factors=2)
CFG = DriftConfig()


@pytest.fixture
def fresh_plane(monkeypatch):
    """A clean enabled plane for this test; restore the lazy singleton."""
    for var in ("DFM_METRICS", "DFM_DRIFT", "DFM_SLO_P99_MS",
                "DFM_FLIGHT_DIR", "DFM_METRICS_SNAPSHOT"):
        monkeypatch.delenv(var, raising=False)
    live_mod.reset_plane()
    yield live_mod.plane()
    live_mod.reset_plane()


def _panel(T, N, k, seed):
    rng = np.random.default_rng(seed)
    Y, _ = dgp.simulate(dgp.dfm_params(N, k, rng), T, rng)
    return Y


def _feed_healthy(det, n, z=0.8, cov=0.92, ll=-1.3):
    """Healthy stream with small deterministic jitter so the baseline
    sds are honest (a constant signal would pin them to the floor and
    turn the first real deviation into a ~1000-sd event)."""
    out = []
    for i in range(n):
        j = 0.1 * (-1.0) ** i
        out.append(det.observe(float(i), innov_z=z + 0.5 * j,
                               coverage=cov, ll_per_row=ll + j))
    return out


# ------------------------------------------------------- detector ------

def test_fire_then_clear_hysteresis():
    det = DriftDetector(CFG)
    assert all(r is None for r in _feed_healthy(det, CFG.baseline_n + 2))
    assert not det.breached and det.drift_score == 0.0
    # A sustained break: hot innovations + undercoverage + loglik drop.
    fired_at = None
    for j in range(20):
        r = det.observe(100.0 + j, innov_z=3.0, coverage=0.4,
                        ll_per_row=-8.0 - j)
        if r == "fire":
            fired_at = j
            break
    assert fired_at is not None and det.breached and det.n_fired == 1
    assert det.drift_score > 1.0
    assert det.drift_score_max >= det.drift_score
    # Recovery: healthy signals decay g below clear_at * threshold (the
    # loop bound scales with g at the fire — g shrinks by at most the
    # allowance per healthy update).
    seen = set()
    det2_ll = det.last["ll_per_row"]
    for j in range(int(det.g / CFG.allowance) + 20):
        seen.add(det.observe(200.0 + j, innov_z=0.8, coverage=0.92,
                             ll_per_row=det2_ll))
        if "clear" in seen:
            break
    assert "clear" in seen and not det.breached
    assert det.n_fired == 1          # clear does not double-count


def test_ll_level_trend_is_not_drift():
    """A steadily trending loglik LEVEL (constant first difference, as a
    growing or ring-evicting panel produces) must never fire; a sudden
    drop in the difference must."""
    det = DriftDetector(CFG)
    for i in range(40):
        r = det.observe(float(i), innov_z=0.8, coverage=0.92,
                        ll_per_row=-1.0 - 0.05 * i)   # trending level
        assert r is None, f"trending ll level fired at {i}"
    assert det.g == 0.0
    for j in range(10):
        r = det.observe(100.0 + j, innov_z=0.8, coverage=0.92,
                        ll_per_row=-3.0 - 4.0 * j)    # diff jumps to -4
        if r == "fire":
            break
    assert det.breached


def test_partial_and_missing_signals():
    det = DriftDetector(CFG)
    _feed_healthy(det, CFG.baseline_n)
    g = det.g
    assert det.observe(50.0) is None                  # no signals at all
    assert det.g == g
    det.observe(51.0, innov_z=float("nan"), coverage=None)
    assert det.g == g                                 # non-finite ignored
    det.observe(52.0, coverage=0.9)                   # coverage-only is fine
    assert np.isfinite(det.g)


def test_overcoverage_never_fires():
    """Conservative rank-r bands OVER-cover — that must read as healthy
    (the coverage deviation is one-sided against the nominal level)."""
    det = DriftDetector(CFG)
    _feed_healthy(det, CFG.baseline_n)
    for j in range(40):
        r = det.observe(100.0 + j, innov_z=0.8, coverage=1.0,
                        ll_per_row=-1.3)
        assert r is None
    assert det.g == 0.0


def test_state_roundtrip_continues_identically():
    """snapshot/restore mid-stream == uninterrupted, including the
    ll first-difference accumulator."""
    for cut in (CFG.baseline_n // 2, CFG.baseline_n + 4):
        a = DriftDetector(CFG)
        _feed_healthy(a, cut, ll=-2.0)
        b = DriftDetector.from_state(
            json.loads(json.dumps(a.state_dict())))
        assert b._ll_prev == a._ll_prev
        for j in range(25):
            ra = a.observe(100.0 + j, innov_z=2.5, coverage=0.5,
                           ll_per_row=-6.0 - j)
            rb = b.observe(100.0 + j, innov_z=2.5, coverage=0.5,
                           ll_per_row=-6.0 - j)
            assert ra == rb
            assert a.g == b.g and a.drift_score == b.drift_score
        assert a.status() == b.status()


def test_reset_keeps_fire_counter():
    det = DriftDetector(CFG)
    _feed_healthy(det, CFG.baseline_n)
    for j in range(30):
        if det.observe(100.0 + j, innov_z=4.0, coverage=0.3):
            break
    assert det.n_fired == 1 and det.breached
    det.reset()
    assert det.n_fired == 1          # ledger survives
    assert det.n == 0 and det.g == 0.0 and not det.breached
    assert det._ll_prev is None and det.last == {}
    assert det._in_baseline()        # fresh regime, fresh baseline


def test_drift_from_env(monkeypatch):
    for off in (None, "", "0", "off", "false", "OFF"):
        if off is None:
            monkeypatch.delenv("DFM_DRIFT", raising=False)
        else:
            monkeypatch.setenv("DFM_DRIFT", off)
        assert drift_from_env() is None
    monkeypatch.setenv("DFM_DRIFT", "1")
    assert drift_from_env() == DriftConfig()
    monkeypatch.setenv("DFM_DRIFT_THRESHOLD", "9.5")
    monkeypatch.setenv("DFM_DRIFT_BASELINE_N", "7")
    cfg = drift_from_env()
    assert cfg.threshold == 9.5 and cfg.baseline_n == 7


# ------------------------------------------------------ live plane -----

def test_plane_fire_emits_health_event_and_metrics(fresh_plane):
    pl = fresh_plane
    pl.set_drift(DriftConfig())
    for i in range(CFG.baseline_n + 10):
        drifted = i >= CFG.baseline_n + 2
        pl.observe({"t": float(i), "kind": "query", "session": "s9",
                    "tenant": "acme", "wall": 0.002,
                    "innov_z": 3.5 if drifted else 0.8,
                    "coverage": 0.3 if drifted else 0.92,
                    "ll_per_row": -9.0 - i if drifted else -1.3})
    st = pl.drift_status()
    assert st["armed"] and "acme" in st["breached"]
    assert st["per_tenant"]["acme"]["n_fired"] == 1
    snap = pl.registry.snapshot()
    assert any(k.startswith("drift_events_total")
               for k in snap["counters"])
    assert any(k.startswith("drift_score") for k in snap["gauges"])
    # state snapshot surfaces per tenant + restore continues
    state = pl.drift_state("acme")
    pl.set_drift(DriftConfig())       # drops detectors
    assert pl.drift_status()["per_tenant"] == {}
    pl.restore_drift("acme", state)
    assert pl.drift_status()["per_tenant"]["acme"]["n_fired"] == 1


def test_disarmed_plane_tracks_nothing(fresh_plane):
    pl = fresh_plane
    assert pl.drift_cfg is None       # library default: off
    pl.observe({"t": 0.0, "kind": "query", "session": "s1",
                "wall": 0.001, "innov_z": 99.0, "coverage": 0.0})
    assert pl.drift_status() == {"armed": False, "n_tenants": 0,
                                 "breached": [], "per_tenant": {}}
    pl.restore_drift("x", {"v": 1})   # no-op while disarmed
    assert pl.drift_status()["per_tenant"] == {}


# ------------------------------------------------------ report ---------

def test_summarize_maintenance_section_always_present_empty_shape():
    s = summarize([{"kind": "dispatch", "program": "x", "key": "k",
                    "t": 0.0, "dur": 0.01, "barrier": True,
                    "first_call": True}])
    assert s["maintenance"] == {"drift_fires": 0, "drift_clears": 0,
                                "triggers": 0, "refits": 0, "swaps": 0,
                                "retunes": 0, "skips": 0,
                                "per_tenant": {}}
    assert json.loads(json.dumps(s)) == s


def test_summarize_maintenance_rows_from_trace_events():
    evs = [
        {"kind": "maintenance", "t": 1.0, "tenant": "acme",
         "action": "trigger", "engine": "info", "advice": "info",
         "drift_score": 1.4, "innov_z": 2.1, "coverage": 0.5},
        {"kind": "maintenance", "t": 2.0, "tenant": "acme",
         "action": "refit", "refit_s": 0.8, "n_iters": 12,
         "converged": True, "engine": "info", "advice": "info"},
        {"kind": "maintenance", "t": 3.0, "tenant": "acme",
         "action": "swap", "quality_delta": 0.25, "score_before": 1.0,
         "score_after": 0.75, "engine": "info", "advice": "info"},
    ]
    mt = summarize(evs)["maintenance"]
    assert (mt["triggers"], mt["refits"], mt["swaps"], mt["skips"]) \
        == (1, 1, 1, 0)
    row = mt["per_tenant"]["acme"]
    assert row["action"] == "swap"
    assert row["quality_delta"] == 0.25
    assert row["trigger"]["drift_score"] == 1.4
    assert row["engine"] == "info" and row["advice"] == "info"


# --------------------------------------------- serving integration -----

Y_ALL = None


def _data():
    global Y_ALL
    if Y_ALL is None:
        Y_ALL = _panel(48, 8, 2, 77)
    return Y_ALL[:40], Y_ALL[40:]


def _session_workload():
    """Tiny traced session run: (sha over answers, dispatch count)."""
    import hashlib
    Y0, stream = _data()
    h = hashlib.sha256()
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        res = fit(MODEL, Y0, max_iters=4, tol=1e-6, fused=True)
        sess = open_session(res, Y0, capacity=48, max_update_rows=2,
                            max_iters=2, tol=0.0)
        for i in range(3):
            u = sess.update(stream[2 * i:2 * i + 2])
            h.update(np.asarray(u.nowcast, np.float64).tobytes())
            h.update(np.asarray(u.forecasts["y"], np.float64).tobytes())
        sess.close()
    return h.hexdigest(), tr.summary()["dispatches"]


def test_drift_armed_is_bit_identical_at_equal_dispatches(fresh_plane):
    live_mod.set_drift(None)
    off = _session_workload()
    live_mod.set_drift(DriftConfig())
    on = _session_workload()
    assert off == on
    # ... and the armed run actually scored the queries.
    assert live_mod.drift_status()["n_tenants"] == 1


def _fleet_answer(res, Y0, rows, swap=None, ring=False, n_updates=1):
    fl = open_fleet([res], [Y0], tenants=["t0"], capacity=48,
                    max_update_rows=2, max_iters=2, tol=0.0, ring=ring)
    if swap is not None:
        fl.swap_params("t0", swap)
    for i in range(n_updates):
        fl.submit("t0", rows[2 * i:2 * i + 2])
        u = fl.drain()["t0"][-1]
    fl.close()
    return u


def test_hot_swap_bit_exact_vs_cold_open_and_noop():
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    res2 = fit(MODEL, Y0, max_iters=10, tol=0.0, fused=True)
    assert not np.allclose(res.params.Lam, res2.params.Lam)
    a = _fleet_answer(res, Y0, stream, swap=res2.params)
    b = _fleet_answer(dataclasses.replace(res, params=res2.params), Y0,
                      stream)
    assert np.array_equal(np.asarray(a.nowcast), np.asarray(b.nowcast))
    for key in a.forecasts:
        assert np.array_equal(np.asarray(a.forecasts[key]),
                              np.asarray(b.forecasts[key])), key
    # No-op swap: unchanged params are bit-identical.
    c = _fleet_answer(res, Y0, stream)
    d = _fleet_answer(res, Y0, stream, swap=res.params.copy())
    assert np.array_equal(np.asarray(c.nowcast), np.asarray(d.nowcast))


def test_swap_mid_ring_stream_keeps_eviction_ledger():
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    ledgers = {}
    # The per-query warm EM evolves the resident params, so the true
    # no-op is re-installing the CURRENT resident params (an f64 read
    # is an exact representation of the device values).
    for do_swap in (False, True):
        sess = open_session(res, Y0, capacity=42, max_update_rows=2,
                            max_iters=2, tol=0.0, ring=True)
        led = []
        for i in range(4):
            if do_swap and i == 2:
                sess.swap_params(sess._p.to_numpy())   # no-op swap
            u = sess.update(stream[2 * i:2 * i + 2])
            led.append((sess.n_evicted, sess.total_rows,
                        np.asarray(u.nowcast).tobytes()))
        sess.close()
        ledgers[do_swap] = led
    assert ledgers[False] == ledgers[True]
    # the ring actually evicted during the run
    assert ledgers[False][-1][0] > 0


def test_maintenance_skip_leaves_params_untouched(fresh_plane):
    live_mod.set_drift(DriftConfig())
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    answers = {}
    for gate in ("none", "inf"):
        fl = open_fleet([res], [Y0], tenants=["t0"], capacity=48,
                        max_update_rows=2, max_iters=2, tol=0.0)
        fl.submit("t0", stream[:2])
        fl.drain()
        if gate == "inf":
            recs = run_maintenance(
                fl, ["t0"], policy=MaintenancePolicy(
                    min_gain=float("inf"), max_iters=6))
            assert len(recs) == 1 and recs[0].action == "skip"
            assert recs[0].swap_t is None
            assert np.isfinite(recs[0].quality_delta)
        fl.submit("t0", stream[2:4])
        answers[gate] = np.asarray(fl.drain()["t0"][-1].nowcast)
        fl.close()
    assert np.array_equal(answers["none"], answers["inf"])


def test_maintenance_swap_installs_refit_and_resets_detector(fresh_plane):
    pl = fresh_plane
    live_mod.set_drift(DriftConfig())
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    fl = open_fleet([res], [Y0], tenants=["t0"], capacity=48,
                    max_update_rows=2, max_iters=2, tol=0.0)
    fl.submit("t0", stream[:2])
    fl.drain()
    n_before = pl.drift_status()["per_tenant"]["t0"]["n_observed"]
    assert n_before >= 1
    recs = run_maintenance(fl, ["t0"],
                           policy=MaintenancePolicy(
                               min_gain=float("-inf"), max_iters=8))
    assert len(recs) == 1
    r = recs[0]
    assert r.action == "swap" and r.swap_t is not None
    assert r.engine == "info" and r.advice
    assert r.refit_iters >= 1 and r.refit_s >= 0.0
    assert np.isfinite(r.score_before) and np.isfinite(r.score_after)
    assert r.quality_delta == pytest.approx(
        r.score_before - r.score_after)
    # swap reset the tenant's detector: a fresh baseline follows.
    assert pl.drift_status()["per_tenant"]["t0"]["n_observed"] == 0
    # ... and the refit params are what the fleet now serves.
    _, slot = fl._slot_of["t0"]
    p_now = fl._slot_params_np(*fl._slot_of["t0"])
    Yz = slot.std.transform(np.asarray(slot.Y_orig, np.float64))
    W = np.asarray(slot.W_orig, np.float64)
    Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
    assert heldout_score(Yz, W, p_now, 8) == pytest.approx(r.score_after)
    fl.close()


def test_unknown_tenant_raises(fresh_plane):
    Y0, _ = _data()
    res = fit(MODEL, Y0, max_iters=2, tol=0.0, fused=True)
    fl = open_fleet([res], [Y0], tenants=["t0"], capacity=44,
                    max_update_rows=2, max_iters=2, tol=0.0)
    with pytest.raises(KeyError):
        fl.swap_params("ghost", res.params)
    with pytest.raises(KeyError):
        run_maintenance(fl, ["ghost"])
    fl.close()


def test_session_snapshot_roundtrips_drift_state(tmp_path, fresh_plane):
    pl = fresh_plane
    live_mod.set_drift(DriftConfig())
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    sess = open_session(res, Y0, capacity=48, max_update_rows=2,
                        max_iters=2, tol=0.0)
    for i in range(3):
        sess.update(stream[2 * i:2 * i + 2])
    state = pl.drift_state(sess.session_id)
    assert state is not None and state["n"] == 3
    path = sess.snapshot(str(tmp_path / "sess.npz"))
    sess.close()
    pl.set_drift(DriftConfig())       # wipe in-process detectors
    sess2 = open_session(snapshot=path)
    st2 = pl.drift_state(sess2.session_id)
    assert st2 is not None
    assert {k: v for k, v in st2.items()} == \
        {k: v for k, v in state.items()}
    sess2.close()


def test_fleet_snapshot_roundtrips_drift_state(tmp_path, fresh_plane):
    from dfm_tpu.fleet import restore_fleet
    pl = fresh_plane
    live_mod.set_drift(DriftConfig())
    Y0, stream = _data()
    res = fit(MODEL, Y0, max_iters=3, tol=0.0, fused=True)
    fl = open_fleet([res], [Y0], tenants=["t0"], capacity=48,
                    max_update_rows=2, max_iters=2, tol=0.0)
    for i in range(2):
        fl.submit("t0", stream[2 * i:2 * i + 2])
        fl.drain()
    state = pl.drift_state("t0")
    assert state is not None and state["n"] == 2
    fl.snapshot_all(str(tmp_path / "snap"))
    fl.close()
    pl.set_drift(DriftConfig())
    fl2 = restore_fleet(str(tmp_path / "snap"))
    assert pl.drift_state("t0") == state
    fl2.close()

"""Differentiable hyper-tuning (estim/tune.py + fit(tune=...)).

Pins the PR-20 contracts:

- The in-graph held-out objective equals the NumPy f64 oracle twin, and
  its ``jax.grad`` matches central finite differences of the ORACLE to
  <= 1e-5 relative (x64, masked and unmasked) — the gradient really
  flows through filter -> smoother -> M-step chain -> eval filter.
- The gradient search and the CV sweep never return a point worse than
  untuned at the same EM budget (best-tracking includes theta = 0), and
  on a masked panel the tuned fit strictly improves held-out one-step
  MSE over the untuned EM fit.
- ``fit(tune=...)``: record on ``FitResult.tune``, tuned hypers really
  reach the fit's M-step, hypers are transient (the backend serves
  untuned fits bit-identically afterwards), ``tune=None`` is
  bit-identical to pre-tune ``fit()``, ``auto=True`` conflicts, the CPU
  backend warns + skips, fused/telemetry/robust compose, and the whole
  search stays on its dispatch budget (proven from the trace).
- Tuned (generalized) EM is non-monotone in the loglik by design: the
  convergence seams classify a beyond-floor terminal drop as plateau
  convergence (``monotone=False``) instead of divergence.
- ``MaintenancePolicy(retune=True)``: the tuned candidate rides the
  held-out gate and lands through the params-only swap seam with
  ``action="retune"`` + the chosen hypers in the decision trail.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dfm_tpu import DynamicFactorModel, fit, open_fleet
from dfm_tpu.api import CPUBackend, TPUBackend
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig, cfg_hypers, em_progress
from dfm_tpu.estim.score import heldout_mse_np
from dfm_tpu.estim.tune import (DEFAULT_GRID, TuneOptions, _heldout_loss,
                                heldout_loss_np, resolve_tune, tune_fit)
from dfm_tpu.fleet import MaintenancePolicy, run_maintenance
from dfm_tpu.ssm.params import SSMParams as JaxParams
from dfm_tpu.utils import dgp

N, T, K = 10, 48, 2


def _panel(seed=5, frac_missing=0.0):
    rng = np.random.default_rng(seed)
    Y_raw, _ = dgp.simulate(dgp.dfm_params(N, K, rng), T, rng)
    Y = (Y_raw - Y_raw.mean(0)) / Y_raw.std(0)
    W = (dgp.random_mask(T, N, rng, frac_missing) if frac_missing
         else np.ones((T, N)))
    p0 = cpu_ref.pca_init(Y * W if frac_missing else Y, K)
    return Y, W, p0


# ------------------------------------------------ gradient parity -----

@pytest.mark.parametrize("frac", [0.0, 0.25], ids=["unmasked", "masked"])
def test_grad_matches_central_fd_of_oracle(frac):
    """jax.grad of the in-graph loss == central FD of the NumPy oracle
    (<= 1e-5 rel, x64) at a non-trivial theta, with the ridge active."""
    h, iters, lam = 6, 3, 0.05
    Y, W, p0 = _panel(11, frac)
    Wfull = np.asarray(W, np.float64)
    Wtr = Wfull.copy()
    Wtr[T - h:] = 0.0
    Yz = np.where(Wfull > 0, Y, 0.0)
    cfg = EMConfig(filter="info")
    p0g = JaxParams(*(jnp.asarray(x, jnp.float64) for x in
                      (p0.Lam, p0.A, p0.Q, p0.R, p0.mu0, p0.P0)))
    theta = np.array([0.3, -0.2])

    def graph_loss(th):
        loss, _ = _heldout_loss(
            jnp.asarray(th, jnp.float64), jnp.asarray(Yz, jnp.float64),
            jnp.asarray(Wtr, jnp.float64), jnp.asarray(Wfull, jnp.float64),
            p0g, cfg, iters, h, jnp.asarray(lam, jnp.float64))
        return loss

    with jax.default_matmul_precision("highest"):
        # Objective parity first: graph == oracle at the same theta.
        f_graph = float(graph_loss(jnp.asarray(theta)))
        f_np = heldout_loss_np(theta, Yz, Wtr, Wfull, p0, iters, h,
                               lam_ridge=lam)
        assert abs(f_graph - f_np) / abs(f_np) < 1e-8, (f_graph, f_np)
        g_ad = np.asarray(jax.grad(graph_loss)(jnp.asarray(theta)),
                          np.float64)

    eps = 1e-6
    g_fd = np.empty(2)
    for i in range(2):
        tp, tm = theta.copy(), theta.copy()
        tp[i] += eps
        tm[i] -= eps
        g_fd[i] = (heldout_loss_np(tp, Yz, Wtr, Wfull, p0, iters, h,
                                   lam_ridge=lam)
                   - heldout_loss_np(tm, Yz, Wtr, Wfull, p0, iters, h,
                                     lam_ridge=lam)) / (2 * eps)
    rel = np.abs(g_ad - g_fd) / np.maximum(np.abs(g_fd), 1e-12)
    assert rel.max() < 1e-5, (g_ad, g_fd, rel)


# ------------------------------------------------ search quality ------

def test_grad_search_never_worse_and_strictly_improves_masked():
    """Best-tracking includes theta = 0, so tuned <= untuned always; on
    this masked panel the search strictly improves the held-out MSE."""
    Y, W, p0 = _panel(21, 0.2)
    rec = tune_fit(Y, W, p0, EMConfig(filter="info"),
                   TuneOptions(method="grad", steps=8, em_iters=4),
                   dtype=jnp.float64)
    assert rec["dispatches"] == 1
    assert rec["heldout_after"] <= rec["heldout_before"] + 1e-12
    assert rec["heldout_after"] < rec["heldout_before"], rec
    assert len(rec["trajectory"]["loss"]) == 8
    # theta = 0 is the first evaluation: the recorded "before" IS it.
    assert rec["heldout_before"] == rec["trajectory"]["loss"][0]


def test_sweep_scores_every_lane_and_picks_argmin():
    Y, W, p0 = _panel(22, 0.1)
    rec = tune_fit(Y, W, p0, EMConfig(filter="info"),
                   TuneOptions(method="sweep", em_iters=4),
                   dtype=jnp.float64)
    assert rec["dispatches"] == 2
    assert len(rec["cv"]) == len(DEFAULT_GRID)
    scores = [c["heldout"] for c in rec["cv"]]
    best = rec["cv"][int(np.nanargmin(scores))]
    assert rec["heldout_after"] == best["heldout"]
    assert (rec["q_scale"], rec["r_scale"]) == (best["q_scale"],
                                                best["r_scale"])
    # The (1,1,0) lane is the untuned yardstick: sweep can only improve.
    assert rec["heldout_after"] <= rec["heldout_before"] + 1e-12


def test_sweep_single_untuned_point_is_identity():
    Y, _, p0 = _panel(23)
    rec = tune_fit(Y, None, p0, EMConfig(filter="info"),
                   TuneOptions(method="sweep", grid=((1.0, 1.0, 0.0),),
                               em_iters=3), dtype=jnp.float64)
    assert rec["q_scale"] == 1.0 and rec["r_scale"] == 1.0
    assert rec["heldout_after"] == rec["heldout_before"]


def test_oracle_scoring_agrees_with_sweep_lane():
    """A sweep lane's in-graph held-out score == oracle rescoring of the
    lane's returned params (same estim.score definition end to end)."""
    Y, W, p0 = _panel(24, 0.1)
    rec = tune_fit(Y, W, p0, EMConfig(filter="info"),
                   TuneOptions(method="sweep", grid=((2.0, 0.5, 0.0),),
                               em_iters=3),
                   dtype=jnp.float64, return_params=True)
    s_np = heldout_mse_np(np.where(W > 0, Y, np.nan), W,
                          rec["best_params"], rec["holdout_rows"])
    assert rec["heldout_after"] == pytest.approx(s_np, rel=1e-9)


# ------------------------------------------------ fit() wiring --------

def test_fit_tune_record_transient_hypers_and_off_path_identity():
    Y, _, _ = _panel(31)
    b = TPUBackend(dtype=jnp.float64)
    model = DynamicFactorModel(n_factors=K)
    base = fit(model, Y, max_iters=6, tol=0.0, backend=b)
    # A single forced non-default grid point: the winning hypers are
    # (2.0, 0.5) by construction, so the tuned fit's M-step provably ran
    # with them (params MUST differ from the untuned twin).
    tuned = fit(model, Y, max_iters=6, tol=0.0, backend=b,
                tune=TuneOptions(method="sweep", grid=((2.0, 0.5, 0.0),),
                                 em_iters=3))
    assert tuned.tune is not None and tuned.tune["method"] == "sweep"
    assert tuned.tune["q_scale"] == 2.0
    assert tuned.tune["dispatches"] == 2
    assert not np.allclose(np.asarray(tuned.params.Q),
                           np.asarray(base.params.Q))
    # Hypers are transient: the SAME backend serves untuned fits
    # bit-identically after the tuned one (seam restored on exit).
    assert b._tune_hypers is None
    again = fit(model, Y, max_iters=6, tol=0.0, backend=b)
    assert np.array_equal(np.asarray(base.logliks),
                          np.asarray(again.logliks))
    assert np.array_equal(np.asarray(base.params.Lam),
                          np.asarray(again.params.Lam))
    # tune=None is the same code path as omitting it entirely.
    none_fit = fit(model, Y, max_iters=6, tol=0.0, backend=b, tune=None)
    assert np.array_equal(np.asarray(base.logliks),
                          np.asarray(none_fit.logliks))
    assert none_fit.tune is None


def test_fit_tuned_beats_untuned_heldout_on_masked_panel():
    """The acceptance contract: at the same EM budget on a masked panel,
    the tuned fit's held-out one-step MSE strictly beats the untuned
    fit's (both scored by the f64 oracle on the standardized panel)."""
    Y, W, _ = _panel(32, 0.2)
    Ym = np.where(W > 0, Y, np.nan)
    model = DynamicFactorModel(n_factors=K, standardize=False)
    b = TPUBackend(dtype=jnp.float64)
    h = 8
    base = fit(model, Ym, max_iters=5, tol=0.0, backend=b)
    tuned = fit(model, Ym, max_iters=5, tol=0.0, backend=b,
                tune=TuneOptions(method="both", steps=8, em_iters=5,
                                 holdout_rows=h))
    s_base = heldout_mse_np(Ym, W, base.params, h)
    s_tuned = heldout_mse_np(Ym, W, tuned.params, h)
    assert s_tuned < s_base, (s_tuned, s_base, tuned.tune)


def test_fit_auto_conflicts_and_cpu_backend_warns():
    Y, _, _ = _panel(33)
    model = DynamicFactorModel(n_factors=K)
    with pytest.raises(ValueError, match="mutually exclusive"):
        fit(model, Y, auto=True, tune=TuneOptions())
    with pytest.warns(RuntimeWarning, match="no tuned-hyper seam"):
        res = fit(model, Y, max_iters=4, tol=0.0, backend=CPUBackend(),
                  tune=TuneOptions(method="grad", steps=3))
    assert res.tune is None


def test_fit_tune_composes_with_fused_and_telemetry(tmp_path):
    """Tuned fused fit keeps the one-program contract (nowcast present,
    no fallback event) and the trace proves the budget: ONE tune event,
    ONE barrier'd tune_grad dispatch for the whole search."""
    Y, _, _ = _panel(34)
    trace = tmp_path / "t.jsonl"
    res = fit(DynamicFactorModel(n_factors=K), Y, max_iters=9, tol=0.0,
              fused=True, backend=TPUBackend(dtype=jnp.float64),
              tune=TuneOptions(method="grad", steps=4, em_iters=3),
              telemetry=str(trace))
    assert res.tune is not None and res.nowcast is not None
    evs = [json.loads(l) for l in trace.read_text().splitlines()]
    tunes = [e for e in evs if e["kind"] == "tune"]
    assert len(tunes) == 1 and tunes[0]["dispatches"] == 1
    assert not any(e["kind"] == "fused_fallback" for e in evs)
    grad_disp = [e for e in evs if e["kind"] == "dispatch"
                 and e.get("program") == "tune_grad"]
    assert len(grad_disp) == 1 and grad_disp[0]["barrier"]


def test_fit_tune_composes_with_robust_guard():
    """The guard must not count generalized EM's plateau dip as a
    divergence (monotone=False reaches the guarded driver too)."""
    from dfm_tpu.robust import RobustPolicy
    Y, _, _ = _panel(35)
    res = fit(DynamicFactorModel(n_factors=K), Y, max_iters=8, tol=0.0,
              backend=TPUBackend(dtype=jnp.float64),
              tune=TuneOptions(method="grad", steps=3, em_iters=3),
              robust=RobustPolicy())
    assert res.tune is not None
    if res.health is not None:
        assert not [e for e in res.health.events
                    if e.kind == "divergence"], res.health.events


def test_resolve_and_options_validation():
    assert resolve_tune(None) is None and resolve_tune(False) is None
    assert resolve_tune(True) == TuneOptions()
    o = resolve_tune({"method": "sweep", "em_iters": 7})
    assert o.method == "sweep" and o.em_iters == 7
    with pytest.raises(ValueError):
        TuneOptions(method="bayes")
    with pytest.raises(TypeError):
        resolve_tune(42)


# ------------------------------------------------ monotone seam -------

def test_em_progress_tuned_rule_classifies_drop_as_plateau():
    lls = [-100.0, -90.0, -90.5]          # beyond-floor terminal drop
    assert em_progress(lls, 1e-6, 0.1, monotone=True) == "diverged"
    assert em_progress(lls, 1e-6, 0.1, monotone=False) == "converged"
    # Rising histories are unaffected by the flag.
    assert em_progress([-100.0, -90.0], 1e-6, 0.1,
                       monotone=False) == "continue"
    assert cfg_hypers(EMConfig()) is None
    assert cfg_hypers(EMConfig(q_scale=2.0)) == (2.0, 1.0, 0.0)


# ------------------------------------------------ maintenance retune --

def _small_fleet():
    rng = np.random.default_rng(77)
    Y_all, _ = dgp.simulate(dgp.dfm_params(8, 2, rng), 48, rng)
    Y0, stream = Y_all[:40], Y_all[40:]
    res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=3, tol=0.0,
              fused=True)
    fl = open_fleet([res], [Y0], tenants=["t0"], capacity=48,
                    max_update_rows=2, max_iters=2, tol=0.0)
    fl.submit("t0", stream[:2])
    fl.drain()
    return fl


def test_maintenance_retune_records_tune_trail():
    fl = _small_fleet()
    recs = run_maintenance(fl, ["t0"], policy=MaintenancePolicy(
        min_gain=float("-inf"), max_iters=8, retune=True,
        retune_steps=4, retune_em_iters=3))
    r = recs[0]
    assert r.action in ("swap", "retune") and r.swap_t is not None
    assert r.tune is not None and "best_params" not in r.tune
    for key in ("q_scale", "r_scale", "heldout_before", "heldout_after"):
        assert key in r.tune
    fl.close()


def test_maintenance_retune_swaps_winning_tuned_candidate(monkeypatch):
    """When the tuned candidate wins the held-out gate, the fleet serves
    exactly those params (params-only through swap_params) and the trail
    says action="retune" with the chosen hypers."""
    fl = _small_fleet()
    _, slot = fl._slot_of["t0"]
    Y_host = np.asarray(slot.Y_orig, np.float64)
    W = np.asarray(slot.W_orig, np.float64)
    Yz = slot.std.transform(Y_host) if slot.std is not None else Y_host
    # A tuned candidate distinguishable from the refit: a lone fit on
    # the current window.  Stands in for the tune search, and the gate's
    # scorer is biased to prefer it BY IDENTITY, so the decision seam
    # (gate -> retune swap -> trail) runs deterministically.
    strong = fit(DynamicFactorModel(n_factors=2, standardize=False), Yz,
                 max_iters=20, tol=0.0).params
    import dfm_tpu.estim.tune as tune_mod
    import dfm_tpu.fleet.maintenance as maint_mod

    def fake_tune(Y, mask, p0, cfg, opts=None, dtype=None,
                  return_params=False):
        return {"method": "grad", "q_scale": 1.3, "r_scale": 0.8,
                "lam_ridge": 0.0, "heldout_before": 1.0,
                "heldout_after": 0.5, "dispatches": 1,
                "best_params": strong}

    real_score = maint_mod.heldout_score

    def biased_score(Yz_, W_, params, h):
        return 0.0 if params is strong else real_score(Yz_, W_, params, h)

    monkeypatch.setattr(tune_mod, "tune_fit", fake_tune)
    monkeypatch.setattr(maint_mod, "heldout_score", biased_score)
    recs = run_maintenance(fl, ["t0"], policy=MaintenancePolicy(
        min_gain=float("-inf"), max_iters=1, retune=True))
    r = recs[0]
    assert r.action == "retune" and r.swap_t is not None
    assert r.tune["q_scale"] == 1.3 and "best_params" not in r.tune
    assert r.score_after == 0.0
    p_now = fl._slot_params_np(*fl._slot_of["t0"])
    assert np.allclose(np.asarray(p_now.Lam), np.asarray(strong.Lam),
                       rtol=1e-6, atol=1e-8)
    fl.close()


def test_maintenance_retune_off_is_unchanged():
    fl = _small_fleet()
    recs = run_maintenance(fl, ["t0"], policy=MaintenancePolicy(
        min_gain=float("-inf"), max_iters=8))
    assert recs[0].tune is None and recs[0].action == "swap"
    fl.close()

"""AST audit: every emitted event kind is registered in EVENT_KINDS.

``summarize()``, ``LivePlane.record_event`` and ``to_chrome`` all route on
the ``kind`` string of an event — a typo'd kind is an event NOTHING will
ever aggregate, and it fails silently (the tracer happily records it, the
report happily ignores it).  This test closes the schema: it walks every
module under ``dfm_tpu/`` with ``ast`` and collects every event-kind
literal from the two emission idioms in the codebase:

  * ``tracer.emit("<kind>", ...)`` — first positional string constant of
    any ``*.emit(...)`` call;
  * ``live_observe({"t": ..., "kind": "<kind>", ...})`` — dict literals
    with a constant ``"kind"`` key (the untraced live-plane mirror).  The
    ``"t"`` key is required alongside: RunRecord dicts in ``obs/regress``
    also carry a ``"kind"`` field (``"trace"``/``"profile"`` — run kinds,
    not event kinds) but never a ``"t"`` timestamp.

Both directions are asserted: no module emits a kind missing from
``EVENT_KINDS`` (unroutable event), and no ``EVENT_KINDS`` entry is dead
(registered but never emitted anywhere — a schema entry that rotted).
"""

from __future__ import annotations

import ast
import pathlib

import dfm_tpu
from dfm_tpu.obs.trace import EVENT_KINDS

PKG_ROOT = pathlib.Path(dfm_tpu.__file__).parent


def _is_str_const(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def _emitted_kinds():
    """(kind, location) pairs for every event-kind literal in the package."""
    out = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        rel = str(path.relative_to(PKG_ROOT.parent))
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            # tracer.emit("<kind>", ...) — also catches self.emit / tr.emit.
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and node.args and _is_str_const(node.args[0])):
                out.append((node.args[0].value, f"{rel}:{node.lineno}"))
            # {"t": ..., "kind": "<kind>", ...} — live-plane event payloads.
            # Requiring the "t" key alongside excludes RunRecord dicts
            # (obs/regress uses "kind" for run kinds, never with "t").
            elif isinstance(node, ast.Dict):
                keys = [k.value for k in node.keys if _is_str_const(k)]
                if "kind" not in keys or "t" not in keys:
                    continue
                for k, v in zip(node.keys, node.values):
                    if _is_str_const(k) and k.value == "kind":
                        if _is_str_const(v):
                            out.append((v.value, f"{rel}:{node.lineno}"))
    return out


def test_every_emitted_kind_is_registered():
    """No emission site uses a kind outside the closed EVENT_KINDS schema."""
    unregistered = [(k, loc) for k, loc in _emitted_kinds()
                    if k not in EVENT_KINDS]
    assert not unregistered, (
        "event kinds emitted but missing from obs.trace.EVENT_KINDS "
        "(the report/live plane will silently drop them): "
        f"{unregistered}")


def test_no_dead_registry_entries():
    """Every registered kind is emitted somewhere — no rotted entries."""
    seen = {k for k, _ in _emitted_kinds()}
    dead = EVENT_KINDS - seen
    assert not dead, (
        f"EVENT_KINDS entries never emitted anywhere in dfm_tpu/: {dead}")


def test_registry_is_frozen_inventory():
    """The schema itself — additions must be deliberate (update this test,
    obs/metrics.record_event, and obs/report together)."""
    assert EVENT_KINDS == frozenset({
        "fit", "dispatch", "transfer", "chunk", "freeze", "health", "cost",
        "span", "query", "tick", "tenant", "page", "daemon", "maintenance",
        "compile_cache", "advice", "panel_reupload", "fused_fallback",
        "request", "tune",
    })

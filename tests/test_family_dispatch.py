"""api.fit family dispatch: the reference's single fit(model; backend=...)
seam (BASELINE.json:5) covers every model family via its spec type."""

import numpy as np
import pytest

from dfm_tpu.api import fit
from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
from dfm_tpu.models.sv import SVSpec, sv_fit
from dfm_tpu.models.tv_loadings import TVLSpec, tvl_fit
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def mf_data():
    rng = np.random.default_rng(41)
    Y, mask, _, _ = dgp.simulate_mixed_freq(24, 6, 60, 2, rng)
    return Y, mask


def test_fit_dispatches_mixed_freq(mf_data):
    Y, mask = mf_data
    spec = MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2)
    r_api = fit(spec, Y, mask=mask, max_iters=4, tol=0.0)
    r_dir = mf_fit(Y, spec, mask=mask, max_iters=4, tol=0.0)
    np.testing.assert_allclose(r_api.logliks, r_dir.logliks, rtol=1e-12)
    assert hasattr(r_api, "nowcast")


def test_fit_dispatches_mixed_freq_sharded(mf_data):
    Y, mask = mf_data
    spec = MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2)
    r_sh = fit(spec, Y, mask=mask, backend="sharded", max_iters=4, tol=0.0)
    r_1d = fit(spec, Y, mask=mask, max_iters=4, tol=0.0)
    # psum reduction order differs from the single-device sum: fp-level
    # tolerance, same bound as the sharded-MF equivalence tests.
    np.testing.assert_allclose(r_sh.logliks, r_1d.logliks, rtol=1e-6)


def test_fit_dispatches_tvl_and_keeps_spec_defaults():
    rng = np.random.default_rng(42)
    Y = dgp.simulate_tv_loadings(40, 50, 2, rng)[0]
    spec = TVLSpec(n_factors=2, n_rounds=3, tol=0.0)
    r_api = fit(spec, Y)                      # no max_iters: spec's 3 rounds
    r_dir = tvl_fit(Y, spec)
    assert len(r_api.logliks) == 3
    np.testing.assert_allclose(r_api.logliks, r_dir.logliks, rtol=1e-12)
    # Explicit max_iters override: identical to running the family driver
    # with the re-specced round budget (the fused driver may still STOP
    # early on an alternation-noise dip — both paths must agree on that).
    import dataclasses
    r5_api = fit(spec, Y, max_iters=5, tol=0.0)
    r5_dir = tvl_fit(Y, dataclasses.replace(spec, n_rounds=5, tol=0.0))
    assert r5_api.spec.n_rounds == 5
    np.testing.assert_allclose(r5_api.logliks, r5_dir.logliks, rtol=1e-12)


def test_fit_dispatches_sv_and_validates():
    from dfm_tpu.api import forecast
    from dfm_tpu.models.sv import sv_forecast
    rng = np.random.default_rng(43)
    Y = dgp.simulate_sv(30, 40, 2, rng)[0]
    spec = SVSpec(n_factors=2, n_particles=32)
    r_api = fit(spec, Y, max_iters=2)
    r_dir = sv_fit(Y, spec, sv_iters=2)
    assert np.isfinite(r_api.loglik)
    np.testing.assert_allclose(r_api.loglik, r_dir.loglik, rtol=1e-10)
    # SV forecast: finite conditional means in DATA units + vol bands.
    y_f, f_f, vol_f = sv_forecast(r_api, 6)
    assert y_f.shape == (6, 30) and vol_f.shape == (6, 2)
    assert np.isfinite(y_f).all() and (vol_f > 0).all()
    y2, f2 = forecast(r_api, 6)
    np.testing.assert_array_equal(y2, y_f)
    with pytest.raises(ValueError, match="missing data"):
        fit(spec, Y, mask=np.ones_like(Y))
    with pytest.raises(ValueError, match="cannot run"):
        fit(spec, Y, backend="cpu")
    with pytest.raises(ValueError, match="checkpoint"):
        fit(spec, Y, checkpoint_path="x.npz")
    with pytest.raises(ValueError, match="callback"):
        fit(spec, Y, callback=lambda *a: None)
    # Wrong-family warm starts are rejected at the seam, not deep inside.
    mf = MixedFreqSpec(n_monthly=20, n_quarterly=10, n_factors=2)
    with pytest.raises(TypeError, match="MFParams"):
        fit(mf, Y, init=object())

"""Information-form filter == dense filter (SURVEY.md section 7.2 item 2).

The Woodbury/determinant-lemma log-likelihood is the easy-to-get-wrong piece;
these tests pin it against the dense CPU oracle, with and without masks.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.ssm.info_filter import (info_filter, obs_stats, info_scan,
                                     loglik_terms_local, loglik_from_terms)
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(37, 4, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    return p, Y, rng


def test_info_matches_dense_loglik_and_moments(setup):
    p, Y, _ = setup
    kf_np = cpu_ref.kalman_filter(Y, p)
    kf = info_filter(jnp.asarray(Y), JP.from_numpy(p, jnp.float64))
    assert abs(float(kf.loglik) - kf_np.loglik) < 1e-6 * abs(kf_np.loglik)
    np.testing.assert_allclose(np.asarray(kf.x_filt), kf_np.x_filt, atol=1e-8)
    np.testing.assert_allclose(np.asarray(kf.P_filt), kf_np.P_filt, atol=1e-8)
    np.testing.assert_allclose(np.asarray(kf.x_pred), kf_np.x_pred, atol=1e-8)


def test_info_matches_dense_masked(setup):
    p, Y, _ = setup
    rng = np.random.default_rng(8)
    W = dgp.random_mask(*Y.shape, rng, frac_missing=0.3)
    W[5] = 0.0  # an entirely-missing time step
    kf_np = cpu_ref.kalman_filter(Y, p, mask=W)
    kf = info_filter(jnp.asarray(Y), JP.from_numpy(p, jnp.float64),
                     mask=jnp.asarray(W))
    assert abs(float(kf.loglik) - kf_np.loglik) < 1e-6 * abs(kf_np.loglik)
    np.testing.assert_allclose(np.asarray(kf.x_filt), kf_np.x_filt, atol=1e-8)


def test_info_accepts_nan_at_masked(setup):
    p, Y, _ = setup
    rng = np.random.default_rng(9)
    W = dgp.random_mask(*Y.shape, rng, frac_missing=0.2)
    Ynan = np.where(W > 0, Y, np.nan)
    kf_a = info_filter(jnp.asarray(Y), JP.from_numpy(p, jnp.float64),
                       mask=jnp.asarray(W))
    kf_b = info_filter(jnp.asarray(Ynan), JP.from_numpy(p, jnp.float64),
                       mask=jnp.asarray(W))
    assert np.isfinite(float(kf_b.loglik))
    assert abs(float(kf_a.loglik) - float(kf_b.loglik)) < 1e-10


def test_smoother_on_info_filter_matches_dense(setup):
    p, Y, _ = setup
    pj = JP.from_numpy(p, jnp.float64)
    kf = info_filter(jnp.asarray(Y), pj)
    sm = rts_smoother(kf, pj)
    kf_np = cpu_ref.kalman_filter(Y, p)
    sm_np = cpu_ref.rts_smoother(kf_np, p)
    np.testing.assert_allclose(np.asarray(sm.x_sm), sm_np.x_sm, atol=1e-8)
    np.testing.assert_allclose(np.asarray(sm.P_lag), sm_np.P_lag, atol=1e-8)


def test_stats_additivity_over_series_blocks(setup):
    """obs_stats over the whole panel == sum of obs_stats over series blocks —
    the algebraic fact that makes the psum sharding correct."""
    p, Y, _ = setup
    Yj = jnp.asarray(Y)
    Lam = jnp.asarray(p.Lam)
    R = jnp.asarray(p.R)
    full = obs_stats(Yj, Lam, R)
    blocks = [obs_stats(Yj[:, s], Lam[s], R[s])
              for s in (slice(0, 10), slice(10, 25), slice(25, 37))]
    for i, name in enumerate(full._fields):
        summed = sum(np.asarray(b[i]) for b in blocks)
        np.testing.assert_allclose(np.asarray(full[i]), summed, atol=1e-9,
                                   err_msg=name)
    # The loglik residual terms are additive over blocks the same way
    # (the psum'd payload of the sharded filter).
    summed_stats = type(full)(*(jnp.asarray(sum(np.asarray(b[i])
                                                for b in blocks))
                                for i in range(len(full))))
    xp, Pp, xf, Pf, logdetG = info_scan(
        summed_stats, jnp.asarray(p.A), jnp.asarray(p.Q),
        jnp.asarray(p.mu0), jnp.asarray(p.P0))
    qs, Us = zip(*(loglik_terms_local(Yj[:, s], Lam[s], R[s], xp, None)
                   for s in (slice(0, 10), slice(10, 25), slice(25, 37))))
    ll_blocks = loglik_from_terms(summed_stats, logdetG, Pf,
                                  sum(qs), sum(Us))
    kf_full = info_filter(Yj, JP.from_numpy(p, jnp.float64))
    assert abs(float(ll_blocks) - float(kf_full.loglik)) < 1e-8


def test_loglik_eval_precise_matches_oracle():
    """Reporting-grade evaluator (f64 on device) vs the NumPy f64 oracle."""
    from dfm_tpu.ssm.info_filter import loglik_eval
    rng = np.random.default_rng(21)
    p = dgp.dfm_params(64, 3, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    ref = cpu_ref.kalman_filter_info(Y, p).loglik
    # accepts numpy params
    ll = loglik_eval(Y, p)
    assert abs(ll - ref) < 1e-9 * abs(ref)
    # accepts jax params + mask
    W = dgp.random_mask(80, 64, rng, 0.2)
    ref_m = cpu_ref.kalman_filter_info(Y, p, mask=W).loglik
    pj = JP.from_numpy(p, jnp.float64)
    ll_m = loglik_eval(jnp.asarray(Y), pj, mask=W)
    assert abs(ll_m - ref_m) < 1e-9 * abs(ref_m)

"""Test configuration: force an 8-device fake CPU mesh before JAX imports.

SURVEY.md section 4.2.4: only one physical TPU exists in this environment, so
distributed tests run on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8``.  These env vars must be set
before the first ``import jax`` anywhere in the test process, hence this
conftest (pytest imports it before collecting test modules).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Tests compare against float64 NumPy goldens; enable x64 on the CPU backend.
os.environ.setdefault("JAX_ENABLE_X64", "1")

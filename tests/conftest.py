"""Test configuration: force an 8-device fake CPU mesh for all tests.

SURVEY.md section 4.2.4: only one physical TPU exists in this environment, so
distributed tests run on a virtual 8-device CPU mesh.  Two wrinkles specific
to this machine:

- ``jax`` is already imported at interpreter startup (a sitecustomize hook
  registers the ``axon`` TPU PJRT plugin), so setting ``JAX_PLATFORMS`` via
  ``os.environ`` here is too late — we must go through ``jax.config.update``.
- ``XLA_FLAGS`` is read by the XLA client at backend *creation*, which has not
  happened yet at conftest time, so the env route still works for the device
  count.

Tests compare against float64 NumPy goldens, hence x64; float32/TPU behavior
is covered by dedicated tolerance tests and the on-device bench.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

"""Test configuration: force an 8-device fake CPU mesh for all tests.

SURVEY.md section 4.2.4: only one physical TPU exists in this environment, so
distributed tests run on a virtual 8-device CPU mesh.  Two wrinkles specific
to this machine:

- ``jax`` is already imported at interpreter startup (a sitecustomize hook
  registers the ``axon`` TPU PJRT plugin), so setting ``JAX_PLATFORMS`` via
  ``os.environ`` here is too late — we must go through ``jax.config.update``.
- ``XLA_FLAGS`` is read by the XLA client at backend *creation*, which has not
  happened yet at conftest time, so the env route still works for the device
  count.

Tests compare against float64 NumPy goldens, hence x64; float32/TPU behavior
is covered by dedicated tolerance tests and the on-device bench.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-program state between test modules.

    With the whole suite in one process the XLA CPU compiler segfaults
    compiling ``tvl_round_scan`` near the END of the run (reproducibly at
    test_tvl in full-suite order; never in any half-suite prefix or
    standalone — an accumulated-state compiler bug, 2026-07).  Clearing
    JAX's executable caches between modules bounds that state; programs
    shared across modules recompile, which costs far less than the
    headroom it buys.
    """
    yield
    jax.clear_caches()

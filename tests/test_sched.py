"""Multi-tenant scheduler equivalence + bucket-packing properties.

The operative contract (ISSUE 8): ``fit_jobs`` over heterogeneous
(N, T, k) jobs must reproduce each job's lone ``fit()`` — loglik traces,
params, factors, convergence, health — while running ONE fused batched
program per shape bucket.  Verified here on the fake 8-device CPU mesh
(conftest), x64-exact and f32-tolerance variants, on both the
single-device and sharded scheduler backends; plus the jax-free planner
properties (every job in exactly one bucket, dominating dims,
determinism, degenerate mixes), per-axis padding-seam inertness through
the public helpers, NaN-poisoned tenant isolation, and the
``obs.advise --jobs`` layout ranking.
"""

import dataclasses

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, Job, fit, fit_jobs
from dfm_tpu.api import TPUBackend
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.batched import (pad_panel_to_n, pad_panel_to_t,
                                   pad_params_to_k, pad_params_to_n,
                                   slice_params_to_k, slice_params_to_n)
from dfm_tpu.obs.advise import advise_jobs
from dfm_tpu.sched import plan_buckets
from dfm_tpu.utils import dgp


def _panel(T, N, k, seed=0):
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    return Y


def _jobs(shapes, seed=0, **kw):
    return [Job(Y=_panel(T, N, k, seed=seed + i),
                model=DynamicFactorModel(n_factors=k), tenant=f"t{i}",
                **kw)
            for i, (T, N, k) in enumerate(shapes)]


def _ref(job, dtype="float64"):
    """The lone-fit oracle: same engine (info filter) as the scheduler."""
    return fit(job.model, job.Y,
               backend=TPUBackend(dtype=dtype, filter="info"),
               max_iters=job.max_iters, tol=job.tol)


def _assert_job_matches(r, ref, rtol=1e-9, atol=1e-7, p_rtol=1e-7):
    assert len(r.fit.logliks) == len(ref.logliks)
    np.testing.assert_allclose(r.fit.logliks, ref.logliks,
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(r.fit.factors, ref.factors,
                               rtol=p_rtol, atol=1e-8)
    np.testing.assert_allclose(np.asarray(r.fit.params.Lam),
                               np.asarray(ref.params.Lam),
                               rtol=p_rtol, atol=1e-8)
    assert r.fit.converged == ref.converged
    assert r.fit.health.ok == ref.health.ok


# ---------------------------------------------------------------------------
# Padding helpers (the public N/T seams the scheduler is built on)
# ---------------------------------------------------------------------------

def test_pad_panel_helpers_shapes_and_zeros():
    Y = _panel(20, 6, 2, seed=0)
    Yn = pad_panel_to_n(Y, 9)
    assert Yn.shape == (20, 9)
    np.testing.assert_array_equal(Yn[:, :6], Y)
    assert np.all(Yn[:, 6:] == 0.0)
    Yt = pad_panel_to_t(Y, 25)
    assert Yt.shape == (25, 6)
    np.testing.assert_array_equal(Yt[:20], Y)
    assert np.all(Yt[20:] == 0.0)
    # No-op at the target size; refuse to "pad" downward.
    assert pad_panel_to_n(Y, 6).shape == Y.shape
    assert pad_panel_to_t(Y, 20).shape == Y.shape
    with pytest.raises(ValueError):
        pad_panel_to_n(Y, 5)
    with pytest.raises(ValueError):
        pad_panel_to_t(Y, 19)


def test_pad_params_slice_roundtrip():
    Y = _panel(40, 8, 2, seed=1)
    p = cpu_ref.pca_init(Y, 2)
    for pad, sl, to in ((pad_params_to_n, slice_params_to_n, 12),
                        (pad_params_to_k, slice_params_to_k, 4)):
        q = sl(pad(p, to), p.Lam.shape[0] if pad is pad_params_to_n else 2)
        for f in ("Lam", "A", "Q", "R", "mu0", "P0"):
            np.testing.assert_array_equal(np.asarray(getattr(q, f)),
                                          np.asarray(getattr(p, f)))


@pytest.mark.parametrize("other_shape", [(64, 12, 2),   # pads T only
                                         (50, 16, 2),   # pads N only
                                         (50, 12, 3)],  # pads k only
                         ids=["T", "N", "k"])
def test_padding_inert_per_axis(other_shape):
    """Force the (50, 12, 2) job into a bucket that pads exactly one axis
    (max_buckets=1 with a dominating partner); its result must still be
    the lone fit, axis by axis — the inertness proofs in estim.batched
    composed through the scheduler."""
    base = Job(Y=_panel(50, 12, 2, seed=7),
               model=DynamicFactorModel(n_factors=2), tenant="small",
               max_iters=40, tol=1e-6)
    T, N, k = other_shape
    big = Job(Y=_panel(T, N, k, seed=8),
              model=DynamicFactorModel(n_factors=k), tenant="big",
              max_iters=40, tol=1e-6)
    stats = {}
    res = fit_jobs([base, big], max_buckets=1, dtype="float64",
                   stats=stats)
    assert stats["n_buckets"] == 1
    assert stats["bucket_dims"] == [(max(50, T), max(12, N), max(2, k))]
    _assert_job_matches(res[0], _ref(base))
    _assert_job_matches(res[1], _ref(big))
    assert res[0].shape == (50, 12, 2)
    assert res[0].fit.params.Lam.shape == (12, 2)
    assert res[0].fit.factors.shape[1] == 2


# ---------------------------------------------------------------------------
# Bucket planner properties (jax-free)
# ---------------------------------------------------------------------------

_MIXES = [
    [(64, 20, 2), (40, 14, 1), (96, 26, 2), (64, 20, 2)],
    [(30, 8, 1)] * 5,                                   # all same shape
    [(20, 6, 1), (400, 80, 6)],                         # pathological spread
    [(50, 10, 2)],                                      # single job
    [(64, 20, 2), (65, 20, 2), (66, 20, 2), (200, 40, 4)],
]


@pytest.mark.parametrize("shapes", _MIXES)
@pytest.mark.parametrize("max_buckets", [1, 2, 3])
def test_plan_partitions_jobs_exactly_once(shapes, max_buckets):
    its = [10 + 3 * i for i in range(len(shapes))]
    plan = plan_buckets(shapes, its, max_buckets=max_buckets)
    assert 1 <= len(plan.buckets) <= max_buckets
    covered = sorted(j for b in plan.buckets for j in b.jobs)
    assert covered == list(range(len(shapes)))          # exactly once
    for bi, b in enumerate(plan.buckets):
        for j in b.jobs:
            assert plan.bucket_of[j] == bi
            assert all(d >= s for d, s in zip(b.dims, shapes[j]))
        assert b.cap == max(its[j] for j in b.jobs)
    assert 0.0 <= plan.pad_waste_frac < 1.0
    assert all(0.0 <= w < 1.0 for w in plan.job_pad_waste)


@pytest.mark.parametrize("shapes", _MIXES)
def test_plan_deterministic(shapes):
    a = plan_buckets(shapes, max_buckets=3)
    b = plan_buckets(shapes, max_buckets=3)
    assert a.buckets == b.buckets
    assert a.bucket_of == b.bucket_of
    assert a.predicted_wall_s == b.predicted_wall_s


def test_plan_degenerate_mixes():
    # Same-shape jobs always collapse into one zero-waste bucket.
    plan = plan_buckets([(30, 8, 1)] * 4, max_buckets=3)
    assert len(plan.buckets) == 1 and plan.pad_waste_frac == 0.0
    # A single job is its own exact bucket.
    plan = plan_buckets([(50, 10, 2)], [25], max_buckets=3)
    assert plan.buckets[0].dims == (50, 10, 2)
    assert plan.buckets[0].cap == 25
    # Pathological spread at max_buckets>=2 refuses to merge: padding the
    # tiny job into the huge bucket costs more than a second executable.
    plan = plan_buckets([(20, 6, 1), (400, 80, 6)], max_buckets=2)
    assert len(plan.buckets) == 2 and plan.pad_waste_frac == 0.0
    # Empty input, empty plan.
    assert plan_buckets([]).buckets == []
    with pytest.raises(ValueError):
        plan_buckets([(30, 8, 1)], [0])
    with pytest.raises(ValueError):
        plan_buckets([(30, 8, 1)], [5, 5])


# ---------------------------------------------------------------------------
# Scheduler end-to-end equivalence
# ---------------------------------------------------------------------------

_PARITY_SHAPES = [(60, 20, 2), (44, 14, 1), (80, 26, 2), (60, 20, 2),
                  (30, 10, 1)]


@pytest.mark.parametrize("backend", ["tpu", "sharded"])
def test_fit_jobs_matches_lone_fits_x64(backend):
    jobs = _jobs(_PARITY_SHAPES, seed=100, max_iters=40, tol=1e-6)
    stats = {}
    res = fit_jobs(jobs, backend=backend, max_buckets=3, dtype="float64",
                   stats=stats)
    assert stats["n_jobs"] == len(jobs)
    assert 1 <= stats["n_buckets"] <= 3
    for i, (r, job) in enumerate(zip(res, jobs)):
        _assert_job_matches(r, _ref(job))
        assert r.tenant == f"t{i}"
        assert r.fit.backend == f"sched:{backend}"
        assert r.queue_wait_s >= 0.0 and r.compute_s > 0.0
        assert 0.0 <= r.pad_waste_frac < 1.0


def test_fit_jobs_f32_fixed_iters():
    """f32 variant at tol=0 (fixed iteration count — the convergence
    decision is f32-noise-sensitive, the trajectory is not)."""
    jobs = _jobs([(60, 12, 2), (40, 9, 1), (60, 12, 2)], seed=200,
                 max_iters=10, tol=0.0)
    res = fit_jobs(jobs, max_buckets=2, dtype=np.float32)
    for r, job in zip(res, jobs):
        ref = _ref(job, dtype=np.float32)
        assert len(r.fit.logliks) == len(ref.logliks) == 10
        # Same math, different reduction order: f32 rounding only.
        np.testing.assert_allclose(r.fit.logliks, ref.logliks,
                                   rtol=2e-3, atol=0.5)
        np.testing.assert_allclose(np.asarray(r.fit.params.Lam),
                                   np.asarray(ref.params.Lam),
                                   rtol=5e-3, atol=5e-3)


def test_per_tenant_iteration_caps():
    """Tenants sharing one bucket keep their OWN budgets: at tol=0 each
    runs exactly its max_iters, frozen in-carry past its cap."""
    shapes = [(50, 10, 2)] * 3
    jobs = [Job(Y=_panel(T, N, k, seed=300 + i),
                model=DynamicFactorModel(n_factors=k), tenant=f"t{i}",
                max_iters=m, tol=0.0)
            for i, ((T, N, k), m) in enumerate(zip(shapes, [5, 12, 9]))]
    stats = {}
    res = fit_jobs(jobs, max_buckets=1, dtype="float64", stats=stats)
    assert stats["n_buckets"] == 1
    assert [len(r.fit.logliks) for r in res] == [5, 12, 9]
    for r, job in zip(res, jobs):
        _assert_job_matches(r, _ref(job))


def test_nan_poisoned_tenant_is_isolated():
    """A tenant whose init is NaN-poisoned diverges ALONE: it runs to its
    cap unconverged while its bucket-mates stay bit-identical to their
    lone fits (independent batch lanes — the multi-tenant safety story)."""
    jobs = _jobs([(50, 12, 2)] * 3, seed=400, max_iters=15, tol=1e-6)
    bad_init = cpu_ref.pca_init(
        np.asarray(jobs[1].Y) / np.asarray(jobs[1].Y).std(axis=0), 2)
    bad_init = dataclasses.replace(
        bad_init, Lam=np.full_like(bad_init.Lam, np.nan))
    jobs[1] = Job(Y=jobs[1].Y, model=jobs[1].model, tenant="poisoned",
                  init=bad_init, max_iters=15, tol=1e-6)
    res = fit_jobs(jobs, max_buckets=1, dtype="float64")
    assert not res[1].fit.converged
    assert len(res[1].fit.logliks) == 15          # ran to cap
    assert not np.isfinite(np.asarray(res[1].fit.logliks)).all()
    for i in (0, 2):                              # mates unperturbed
        _assert_job_matches(res[i], _ref(jobs[i]))


def test_fit_jobs_validation_and_empty():
    assert fit_jobs([]) == []
    with pytest.raises(TypeError):
        fit_jobs([object()])
    Y = _panel(30, 8, 1, seed=5)
    Y[3, 2] = np.nan
    with pytest.raises(ValueError, match="fully-observed"):
        fit_jobs([Job(Y=Y, model=DynamicFactorModel(n_factors=1))])
    with pytest.raises(ValueError, match="backend"):
        fit_jobs(_jobs([(30, 8, 1)], seed=6), backend="gpu")


def test_tenant_telemetry_and_fairness_summary():
    jobs = _jobs([(50, 12, 2), (40, 9, 1)], seed=500, max_iters=8,
                 tol=0.0)
    res = fit_jobs(jobs, max_buckets=2, dtype="float64", telemetry=True)
    s = res[0].telemetry
    assert s is not None and s is res[1].telemetry is res[0].fit.telemetry
    tenants = {e["tenant"]: e for e in s["tenants"]}
    assert set(tenants) == {"t0", "t1"}
    for i, job in enumerate(jobs):
        e = tenants[f"t{i}"]
        assert (e["T"], e["N"]) == job.Y.shape
        assert e["n_iters"] == 8 and e["queue_wait_s"] >= 0.0
        assert e["bucket_T"] >= e["T"] and e["bucket_N"] >= e["N"]
    fair = s["tenant_fairness"]
    assert fair["n_tenants"] == 2
    assert 1 <= fair["n_buckets"] <= 2
    assert 0.0 <= fair["pad_waste_frac_mean"] < 1.0


# ---------------------------------------------------------------------------
# Layout advisor (obs.advise --jobs)
# ---------------------------------------------------------------------------

def test_advise_jobs_ranks_layouts_deterministically(tmp_path):
    shapes = [(20, 64, 2), (14, 40, 1), (26, 96, 2), (20, 64, 2)]  # N,T,k
    a = advise_jobs(shapes, max_iters=20, runs=str(tmp_path))
    b = advise_jobs(shapes, max_iters=20, runs=str(tmp_path))
    assert a == b                                   # fully deterministic
    assert a["calibrated"] is False                 # empty registry
    walls = [l["predicted_wall_s"] for l in a["layouts"]]
    assert walls == sorted(walls)
    assert [l["rank"] for l in a["layouts"]] == list(
        range(1, len(a["layouts"]) + 1))
    for l in a["layouts"]:
        covered = sorted(j for bk in l["buckets"] for j in bk["jobs"])
        assert covered == list(range(len(shapes)))
        # Engine-annotated layouts: the evidence-gated choice is "info"
        # on an uncalibrated registry (no engine was ever profiled).
        assert all(bk["filter"] == "info" for bk in l["buckets"])

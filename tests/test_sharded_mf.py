"""Sharded mixed-frequency EM == single-device mf_fit on the fake mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
from dfm_tpu.parallel.mesh import make_mesh
from dfm_tpu.parallel.sharded_mf import sharded_mf_fit
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def mf_panel():
    rng = np.random.default_rng(91)
    Y, mask, F, truth = dgp.simulate_mixed_freq(
        n_monthly=30, n_quarterly=8, T=100, k=2, rng=rng)
    return Y, mask


def test_sharded_mf_matches_single_device(mf_panel):
    Y, mask = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    r1 = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0)
    r8 = sharded_mf_fit(Y, spec, mask=mask, mesh=make_mesh(8),
                        max_iters=6, tol=0.0, dtype=jnp.float64)
    np.testing.assert_allclose(r8.logliks, r1.logliks, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(r8.params.Lam_m),
                               np.asarray(r1.params.Lam_m), atol=1e-6)
    np.testing.assert_allclose(np.asarray(r8.params.Lam_q),
                               np.asarray(r1.params.Lam_q), atol=1e-6)
    np.testing.assert_allclose(r8.factors, r1.factors, atol=1e-6)
    np.testing.assert_allclose(r8.nowcast, r1.nowcast, atol=1e-5)


def test_sharded_mf_padding_path(mf_panel):
    """5-shard mesh forces padding of both blocks (30->35, 8->10)."""
    Y, mask = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    r1 = mf_fit(Y, spec, mask=mask, max_iters=4, tol=0.0)
    r5 = sharded_mf_fit(Y, spec, mask=mask, mesh=make_mesh(5),
                        max_iters=4, tol=0.0, dtype=jnp.float64)
    np.testing.assert_allclose(r5.logliks, r1.logliks, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(r5.params.Lam_q),
                               np.asarray(r1.params.Lam_q), atol=1e-6)


def test_sharded_mf_f32_tolerance(mf_panel):
    """TPU-dtype (f32) sharded run vs the f64 oracle: the round-trip must
    stay inside the f32 loglik noise floor (VERDICT r2 item 9 — the sharded
    MF path previously had only x64 equivalence evidence)."""
    Y, mask = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    r64 = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0)
    r32 = sharded_mf_fit(Y, spec, mask=mask, mesh=make_mesh(8),
                         max_iters=6, tol=0.0, dtype=jnp.float32)
    # loglik: absolute tolerance at the f32 noise floor scale (~eps * n_obs)
    n_obs = float(np.asarray(mask).sum())
    floor = 200 * np.finfo(np.float32).eps * n_obs
    np.testing.assert_allclose(r32.logliks, r64.logliks, atol=floor,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(r32.params.Lam_m),
                               np.asarray(r64.params.Lam_m), atol=5e-3)
    np.testing.assert_allclose(r32.factors, r64.factors, atol=5e-3)


def test_sharded_mf_fused_chunk_matches_unfused(mf_panel):
    """fused_chunk>1 == fused_chunk=1 on the fake mesh (x64 exact): guards
    the chunked scan_fn plumbing independently of the single-device
    comparison (both defaults are fused — VERDICT r5 review)."""
    Y, mask = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    r1 = sharded_mf_fit(Y, spec, mask=mask, mesh=make_mesh(8),
                        dtype=jnp.float64, max_iters=7, tol=0.0,
                        fused_chunk=1)
    r3 = sharded_mf_fit(Y, spec, mask=mask, mesh=make_mesh(8),
                        dtype=jnp.float64, max_iters=7, tol=0.0,
                        fused_chunk=3)
    np.testing.assert_allclose(r3.logliks, r1.logliks, rtol=1e-12)
    np.testing.assert_allclose(r3.nowcast, r1.nowcast, atol=1e-10)

"""Source-level invariant: every standalone fit driver forces true-f32
matmuls (``jax.default_matmul_precision("highest")``).

XLA's default f32 matmul precision rounds MXU inputs to bf16, which costs
~1e-4 relative log-likelihood — far outside the 1e-5 oracle contract
(docs/PERF.md item 2) and enough to fake EM divergences.  Each driver
that owns its device dispatches must therefore open the precision context
itself; a handful of functions intentionally DELEGATE that duty and are
allowlisted below with the reason.  This test walks the AST of every
``dfm_tpu`` source file so a new driver added without the guard (or a
refactor that drops one) fails CI instead of silently shipping bf16
logliks.
"""

import ast
import pathlib

import dfm_tpu

PKG_ROOT = pathlib.Path(dfm_tpu.__file__).parent

# Functions that are fit drivers by name but must NOT (or need not) carry
# their own precision context.  Frozen: extending it requires justifying a
# new delegation here.
ALLOWLIST = {
    # Delegates to the backend's _precision_ctx (the user-facing knob
    # TPUBackend(matmul_precision=...) lives there).
    "dfm_tpu.api.fit",
    # Pure dispatcher onto the family drivers below.
    "dfm_tpu.api._family_fit",
    # Always invoked under the calling backend's context; its own ctx
    # would be innermost and silently OVERRIDE
    # TPUBackend(matmul_precision="default").
    "dfm_tpu.estim.em.em_fit",
    # NumPy f64 oracle: no XLA, no MXU, nothing to guard.
    "dfm_tpu.backends.cpu_ref.em_fit",
    # EM pre-fit runs through api.fit; every particle-filter dispatch runs
    # through sv_filter / sharded_sv_filter (checked in MUST_GUARD).
    "dfm_tpu.models.sv.sv_fit",
    # The fused while-loop driver is always invoked under the calling
    # backend's _precision_ctx (api.TPUBackend._run_fused); its own ctx
    # would silently override TPUBackend(matmul_precision="default").
    "dfm_tpu.estim.fused.run_fused",
}

# Compute kernels the allowlist reasons lean on: these MUST contain the
# context so the delegation story above stays true.
MUST_GUARD_EXTRA = {
    "dfm_tpu.models.sv.sv_filter",
    "dfm_tpu.parallel.sharded_sv.sharded_sv_filter",
}


def _qualname(path: pathlib.Path, fn: str) -> str:
    rel = path.relative_to(PKG_ROOT.parent).with_suffix("")
    return ".".join(rel.parts) + "." + fn


def _signature_defaults(fn: ast.FunctionDef) -> dict:
    """arg name -> default constant value (positional + kw-only)."""
    out = {}
    args = fn.args
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(d, ast.Constant):
            out[a.arg] = d.value
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant):
            out[a.arg] = d.value
    return out


def _has_precision_ctx(fn: ast.FunctionDef) -> bool:
    """True if fn contains ``with ...default_matmul_precision(X)`` where X
    is the literal "highest" or a parameter defaulting to "highest"."""
    defaults = _signature_defaults(fn)
    for node in ast.walk(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            call = item.context_expr
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, (ast.Attribute, ast.Name))):
                continue
            name = (call.func.attr if isinstance(call.func, ast.Attribute)
                    else call.func.id)
            if name != "default_matmul_precision" or not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Constant) and arg.value == "highest":
                return True
            if (isinstance(arg, ast.Name)
                    and defaults.get(arg.id) == "highest"):
                return True
    return False


def _module_functions():
    for path in sorted(PKG_ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in tree.body:                 # module level only
            if isinstance(node, ast.FunctionDef):
                yield path, node


def test_every_fit_driver_forces_highest_precision():
    seen, missing = set(), []
    for path, fn in _module_functions():
        qual = _qualname(path, fn.name)
        is_driver = fn.name == "fit" or fn.name.endswith("_fit")
        if (not is_driver and qual not in MUST_GUARD_EXTRA
                and qual not in ALLOWLIST):
            continue
        seen.add(qual)
        if qual in ALLOWLIST:
            continue
        if not _has_precision_ctx(fn):
            missing.append(qual)
    assert not missing, (
        "fit drivers without a matmul_precision='highest' context "
        f"(bf16-rounded MXU matmuls poison the loglik): {missing}")
    # The audit actually saw the drivers it exists to protect (a rename
    # must update this list, not silently skip the check).
    expected = {
        "dfm_tpu.models.mixed_freq.mf_fit",
        "dfm_tpu.models.tv_loadings.tvl_fit",
        "dfm_tpu.parallel.sharded.sharded_em_fit",
        "dfm_tpu.parallel.sharded_mf.sharded_mf_fit",
        "dfm_tpu.parallel.sharded_tvl.sharded_tvl_fit",
        # The differentiable hyper search carries its own precision ctx
        # (the whole search is one program through the loglik).
        "dfm_tpu.estim.tune.tune_fit",
    } | MUST_GUARD_EXTRA | ALLOWLIST
    assert expected <= seen, sorted(expected - seen)


def test_allowlist_is_frozen():
    # The allowlist names real functions; a stale entry means the
    # delegation story changed and this file must be revisited.
    assert {q for q in ALLOWLIST} == {
        "dfm_tpu.api.fit", "dfm_tpu.api._family_fit",
        "dfm_tpu.estim.em.em_fit", "dfm_tpu.backends.cpu_ref.em_fit",
        "dfm_tpu.models.sv.sv_fit", "dfm_tpu.estim.fused.run_fused"}
    seen = {_qualname(p, f.name) for p, f in _module_functions()}
    assert ALLOWLIST <= seen, sorted(ALLOWLIST - seen)

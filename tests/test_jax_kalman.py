"""M1: JAX filter/smoother/EM must match the NumPy CPU oracle.

Runs on the fake-CPU JAX platform with x64 enabled (conftest), so agreement is
near machine precision; a separate float32 test checks the TPU-precision
tolerance story (BASELINE.json:5 demands loglik match to 1e-5 for the real
backend pairing, which bench configs verify on device).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dfm_tpu.backends import cpu_ref as cr
from dfm_tpu.estim.em import EMConfig, em_fit, em_step, em_fit_scan
from dfm_tpu.ssm import kalman as jk
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def prob():
    rng = np.random.default_rng(11)
    p = dgp.dfm_params(N=7, k=3, rng=rng)
    Y, F = dgp.simulate(p, T=25, rng=rng)
    return Y, p


def test_filter_matches_cpu(prob):
    Y, p = prob
    kf_np = cr.kalman_filter(Y, p)
    kf_jx = jk.kalman_filter(jnp.asarray(Y), JP.from_numpy(p))
    np.testing.assert_allclose(kf_jx.loglik, kf_np.loglik, rtol=1e-10)
    np.testing.assert_allclose(kf_jx.x_filt, kf_np.x_filt, atol=1e-9)
    np.testing.assert_allclose(kf_jx.P_filt, kf_np.P_filt, atol=1e-9)
    np.testing.assert_allclose(kf_jx.x_pred, kf_np.x_pred, atol=1e-9)


def test_smoother_matches_cpu(prob):
    Y, p = prob
    kf_np = cr.kalman_filter(Y, p)
    sm_np = cr.rts_smoother(kf_np, p)
    kf_jx, sm_jx = jk.filter_smoother(jnp.asarray(Y), JP.from_numpy(p))
    np.testing.assert_allclose(sm_jx.x_sm, sm_np.x_sm, atol=1e-8)
    np.testing.assert_allclose(sm_jx.P_sm, sm_np.P_sm, atol=1e-8)
    np.testing.assert_allclose(sm_jx.P_lag, sm_np.P_lag, atol=1e-8)


def test_masked_filter_matches_cpu(prob):
    Y, p = prob
    rng = np.random.default_rng(12)
    mask = dgp.random_mask(*Y.shape, rng=rng, frac_missing=0.3)
    kf_np = cr.kalman_filter(Y, p, mask=mask)
    kf_jx = jk.kalman_filter(jnp.asarray(Y), JP.from_numpy(p),
                             mask=jnp.asarray(mask))
    np.testing.assert_allclose(kf_jx.loglik, kf_np.loglik, rtol=1e-10)
    np.testing.assert_allclose(kf_jx.x_filt, kf_np.x_filt, atol=1e-9)


def test_em_step_matches_cpu(prob):
    Y, p = prob
    p_np, ll_np, _ = cr.em_step(Y, p)
    p_jx, ll_jx, _ = em_step(jnp.asarray(Y), JP.from_numpy(p))
    np.testing.assert_allclose(ll_jx, ll_np, rtol=1e-10)
    np.testing.assert_allclose(p_jx.Lam, p_np.Lam, atol=1e-8)
    np.testing.assert_allclose(p_jx.A, p_np.A, atol=1e-8)
    np.testing.assert_allclose(p_jx.Q, p_np.Q, atol=1e-8)
    np.testing.assert_allclose(p_jx.R, p_np.R, atol=1e-8)


def test_em_step_masked_matches_cpu(prob):
    Y, p = prob
    rng = np.random.default_rng(13)
    mask = dgp.random_mask(*Y.shape, rng=rng, frac_missing=0.25)
    p_np, ll_np, _ = cr.em_step(Y, p, mask=mask)
    p_jx, ll_jx, _ = em_step(jnp.asarray(Y), JP.from_numpy(p),
                          mask=jnp.asarray(mask))
    np.testing.assert_allclose(ll_jx, ll_np, rtol=1e-10)
    np.testing.assert_allclose(p_jx.Lam, p_np.Lam, atol=1e-8)
    np.testing.assert_allclose(p_jx.R, p_np.R, atol=1e-8)


def test_em_fit_matches_cpu_20_iters(prob):
    """S1-shaped end-to-end agreement: 20 EM iterations, loglik path equal."""
    Y, p = prob
    _, lls_np, _ = cr.em_fit(Y, p, max_iters=20, tol=0.0)
    _, lls_jx, _, _ = em_fit(jnp.asarray(Y), JP.from_numpy(p), max_iters=20, tol=0.0)
    np.testing.assert_allclose(np.asarray(lls_jx), lls_np, rtol=1e-8)


def test_em_fit_scan_equals_python_loop(prob):
    Y, p = prob
    _, lls_loop, _, _ = em_fit(jnp.asarray(Y), JP.from_numpy(p), max_iters=10, tol=0.0)
    _, lls_scan, _ = em_fit_scan(jnp.asarray(Y), JP.from_numpy(p), n_iters=10)
    np.testing.assert_allclose(np.asarray(lls_scan), np.asarray(lls_loop),
                               rtol=1e-10)


def test_float32_loglik_tolerance(prob):
    """f32 vs f64 loglik on an S1-scale problem: relative error small.

    This calibrates the expectation for TPU (f32) vs CPU (f64) agreement; the
    1e-5 absolute bound of BASELINE.json:5 applies to per-observation averaged
    loglik, which is the metric bench compares."""
    rng = np.random.default_rng(14)
    p = dgp.dfm_params(N=50, k=2, rng=rng, static=True)
    Y, _ = dgp.simulate(p, T=200, rng=rng)
    ll64 = jk.kalman_filter(jnp.asarray(Y, jnp.float64),
                            JP.from_numpy(p, jnp.float64)).loglik
    ll32 = jk.kalman_filter(jnp.asarray(Y, jnp.float32),
                            JP.from_numpy(p, jnp.float32)).loglik
    rel = abs(float(ll32) - float(ll64)) / abs(float(ll64))
    assert rel < 1e-4, f"f32 loglik rel err {rel}"


def test_static_em_cfg(prob):
    Y, p = prob
    cfg = EMConfig(estimate_A=False, estimate_Q=False)
    p0 = cr.SSMParams(p.Lam, np.zeros_like(p.A), np.eye(3), p.R,
                      np.zeros(3), np.eye(3))
    p_np, ll_np, _ = cr.em_step(Y, p0, estimate_A=False, estimate_Q=False)
    p_jx, ll_jx, _ = em_step(jnp.asarray(Y), JP.from_numpy(p0), cfg=cfg)
    np.testing.assert_allclose(ll_jx, ll_np, rtol=1e-10)
    np.testing.assert_allclose(p_jx.Lam, p_np.Lam, atol=1e-8)
    np.testing.assert_allclose(np.asarray(p_jx.A), p0.A)  # A untouched


@pytest.mark.parametrize("k", [1, 2, 4, 7])
def test_chol_unrolled_matches_linalg(k):
    """Unrolled small-k Cholesky/solve (the S4/S5 hot-loop path) agrees
    with the jnp.linalg reference on batched PSD inputs."""
    from dfm_tpu.ops.linalg import chol_unrolled, chol_solve_unrolled
    rng = np.random.default_rng(k)
    A = rng.normal(size=(64, k, k))
    P = A @ np.swapaxes(A, -1, -2) + 0.1 * np.eye(k)
    B = rng.normal(size=(64, k, k))
    L = np.asarray(chol_unrolled(jnp.asarray(P)))
    np.testing.assert_allclose(L, np.linalg.cholesky(P), atol=1e-10)
    X = np.asarray(chol_solve_unrolled(jnp.asarray(L), jnp.asarray(B)))
    np.testing.assert_allclose(X, np.linalg.solve(P, B), atol=1e-8)
    # vector RHS path
    b = rng.normal(size=(64, k))
    xv = np.asarray(chol_solve_unrolled(jnp.asarray(L), jnp.asarray(b)))
    np.testing.assert_allclose(xv, np.linalg.solve(P, b[..., None])[..., 0],
                               atol=1e-8)

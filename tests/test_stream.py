"""Unbounded streams (ISSUE 14): ring-buffer panels + snapshot tiering.

The operative contracts, verified on the fake 8-device CPU mesh:

- RING PARITY: a ``ring=True`` session update past capacity retires the
  oldest rows IN GRAPH and is numerically pinned to a cold
  ``fit(fused=True)`` of the equivalent TRAILING WINDOW at the same
  start params and iteration budget — x64 to ~1e-11, an f32 variant to
  f32 tolerance; ring fleets match lone ring sessions and the sharded
  ring fleet matches the single-device one.
- CONSTANT-MEMORY BUDGET: the eviction roll rides the one serve_update
  executable — across a soak past capacity a traced ring session pays
  1 first-call + 0 recompiles, exactly one blocking d2h per query, and
  neither the device buffer nor the host shadows grow a byte.
- OVERFLOW ERGONOMICS (satellite): non-ring overflow names ``ring=True``
  as the fix; ``remaining`` is None (unbounded) in ring mode.
- SNAPSHOT ACROSS CAPACITY (satellite): restoring a ring snapshot into
  a smaller capacity keeps the TRAILING window (the eviction rule
  applied retroactively); a non-ring restore refuses to drop data.
- TIERING: a fleet holds >= 4x more registered tenants than resident
  HBM lanes — paged tenants heal BIT-IDENTICAL to an all-hot twin,
  including a cold (on-disk) spill/thaw round-trip; paging is traced
  into the report's fleet section.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.api import TPUBackend
from dfm_tpu.fleet.admission import plan_residency, readmission_cost_s
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import _print_text, summarize
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.sched.buckets import lane_rent_bytes
from dfm_tpu.utils import dgp

MODEL = DynamicFactorModel(n_factors=2, standardize=False)
# Default-engine pins run info explicitly so parity references are
# deterministic (the auto heuristic would pick dense at these small N,
# which fleet buckets map to the info twins); ring eviction under the
# routed engines is pinned in test_ring_engine_roundtrip below.
BE = TPUBackend(filter="info")


@pytest.fixture(scope="module")
def panel():
    """(T_all, N) panel with one missing cell; the first 40 rows open a
    FULL ring session (capacity 40), the rest stream in past capacity."""
    rng = np.random.default_rng(14)
    p = dgp.dfm_params(N=12, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=60, rng=rng)
    Y[3, 5] = np.nan
    return Y


def _cold_ref(Ywin, init, m, backend=None):
    """The ring parity oracle: a cold fused fit of the TRAILING WINDOW
    from the same start params at the same pinned budget."""
    return fit(MODEL, Ywin, backend=backend, fused=True, max_iters=m,
               tol=0.0, init=init)


def _assert_update_matches(u, ref, tol=1e-9, atol=1e-10, ll_rtol=1e-7):
    np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=tol, atol=atol)
    np.testing.assert_allclose(u.factors, ref.factors, rtol=tol, atol=atol)
    np.testing.assert_allclose(u.forecasts["y"], ref.forecasts["y"],
                               rtol=tol, atol=atol)
    assert u.n_iters == ref.n_iters
    np.testing.assert_allclose(u.logliks, ref.logliks, rtol=ll_rtol,
                               atol=1e-6)


def _tenant(N, T, k, seed, extra=10):
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T + extra, rng)
    res = fit(DynamicFactorModel(n_factors=k), Y[:T], max_iters=8,
              backend=BE, telemetry=False)
    return res, Y[:T], Y[T:]


# ------------------------------------------------------- ring parity --

def test_ring_update_matches_cold_fit_trailing_window(panel):
    """The acceptance pin: every post-capacity update == a cold fused
    fit of the trailing ``capacity``-row window, chained across queries
    (update 2 starts from update 1's params, window slides by n_new)."""
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=20, tol=1e-6)
    sess = open_session(res0, Y0, capacity=40, max_update_rows=4,
                        max_iters=5, tol=0.0, ring=True)
    assert sess.t == 40 and sess.ring

    u1 = sess.update(panel[40:43])       # evicts rows 0-2 in graph
    assert u1.t == 40 and sess.t == 40
    assert sess.n_evicted == 3 and sess.total_rows == 43
    ref1 = _cold_ref(panel[3:43], res0.params, 5)
    _assert_update_matches(u1, ref1)

    u2 = sess.update(panel[43:45])       # window slides to rows 5..45
    assert sess.n_evicted == 5 and sess.total_rows == 45
    ref2 = _cold_ref(panel[5:45], ref1.params, 5)
    _assert_update_matches(u2, ref2)
    np.testing.assert_allclose(u2.factor_cov, ref2.factor_cov,
                               rtol=1e-9, atol=1e-10)
    sess.close()


def test_ring_partial_overflow_and_below_capacity_updates(panel):
    """A session BELOW capacity evicts only the overflow: a 3-row update
    at t=38 of 40 retires one row; an update that still fits evicts
    none (bit-path-identical to a non-ring session)."""
    Y0 = panel[:38]
    res0 = fit(MODEL, Y0, fused=True, max_iters=16, tol=1e-6)
    sess = open_session(res0, Y0, capacity=40, max_update_rows=3,
                        max_iters=4, tol=0.0, ring=True)

    u0 = sess.update(panel[38:40])       # fits: no eviction
    assert sess.t == 40 and sess.n_evicted == 0
    ref0 = _cold_ref(panel[:40], res0.params, 4)
    _assert_update_matches(u0, ref0)

    u1 = sess.update(panel[40:43])       # 40 + 3 -> evict exactly 3
    assert sess.t == 40 and sess.n_evicted == 3
    ref1 = _cold_ref(panel[3:43], ref0.params, 4)
    _assert_update_matches(u1, ref1)
    sess.close()


def test_ring_update_matches_cold_fit_f32(panel):
    b = TPUBackend(dtype=jnp.float32)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=16, tol=1e-5)
    sess = open_session(res0, Y0, backend=b, capacity=40,
                        max_update_rows=2, max_iters=4, tol=0.0,
                        ring=True)
    u = sess.update(panel[40:42])
    assert sess.n_evicted == 2
    ref = _cold_ref(panel[2:42], res0.params, 4,
                    backend=TPUBackend(dtype=jnp.float32))
    np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=5e-3,
                               atol=5e-3)
    np.testing.assert_allclose(u.factors, ref.factors, rtol=5e-3,
                               atol=5e-3)
    assert u.n_iters == ref.n_iters
    sess.close()


# ------------------------------------------- constant-memory budget --

def test_ring_soak_one_executable_flat_footprint(panel):
    """Queries >> remaining capacity: 1 first-call + 0 recompiles, one
    blocking d2h per query, and the buffers never grow — the report
    carries the traced eviction ledger."""
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        sess = open_session(res0, Y0, capacity=40, max_update_rows=3,
                            max_iters=3, tol=0.0, ring=True)
        dev_shape = sess._Ybuf.shape
        host_bytes = sess._Yhost.nbytes + sess._Whost.nbytes
        t = 40
        for n in (2, 3, 1, 2, 3):   # ragged row counts, one padded shape
            u = sess.update(panel[t:t + n])
            t += n
            assert u.t == 40 and sess.t == 40
        assert sess._Ybuf.shape == dev_shape
        assert sess._Yhost.nbytes + sess._Whost.nbytes == host_bytes
        assert sess.n_evicted == 11 and sess.total_rows == 51

    disp = [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "serve_update"]
    assert len(disp) == 5
    assert sum(1 for e in disp if e.get("first_call")) == 1
    assert sum(1 for e in disp if e.get("recompile")) == 0

    s = summarize(tr.events)
    assert s["blocking_transfers"] == 5
    q = s["queries"]
    assert q["n_queries"] == 5 and q["recompiles_after_warmup"] == 0
    assert q["rows_evicted"] == 11 and q["evicting_queries"] == 5
    _print_text(s)   # the text report renders the eviction ledger
    sess.close()


def test_ring_query_events_carry_eviction_count(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    tr = Tracer()
    with activate(tr):
        sess = open_session(res0, Y0, capacity=40, max_update_rows=2,
                            max_iters=3, tol=0.0, ring=True)
        sess.update(panel[40:42])
        sess.close()
    ev = [e for e in tr.events if e.get("kind") == "query"]
    assert len(ev) == 1 and ev[0]["n_evicted"] == 2

    # The always-on metrics plane sees the same ledger.
    from dfm_tpu.obs.metrics import MetricsRegistry, record_event
    reg = MetricsRegistry()
    for e in tr.events:
        record_event(reg, None, e)
    snap = reg.snapshot()
    assert any(k.startswith("evicted_rows_total") and v == 2
               for k, v in snap["counters"].items())


# ------------------------------------------- overflow ergonomics -----

def test_non_ring_overflow_message_names_ring_option(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=41, max_update_rows=4)
    assert not sess.ring and sess.remaining == 1
    with pytest.raises(ValueError, match="capacity overflow") as ei:
        sess.update(panel[40:43])
    assert "ring=True" in str(ei.value)
    assert sess.t == 40          # raised BEFORE any dispatch
    sess.close()


def test_ring_remaining_is_none_and_repr(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=40, max_update_rows=2,
                        ring=True)
    assert sess.remaining is None     # unbounded: no overflow exists
    assert "ring" in repr(sess)
    sess.close()


def test_ring_rejects_update_rows_above_capacity(panel):
    res0 = fit(MODEL, panel[:40], fused=True, max_iters=8, tol=1e-6)
    with pytest.raises(ValueError, match="max_update_rows"):
        open_session(res0, panel[:40], capacity=40, max_update_rows=41,
                     ring=True)


# ------------------------------- snapshot across a capacity change ---

def _open_ring(res0, Y0, **kw):
    kw.setdefault("capacity", 40)
    kw.setdefault("max_update_rows", 3)
    kw.setdefault("max_iters", 3)
    kw.setdefault("tol", 0.0)
    kw.setdefault("ring", True)
    return open_session(res0, Y0, **kw)


def test_snapshot_restore_smaller_capacity_keeps_trailing_window(
        panel, tmp_path):
    """The pinned semantics: restoring into capacity C keeps the LAST C
    live rows — the ring eviction rule applied retroactively — and the
    restored session's next update matches a cold fused fit of the new
    (smaller) trailing window."""
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    sess = _open_ring(res0, Y0)
    u1 = sess.update(panel[40:43])        # live window = rows 3..43
    path = sess.snapshot(str(tmp_path / "ring.npz"))
    p_now = sess._p.to_numpy()
    sess.close()

    re = open_session(snapshot=path, capacity=36)
    assert re.ring and re.t == 36 and re.capacity == 36
    # The kept rows ARE the trailing 36 of the live window (rows 7..43).
    np.testing.assert_allclose(re._Yhost[:36],
                               np.nan_to_num(panel[7:43]), atol=1e-12)

    u2 = re.update(panel[43:45])          # window slides to rows 9..45
    # The lifetime total rides the snapshot: 43 streamed + 2 new; the
    # eviction ledger is the difference to the held window.
    assert re.t == 36 and re.total_rows == 45 and re.n_evicted == 9
    ref = _cold_ref(panel[9:45], p_now, 3)
    _assert_update_matches(u2, ref)
    assert np.isfinite(u1.nowcast).all()
    re.close()


def test_ring_engine_roundtrip(panel, tmp_path):
    """Ring eviction under a routed engine: a pit_qr ring session past
    capacity pins to a cold SAME-engine fused fit of the trailing window
    (fp tolerance — the parallel-scan combine tree reassociates), and a
    snapshot restore into a SMALLER capacity keeps the engine.  Runs a
    small window: pit_qr CPU-mesh compiles grow quickly with the scan
    length and the ring contract is shape-independent."""
    b = TPUBackend(filter="pit_qr")
    Y0 = panel[:28]
    # Same (T, max_iters, tol) as the trailing-window oracle below, so
    # both cold fits ride ONE compiled pit program.
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=4, tol=0.0)
    sess = open_session(res0, Y0, backend=b, capacity=28,
                        max_update_rows=4, max_iters=4, tol=0.0,
                        ring=True)
    assert sess.filter == "pit_qr"
    u1 = sess.update(panel[28:31])        # evicts rows 0-2 in graph
    assert sess.n_evicted == 3 and sess.total_rows == 31
    ref1 = _cold_ref(panel[3:31], res0.params, 4, backend=b)
    _assert_update_matches(u1, ref1, tol=1e-8, atol=1e-8, ll_rtol=1e-6)
    path = sess.snapshot(str(tmp_path / "ring_eng.npz"))
    sess.close()
    re = open_session(snapshot=path, capacity=24, backend=b)
    assert re.filter == "pit_qr" and re.capacity == 24 and re.ring
    re.close()


def test_snapshot_restore_larger_capacity_repads(panel, tmp_path):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    sess = _open_ring(res0, Y0)
    sess.update(panel[40:42])
    path = sess.snapshot(str(tmp_path / "ring.npz"))
    p_now = sess._p.to_numpy()
    sess.close()

    re = open_session(snapshot=path, capacity=64)
    assert re.t == 40 and re.capacity == 64 and re.ring
    u = re.update(panel[42:44])           # room again: NO eviction —
    # t grows by n_new; the ledger still remembers the 2 pre-snapshot
    # evictions (lifetime total 44, held 42).
    assert re.t == 42 and re.n_evicted == 2 and re.total_rows == 44
    ref = _cold_ref(panel[2:44], p_now, 3)
    _assert_update_matches(u, ref)
    re.close()


def test_non_ring_restore_refuses_to_drop_data(panel, tmp_path):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=44, max_update_rows=2)
    sess.update(panel[40:42])
    path = sess.snapshot(str(tmp_path / "flat.npz"))
    sess.close()

    # A fixed-capacity session never drops data silently.
    with pytest.raises(ValueError, match="ring"):
        open_session(snapshot=path, capacity=38)
    # The SAME shrink is legal once the caller opts into ring semantics.
    re = open_session(snapshot=path, capacity=38, ring=True)
    assert re.ring and re.t == 38
    re.close()


# ------------------------------------------------------ ring fleets --

@pytest.fixture(scope="module")
def duo():
    return [_tenant(10, 40, 2, 31), _tenant(12, 40, 2, 32)]


def _open_fleet(tenants, **kw):
    kw.setdefault("capacity", 42)
    kw.setdefault("max_update_rows", 2)
    kw.setdefault("max_iters", 3)
    kw.setdefault("tol", 0.0)
    kw.setdefault("backend", BE)
    kw.setdefault("max_classes", 1)
    return open_fleet([t[0] for t in tenants], [t[1] for t in tenants],
                      **kw)


def test_ring_fleet_matches_lone_ring_sessions(duo):
    """Each tenant's ring-fleet answer IS its lone ring session's,
    through rounds that roll both panels past capacity."""
    fl = _open_fleet(duo, ring=True)
    lone = [open_session(r, Y, capacity=42, max_update_rows=2,
                         max_iters=3, tol=0.0, backend=BE, ring=True)
            for r, Y, _ in duo]
    for rnd in range(4):                 # 8 rows streamed into cap 42
        for i in range(2):
            fl.submit(f"t{i}", duo[i][2][2 * rnd:2 * rnd + 2])
        out = fl.drain()
        for i in range(2):
            u = out[f"t{i}"][0]
            ref = lone[i].update(duo[i][2][2 * rnd:2 * rnd + 2])
            assert u.t == ref.t and u.n_iters == ref.n_iters
            np.testing.assert_allclose(u.nowcast, ref.nowcast,
                                       rtol=1e-9, atol=1e-10)
            np.testing.assert_allclose(u.forecasts["y"],
                                       ref.forecasts["y"],
                                       rtol=1e-9, atol=1e-10)
    assert lone[0].n_evicted == 6 and lone[0].t == 42
    for s in lone:
        s.close()
    fl.close()


def test_sharded_ring_fleet_matches_single_device(duo):
    outs = []
    for backend in (BE, "sharded"):
        fl = _open_fleet(duo, ring=True, backend=backend)
        for rnd in range(3):
            for i in range(2):
                fl.submit(f"t{i}", duo[i][2][2 * rnd:2 * rnd + 2])
            out = fl.drain()
        outs.append(out)
        fl.close()
    for t in ("t0", "t1"):
        a, b = outs[0][t][0], outs[1][t][0]
        np.testing.assert_allclose(a.nowcast, b.nowcast, rtol=1e-9,
                                   atol=1e-10)
        np.testing.assert_allclose(a.forecasts["y"], b.forecasts["y"],
                                   rtol=1e-9, atol=1e-10)
        assert a.n_iters == b.n_iters


def test_non_ring_fleet_overflow_names_ring_option(duo):
    fl = _open_fleet(duo, capacity=42)
    assert fl.submit("t0", duo[0][2][:2]) == 1      # 40 -> 42, exact fit
    with pytest.raises(ValueError, match="capacity overflow") as ei:
        fl.submit("t0", duo[0][2][2:4])             # projected 44 > 42
    assert "ring=True" in str(ei.value)
    fl.drain()
    fl.close()


# -------------------------------------------------- snapshot tiering --

@pytest.fixture(scope="module")
def octet():
    """Eight tenants, one shape — the >= 4x-over-lanes acceptance mix."""
    return [_tenant(8, 36, 2, 40 + i) for i in range(8)]


def test_fleet_tiering_4x_tenants_bit_identical(octet):
    """The acceptance pin: 8 registered tenants on 2 resident lanes —
    every answer through warm-paging churn is BIT-IDENTICAL to an
    all-hot twin's, and the paging traffic lands in the report."""
    kw = dict(capacity=42, max_update_rows=2, max_iters=3, tol=0.0,
              backend=BE, max_classes=1)
    results = [t[0] for t in octet]
    panels = [t[1] for t in octet]
    tw = open_fleet(results, panels, **kw)
    tr = Tracer()
    with activate(tr):
        fl = open_fleet(results, panels, resident=2, **kw)
        assert fl.resident_lanes == 2
        assert sum(fl.tier(f"t{i}") == "hot" for i in range(8)) == 2
        n_paged = 0
        for rnd in range(2):
            for i in range(8):
                rows = octet[i][2][2 * rnd:2 * rnd + 2]
                n_paged += fl.tier(f"t{i}") != "hot"
                fl.submit(f"t{i}", rows)
                tw.submit(f"t{i}", rows)
                a = fl.drain()[f"t{i}"][0]
                b = tw.drain()[f"t{i}"][0]
                assert np.array_equal(a.nowcast, b.nowcast), (i, rnd)
                assert np.array_equal(a.forecasts["y"],
                                      b.forecasts["y"]), (i, rnd)
                assert np.array_equal(a.factors, b.factors), (i, rnd)
        fl.close()
    assert n_paged >= 12          # churn: nearly every submit paged

    s = summarize(tr.events)
    pg = s["fleet"]["paging"]
    assert pg["admits"] == n_paged and pg["demotes"] >= n_paged - 2
    assert pg["readmission_s"]["p50"] > 0
    _print_text(s)                # renders the paging line
    tw.close()


def test_cold_spill_thaw_roundtrip(octet, tmp_path):
    kw = dict(capacity=42, max_update_rows=2, max_iters=3, tol=0.0,
              backend=BE, max_classes=1)
    results = [t[0] for t in octet[:3]]
    panels = [t[1] for t in octet[:3]]
    tw = open_fleet(results, panels, **kw)
    fl = open_fleet(results, panels, **kw)

    path = str(tmp_path / "t1.npz")
    fl.evict("t1", tier="cold", path=path)
    assert fl.tier("t1") == "cold"
    import os
    assert os.path.exists(path)

    rows = octet[1][2][:2]
    fl.submit("t1", rows)         # auto-thaws + re-admits
    tw.submit("t1", rows)
    a, b = fl.drain()["t1"][0], tw.drain()["t1"][0]
    assert fl.tier("t1") == "hot"
    assert np.array_equal(a.nowcast, b.nowcast)
    assert np.array_equal(a.forecasts["y"], b.forecasts["y"])

    # Validation: unknown tenants and tiers fail fast; a tenant with a
    # pending query can't be paged out from under its own tick.
    with pytest.raises(KeyError):
        fl.evict("nope")
    with pytest.raises(ValueError, match="tier"):
        fl.evict("t0", tier="lukewarm")
    fl.submit("t0", octet[0][2][:2])
    with pytest.raises(ValueError, match="pending"):
        fl.evict("t0")
    fl.drain()
    fl.close()
    tw.close()


# -------------------------------------------- admission economics ----

def test_plan_residency_properties():
    from dfm_tpu.fleet.admission import ClassAssignment
    classes = [ClassAssignment(dims=(48, 12, 2), members=(0, 1, 2, 3)),
               ClassAssignment(dims=(64, 20, 3), members=(4, 5))]
    # No budget: every tenant is hot.
    assert plan_residency(classes, None) == [4, 2]
    # The budget is split deterministically, >= 1 lane per class, and
    # never exceeds a class's tenant count.
    plan = plan_residency(classes, 3)
    assert plan == plan_residency(classes, 3)        # deterministic
    assert sum(plan) == 3 and all(p >= 1 for p in plan)
    assert all(p <= len(ca.members) for p, ca in zip(plan, classes))
    # A budget covering everyone degenerates to all-hot.
    assert plan_residency(classes, 99) == [4, 2]


def test_readmission_cost_scales_with_lane_rent():
    small = readmission_cost_s((48, 12, 2), r_max=2)
    big = readmission_cost_s((512, 120, 8), r_max=2)
    assert 0 < small < big
    assert lane_rent_bytes((48, 12, 2), 2) < lane_rent_bytes(
        (512, 120, 8), 2)


# ------------------------------------------------------ obs plumbing --

def test_stream_metrics_registered_in_store():
    from dfm_tpu.obs import store
    need = ("stream_qps", "stream_p99_ms", "evictions_per_query",
            "readmission_ms", "stream_blocking_transfers_per_query")
    for k in need:
        assert k in store._BENCH_NUMERIC_KEYS
    assert not store.lower_is_better("stream_qps")
    for k in need[1:]:
        assert store.lower_is_better(k)
    assert store.noise_floor("evictions_per_query") == 0.5
    assert store.noise_floor("stream_p99_ms") == 2.0
    assert store.noise_floor("readmission_ms") == 2.0

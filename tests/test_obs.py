"""Telemetry layer (``dfm_tpu.obs``): trace schema, dispatch/recompile
accounting, convergence telemetry, the report CLI, and the
zero-overhead-when-off contract — on the fake 8-device mesh (conftest).

The operative acceptance checks (ISSUE 3): a traced smoke fit leaves a
valid-JSONL trace whose dispatch count and per-chunk loglik curve are
reproduced by ``python -m dfm_tpu.obs.report``; a repeated same-shape fit
reports ZERO first-calls/recompiles (the detector mirrors the process's
XLA executable cache); telemetry off emits nothing and changes nothing.
"""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.obs import (RecompileDetector, Tracer, activate, current_tracer,
                         fit_tracer, program_cost, shape_key, summarize)
from dfm_tpu.utils import dgp

EVENT_KINDS = {"fit", "dispatch", "transfer", "chunk", "freeze", "health",
               "cost", "span"}


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(16, 2, rng)
    Y, _ = dgp.simulate(p, 40, rng)
    return (Y - Y.mean(0)) / Y.std(0)


def _fit(Y, **kw):
    kw.setdefault("max_iters", 12)
    kw.setdefault("tol", 1e-8)
    return fit(DynamicFactorModel(n_factors=2), Y,
               backend=TPUBackend(dtype=jnp.float64, filter="info"), **kw)


# -- unit surface ---------------------------------------------------------

def test_shape_key():
    a = np.zeros((40, 16), np.float32)
    assert shape_key(a) == "40x16xfloat32"
    assert shape_key(a, "info", "iters8") == "40x16xfloat32/info/iters8"
    assert shape_key(3, "x") == "3/x"


def test_recompile_detector():
    d = RecompileDetector()
    assert d.note("p", "k1") == "new"
    assert d.note("p", "k1") == "cached"
    assert d.note("p", "k2") == "recompile"   # same program, 2nd shape key
    assert d.note("p", "k2") == "cached"
    assert d.note("q", "k1") == "new"         # different program: fresh


def test_fit_tracer_resolution(tmp_path):
    assert fit_tracer(None) == (current_tracer(), False)
    assert fit_tracer(False) == (None, False)
    tr, owned = fit_tracer(True)
    assert isinstance(tr, Tracer) and owned and tr.path is None
    mine = Tracer()
    assert fit_tracer(mine) == (mine, False)
    p = tmp_path / "t.jsonl"
    tr, owned = fit_tracer(str(p))
    assert owned and tr.path == str(p)
    tr.close()


def test_health_events_are_stamped():
    from dfm_tpu.robust.health import FitHealth, HealthEvent
    h = FitHealth(engine="tpu_em")
    ev = h.record(HealthEvent(chunk=0, iteration=3, kind="divergence"))
    assert ev.t > 0.0 and ev.engine == "tpu_em"
    # mirrored into an active tracer with the same timestamp
    with activate(Tracer()) as tr:
        ev2 = h.record(HealthEvent(chunk=1, iteration=9, kind="stall"))
        (rec,) = [e for e in tr.events if e["kind"] == "health"]
    assert rec["t"] == ev2.t and rec["engine"] == "tpu_em"
    assert rec["event"] == "stall"


def test_program_cost_static():
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((16, 16), jnp.float32)
    c = program_cost(f, x)
    assert c is None or (isinstance(c, dict) and
                         all(v >= 0 for v in c.values()))
    # on the CPU backend the cost model is available and counts the matmul
    if c is not None and "flops" in c:
        assert c["flops"] >= 2 * 16 ** 3 * 0.5


# -- traced fit: schema + report round-trip -------------------------------

def test_traced_fit_schema_and_report_roundtrip(panel, tmp_path):
    trace = tmp_path / "fit.jsonl"
    r = _fit(panel, telemetry=str(trace))
    assert r.converged or len(r.logliks) == 12

    events = [json.loads(ln) for ln in
              trace.read_text().splitlines() if ln.strip()]
    assert events, "trace file is empty"
    for e in events:
        assert isinstance(e["t"], float)
        assert e["kind"] in EVENT_KINDS, e
    kinds = {e["kind"] for e in events}
    assert {"fit", "dispatch", "chunk"} <= kinds

    # per-chunk loglik telemetry reassembles into the fit's own trace
    lls = [x for e in events if e["kind"] == "chunk"
           for x in e.get("lls", [])]
    np.testing.assert_allclose(lls, r.logliks, rtol=0, atol=0)

    # FitResult.telemetry and the offline report agree exactly
    s = summarize(str(trace))
    assert r.telemetry == s
    assert s["dispatches"] == sum(1 for e in events
                                  if e["kind"] == "dispatch")
    assert s["dispatches"] > 0
    assert s["convergence"]["n_iters"] == len(r.logliks)
    np.testing.assert_allclose(s["convergence"]["deltas"],
                               np.diff(r.logliks), rtol=0, atol=0)
    assert s["convergence"]["noise_floor"] is not None
    (f_ev,) = [e for e in events if e["kind"] == "fit"]
    assert f_ev["n_iters"] == r.n_iters and f_ev["wall"] > 0


def test_report_cli(panel, tmp_path):
    trace = tmp_path / "cli.jsonl"
    r = _fit(panel, telemetry=str(trace))
    # the report CLI is jax-free: it must come up instantly in a bare env
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(trace)],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    assert "dispatches:" in out.stdout
    assert "convergence:" in out.stdout
    js = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(trace), "--json"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo")
    s = json.loads(js.stdout)
    assert s["dispatches"] == r.telemetry["dispatches"]
    assert s["recompiles"] == r.telemetry["recompiles"]


# -- recompile accounting -------------------------------------------------

def test_repeated_same_shape_fit_zero_recompiles(panel):
    _fit(panel, telemetry=True)            # warm the process program cache
    r2 = _fit(panel, telemetry=True)       # identical shapes: all cached
    assert r2.telemetry["first_calls"] == 0
    assert r2.telemetry["recompiles"] == 0


def test_recompile_detector_fires_on_shape_change(panel):
    # Fresh injected detector: this tracer's view of "first" is its own.
    # max_iters == one fused chunk, so each program has exactly one shape
    # key per panel shape (a tail chunk of a different length would itself
    # be a truthful recompile — see obs/trace.py shape_key).
    tr = Tracer(detector=RecompileDetector())
    _fit(panel, telemetry=tr, max_iters=8, tol=0.0)
    assert not any(e.get("recompile") for e in tr.events)
    _fit(np.ascontiguousarray(panel[:, :12]),  # N changed: new executable
         telemetry=tr, max_iters=8, tol=0.0)
    rec = [e for e in tr.events
           if e["kind"] == "dispatch" and e.get("recompile")]
    assert rec, "shape change must register as a recompile"
    assert any(e["program"] == "em_chunk" for e in rec)


# -- zero-overhead-when-off ----------------------------------------------

def test_telemetry_off_emits_nothing(panel):
    ambient = Tracer()
    with activate(ambient):
        r_off = _fit(panel, telemetry=False)   # hard-off masks the ambient
    assert ambient.events == []
    assert r_off.telemetry is None
    # and the fit itself is bit-identical with telemetry on (host-side
    # event emission only — no extra device programs in the fused path)
    r_on = _fit(panel, telemetry=True)
    np.testing.assert_array_equal(r_off.logliks, r_on.logliks)
    np.testing.assert_array_equal(np.asarray(r_off.params.Lam),
                                  np.asarray(r_on.params.Lam))


def test_no_tracer_is_the_default():
    assert current_tracer() is None or True  # DFM_TRACE may be exported
    with activate(None):
        assert current_tracer() is None


# -- batched + sharded engines -------------------------------------------

def test_batched_fit_many_freeze_and_chunk_events():
    from dfm_tpu.estim.batched import DFMBatchSpec, fit_many
    rng = np.random.default_rng(3)
    Y = np.stack([rng.standard_normal((60, 12)) for _ in range(3)])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    with activate(Tracer()) as tr:
        res = fit_many(DFMBatchSpec(Y=Y, model=model),
                       max_iters=40, tol=1e-4, dtype=np.float64)
    kinds = {e["kind"] for e in tr.events}
    assert "dispatch" in kinds and "chunk" in kinds
    freezes = [e for e in tr.events if e["kind"] == "freeze"]
    frozen = [b for b in range(3) if bool(res.converged[b])]
    assert {e["problem"] for e in freezes} >= set(frozen)
    for e in freezes:
        assert e["state"] in ("converged", "diverged")
    chunk = [e for e in tr.events if e["kind"] == "chunk"][-1]
    assert {"running", "converged", "diverged"} <= set(chunk)


def test_sharded_batched_dispatches_are_traced():
    from dfm_tpu.estim.batched import DFMBatchSpec, fit_many
    rng = np.random.default_rng(4)
    Y = np.stack([rng.standard_normal((60, 12)) for _ in range(5)])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    with activate(Tracer()) as tr:
        fit_many(DFMBatchSpec(Y=Y, model=model), backend="sharded",
                 max_iters=16, tol=0.0, dtype=np.float64)
    progs = {e.get("program") for e in tr.events
             if e["kind"] == "dispatch"}
    assert "sharded_batched_em_chunk" in progs
    assert "batched_smooth" in progs


def test_sharded_backend_fit_is_traced(panel):
    from dfm_tpu.api import ShardedBackend
    r = fit(DynamicFactorModel(n_factors=2), panel,
            backend=ShardedBackend(dtype=jnp.float64, filter="info"),
            max_iters=8, tol=1e-8, telemetry=True)
    s = r.telemetry
    assert s["dispatches"] > 0
    assert any(name.startswith("sharded_em") for name in s["programs"])

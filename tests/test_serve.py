"""Streaming nowcast sessions (dfm_tpu/serve/ + api/checkpoint wiring).

The operative contracts of ``open_session`` / ``fit(keep_session=True)``,
verified on the fake 8-device CPU mesh (conftest):

- NUMERICS PARITY: a session ``update`` runs the same program a cold
  ``fit(fused=True)`` of the concatenated panel would run at the same
  iteration budget and start params — x64 states/params/nowcasts pin to
  ~1e-12 (the zero-masked pad tail is exactly inert in the dense filter;
  only reduction ORDER differs), logliks to fp-reduction tolerance, incl.
  a ragged-edge mixed-frequency-style masked panel; an f32 variant holds
  to f32 tolerance.
- ONE-EXECUTABLE BUDGET: across 5 consecutive ragged updates a traced
  session pays 1 first-call + 0 recompiles and exactly one blocking d2h
  per query (the ISSUE 9 acceptance bound, also tools/serve_smoke.sh).
- HOST-SIDE GUARDS: capacity overflow / row-budget / shape violations
  raise BEFORE any dispatch; a diverged update keeps the on-device
  last-good params and warns.
- WARM-REFIT CACHE (satellite): ``fit(warm_start=prev)`` panel reuse is
  content-fingerprint based — a ``Y.copy()`` reuses the device panel,
  changed values re-upload with a ``panel_reupload`` trace event naming
  the differing field (``utils.checkpoint.panel_mismatch``).
"""

import dataclasses
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from dfm_tpu import (DynamicFactorModel, NowcastSession, fit, open_session)
from dfm_tpu.api import TPUBackend
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import summarize, _print_text
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp
from dfm_tpu.utils.checkpoint import panel_fingerprint, panel_mismatch

MODEL = DynamicFactorModel(n_factors=2, standardize=False)


@pytest.fixture(scope="module")
def panel():
    """(T_all, N) panel with one missing cell so cold fits take the
    masked path the session always uses; first 40 rows are the open
    panel, the rest stream in via updates."""
    rng = np.random.default_rng(11)
    p = dgp.dfm_params(N=12, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=52, rng=rng)
    Y[3, 5] = np.nan
    return Y


def _same_params(a, b, tol=1e-9):
    for f in ("Lam", "A", "Q", "R", "mu0", "P0"):
        np.testing.assert_allclose(np.asarray(getattr(a, f)),
                                   np.asarray(getattr(b, f)),
                                   rtol=tol, atol=tol, err_msg=f)


def _cold_ref(Ycat, init, m, model=MODEL, backend=None):
    """The parity oracle: a cold fused fit of the extended panel from the
    same start params at the same (pinned, tol=0) iteration budget."""
    return fit(model, Ycat, backend=backend, fused=True, max_iters=m,
               tol=0.0, init=init)


def _assert_update_matches(u, ref, states_tol=1e-11, ll_rtol=1e-7):
    np.testing.assert_allclose(u.nowcast, ref.nowcast,
                               rtol=states_tol, atol=states_tol)
    np.testing.assert_allclose(u.factors, ref.factors,
                               rtol=states_tol, atol=states_tol)
    np.testing.assert_allclose(u.forecasts["y"], ref.forecasts["y"],
                               rtol=states_tol, atol=states_tol)
    np.testing.assert_allclose(u.forecasts["di"], ref.forecasts["di"],
                               rtol=states_tol, atol=states_tol)
    # Logliks differ by summation ORDER only (T_cap vs T_true terms):
    # fp-reduction tolerance, not exactness.
    assert u.n_iters == ref.n_iters
    np.testing.assert_allclose(u.logliks, ref.logliks,
                               rtol=ll_rtol, atol=1e-6)


# ------------------------------------------------------------- parity --

def test_update_matches_cold_fused_fit(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=20, tol=1e-6)
    sess = open_session(res0, Y0, capacity=80, max_update_rows=4,
                        max_iters=5, tol=0.0)
    assert sess.t == 40 and sess.remaining == 40

    rows1 = panel[40:43]
    u1 = sess.update(rows1)
    assert u1.t == 43 and sess.t == 43
    ref1 = _cold_ref(panel[:43], res0.params, 5)
    _assert_update_matches(u1, ref1)
    _same_params(sess._p.to_numpy(), ref1.params)

    # Chained: the second update starts from update 1's params, exactly
    # like a cold refit warm-started on the first reference fit.
    rows2 = panel[43:45]
    u2 = sess.update(rows2)
    ref2 = _cold_ref(panel[:45], ref1.params, 5)
    _assert_update_matches(u2, ref2)
    np.testing.assert_allclose(u2.factor_cov, ref2.factor_cov,
                               rtol=1e-11, atol=1e-11)


def test_update_matches_cold_fit_ragged_mixed_freq(panel):
    """Mixed-frequency-style panel: one quarterly column (observed every
    3rd row) plus a ragged edge in the update itself."""
    Y = panel[:46].copy()
    q = np.arange(len(Y)) % 3 != 2
    Y[q, 0] = np.nan               # column 0 is quarterly
    Y0, rows = Y[:42], Y[42:46]    # the 4-row update spans a quarter
    res0 = fit(MODEL, Y0, fused=True, max_iters=20, tol=1e-6)
    sess = open_session(res0, Y0, capacity=64, max_update_rows=4,
                        max_iters=4, tol=0.0)
    u = sess.update(rows)
    ref = _cold_ref(Y[:46], res0.params, 4)
    _assert_update_matches(u, ref)


def test_update_matches_cold_fit_f32(panel):
    b = TPUBackend(dtype=jnp.float32)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=16, tol=1e-5)
    sess = open_session(res0, Y0, backend=b, capacity=60,
                        max_update_rows=2, max_iters=4, tol=0.0)
    u = sess.update(panel[40:42])
    ref = _cold_ref(panel[:42], res0.params, 4,
                    backend=TPUBackend(dtype=jnp.float32))
    np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=5e-3,
                               atol=5e-3)
    np.testing.assert_allclose(u.factors, ref.factors, rtol=5e-3,
                               atol=5e-3)
    assert u.n_iters == ref.n_iters


# ----------------------------------------------------- engine routing --

def _eng_backend(eng, rk=0):
    return TPUBackend(filter=eng, rank=rk)


@pytest.fixture(scope="module")
def eng_panel():
    """Small panel for the routed-engine pins.  The pit_qr executables
    carry a log-depth combine tree whose CPU-mesh compile cost grows
    quickly with the padded length; the parity contract is
    shape-independent, so these pins run the smallest shape that still
    pads (capacity > T) and masks (one NaN cell)."""
    rng = np.random.default_rng(17)
    p = dgp.dfm_params(N=8, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=32, rng=rng)
    Y[2, 3] = np.nan
    return Y


@pytest.mark.parametrize("eng,rk", [("pit_qr", 0), ("lowrank", 2)])
def test_session_engine_matches_cold_fused_fit(eng_panel, eng, rk):
    """Per-engine parity: a session opened on a pit_qr/lowrank fit
    inherits the engine and pins to a cold SAME-engine ``fit(fused=True)``
    of the concatenated panel.  fp tolerance, not exactness: the pit_qr
    combine tree (and the lowrank downdate ordering) reassociates across
    the capacity-padded length.  (Chained-update pinning is engine-free
    session state and covered by the info tests above; the smoke legs
    chain updates through both engines.)"""
    b = _eng_backend(eng, rk)
    # res0 runs at the oracle's exact (T, max_iters, tol) statics so the
    # inheritance fit and the parity reference ride ONE compiled program
    # per engine; where its start params came from is irrelevant to the
    # pin (session from res0.params over 29 rows == cold fit from the
    # same params).
    res0 = fit(MODEL, eng_panel[:29], backend=b, fused=True, max_iters=5,
               tol=0.0)
    assert res0.filter == eng
    Y0 = eng_panel[:26]
    sess = open_session(res0, Y0, backend=b, capacity=30,
                        max_update_rows=3, max_iters=5, tol=0.0)
    assert sess.filter == eng and sess.rank == (rk if eng == "lowrank"
                                                else 0)
    u1 = sess.update(eng_panel[26:29])
    ref1 = _cold_ref(eng_panel[:29], res0.params, 5, backend=b)
    _assert_update_matches(u1, ref1, states_tol=1e-8, ll_rtol=1e-6)
    sess.close()


def test_session_engine_inherit_and_override(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6)
    # Explicit filter= wins over the fit's resolved engine.
    sess = open_session(res0, Y0, capacity=60, max_update_rows=2,
                        max_iters=2, filter="lowrank", rank=2)
    assert sess.filter == "lowrank" and sess.rank == 2
    sess.close()
    # Non-lowrank engines zero the rank.
    sess = open_session(res0, Y0, capacity=60, max_update_rows=2,
                        max_iters=2, filter="info", rank=3)
    assert sess.filter == "info" and sess.rank == 0
    sess.close()
    with pytest.raises(ValueError, match="filter"):
        open_session(res0, Y0, filter="nope")


def test_session_snapshot_roundtrip_engine(eng_panel, tmp_path):
    """snapshot → restore round-trips the engine + rank (lowrank carries
    BOTH keys; pit_qr's snapshot path is pinned by test_stream's ring
    round-trip); a pre-engine snapshot (no filter/rank keys) restores
    through the backend's auto resolution."""
    b = _eng_backend("lowrank", 2)
    # Identical fit/session statics to the lowrank pin above: zero new
    # executables in this test.
    res0 = fit(MODEL, eng_panel[:29], backend=b, fused=True, max_iters=5,
               tol=0.0)
    Y0 = eng_panel[:26]
    sess = open_session(res0, Y0, backend=b, capacity=30,
                        max_update_rows=3, max_iters=5, tol=0.0)
    sess.update(eng_panel[26:28])
    p = str(tmp_path / "s.npz")
    sess.snapshot(p)
    sess2 = open_session(snapshot=p, backend=b)
    assert sess2.filter == "lowrank" and sess2.rank == 2
    ua = sess.update(eng_panel[28:30])
    ub = sess2.update(eng_panel[28:30])
    np.testing.assert_array_equal(ua.nowcast, ub.nowcast)
    np.testing.assert_array_equal(ua.logliks, ub.logliks)
    sess.close()
    sess2.close()
    # Strip the engine keys: the restore resolves via the backend.
    with np.load(p) as z:
        data = {k: z[k] for k in z.files if k not in ("filter", "rank")}
    p_old = str(tmp_path / "old.npz")
    np.savez(p_old, **data)
    sess3 = open_session(snapshot=p_old)
    assert sess3.filter in ("dense", "info", "pit", "pit_qr", "lowrank")
    sess3.close()


def test_session_bands_and_coverage(panel):
    """Conservative uncertainty bands as first-class outputs: per-series
    nowcast_sd + per-step forecast_sd ride the query's one d2h; the NEXT
    update scores realized rows against the previous 90% bands."""
    from dfm_tpu.serve.session import _Z90
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=64, max_update_rows=4,
                        max_iters=3, tol=0.0, horizon=2)
    u1 = sess.update(panel[40:42])
    assert u1.nowcast_sd.shape == (12,) and (u1.nowcast_sd > 0).all()
    assert u1.forecast_sd.shape == (2, 12)
    assert (u1.forecast_sd > 0).all()
    assert u1.coverage is None          # nothing was predicted before
    u2 = sess.update(panel[42:44])
    hit = (np.abs(panel[42:44] - u1.forecasts["y"][:2])
           <= _Z90 * u1.forecast_sd[:2])
    assert u2.coverage == pytest.approx(float(np.mean(hit)))
    assert 0.0 <= u2.coverage <= 1.0
    sess.close()


def test_query_events_and_report_carry_engine_coverage(eng_panel):
    """Traced queries stamp the resolved engine; realized coverage rides
    the query event into summarize()'s per-session section and the text
    report.  (Statics match the lowrank pins above: one shared serve
    executable across the engine tests.)"""
    Y0 = eng_panel[:26]
    res0 = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6)
    tr = Tracer()
    with activate(tr):
        sess = open_session(res0, Y0, capacity=30, max_update_rows=3,
                            max_iters=5, tol=0.0, filter="lowrank", rank=2)
        sess.update(eng_panel[26:28])
        sess.update(eng_panel[28:30])
        sess.close()
    q = [e for e in tr.events if e.get("kind") == "query"]
    assert all(e.get("engine") == "lowrank" for e in q)
    assert "coverage" not in q[0] and isinstance(q[1]["coverage"], float)
    s = summarize(tr.events)
    ps = s["queries"]["per_session"][sess.session_id]
    assert ps["engine"] == "lowrank"
    assert ps["forecast_coverage"] == pytest.approx(q[1]["coverage"])
    _print_text(s)


def test_pure_reforecast_update(panel):
    """Satellite (ISSUE 11): ``update(None)`` is a pure RE-FORECAST —
    no append, t unchanged, SAME executable and exactly one blocking
    d2h, answer pinned to a cold fused fit of the resident panel from
    the same params at the same budget."""
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        # Same session config as the 5-update budget test: the
        # serve_update executable is reused within this module.
        sess = open_session(res0, Y0, capacity=80, max_update_rows=3,
                            max_iters=3, tol=0.0)
        u1 = sess.update(panel[40:42])
        u2 = sess.update(None)
        assert u2.t == sess.t == 42        # nothing appended
        with pytest.raises(ValueError, match="mask requires new_rows"):
            sess.update(None, mask=np.ones((1, 12)))
        assert sess.t == 42
    ref1 = _cold_ref(panel[:42], res0.params, 3)
    ref2 = _cold_ref(panel[:42], ref1.params, 3)
    _assert_update_matches(u2, ref2)
    disp = [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "serve_update"]
    assert len(disp) == 2                  # the re-forecast dispatched
    assert sum(1 for e in disp if e.get("recompile")) == 0
    s = summarize(tr.events)
    assert s["blocking_transfers"] == 2    # one d2h per query, incl. None
    q = [e for e in tr.events if e.get("kind") == "query"]
    assert q[-1]["n_new"] == 0 and q[-1]["t_rows"] == 42


# ----------------------------------------------- one-executable budget --

def test_five_updates_one_executable_one_barrier(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        sess = open_session(res0, Y0, capacity=80, max_update_rows=3,
                            max_iters=3, tol=0.0)
        t = 40
        for n in (1, 3, 2, 1, 2):  # ragged row counts, one padded shape
            u = sess.update(panel[t:t + n])
            t += n
            assert u.t == t
    disp = [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "serve_update"]
    assert len(disp) == 5
    assert sum(1 for e in disp if e.get("first_call")) == 1
    assert sum(1 for e in disp if e.get("recompile")) == 0
    assert all(e.get("barrier") for e in disp)

    s = summarize(tr.events)
    # Exactly one blocking d2h per query, none anywhere else.
    assert s["blocking_transfers"] == 5
    q = s["queries"]
    assert q["n_queries"] == 5 and q["n_sessions"] == 1
    assert q["recompiles_after_warmup"] == 0
    assert q["per_session"][sess.session_id]["queries"] == 5
    assert q["per_session"][sess.session_id]["t_rows"] == 49
    _print_text(s)   # the text report renders the queries section


def test_query_events_carry_shape_and_convergence(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=12, tol=1e-6)
    tr = Tracer()
    with activate(tr):
        sess = open_session(res0, Y0, capacity=60, max_iters=8, tol=1e-4)
        sess.update(panel[40:42])
    ev = [e for e in tr.events if e.get("kind") == "query"]
    assert len(ev) == 1
    assert ev[0]["session"] == sess.session_id
    assert ev[0]["t_rows"] == 42 and ev[0]["n_new"] == 2
    assert ev[0]["n_iters"] >= 1 and ev[0]["wall"] > 0


# -------------------------------------------------- host-side guards --

def test_capacity_overflow_raises_before_dispatch(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    tr = Tracer()
    with activate(tr):
        sess = open_session(res0, Y0, capacity=41, max_update_rows=4)
        with pytest.raises(ValueError, match="capacity overflow"):
            sess.update(panel[40:43])
        assert sess.t == 40    # untouched
        u = sess.update(panel[40:41])   # the fitting update still lands
        assert u.t == 41 and sess.remaining == 0
    disp = [e for e in tr.events if e.get("kind") == "dispatch"]
    assert len(disp) == 1      # only the valid update dispatched


def test_update_validation(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=60, max_update_rows=2)
    with pytest.raises(ValueError, match="max_update_rows"):
        sess.update(panel[40:43])
    with pytest.raises(ValueError, match="must be"):
        sess.update(np.zeros((1, 5)))
    with pytest.raises(ValueError, match="empty"):
        sess.update(np.zeros((0, 12)))
    u = sess.update(panel[40])        # 1-D row promotes to (1, N)
    assert u.t == 41
    sess.close()
    with pytest.raises(RuntimeError, match="closed"):
        sess.update(panel[41])
    assert "closed" in repr(sess)


def test_open_validation(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    with pytest.raises(TypeError, match="FitResult"):
        open_session("nope", Y0)
    with pytest.raises(ValueError, match="fused device programs"):
        open_session(res0, Y0, backend="cpu")
    with pytest.raises(ValueError, match="capacity"):
        open_session(res0, Y0, capacity=10)
    with pytest.raises(ValueError, match="N=12"):
        open_session(res0, Y0[:, :5])
    with pytest.raises(ValueError, match="horizon"):
        open_session(res0, Y0[:3])
    sess = open_session(res0, Y0)
    assert sess.capacity == 80        # default 2*T
    assert "NowcastSession" in repr(sess)
    np.testing.assert_allclose(sess.params().Lam,
                               np.asarray(res0.params.Lam), rtol=1e-12)


def test_diverged_update_keeps_last_good_params(panel):
    b = TPUBackend(fused_chunk=4)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, backend=b, capacity=60,
                        max_update_rows=2, max_iters=8, tol=0.0)
    # Fault seam: crater chunk 1's logliks on device, as the fused-fit
    # robustness tests do — the update must flag divergence, warn, and
    # keep the pre-divergence checkpoint as the resident params.
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=1)
    with pytest.warns(RuntimeWarning, match="diverged"):
        u = sess.update(panel[40:41])
    assert u.diverged and not u.converged
    assert np.isfinite(u.nowcast).all()
    # The session survives: clear the fault and keep streaming.
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=None)
    u2 = sess.update(panel[41:42])
    assert u2.t == 42 and np.isfinite(u2.nowcast).all()
    assert not u2.diverged


# ------------------------------------------------- fit(keep_session=) --

def test_fit_keep_session(panel):
    model = DynamicFactorModel(n_factors=2)    # standardize on: the
    Y0 = panel[:40]                            # session must freeze stats
    res = fit(model, Y0, fused=True, max_iters=12, tol=1e-6,
              keep_session=True)
    assert isinstance(res.session, NowcastSession)
    assert res.session.t == 40
    u = res.session.update(panel[40:41])
    assert u.t == 41
    assert np.isfinite(u.nowcast).all()
    # Original units: the nowcast lives on the data scale, not z-scores.
    assert u.nowcast.shape == (12,)
    res_plain = fit(model, Y0, fused=True, max_iters=12, tol=1e-6)
    assert res_plain.session is None


def test_fit_keep_session_kwargs(panel):
    res = fit(MODEL, panel[:40], fused=True, max_iters=8, tol=1e-6,
              keep_session=dict(capacity=90, max_update_rows=6,
                                max_iters=2))
    assert res.session.capacity == 90
    assert res.session._r_max == 6 and res.session._max_iters == 2


# ------------------------------- warm-start content fingerprint cache --

def test_warm_refit_panel_cache_survives_copy(panel):
    b = TPUBackend(filter="info")
    Y0 = np.ascontiguousarray(panel[:40])
    cold = fit(MODEL, Y0, backend=b, fused=True, max_iters=6, tol=0.0)
    tr = Tracer()
    with activate(tr):
        warm = fit(MODEL, Y0.copy(), backend=b, fused=True, max_iters=6,
                   tol=0.0, warm_start=cold)
    # Content-equal host copy: the device panel is reused, no re-upload.
    assert not [e for e in tr.events if e.get("kind") == "panel_reupload"]
    assert warm.n_iters == 6


def test_warm_refit_reuploads_on_changed_values(panel):
    b = TPUBackend(filter="info")
    Y0 = np.ascontiguousarray(panel[:40])
    cold = fit(MODEL, Y0, backend=b, fused=True, max_iters=6, tol=0.0)
    Y1 = Y0.copy()
    Y1[0, 1] += 0.5
    tr = Tracer()
    with activate(tr):
        warm = fit(MODEL, Y1, backend=b, fused=True, max_iters=6,
                   tol=0.0, warm_start=cold)
    ev = [e for e in tr.events if e.get("kind") == "panel_reupload"]
    assert len(ev) == 1
    assert "panel values" in ev[0]["reason"]
    assert warm.n_iters == 6


def test_panel_fingerprint_and_mismatch():
    rng = np.random.default_rng(0)
    Y = rng.normal(size=(10, 4))
    Y[2, 3] = np.nan
    m = np.isfinite(Y)
    assert panel_fingerprint(Y) == panel_fingerprint(Y.copy())
    assert panel_fingerprint(Y, m) == panel_fingerprint(Y.copy(), m.copy())
    assert panel_fingerprint(Y) != panel_fingerprint(Y, m)
    Y2 = Y.copy()
    Y2[0, 0] += 1e-9
    assert panel_fingerprint(Y) != panel_fingerprint(Y2)

    assert panel_mismatch(Y, None, Y.copy(), None) is None      # NaN == NaN
    assert panel_mismatch(Y, m, Y.copy(), m.copy()) is None
    assert "panel shape" in panel_mismatch(Y, None, Y[:5], None)
    assert "panel dtype" in panel_mismatch(Y, None,
                                           Y.astype(np.float32), None)
    assert "mask presence" in panel_mismatch(Y, m, Y, None)
    m2 = m.copy()
    m2[0, 0] = ~m2[0, 0]
    assert "mask pattern" in panel_mismatch(Y, m, Y, m2)
    assert "panel values" in panel_mismatch(Y, None, Y2, None)


# ------------------------------------------------------- obs plumbing --

def test_summarize_without_queries_emits_empty_stable_section():
    # Schema v1 (ISSUE 12): the queries section is always present with
    # stable keys so downstream consumers never branch on key existence.
    s = summarize([{"kind": "dispatch", "program": "x", "key": "k",
                    "t": 0.0, "dur": 0.01, "barrier": True}])
    q = s["queries"]
    assert q["n_queries"] == 0
    assert q["per_session"] == {}
    assert s["schema_version"] == 1


def test_serve_metrics_registered_in_store():
    from dfm_tpu.obs import store
    for k in ("serve_p50_ms", "serve_p99_ms",
              "serve_blocking_transfers_per_query"):
        assert k in store._BENCH_NUMERIC_KEYS
        assert store.lower_is_better(k)
    assert store.noise_floor("serve_p50_ms") == store.noise_floor(
        "serve_p99_ms") > 0

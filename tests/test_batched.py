"""Batched multi-fit engine equivalence (estim.batched / parallel.batched).

The operative contract: ``fit_many`` over B stacked problems must
reproduce B independent ``fit()`` calls — loglik traces, params,
factors, convergence states, and health — while running ONE fused
program per chunk.  Verified here on the fake 8-device CPU mesh
(conftest), x64-exact and f32-tolerance variants, including staggered
mid-chunk convergence, the sharded batch axis with padding, the k-grid
inert-factor padding, restarts, and the API layers built on top
(``select_n_factors_em``, batched ``oos_evaluate``).
"""

import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.estim.batched import BatchFitResult, DFMBatchSpec, fit_many
from dfm_tpu.estim.evaluate import oos_evaluate
from dfm_tpu.estim.select import select_n_factors_em
from dfm_tpu.utils import dgp


def _panels(B, T, N, k, seed=0, noises=None):
    """B independent factor panels with optional per-problem noise scale."""
    rng = np.random.default_rng(seed)
    out = []
    for b in range(B):
        F = rng.standard_normal((T, k))
        Lam = rng.standard_normal((N, k))
        nz = 0.5 if noises is None else noises[b]
        out.append(F @ Lam.T + nz * rng.standard_normal((T, N)))
    return np.stack(out)


def _single_fits(model, Y, dtype, **kw):
    return [fit(model, Y[b],
                backend=TPUBackend(dtype=dtype, filter="info"), **kw)
            for b in range(Y.shape[0])]


def _assert_matches(res: BatchFitResult, singles, rtol=1e-9, atol=1e-7,
                    p_rtol=1e-7):
    for b, single in enumerate(singles):
        tb, ts = res.logliks[b], single.logliks
        assert len(tb) == len(ts), (b, len(tb), len(ts))
        np.testing.assert_allclose(tb, ts, rtol=rtol, atol=atol)
        np.testing.assert_allclose(res.params[b].Lam, single.params.Lam,
                                   rtol=p_rtol, atol=1e-8)
        assert bool(res.converged[b]) == bool(single.converged)


def test_fit_many_matches_looped_x64():
    Y = _panels(3, 60, 12, 2, seed=0)
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    res = fit_many(DFMBatchSpec(Y=Y, model=model),
                   max_iters=120, tol=1e-4, dtype=np.float64)
    singles = _single_fits(model, Y, np.float64, max_iters=120, tol=1e-4)
    _assert_matches(res, singles)
    for b, single in enumerate(singles):
        np.testing.assert_allclose(res.factors[b], single.factors,
                                   rtol=1e-4, atol=1e-8)
        assert res.health[b].ok, res.health[b].summary()
        assert res.p_iters[b] == single.n_iters


def test_fit_many_staggered_midchunk_convergence():
    """Problems converging at different iterations INSIDE a fused chunk
    (fused_chunk=7 does not divide anyone's stopping point) must freeze
    via the in-carry state without perturbing the still-running ones."""
    Y = _panels(4, 80, 15, 2, seed=1, noises=[0.05, 0.5, 2.0, 5.0])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    res = fit_many(DFMBatchSpec(Y=Y, model=model), max_iters=100,
                   tol=1e-5, dtype=np.float64, fused_chunk=7)
    singles = _single_fits(model, Y, np.float64, max_iters=100, tol=1e-5)
    _assert_matches(res, singles, p_rtol=1e-6)
    # The point of the test: they must NOT all stop at the same iteration.
    assert len(set(res.n_iters.tolist())) > 1


def test_fit_many_f32_fixed_iters():
    """f32 variant at tol=0 (fixed iteration count — the convergence
    decision itself is f32-noise-sensitive, the trajectory is not)."""
    Y = _panels(3, 60, 12, 2, seed=2)
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    res = fit_many(DFMBatchSpec(Y=Y, model=model),
                   max_iters=10, tol=0.0, dtype=np.float32)
    singles = _single_fits(model, Y, np.float32, max_iters=10, tol=0.0)
    for b, single in enumerate(singles):
        tb, ts = res.logliks[b], single.logliks
        assert len(tb) == len(ts) == 10
        # Same math, different reduction order: f32 rounding only.
        np.testing.assert_allclose(tb, ts, rtol=2e-3, atol=0.5)
        np.testing.assert_allclose(res.params[b].Lam, single.params.Lam,
                                   rtol=5e-3, atol=5e-3)


def test_fit_many_sharded_matches_single_device():
    """Batch axis across the fake 8-device mesh, B=5 (not a multiple of
    the mesh size — exercises the PADDED problems) must be bit-compatible
    with the single-device batched path."""
    Y = _panels(5, 60, 12, 2, seed=3, noises=[0.3, 0.7, 1.1, 1.5, 1.9])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    spec = DFMBatchSpec(Y=Y, model=model)
    r1 = fit_many(spec, backend="tpu", max_iters=40, tol=1e-5,
                  dtype=np.float64)
    r2 = fit_many(spec, backend="sharded", max_iters=40, tol=1e-5,
                  dtype=np.float64)
    for b in range(5):
        assert len(r1.logliks[b]) == len(r2.logliks[b])
        np.testing.assert_allclose(r1.logliks[b], r2.logliks[b],
                                   rtol=1e-10, atol=1e-8)
        np.testing.assert_allclose(r1.params[b].Lam, r2.params[b].Lam,
                                   rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(r1.factors[b], r2.factors[b],
                                   rtol=1e-8, atol=1e-10)
        assert bool(r1.converged[b]) == bool(r2.converged[b])


def test_k_grid_padding_matches_individual_fits():
    """Inert-factor padding to k_max must leave each problem's EM
    trajectory exactly what an unpadded fit at its own k produces."""
    rng = np.random.default_rng(4)
    F = rng.standard_normal((70, 3))
    Lam = rng.standard_normal((14, 3))
    Y = F @ Lam.T + 0.4 * rng.standard_normal((70, 14))
    ks = [1, 3]
    spec = DFMBatchSpec.k_grid(Y, ks=ks, dynamics="ar1")
    res = fit_many(spec, max_iters=12, tol=0.0, dtype=np.float64)
    for b, k in enumerate(ks):
        model_k = DynamicFactorModel(n_factors=k, dynamics="ar1")
        single = fit(model_k, Y,
                     backend=TPUBackend(dtype=np.float64, filter="info"),
                     max_iters=12, tol=0.0)
        np.testing.assert_allclose(res.logliks[b], single.logliks,
                                   rtol=1e-8, atol=1e-6)
        assert res.params[b].Lam.shape == (14, k)
        np.testing.assert_allclose(res.params[b].Lam, single.params.Lam,
                                   rtol=1e-6, atol=1e-8)


def test_restarts_best_and_exact_first_init():
    rng = np.random.default_rng(5)
    F = rng.standard_normal((60, 2))
    Lam = rng.standard_normal((12, 2))
    Y = F @ Lam.T + 0.5 * rng.standard_normal((60, 12))
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    spec = DFMBatchSpec.restarts(model, Y, 4, seed=1)
    res = fit_many(spec, max_iters=10, tol=0.0, dtype=np.float64)
    finals = res.logliks_final
    assert np.isfinite(finals).all()
    assert res.best() == int(np.argmax(finals))
    # Restart 0 is the unjittered PCA init — identical to a plain fit.
    single = fit(model, Y,
                 backend=TPUBackend(dtype=np.float64, filter="info"),
                 max_iters=10, tol=0.0)
    np.testing.assert_allclose(res.logliks[0], single.logliks,
                               rtol=1e-9, atol=1e-7)


def test_select_n_factors_em_recovers_true_k():
    rng = np.random.default_rng(6)
    p_true = dgp.dfm_params(16, 3, rng, noise_scale=0.3)
    Y, _ = dgp.simulate(p_true, 90, rng)
    sel = select_n_factors_em(Y, ks=[1, 2, 3, 4], max_iters=15,
                              dtype=np.float64)
    assert sel.k_best == 3
    assert list(sel.ks) == [1, 2, 3, 4]
    # loglik must be non-decreasing in k (nested models, same panel)
    assert np.all(np.diff(sel.logliks) > -1e-6 * np.abs(sel.logliks[:-1]))


def test_oos_warm_start_and_batched_engine():
    rng = np.random.default_rng(7)
    F = rng.standard_normal((90, 2))
    Lam = rng.standard_normal((16, 2))
    Y = F @ Lam.T + 0.4 * rng.standard_normal((90, 16))
    model = DynamicFactorModel(n_factors=2)
    cold = oos_evaluate(model, Y, n_windows=4, max_iters=8,
                        warm_start=False)
    warm = oos_evaluate(model, Y, n_windows=4, max_iters=8,
                        warm_start=True)
    bat = oos_evaluate(model, Y, n_windows=4, max_iters=8,
                       engine="batched", backend="tpu")
    for r in (cold, warm, bat):
        assert np.isfinite(r.rel_rmse).all()
        assert r.rel_rmse.shape == (16,)
    # Warm starts change the trajectory but not validity: both must land
    # in the same ballpark on a well-specified panel.
    assert abs(warm.rel_rmse.mean() - cold.rel_rmse.mean()) < 0.25
    assert abs(bat.rel_rmse.mean() - cold.rel_rmse.mean()) < 0.25

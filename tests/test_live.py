"""Live serving telemetry plane (ISSUE 12: obs.metrics / obs.slo / obs.live).

The operative contracts, on the fake 8-device CPU mesh (conftest):

- OFF-PATH INERTNESS: the always-on plane reuses timestamps the trace
  layer already takes — the same workload (fit, fit_jobs, session,
  fleet) run with DFM_METRICS=0 and =1 produces BIT-IDENTICAL numbers
  and the SAME dispatch count.
- STREAMING QUANTILES: the fixed-log-bucket histogram's p50/p90/p99
  track the exact nearest-rank quantiles within the geometric-bucket
  error bound, at O(1) memory; snapshots round-trip through JSON.
- LEDGER RECONCILIATION: per-tenant accounting (queries, device-wall
  ms, EM iters, est. flops) reconciles exactly with the trace events
  that fed it — traced and untraced seams meter identically.
- SLO BURN: the rolling error-budget burn-rate monitor fires and clears
  deterministically from the observation sequence alone, and a breach
  dumps the flight ring to an ``obs.report``-readable JSONL.
- SCHEMA v1: ``summarize`` emits a versioned, stable-keyed JSON (the
  serving sections present even when empty), byte-preserved through a
  json round-trip, with a ``metrics`` section fed through the SAME
  ``record_event`` mapping the live plane uses.
- ROTATION: ``Tracer(max_bytes=)`` shift-rotates the JSONL; the report
  CLI accepts the rotated files in order and reproduces the in-memory
  summary.
"""

import hashlib
import json
import math
import os

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, Job, fit, fit_jobs, open_fleet, \
    open_session
from dfm_tpu.api import TPUBackend
from dfm_tpu.obs import live as live_mod
from dfm_tpu.obs.cost import RecompileDetector, em_iter_work
from dfm_tpu.obs.live import LivePlane
from dfm_tpu.obs.metrics import (Histogram, Ledger, MetricsRegistry,
                                 metrics_summary, record_event)
from dfm_tpu.obs.report import summarize
from dfm_tpu.obs.slo import AnomalyDetector, SLOConfig, SLOMonitor
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

BE = TPUBackend(filter="info")   # fleet core is info-filter-only
MODEL = DynamicFactorModel(n_factors=2)


@pytest.fixture
def fresh_plane(monkeypatch):
    """A clean enabled plane for this test; restore the lazy singleton."""
    for var in ("DFM_METRICS", "DFM_SLO_P99_MS", "DFM_SLO_ERROR_RATE",
                "DFM_SLO_WINDOW", "DFM_FLIGHT_DIR", "DFM_METRICS_SNAPSHOT"):
        monkeypatch.delenv(var, raising=False)
    live_mod.reset_plane()
    yield live_mod.plane()
    live_mod.reset_plane()


def _panel(T, N, k, seed):
    rng = np.random.default_rng(seed)
    Y, _ = dgp.simulate(dgp.dfm_params(N, k, rng), T, rng)
    return Y


# ------------------------------------------------ off-path inertness --

def _full_workload():
    """fit + fit_jobs + session + fleet, hashed, under a fresh tracer."""
    h = hashlib.sha256()
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        res = fit(MODEL, _panel(40, 12, 2, 5), max_iters=6, tol=1e-6,
                  fused=True)
        h.update(np.asarray(res.params.Lam, np.float64).tobytes())
        h.update(np.asarray(res.nowcast, np.float64).tobytes())

        jrs = fit_jobs([Job(Y=_panel(36, 10, 2, 6), model=MODEL,
                            tenant="a", max_iters=4, tol=0.0),
                        Job(Y=_panel(40, 12, 2, 7), model=MODEL,
                            tenant="b", max_iters=4, tol=0.0)])
        for jr in jrs:
            h.update(np.asarray(jr.fit.params.Lam, np.float64).tobytes())

        Yb = _panel(44, 12, 2, 8)
        resb = fit(MODEL, Yb[:40], max_iters=6, backend=BE,
                   telemetry=False)
        sess = open_session(resb, Yb[:40], capacity=60, max_update_rows=2,
                            max_iters=3, tol=0.0)
        u = sess.update(Yb[40:42])
        h.update(np.asarray(u.nowcast, np.float64).tobytes())

        fl = open_fleet([resb], [Yb[:40]], capacity=60, max_update_rows=2,
                        max_iters=3, tol=0.0, backend=BE)
        t0 = fl.tenants[0]
        fl.submit(t0, Yb[42:44])
        out = fl.drain()
        h.update(np.asarray(out[t0][0].nowcast, np.float64).tobytes())
        fl.close()
    return h.hexdigest(), tr.summary()["dispatches"]


def test_metrics_plane_off_path_bit_identity(monkeypatch):
    """Plane disabled vs enabled: identical numbers, identical dispatch
    count, across every serving layer (fit / fit_jobs / session / fleet)."""
    monkeypatch.setenv("DFM_METRICS", "0")
    live_mod.reset_plane()
    try:
        sha_off, disp_off = _full_workload()
        assert not live_mod.plane().enabled
        monkeypatch.setenv("DFM_METRICS", "1")
        live_mod.reset_plane()
        sha_on, disp_on = _full_workload()
        assert live_mod.plane().enabled
        assert live_mod.plane().registry.n_series > 0
    finally:
        live_mod.reset_plane()
    assert sha_on == sha_off
    assert disp_on == disp_off


# ------------------------------------------------ streaming quantiles --

def test_histogram_tracks_exact_nearest_rank_quantiles():
    rng = np.random.default_rng(0)
    xs = np.exp(rng.uniform(np.log(1e-2), np.log(1e3), size=5000))
    h = Histogram()
    for x in xs:
        h.observe(float(x))
    assert h.count == 5000
    assert h.sum == pytest.approx(float(xs.sum()))
    assert h.min == float(xs.min()) and h.max == float(xs.max())
    srt = np.sort(xs)
    for q in (0.5, 0.9, 0.99):
        exact = float(srt[max(1, math.ceil(q * len(srt))) - 1])
        est = h.quantile(q)
        assert abs(est - exact) / exact < 0.1, (q, est, exact)
    # O(1) memory: bucket count is bounded by the fixed grid, not n.
    assert len(h.buckets) < 400


def test_histogram_edge_cases():
    h = Histogram()
    assert h.quantile(0.5) is None
    h.observe(float("nan"))          # ignored
    assert h.count == 0
    h.observe(0.0)                   # clamps to the bottom bucket
    h.observe(1e9)                   # clamps to the top bucket
    assert h.count == 2
    assert h.min == 0.0 and h.max == 1e9
    assert h.quantile(0.0) <= h.quantile(1.0)


def test_registry_snapshot_roundtrip_and_prom():
    reg = MetricsRegistry()
    reg.counter("queries_total", tenant="t0").inc(3)
    reg.gauge("fleet_occupancy", fleet="f1", bucket="0").set(0.75)
    for w in (1.0, 2.0, 10.0):
        reg.histogram("query_wall_ms", tenant="t0").observe(w)
    snap = json.loads(json.dumps(reg.snapshot()))
    reg2 = MetricsRegistry.from_snapshot(snap)
    assert reg2.snapshot() == snap
    prom = reg2.render_prom()
    assert 'dfm_queries_total{tenant="t0"} 3' in prom
    assert "# TYPE dfm_query_wall_ms summary" in prom
    assert 'quantile="0.99"' in prom
    assert 'dfm_query_wall_ms_count{tenant="t0"} 3' in prom


# ------------------------------------------------ ledger reconciliation --

def test_session_ledger_reconciles_with_trace(fresh_plane):
    Y = _panel(46, 12, 2, 11)
    res = fit(MODEL, Y[:40], max_iters=6, backend=BE, telemetry=False)
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        sess = open_session(res, Y[:40], capacity=60, max_update_rows=2,
                            max_iters=3, tol=0.0)
        for i in range(3):
            sess.update(Y[40 + 2 * i:42 + 2 * i])
    q_evs = [e for e in tr.events if e.get("kind") == "query"]
    assert len(q_evs) == 3
    acct = sess.accounting()
    assert set(acct) == {sess.session_id}
    row = acct[sess.session_id]
    assert row["queries"] == 3
    assert row["em_iters"] == sum(e["n_iters"] for e in q_evs)
    assert row["device_ms"] == pytest.approx(
        sum(e["wall"] for e in q_evs) * 1e3)
    want_flops = sum(
        em_iter_work(e["N"], e["t_rows"], e["k"])[0] * e["n_iters"]
        for e in q_evs)
    assert row["est_flops"] == pytest.approx(want_flops)


def test_untraced_seams_meter_identically_to_traced(fresh_plane):
    """The explicit live_observe fallbacks build the SAME event payload
    the tracer would: ledger rows from an untraced session match a
    traced twin field-for-field."""
    Y = _panel(44, 10, 2, 12)
    res = fit(MODEL, Y[:40], max_iters=6, backend=BE, telemetry=False)

    def serve():
        sess = open_session(res, Y[:40], capacity=60, max_update_rows=2,
                            max_iters=3, tol=0.0)
        sess.update(Y[40:42])
        sess.update(Y[42:44])
        return sess.accounting()[sess.session_id]

    untraced = serve()
    with activate(Tracer(detector=RecompileDetector())):
        traced = serve()
    assert set(untraced) == set(traced)
    assert untraced["queries"] == traced["queries"] == 2
    assert untraced["em_iters"] == traced["em_iters"]
    assert untraced["est_flops"] == pytest.approx(traced["est_flops"])


def test_fit_jobs_feeds_tenant_ledger_untraced(fresh_plane):
    fit_jobs([Job(Y=_panel(36, 10, 2, 13), model=MODEL, tenant="t_a",
                  max_iters=4, tol=0.0),
              Job(Y=_panel(40, 12, 2, 14), model=MODEL, tenant="t_b",
                  max_iters=4, tol=0.0)])
    acct = live_mod.accounting()
    assert {"t_a", "t_b"} <= set(acct)
    for t in ("t_a", "t_b"):
        assert acct[t]["jobs"] == 1
        assert acct[t]["em_iters"] > 0
        assert acct[t]["est_flops"] > 0
        assert acct[t]["device_ms"] > 0


def test_fleet_accounting_per_tenant(fresh_plane):
    Ya, Yb = _panel(46, 12, 2, 15), _panel(46, 12, 2, 16)
    ra = fit(MODEL, Ya[:40], max_iters=6, backend=BE, telemetry=False)
    rb = fit(MODEL, Yb[:40], max_iters=6, backend=BE, telemetry=False)
    fl = open_fleet([ra, rb], [Ya[:40], Yb[:40]], capacity=60,
                    max_update_rows=2, max_iters=3, tol=0.0, backend=BE)
    ta, tb = fl.tenants
    fl.submit(ta, Ya[40:42])
    fl.submit(tb, Yb[40:42])
    fl.drain()
    fl.submit(ta, Ya[42:44])
    fl.drain()
    acct = fl.accounting()
    assert acct[ta]["queries"] == 2 and acct[tb]["queries"] == 1
    # wall_share attribution: tenant device_ms sums to the tick walls.
    assert acct[ta]["device_ms"] > 0 and acct[tb]["device_ms"] > 0
    fl.close()


# ------------------------------------------------ SLO burn / anomaly --

def test_slo_burn_fires_and_clears_deterministically():
    mon = SLOMonitor(SLOConfig(p99_ms=1.0, error_rate=0.5, window=10.0,
                               min_events=5))
    trans = [mon.observe(float(i), 50.0) for i in range(5)]
    assert trans[:4] == [None] * 4 and trans[4] == "fire"
    assert mon.breached and mon.burn_rate > 1.0
    # Fast queries march the window past the slow ones -> clear, once.
    trans = [mon.observe(float(5 + i), 0.01) for i in range(20)]
    assert trans.count("clear") == 1
    assert not mon.breached and mon.burn_rate == 0.0
    assert mon.n_fired == 1
    assert mon.status()["burn_rate_max"] > 1.0


def test_slo_error_rate_arm_and_unarmed_monitor():
    mon = SLOMonitor(None)
    assert mon.observe(0.0, 1e9) is None        # unarmed: observes nothing
    mon = SLOMonitor(SLOConfig(p99_ms=1e9, error_rate=0.1, window=100.0,
                               min_events=4))
    for i in range(3):
        assert mon.observe(float(i), 0.1, error=True) is None
    assert mon.observe(3.0, 0.1, error=True) == "fire"


def test_anomaly_detector_flags_spike_transition():
    det = AnomalyDetector(window_n=32, warmup=10, spike_ratio=3.0,
                          floor_ms=0.001)
    fired = [det.observe(1.0) for _ in range(20)]
    assert not any(fired)
    fired = [det.observe(50.0) for _ in range(5)]
    assert fired[0] and not any(fired[1:])      # transition fires once
    assert det.spiking and det.n_spikes == 1


def test_slo_burn_emits_health_event_and_flight_dump(tmp_path):
    plane = LivePlane(enabled=True,
                      slo=SLOConfig(p99_ms=1.0, window=100.0, min_events=5),
                      flight_dir=str(tmp_path), flight_min_interval_s=0.0)
    for i in range(6):
        plane.observe({"t": float(i), "kind": "query", "session": "s0",
                       "t_rows": 40, "n_new": 2, "wall": 0.5, "n_iters": 3,
                       "N": 12, "k": 2, "converged": True,
                       "diverged": False})
    assert plane.slo.breached
    assert [he.kind for he in plane.health_events] == ["slo_burn"]
    assert plane.health_events[0].action == "fired"
    assert plane.flight_dumps == 1
    dumps = sorted(os.listdir(tmp_path))
    assert len(dumps) == 1 and dumps[0].endswith(".jsonl")
    # The dump is a valid obs.report input carrying the whole story.
    s = summarize(str(tmp_path / dumps[0]))
    assert s["queries"]["n_queries"] == 5     # ring at dump time
    assert "slo_burn" in s["health_kinds"]
    assert s["metrics"]["counters"]["health_events_total{event=slo_burn}"] \
        == 1.0
    assert plane.errors == 0


def test_injected_fault_trips_slo_via_dispatch_seam(fresh_plane):
    """An availability fault injected at the ``wrap_dispatch`` seam (a
    failed dispatch, retried by the guard) reaches the armed SLO monitor
    as an error observation: the burn rate fires deterministically from
    the error budget, with zero real latency involved."""
    from dfm_tpu.robust import FaultInjector, RobustPolicy
    live_mod.set_slo(SLOConfig(p99_ms=1e9, error_rate=0.1, window=1e9,
                               min_events=3))
    Y = _panel(46, 10, 2, 17)
    res = fit(MODEL, Y[:40], max_iters=6, backend=BE, telemetry=False)
    inj = FaultInjector().dispatch_failure(at=0)
    pol = RobustPolicy(backoff_base=1e-6, wrap_dispatch=inj.wrap_call)
    sess = open_session(res, Y[:40], capacity=60, max_update_rows=2,
                        max_iters=3, tol=0.0, robust=pol)
    for i in range(3):
        sess.update(Y[40 + 2 * i:42 + 2 * i])
    pl = live_mod.plane()
    assert pl.registry.counter("dispatch_retries_total").value >= 1
    assert pl.slo.n_fired >= 1
    assert any(he.kind == "slo_burn" for he in pl.health_events)


def test_flight_dump_disabled_without_dir():
    plane = LivePlane(enabled=True,
                      slo=SLOConfig(p99_ms=1.0, window=100.0, min_events=2))
    for i in range(3):
        plane.observe({"t": float(i), "kind": "query", "session": "s0",
                       "wall": 0.5})
    assert plane.slo.breached
    assert plane.flight_dumps == 0            # library never writes files
    assert plane.dump_flight() is None


# ------------------------------------------------ schema / summarize --

def test_summary_schema_v1_stable_and_json_roundtrip():
    s = summarize([{"kind": "dispatch", "program": "x", "key": "k",
                    "t": 0.0, "dur": 0.01, "barrier": True,
                    "first_call": True}])
    assert s["schema_version"] == 1
    for section in ("tenants", "tenant_fairness", "queries", "fleet",
                    "robustness", "maintenance", "metrics"):
        assert section in s, section
    assert s["robustness"]["per_tenant"] == {}
    assert s["robustness"]["per_session"] == {}
    assert json.loads(json.dumps(s)) == s
    # metrics section goes through the same record_event mapping the
    # live plane runs — rebuild it independently and compare.
    reg = MetricsRegistry()
    record_event(reg, None, {"kind": "dispatch", "program": "x", "key": "k",
                             "t": 0.0, "dur": 0.01, "barrier": True,
                             "first_call": True})
    assert s["metrics"] == json.loads(json.dumps(metrics_summary(reg)))


def test_summarize_accepts_event_list_file_and_multi_file(tmp_path):
    evs = [{"kind": "dispatch", "program": "p", "key": "a", "t": float(i),
            "dur": 0.01, "barrier": True, "first_call": i == 0}
           for i in range(6)]
    one = tmp_path / "t.jsonl"
    with open(one, "w") as fh:
        for e in evs:
            fh.write(json.dumps(e) + "\n")
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    with open(a, "w") as fh:
        for e in evs[:3]:
            fh.write(json.dumps(e) + "\n")
    with open(b, "w") as fh:
        for e in evs[3:]:
            fh.write(json.dumps(e) + "\n")
    want = summarize(evs)
    assert summarize(str(one)) == want
    assert summarize([str(a), str(b)]) == want


def test_tracer_rotation_and_report_reads_rotated_files(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path, max_bytes=512, keep=32,
                detector=RecompileDetector())
    for i in range(40):
        tr.emit("dispatch", program="p", key="k", dur=0.001,
                barrier=True, first_call=i == 0, recompile=False)
    tr.close()
    assert tr.rotations >= 1
    rotated = sorted((p for p in os.listdir(tmp_path)
                      if p.startswith("trace.jsonl.")),
                     key=lambda p: int(p.rsplit(".", 1)[1]), reverse=True)
    assert rotated
    files = [str(tmp_path / p) for p in rotated] + [path]
    s = summarize(files)
    assert s["dispatches"] == 40          # keep high enough: none dropped
    assert s == summarize(tr.events)


def test_tracer_rotation_caps_file_count(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tr = Tracer(path, max_bytes=256, keep=2, detector=RecompileDetector())
    for _ in range(60):
        tr.emit("span", name="x", dur=0.001)
    tr.close()
    names = sorted(os.listdir(tmp_path))
    assert names == ["t.jsonl", "t.jsonl.1", "t.jsonl.2"]


def test_live_metrics_registered_in_store():
    from dfm_tpu.obs import store
    for k in ("fleet_slo_burn_rate", "flight_dumps"):
        assert k in store._BENCH_NUMERIC_KEYS
        assert store.lower_is_better(k)
        assert store.noise_floor(k) > 0


def test_ledger_snapshot_roundtrip():
    led = Ledger()
    r = led.row("s0", "t0")
    r["queries"] += 2
    r["device_ms"] += 12.5
    r["pad_waste_sum"] += 0.2
    r["pad_waste_n"] += 1
    led2 = Ledger.from_snapshot(json.loads(json.dumps(led.snapshot())))
    assert led2.accounting() == led.accounting()
    acct = led2.accounting("s0")
    assert acct["t0"]["queries"] == 2
    assert acct["t0"]["pad_waste_frac"] == pytest.approx(0.2)
    assert led2.accounting("nope") == {}

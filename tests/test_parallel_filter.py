"""Parallel-in-time filter/smoother == sequential (SURVEY.md section 4.2.5).

Covers both scan implementations (work-efficient blocked scan and
lax.associative_scan), masked and unmasked, divisible and non-divisible T,
plus EM-through-pit equivalence and the blocked_scan utility itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.estim.em import EMConfig, em_fit
from dfm_tpu.ops.scan import blocked_scan
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.parallel_filter import pit_filter, pit_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(51)
    p = dgp.dfm_params(33, 3, rng)
    Y, _ = dgp.simulate(p, 90, rng)
    return p, Y


def test_blocked_scan_matches_cumulative_matmul():
    rng = np.random.default_rng(52)
    Ms = jnp.asarray(rng.standard_normal((23, 3, 3)) * 0.5)
    ref = jax.lax.associative_scan(lambda a, b: a @ b, Ms)
    for bs in (1, 4, 5, 23, 40):
        out = blocked_scan(lambda a, b: a @ b, Ms, block_size=bs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-10, err_msg=f"bs={bs}")
    rev_ref = jax.lax.associative_scan(lambda a, b: a @ b, Ms, reverse=True)
    out = blocked_scan(lambda a, b: a @ b, Ms, block_size=5, reverse=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(rev_ref),
                               atol=1e-10)


@pytest.mark.parametrize("impl", ["blocked", "associative"])
@pytest.mark.parametrize("masked", [False, True])
def test_pit_filter_matches_sequential(setup, impl, masked):
    p, Y = setup
    pj = JP.from_numpy(p, jnp.float64)
    mask = None
    if masked:
        rng = np.random.default_rng(53)
        W = dgp.random_mask(*Y.shape, rng, 0.3)
        W[5] = 0.0
        mask = jnp.asarray(W)
    kf_s = info_filter(jnp.asarray(Y), pj, mask=mask)
    kf_p = pit_filter(jnp.asarray(Y), pj, mask=mask, scan_impl=impl)
    assert abs(float(kf_p.loglik) - float(kf_s.loglik)) < 1e-7 * abs(
        float(kf_s.loglik))
    np.testing.assert_allclose(np.asarray(kf_p.x_filt),
                               np.asarray(kf_s.x_filt), atol=1e-9)
    np.testing.assert_allclose(np.asarray(kf_p.P_filt),
                               np.asarray(kf_s.P_filt), atol=1e-9)
    sm_s = rts_smoother(kf_s, pj)
    sm_p = pit_smoother(kf_p, pj, scan_impl=impl)
    np.testing.assert_allclose(np.asarray(sm_p.x_sm),
                               np.asarray(sm_s.x_sm), atol=1e-8)
    np.testing.assert_allclose(np.asarray(sm_p.P_lag),
                               np.asarray(sm_s.P_lag), atol=1e-8)


def test_pit_non_divisible_lengths(setup):
    p, _ = setup
    rng = np.random.default_rng(54)
    for T in (7, 29, 97):
        Y, _ = dgp.simulate(p, T, rng)
        pj = JP.from_numpy(p, jnp.float64)
        kf_s = info_filter(jnp.asarray(Y), pj)
        kf_p = pit_filter(jnp.asarray(Y), pj)
        assert abs(float(kf_p.loglik) - float(kf_s.loglik)) < 1e-9 * abs(
            float(kf_s.loglik)), T


def test_em_with_pit_matches_info(setup):
    p, Y = setup
    from dfm_tpu.backends import cpu_ref
    p0 = cpu_ref.pca_init((Y - Y.mean(0)) / Y.std(0), 3)
    Yz = jnp.asarray((Y - Y.mean(0)) / Y.std(0))
    pj = JP.from_numpy(p0, jnp.float64)
    _, lls_i, _, _ = em_fit(Yz, pj, max_iters=5, cfg=EMConfig(filter="info"))
    _, lls_p, _, _ = em_fit(Yz, pj, max_iters=5, cfg=EMConfig(filter="pit"))
    np.testing.assert_allclose(np.asarray(lls_p), np.asarray(lls_i),
                               rtol=1e-9)

"""Driver-contract and bench-harness surface tests.

Covers: the CPU info-form filter golden (the bench baseline algorithm), the
bench config presets (BASELINE.json:6-12), and the __graft_entry__ contract
(single-chip jittable entry + multi-chip dry run on the fake CPU mesh).
"""

import sys

import jax
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.utils import dgp


def test_cpu_info_filter_matches_dense():
    rng = np.random.default_rng(11)
    p = dgp.dfm_params(29, 3, rng)
    Y, _ = dgp.simulate(p, 50, rng)
    kf_d = cpu_ref.kalman_filter(Y, p)
    kf_i = cpu_ref.kalman_filter_info(Y, p)
    assert abs(kf_d.loglik - kf_i.loglik) < 1e-8 * abs(kf_d.loglik)
    np.testing.assert_allclose(kf_i.x_filt, kf_d.x_filt, atol=1e-8)
    np.testing.assert_allclose(kf_i.P_filt, kf_d.P_filt, atol=1e-8)


def test_cpu_info_filter_matches_dense_masked():
    rng = np.random.default_rng(12)
    p = dgp.dfm_params(29, 3, rng)
    Y, _ = dgp.simulate(p, 50, rng)
    W = dgp.random_mask(50, 29, rng, 0.3)
    W[7] = 0.0
    kf_d = cpu_ref.kalman_filter(Y, p, mask=W)
    kf_i = cpu_ref.kalman_filter_info(Y, p, mask=W)
    assert abs(kf_d.loglik - kf_i.loglik) < 1e-8 * abs(kf_d.loglik)
    np.testing.assert_allclose(kf_i.x_filt, kf_d.x_filt, atol=1e-8)


def test_cpu_em_step_info_matches_dense():
    rng = np.random.default_rng(13)
    p = dgp.dfm_params(40, 2, rng)
    Y, _ = dgp.simulate(p, 60, rng)
    p0 = cpu_ref.pca_init(Y, 2)
    pd_, lld, _ = cpu_ref.em_step(Y, p0, filter="dense")
    pi_, lli, _ = cpu_ref.em_step(Y, p0, filter="info")
    assert abs(lld - lli) < 1e-8 * abs(lld)
    np.testing.assert_allclose(pi_.Lam, pd_.Lam, atol=1e-8)
    np.testing.assert_allclose(pi_.A, pd_.A, atol=1e-8)


def test_bench_configs_cover_baseline():
    from bench.configs import CONFIGS
    assert set(CONFIGS) >= {"s1", "s2", "s3", "s4", "s5", "headline"}
    s1 = CONFIGS["s1"]
    assert (s1.N, s1.T, s1.k, s1.dynamics) == (50, 200, 2, "static")
    h = CONFIGS["headline"]
    assert (h.N, h.T, h.k) == (10_000, 500, 10)


def test_graft_entry_contract():
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    ll = float(out[0])
    assert np.isfinite(ll)
    ge.dryrun_multichip(min(jax.device_count(), 8))


def _driver_env():
    """Env as the driver sees it: none of conftest's provisioning applies."""
    import os
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "_DFM_DRYRUN_CHILD"):
        env.pop(k, None)
    return env


def _run_driver_style(code):
    import os
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-c", code], cwd=repo, env=_driver_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=900)  # > the 600s inner dryrun subprocess timeout


def test_batched_bench_prints_one_json_line(tmp_path):
    """bench.batched must keep the bench contract: exactly ONE JSON line
    on stdout (diagnostics on stderr), smoke-sized via DFM_BENCH_* — with
    DFM_TRACE set, the trace goes to the FILE and the JSON line gains
    telemetry counts that agree with it."""
    import json
    import os
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace = tmp_path / "bench_batched.jsonl"
    runs = tmp_path / "runs"
    env = _driver_env()
    env.update({"JAX_PLATFORMS": "cpu", "DFM_BENCH_B": "1,2",
                "DFM_BENCH_N": "10", "DFM_BENCH_T": "30",
                "DFM_BENCH_K": "2", "DFM_BENCH_ITERS": "3",
                "DFM_TRACE": str(trace), "DFM_RUNS": str(runs)})
    proc = subprocess.run(
        [sys.executable, "-m", "bench.batched"], cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["unit"] == "iters/sec"
    assert out["value"] > 0
    assert set(out["sweep"]) == {"1", "2"}
    # Telemetry fields (ISSUE 3 satellite): counts in the JSON line must
    # reproduce from the JSONL trace the run left behind.
    assert out["dispatches"] > 0
    assert out["recompiles"] >= 0
    events = [json.loads(ln) for ln in
              trace.read_text().splitlines() if ln.strip()]
    n_disp = sum(1 for e in events if e.get("kind") == "dispatch")
    assert n_disp == out["dispatches"]
    # Perf-observatory contract (ISSUE 4): the line carries a run_id and
    # the run landed in the DFM_RUNS registry under that id.
    from dfm_tpu.obs.store import RunStore
    (rec,) = RunStore(str(runs)).load()
    assert rec["run_id"] == out["run_id"]
    assert rec["metrics"][out["metric"]] == out["value"]


def test_headline_bench_prints_one_json_line_with_telemetry(tmp_path):
    """Smoke-size bench.py keeps the one-JSON-line contract and reports
    dispatch/recompile counts that agree with the DFM_TRACE file."""
    import json
    import os
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace = tmp_path / "bench_headline.jsonl"
    runs = tmp_path / "runs"
    env = _driver_env()
    env.update({"JAX_PLATFORMS": "cpu", "DFM_BENCH_N": "20",
                "DFM_BENCH_T": "30", "DFM_BENCH_K": "2",
                "DFM_BENCH_ITERS": "3", "DFM_BENCH_CPU_TIMING_ITERS": "1",
                "DFM_BENCH_CPU_CHECK_ITERS": "3", "DFM_TRACE": str(trace),
                "DFM_RUNS": str(runs)})
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["unit"] == "iters/sec"
    # Two fused lengths per label make >= 1 recompile unavoidable — the
    # field exists to catch UNEXPECTED churn in longitudinal runs.
    assert out["dispatches"] > 0
    assert out["recompiles"] >= 1
    # Dispatch-free fused-fit metrics (ISSUE 6 satellite): the warm fused
    # refit rate and its own dispatch count (one while-loop program + the
    # cache-consuming smooth read => <= 2).
    assert out["e2e_fused_fit_iters_per_sec"] > 0
    assert out["dispatches_per_fit"] is not None
    assert out["dispatches_per_fit"] <= 2
    events = [json.loads(ln) for ln in
              trace.read_text().splitlines() if ln.strip()]
    n_disp = sum(1 for e in events if e.get("kind") == "dispatch")
    assert n_disp == out["dispatches"]
    # run_id + registry append (ISSUE 4), and the recorded run passes the
    # regression gate against itself-in-history trivially (nothing gated
    # on the first same-fingerprint run).  Since ISSUE 7 the bench also
    # seeds the advisor's calibration set: one profile record per variant
    # rides along in the same registry.
    from dfm_tpu.obs.store import RunStore
    recs = RunStore(str(runs)).load()
    (rec,) = [r for r in recs if r["kind"] == "bench"]
    assert rec["run_id"] == out["run_id"]
    profiles = [r for r in recs if r["kind"] == "profile"]
    assert {p["config"]["profile"] for p in profiles} == \
        {"chunked", "pipelined", "fused"}
    # ... which is exactly what lets the in-bench fit(auto=True) produce
    # a calibrated advice line (ISSUE 7 satellite).
    assert out["advice_rel_err"] is not None
    assert out["p99_dispatch_ms"] is not None
    gate = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.regress", out["run_id"]],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=120)
    assert gate.returncode == 0, gate.stdout + gate.stderr


def test_longt_bench_prints_one_json_line(tmp_path):
    """bench.longt (ported onto bench/_common.py, ISSUE 14 satellite)
    keeps the contract: ONE JSON line, speedup keys for every swept T,
    a run_id that round-trips through the DFM_RUNS registry, and a clean
    regression gate."""
    import json
    import subprocess
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runs = tmp_path / "runs"
    env = _driver_env()
    env.update({"JAX_PLATFORMS": "cpu", "DFM_BENCH_N": "8",
                "DFM_BENCH_K": "2", "DFM_BENCH_TSWEEP": "24,32",
                "DFM_BENCH_ITERS": "2", "DFM_BENCH_REPS": "1",
                "DFM_RUNS": str(runs)})
    proc = subprocess.run(
        [sys.executable, "-m", "bench.longt"], cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["unit"] == "x"
    assert out["pit_qr_speedup_t24"] > 0
    assert out["pit_qr_speedup_t32"] > 0
    assert out["pit_qr_noise_ratio"] >= 0
    from dfm_tpu.obs.store import RunStore
    (rec,) = RunStore(str(runs)).load()
    assert rec["run_id"] == out["run_id"]
    assert rec["kind"] == "bench_longt"
    gate = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.regress", out["run_id"]],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=120)
    assert gate.returncode == 0, gate.stdout + gate.stderr


def test_stream_bench_prints_one_json_line(tmp_path):
    """bench.stream (ISSUE 14): ONE JSON line carrying the ring-soak and
    tiering metrics, zero recompiles after warmup, a registry round-trip
    under kind="bench_stream", and a clean regression gate."""
    import json
    import subprocess
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runs = tmp_path / "runs"
    env = _driver_env()
    env.update({"JAX_PLATFORMS": "cpu", "DFM_BENCH_N": "10",
                "DFM_BENCH_K": "2", "DFM_BENCH_STREAM_CAPACITY": "40",
                "DFM_BENCH_QUERIES": "6", "DFM_BENCH_ROWS": "2",
                "DFM_BENCH_SERVE_ITERS": "3", "DFM_BENCH_ITERS": "6",
                "DFM_BENCH_STREAM_TENANTS": "4",
                "DFM_BENCH_STREAM_RESIDENT": "2",
                "DFM_RUNS": str(runs)})
    proc = subprocess.run(
        [sys.executable, "-m", "bench.stream"], cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["unit"] == "queries/sec"
    assert out["stream_qps"] > 0 and out["stream_p99_ms"] > 0
    # The soak runs at a FULL panel: every query evicts exactly `rows`,
    # on the ONE warm executable with <= 1 blocking d2h per query.
    assert out["evictions_per_query"] == out["rows_per_query"]
    assert out["recompiles_after_warmup"] == 0
    assert out["stream_blocking_transfers_per_query"] <= 1
    assert out["readmission_ms"] >= 0 and out["tiering_page_ins"] > 0
    # The traced cold fit records its own run too (DFM_RUNS is set) —
    # the bench line is the one bench_stream record.
    from dfm_tpu.obs.store import RunStore
    recs = RunStore(str(runs)).load()
    (rec,) = [r for r in recs if r["kind"] == "bench_stream"]
    assert rec["run_id"] == out["run_id"]
    gate = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.regress", out["run_id"]],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=120)
    assert gate.returncode == 0, gate.stdout + gate.stderr


def test_dryrun_multichip_driver_context():
    """The VERDICT r1 failure: plain import + dryrun, no conftest, no env.

    dryrun_multichip must self-provision the 8-device CPU topology.
    """
    proc = _run_driver_style(
        "import __graft_entry__ as g; g.dryrun_multichip(8)")
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "dryrun_multichip(8): ok" in proc.stdout


def test_dryrun_multichip_after_backend_init():
    """Backend already initialized with too few devices -> subprocess path."""
    proc = _run_driver_style(
        "import jax; jax.config.update('jax_platforms','cpu'); jax.devices();"
        "import __graft_entry__ as g; g.dryrun_multichip(8)")
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "dryrun_multichip(8): ok" in proc.stdout

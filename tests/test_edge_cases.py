"""Edge-case coverage for shapes the batched engine produces (short rolling
windows) plus the one-pass host standardize.

- ``ops.scan.affine_const_prefix``: sequence lengths that are not powers of
  two (the doubling loop's padding logic), n = 0 and n = 1.
- ``ssm.steady``: tau >= T (must fall back to the exact pair), tau <= 0
  (means "no ss horizon" — exact pair, never a zero-length scan), T == 1,
  and ``auto_tau`` staying inside its [lo, hi] bucket range.
- ``utils.data.standardize_onepass``: equivalence with the two-pass f64
  path, direct f32 emission, and the ``api.fit`` gate.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import dfm_tpu.api as api
from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.ops.scan import affine_const_prefix
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.params import SSMParams
from dfm_tpu.ssm.steady import auto_tau, ss_filter_smoother
from dfm_tpu.utils import dgp
from dfm_tpu.utils.data import standardize, standardize_onepass


# ---------------------------------------------------------------------------
# affine_const_prefix
# ---------------------------------------------------------------------------

def _naive_affine(M, d, x0):
    xs, x = [], x0
    for t in range(d.shape[0]):
        x = M @ x + d[t]
        xs.append(x.copy())
    return (np.stack(xs) if xs
            else np.zeros((0,) + x0.shape, dtype=x0.dtype))


@pytest.mark.parametrize("n", [0, 1, 2, 3, 5, 12, 17])
def test_affine_const_prefix_matches_naive(n):
    """Lengths straddling/between powers of two — the doubling rounds must
    window correctly when n + 1 is not a power of two (and n = 0 must
    return an empty stack, n = 1 a single exact step)."""
    rng = np.random.default_rng(5)
    k = 3
    M = 0.5 * rng.standard_normal((k, k)) / np.sqrt(k)   # contraction
    d = rng.standard_normal((n, k))
    x0 = rng.standard_normal(k)
    out = np.asarray(affine_const_prefix(
        jnp.asarray(M), jnp.asarray(d), jnp.asarray(x0)))
    ref = _naive_affine(M, d, x0)
    assert out.shape == (n, k)
    np.testing.assert_allclose(out, ref, rtol=1e-10, atol=1e-12)


# ---------------------------------------------------------------------------
# steady-state fallbacks and auto_tau
# ---------------------------------------------------------------------------

def _exact_pair(Yj, pj):
    kf = info_filter(Yj, pj)
    return kf, rts_smoother(kf, pj)


@pytest.mark.parametrize("tau", [0, -3, 50, 200])
def test_ss_fallback_degenerate_tau_and_short_T(tau):
    """tau <= 0 and tau >= T (T <= 2 tau + 4) must route to the exact
    sequential pair bit-for-bit — no frozen-at-the-prior approximation."""
    rng = np.random.default_rng(6)
    p = dgp.dfm_params(8, 2, rng)
    Y, _ = dgp.simulate(p, 50, rng)
    pj = SSMParams.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y, jnp.float64)
    kf, sm, delta = ss_filter_smoother(Yj, pj, tau=tau)
    kfe, sme = _exact_pair(Yj, pj)
    assert float(delta) == 0.0
    np.testing.assert_allclose(float(kf.loglik), float(kfe.loglik),
                               rtol=1e-12)
    np.testing.assert_allclose(np.asarray(sm.x_sm), np.asarray(sme.x_sm),
                               rtol=1e-10, atol=1e-12)


def test_ss_single_step_panel():
    """T == 1: the shortest window the rolling evaluator can produce."""
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(6, 2, rng)
    Y, _ = dgp.simulate(p, 1, rng)
    pj = SSMParams.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y, jnp.float64)
    kf, sm, delta = ss_filter_smoother(Yj, pj, tau=8)
    kfe, sme = _exact_pair(Yj, pj)
    assert sm.x_sm.shape == (1, 2) and sm.P_sm.shape == (1, 2, 2)
    np.testing.assert_allclose(float(kf.loglik), float(kfe.loglik),
                               rtol=1e-12)


def test_ss_non_power_of_two_T():
    """The ss path itself (not the fallback) at a T where T - tau is not a
    power of two — exercises the doubling windows inside the engine."""
    rng = np.random.default_rng(8)
    p = dgp.dfm_params(10, 2, rng, spectral_radius=0.6)
    Y, _ = dgp.simulate(p, 137, rng)
    pj = SSMParams.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y, jnp.float64)
    kf, sm, _ = ss_filter_smoother(Yj, pj, tau=48)
    kfe, sme = _exact_pair(Yj, pj)
    np.testing.assert_allclose(float(kf.loglik), float(kfe.loglik),
                               rtol=1e-8)
    np.testing.assert_allclose(np.asarray(sm.x_sm), np.asarray(sme.x_sm),
                               rtol=1e-6, atol=1e-8)


def test_auto_tau_stays_in_bucket_range():
    rng = np.random.default_rng(9)
    fast = dgp.dfm_params(10, 2, rng, spectral_radius=0.3)
    slow = dgp.dfm_params(10, 2, rng, spectral_radius=0.98)
    lo, hi = 8, 192
    t_fast = auto_tau(fast, lo=lo, hi=hi)
    t_slow = auto_tau(slow, lo=lo, hi=hi)
    for t in (t_fast, t_slow):
        assert lo <= t <= hi
    assert t_fast <= t_slow


# ---------------------------------------------------------------------------
# one-pass standardize
# ---------------------------------------------------------------------------

def test_standardize_onepass_matches_two_pass_f64():
    rng = np.random.default_rng(10)
    Y = rng.standard_normal((300, 40)) * 3.0 + 7.0
    Z1, s1 = standardize(Y)
    Z2, s2 = standardize_onepass(Y)
    assert Z2.dtype == np.float64
    np.testing.assert_allclose(Z1, Z2, rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(s1.mean, s2.mean, rtol=1e-12)
    np.testing.assert_allclose(s1.scale, s2.scale, rtol=1e-12)


def test_standardize_onepass_emits_f32_directly():
    rng = np.random.default_rng(11)
    Y = rng.standard_normal((200, 30)) * 2.0 - 4.0
    Z64, _ = standardize(Y)
    Z32, s32 = standardize_onepass(Y, out_dtype=np.float32)
    assert Z32.dtype == np.float32
    # Stats still accumulate in f64; only the output write is f32.
    assert s32.mean.dtype == np.float64
    np.testing.assert_allclose(Z32, Z64.astype(np.float32),
                               rtol=2e-6, atol=2e-6)


def test_fit_onepass_gate_equivalence(monkeypatch):
    """Lower the size gate so fit() takes the one-pass path and check the
    fit is unchanged vs the two-pass route."""
    rng = np.random.default_rng(12)
    p = dgp.dfm_params(15, 2, rng, noise_scale=0.5)
    Y, _ = dgp.simulate(p, 80, rng)
    Y = Y + 3.0                       # nonzero mean so standardize matters
    model = DynamicFactorModel(n_factors=2)
    monkeypatch.setattr(api, "_ONEPASS_MIN_SIZE", 0)
    r1 = fit(model, Y, backend="cpu", max_iters=8)
    monkeypatch.setattr(api, "_ONEPASS_MIN_SIZE", 10 ** 12)
    r2 = fit(model, Y, backend="cpu", max_iters=8)
    np.testing.assert_allclose(r1.logliks, r2.logliks, rtol=1e-8)
    np.testing.assert_allclose(r1.params.Lam, r2.params.Lam,
                               rtol=1e-7, atol=1e-9)

"""M0 golden tests for the NumPy CPU reference backend.

The reference package could not be mounted (SURVEY.md section 0), so
correctness is pinned to first principles: a brute-force joint-Gaussian oracle
(the whole linear-Gaussian model stacked into one multivariate normal) must
agree with the filter/smoother/log-likelihood exactly, plus the invariant suite
of SURVEY.md section 4.2.
"""

import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref as cr
from dfm_tpu.utils import dgp


def brute_force_gaussian(Y, p, mask=None):
    """Joint-Gaussian oracle: stack f_1..f_T and observed y entries into one
    normal; return (loglik, cond mean (T,k), cond cov (Tk,Tk))."""
    T, N = Y.shape
    k = p.n_factors
    # State means and covariances.
    mu = np.zeros((T, k))
    mu[0] = p.mu0
    Sig = np.zeros((T, k, k))
    Sig[0] = p.P0
    for t in range(1, T):
        mu[t] = p.A @ mu[t - 1]
        Sig[t] = p.A @ Sig[t - 1] @ p.A.T + p.Q
    # Cov(f_s, f_t), s <= t: Sig[s] @ (A^(t-s))'.
    C = np.zeros((T * k, T * k))
    for s in range(T):
        Apow = np.eye(k)
        for t in range(s, T):
            blk = Sig[s] @ Apow.T
            C[s * k:(s + 1) * k, t * k:(t + 1) * k] = blk
            C[t * k:(t + 1) * k, s * k:(s + 1) * k] = blk.T
            Apow = p.A @ Apow
    mu_f = mu.reshape(-1)
    # Observation selector.
    obs_idx = []
    for t in range(T):
        for i in range(N):
            if mask is None or mask[t, i] > 0:
                obs_idx.append((t, i))
    m = len(obs_idx)
    H = np.zeros((m, T * k))
    r = np.zeros(m)
    y = np.zeros(m)
    for j, (t, i) in enumerate(obs_idx):
        H[j, t * k:(t + 1) * k] = p.Lam[i]
        r[j] = p.R[i]
        y[j] = Y[t, i]
    S = H @ C @ H.T + np.diag(r)
    mu_y = H @ mu_f
    v = y - mu_y
    Sinv_v = np.linalg.solve(S, v)
    sign, logdet = np.linalg.slogdet(S)
    loglik = -0.5 * (m * np.log(2 * np.pi) + logdet + v @ Sinv_v)
    G = C @ H.T
    cond_mean = mu_f + G @ Sinv_v
    cond_cov = C - G @ np.linalg.solve(S, G.T)
    return loglik, cond_mean.reshape(T, k), cond_cov


@pytest.fixture(scope="module")
def small_problem():
    rng = np.random.default_rng(0)
    p = dgp.dfm_params(N=4, k=2, rng=rng)
    Y, F = dgp.simulate(p, T=12, rng=rng)
    return Y, F, p


def test_filter_loglik_matches_bruteforce(small_problem):
    Y, _, p = small_problem
    kf = cr.kalman_filter(Y, p)
    ll, _, _ = brute_force_gaussian(Y, p)
    assert kf.loglik == pytest.approx(ll, rel=1e-10)


def test_smoother_matches_bruteforce(small_problem):
    Y, _, p = small_problem
    T, k = 12, 2
    kf = cr.kalman_filter(Y, p)
    sm = cr.rts_smoother(kf, p)
    _, cond_mean, cond_cov = brute_force_gaussian(Y, p)
    np.testing.assert_allclose(sm.x_sm, cond_mean, atol=1e-9)
    for t in range(T):
        blk = cond_cov[t * k:(t + 1) * k, t * k:(t + 1) * k]
        np.testing.assert_allclose(sm.P_sm[t], blk, atol=1e-9)
    for t in range(1, T):
        lag = cond_cov[t * k:(t + 1) * k, (t - 1) * k:t * k]
        np.testing.assert_allclose(sm.P_lag[t], lag, atol=1e-9)


def test_masked_matches_bruteforce(small_problem):
    Y, _, p = small_problem
    rng = np.random.default_rng(1)
    mask = dgp.random_mask(12, 4, rng, frac_missing=0.3)
    kf = cr.kalman_filter(Y, p, mask=mask)
    sm = cr.rts_smoother(kf, p)
    ll, cond_mean, _ = brute_force_gaussian(Y, p, mask=mask)
    assert kf.loglik == pytest.approx(ll, rel=1e-10)
    np.testing.assert_allclose(sm.x_sm, cond_mean, atol=1e-9)


def test_full_mask_equals_dense(small_problem):
    Y, _, p = small_problem
    kf_d = cr.kalman_filter(Y, p)
    kf_m = cr.kalman_filter(Y, p, mask=np.ones_like(Y))
    assert kf_m.loglik == pytest.approx(kf_d.loglik, rel=1e-14)
    np.testing.assert_allclose(kf_m.x_filt, kf_d.x_filt, atol=1e-14)


def test_smoother_equals_filter_at_T(small_problem):
    Y, _, p = small_problem
    kf = cr.kalman_filter(Y, p)
    sm = cr.rts_smoother(kf, p)
    np.testing.assert_allclose(sm.x_sm[-1], kf.x_filt[-1], atol=1e-14)
    np.testing.assert_allclose(sm.P_sm[-1], kf.P_filt[-1], atol=1e-14)


def test_identity_model_reproduces_data():
    # R -> 0, Lam = I, k = N: filtered state must equal the data.
    rng = np.random.default_rng(2)
    N = k = 3
    p = cr.SSMParams(Lam=np.eye(N), A=0.5 * np.eye(k), Q=np.eye(k),
                     R=1e-10 * np.ones(N), mu0=np.zeros(k), P0=np.eye(k))
    Y, _ = dgp.simulate(p, T=10, rng=rng)
    kf = cr.kalman_filter(Y, p)
    np.testing.assert_allclose(kf.x_filt, Y, atol=1e-6)


def test_filter_covariances_psd(small_problem):
    Y, _, p = small_problem
    kf = cr.kalman_filter(Y, p)
    for P in kf.P_filt:
        np.testing.assert_allclose(P, P.T, atol=1e-12)
        assert np.linalg.eigvalsh(P).min() > -1e-12


def test_em_monotone_loglik(small_problem):
    Y, _, p_true = small_problem
    rng = np.random.default_rng(3)
    p0 = dgp.dfm_params(N=4, k=2, rng=rng)  # wrong params on purpose
    _, lls, _ = cr.em_fit(Y, p0, max_iters=30, tol=0.0)
    assert np.all(np.diff(lls) >= -1e-8), f"EM loglik not monotone: {lls}"


def test_em_monotone_loglik_masked():
    rng = np.random.default_rng(4)
    p_true = dgp.dfm_params(N=6, k=2, rng=rng)
    Y, _ = dgp.simulate(p_true, T=40, rng=rng)
    mask = dgp.random_mask(40, 6, rng, frac_missing=0.2)
    p0 = dgp.dfm_params(N=6, k=2, rng=np.random.default_rng(5))
    _, lls, _ = cr.em_fit(Y, p0, mask=mask, max_iters=25, tol=0.0)
    assert np.all(np.diff(lls) >= -1e-8), f"masked EM not monotone: {lls}"


def test_em_static_monotone():
    rng = np.random.default_rng(6)
    p_true = dgp.dfm_params(N=10, k=2, rng=rng, static=True)
    Y, _ = dgp.simulate(p_true, T=60, rng=rng)
    p0 = cr.pca_init(Y, k=2, static=True)
    _, lls, _ = cr.em_fit(Y, p0, max_iters=20, tol=0.0,
                       estimate_A=False, estimate_Q=False)
    assert np.all(np.diff(lls) >= -1e-8)


def test_recovery_pca_em():
    # simulate -> estimate -> recover (SURVEY.md section 4.2.3): smoothed
    # factors must span the truth (canonical correlation, rotation-invariant).
    rng = np.random.default_rng(7)
    p_true = dgp.dfm_params(N=30, k=2, rng=rng, noise_scale=0.3)
    Y, F = dgp.simulate(p_true, T=150, rng=rng)
    p0 = cr.pca_init(Y, k=2)
    p_hat, lls, _ = cr.em_fit(Y, p0, max_iters=30)
    kf = cr.kalman_filter(Y, p_hat)
    sm = cr.rts_smoother(kf, p_hat)
    # Regression R^2 of each true factor on the estimated ones.
    X = sm.x_sm - sm.x_sm.mean(0)
    for j in range(2):
        f = F[:, j] - F[:, j].mean()
        beta = np.linalg.lstsq(X, f, rcond=None)[0]
        r2 = 1 - np.sum((f - X @ beta) ** 2) / np.sum(f ** 2)
        assert r2 > 0.95, f"factor {j} recovery R^2={r2}"
    # EM must also improve on the PCA init.
    assert lls[-1] >= lls[0]


def test_pca_init_static_shapes():
    rng = np.random.default_rng(8)
    p_true = dgp.dfm_params(N=20, k=3, rng=rng)
    Y, _ = dgp.simulate(p_true, T=50, rng=rng)
    p = cr.pca_init(Y, k=3)
    assert p.Lam.shape == (20, 3) and p.A.shape == (3, 3)
    assert np.all(p.R > 0)
    assert np.max(np.abs(np.linalg.eigvals(p.A))) < 1.0


def test_forecast_shapes_and_decay():
    rng = np.random.default_rng(9)
    p = dgp.dfm_params(N=5, k=2, rng=rng, spectral_radius=0.5)
    Y, _ = dgp.simulate(p, T=30, rng=rng)
    kf = cr.kalman_filter(Y, p)
    f, y, P = cr.forecast(p, kf.x_filt[-1], kf.P_filt[-1], horizon=20)
    assert f.shape == (20, 2) and y.shape == (20, 5)
    # Stable dynamics: long-horizon forecast decays toward zero mean.
    assert np.linalg.norm(f[-1]) < np.linalg.norm(f[0]) + 1e-9


def test_em_series_never_observed():
    # A series with zero observed entries must not crash the masked M-step;
    # its loading comes out zero.
    rng = np.random.default_rng(10)
    p_true = dgp.dfm_params(N=5, k=2, rng=rng)
    Y, _ = dgp.simulate(p_true, T=30, rng=rng)
    mask = np.ones((30, 5))
    mask[:, 3] = 0.0
    p_new, ll, _ = cr.em_step(Y, p_true, mask=mask)
    assert np.isfinite(ll)
    np.testing.assert_allclose(p_new.Lam[3], 0.0, atol=1e-12)

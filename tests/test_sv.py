"""SV-DFM / Rao-Blackwellized particle filter tests (SURVEY.md section 4.2.6).

Key oracle: in the degenerate limit sigma_h = 0, h0_scale = 0 every particle
carries the same h path, so the RBPF log-likelihood must equal the EXACT
Kalman loglik of the homoskedastic model with Q = diag(exp(h_center)) — a
whole-pipeline equality, not a statistical approximation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.models.sv import SVSpec, sv_filter, sv_fit
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


def test_rbpf_equals_kf_in_linear_gaussian_limit():
    rng = np.random.default_rng(41)
    k = 3
    p = dgp.dfm_params(20, k, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    spec = SVSpec(n_factors=k, n_particles=8, sigma_h=0.0, h0_scale=0.0)
    # h pinned at log diag(Q): the conditional model has Q_t = diag(diag(Q)).
    pj = JP.from_numpy(p, jnp.float64)
    res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(1))
    p_diag = cpu_ref.SSMParams(p.Lam, p.A, np.diag(np.diag(p.Q)), p.R,
                               p.mu0, p.P0)
    ll_kf = cpu_ref.kalman_filter(Y, p_diag).loglik
    assert abs(float(res.loglik) - ll_kf) < 1e-7 * abs(ll_kf)


def test_rbpf_loglik_converges_with_particles():
    """With vol randomness on, the PF loglik estimate should approach the
    exact KF loglik as M grows when the DGP is actually homoskedastic."""
    rng = np.random.default_rng(42)
    k = 2
    p = dgp.dfm_params(15, k, rng)
    Y, _ = dgp.simulate(p, 60, rng)
    p_diag = cpu_ref.SSMParams(p.Lam, p.A, np.diag(np.diag(p.Q)), p.R,
                               p.mu0, p.P0)
    ll_kf = cpu_ref.kalman_filter(Y, p_diag).loglik
    pj = JP.from_numpy(p, jnp.float64)
    errs = []
    for M in (16, 256):
        spec = SVSpec(n_factors=k, n_particles=M, sigma_h=0.03,
                      h0_scale=0.05)
        res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(2))
        errs.append(abs(float(res.loglik) - ll_kf) / abs(ll_kf))
    assert errs[1] < errs[0] + 1e-4, errs
    assert errs[1] < 5e-3, errs


def test_rbpf_deterministic_given_key():
    rng = np.random.default_rng(43)
    p = dgp.dfm_params(10, 2, rng)
    Y, _ = dgp.simulate(p, 40, rng)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=2, n_particles=64, sigma_h=0.1)
    r1 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(7))
    r2 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(7))
    assert float(r1.loglik) == float(r2.loglik)
    r3 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(8))
    assert float(r1.loglik) != float(r3.loglik)


def test_rbpf_tracks_volatility():
    rng = np.random.default_rng(44)
    k = 1
    Y, F, H, p = dgp.simulate_sv(40, 400, k, rng, vol_walk_scale=0.15)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=k, n_particles=512, sigma_h=0.15, h0_scale=0.3)
    res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(3))
    h_est = np.asarray(res.h_mean)[:, 0]
    corr = np.corrcoef(h_est[50:], H[50:, 0])[0, 1]
    assert corr > 0.5, corr
    assert np.all(np.asarray(res.ess) >= 1.0)
    assert int(res.n_resamples) > 0


def test_sv_fit_two_stage_runs():
    rng = np.random.default_rng(45)
    Y, F, H, _ = dgp.simulate_sv(25, 120, 2, rng)
    fitres = sv_fit(Y, SVSpec(n_factors=2, n_particles=128), em_iters=5,
                    backend="cpu", key=jax.random.PRNGKey(4))
    assert np.isfinite(fitres.loglik)
    assert fitres.vol_paths.shape == (120, 2)
    assert np.all(fitres.vol_paths > 0)

"""SV-DFM / Rao-Blackwellized particle filter tests (SURVEY.md section 4.2.6).

Key oracle: in the degenerate limit sigma_h = 0, h0_scale = 0 every particle
carries the same h path, so the RBPF log-likelihood must equal the EXACT
Kalman loglik of the homoskedastic model with Q = diag(exp(h_center)) — a
whole-pipeline equality, not a statistical approximation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.models.sv import SVSpec, sv_filter, sv_fit
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


def test_rbpf_equals_kf_in_linear_gaussian_limit():
    rng = np.random.default_rng(41)
    k = 3
    p = dgp.dfm_params(20, k, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    spec = SVSpec(n_factors=k, n_particles=8, sigma_h=0.0, h0_scale=0.0)
    # h pinned at log diag(Q): the conditional model has Q_t = diag(diag(Q)).
    pj = JP.from_numpy(p, jnp.float64)
    res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(1))
    p_diag = cpu_ref.SSMParams(p.Lam, p.A, np.diag(np.diag(p.Q)), p.R,
                               p.mu0, p.P0)
    ll_kf = cpu_ref.kalman_filter(Y, p_diag).loglik
    assert abs(float(res.loglik) - ll_kf) < 1e-7 * abs(ll_kf)


def test_rbpf_loglik_converges_with_particles():
    """With vol randomness on, the PF loglik estimate should approach the
    exact KF loglik as M grows when the DGP is actually homoskedastic."""
    rng = np.random.default_rng(42)
    k = 2
    p = dgp.dfm_params(15, k, rng)
    Y, _ = dgp.simulate(p, 60, rng)
    p_diag = cpu_ref.SSMParams(p.Lam, p.A, np.diag(np.diag(p.Q)), p.R,
                               p.mu0, p.P0)
    ll_kf = cpu_ref.kalman_filter(Y, p_diag).loglik
    pj = JP.from_numpy(p, jnp.float64)
    errs = []
    for M in (16, 256):
        spec = SVSpec(n_factors=k, n_particles=M, sigma_h=0.03,
                      h0_scale=0.05)
        res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(2))
        errs.append(abs(float(res.loglik) - ll_kf) / abs(ll_kf))
    assert errs[1] < errs[0] + 1e-4, errs
    assert errs[1] < 5e-3, errs


def test_rbpf_deterministic_given_key():
    rng = np.random.default_rng(43)
    p = dgp.dfm_params(10, 2, rng)
    Y, _ = dgp.simulate(p, 40, rng)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=2, n_particles=64, sigma_h=0.1)
    r1 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(7))
    r2 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(7))
    assert float(r1.loglik) == float(r2.loglik)
    r3 = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(8))
    assert float(r1.loglik) != float(r3.loglik)


def test_rbpf_tracks_volatility():
    rng = np.random.default_rng(44)
    k = 1
    Y, F, H, p = dgp.simulate_sv(40, 400, k, rng, vol_walk_scale=0.15)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=k, n_particles=512, sigma_h=0.15, h0_scale=0.3)
    res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(3))
    h_est = np.asarray(res.h_mean)[:, 0]
    corr = np.corrcoef(h_est[50:], H[50:, 0])[0, 1]
    assert corr > 0.5, corr
    assert np.all(np.asarray(res.ess) >= 1.0)
    assert int(res.n_resamples) > 0


def test_sv_fit_two_stage_runs():
    rng = np.random.default_rng(45)
    Y, F, H, _ = dgp.simulate_sv(25, 120, 2, rng)
    fitres = sv_fit(Y, SVSpec(n_factors=2, n_particles=128), em_iters=5,
                    backend="cpu", key=jax.random.PRNGKey(4),
                    estimate_sv=False)
    assert np.isfinite(fitres.loglik)
    assert fitres.vol_paths.shape == (120, 2)
    assert np.all(fitres.vol_paths > 0)
    assert fitres.h_smooth.shape == (120, 2)


def test_sv_fit_recovers_vol_walk_scale():
    """Particle EM re-estimates sigma_h from simulated SV data
    (VERDICT r1 missing item #2): truth 0.15, start at the 0.1 default."""
    rng = np.random.default_rng(46)
    Y, F, H, _ = dgp.simulate_sv(40, 400, 1, rng, vol_walk_scale=0.15)
    fitres = sv_fit(Y, SVSpec(n_factors=1, n_particles=256, sigma_h=0.1,
                              n_smooth_draws=32),
                    em_iters=10, backend="cpu", key=jax.random.PRNGKey(5),
                    sv_iters=12)
    sig = float(fitres.sigma_h[0])
    assert abs(sig - 0.15) / 0.15 < 0.3, sig
    assert fitres.logliks.shape == (13,)  # 12 EM iters + final consistency pass
    # Filter-only baseline would have kept sigma at 0.1 exactly.
    assert sig > 0.12, sig


def test_sv_em_fixed_point_and_direction():
    """Started AT the truth the estimate stays; started 3x high it moves
    down substantially — the EM map's fixed point is the MLE region."""
    rng = np.random.default_rng(47)
    Y, _, _, _ = dgp.simulate_sv(40, 300, 1, rng, vol_walk_scale=0.15)
    common = dict(em_iters=8, backend="cpu", key=jax.random.PRNGKey(6))
    at_truth = sv_fit(Y, SVSpec(n_factors=1, n_particles=192, sigma_h=0.15,
                                n_smooth_draws=32), sv_iters=6, **common)
    assert abs(float(at_truth.sigma_h[0]) - 0.15) / 0.15 < 0.35
    high = sv_fit(Y, SVSpec(n_factors=1, n_particles=192, sigma_h=0.45,
                            n_smooth_draws=32), sv_iters=8, **common)
    assert float(high.sigma_h[0]) < 0.32, float(high.sigma_h[0])


def test_rbpf_f32_loglik_accuracy_at_scale():
    """f32 residual-path loglik vs the exact f64 KF at N=1000 (VERDICT r1
    weak item #4): the cancellation-prone expanded quadratic measured ~1e-3
    here; the residual pass must stay orders of magnitude tighter."""
    rng = np.random.default_rng(48)
    k = 3
    p = dgp.dfm_params(1000, k, rng)
    Y, _ = dgp.simulate(p, 100, rng)
    p_diag = cpu_ref.SSMParams(p.Lam, p.A, np.diag(np.diag(p.Q)), p.R,
                               p.mu0, p.P0)
    ll_kf = cpu_ref.kalman_filter(Y, p_diag).loglik
    spec = SVSpec(n_factors=k, n_particles=8, sigma_h=0.0, h0_scale=0.0)
    r32 = sv_filter(jnp.asarray(Y, jnp.float32),
                    JP.from_numpy(p, jnp.float32), spec,
                    key=jax.random.PRNGKey(1))
    assert abs(float(r32.loglik) - ll_kf) / abs(ll_kf) < 2e-5


def test_sv_filter_no_recompile_on_sigma_sweep():
    """sigma_h/h0_scale are traced: sweeping spec.sigma_h (particle EM,
    likelihood profiling) must reuse one compiled filter."""
    import dataclasses
    from dfm_tpu.models.sv import _sv_filter_impl
    rng = np.random.default_rng(50)
    p = dgp.dfm_params(12, 2, rng)
    Y, _ = dgp.simulate(p, 30, rng)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=2, n_particles=16)
    sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(0))
    n0 = _sv_filter_impl._cache_size()
    for s in (0.05, 0.2, 0.7):
        sv_filter(jnp.asarray(Y), pj, dataclasses.replace(spec, sigma_h=s),
                  key=jax.random.PRNGKey(0))
    assert _sv_filter_impl._cache_size() == n0


def test_sv_fit_sigma_zero_start_no_nan():
    """sigma_h=0 with estimation on must not NaN-poison the fit (the
    log-domain M-step floors sigma instead of dividing by zero)."""
    rng = np.random.default_rng(51)
    Y, _, _, _ = dgp.simulate_sv(20, 80, 1, rng, vol_walk_scale=0.1)
    fitres = sv_fit(Y, SVSpec(n_factors=1, n_particles=64, sigma_h=0.0,
                              n_smooth_draws=16),
                    em_iters=4, backend="cpu", key=jax.random.PRNGKey(8),
                    sv_iters=3)
    assert np.all(np.isfinite(fitres.logliks))
    assert np.isfinite(fitres.sigma_h).all() and fitres.sigma_h[0] >= 1e-4


def test_ffbs_smoother_beats_filter_on_h():
    """Smoothed h should track the true vol path at least as well as the
    filtered mean (it uses future data), and its draws must be finite."""
    from dfm_tpu.models.sv import sv_smooth_h
    rng = np.random.default_rng(49)
    k = 1
    Y, F, H, p = dgp.simulate_sv(40, 400, k, rng, vol_walk_scale=0.15)
    pj = JP.from_numpy(p, jnp.float64)
    spec = SVSpec(n_factors=k, n_particles=512, sigma_h=0.15, h0_scale=0.3)
    res = sv_filter(jnp.asarray(Y), pj, spec, key=jax.random.PRNGKey(3))
    Hs = sv_smooth_h(res, 0.15, jax.random.PRNGKey(4), n_draws=64)
    assert Hs.shape == (400, 64, k)
    assert np.all(np.isfinite(np.asarray(Hs)))
    h_sm = np.asarray(Hs.mean(axis=1))[:, 0]
    h_f = np.asarray(res.h_mean)[:, 0]
    c_sm = np.corrcoef(h_sm[50:], H[50:, 0])[0, 1]
    c_f = np.corrcoef(h_f[50:], H[50:, 0])[0, 1]
    assert c_sm > 0.5, (c_sm, c_f)
    assert c_sm > c_f - 0.05, (c_sm, c_f)

"""Debug (checkify) NaN-guard mode: poisoned inputs raise LOCATED errors
instead of silently propagating NaNs (SURVEY.md section 5, sanitizers row;
VERDICT r2 item 8/"What's missing" 5).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig, em_step, em_fit_scan
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(5)
    p = dgp.dfm_params(24, 2, rng)
    Y, _ = dgp.simulate(p, 40, rng)
    Yz = (Y - Y.mean(0)) / Y.std(0)
    return Yz, cpu_ref.pca_init(Yz, 2)


def test_debug_em_step_raises_on_poisoned_panel(panel):
    Yz, p0 = panel
    Yp = Yz.copy()
    Yp[7, 3] = np.nan            # poison reaching the filter unmasked
    pj = JP.from_numpy(p0, jnp.float64)
    # without debug: the NaN sails through to the loglik silently
    _, ll, _ = em_step(jnp.asarray(Yp), pj, cfg=EMConfig(filter="info"))
    assert not np.isfinite(float(ll))
    # with debug: a located error
    with pytest.raises(Exception, match="(?i)nan"):
        em_step(jnp.asarray(Yp), pj,
                cfg=EMConfig(filter="info", debug=True))


def test_debug_fused_scan_raises_on_poisoned_params(panel):
    Yz, p0 = panel
    bad = p0.copy()
    bad.R = -np.abs(bad.R)       # log R = NaN inside the loglik pieces
    pj = JP.from_numpy(bad, jnp.float64)
    with pytest.raises(Exception, match="(?i)nan"):
        em_fit_scan(jnp.asarray(Yz), pj, 3,
                    cfg=EMConfig(filter="info", debug=True))
    # clean inputs pass through the checked path unharmed
    _, lls, _ = em_fit_scan(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                            3, cfg=EMConfig(filter="info", debug=True))
    assert np.all(np.isfinite(np.asarray(lls)))


def test_fit_debug_flag(panel):
    Yz, p0 = panel
    model = DynamicFactorModel(n_factors=2)
    bad = p0.copy()
    bad.R = -np.abs(bad.R)
    # non-debug: fit completes, returning a garbage (non-finite) loglik
    r = fit(model, Yz, backend=TPUBackend(dtype=jnp.float64), init=bad,
            max_iters=3, tol=0.0)
    assert not np.isfinite(r.loglik)
    # debug: the same poisoned fit raises
    with pytest.raises(Exception, match="(?i)nan"):
        fit(model, Yz, backend=TPUBackend(dtype=jnp.float64), init=bad,
            max_iters=3, tol=0.0, debug=True)


def test_fit_debug_flag_warns_on_cpu_backend(panel):
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=2)
    with pytest.warns(RuntimeWarning, match="no debug"):
        fit(model, Yz, backend="cpu", max_iters=2, debug=True)


def test_fit_debug_does_not_stick_to_user_backend(panel):
    """fit(debug=True) must not leave checkify mode on the caller's
    backend instance (code-review r4)."""
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=2)
    b = TPUBackend(dtype=jnp.float64)
    assert b.debug is False
    fit(model, Yz, backend=b, max_iters=2, debug=True)
    assert b.debug is False


def test_sharded_debug_raises_located_error(panel):
    """ShardedBackend(debug=True): checkify composes with shard_map — a
    poisoned sharded fit raises a located error on the fake mesh, both in
    the fused-chunk and per-iteration drivers (VERDICT r4 item 7)."""
    from dfm_tpu.api import ShardedBackend
    Yz, p0 = panel
    model = DynamicFactorModel(n_factors=2)
    bad = p0.copy()
    bad.R = -np.abs(bad.R)          # log R = NaN inside the loglik pieces
    for chunk in (8, 1):
        b = ShardedBackend(dtype=jnp.float64, n_devices=8,
                           fused_chunk=chunk, debug=True)
        with pytest.raises(Exception, match="(?i)nan"):
            fit(model, Yz, backend=b, init=bad, max_iters=3, tol=0.0)
    # same poisoned fit without debug: sails through to a garbage loglik
    r = fit(model, Yz, init=bad, max_iters=3, tol=0.0,
            backend=ShardedBackend(dtype=jnp.float64, n_devices=8))
    assert not np.isfinite(r.loglik)


def test_sharded_debug_clean_fit_matches_unchecked(panel):
    """Clean inputs pass the checked sharded path unharmed and unchanged."""
    from dfm_tpu.api import ShardedBackend
    Yz, p0 = panel
    model = DynamicFactorModel(n_factors=2)
    r_dbg = fit(model, Yz, init=p0, max_iters=3, tol=0.0,
                backend=ShardedBackend(dtype=jnp.float64, n_devices=8,
                                       debug=True))
    r_ref = fit(model, Yz, init=p0, max_iters=3, tol=0.0,
                backend=ShardedBackend(dtype=jnp.float64, n_devices=8))
    np.testing.assert_allclose(r_dbg.logliks, r_ref.logliks, rtol=1e-12)

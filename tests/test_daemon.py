"""Serving daemon (dfm_tpu/daemon/ — ISSUE 16).

The operative contracts of the socket front door, verified without real
processes (tools/daemon_smoke.sh covers SIGKILL + cross-process
blue/green with real signals):

- DURABILITY: the request journal is fsync'd append-only JSONL with
  monotone seqs that survive reopen; torn tails and mid-file corruption
  are skipped by count, never raised; ``compact`` atomically drops only
  snapshot-covered entries.  Fleet/EM snapshots are tmp+fsync+rename
  atomic (a torn write leaves the OLD snapshot readable) and carry
  ``schema_version`` — a future version is refused with a ValueError
  naming both versions.
- REPLAY PARITY: a daemon answering via ``handle()`` is bit-equal to a
  lone fleet; a crash-simulated restart (abandon without close, recover
  from snapshot + journal) continues bit-equal; duplicate request ids
  answer from cache without touching the fleet.
- OVERLOAD: the bounded queue answers deterministic backpressure with a
  ``retry_after_s`` quoted from the calibrated cost model; under a
  forced SLO burn the lowest-priority class is shed, every shed recorded
  as ``HealthEvent(kind="shed")`` — never silent.
- HANDOFF: a same-process blue/green ``takeover`` moves the listening
  socket without closing it; answers across the swap stay bit-equal and
  the successor records the handoff (gap_ms) for ``obs.report``.
- VALIDATION: ``DaemonConfig`` and ``RobustPolicy`` reject nonsense at
  construction, naming the offending field; flight-recorder dumps to a
  missing/unwritable DFM_FLIGHT_DIR warn ONCE and never raise.
"""

import json
import os
import threading
import warnings
from types import SimpleNamespace

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, fit, open_fleet
from dfm_tpu.api import TPUBackend
from dfm_tpu.backends.cpu_ref import SSMParams
from dfm_tpu.daemon import (DaemonClient, DaemonConfig, DFMDaemon, Journal,
                            make_listener)
from dfm_tpu.daemon.server import _Ticket
from dfm_tpu.obs.live import LivePlane, plane, set_slo
from dfm_tpu.obs.report import summarize
from dfm_tpu.obs.slo import SLOConfig
from dfm_tpu.robust import RobustPolicy
from dfm_tpu.utils import checkpoint as ckpt
from dfm_tpu.utils import dgp

BE = TPUBackend(filter="info")
R = 2                                    # rows per query


# ---------------------------------------------------------------------------
# journal: durability unit tests (no jax)
# ---------------------------------------------------------------------------

def test_journal_seq_roundtrip_and_reopen(tmp_path):
    p = str(tmp_path / "j.jsonl")
    with Journal(p) as j:
        assert j.append({"id": "a", "tenant": "t0"}) == 1
        assert j.append({"id": "b", "tenant": "t1"}) == 2
    # seq resumes across reopen (crash recovery scans the file).
    with Journal(p) as j:
        assert j.last_seq == 2
        assert j.append({"id": "c", "tenant": "t0"}) == 3
    entries = Journal.read(p)
    assert [e["id"] for e in entries] == ["a", "b", "c"]
    assert Journal.read(p, after=1, upto=2) == [entries[1]]


def test_journal_torn_tail_and_corruption_skipped(tmp_path):
    p = str(tmp_path / "j.jsonl")
    with Journal(p) as j:
        for i in range(3):
            j.append({"id": f"q{i}", "tenant": "t0"})
    with open(p, "ab") as f:                 # crash mid-append: torn tail
        f.write(b'{"seq": 4, "id": "torn')
    assert [e["id"] for e in Journal.read(p)] == ["q0", "q1", "q2"]
    # mid-file damage loses ONE entry, not the journal
    lines = open(p, "rb").read().split(b"\n")
    lines[1] = b"\x00garbage\x00"
    open(p, "wb").write(b"\n".join(lines))
    assert [e["id"] for e in Journal.read(p)] == ["q0", "q2"]
    with Journal(p) as j:                    # seq still resumes past damage
        assert j.append({"id": "q3", "tenant": "t0"}) == 4


def test_journal_compact_drops_only_covered_entries(tmp_path):
    p = str(tmp_path / "j.jsonl")
    with Journal(p) as j:
        for i in range(5):
            j.append({"id": f"q{i}", "tenant": "t0"})
        assert j.compact(3) == 2             # keeps seq 4, 5
        assert [e["seq"] for e in j.replay()] == [4, 5]
        assert j.append({"id": "q5", "tenant": "t0"}) == 6  # seq monotone
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


# ---------------------------------------------------------------------------
# snapshots: atomicity + schema versioning (satellites a, b)
# ---------------------------------------------------------------------------

def _params(k=2, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return SSMParams(rng.standard_normal((n, k)), np.eye(k) * 0.5,
                     np.eye(k), np.eye(n), np.zeros(k), np.eye(k))


def test_checkpoint_atomic_under_torn_write(tmp_path, monkeypatch):
    path = str(tmp_path / "state.npz")
    ckpt.save_checkpoint(path, _params(seed=1), 3, [0.0, 1.0])
    before = ckpt.load_checkpoint(path)
    assert before is not None and before[1] == 3

    def torn(src, dst):
        raise OSError("simulated crash before rename")
    monkeypatch.setattr(ckpt.os, "replace", torn)
    with pytest.raises(OSError):
        ckpt.save_checkpoint(path, _params(seed=2), 9, [2.0])
    monkeypatch.undo()
    # The interrupted write left the OLD snapshot intact and no tmp junk.
    after = ckpt.load_checkpoint(path)
    assert after is not None and after[1] == 3
    np.testing.assert_array_equal(after[0].Lam, before[0].Lam)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


def test_snapshot_schema_future_version_refused(tmp_path):
    # Unit level: the checker names BOTH versions in the error.
    bad = {"schema_version": np.asarray(ckpt.SNAPSHOT_SCHEMA_VERSION + 41)}
    with pytest.raises(ValueError) as ei:
        ckpt.check_schema_version(bad, "x.npz")
    msg = str(ei.value)
    assert f"schema_version={ckpt.SNAPSHOT_SCHEMA_VERSION + 41}" in msg
    assert f"schema_version<={ckpt.SNAPSHOT_SCHEMA_VERSION}" in msg
    # File level: a future-version npz refuses through load_checkpoint
    # (which swallows mere corruption — the refusal must NOT be eaten).
    path = str(tmp_path / "future.npz")
    ckpt.save_checkpoint(path, _params(), 1, [0.0])
    with np.load(path) as z:
        arrays = dict(z)
    arrays["schema_version"] = np.asarray(ckpt.SNAPSHOT_SCHEMA_VERSION + 1)
    np.savez(path, **arrays)
    with pytest.raises(ValueError, match="schema_version"):
        ckpt.load_checkpoint(path)
    # Pre-versioning files (no stamp) stay accepted.
    arrays.pop("schema_version")
    np.savez(path, **arrays)
    assert ckpt.load_checkpoint(path) is not None


# ---------------------------------------------------------------------------
# construction-time validation (satellite c) + flight dumps (satellite d)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,field", [
    (dict(dispatch_retries=-1), "dispatch_retries"),
    (dict(backoff_factor=0.5), "backoff_factor"),
    (dict(dispatch_deadline_s=0.0), "dispatch_deadline_s"),
    (dict(on_failure="explode"), "on_failure"),
])
def test_robust_policy_validation_names_field(kw, field):
    with pytest.raises(ValueError, match=f"RobustPolicy.{field}"):
        RobustPolicy(**kw)


@pytest.mark.parametrize("kw,field", [
    (dict(queue_max=0), "queue_max"),
    (dict(work_max_s=0.0), "work_max_s"),
    (dict(tick_requests=0), "tick_requests"),
    (dict(snapshot_every=-1), "snapshot_every"),
    (dict(retry_after_floor_s=0.0), "retry_after_floor_s"),
    (dict(request_timeout_s=0.0), "request_timeout_s"),
])
def test_daemon_config_validation_names_field(kw, field):
    with pytest.raises(ValueError, match=f"DaemonConfig.{field}"):
        DaemonConfig(**kw)


def test_flight_dump_unwritable_dir_warns_once_never_raises(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("plain file")
    lp = LivePlane(flight_dir=str(blocker / "sub"))   # makedirs must fail
    lp.ring.append({"kind": "query", "wall": 0.001})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert lp.dump_flight() is None               # no raise
        assert lp.dump_flight() is None               # warn ONCE
    assert len(w) == 1
    assert "flight-recorder" in str(w[0].message)
    assert lp.flight_dumps == 0 and lp.errors >= 1


# ---------------------------------------------------------------------------
# observability: the obs.report daemon section (no jax state needed)
# ---------------------------------------------------------------------------

def test_report_daemon_section(tmp_path):
    tr = str(tmp_path / "trace.jsonl")
    evs = [
        {"kind": "daemon", "action": "request", "tenant": "t0", "depth": 1},
        {"kind": "daemon", "action": "request", "tenant": "t0", "depth": 3},
        {"kind": "daemon", "action": "backpressure", "tenant": "t1",
         "depth": 8, "retry_after_s": 0.4},
        {"kind": "daemon", "action": "snapshot", "journal_seq": 7},
        {"kind": "daemon", "action": "replay", "n_entries": 5},
        {"kind": "daemon", "action": "handoff", "role": "successor",
         "gap_ms": 12.5},
        {"kind": "health", "event": "shed", "action": "rejected",
         "tenant": "t1", "chunk": -1, "iteration": 0, "detail": "",
         "engine": "daemon"},
    ]
    with open(tr, "w") as f:
        for i, e in enumerate(evs):
            f.write(json.dumps(dict(t=float(i), **e)) + "\n")
    dm = summarize(tr)["daemon"]
    assert dm["n_requests"] == 2
    assert dm["n_backpressure"] == 1
    assert dm["n_shed"] == 1
    assert dm["n_snapshots"] == 1
    assert dm["n_replays"] == 1 and dm["n_replayed_entries"] == 5
    assert dm["n_handoffs"] == 1
    assert dm["handoff_gap_ms"]["p99"] == pytest.approx(12.5)
    assert dm["queue_depth"]["p50"] == pytest.approx(3.0)
    assert dm["per_tenant"]["t1"]["backpressure"] == 1
    assert dm["per_tenant"]["t1"]["shed"] == 1
    # Empty traces keep the section with stable keys (dashboards).
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    dm0 = summarize(empty)["daemon"]
    assert dm0["n_requests"] == 0 and dm0["n_handoffs"] == 0


# ---------------------------------------------------------------------------
# the daemon over a real (tiny) fleet
# ---------------------------------------------------------------------------

def _mk_tenant(N, T, k, seed, extra):
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T + extra, rng)
    res = fit(DynamicFactorModel(n_factors=k), Y[:T], max_iters=6,
              backend=BE, telemetry=False)
    return res, Y[:T], Y[T:]


@pytest.fixture(scope="module")
def denv(tmp_path_factory):
    """Two tenants, a bootstrap snapshot, and an uninterrupted twin fleet.

    Tests recover their OWN daemon from (snapshot, journal) — every
    served submit is journaled, so a fresh recover always lands on
    exactly the state the shared twin has, independent of test order."""
    work = tmp_path_factory.mktemp("daemon")
    tens = [_mk_tenant(8, 30, 2, 301, 40 * R), _mk_tenant(10, 34, 2, 302,
                                                          40 * R)]
    caps = [t[1].shape[0] + 42 * R for t in tens]
    twin = open_fleet([t[0] for t in tens], [t[1] for t in tens],
                      capacity=caps, max_update_rows=R, max_iters=4,
                      tol=0.0, backend=BE)
    names = list(twin.tenants)
    boot = open_fleet([t[0] for t in tens], [t[1] for t in tens],
                      tenants=names, capacity=caps, max_update_rows=R,
                      max_iters=4, tol=0.0, backend=BE)
    snap = str(work / "snap")
    boot.snapshot_all(snap)
    boot.close()
    env = SimpleNamespace(work=work, tens=tens, twin=twin, names=names,
                          snap=snap, journal=str(work / "journal.jsonl"),
                          cursor=[0] * len(tens), nreq=[0])
    yield env
    twin.close()


def _recover(env, **cfg_kw):
    return DFMDaemon.recover(env.snap, env.journal, backend=BE,
                             config=DaemonConfig(**cfg_kw) if cfg_kw
                             else None)


def _roundtrip(env, daemon, i, where):
    rows = env.tens[i][2][env.cursor[i]:env.cursor[i] + R]
    env.cursor[i] += R
    env.nreq[0] += 1
    rid = f"{where}-{env.nreq[0]}"
    resp = daemon.handle({"op": "submit", "tenant": env.names[i],
                          "rows": rows.tolist(), "id": rid})
    assert resp.get("ok"), (where, resp)
    env.twin.submit(env.names[i], rows)
    upd = env.twin.drain()[env.names[i]][0]
    np.testing.assert_array_equal(np.asarray(resp["nowcast"]),
                                  np.asarray(upd.nowcast), err_msg=where)
    np.testing.assert_array_equal(np.asarray(resp["forecast_y"]),
                                  np.asarray(upd.forecasts["y"]),
                                  err_msg=where)
    return rid, resp


def test_daemon_parity_dedup_and_crash_replay(denv):
    d1 = _recover(denv)
    try:
        rid = None
        for q in range(3):
            i = q % 2
            rid, _ = _roundtrip(denv, d1, i, f"par{q}")
        # Duplicate id: answered from cache, state-neutral (the fleet is
        # NOT re-ticked — the next fresh query still matches the twin).
        dup = d1.handle({"op": "submit", "tenant": denv.names[0],
                         "rows": denv.tens[0][2][:R].tolist(), "id": rid})
        assert dup.get("duplicate") is True
        _roundtrip(denv, d1, 0, "post-dup")
        # Unknown tenants are rejected at admission, not at the fleet.
        bad = d1.handle({"op": "submit", "tenant": "nobody", "rows": None})
        assert not bad["ok"] and "unknown tenant" in bad["error"]
        st = d1.status()
        assert st["n_served"] == 4 and st["journal_seq"] == 4
    finally:
        d1._journal.close()     # crash-sim: abandon WITHOUT fleet close
    # Recover from (bootstrap snapshot, journal): replays all 4 served
    # submits and continues bit-equal to the uninterrupted twin — and
    # the served-id set survives, so dedup works across the "crash".
    d2 = _recover(denv)
    try:
        dup = d2.handle({"op": "submit", "tenant": denv.names[0],
                         "rows": None, "id": "par0-1"})
        assert dup.get("duplicate") is True
        for q in range(2):
            _roundtrip(denv, d2, q % 2, f"postcrash{q}")
    finally:
        d2.close()


def test_backpressure_deterministic_and_shed_recorded(denv):
    d = _recover(denv, queue_max=2, retry_after_floor_s=0.05,
                 priority={denv.names[1]: 1})
    try:
        # Fill the bounded queue below the pump (white-box: admission
        # only), then verify the deterministic rejection quote.
        with d._lock:
            for _ in range(2):
                got = d._admit({"op": "submit", "tenant": denv.names[0],
                                "rows": None})
                assert isinstance(got, _Ticket)
            work = d._queued_work_s()
            rej = d._admit({"op": "submit", "tenant": denv.names[0],
                            "rows": None})
        assert rej["backpressure"] is True
        assert rej["retry_after_s"] == pytest.approx(max(0.05, work))
        assert d.n_backpressure == 1
        # SLO burn firing -> the lowest-priority class sheds; the
        # higher class is still admitted.  Every shed is a HealthEvent.
        set_slo(SLOConfig(p99_ms=1e-6, min_events=3, window=3600.0))
        for t in range(4):
            plane().slo.observe(float(t), wall_ms=5.0)
        assert plane().slo.breached
        with d._lock:
            d._queue.clear()
            shed = d._admit({"op": "submit", "tenant": denv.names[0],
                             "rows": None})
            kept = d._admit({"op": "submit", "tenant": denv.names[1],
                             "rows": None})
            d._queue.clear()
        assert shed.get("shed") is True and d.n_shed == 1
        assert isinstance(kept, _Ticket)
        evs = [e for e in d.health.events if e.kind == "shed"]
        assert len(evs) == 1 and evs[0].tenant == denv.names[0]
        assert evs[0].action == "rejected"
    finally:
        set_slo(None)
        assert not plane().slo.breached      # disarm clears the latch
        d.close()


def test_handoff_same_process_bit_equal(denv):
    pred = _recover(denv)
    addr = str(denv.work / "d.sock")
    listener = make_listener(addr)
    th = threading.Thread(target=pred.serve_forever, args=(listener,),
                          daemon=True)
    th.start()
    cli = DaemonClient(addr, timeout=120.0)
    assert cli.ping()["pong"]
    # Socket path answers == handle() path == lone fleet.
    for q in range(2):
        i = q % 2
        rows = denv.tens[i][2][denv.cursor[i]:denv.cursor[i] + R]
        denv.cursor[i] += R
        resp = cli.submit(denv.names[i], rows, req_id=f"ho-pre{q}",
                          wait=True)
        assert resp.get("ok"), resp
        denv.twin.submit(denv.names[i], rows)
        upd = denv.twin.drain()[denv.names[i]][0]
        np.testing.assert_array_equal(np.asarray(resp["nowcast"]),
                                      upd.nowcast)
    succ, lst2, gap_ms = DFMDaemon.takeover(addr, denv.snap, denv.journal,
                                            backend=BE)
    th.join(timeout=60)
    assert not th.is_alive(), "predecessor kept serving after handoff"
    assert gap_ms >= 0.0 and succ.n_handoffs == 1
    assert [e.kind for e in succ.health.events] == ["handoff"]
    th2 = threading.Thread(target=succ.serve_forever, args=(lst2,),
                           daemon=True)
    th2.start()
    try:
        # Same client, same address: the successor's answers continue
        # bit-equal to the uninterrupted twin (delta replay worked).
        for q in range(2):
            i = q % 2
            rows = denv.tens[i][2][denv.cursor[i]:denv.cursor[i] + R]
            denv.cursor[i] += R
            resp = cli.submit(denv.names[i], rows, req_id=f"ho-post{q}",
                              wait=True)
            assert resp.get("ok"), resp
            denv.twin.submit(denv.names[i], rows)
            upd = denv.twin.drain()[denv.names[i]][0]
            np.testing.assert_array_equal(np.asarray(resp["nowcast"]),
                                          upd.nowcast)
            np.testing.assert_array_equal(np.asarray(resp["forecast_y"]),
                                          upd.forecasts["y"])
    finally:
        cli.shutdown()
        th2.join(timeout=60)
        succ.close()
        pred._journal.close()

"""Sharded RBPF == single-device RBPF at matched PRNG (VERDICT r2 item 5).

The sharded filter runs the identical scan body with the series reductions
psum'd (see ``parallel.sharded_sv``), so with the same key the particle
paths and resampling decisions match and the loglik agrees to fp tolerance.
Runs on the fake 8-device CPU mesh from conftest (x64).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.models.sv import SVSpec, sv_filter
from dfm_tpu.parallel.mesh import make_mesh
from dfm_tpu.parallel.sharded_sv import sharded_sv_filter
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def sv_panel():
    rng = np.random.default_rng(7)
    Y, _, _, _ = dgp.simulate_sv(48, 60, 3, rng)
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 3)
    return Yz, p0


@pytest.mark.parametrize("quad_form", ["residual", "expanded"])
def test_sharded_sv_matches_single_device(sv_panel, quad_form):
    Yz, p0 = sv_panel
    spec = SVSpec(n_factors=3, n_particles=64, quad_form=quad_form)
    pj = JP.from_numpy(p0, jnp.float64)
    Yj = jnp.asarray(Yz)
    key = jax.random.PRNGKey(11)
    r_single = sv_filter(Yj, pj, spec, key=key)
    r_shard = sharded_sv_filter(Yj, pj, spec, key=key, mesh=make_mesh(8))
    assert abs(float(r_shard.loglik) - float(r_single.loglik)) < (
        1e-8 * abs(float(r_single.loglik)))
    np.testing.assert_allclose(np.asarray(r_shard.f_mean),
                               np.asarray(r_single.f_mean), atol=1e-8)
    np.testing.assert_allclose(np.asarray(r_shard.h_mean),
                               np.asarray(r_single.h_mean), atol=1e-8)
    assert int(r_shard.n_resamples) == int(r_single.n_resamples)


def test_sharded_sv_padding_neutral(sv_panel):
    """N=48 on a 5-device mesh -> 2 padded series; loglik must not move."""
    Yz, p0 = sv_panel
    spec = SVSpec(n_factors=3, n_particles=32)
    pj = JP.from_numpy(p0, jnp.float64)
    Yj = jnp.asarray(Yz)
    key = jax.random.PRNGKey(3)
    r_single = sv_filter(Yj, pj, spec, key=key)
    r_pad = sharded_sv_filter(Yj, pj, spec, key=key, mesh=make_mesh(5))
    assert abs(float(r_pad.loglik) - float(r_single.loglik)) < (
        1e-8 * abs(float(r_single.loglik)))


def test_sharded_sv_store_paths_off(sv_panel):
    Yz, p0 = sv_panel
    spec = SVSpec(n_factors=3, n_particles=32)
    r = sharded_sv_filter(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                          spec, store_paths=False, mesh=make_mesh(8))
    assert r.h_particles is None and r.logw is None
    assert np.isfinite(float(r.loglik))


def test_sv_fit_sharded_mesh_matches(sv_panel):
    """Full particle EM with every E-step on the fake 8-mesh == single
    device at matched PRNG (sv_fit(mesh=...))."""
    import jax
    from dfm_tpu.models.sv import sv_fit
    Yz, _ = sv_panel
    spec = SVSpec(n_factors=2, n_particles=32, n_smooth_draws=8)
    kw = dict(em_iters=3, sv_iters=2, key=jax.random.PRNGKey(9),
              backend="cpu")
    r1 = sv_fit(Yz, spec, **kw)
    r8 = sv_fit(Yz, spec, mesh=make_mesh(8), **kw)
    np.testing.assert_allclose(r8.logliks, r1.logliks, rtol=1e-8)
    np.testing.assert_allclose(r8.sigma_h, r1.sigma_h, rtol=1e-8)
    np.testing.assert_allclose(r8.h_smooth, r1.h_smooth, atol=1e-8)

"""Guarded-fit tests (ISSUE: health-monitored EM with automatic recovery).

Every recovery path is driven deterministically on the fake 8-device CPU
mesh via ``robust.faults.FaultInjector`` (``RobustPolicy.wrap_scan``):
NaN-poisoned chunks, transient and persistent dispatch failures, non-PSD
parameter corruption, forced steady-state freeze drift.  The CPU NumPy
backend is the f64 oracle throughout (conftest forces x64, so the TPU
path is numerically exact too — clean guarded fits must MATCH unguarded
ones, not just resemble them).
"""

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, fit
from dfm_tpu.api import ShardedBackend, TPUBackend
from dfm_tpu.backends.cpu_ref import SSMParams
from dfm_tpu.robust import (FaultInjector, FitHealth, GuardFailure,
                            RobustPolicy, check_param_health,
                            health_from_trace, repair_params)
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(N=20, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=60, rng=rng)
    return Y


MODEL = DynamicFactorModel(n_factors=2, standardize=False)


def quick_policy(inj=None, **kw):
    """Test policy: no real sleeps, fault hook installed."""
    kw.setdefault("backoff_base", 1e-4)
    if inj is not None:
        kw.setdefault("wrap_scan", inj.wrap)
    return RobustPolicy(**kw)


# ---------------------------------------------------------------- units --

def test_health_ok_and_summary():
    h = FitHealth(n_chunks=3)
    assert h.ok and "healthy" in h.summary()
    h.escalate("fallback_info")
    assert not h.ok and "fallback_info" in h.summary()


def test_health_from_trace_counts():
    h = health_from_trace([-10.0, -9.0, np.nan, -8.0, -8.5], noise_floor=0.1)
    assert [e.kind for e in h.events] == ["nan_loglik"]
    assert h.monotonicity_violations == 1          # the 0.5 drop; NaN ignored
    assert not h.ok


def test_check_and_repair_params():
    k, N = 3, 6
    good = SSMParams(Lam=np.ones((N, k)), A=0.5 * np.eye(k), Q=np.eye(k),
                     R=np.ones(N), mu0=np.zeros(k), P0=np.eye(k))
    assert check_param_health(good) == []
    bad = SSMParams(Lam=good.Lam, A=good.A, Q=np.eye(k) - 2.0,
                    R=np.full(N, 1e-9), mu0=good.mu0, P0=good.P0)
    issues = check_param_health(bad)
    assert "nonpsd_Q" in issues and "r_floor" in issues
    fixed = repair_params(bad, r_floor=1e-6, jitter=1e-8)
    assert check_param_health(fixed) == []
    nan = SSMParams(Lam=np.full((N, k), np.nan), A=good.A, Q=good.Q,
                    R=good.R, mu0=good.mu0, P0=np.full((k, k), np.inf))
    assert check_param_health(nan) == ["nonfinite"]
    fixed = repair_params(nan)
    assert check_param_health(fixed) == []


def test_remeasure_tau_monotone(panel):
    from dfm_tpu.ssm.steady import auto_tau, remeasure_tau
    rng = np.random.default_rng(3)
    p = dgp.dfm_params(N=20, k=2, rng=rng)
    params = SSMParams(Lam=p.Lam, A=p.A, Q=p.Q, R=p.R, mu0=p.mu0, P0=p.P0)
    tau0 = auto_tau(params)
    assert remeasure_tau(params, tau0) >= tau0
    # A near-unit-root transition mixes slower: tau must grow.
    slow = SSMParams(Lam=p.Lam, A=0.999 * np.eye(2), Q=p.Q, R=p.R,
                     mu0=p.mu0, P0=p.P0)
    assert remeasure_tau(slow, 4) > 4


def test_policy_resolution(panel):
    with pytest.raises(TypeError, match="robust"):
        fit(MODEL, panel, backend="tpu", max_iters=2, robust="yes")


# ---------------------------------------------- clean-path equivalence --

def test_guarded_matches_unguarded(panel):
    r_off = fit(MODEL, panel, backend="tpu", max_iters=10, tol=0.0,
                robust=False)
    r_on = fit(MODEL, panel, backend="tpu", max_iters=10, tol=0.0,
               robust=True)
    np.testing.assert_array_equal(r_on.logliks, r_off.logliks)
    np.testing.assert_array_equal(r_on.params.Lam, r_off.params.Lam)
    assert r_off.health is None
    assert r_on.health is not None and r_on.health.ok
    assert r_on.health.n_chunks >= 1


def test_guarded_default_on(panel):
    r = fit(MODEL, panel, backend="tpu", max_iters=4, tol=0.0)
    assert r.health is not None and r.health.ok


# ------------------------------------------------------- fault recovery --

def test_nan_chunk_recovers(panel):
    b = TPUBackend(fused_chunk=2)
    r_clean = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                  robust=False)
    inj = FaultInjector().nan_chunk(1)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj, recover_divergence=True))
    assert np.isfinite(r.logliks).all() and len(r.logliks) == 8
    assert "nan_loglik" in [e.kind for e in r.health.events]
    assert r.health.n_recoveries >= 1 and not r.health.ok
    # Restore + (tiny-jitter) repair resumes from the chunk entry: the
    # final loglik must land back on the clean trajectory.
    np.testing.assert_allclose(r.logliks[-1], r_clean.logliks[-1],
                               rtol=1e-6)


def test_nan_chunk_default_records_only(panel):
    # Default policy (recover_divergence=False): legacy semantics — the
    # NaN logliks stay in the trace (em_progress treats NaN as
    # "continue"; tests/test_debug.py pins the poisoned-fit behavior),
    # but the pathology is on the health record.
    b = TPUBackend(fused_chunk=2)
    inj = FaultInjector().nan_chunk(1)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj))
    assert len(r.logliks) == 8
    assert np.isnan(r.logliks[2:4]).all()       # dispatch #1 = iters 2-3
    assert "nan_loglik" in [e.kind for e in r.health.events]
    assert not r.health.ok


def test_transient_dispatch_failure_retried(panel):
    b = TPUBackend(fused_chunk=2)
    r_clean = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                  robust=False)
    inj = FaultInjector().dispatch_failure(at=1, count=2)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj))
    # Retries re-dispatch the untouched params: exact reproduction.
    np.testing.assert_array_equal(r.logliks, r_clean.logliks)
    assert r.health.n_dispatch_retries == 2
    assert [e.action for e in r.health.events
            if e.kind == "dispatch_error"] == ["retried", "retried"]
    assert r.backend == "tpu"


def test_persistent_dispatch_failure_raises(panel):
    inj = FaultInjector().dispatch_failure(at=1, count=-1)
    with pytest.raises(GuardFailure, match="dispatch failed"):
        fit(MODEL, panel, backend=TPUBackend(fused_chunk=2), max_iters=8,
            tol=0.0, robust=quick_policy(inj, dispatch_retries=1))


def test_persistent_dispatch_failure_cpu_fallback(panel):
    b = TPUBackend(fused_chunk=2)
    inj = FaultInjector().dispatch_failure(at=2, count=-1)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj, dispatch_retries=1, on_failure="cpu"))
    assert r.backend == "cpu"
    assert r.health.fallback_backend == "cpu"
    assert np.isfinite(r.logliks).all() and len(r.logliks) == 8
    # The degraded run continues from the last good params: its trace is
    # the uninterrupted f64-oracle trajectory.
    r_cpu = fit(MODEL, panel, backend="cpu", max_iters=8, tol=0.0)
    np.testing.assert_allclose(r.logliks, r_cpu.logliks, rtol=1e-6)
    np.testing.assert_allclose(r.factors, r_cpu.factors, atol=1e-6)


def test_nonpsd_params_repaired(panel):
    b = TPUBackend(fused_chunk=2)
    inj = FaultInjector().nonpsd_params(at=0)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj, check_params="always",
                                recover_divergence=True))
    assert np.isfinite(r.logliks[-1])
    assert r.health.nonpsd_events >= 1
    assert any(e.action == "repaired" for e in r.health.events)


# ----------------------------------------------- freeze-drift escalation --

def test_freeze_drift_info_fallback(panel):
    b = TPUBackend(filter="ss", fused_chunk=2)
    inj = FaultInjector().freeze_drift(at=0, delta=1e-2)
    r = fit(MODEL, panel, backend=b, max_iters=12, tol=0.0,
            robust=quick_policy(inj, freeze_action="fallback_info"))
    assert "fallback_info" in r.health.escalations
    assert any(e.kind == "freeze_drift" for e in r.health.events)
    # Acceptance: after the ss -> info fallback the final loglik matches
    # the f64 oracle trajectory to the BASELINE accuracy bound.
    r_cpu = fit(MODEL, panel, backend="cpu", max_iters=12, tol=0.0)
    np.testing.assert_allclose(r.logliks[-1], r_cpu.logliks[-1], rtol=1e-5)


def test_freeze_drift_warn_mode(panel):
    # freeze_action="warn" preserves the legacy diagnostic verbatim.
    b = TPUBackend(filter="ss", fused_chunk=2)
    inj = FaultInjector().freeze_drift(at=1, delta=1e-2)
    with pytest.warns(RuntimeWarning, match="freeze error"):
        r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                robust=quick_policy(inj, freeze_action="warn"))
    assert r.health.max_ss_delta >= 1e-2
    assert not r.health.escalations


# ----------------------------------------------------- sharded guarding --

def test_sharded_guarded_matches_unguarded(panel):
    b = ShardedBackend(fused_chunk=2)
    r_off = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False)
    r_on = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=True)
    np.testing.assert_array_equal(r_on.logliks, r_off.logliks)
    assert r_on.health is not None and r_on.health.ok


def test_sharded_freeze_drift_info_fallback(panel):
    # Satellite: the freeze diagnostic propagates through the sharded
    # chunked driver — and under the guard it CORRECTS (drv.cfg swap,
    # params re-padded through ShardedEM.params_device).
    b = ShardedBackend(filter="ss", fused_chunk=2)
    inj = FaultInjector().freeze_drift(at=0, delta=1e-2)
    r = fit(MODEL, panel, backend=b, max_iters=12, tol=0.0,
            robust=quick_policy(inj, freeze_action="fallback_info"))
    assert "fallback_info" in r.health.escalations
    r_cpu = fit(MODEL, panel, backend="cpu", max_iters=12, tol=0.0)
    np.testing.assert_allclose(r.logliks[-1], r_cpu.logliks[-1], rtol=1e-5)


def test_sharded_freeze_drift_warn_mode(panel):
    b = ShardedBackend(filter="ss", fused_chunk=2)
    inj = FaultInjector().freeze_drift(at=1, delta=1e-2)
    with pytest.warns(RuntimeWarning, match="freeze error"):
        r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                robust=quick_policy(inj, freeze_action="warn"))
    assert r.health.max_ss_delta >= 1e-2


def test_sharded_dispatch_failure_cpu_fallback(panel):
    b = ShardedBackend(fused_chunk=2)
    inj = FaultInjector().dispatch_failure(at=2, count=-1)
    r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
            robust=quick_policy(inj, dispatch_retries=1, on_failure="cpu"))
    assert r.backend == "cpu" and r.health.fallback_backend == "cpu"
    r_cpu = fit(MODEL, panel, backend="cpu", max_iters=8, tol=0.0)
    np.testing.assert_allclose(r.logliks, r_cpu.logliks, rtol=1e-6)


# --------------------------------------------------- panel validation --

def test_validate_all_nan_column(panel):
    Y = panel.copy()
    Y[:, 3] = np.nan
    with pytest.raises(ValueError, match=r"\[3\].*no observed"):
        fit(DynamicFactorModel(n_factors=2), Y, backend="cpu")


def test_validate_zero_variance_column(panel):
    Y = panel.copy()
    Y[:, 5] = 2.5
    Y[:, 11] = -1.0
    with pytest.raises(ValueError, match=r"\[5, 11\].*zero variance"):
        fit(DynamicFactorModel(n_factors=2), Y, backend="cpu")
    # standardize=False skips the variance check (constant columns are
    # legal inputs when no scaling happens).
    r = fit(MODEL, Y, backend="cpu", max_iters=2)
    assert np.isfinite(r.logliks).all()


def test_validate_panel_direct():
    from dfm_tpu.utils.data import validate_panel
    Y = np.random.default_rng(0).normal(size=(30, 4))
    validate_panel(Y)                      # clean: no raise
    mask = np.ones_like(Y)
    mask[:, 2] = 0.0
    with pytest.raises(ValueError, match=r"\[2\]"):
        validate_panel(Y, mask)


# ------------------------------------------------------- checkpointing --

def test_checkpoint_resume_reproduces_trajectory(tmp_path, panel):
    ck = str(tmp_path / "em.npz")
    m = DynamicFactorModel(n_factors=2)
    r_full = fit(m, panel, backend="tpu", max_iters=12, tol=0.0)
    r1 = fit(m, panel, backend="tpu", max_iters=6, tol=0.0,
             checkpoint_path=ck, checkpoint_every=2)
    assert len(r1.logliks) == 6
    r2 = fit(m, panel, backend="tpu", max_iters=12, tol=0.0,
             checkpoint_path=ck, checkpoint_every=2)
    # Resume runs exactly the remaining budget and lands on the
    # uninterrupted trajectory.
    assert len(r2.logliks) == 6
    np.testing.assert_allclose(r2.logliks, r_full.logliks[6:], rtol=1e-7)
    np.testing.assert_allclose(r2.params.Lam, r_full.params.Lam, atol=1e-8)


def test_checkpoint_exhausted_budget_is_stable(tmp_path, panel):
    ck = str(tmp_path / "em.npz")
    m = DynamicFactorModel(n_factors=2)
    fit(m, panel, backend="tpu", max_iters=6, tol=0.0, checkpoint_path=ck)
    from dfm_tpu.utils.checkpoint import load_checkpoint
    before = load_checkpoint(ck)
    r = fit(m, panel, backend="tpu", max_iters=6, tol=0.0,
            checkpoint_path=ck)
    after = load_checkpoint(ck)
    # Re-running an exhausted budget returns the stored state untouched.
    assert before[1] == after[1] == 6
    np.testing.assert_array_equal(before[0].Lam, r.params.Lam)


def test_checkpoint_fingerprint_mismatch_raises(tmp_path, panel):
    ck = str(tmp_path / "em.npz")
    m = DynamicFactorModel(n_factors=2)
    fit(m, panel, backend="tpu", max_iters=4, tol=0.0, checkpoint_path=ck)
    from dfm_tpu.utils.checkpoint import load_checkpoint
    # The strict seam: a caller that must not proceed past foreign state.
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        load_checkpoint(ck, fingerprint="not-this-panel",
                        on_mismatch="raise")
    # fit() itself treats the mismatch as a cold start with the FULL
    # budget (foreign data must never warm-start — test_select_eval.py
    # pins the trajectory equality).
    r = fit(m, panel + 1.0, backend="tpu", max_iters=4, tol=0.0,
            checkpoint_path=ck)
    assert r.n_iters == 4


def test_checkpoint_guard_saves_last_good(tmp_path, panel):
    # A failed guarded fit leaves a resumable checkpoint of the last good
    # params even when the per-iteration cadence never fired.
    ck = str(tmp_path / "em.npz")
    m = DynamicFactorModel(n_factors=2)
    inj = FaultInjector().dispatch_failure(at=2, count=-1)
    with pytest.raises(GuardFailure):
        fit(m, panel, backend=TPUBackend(fused_chunk=2), max_iters=8,
            tol=0.0, checkpoint_path=ck, checkpoint_every=1000,
            robust=quick_policy(inj, dispatch_retries=1))
    from dfm_tpu.utils.checkpoint import load_checkpoint
    state = load_checkpoint(ck)
    assert state is not None and state[1] == 4     # two clean chunks of 2
    assert np.isfinite(state[0].Lam).all()

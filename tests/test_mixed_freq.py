"""Mixed-frequency DFM tests (config S3; SURVEY.md sections 3.4, 4.2).

Spine: DGP -> estimate -> recover, plus the two structural equivalences that
pin the augmentation algebra:
  - with no quarterly series, the augmented model's loglik equals the plain
    k-state model's (the companion lags are deterministic bookkeeping);
  - EM loglik is monotone under masks + augmentation (whole-pipeline oracle).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.models.mixed_freq import (MFParams, MixedFreqSpec, augment,
                                       mf_em_step, mf_fit, mf_pca_init)
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp
from dfm_tpu.utils.data import build_mask


@pytest.fixture(scope="module")
def mf_panel():
    rng = np.random.default_rng(21)
    Y, mask, F, truth = dgp.simulate_mixed_freq(
        n_monthly=30, n_quarterly=8, T=120, k=2, rng=rng)
    return Y, mask, F, truth


def test_augment_shapes():
    spec = MixedFreqSpec(n_monthly=3, n_quarterly=2, n_factors=2)
    p = MFParams(Lam_m=jnp.ones((3, 2)), Lam_q=jnp.ones((2, 2)),
                 A=0.5 * jnp.eye(2), Q=jnp.eye(2), R=jnp.ones(5),
                 mu0=jnp.zeros(10), P0=jnp.eye(10))
    aug = augment(p, spec)
    assert aug.Lam.shape == (5, 10)
    assert aug.A.shape == (10, 10)
    # quarterly row = kron(w, lam_q)
    np.testing.assert_allclose(np.asarray(aug.Lam)[3, :2], 1.0 / 3)
    np.testing.assert_allclose(np.asarray(aug.Lam)[3, 4:6], 1.0)
    # companion shift: block (1,0) is I
    np.testing.assert_allclose(np.asarray(aug.A)[2:4, :2], np.eye(2))
    # top-left is A
    np.testing.assert_allclose(np.asarray(aug.A)[:2, :2], 0.5 * np.eye(2))


def test_monthly_only_equals_plain_model():
    """Augmented filter with zero quarterly series == plain k-state filter."""
    rng = np.random.default_rng(22)
    p_np = dgp.dfm_params(12, 2, rng)
    Y, _ = dgp.simulate(p_np, 60, rng)
    spec = MixedFreqSpec(n_monthly=12, n_quarterly=0, n_factors=2)
    m = spec.state_dim
    # Build consistent augmented initial moments: block-diagonalize P0 over
    # lags using the stationary distribution of the companion.
    A_aug = np.zeros((m, m))
    A_aug[:2, :2] = p_np.A
    A_aug[2:, :-2] = np.eye(m - 2)
    Q_aug = np.zeros((m, m))
    Q_aug[:2, :2] = p_np.Q
    P0_aug = cpu_ref._solve_discrete_lyapunov_or_eye(
        A_aug, Q_aug + 1e-12 * np.eye(m))
    p_mf = MFParams(Lam_m=jnp.asarray(p_np.Lam),
                    Lam_q=jnp.zeros((0, 2)),
                    A=jnp.asarray(p_np.A), Q=jnp.asarray(p_np.Q),
                    R=jnp.asarray(p_np.R),
                    mu0=jnp.zeros(m), P0=jnp.asarray(P0_aug))
    aug = augment(p_mf, spec)
    W = np.ones_like(Y)
    ll_aug = float(info_filter(jnp.asarray(Y), aug,
                               mask=jnp.asarray(W)).loglik)
    # Plain model with the *matching* prior on f_1 (top block of P0_aug).
    p_plain = cpu_ref.SSMParams(p_np.Lam, p_np.A, p_np.Q, p_np.R,
                                np.zeros(2), P0_aug[:2, :2])
    ll_plain = cpu_ref.kalman_filter(Y, p_plain).loglik
    assert abs(ll_aug - ll_plain) < 1e-6 * abs(ll_plain)


def test_mf_em_monotone_loglik(mf_panel):
    Y, mask, _, _ = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    from dfm_tpu.utils.data import standardize
    Yz, _ = standardize(Y, mask=mask)
    W = build_mask(Yz, mask)
    p = mf_pca_init(Yz, W, spec)
    Yj = jnp.asarray(np.nan_to_num(np.where(W > 0, Yz, 0.0)))
    Wj = jnp.asarray(W)
    lls = []
    for _ in range(8):
        p, ll = mf_em_step(Yj, Wj, p, spec)
        lls.append(float(ll))
    dll = np.diff(lls)
    assert np.all(dll >= -1e-7 * np.abs(lls[:-1]).max()), lls


def test_mf_fit_recovers_factors(mf_panel):
    Y, mask, F, truth = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    res = mf_fit(Y, spec, mask=mask, max_iters=30, tol=1e-8)
    assert np.all(np.isfinite(res.logliks))
    # Factor space recovery up to rotation: R^2 of true factors on estimates.
    X = np.column_stack([res.factors, np.ones(len(F))])
    for j in range(2):
        beta, *_ = np.linalg.lstsq(X, F[:, j], rcond=None)
        resid = F[:, j] - X @ beta
        r2 = 1.0 - resid.var() / F[:, j].var()
        assert r2 > 0.85, f"factor {j}: R^2={r2:.3f}"


def test_mf_nowcast_fills_missing_quarterly(mf_panel):
    """The smoothed common component approximates the LATENT quarterly value
    in months where the series is unobserved."""
    Y, mask, F, truth = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    res = mf_fit(Y, spec, mask=mask, max_iters=30, tol=1e-8)
    latent_q = truth["G"] @ truth["Lam_q"].T        # noiseless quarterly path
    miss = mask[:, 30:] == 0
    now_q = res.nowcast[:, 30:]
    corr = np.corrcoef(now_q[miss], latent_q[miss])[0, 1]
    assert corr > 0.9, corr


def test_mf_fused_chunk_matches_per_iteration():
    rng = np.random.default_rng(31)
    Y, mask, _, _ = dgp.simulate_mixed_freq(20, 6, 80, 2, rng)
    spec = MixedFreqSpec(n_monthly=20, n_quarterly=6, n_factors=2)
    r1 = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0, fused_chunk=1)
    r3 = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0, fused_chunk=3)
    np.testing.assert_allclose(r3.logliks, r1.logliks, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(r3.params.Lam_m),
                               np.asarray(r1.params.Lam_m), atol=1e-10)
    np.testing.assert_allclose(r3.nowcast, r1.nowcast, atol=1e-9)


def test_mf_pit_time_scan_matches_seq():
    """spec.time_scan="pit" (parallel-in-time E-step) reproduces the
    sequential filter/smoother EM trajectory exactly (x64)."""
    import dataclasses
    rng = np.random.default_rng(33)
    Y, mask, _, _ = dgp.simulate_mixed_freq(24, 6, 70, 2, rng)
    spec = MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2)
    r_seq = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0)
    r_pit = mf_fit(Y, dataclasses.replace(spec, time_scan="pit"),
                   mask=mask, max_iters=6, tol=0.0)
    np.testing.assert_allclose(r_pit.logliks, r_seq.logliks, rtol=1e-9)
    np.testing.assert_allclose(r_pit.nowcast, r_seq.nowcast, atol=1e-6)
    with pytest.raises(ValueError):
        MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2,
                      time_scan="parallel")


def test_mf_pit_time_scan_matches_seq_f32():
    """f32-tolerance variant (CLAUDE.md convention): the pit E-step's
    compute-dtype trajectory stays within the in-loop noise band of the
    sequential one."""
    import dataclasses
    import jax.numpy as jnp
    from dfm_tpu.models.mixed_freq import mf_em_scan
    rng = np.random.default_rng(34)
    Y, mask, _, _ = dgp.simulate_mixed_freq(24, 6, 70, 2, rng)
    spec = MixedFreqSpec(n_monthly=24, n_quarterly=6, n_factors=2)
    r0 = mf_fit(Y, spec, mask=mask, max_iters=2, tol=0.0)   # warm params
    Yz = r0.standardizer.transform(np.nan_to_num(Y))
    W = np.where(np.isfinite(Y), mask, 0.0)
    Yz = np.where(W > 0, Yz, 0.0)
    args = (jnp.asarray(Yz, jnp.float32), jnp.asarray(W, jnp.float32),
            r0.params.astype(jnp.float32))
    _, lls_seq = mf_em_scan(*args, spec, 4)
    _, lls_pit = mf_em_scan(
        *args, dataclasses.replace(spec, time_scan="pit"), 4)
    np.testing.assert_allclose(np.asarray(lls_pit), np.asarray(lls_seq),
                               rtol=2e-4)


def test_mf_loglik_eval_mask_none():
    """Regression (ADVICE r5 finding #1): the fast compute-dtype path
    crashed in ``asarray(None)`` on a fully-observed panel (mask=None).
    Both paths must accept mask=None and agree with the masked all-ones
    call exactly."""
    from dfm_tpu.models.mixed_freq import mf_loglik_eval
    rng = np.random.default_rng(41)
    Y, _, _, _ = dgp.simulate_mixed_freq(10, 4, 60, 2, rng)
    Y = np.nan_to_num(Y)            # fully observed: every entry is data
    spec = MixedFreqSpec(n_monthly=10, n_quarterly=4, n_factors=2)
    W = np.ones_like(Y)
    p = mf_pca_init(Y, W, spec)
    for precise in (True, False):
        ll_none = mf_loglik_eval(Y, None, p, spec, precise=precise)
        ll_ones = mf_loglik_eval(Y, W, p, spec, precise=precise)
        assert np.isfinite(ll_none)
        np.testing.assert_allclose(ll_none, ll_ones, rtol=1e-12)


def test_mf_loglik_eval_fast_path_routes_through_fit_program(monkeypatch):
    """Regression (CLAUDE.md axon SIGABRT): the fast compute-dtype
    ``mf_loglik_eval`` must evaluate through the fit's OWN E-step program
    (``mf_em_step``), never the standalone loglik-only ``info_scan``
    program — the f32 masked variant of THAT program at the m~25
    augmented shape crashes the axon TPU compiler (fusion-merge check
    failure, 2026-07).  Pin the routing by making the standalone kernel
    explode: the fast path must sail through untouched while the precise
    path (which legitimately uses it) trips the mine."""
    from dfm_tpu.models.mixed_freq import mf_loglik_eval
    from dfm_tpu.ssm import info_filter as info_mod

    rng = np.random.default_rng(53)
    Y, mask, _, _ = dgp.simulate_mixed_freq(30, 8, 48, 5, rng)
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=5)
    assert spec.state_dim == 25            # the documented crash shape
    W = np.where(np.isfinite(Y), mask, 0.0)
    p = mf_pca_init(np.nan_to_num(Y), W, spec)

    def boom(*a, **k):          # stands in for the SIGABRT'ing program
        raise AssertionError("standalone loglik-only program invoked")

    monkeypatch.setattr(info_mod, "_loglik_eval_impl", boom)
    ll_fast = mf_loglik_eval(Y, W, p, spec, precise=False)
    assert np.isfinite(ll_fast)
    # ... and it is exactly the fit's in-loop figure.
    Yj = jnp.asarray(Y)
    _, ll_ref = mf_em_step(Yj, jnp.asarray(W, Yj.dtype),
                           p.astype(Yj.dtype), spec)
    np.testing.assert_allclose(ll_fast, float(ll_ref), rtol=1e-12)
    # The mine is live: the precise path does reach the standalone kernel.
    with pytest.raises(AssertionError, match="standalone"):
        mf_loglik_eval(Y, W, p, spec, precise=True)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="axon-only: exercises the real TPU compiler at "
                           "the m~25 shape the exact masked loglik-only "
                           "program SIGABRTs on")
def test_mf_loglik_eval_fast_path_compiles_at_m25_on_axon():
    from dfm_tpu.models.mixed_freq import mf_loglik_eval
    rng = np.random.default_rng(54)
    Y, mask, _, _ = dgp.simulate_mixed_freq(30, 8, 48, 5, rng)
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=5)
    W = np.where(np.isfinite(Y), mask, 0.0)
    p = mf_pca_init(np.nan_to_num(Y), W, spec)
    assert np.isfinite(mf_loglik_eval(Y, W, p, spec, precise=False))


def test_mf_fit_attaches_health(mf_panel):
    Y, mask, _, _ = mf_panel
    spec = MixedFreqSpec(n_monthly=30, n_quarterly=8, n_factors=2)
    res = mf_fit(Y, spec, mask=mask, max_iters=6, tol=0.0)
    assert res.health is not None and res.health.ok

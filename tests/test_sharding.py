"""Sharded EM == single-device EM on a fake 8-device CPU mesh.

SURVEY.md section 4.2.4: the JAX-native analog of multi-node testing.  The
conftest forces ``--xla_force_host_platform_device_count=8`` so ``jax.devices()``
reports 8 CPU devices; the mesh/psum code paths exercised here are exactly
what runs on a real TPU pod slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, ShardedBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig, em_fit
from dfm_tpu.parallel.mesh import make_mesh, pad_panel
from dfm_tpu.parallel.sharded import sharded_em_fit, sharded_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(3)
    p = dgp.dfm_params(48, 3, rng)
    Y, _ = dgp.simulate(p, 70, rng)
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 3)
    return Yz, p0


def test_eight_devices_available():
    assert jax.device_count() >= 8


def test_sharded_em_matches_single_device(panel):
    Yz, p0 = panel
    mesh = make_mesh(8)
    ps, lls_s, _, _ = sharded_em_fit(Yz, p0, mesh=mesh, max_iters=6,
                                     dtype=jnp.float64)
    pd_, lls_d, _, _ = em_fit(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                           max_iters=6, cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(lls_s, np.asarray(lls_d), rtol=1e-9)
    np.testing.assert_allclose(ps.Lam, np.asarray(pd_.Lam), atol=1e-7)
    np.testing.assert_allclose(ps.A, np.asarray(pd_.A), atol=1e-7)
    np.testing.assert_allclose(ps.R, np.asarray(pd_.R), atol=1e-7)


def test_sharded_em_matches_with_mask_and_padding(panel):
    """N=48 not divisible by 5-shard mesh -> exercises pad_panel; plus mask."""
    Yz, p0 = panel
    rng = np.random.default_rng(4)
    W = dgp.random_mask(*Yz.shape, rng, frac_missing=0.2)
    mesh = make_mesh(5)
    ps, lls_s, _, _ = sharded_em_fit(Yz, p0, mask=W, mesh=mesh, max_iters=4,
                                     dtype=jnp.float64)
    pd_, lls_d, _, _ = em_fit(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                           mask=jnp.asarray(W), max_iters=4,
                           cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(lls_s, np.asarray(lls_d), rtol=1e-8)
    np.testing.assert_allclose(ps.Lam, np.asarray(pd_.Lam), atol=1e-6)


def test_pad_panel_noop_when_divisible(panel):
    Yz, p0 = panel
    Y2, W2, L2, R2, n_pad = pad_panel(Yz, None, p0.Lam, p0.R, 8)
    assert n_pad == 0 and W2 is None and Y2.shape == Yz.shape


def test_sharded_smoother_matches(panel):
    Yz, p0 = panel
    mesh = make_mesh(8)
    Yp, Wp, Lp, Rp, _ = pad_panel(Yz, None, p0.Lam, p0.R, 8)
    pj = JP(Lam=jnp.asarray(Lp), A=jnp.asarray(p0.A), Q=jnp.asarray(p0.Q),
            R=jnp.asarray(Rp), mu0=jnp.asarray(p0.mu0), P0=jnp.asarray(p0.P0))
    x_sm, P_sm, ll = sharded_filter_smoother(jnp.asarray(Yp), pj, mesh=mesh)
    kf_np = cpu_ref.kalman_filter(Yz, p0)
    sm_np = cpu_ref.rts_smoother(kf_np, p0)
    assert abs(float(ll) - kf_np.loglik) < 1e-6 * abs(kf_np.loglik)
    np.testing.assert_allclose(np.asarray(x_sm), sm_np.x_sm, atol=1e-7)


def test_fit_api_sharded_backend_matches_cpu(panel):
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=3)
    r_cpu = fit(model, Yz, backend="cpu", max_iters=8)
    r_sh = fit(model, Yz, backend=ShardedBackend(dtype=jnp.float64),
               max_iters=8)
    assert abs(r_sh.loglik - r_cpu.loglik) < 1e-5 * abs(r_cpu.loglik)
    np.testing.assert_allclose(r_sh.factors, r_cpu.factors, atol=1e-5)


def test_fused_sharded_scan_matches_per_iteration(panel):
    """One-dispatch fused chunk == per-iteration dispatch == single device
    (VERDICT r2 item 3)."""
    from dfm_tpu.parallel.sharded import ShardedEM
    Yz, p0 = panel
    mesh = make_mesh(8)
    drv = ShardedEM(Yz, p0, mesh=mesh, dtype=jnp.float64)
    p_scan, lls_scan, _ = drv.run_scan(drv.p, 6)
    # per-iteration dispatch path from the same start
    lls_iter = [float(drv.step()) for _ in range(6)]
    np.testing.assert_allclose(np.asarray(lls_scan), lls_iter, rtol=1e-12)
    # single-device fused scan
    from dfm_tpu.estim.em import em_fit_scan
    _, lls_d, _ = em_fit_scan(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                              6, cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(np.asarray(lls_scan), np.asarray(lls_d),
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(p_scan.Lam), np.asarray(drv.p.Lam),
                               atol=1e-10)


def test_sharded_backend_fused_chunk_matches_unfused(panel):
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=3)
    r1 = fit(model, Yz, max_iters=8,
             backend=ShardedBackend(dtype=jnp.float64, fused_chunk=1))
    r3 = fit(model, Yz, max_iters=8,
             backend=ShardedBackend(dtype=jnp.float64, fused_chunk=3))
    np.testing.assert_allclose(r3.logliks, r1.logliks, rtol=1e-10)
    np.testing.assert_allclose(r3.factors, r1.factors, atol=1e-9)
    np.testing.assert_allclose(r3.params.Lam, r1.params.Lam, atol=1e-9)


def test_sharded_ss_filter_matches_info(panel):
    """ShardedBackend(filter='ss') == sharded info to the ss freeze
    tolerance (VERDICT r2 item 6).  T=70 <= 2*tau+4 would fall back, so use
    a small tau to exercise the real steady-state path."""
    from dfm_tpu.parallel.sharded import sharded_em_scan
    Yz, p0 = panel
    mesh = make_mesh(8)
    pj = JP.from_numpy(p0, jnp.float64)
    Yj = jnp.asarray(Yz)
    _, lls_ss, deltas = sharded_em_scan(
        Yj, pj, 5, mesh=mesh, cfg=EMConfig(filter="ss", tau=24))
    _, lls_info, _ = sharded_em_scan(
        Yj, pj, 5, mesh=mesh, cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(np.asarray(lls_ss), np.asarray(lls_info),
                               rtol=1e-6)
    assert float(np.max(np.asarray(deltas))) < 1e-3


def test_sharded_em_padding_no_mask_matches(panel):
    """Unmasked panel with padding (N=48 on 5 shards): the row gate — not a
    materialized mask — must keep the padded run identical to single-device."""
    Yz, p0 = panel
    ps, lls_s, _, _ = sharded_em_fit(Yz, p0, mesh=make_mesh(5), max_iters=5,
                                     dtype=jnp.float64)
    pd_, lls_d, _, _ = em_fit(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                              max_iters=5, cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(lls_s, np.asarray(lls_d), rtol=1e-9)
    np.testing.assert_allclose(ps.Lam, np.asarray(pd_.Lam), atol=1e-7)
    np.testing.assert_allclose(ps.R, np.asarray(pd_.R), atol=1e-7)


def test_sharded_ss_active_with_padding(panel):
    """filter='ss' must NOT silently degrade to info when padding exists
    (code-review r4 finding): deltas nonzero proves the ss engine ran."""
    from dfm_tpu.estim.em import em_fit_scan
    from dfm_tpu.parallel.sharded import ShardedEM
    Yz, p0 = panel
    # tau=4 is deliberately too short for full covariance convergence, so a
    # genuinely-running ss engine MUST report a nonzero freeze diagnostic
    # (with this panel's strong observability the recursion hits a bitwise
    # f64 fixed point by tau~6, and delta == 0 on both paths would not
    # distinguish ss from the fallback).
    cfg = EMConfig(filter="ss", tau=4)
    drv = ShardedEM(Yz, p0, mesh=make_mesh(5), dtype=jnp.float64, cfg=cfg)
    _, lls_s, deltas = drv.run_scan(drv.p, 4)
    assert float(np.max(np.asarray(deltas))) > 0.0
    _, lls_d, deltas_d = em_fit_scan(jnp.asarray(Yz),
                                     JP.from_numpy(p0, jnp.float64),
                                     4, cfg=cfg)
    # The diagnostic itself sits at the f64 noise floor (~3e-12 here): the
    # padded shard sums in a different order than the single device, so an
    # absolute floor at relative-rounding scale is needed alongside rtol.
    np.testing.assert_allclose(np.asarray(deltas), np.asarray(deltas_d),
                               rtol=1e-9, atol=1e-15)
    np.testing.assert_allclose(np.asarray(lls_s), np.asarray(lls_d),
                               rtol=1e-9)


def test_sharded_ss_fit_api(panel):
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=3)
    r_info = fit(model, Yz, max_iters=6,
                 backend=ShardedBackend(dtype=jnp.float64, filter="info"))
    r_ss = fit(model, Yz, max_iters=6,
               backend=ShardedBackend(dtype=jnp.float64, filter="ss"))
    # T=70 < 2*96+4 -> ss falls back to the exact path here; equality is
    # exact.  The true ss path is covered by the tau=24 scan test above.
    np.testing.assert_allclose(r_ss.logliks, r_info.logliks, rtol=1e-9)


def test_sharded_f32_expanded_quad_loglik(panel):
    """f32 + ss: the sharded loglik quadratic takes the EXPANDED form
    (f64-assembled; dead code in the suite's f64 runs, so this f32 case is
    its only fake-mesh coverage — code-review r5).  Pin it against the f32
    single-device ss path (same form; tight) AND the f64 NumPy oracle
    chain (noise-floor tolerance)."""
    from dfm_tpu.estim.em import em_fit_scan
    from dfm_tpu.parallel.sharded import ShardedEM
    Yz, p0 = panel
    cfg = EMConfig(filter="ss", tau=8)
    drv = ShardedEM(Yz, p0, mesh=make_mesh(7), dtype=jnp.float32, cfg=cfg)
    _, lls_s, _ = drv.run_scan(drv.p, 4)
    _, lls_1, _ = em_fit_scan(jnp.asarray(Yz, jnp.float32),
                              JP.from_numpy(p0, jnp.float32), 4, cfg=cfg)
    floor = 200 * np.finfo(np.float32).eps * Yz.size
    np.testing.assert_allclose(np.asarray(lls_s), np.asarray(lls_1),
                               atol=floor, rtol=1e-5)
    p = p0.copy()
    lls_np = []
    for _ in range(4):
        p, ll, _ = cpu_ref.em_step(Yz, p, filter="info")
        lls_np.append(ll)
    np.testing.assert_allclose(np.asarray(lls_s, np.float64), lls_np,
                               atol=floor, rtol=1e-4)


def test_sharded_y_dev_reuse_equivalence(panel):
    """ShardedEM(Y_dev=...) panel reuse: identical trajectory when the
    gates allow reuse, and every gate (padding, dtype, mask) rejects a
    panel that would need a host-side rewrite (code-review r5)."""
    from dfm_tpu.parallel.sharded import ShardedEM
    Yz, p0 = panel
    Yj = jnp.asarray(Yz, jnp.float64)
    drv_a = ShardedEM(Yz, p0, mesh=make_mesh(8), dtype=jnp.float64,
                      Y_dev=Yj)
    assert drv_a.Y is Yj                 # N=48 divides 8: reused
    drv_b = ShardedEM(Yz, p0, mesh=make_mesh(8), dtype=jnp.float64)
    _, lls_a, _ = drv_a.run_scan(drv_a.p, 3)
    _, lls_b, _ = drv_b.run_scan(drv_b.p, 3)
    np.testing.assert_allclose(np.asarray(lls_a), np.asarray(lls_b),
                               rtol=1e-14)
    assert ShardedEM(Yz, p0, mesh=make_mesh(5), dtype=jnp.float64,
                     Y_dev=Yj).Y is not Yj          # padding rejects
    assert ShardedEM(Yz, p0, mesh=make_mesh(8), dtype=jnp.float32,
                     Y_dev=Yj).Y is not Yj          # dtype rejects
    W = dgp.random_mask(*Yz.shape, np.random.default_rng(5), 0.1)
    assert ShardedEM(Yz, p0, mask=W, mesh=make_mesh(8), dtype=jnp.float64,
                     Y_dev=Yj).Y is not Yj          # mask rejects

"""Sharded EM == single-device EM on a fake 8-device CPU mesh.

SURVEY.md section 4.2.4: the JAX-native analog of multi-node testing.  The
conftest forces ``--xla_force_host_platform_device_count=8`` so ``jax.devices()``
reports 8 CPU devices; the mesh/psum code paths exercised here are exactly
what runs on a real TPU pod slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, ShardedBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig, em_fit
from dfm_tpu.parallel.mesh import make_mesh, pad_panel
from dfm_tpu.parallel.sharded import sharded_em_fit, sharded_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(3)
    p = dgp.dfm_params(48, 3, rng)
    Y, _ = dgp.simulate(p, 70, rng)
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 3)
    return Yz, p0


def test_eight_devices_available():
    assert jax.device_count() >= 8


def test_sharded_em_matches_single_device(panel):
    Yz, p0 = panel
    mesh = make_mesh(8)
    ps, lls_s, _, _ = sharded_em_fit(Yz, p0, mesh=mesh, max_iters=6,
                                     dtype=jnp.float64)
    pd_, lls_d, _, _ = em_fit(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                           max_iters=6, cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(lls_s, np.asarray(lls_d), rtol=1e-9)
    np.testing.assert_allclose(ps.Lam, np.asarray(pd_.Lam), atol=1e-7)
    np.testing.assert_allclose(ps.A, np.asarray(pd_.A), atol=1e-7)
    np.testing.assert_allclose(ps.R, np.asarray(pd_.R), atol=1e-7)


def test_sharded_em_matches_with_mask_and_padding(panel):
    """N=48 not divisible by 5-shard mesh -> exercises pad_panel; plus mask."""
    Yz, p0 = panel
    rng = np.random.default_rng(4)
    W = dgp.random_mask(*Yz.shape, rng, frac_missing=0.2)
    mesh = make_mesh(5)
    ps, lls_s, _, _ = sharded_em_fit(Yz, p0, mask=W, mesh=mesh, max_iters=4,
                                     dtype=jnp.float64)
    pd_, lls_d, _, _ = em_fit(jnp.asarray(Yz), JP.from_numpy(p0, jnp.float64),
                           mask=jnp.asarray(W), max_iters=4,
                           cfg=EMConfig(filter="info"))
    np.testing.assert_allclose(lls_s, np.asarray(lls_d), rtol=1e-8)
    np.testing.assert_allclose(ps.Lam, np.asarray(pd_.Lam), atol=1e-6)


def test_pad_panel_noop_when_divisible(panel):
    Yz, p0 = panel
    Y2, W2, L2, R2, n_pad = pad_panel(Yz, None, p0.Lam, p0.R, 8)
    assert n_pad == 0 and W2 is None and Y2.shape == Yz.shape


def test_sharded_smoother_matches(panel):
    Yz, p0 = panel
    mesh = make_mesh(8)
    Yp, Wp, Lp, Rp, _ = pad_panel(Yz, None, p0.Lam, p0.R, 8)
    pj = JP(Lam=jnp.asarray(Lp), A=jnp.asarray(p0.A), Q=jnp.asarray(p0.Q),
            R=jnp.asarray(Rp), mu0=jnp.asarray(p0.mu0), P0=jnp.asarray(p0.P0))
    x_sm, P_sm, ll = sharded_filter_smoother(jnp.asarray(Yp), pj, mesh=mesh)
    kf_np = cpu_ref.kalman_filter(Yz, p0)
    sm_np = cpu_ref.rts_smoother(kf_np, p0)
    assert abs(float(ll) - kf_np.loglik) < 1e-6 * abs(kf_np.loglik)
    np.testing.assert_allclose(np.asarray(x_sm), sm_np.x_sm, atol=1e-7)


def test_fit_api_sharded_backend_matches_cpu(panel):
    Yz, _ = panel
    model = DynamicFactorModel(n_factors=3)
    r_cpu = fit(model, Yz, backend="cpu", max_iters=8)
    r_sh = fit(model, Yz, backend=ShardedBackend(dtype=jnp.float64),
               max_iters=8)
    assert abs(r_sh.loglik - r_cpu.loglik) < 1e-5 * abs(r_cpu.loglik)
    np.testing.assert_allclose(r_sh.factors, r_cpu.factors, atol=1e-5)

"""Time-sharded parallel-in-time QR filtering == single-device
(ISSUE 13 tentpole: per-device blocked prefix scans + one log-depth
cross-device combine of boundary elements, on the conftest fake
8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.parallel import (TIME_AXIS, make_time_mesh,
                              pit_qr_filter_time_sharded,
                              pit_qr_time_sharded)
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.parallel_filter import pit_qr_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP


@pytest.fixture(scope="module")
def setup():
    from dfm_tpu.utils import dgp
    rng = np.random.default_rng(71)
    p = dgp.dfm_params(33, 3, rng)
    return p, rng


def test_make_time_mesh():
    mesh = make_time_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == (TIME_AXIS,)
    assert make_time_mesh(4).devices.size == 4


@pytest.mark.parametrize("T", [96, 97])   # divisible / non-divisible by 8
@pytest.mark.parametrize("masked", [False, True])
def test_time_sharded_matches_single_device(setup, T, masked):
    from dfm_tpu.utils import dgp
    p, rng = setup
    Y, _ = dgp.simulate(p, T, rng)
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    mask = None
    if masked:
        W = dgp.random_mask(*Y.shape, rng, 0.3)
        W[3] = 0.0                         # fully-missing step
        mask = jnp.asarray(W)
    kf0, sm0 = pit_qr_filter_smoother(Yj, pj, mask=mask)
    kf1, sm1 = pit_qr_time_sharded(Yj, pj, mask=mask)
    assert abs(float(kf1.loglik) - float(kf0.loglik)) < 1e-10 * abs(
        float(kf0.loglik))
    for a, b in ((kf1.x_filt, kf0.x_filt), (kf1.P_filt, kf0.P_filt),
                 (kf1.x_pred, kf0.x_pred), (kf1.P_pred, kf0.P_pred),
                 (sm1.x_sm, sm0.x_sm), (sm1.P_sm, sm0.P_sm),
                 (sm1.P_lag, sm0.P_lag)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-10)


def test_time_sharded_matches_sequential_oracle(setup):
    """Also pin directly against the sequential info scan + RTS — the
    time-sharded path must not inherit a shared pit_qr bug."""
    from dfm_tpu.utils import dgp
    p, rng = setup
    Y, _ = dgp.simulate(p, 90, rng)
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    kf_s = info_filter(Yj, pj)
    sm_s = rts_smoother(kf_s, pj)
    kf1, sm1 = pit_qr_time_sharded(Yj, pj)
    assert abs(float(kf1.loglik) - float(kf_s.loglik)) < 1e-9 * abs(
        float(kf_s.loglik))
    np.testing.assert_allclose(np.asarray(kf1.x_filt),
                               np.asarray(kf_s.x_filt), atol=1e-9)
    np.testing.assert_allclose(np.asarray(sm1.x_sm),
                               np.asarray(sm_s.x_sm), atol=1e-8)


def test_time_sharded_filter_only_and_small_mesh(setup):
    from dfm_tpu.utils import dgp
    p, rng = setup
    Y, _ = dgp.simulate(p, 50, rng)
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    kf0 = pit_qr_filter_smoother(Yj, pj)[0]
    kf1 = pit_qr_filter_time_sharded(Yj, pj)
    np.testing.assert_allclose(np.asarray(kf1.x_filt),
                               np.asarray(kf0.x_filt), atol=1e-10)
    # An explicit smaller mesh (T=50 not divisible by 4 either).
    kf2, _ = pit_qr_time_sharded(Yj, pj, n_devices=4)
    assert abs(float(kf2.loglik) - float(kf0.loglik)) < 1e-10 * abs(
        float(kf0.loglik))


def test_time_sharded_f32_tolerance(setup):
    from dfm_tpu.utils import dgp
    p, rng = setup
    Y, _ = dgp.simulate(p, 96, rng)
    p64 = JP.from_numpy(p, jnp.float64)
    p32 = JP.from_numpy(p, jnp.float32)
    ll_ref = float(info_filter(jnp.asarray(Y), p64).loglik)
    kf, _ = pit_qr_time_sharded(jnp.asarray(Y, jnp.float32), p32)
    assert abs(float(kf.loglik) - ll_ref) < 1e-4 * abs(ll_ref)

"""Diffusion-index forecasting + factor alignment utilities."""

import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.estim.diffusion import diffusion_index_forecast
from dfm_tpu.utils import dgp
from dfm_tpu.utils.rotation import (align_factors, factor_r2, procrustes,
                                    trace_r2)


def test_diffusion_index_recovers_linear_map():
    """If target_{t+1} = c + b'F_t exactly, the DI forecast is exact."""
    rng = np.random.default_rng(81)
    T, k = 200, 3
    F = rng.standard_normal((T, k))
    b = np.array([1.0, -2.0, 0.5])
    target = np.zeros(T)
    target[1:] = 0.3 + F[:-1] @ b
    res = diffusion_index_forecast(F, target, horizon=1, y_lags=0)
    assert res.r2 > 0.999999
    expect = 0.3 + F[-1] @ b
    assert abs(res.forecast - expect) < 1e-6


def test_diffusion_index_with_lags_runs():
    rng = np.random.default_rng(82)
    p = dgp.dfm_params(25, 2, rng, spectral_radius=0.8)
    Y, F = dgp.simulate(p, 180, rng)
    r = fit(DynamicFactorModel(n_factors=2), Y, backend="cpu", max_iters=10)
    res = diffusion_index_forecast(r.factors, Y[:, 0], horizon=2,
                                   f_lags=1, y_lags=2)
    assert np.isfinite(res.forecast)
    assert 0.0 <= res.r2 <= 1.0


def test_procrustes_undoes_rotation():
    rng = np.random.default_rng(83)
    F = rng.standard_normal((150, 3))
    Q, _ = np.linalg.qr(rng.standard_normal((3, 3)))
    O = procrustes(F @ Q, F)
    np.testing.assert_allclose((F @ Q) @ O, F, atol=1e-10)
    np.testing.assert_allclose(O, Q.T, atol=1e-10)


def test_factor_r2_on_estimated_model():
    rng = np.random.default_rng(84)
    p = dgp.dfm_params(60, 2, rng, noise_scale=0.3)
    Y, F = dgp.simulate(p, 300, rng)
    r = fit(DynamicFactorModel(n_factors=2), Y, backend="cpu", max_iters=20)
    r2 = factor_r2(r.factors, F)
    assert np.all(r2 > 0.9), r2
    assert trace_r2(r.factors, F) > 0.9
    aligned, B = align_factors(r.factors, F)
    assert aligned.shape == F.shape and B.shape == (2, 2)

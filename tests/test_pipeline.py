"""Latency-hiding dispatch pipeline (dfm_tpu/pipeline.py + chunk drivers).

The operative contracts of the pipelined drivers, verified on the fake
8-device CPU mesh (conftest):

- HEALTHY-PATH BIT-IDENTITY: ``fit(pipeline=d)`` returns byte-identical
  logliks/params to the serial driver on every engine (single-device,
  sharded, batched, sharded-batched) — speculative issue only changes WHEN
  device results are read, never what is computed.  x64-exact plus an f32
  variant; bucketed tail padding (convergence-freeze selects) is checked
  exact on x64 and to f32 tolerance under f32.
- FAULT PARITY: an injected mid-pipeline divergence/dispatch failure rolls
  back through the guard's chunk-entry replay to the SAME recovery
  trajectory (logliks, params, health events) the serial guard produces.
- BLOCKING-TRANSFER BUDGET: depth d performs ~ceil(n_chunks/d) blocking
  device->host pulls instead of n_chunks (trace-asserted; the ~60-100 ms
  axon tunnel latency this hides is docs/PERF.md "End-to-end fixed
  costs").
- BUCKETED EXECUTABLE REUSE: one ``itersNb`` shape key serves every chunk
  length; a second same-shape fit triggers zero first-calls/recompiles.
- PERSISTENT COMPILE CACHE: a fresh process with DFM_COMPILE_CACHE warm
  loads every executable from disk (``new_entries == 0``).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from dfm_tpu.api import DynamicFactorModel, ShardedBackend, TPUBackend, fit
from dfm_tpu.estim.batched import DFMBatchSpec, fit_many
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import summarize, _print_text
from dfm_tpu.obs.trace import Tracer
from dfm_tpu.pipeline import (CACHE_ENV, PipelineConfig,
                              compile_cache_entries, resolve_pipeline,
                              setup_compile_cache)
from dfm_tpu.robust import FaultInjector, RobustPolicy
from dfm_tpu.utils import dgp

MODEL = DynamicFactorModel(n_factors=2, standardize=False)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(N=16, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=48, rng=rng)
    return Y


@pytest.fixture(scope="module")
def panels():
    rng = np.random.default_rng(3)
    B, T, N, k = 3, 40, 6, 2
    Y = np.empty((B, T, N))
    for b in range(B):
        F = rng.standard_normal((T, k)).cumsum(0) * 0.3
        C = rng.standard_normal((N, k))
        Y[b] = F @ C.T + 0.5 * rng.standard_normal((T, N))
    return Y


def quick_policy(inj=None, **kw):
    kw.setdefault("backoff_base", 1e-4)
    if inj is not None:
        kw.setdefault("wrap_scan", inj.wrap)
    return RobustPolicy(**kw)


def _same_params(a, b, rtol=None):
    for f in ("Lam", "A", "Q", "R", "mu0", "P0"):
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        if rtol is None:
            np.testing.assert_array_equal(x, y, err_msg=f)
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, err_msg=f)


def _chunk_dispatches(tr):
    return [e for e in tr.events if e.get("kind") == "dispatch"
            and "em_chunk" in e.get("program", "")]


def _blocking_counts(tr):
    """(barrier'd chunk dispatches, blocking transfer events) — their sum
    is the host-barrier count the chunk driver paid."""
    barr = sum(1 for e in _chunk_dispatches(tr) if e.get("barrier"))
    btr = sum(1 for e in tr.events if e.get("kind") == "transfer"
              and e.get("blocking"))
    return barr, btr


# ---------------------------------------------------------------- units --

def test_pipeline_config_resolution():
    assert not resolve_pipeline(None).active
    assert not resolve_pipeline(False).active
    assert resolve_pipeline(True) == PipelineConfig(depth=2)
    cfg = resolve_pipeline(3)
    assert cfg.depth == 3 and not cfg.bucket      # bucketing stays opt-in
    explicit = PipelineConfig(depth=2, bucket=True)
    assert resolve_pipeline(explicit) is explicit and explicit.active
    assert PipelineConfig(depth=1, bucket=True).active
    with pytest.raises(TypeError, match="pipeline"):
        resolve_pipeline("fast")
    with pytest.raises(ValueError, match="depth"):
        PipelineConfig(depth=0)


def test_compile_cache_resolution(monkeypatch, tmp_path):
    # Library mode (fit()): an unset env NEVER creates a default dir.
    monkeypatch.delenv(CACHE_ENV, raising=False)
    assert setup_compile_cache(ambient_only=True) is None
    for off in ("", "0", "off", "disabled"):
        monkeypatch.setenv(CACHE_ENV, off)
        assert setup_compile_cache() is None
        assert setup_compile_cache(ambient_only=True) is None
    # Explicit disable wins over an env value.
    monkeypatch.setenv(CACHE_ENV, str(tmp_path))
    assert setup_compile_cache("off") is None
    # Entry counting tolerates absent/None dirs.
    assert compile_cache_entries(None) == 0
    assert compile_cache_entries(str(tmp_path / "nope")) == 0
    (tmp_path / "sub").mkdir()
    (tmp_path / "a").write_text("x")
    (tmp_path / "sub" / "b").write_text("y")
    assert compile_cache_entries(str(tmp_path)) == 2


def test_fit_rejects_bad_pipeline(panel):
    with pytest.raises(TypeError, match="pipeline"):
        fit(MODEL, panel, backend="tpu", max_iters=2, pipeline="deep")


# ----------------------------------------- healthy-path bit-identity ----

PIPES = [2, PipelineConfig(depth=2, bucket=True),
         PipelineConfig(depth=3, bucket=True)]


@pytest.mark.parametrize("robust", [False, True])
def test_single_device_pipelined_identical(panel, robust):
    b = TPUBackend(fused_chunk=3)                  # 8 iters -> 3,3,2: a tail
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=robust)
    for pipe in PIPES:
        r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                robust=robust, pipeline=pipe)
        np.testing.assert_array_equal(r.logliks, r0.logliks)
        _same_params(r.params, r0.params)
        assert r.n_iters == r0.n_iters and r.converged == r0.converged


def test_single_device_pipelined_identical_f32(panel):
    b = TPUBackend(dtype=jnp.float32, fused_chunk=3)
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False)
    # Pure depth runs the SAME programs: exact even in f32.
    r2 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False,
             pipeline=2)
    np.testing.assert_array_equal(r2.logliks, r0.logliks)
    _same_params(r2.params, r0.params)
    # Bucketed tail padding recompiles one fused-length program; f32 is
    # checked to tolerance (x64 exactness is pinned above).
    rb = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False,
             pipeline=PipelineConfig(depth=2, bucket=True))
    np.testing.assert_allclose(rb.logliks, r0.logliks, rtol=2e-5)
    _same_params(rb.params, r0.params, rtol=2e-4)


@pytest.mark.parametrize("robust", [False, True])
def test_sharded_pipelined_identical(panel, robust):
    b = ShardedBackend(n_devices=8, fused_chunk=3)
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=robust)
    for pipe in PIPES[:2]:
        r = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
                robust=robust, pipeline=pipe)
        np.testing.assert_array_equal(r.logliks, r0.logliks)
        _same_params(r.params, r0.params)


@pytest.mark.parametrize("backend", ["tpu", "sharded"])
def test_batched_pipelined_identical(panels, backend):
    spec = DFMBatchSpec(Y=panels, model=MODEL)
    kw = dict(backend=backend, max_iters=10, tol=1e-8, fused_chunk=3,
              with_metrics=True)
    if backend == "sharded":
        kw["n_devices"] = 4
    r0 = fit_many(spec, **kw)
    for pipe in PIPES[:2]:
        r = fit_many(spec, pipeline=pipe, **kw)
        for p, p0 in zip(r.params, r0.params):
            _same_params(p, p0)
        for a, b in zip(r.logliks, r0.logliks):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(r.metrics, r0.metrics)
        np.testing.assert_array_equal(r.converged, r0.converged)
        np.testing.assert_array_equal(r.p_iters, r0.p_iters)


# ------------------------------------------------------- fault parity ---

def test_nan_divergence_mid_pipeline_same_recovery(panel):
    """An injected NaN chunk lands while younger chunks are in flight; the
    guard discards them and replays from its last-good checkpoint — the
    recovery trajectory must match the serial guard's exactly."""
    b = TPUBackend(fused_chunk=2)
    rs = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(FaultInjector().nan_chunk(1),
                                 recover_divergence=True))
    rp = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(FaultInjector().nan_chunk(1),
                                 recover_divergence=True),
             pipeline=2)
    assert np.isfinite(rp.logliks).all()
    np.testing.assert_array_equal(rp.logliks, rs.logliks)
    _same_params(rp.params, rs.params)
    assert ([e.kind for e in rp.health.events]
            == [e.kind for e in rs.health.events])
    assert rp.health.n_recoveries == rs.health.n_recoveries >= 1


def test_nan_record_only_mid_pipeline_same_trace(panel):
    # Default policy keeps the NaN chunk in the trace (legacy semantics);
    # NaN != NaN, hence equal_nan.
    b = TPUBackend(fused_chunk=2)
    inj = FaultInjector().nan_chunk(1)
    rs = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(FaultInjector().nan_chunk(1)))
    rp = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(inj), pipeline=2)
    assert np.array_equal(rp.logliks, rs.logliks, equal_nan=True)
    _same_params(rp.params, rs.params)
    assert np.isnan(rp.logliks[2:4]).all()


def test_dispatch_failure_mid_pipeline_retried(panel):
    b = TPUBackend(fused_chunk=2)
    rs = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(FaultInjector().dispatch_failure(at=1,
                                                                  count=2)))
    rp = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0,
             robust=quick_policy(FaultInjector().dispatch_failure(at=1,
                                                                  count=2)),
             pipeline=2)
    np.testing.assert_array_equal(rp.logliks, rs.logliks)
    _same_params(rp.params, rs.params)
    assert rp.health.n_dispatch_retries == rs.health.n_dispatch_retries == 2
    assert ([e.action for e in rp.health.events
             if e.kind == "dispatch_error"]
            == ["retried", "retried"])


# --------------------------------------------- blocking-transfer budget --

def _traced_fit(panel, robust, pipeline):
    tr = Tracer(detector=RecompileDetector())
    fit(MODEL, panel, backend=TPUBackend(fused_chunk=2), max_iters=8,
        tol=0.0, robust=robust, telemetry=tr, pipeline=pipeline)
    return tr


@pytest.mark.parametrize("robust", [False, quick_policy()])
def test_depth2_halves_blocking_transfers(panel, robust):
    # Serial: one barrier'd dispatch per chunk (4 chunks at fused_chunk=2).
    tr_s = _traced_fit(panel, robust, None)
    barr_s, btr_s = _blocking_counts(tr_s)
    assert (barr_s, btr_s) == (4, 0)
    # Depth 2: non-barrier speculative dispatches + one blocking pull per
    # round — the ISSUE bound is ceil(n_chunks/depth) + 1.
    tr_p = _traced_fit(panel, robust, 2)
    barr_p, btr_p = _blocking_counts(tr_p)
    assert barr_p == 0
    assert 0 < btr_p <= 4 // 2 + 1
    assert barr_p + btr_p < barr_s + btr_s
    # The speculative launches carry their queue position for the report.
    depths = [e.get("queue_depth") for e in _chunk_dispatches(tr_p)]
    assert max(d for d in depths if d is not None) == 2
    # Summary arithmetic: chunk barriers + blocking pulls (+1 smooth
    # barrier outside the chunk driver) land in ``blocking_transfers``.
    s = summarize(tr_p.events)
    assert s["blocking_transfers"] < summarize(tr_s.events)[
        "blocking_transfers"]


def test_batched_depth2_blocking_budget(panels):
    spec = DFMBatchSpec(Y=panels, model=MODEL)
    kw = dict(backend="tpu", max_iters=12, tol=1e-12, fused_chunk=3)
    tr_s = Tracer(detector=RecompileDetector())
    from dfm_tpu.obs.trace import activate
    with activate(tr_s):
        fit_many(spec, **kw)
    barr_s, btr_s = _blocking_counts(tr_s)
    assert barr_s == 4 and btr_s == 0              # 12 iters / 3 = 4 chunks
    tr_p = Tracer(detector=RecompileDetector())
    with activate(tr_p):
        fit_many(spec, pipeline=PipelineConfig(depth=2, bucket=True), **kw)
    barr_p, btr_p = _blocking_counts(tr_p)
    assert barr_p == 0 and 0 < btr_p <= 4 // 2 + 1


# ------------------------------------------- bucketed executable reuse --

def test_bucketed_fit_compiles_one_chunk_executable(panel):
    det = RecompileDetector()
    b = TPUBackend(fused_chunk=3)
    pipe = PipelineConfig(depth=2, bucket=True)
    tr1 = Tracer(detector=det)
    fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False,
        telemetry=tr1, pipeline=pipe)
    keys = {e["key"] for e in _chunk_dispatches(tr1)}
    assert len(keys) == 1 and keys.pop().endswith("iters3b")
    assert sum(e.get("recompile", False)
               for e in _chunk_dispatches(tr1)) == 0
    # Second same-shape fit against the SAME detector: zero first-calls,
    # zero recompiles — the single bucketed executable served every chunk.
    tr2 = Tracer(detector=det)
    fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False,
        telemetry=tr2, pipeline=pipe)
    assert all(not e.get("first_call") and not e.get("recompile")
               for e in _chunk_dispatches(tr2))
    # Serial control: the 3,3,2 tail split needs a second executable.
    tr3 = Tracer(detector=RecompileDetector())
    fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, robust=False,
        telemetry=tr3)
    assert len({e["key"] for e in _chunk_dispatches(tr3)}) == 2


def test_bucket_degrades_in_debug_mode(panel):
    """Debug (checkify) fits have no bucketed twin: the driver silently
    falls back to unbucketed chunk programs instead of failing."""
    b = TPUBackend(fused_chunk=3, debug=True)
    r0 = fit(MODEL, panel, backend=b, max_iters=4, tol=0.0, robust=False,
             debug=True)
    tr = Tracer(detector=RecompileDetector())
    r = fit(MODEL, panel, backend=b, max_iters=4, tol=0.0, robust=False,
            debug=True, pipeline=PipelineConfig(depth=2, bucket=True),
            telemetry=tr)
    np.testing.assert_array_equal(r.logliks, r0.logliks)
    assert not any(e["key"].endswith("b") for e in _chunk_dispatches(tr))


# --------------------------------------------------- report rendering ---

def _disp(key="x//iters8b", **kw):
    ev = dict(kind="dispatch", t=0.0, dur=0.1, program="em_chunk", key=key,
              barrier=False, first_call=False, recompile=False, n_iters=8)
    ev.update(kw)
    return ev


def test_report_bucketed_reuse_vs_churn(capsys):
    # Bucketed, zero recompiles -> the reuse note.
    s = summarize([_disp(bucket=8, queue_depth=2),
                   _disp(bucket=8, queue_depth=1),
                   dict(kind="transfer", t=0.3, dur=0.01, program="em_chunk",
                        direction="d2h", blocking=True, n_iters=8),
                   dict(kind="transfer", t=0.2, dur=0.01, program="em_chunk",
                        direction="d2h", blocking=False, n_iters=8)])
    p = s["programs"]["em_chunk"]
    assert p["bucketed_dispatches"] == 2
    assert p["speculative_dispatches"] == 1 and p["max_queue_depth"] == 2
    assert s["blocking_transfers"] == 1 and s["nonblocking_transfers"] == 1
    _print_text(s)
    out = capsys.readouterr().out
    assert "bucketed reuse" in out
    assert "overlapped by the dispatch pipeline" in out
    # Recompiles despite bucketing -> genuine churn, not tail-chunk noise.
    s2 = summarize([_disp(bucket=8), _disp(bucket=8, key="y//iters8b",
                                           first_call=True, recompile=True)])
    _print_text(s2)
    out2 = capsys.readouterr().out
    assert "RECOMPILE" in out2 and "genuine churn" in out2
    assert "bucketed reuse" not in out2


def test_report_compile_cache_section(capsys):
    s = summarize([_disp(), dict(kind="compile_cache", t=1.0,
                                 dir="/tmp/cc", entries=5, new_entries=0)])
    assert s["compile_cache"] == {"dir": "/tmp/cc", "entries": 5,
                                  "new_entries": 0}
    _print_text(s)
    assert "warm" in capsys.readouterr().out


# ------------------------------------------------ persistent cache -----

_CACHE_SCRIPT = r'''
import json
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.utils import dgp
rng = np.random.default_rng(0)
p = dgp.dfm_params(N=10, k=2, rng=rng)
Y, _ = dgp.simulate(p, T=30, rng=rng)
res = fit(DynamicFactorModel(n_factors=2, standardize=False), Y,
          max_iters=4, tol=0.0, telemetry=True, pipeline=2)
cc = (res.telemetry or {}).get("compile_cache") or {}
print(json.dumps({"entries": cc.get("entries"),
                  "new": cc.get("new_entries")}))
'''


def test_compile_cache_warm_across_processes(tmp_path):
    """Fresh process + warm DFM_COMPILE_CACHE: every executable loads from
    disk (zero new cache entries on the second run)."""
    env = dict(os.environ, DFM_COMPILE_CACHE=str(tmp_path / "cc"),
               PYTHONPATH=REPO)
    for k in ("DFM_TRACE", "DFM_RUNS"):
        env.pop(k, None)

    def run():
        out = subprocess.run([sys.executable, "-c", _CACHE_SCRIPT],
                             env=env, capture_output=True, text=True,
                             timeout=560, cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["new"] and cold["new"] > 0         # populated the cache
    warm = run()
    assert warm["new"] == 0                        # fully served from disk
    assert warm["entries"] == cold["entries"]

"""Micro-profiler (``obs.profile``), Chrome trace export
(``obs.report --chrome``), latency percentiles, and the fused-path cost
capture — on the fake 8-device mesh (conftest).

The round-trip contract under test (ISSUE 7 satellites 1–2): a depth-2
pipelined trace survives ``to_chrome`` with one "X" span per dispatch on
the device track and blocking transfers on the host track; the fused
path reports ``maybe_cost``/recompile telemetry, and the donated warm
twin is the SAME logical program as the cold fit (no recompile).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.obs import Tracer, summarize
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.profile import VARIANTS, profile_shape
from dfm_tpu.obs.report import to_chrome
from dfm_tpu.utils import dgp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, T, K = 16, 40, 2


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(N, K, rng)
    Y, _ = dgp.simulate(p, T, rng)
    return (Y - Y.mean(0)) / Y.std(0)


# -- profiler ----------------------------------------------------------------

def test_profile_shape_measures_all_variants(monkeypatch, tmp_path):
    # profile_shape masks DFM_RUNS itself; set one to prove it is restored
    # and that the probes never leak fit records into it.
    monkeypatch.setenv("DFM_RUNS", str(tmp_path / "masked"))
    records, device = profile_shape(N, T, K, iters=8, repeats=1)
    assert os.environ["DFM_RUNS"] == str(tmp_path / "masked")
    assert not (tmp_path / "masked").exists()
    assert device.startswith("cpu")
    assert [r["config"]["profile"] for r in records] == list(VARIANTS)
    by = {r["config"]["profile"]: r for r in records}
    for variant, rec in by.items():
        assert rec["kind"] == "profile"
        cfg = rec["config"]
        assert (cfg["N"], cfg["T"], cfg["k"]) == (N, T, K)
        assert cfg["device"] == "cpu" and cfg["chunk"] == 8
        m = rec["metrics"]
        assert m["warm_wall_s"] > 0 and m["cold_wall_s"] > 0
        assert m["ms_per_iter_warm"] == pytest.approx(
            1e3 * m["warm_wall_s"] / 8)
        assert m["dispatches"] >= 1
    assert by["pipelined"]["config"]["depth"] == 2
    m = by["chunked"]["metrics"]
    assert m["sustained_ms_per_iter"] > 0
    assert m["dispatch_ms_per_program"] >= 0
    assert m["flops_per_iter"] > 0          # capture_costs fed the record
    assert by["fused"]["metrics"]["dispatches_per_fit"] >= 1
    assert by["fused"]["metrics"]["flops_per_iter"] > 0


def test_profile_shape_rejects_unknown_variant():
    with pytest.raises(ValueError, match="unknown profile variant"):
        profile_shape(N, T, K, iters=4, repeats=1, variants=["turbo"])


def test_profile_cli_persists_records(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.profile", "--shape",
         f"{N},{T},{K}", "--iters", "6", "--repeats", "1",
         "--variants", "chunked,fused", "--json"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env=dict(os.environ, DFM_RUNS=str(tmp_path),
                 JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    recs = json.loads(out.stdout)
    assert [r["config"]["profile"] for r in recs] == ["chunked", "fused"]
    from dfm_tpu.obs import store as obs_store
    persisted = obs_store.RunStore(str(tmp_path)).load()
    assert [r["config"]["profile"] for r in persisted
            if r["kind"] == "profile"] == ["chunked", "fused"]


# -- latency percentiles -----------------------------------------------------

def test_summarize_dispatch_percentiles_exact():
    evs = [{"kind": "dispatch", "program": "p", "t": float(i),
            "dur": 0.001 * (i + 1)} for i in range(10)]
    s = summarize(evs)
    dp = s["dispatch_percentiles_ms"]
    assert dp["n"] == 10
    # Nearest-rank over durations 1..10 ms.
    assert dp["p50"] == pytest.approx(5.0)
    assert dp["p90"] == pytest.approx(9.0)
    assert dp["p99"] == pytest.approx(10.0)
    assert s["programs"]["p"]["steady_s"]["p99"] == pytest.approx(0.010)


def test_summarize_e2e_percentiles_from_barriers():
    evs = [{"kind": "dispatch", "program": "p", "t": 0.0, "dur": 0.5,
            "barrier": True},
           {"kind": "dispatch", "program": "p", "t": 0.6, "dur": 0.7,
            "barrier": True}]
    s = summarize(evs)
    e2e = s["programs"]["p"]["e2e_s"]
    assert e2e["n"] == 2
    assert e2e["p99"] == pytest.approx(0.7)


# -- Chrome export -----------------------------------------------------------

def test_chrome_roundtrip_depth2_pipelined_trace(panel, tmp_path):
    tr = Tracer()
    b = TPUBackend(dtype=jnp.float64, filter="info")
    fit(DynamicFactorModel(n_factors=K), panel, backend=b, max_iters=12,
        tol=1e-8, pipeline=2, telemetry=tr)
    trace_path = tmp_path / "trace.jsonl"
    with open(trace_path, "w") as fh:
        for e in tr.events:
            fh.write(json.dumps(e, default=str) + "\n")

    chrome = to_chrome(tr.events)
    evs = chrome["traceEvents"]
    dispatches = [e for e in tr.events if e.get("kind") == "dispatch"]
    spans = [e for e in evs if e.get("ph") == "X"]
    dev_spans = [e for e in spans if e["pid"] == 0]
    assert len(dev_spans) == len(dispatches)      # one span per dispatch
    assert all(e["cat"] == "dispatch" for e in dev_spans)
    assert {e["name"] for e in dev_spans} == \
        {e["program"] for e in dispatches}
    # Blocking transfers land on the host track, flagged by name.
    host_spans = [e for e in spans if e["pid"] == 1]
    assert any(e["name"] == "transfer (blocking)" for e in host_spans)
    # Timestamps are rebased and non-negative; durations in µs.
    assert min(e["ts"] for e in spans) >= 0.0
    assert all(e["dur"] >= 0.0 for e in spans)
    # Both process tracks are named, plus one thread lane per program.
    meta = [e for e in evs if e.get("ph") == "M"]
    assert {m["pid"] for m in meta if m["name"] == "process_name"} == {0, 1}
    lanes = {m["args"]["name"] for m in meta if m["name"] == "thread_name"}
    assert {e["program"] for e in dispatches} <= lanes

    # CLI round-trip: --chrome writes the same export, summary still prints.
    out_json = tmp_path / "chrome.json"
    cli = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(trace_path),
         "--chrome", str(out_json)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert cli.returncode == 0, cli.stderr
    loaded = json.loads(out_json.read_text())
    assert loaded["displayTimeUnit"] == "ms"
    reload_spans = [e for e in loaded["traceEvents"]
                    if e.get("ph") == "X" and e.get("pid") == 0]
    assert len(reload_spans) == len(dev_spans)
    assert "dispatch walls" in cli.stdout


def test_chrome_empty_trace():
    assert to_chrome([]) == {"traceEvents": [], "displayTimeUnit": "ms"}


# -- fused cost capture + recompile telemetry (satellite 2) ------------------

def test_fused_cost_captured_and_warm_twin_same_program(panel):
    tr = Tracer(capture_costs=True, detector=RecompileDetector())
    b = TPUBackend(dtype=jnp.float64, filter="info")
    model = DynamicFactorModel(n_factors=K)
    cold = fit(model, panel, backend=b, max_iters=12, tol=1e-8, fused=True,
               telemetry=tr)
    fit(model, panel, backend=b, max_iters=12, tol=1e-8, fused=True,
        warm_start=cold, telemetry=tr)
    s = tr.summary()
    # maybe_cost fed static flops/bytes for the fused program.
    assert s["costs"]["fused_fit"]["flops"] > 0
    assert s["costs"]["fused_fit"]["bytes_accessed"] > 0
    # The donated warm twin is the SAME logical program: two dispatches,
    # one first_call, zero recompiles.
    prog = s["programs"]["fused_fit"]
    assert prog["dispatches"] == 2
    assert prog["first_calls"] == 1
    assert prog.get("recompiles", 0) == 0

"""Device-side PCA initializer (estim.init): quality + cache safety."""

import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.init import pca_init_device
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(17)
    p = dgp.dfm_params(64, 3, rng)
    Y, _ = dgp.simulate(p, 90, rng)
    return (Y - Y.mean(0)) / Y.std(0)


def test_device_init_spans_host_init_subspace(panel):
    """Gram-eigh loadings span the same top-k subspace as the host SVD
    (signs/rotations within the space are irrelevant to EM)."""
    p_host = cpu_ref.pca_init(panel, 3)
    p_dev = pca_init_device(panel, 3, dtype=np.float64)
    V1 = p_host.Lam / np.linalg.norm(p_host.Lam, axis=0)
    V2 = np.asarray(p_dev.Lam) / np.linalg.norm(p_dev.Lam, axis=0)
    np.testing.assert_allclose(V1 @ V1.T, V2 @ V2.T, atol=1e-8)
    np.testing.assert_allclose(np.asarray(p_dev.R), p_host.R, atol=1e-8)


def test_device_init_fit_reaches_same_optimum(panel):
    model = DynamicFactorModel(n_factors=3)
    r_host = fit(model, panel, backend=TPUBackend(), max_iters=30, tol=0.0)
    r_dev = fit(model, panel, backend=TPUBackend(device_init=True),
                max_iters=30, tol=0.0)
    assert abs(r_dev.loglik - r_host.loglik) < 1e-6 * abs(r_host.loglik)


def test_device_init_masked_panel_cache_hits_and_is_mask_safe(panel):
    """ADVICE r4 item 1: the cache is keyed on the CALLER'S panel object (so
    fit()'s pre-filled masked panel hits it), and carries the mask identity
    (so a different mask can never see the old mask's zero-fill)."""
    import jax.numpy as jnp
    rng = np.random.default_rng(19)
    W = dgp.random_mask(90, 64, rng, 0.15)
    Yz = np.where(W > 0, panel, 0.0)
    b = TPUBackend(device_init=True, dtype=jnp.float64)
    model = DynamicFactorModel(n_factors=3)
    b.default_init(Yz, W, model)
    got = b._device_panel(Yz, W, jnp.float64)
    assert b._panel_cache is None          # one-shot
    np.testing.assert_array_equal(np.asarray(got), Yz)
    # Same panel object under a DIFFERENT mask must MISS (values were filled
    # under the first mask).
    b.default_init(Yz, W, model)
    W2 = dgp.random_mask(90, 64, rng, 0.15)
    assert b._device_panel(Yz, W2, jnp.float64) is not None
    # identity check inside: fresh transfer, not the cached object
    b.default_init(Yz, W, model)
    cached = b._panel_cache[2]
    assert b._device_panel(Yz, W2, jnp.float64) is not cached


@pytest.fixture(scope="module")
def raw_panel():
    """UN-standardized panel (nonzero means, heterogeneous scales) so the
    standardization step actually matters."""
    rng = np.random.default_rng(23)
    p = dgp.dfm_params(64, 3, rng)
    Y, _ = dgp.simulate(p, 90, rng)
    return Y * np.exp(rng.normal(size=64)) + 10.0 * rng.normal(size=64)


def test_device_prep_standardize_matches_host(raw_panel):
    """fit() with device-side standardization (prep_standardize) reproduces
    the host-prep fit: same transform, same loglik trajectory.  x64 CPU runs
    make the device stats near-exact; the residual tolerance is summation
    order."""
    from dfm_tpu.utils.data import standardize
    model = DynamicFactorModel(n_factors=3)
    r_host = fit(model, raw_panel, backend=TPUBackend(device_init=False),
                 max_iters=8, tol=0.0)
    b = TPUBackend(device_init=True)
    r_dev = fit(model, raw_panel, backend=b, max_iters=8, tol=0.0)
    _, std_host = standardize(raw_panel)
    np.testing.assert_allclose(r_dev.standardizer.mean, std_host.mean,
                               rtol=1e-9)
    np.testing.assert_allclose(r_dev.standardizer.scale, std_host.scale,
                               rtol=1e-9)
    # Host init (SVD) vs device init (Gram eigh) start EM from different
    # rotations of the same subspace; compare the trajectory through the
    # rotation-invariant loglik.
    np.testing.assert_allclose(r_dev.logliks, r_host.logliks,
                               rtol=1e-6, atol=1e-5)


def test_device_prep_skips_missing_data(raw_panel):
    """A NaN anywhere routes prep to the host masked path: the standardizer
    must be the HOST masked transform bit-for-bit (prep_standardize never
    sees the panel)."""
    from dfm_tpu.utils.data import build_mask, standardize
    Y = raw_panel.copy()
    Y[5, 7] = np.nan
    model = DynamicFactorModel(n_factors=3)
    r = fit(model, Y, backend=TPUBackend(device_init=True), max_iters=3)
    W = build_mask(Y)
    _, std_host = standardize(Y, mask=W)
    np.testing.assert_array_equal(r.standardizer.mean, std_host.mean)
    np.testing.assert_array_equal(r.standardizer.scale, std_host.scale)
    assert np.isfinite(r.loglik)


def test_device_prep_sharded(raw_panel):
    """ShardedBackend device prep (N divisible by the mesh) matches the
    host-prep sharded fit; a non-divisible N falls back to the host path."""
    from dfm_tpu.api import ShardedBackend
    model = DynamicFactorModel(n_factors=3)
    r_host = fit(model, raw_panel, backend=ShardedBackend(device_init=False),
                 max_iters=6, tol=0.0)
    r_dev = fit(model, raw_panel, backend=ShardedBackend(device_init=True),
                max_iters=6, tol=0.0)
    np.testing.assert_allclose(r_dev.logliks, r_host.logliks,
                               rtol=1e-6, atol=1e-5)
    # 63 series over an 8-device mesh: prep must decline (padding needs the
    # host panel) and the fit still run end-to-end through the host path.
    Y63 = np.ascontiguousarray(raw_panel[:, :63])
    b = ShardedBackend(device_init=True)
    assert b.prep_standardize(Y63, model) is None
    r63 = fit(model, Y63, backend=b, max_iters=3)
    assert np.isfinite(r63.loglik)


def test_device_init_panel_cache_not_reused_across_panels(panel):
    """The on-device panel cache is keyed by object identity: fitting a
    SECOND panel on the same backend must not reuse the first's data."""
    rng = np.random.default_rng(18)
    p2 = dgp.dfm_params(64, 3, rng)
    Y2, _ = dgp.simulate(p2, 90, rng)
    Y2 = (Y2 - Y2.mean(0)) / Y2.std(0)
    model = DynamicFactorModel(n_factors=3)
    b = TPUBackend(device_init=True)
    fit(model, panel, backend=b, max_iters=3)
    r_reused = fit(model, Y2, backend=b, max_iters=3)
    r_fresh = fit(model, Y2, backend=TPUBackend(device_init=True),
                  max_iters=3)
    np.testing.assert_allclose(r_reused.logliks, r_fresh.logliks, rtol=1e-10)


def test_device_prep_accepts_f32_panel(raw_panel):
    """A float32 input panel goes through device prep without an f64 host
    round trip and fits to the same optimum (f32-tolerance)."""
    model = DynamicFactorModel(n_factors=3)
    Y32 = np.asarray(raw_panel, np.float32)
    # the claimed behavior: f32 input is ACCEPTED by the device-prep path
    assert TPUBackend(device_init=True).prep_standardize(Y32, model) \
        is not None
    r32 = fit(model, Y32, backend=TPUBackend(device_init=True),
              max_iters=6, tol=0.0)
    r64 = fit(model, raw_panel, backend=TPUBackend(device_init=True),
              max_iters=6, tol=0.0)
    assert np.isfinite(r32.loglik)
    np.testing.assert_allclose(r32.loglik, r64.loglik, rtol=1e-4)

"""Perf observatory (ISSUE 4): run registry (``obs.store``), cross-run
regression gate (``obs.regress`` / ``report --diff``), device-side
per-iteration metrics, and the ``fit(progress=...)`` live hook — on the
fake 8-device mesh (conftest).

The operative acceptance checks: ``obs.regress`` detects an injected 2x
per-iter slowdown against stored history and exits nonzero; with metrics
and progress disabled, fit results are bit-identical to the PR 3 path and
the chunk-program dispatch count is unchanged (asserted via the tracer).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, ShardedBackend, TPUBackend, fit
from dfm_tpu.obs import Tracer, activate
from dfm_tpu.obs import regress as obs_regress
from dfm_tpu.obs import store as obs_store
from dfm_tpu.utils import dgp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(16, 2, rng)
    Y, _ = dgp.simulate(p, 40, rng)
    return (Y - Y.mean(0)) / Y.std(0)


def _fit(Y, **kw):
    kw.setdefault("max_iters", 12)
    kw.setdefault("tol", 1e-8)
    return fit(DynamicFactorModel(n_factors=2), Y,
               backend=TPUBackend(dtype=jnp.float64, filter="info"), **kw)


def _bench_record(run_id, value, *, metric="em_iters_per_sec_s1",
                  loglik=None, t_unix=None):
    return obs_store.make_record(
        "bench", {"bench": "headline", "metric": metric, "device": "cpu"},
        {metric: value}, loglik=loglik, run_id=run_id, t_unix=t_unix)


# -- store: round-trip, baselines, damage tolerance ----------------------

def test_store_roundtrip_and_query(tmp_path):
    store = obs_store.RunStore(str(tmp_path / "runs"))
    r1 = _bench_record("a", 100.0, t_unix=1.0)
    r2 = _bench_record("b", 120.0, t_unix=2.0)
    other = obs_store.make_record("fit", {"fit": "DFM"}, {"wall_s": 3.0},
                                  run_id="c", t_unix=3.0)
    for r in (r1, r2, other):
        store.append(r)
    recs = store.load()
    assert [r["run_id"] for r in recs] == ["a", "b", "c"]
    assert store.get("b")["metrics"]["em_iters_per_sec_s1"] == 120.0
    assert store.get("nope") is None
    fp = r1["fingerprint"]
    assert fp == r2["fingerprint"] != other["fingerprint"]
    assert [r["run_id"] for r in store.query(fp)] == ["a", "b"]
    assert store.latest()["run_id"] == "c"
    assert store.latest(kind="bench")["run_id"] == "b"


def test_store_skips_corrupt_lines(tmp_path, capsys):
    store = obs_store.RunStore(str(tmp_path))
    store.append(_bench_record("a", 1.0))
    with open(store.file, "a") as f:
        f.write('{"run_id": "tr'          # killed mid-append
                '\nnot json at all\n[1, 2]\n')
    store.append(_bench_record("b", 2.0))
    recs = store.load()
    assert [r["run_id"] for r in recs] == ["a", "b"]
    assert "corrupt record skipped" in capsys.readouterr().err


def test_baseline_is_median_of_best_n(tmp_path):
    store = obs_store.RunStore(str(tmp_path))
    for i, v in enumerate([100.0, 200.0, 300.0, 400.0, 500.0, 600.0]):
        store.append(_bench_record(f"r{i}", v, t_unix=float(i)))
    fp = store.load()[0]["fingerprint"]
    # throughput: best 3 = [600, 500, 400] -> median 500
    assert store.baseline(fp, "em_iters_per_sec_s1", best_n=3) == 500.0
    # exclude_run drops the candidate itself from its own baseline
    assert store.baseline(fp, "em_iters_per_sec_s1", best_n=3,
                          exclude_run="r5") == 400.0
    assert store.baseline(fp, "missing_metric") is None
    # a wall-clock metric picks the SMALLEST values as "best"
    for i, v in enumerate([9.0, 5.0, 7.0]):
        store.append(obs_store.make_record(
            "bench", {"metric": "wall"}, {"wall_s": v}, run_id=f"w{i}"))
    fpw = obs_store.fingerprint({"metric": "wall"})
    assert store.baseline(fpw, "wall_s", best_n=3) == 7.0


def test_metric_direction_markers():
    assert obs_store.lower_is_better("amortized_ms_per_iter")
    assert obs_store.lower_is_better("wall_s")
    assert obs_store.lower_is_better("loglik_rel_err_iter3")
    assert not obs_store.lower_is_better("em_iters_per_sec_sustained")
    assert not obs_store.lower_is_better("vs_baseline")


# -- backfill importer on the checked-in artifacts ------------------------

def test_backfill_checked_in_artifacts(tmp_path):
    store = obs_store.RunStore(str(tmp_path))
    n = obs_store.backfill(REPO, store=store)
    recs = store.load()
    assert n == len(recs) >= 5          # 5 BENCH_r rounds + BENCH_ALL
    kinds = {r["kind"] for r in recs}
    assert "bench" in kinds and "bench_all" in kinds
    # the bench records carry the real device string + a numeric metric
    bench = [r for r in recs if r["kind"] == "bench"]
    assert any(r["device"] and "TPU" in r["device"] for r in bench)
    for r in recs:
        assert r["metrics"], r["run_id"]
        assert r["fingerprint"]
    # idempotent: a second import appends nothing
    assert obs_store.backfill(REPO, store=store) == 0
    assert len(store.load()) == len(recs)


def test_backfill_seeds_complete_serving_baseline(tmp_path):
    """Every bench family's artifact is checked in (PR 17 satellite):
    one ``store backfill`` on a fresh registry seeds a regression
    baseline for EVERY serving CLI — including the engine-leg metrics —
    and a second import appends nothing."""
    store = obs_store.RunStore(str(tmp_path))
    obs_store.backfill(REPO, store=store)
    recs = store.load()
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    for kind in ("bench", "bench_all", "bench_longt", "bench_kscale",
                 "bench_stream", "bench_serve", "bench_mixed",
                 "bench_fleet", "bench_daemon", "bench_drift"):
        assert kind in by_kind, f"no checked-in artifact seeds {kind}"
    # The engine-leg speedups ride the fleet/stream artifacts so
    # obs.regress gates them from the first live run.
    fleet_metrics = {k for r in by_kind["bench_fleet"]
                     for k in r["metrics"]}
    stream_metrics = {k for r in by_kind["bench_stream"]
                      for k in r["metrics"]}
    assert "fleet_widek_speedup" in fleet_metrics
    assert "stream_pit_speedup" in stream_metrics
    assert obs_store.backfill(REPO, store=store) == 0
    assert len(store.load()) == len(recs)


def test_backfill_glob_infers_kind_per_file(tmp_path):
    """The importer sweeps EVERY ``BENCH_*.json`` (not a hand-kept list):
    a new bench CLI's checked-in artifact seeds history the moment it
    lands, with its family kind inferred from the filename so
    ``obs.regress`` fingerprints match the live CLI's records."""
    root = tmp_path / "repo"
    root.mkdir()
    line = {"metric": "m", "value": 1.0, "shape": [2, 2]}
    for name, run in (("BENCH_longt.json", "lt1"),
                      ("BENCH_kscale.json", "ks1"),
                      ("BENCH_r07.json", "r7"),
                      ("BENCH_novel2.json", "nv1")):
        (root / name).write_text(json.dumps(
            {"parsed": dict(line, run_id=run), "tail": ""}))
    store = obs_store.RunStore(str(tmp_path / "runs"))
    assert obs_store.backfill(str(root), store=store) == 4
    kinds = {r["run_id"]: r["kind"] for r in store.load()}
    assert kinds == {"lt1": "bench_longt", "ks1": "bench_kscale",
                     "r7": "bench",        # BENCH_r<round> stays plain
                     "nv1": "bench"}       # unknown families default
    # idempotent across the glob too
    assert obs_store.backfill(str(root), store=store) == 0


def test_store_cli_backfill_and_list(tmp_path):
    env = dict(os.environ, DFM_RUNS=str(tmp_path))
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.store", "backfill",
         "--root", REPO], capture_output=True, text=True, timeout=120,
        cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr
    assert "backfilled" in out.stdout
    ls = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.store", "list", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert ls.returncode == 0, ls.stderr
    assert len(json.loads(ls.stdout)) >= 5


# -- regress: the 2x-slowdown gate (acceptance criterion) -----------------

def _seed_history(runs, *, n=3):
    store = obs_store.RunStore(str(runs))
    for i in range(n):
        store.append(_bench_record(f"h{i}", 1000.0 + i, loglik=-500.0,
                                   t_unix=float(i)))
    return store


def _regress(args, runs):
    return subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.regress", *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=dict(os.environ, DFM_RUNS=str(runs)))


def test_regress_detects_2x_slowdown(tmp_path):
    store = _seed_history(tmp_path)
    store.append(_bench_record("cand", 500.0, loglik=-500.0))  # 2x slower
    out = _regress(["cand", "--json"], tmp_path)
    assert out.returncode == 1, out.stdout + out.stderr
    d = json.loads(out.stdout)
    assert not d["ok"]
    (chk,) = [c for c in d["checks"]
              if c["metric"] == "em_iters_per_sec_s1"]
    assert not chk["ok"] and chk["ratio"] < 0.6


def test_regress_ok_within_tolerance(tmp_path):
    store = _seed_history(tmp_path)
    store.append(_bench_record("cand", 950.0, loglik=-500.0))
    out = _regress(["cand"], tmp_path)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "regress: OK" in out.stdout


def test_regress_convergence_gate(tmp_path):
    store = _seed_history(tmp_path)
    # perf fine, but the final loglik fell: convergence regression
    store.append(_bench_record("cand", 1100.0, loglik=-520.0))
    out = _regress(["cand"], tmp_path)
    assert out.returncode == 1, out.stdout
    assert "REGRESSION" in out.stdout and "final loglik" in out.stdout


def test_regress_against_explicit_file(tmp_path):
    base = _bench_record("base", 1000.0)
    cand = _bench_record("cand", 490.0)
    bp, cp = tmp_path / "base.json", tmp_path / "cand.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cand))
    out = _regress([str(cp), "--against", str(bp)], tmp_path / "empty")
    assert out.returncode == 1, out.stdout + out.stderr
    # and the pure-API path agrees
    d = obs_regress.diff_records(cand, base)
    assert not d["ok"]
    d2 = obs_regress.diff_records(base, base)
    assert d2["ok"]


def test_regress_usage_errors(tmp_path):
    out = _regress(["no-such-run"], tmp_path)     # empty registry
    assert out.returncode == 2
    store = _seed_history(tmp_path)
    assert _regress(["still-missing"], tmp_path).returncode == 2
    # the latest run IS gated by default (no candidate argument)
    store.append(_bench_record("slow", 400.0))
    assert _regress([], tmp_path).returncode == 1


def test_regress_sub_noise_floor():
    # A lower-is-better metric with a TINY baseline must not flag on
    # absolute moves below its unit floor: a 0.6 -> 1.3 ms dispatch cost
    # (CPU-fallback jitter) is out of the 30% band but carries no signal,
    # while the same ratio at tunnel scale (60 -> 130 ms) is real.
    def rec(rid, ms):
        return obs_store.make_record(
            "bench", {"bench": "h", "metric": "m", "device": "cpu"},
            {"dispatch_ms_per_program": ms}, run_id=rid)
    d = obs_regress.diff_records(rec("cand", 1.3), rec("base", 0.6))
    assert d["ok"]
    (chk,) = d["checks"]
    assert chk["sub_noise"] and chk["ratio"] > 2.0
    d2 = obs_regress.diff_records(rec("cand", 130.0), rec("base", 60.0))
    assert not d2["ok"]
    # seconds floor: 1.888 -> 3.776 s is far above 50 ms and still gates
    def recs(rid, s):
        return obs_store.make_record(
            "bench", {"bench": "h", "metric": "m", "device": "cpu"},
            {"wall_s": s}, run_id=rid)
    assert not obs_regress.diff_records(recs("c", 3.776),
                                        recs("b", 1.888))["ok"]


def test_regress_reads_bench_r_wrapper(tmp_path):
    # a checked-in BENCH_r*.json wrapper is a valid --against baseline
    out = _regress(
        [os.path.join(REPO, "BENCH_r01.json"),
         "--against", os.path.join(REPO, "BENCH_r01.json")],
        tmp_path / "empty")
    assert out.returncode == 0, out.stdout + out.stderr


# -- report: damage tolerance + --diff ------------------------------------

def test_report_tolerates_truncated_trace(panel, tmp_path):
    trace = tmp_path / "t.jsonl"
    _fit(panel, telemetry=str(trace))
    whole = trace.read_text()
    cut = tmp_path / "cut.jsonl"
    # a process killed mid-append leaves a partial last line
    cut.write_text(whole[: int(len(whole) * 0.6)])
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(cut)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "skipping invalid JSONL" in out.stderr
    assert "dispatches:" in out.stdout
    # empty file: no events, still rc 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(empty)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert out.returncode == 0, out.stderr


def test_report_diff_traces(panel, tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _fit(panel, telemetry=str(a))
    _fit(panel, telemetry=str(b))
    out = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(a),
         "--diff", str(b)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    # same fit twice: never a CONVERGENCE regression; perf walls may
    # jitter, so only the exit-code domain is asserted
    assert out.returncode in (0, 1), out.stderr
    assert "final loglik" in out.stdout
    assert "[ok] final loglik" in out.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "dfm_tpu.obs.report", str(a),
         "--diff", "/does/not/exist.json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert bad.returncode == 2


def test_telemetry_summary_has_wall_and_phases(panel):
    r = _fit(panel, telemetry=True)
    s = r.telemetry
    assert s["wall_s"] > 0
    ph = s["phases"]
    assert set(ph) == {"dispatch_s", "transfer_s", "host_s"}
    assert all(v >= 0 for v in ph.values())
    assert ph["dispatch_s"] + ph["transfer_s"] <= s["wall_s"] + 1e-9


# -- fit(progress=...) + per-iteration metrics ----------------------------

def test_progress_callback_ordering(panel):
    infos = []
    r = _fit(panel, max_iters=20, tol=0.0, progress=infos.append)
    assert len(infos) >= 2                      # 20 iters / chunk 8 -> 3
    assert [i["chunk"] for i in infos] == list(range(len(infos)))
    iters = [i["iter"] for i in infos]
    assert iters == sorted(iters) and iters[-1] == r.n_iters
    assert infos[-1]["total"] == 20
    for info in infos:
        m = info["metrics"]
        assert m is not None and m.ndim == 2 and m.shape[1] == 3
        assert np.all(np.isfinite(m[:, 0]))     # loglik column
        assert info["elapsed_s"] > 0
    # in-loop metrics agree with the host-side loglik trajectory
    lls = np.concatenate([i["metrics"][:, 0] for i in infos])[:r.n_iters]
    np.testing.assert_allclose(lls, r.logliks, rtol=0, atol=0)
    # final chunk knows it stopped; ETA only meaningful before that
    assert infos[-1]["stopped"] or iters[-1] == 20
    assert infos[0]["eta_s"] is None or infos[0]["eta_s"] >= 0
    assert infos[-1]["dparam"] is not None and infos[-1]["dparam"] >= 0


def test_progress_on_sharded_backend(panel):
    infos = []
    r = fit(DynamicFactorModel(n_factors=2), panel,
            backend=ShardedBackend(dtype=jnp.float64, filter="info"),
            max_iters=12, tol=0.0, progress=infos.append)
    assert infos and infos[-1]["iter"] == r.n_iters
    assert infos[0]["metrics"] is not None
    lls = np.concatenate([i["metrics"][:, 0] for i in infos])[:r.n_iters]
    np.testing.assert_allclose(lls, r.logliks, rtol=0, atol=0)


def test_progress_off_is_bit_identical_same_dispatches(panel):
    """Acceptance: metrics/progress off -> bit-identical results AND an
    unchanged chunk-program dispatch count (the tracer is the witness)."""
    with activate(Tracer()) as tr_off:
        r_off = _fit(panel, max_iters=16, tol=0.0)
    infos = []
    with activate(Tracer()) as tr_on:
        r_on = _fit(panel, max_iters=16, tol=0.0, progress=infos.append)
    assert infos, "progress hook never fired"
    np.testing.assert_array_equal(r_off.logliks, r_on.logliks)
    np.testing.assert_array_equal(np.asarray(r_off.params.Lam),
                                  np.asarray(r_on.params.Lam))
    np.testing.assert_array_equal(np.asarray(r_off.params.A),
                                  np.asarray(r_on.params.A))

    def chunk_dispatches(tr):
        return sum(1 for e in tr.events if e["kind"] == "dispatch"
                   and e["program"] == "em_chunk")
    assert chunk_dispatches(tr_off) == chunk_dispatches(tr_on) > 0
    # chunk events carry dparams ONLY when the metrics twin ran
    assert not any("dparams" in e for e in tr_off.events
                   if e["kind"] == "chunk")
    assert all("dparams" in e for e in tr_on.events
               if e["kind"] == "chunk")


def test_progress_dparams_reach_report_curve(panel):
    tr = Tracer()
    r = _fit(panel, max_iters=12, tol=0.0, telemetry=tr,
             progress=lambda i: None)
    s = tr.summary()
    conv = s["convergence"]
    assert len(conv["dparams"]) == r.n_iters
    assert conv["dparam_last"] == conv["dparams"][-1]
    assert all(d >= 0 for d in conv["dparams"])


def test_progress_warns_on_family_and_cpu(panel):
    from dfm_tpu.models.tv_loadings import TVLSpec
    with pytest.warns(RuntimeWarning, match="progress"):
        fit(TVLSpec(n_factors=2, n_rounds=1), panel,
            progress=lambda i: None)
    with pytest.warns(RuntimeWarning, match="progress"):
        fit(DynamicFactorModel(n_factors=2), panel, backend="cpu",
            max_iters=2, progress=lambda i: None)


def test_batched_metrics_block():
    from dfm_tpu.estim.batched import DFMBatchSpec, fit_many
    rng = np.random.default_rng(5)
    Y = np.stack([rng.standard_normal((50, 10)) for _ in range(3)])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    spec = DFMBatchSpec(Y=Y, model=model)
    r_off = fit_many(spec, max_iters=10, tol=0.0, dtype=np.float64)
    r_on = fit_many(spec, max_iters=10, tol=0.0, dtype=np.float64,
                    with_metrics=True)
    assert r_off.metrics is None
    assert r_on.metrics.shape == (10, 3, 3)     # (iters, B, 3)
    np.testing.assert_array_equal(r_off.logliks_final, r_on.logliks_final)
    # metrics loglik column = the per-problem trajectories
    for b in range(3):
        np.testing.assert_allclose(r_on.metrics[: len(r_on.logliks[b]), b, 0],
                                   r_on.logliks[b], rtol=0, atol=0)


def test_sharded_batched_metrics_match_single(monkeypatch):
    from dfm_tpu.estim.batched import DFMBatchSpec, fit_many
    rng = np.random.default_rng(6)
    Y = np.stack([rng.standard_normal((50, 10)) for _ in range(3)])
    model = DynamicFactorModel(n_factors=2, dynamics="ar1")
    spec = DFMBatchSpec(Y=Y, model=model)
    r1 = fit_many(spec, max_iters=8, tol=0.0, dtype=np.float64,
                  with_metrics=True)
    r2 = fit_many(spec, backend="sharded", max_iters=8, tol=0.0,
                  dtype=np.float64, with_metrics=True, n_devices=2)
    assert r2.metrics.shape == r1.metrics.shape
    np.testing.assert_allclose(r2.metrics[:, :, 0], r1.metrics[:, :, 0],
                               rtol=0, atol=0)


# -- traced fits append to the registry (DFM_RUNS) ------------------------

def test_traced_fit_appends_run_record(panel, tmp_path, monkeypatch):
    monkeypatch.setenv("DFM_RUNS", str(tmp_path))
    r = _fit(panel, telemetry=True)
    recs = obs_store.RunStore(str(tmp_path)).load()
    assert len(recs) == 1
    rec = recs[0]
    assert rec["kind"] == "fit"
    assert rec["config"]["T"] == panel.shape[0]
    assert rec["config"]["N"] == panel.shape[1]
    assert rec["metrics"]["wall_s"] > 0
    assert rec["loglik"] == pytest.approx(float(r.logliks[-1]))
    assert rec["convergence"] == [float(x) for x in r.logliks]
    assert rec["dispatches"] == r.telemetry["dispatches"]


def test_untraced_fit_does_not_append(panel, tmp_path, monkeypatch):
    monkeypatch.setenv("DFM_RUNS", str(tmp_path))
    _fit(panel)                                   # no telemetry: no record
    assert obs_store.RunStore(str(tmp_path)).load() == []


def test_traced_fit_without_dfm_runs_does_not_append(panel, monkeypatch,
                                                     tmp_path):
    monkeypatch.delenv("DFM_RUNS", raising=False)
    monkeypatch.chdir(tmp_path)                   # guard the repo root
    _fit(panel, telemetry=True)
    assert not os.path.exists(tmp_path / obs_store.DEFAULT_DIR)

"""Dispatch-free fused fit (dfm_tpu/estim/fused.py + api wiring).

The operative contracts of ``fit(fused=True)``, verified on the fake
8-device CPU mesh (conftest):

- CONVERGENCE PARITY: the on-device while-loop predicate mirrors the host
  ``em_progress`` rule — with the stop disabled (tol=0) the loglik path
  and params are byte-identical to the chunked driver (x64; f32 to
  tolerance), and with tol>0 the fused fit stops within one chunk-length
  of the chunked driver's stopping iteration.
- ONE-PROGRAM BUDGET: a traced fused fit pays exactly ONE barrier'd
  dispatch; reading factors afterwards consumes the in-program smooth as
  a non-blocking cache hit, so ``blocking_transfers <= 2`` end to end
  (the ISSUE 6 acceptance bound, also asserted by tools/fused_smoke.sh).
- WARM REFIT: ``fit(warm_start=prev)`` on the same backend + panel object
  re-enters the program with zero h2d panel upload (persistent
  ``_fused_panel`` residency) and validates shape/model/fingerprint
  compatibility with clear errors.
- ROBUST FALLBACK: a diverged fused run (injected via the
  ``FusedOptions(fault_chunk=...)`` test seam) falls back to the
  health-monitored chunked driver from the last-good checkpoint and
  reaches the same answer as a clean chunked fit.
- FORECASTS: the in-graph diffusion-index port matches the host oracle
  (``estim.diffusion.diffusion_index_forecast``) per column.
"""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from dfm_tpu.api import DynamicFactorModel, ShardedBackend, TPUBackend, fit
from dfm_tpu.estim.diffusion import diffusion_index_forecast
from dfm_tpu.estim.fused import FusedOptions, resolve_fused
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import summarize, _print_text
from dfm_tpu.obs.trace import Tracer
from dfm_tpu.robust import RobustPolicy
from dfm_tpu.utils import dgp
from dfm_tpu.utils.data import standardize

MODEL = DynamicFactorModel(n_factors=2, standardize=False)


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(7)
    p = dgp.dfm_params(N=16, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=48, rng=rng)
    return Y


def _same_params(a, b, rtol=None):
    for f in ("Lam", "A", "Q", "R", "mu0", "P0"):
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        if rtol is None:
            np.testing.assert_array_equal(x, y, err_msg=f)
        else:
            np.testing.assert_allclose(x, y, rtol=rtol, err_msg=f)


def _fused_dispatches(tr):
    return [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "fused_fit"]


def _blocking_counts(tr):
    barr = sum(1 for e in tr.events if e.get("kind") == "dispatch"
               and e.get("barrier"))
    btr = sum(1 for e in tr.events if e.get("kind") == "transfer"
              and e.get("blocking"))
    return barr, btr


# ---------------------------------------------------------------- units --

def test_resolve_fused():
    assert resolve_fused(False) is None
    assert resolve_fused(None) is None
    assert resolve_fused(True) == FusedOptions()
    assert resolve_fused(3) == FusedOptions(horizon=3)
    opts = FusedOptions(horizon=2, di=False)
    assert resolve_fused(opts) is opts
    with pytest.raises(TypeError, match="fused"):
        resolve_fused("yes")


def test_fused_rejects_debug(panel):
    with pytest.raises(ValueError, match="debug"):
        fit(MODEL, panel, backend=TPUBackend(), max_iters=2, tol=0.0,
            fused=True, debug=True)


def test_fused_ignores_progress_with_warning(panel):
    with pytest.warns(RuntimeWarning, match="progress"):
        r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=3),
                max_iters=3, tol=0.0, fused=True,
                progress=lambda *a, **k: None)
    assert r.n_iters == 3


# ----------------------------------------------- convergence parity -----

def test_fused_matches_chunked_x64(panel):
    b = TPUBackend(fused_chunk=3)                  # 8 iters -> 3,3,2: a tail
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0)
    rf = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, fused=True)
    np.testing.assert_array_equal(rf.logliks, r0.logliks)
    _same_params(rf.params, r0.params)
    assert rf.n_iters == r0.n_iters == 8
    assert rf.converged == r0.converged


def test_fused_matches_chunked_f32(panel):
    b = TPUBackend(dtype=jnp.float32, fused_chunk=3)
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0)
    rf = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, fused=True)
    np.testing.assert_allclose(rf.logliks, r0.logliks, rtol=2e-5)
    _same_params(rf.params, r0.params, rtol=2e-4)


def test_fused_stop_parity(panel):
    """With tol>0 the fused while-loop exits at the end of the chunk whose
    predicate fires — within one chunk-length of the host rule's stop."""
    b = TPUBackend(fused_chunk=4)
    r0 = fit(MODEL, panel, backend=b, max_iters=40, tol=1e-4)
    rf = fit(MODEL, panel, backend=b, max_iters=40, tol=1e-4, fused=True)
    assert r0.converged and rf.converged
    assert abs(rf.n_iters - r0.n_iters) <= 4
    np.testing.assert_allclose(rf.logliks[-1], r0.logliks[-1], rtol=1e-8)


def test_fused_smoothed_factors_match(panel):
    b = TPUBackend(fused_chunk=3)
    r0 = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0)
    rf = fit(MODEL, panel, backend=b, max_iters=8, tol=0.0, fused=True)
    np.testing.assert_allclose(rf.factors, r0.factors, atol=1e-10)


# ------------------------------------------------------------ forecasts --

def test_fused_forecasts_match_host_oracle(panel):
    rf = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=12,
             tol=0.0, fused=True)
    assert rf.forecasts is not None and rf.nowcast is not None
    N = panel.shape[1]
    assert rf.nowcast.shape == (N,)
    assert rf.forecasts["y"].shape == (1, N)
    assert rf.forecasts["f"].shape == (1, 2)
    np.testing.assert_allclose(
        rf.nowcast, np.asarray(rf.params.Lam) @ rf.factors[-1], atol=1e-10)
    # Diffusion-index port vs the host oracle, column by column.
    oracle = np.array([
        diffusion_index_forecast(rf.factors, panel[:, i], horizon=1).forecast
        for i in range(N)])
    np.testing.assert_allclose(rf.forecasts["di"], oracle, atol=1e-8)


def test_fused_horizon_and_di_knobs(panel):
    r3 = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=4,
             tol=0.0, fused=3)
    assert r3.forecasts["y"].shape == (3, panel.shape[1])
    assert r3.forecasts["f"].shape == (3, 2)
    rnd = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=4,
              tol=0.0, fused=FusedOptions(horizon=1, di=False))
    assert rnd.forecasts["di"] is None


def test_fused_destandardizes_outputs(panel):
    """nowcast/forecasts come back in ORIGINAL data units: a fused fit on
    the standardized model must equal the plain-model fit on the
    pre-standardized panel pushed through the inverse transform."""
    Ys, std = standardize(panel)
    r_plain = fit(MODEL, Ys, backend=TPUBackend(fused_chunk=4),
                  max_iters=6, tol=0.0, fused=True)
    r_std = fit(DynamicFactorModel(n_factors=2, standardize=True), panel,
                backend=TPUBackend(fused_chunk=4), max_iters=6, tol=0.0,
                fused=True)
    np.testing.assert_allclose(
        r_std.nowcast, std.inverse(r_plain.nowcast), atol=1e-8)
    np.testing.assert_allclose(
        r_std.forecasts["y"], std.inverse(r_plain.forecasts["y"]),
        atol=1e-8)


def test_nonfused_fit_has_no_forecast_fields(panel):
    r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=3,
            tol=0.0)
    assert r.nowcast is None and r.forecasts is None


# ---------------------------------------------- one-program budget ------

def test_fused_blocking_transfer_budget(panel):
    """Cold fused fit + factor read: one barrier'd dispatch, zero blocking
    transfers — the ISSUE 6 ``blocking_transfers <= 2`` bound with room."""
    tr = Tracer(detector=RecompileDetector())
    r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=3), max_iters=8,
            tol=0.0, fused=True, telemetry=tr)
    assert r.factors is not None               # smooth consumed from cache
    barr, btr = _blocking_counts(tr)
    assert barr == 1 and btr == 0
    s = summarize(tr.events)
    assert s["blocking_transfers"] <= 2
    # The while-loop fit is ONE dispatch span carrying the realized
    # iteration count, not the max_iters budget.
    (d,) = _fused_dispatches(tr)
    assert d["fused"] and d["n_iters"] == 8
    assert s["fused_iterations"] == 8


def test_warm_fused_refit_budget_and_panel_residency(panel):
    b = TPUBackend(fused_chunk=3)
    r1 = fit(MODEL, panel, backend=b, max_iters=6, tol=0.0, fused=True)
    Yj_cold = b._fused_panel[2]
    tr = Tracer(detector=RecompileDetector())
    r2 = fit(MODEL, panel, backend=b, max_iters=6, tol=0.0, fused=True,
             warm_start=r1, telemetry=tr)
    # Same panel object on the same backend: the device buffers are reused
    # (zero h2d upload), and the refit stays within the dispatch budget.
    assert b._fused_panel[2] is Yj_cold
    barr, btr = _blocking_counts(tr)
    assert barr + btr <= 2
    assert len(_fused_dispatches(tr)) == 1
    # The warm seed actually took: refit resumes from the fitted params,
    # so its first loglik is at least the cold fit's last.
    assert r2.logliks[0] >= r1.logliks[-1] - 1e-8


def test_warm_start_equals_init_seed(panel):
    b = TPUBackend(fused_chunk=3)
    r1 = fit(MODEL, panel, backend=b, max_iters=5, tol=0.0, fused=True)
    r2 = fit(MODEL, panel, backend=b, max_iters=5, tol=0.0, fused=True,
             warm_start=r1)
    r2b = fit(MODEL, panel, backend=TPUBackend(fused_chunk=3), max_iters=5,
              tol=0.0, fused=True, init=r1.params)
    np.testing.assert_allclose(r2.logliks, r2b.logliks, rtol=1e-12)
    _same_params(r2.params, r2b.params, rtol=1e-12)


# ------------------------------------------------ warm_start validation --

def test_warm_start_validation_errors(panel):
    r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=3,
            tol=0.0, fused=True)
    assert r.fingerprint is not None
    with pytest.raises(ValueError, match="not both"):
        fit(MODEL, panel, max_iters=2, warm_start=r, init=r.params)
    with pytest.raises(TypeError, match="FitResult"):
        fit(MODEL, panel, max_iters=2, warm_start=r.params)
    with pytest.raises(ValueError, match="Lam shape"):
        fit(MODEL, panel[:, :10], max_iters=2, warm_start=r)
    with pytest.raises(ValueError, match="fitted with"):
        fit(DynamicFactorModel(n_factors=2, standardize=True), panel,
            max_iters=2, warm_start=r)
    # Same shape + model but different missingness structure: the
    # fingerprint catches what the shape check cannot.
    Ymiss = panel.copy()
    Ymiss[3, 2] = np.nan
    with pytest.raises(ValueError, match="fingerprint"):
        fit(MODEL, Ymiss, max_iters=2, warm_start=r)


# ------------------------------------------------------ robust fallback --

def test_fused_divergence_unguarded(panel):
    """No guard: the fused driver mirrors the chunked divergence return —
    last-good params, truncated loglik path, converged=False."""
    rf = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=40,
             tol=0.0, fused=FusedOptions(fault_chunk=2), robust=False)
    assert not rf.converged
    assert rf.n_iters < 40                         # stopped at the fault
    assert rf.nowcast is None                      # no smooth of bad params


def test_fused_divergence_robust_fallback(panel):
    """Guarded: fall back to the chunked driver from the last-good
    checkpoint and land on the same answer as a clean chunked fit."""
    tr = Tracer(detector=RecompileDetector())
    policy = RobustPolicy(backoff_base=1e-4, recover_divergence=True)
    rf = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=40,
             tol=0.0, fused=FusedOptions(fault_chunk=2), robust=policy,
             telemetry=tr)
    r0 = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), max_iters=40,
             tol=0.0)
    assert rf.n_iters == 40
    assert np.isfinite(rf.logliks).all()
    np.testing.assert_allclose(rf.logliks[-1], r0.logliks[-1], rtol=1e-10)
    _same_params(rf.params, r0.params, rtol=1e-8)
    assert rf.health is not None
    assert any(e.get("kind") == "fused_fallback" for e in tr.events)


# ------------------------------------------------- telemetry & report ---

def test_fused_report_text(capsys):
    events = [dict(kind="dispatch", t=0.0, dur=0.5, program="fused_fit",
                   key="x//chunk8max50", barrier=True, first_call=True,
                   recompile=False, fused=True, n_iters=17)]
    s = summarize(events)
    assert s["fused_iterations"] == 17
    assert s["programs"]["fused_fit"]["fused_programs"] == 1
    _print_text(s)
    assert "fused (1 program)" in capsys.readouterr().out


def test_sharded_backend_falls_back_with_warning(panel):
    b = ShardedBackend(n_devices=8, fused_chunk=3)
    r0 = fit(MODEL, panel, backend=b, max_iters=6, tol=0.0)
    with pytest.warns(RuntimeWarning, match="sharded"):
        rf = fit(MODEL, panel, backend=b, max_iters=6, tol=0.0, fused=True)
    np.testing.assert_array_equal(rf.logliks, r0.logliks)
    assert rf.nowcast is None                      # chunked path ran


def test_fused_callback_replay(panel):
    seen = []
    fit(MODEL, panel, backend=TPUBackend(fused_chunk=3), max_iters=6,
        tol=0.0, fused=True, callback=lambda i, ll, p: seen.append((i, ll)))
    assert [i for i, _ in seen] == list(range(6))
    assert all(np.isfinite(ll) for _, ll in seen)

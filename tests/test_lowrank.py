"""Rank-r low-rank filter/smoother engine (ISSUE 15): ``filter="lowrank"``
keeps the state posterior as mean + rank-r downdate (O(k r^2) + O(N k r)
per step instead of the exact path's O(k^3)), so wide factor models
(k >> 10) and the m~25 MF augmented state stay cheap/compilable.

Operative checks: the JAX engine matches the NumPy f64 low-rank oracle
(``backends/cpu_ref``) exactly as an algorithm; at r = k it collapses to
the exact info-form answer (filter, smoother, AND whole fits — chunked
and fused) to x64-exact tolerance; the downdate is conservative
(P_lowrank >= P_exact in the PSD order); the advisor learns exact-vs-
rank-r per shape and ``fit(auto=True)`` applies the winner bit-
identically to the explicit knob; the kscale bench metrics stay
registered.  Runs on the fake 8-device CPU mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.obs import store as obs_store
from dfm_tpu.obs.advise import advise, candidate_plans
from dfm_tpu.obs.profile import profile_record
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.lowrank_filter import (DEFAULT_MAX_RANK, lowrank_filter,
                                        lowrank_filter_smoother,
                                        lowrank_smoother, policy_basis,
                                        resolve_rank, state_coverage)
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp

N, T, K = 21, 48, 5


def _panel(seed=0, N_=N, T_=T, k_=K, mask_frac=0.0):
    rng = np.random.default_rng(seed)
    p = dgp.dfm_params(N_, k_, rng)
    Y, F = dgp.simulate(p, T_, rng)
    mask = dgp.random_mask(T_, N_, rng, mask_frac) if mask_frac else None
    return p, Y, F, mask


@pytest.fixture(scope="module")
def panel():
    return _panel(seed=11)


# -- rank resolution and policy basis --------------------------------------

def test_resolve_rank_matches_oracle():
    for k, r in [(4, 0), (12, 0), (12, 3), (12, 99), (12, -1), (3, 2)]:
        assert resolve_rank(k, r) == cpu_ref.resolve_rank(k, r)
    assert resolve_rank(20, 0) == DEFAULT_MAX_RANK
    assert resolve_rank(4, 0) == 4
    assert resolve_rank(12, 99) == 12       # clamped to k


def test_policy_basis_orthonormal(panel):
    p, _, _, _ = panel
    V = policy_basis(jnp.asarray(p.Lam), jnp.asarray(p.R), 3)
    assert V.shape == (K, 3)
    np.testing.assert_allclose(np.asarray(V.T @ V), np.eye(3), atol=1e-12)


# -- algorithmic parity vs the NumPy f64 oracle ----------------------------

@pytest.mark.parametrize("mask_frac", [0.0, 0.3])
def test_oracle_parity(mask_frac):
    rng = np.random.default_rng(5)
    p = dgp.dfm_params(N, K, rng)
    Y, _ = dgp.simulate(p, T, rng)
    mask = dgp.random_mask(T, N, rng, mask_frac) if mask_frac else None
    pj = JP.from_numpy(p, jnp.float64)
    mj = None if mask is None else jnp.asarray(mask)
    kf = lowrank_filter(jnp.asarray(Y), pj, mask=mj, rank=3)
    sm = lowrank_smoother(kf, pj, rank=3)
    kf_n = cpu_ref.kalman_filter_lowrank(Y, p, mask=mask, rank=3)
    sm_n = cpu_ref.rts_smoother_lowrank(kf_n, p, rank=3)
    assert float(kf.loglik) == pytest.approx(kf_n.loglik, abs=1e-8)
    np.testing.assert_allclose(np.asarray(kf.x_filt), kf_n.x_filt,
                               atol=1e-10)
    np.testing.assert_allclose(np.asarray(kf.P_filt), kf_n.P_filt,
                               atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.x_sm), sm_n.x_sm, atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.P_sm), sm_n.P_sm, atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.P_lag), sm_n.P_lag,
                               atol=1e-10)


# -- r = k exactness --------------------------------------------------------

def test_rank_k_collapses_to_exact(panel):
    p, Y, _, _ = panel
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    kf_e = info_filter(Yj, pj)
    sm_e = rts_smoother(kf_e, pj)
    kf, sm = lowrank_filter_smoother(Yj, pj, rank=K)
    assert float(kf.loglik) == pytest.approx(float(kf_e.loglik),
                                             rel=1e-10)
    np.testing.assert_allclose(np.asarray(kf.x_filt),
                               np.asarray(kf_e.x_filt), atol=1e-9)
    np.testing.assert_allclose(np.asarray(sm.x_sm),
                               np.asarray(sm_e.x_sm), atol=1e-9)
    np.testing.assert_allclose(np.asarray(sm.P_sm),
                               np.asarray(sm_e.P_sm), atol=1e-9)
    np.testing.assert_allclose(np.asarray(sm.P_lag),
                               np.asarray(sm_e.P_lag), atol=1e-9)


def test_downdate_is_conservative(panel):
    # At r < k the update only removes uncertainty along r directions:
    # P_lowrank - P_exact must be PSD at every step (honest, wider bands).
    p, Y, _, _ = panel
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    kf_e = info_filter(Yj, pj)
    kf = lowrank_filter(Yj, pj, rank=2)
    gap = np.asarray(kf.P_filt) - np.asarray(kf_e.P_filt)
    min_eig = np.linalg.eigvalsh(gap).min()
    assert min_eig > -1e-9, min_eig


def test_state_coverage_bounds(panel):
    p, Y, F, _ = panel
    pj = JP.from_numpy(p, jnp.float64)
    _, sm = lowrank_filter_smoother(jnp.asarray(Y), pj, rank=K)
    cov = state_coverage(sm.x_sm, sm.P_sm, F)
    assert 0.75 <= cov <= 1.0           # 90% bands, finite-sample slack
    assert state_coverage(sm.x_sm, sm.P_sm, F, z=50.0) == 1.0


# -- whole fits: chunked AND fused, r = k vs exact --------------------------

@pytest.mark.parametrize("fused", [False, True])
def test_fit_rank_k_matches_info_fit(panel, fused):
    p, Y, _, _ = panel
    Ys = (Y - Y.mean(0)) / Y.std(0)
    model = DynamicFactorModel(n_factors=K)
    kw = dict(max_iters=6, tol=0.0, fused=fused)
    r_e = fit(model, Ys, backend=TPUBackend(dtype=jnp.float64,
                                            filter="info"), **kw)
    r_l = fit(model, Ys, backend=TPUBackend(dtype=jnp.float64,
                                            filter="lowrank", rank=K),
              **kw)
    assert r_e.filter == "info" and r_l.filter == "lowrank"
    np.testing.assert_allclose(np.asarray(r_l.logliks),
                               np.asarray(r_e.logliks), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(r_l.params.Lam),
                               np.asarray(r_e.params.Lam), atol=1e-7)
    np.testing.assert_allclose(np.asarray(r_l.params.A),
                               np.asarray(r_e.params.A), atol=1e-7)


def test_fit_rank_r_converges(panel):
    # The r < k fit targets the rank-r approximating likelihood (a true
    # Gaussian density — ssm.lowrank_filter docstring — so magnitudes are
    # sane at every rank); the approximate E-step voids the exact-EM
    # monotonicity guarantee, so the contract is net improvement +
    # finiteness, not per-step ascent.
    p, Y, _, _ = panel
    Ys = (Y - Y.mean(0)) / Y.std(0)
    r = fit(DynamicFactorModel(n_factors=K), Ys,
            backend=TPUBackend(dtype=jnp.float64, filter="lowrank",
                               rank=2), max_iters=8, tol=0.0)
    ll = np.asarray(r.logliks)
    assert np.all(np.isfinite(ll))
    assert ll[-1] > ll[0], ll


def test_backend_rejects_unknown_rank_filter():
    with pytest.raises(ValueError):
        TPUBackend(filter="lowrnk")


# -- MF m~25 augmented shape ------------------------------------------------

def test_mf_m25_lowrank_fit_completes():
    # k=5 factors x 5 Mariano-Murasawa lags -> the m=25 augmented state
    # whose exact masked program SIGABRTs the axon compiler; the rank-r
    # engine keeps every per-step factorization r x r.
    from dfm_tpu.models.mixed_freq import MixedFreqSpec, mf_fit
    rng = np.random.default_rng(42)
    Y, mask, F, _ = dgp.simulate_mixed_freq(
        n_monthly=18, n_quarterly=6, T=36, k=5, rng=rng)
    spec = MixedFreqSpec(n_monthly=18, n_quarterly=6, n_factors=5,
                         time_scan="lowrank")
    assert spec.state_dim == 25
    res = mf_fit(Y, spec, mask=mask, max_iters=3, tol=0.0)
    ll = np.asarray(res.logliks)
    assert np.all(np.isfinite(ll)) and ll[-1] >= ll[0]


def test_mf_spec_validates_time_scan():
    from dfm_tpu.models.mixed_freq import MixedFreqSpec
    with pytest.raises(ValueError):
        MixedFreqSpec(n_monthly=6, n_quarterly=2, n_factors=2,
                      time_scan="lowrnk")


# -- advisor: exact-vs-rank-r learned per shape -----------------------------

def _seed_widek(d, N_, T_, K_, iters, walls):
    """Registry with per-variant walls (incl. the lowrank profile
    variant: the chunked driver under filter="lowrank")."""
    store = obs_store.RunStore(str(d))
    for variant, warm in walls.items():
        m = {"warm_wall_s": warm, "ms_per_iter_warm": 1e3 * warm / iters}
        if variant == "chunked":
            m["sustained_ms_per_iter"] = 1e3 * warm / iters
            m["dispatch_ms_per_program"] = 1.0
        store.append(profile_record(variant, N_, T_, K_, iters=iters,
                                    chunk=8, metrics=m, device="cpu"))
    return store


def test_candidate_plans_include_lowrank():
    plans = candidate_plans(chunk=8)
    filters = {(p["engine"], p.get("filter", "seq")) for p in plans}
    assert ("chunked", "lowrank") in filters
    assert ("fused", "lowrank") in filters


def test_advise_picks_lowrank_at_profiled_wide_k(tmp_path):
    _seed_widek(tmp_path, 64, 200, 50, 12,
                {"chunked": 4.0, "fused": 3.5, "lowrank": 0.9})
    res = advise(64, 200, 50, max_iters=12, runs=str(tmp_path))
    top = res["plans"][0]
    assert top["filter"] == "lowrank" and top["engine"] == "chunked"
    assert top["anchored"]
    assert res == advise(64, 200, 50, max_iters=12, runs=str(tmp_path))


def test_advise_keeps_seq_at_narrow_k(tmp_path):
    # seq profiles only: the lowrank residual scale stays 1.0 and the
    # sequential plans keep winning; lowrank plans still ranked.
    _seed_widek(tmp_path, 16, 40, 2, 12, {"chunked": 1.0, "fused": 0.1})
    res = advise(16, 40, 2, max_iters=12, runs=str(tmp_path))
    assert res["plans"][0]["filter"] == "seq"
    assert any(p["filter"] == "lowrank" for p in res["plans"])


def test_advise_unprofiled_lowrank_never_undercuts_measured_plans(tmp_path):
    # Wide k with SEQ profiles only: LOWRANK_FLOP_MULT halves the flop
    # term on paper, so the raw-prior lowrank plans would undercut every
    # anchored plan — but nobody timed that engine, and acting on the
    # prior forces a fresh compile the model can't see.  The evidence
    # gate clamps the unprofiled plans to the best measured wall; a
    # measured lowrank profile lifts the gate (the profiled-wide-k
    # selection test above).
    _seed_widek(tmp_path, 64, 200, 50, 12, {"chunked": 4.0, "fused": 3.5})
    res = advise(64, 200, 50, max_iters=12, runs=str(tmp_path))
    top = res["plans"][0]
    assert top["filter"] == "seq" and top["anchored"]
    assert not res["model"]["lowrank_calibrated"]
    clamped = [p for p in res["plans"] if p.get("evidence_clamped")]
    assert any(p["filter"] == "lowrank" for p in clamped)
    floor = min(p["predicted_wall_s"] for p in res["plans"]
                if p.get("anchored"))
    assert all(p["predicted_wall_s"] >= floor for p in clamped)


def test_fit_auto_applies_lowrank_plan_bit_identical(tmp_path,
                                                     monkeypatch):
    p, Y, _, _ = _panel(seed=23, N_=16, T_=40, k_=2)
    Ys = (Y - Y.mean(0)) / Y.std(0)
    _seed_widek(tmp_path / "r", 16, 40, 2, 12,
                {"chunked": 1.5, "fused": 2.0, "lowrank": 0.4})
    monkeypatch.setenv("DFM_RUNS", str(tmp_path / "r"))
    b_auto = TPUBackend(dtype=jnp.float64)       # filter="auto"
    r_auto = fit(DynamicFactorModel(n_factors=2), Ys, backend=b_auto,
                 max_iters=12, tol=1e-8, auto=True)
    assert r_auto.advice["filter"] == "lowrank"
    assert r_auto.filter == "lowrank"
    assert b_auto.filter == "auto"               # override was transient
    monkeypatch.delenv("DFM_RUNS")
    # Plans carry no rank key: the explicit twin uses the same backend
    # default (rank=0 -> auto), so the answers must be bit-equal.
    r_exp = fit(DynamicFactorModel(n_factors=2), Ys,
                backend=TPUBackend(dtype=jnp.float64, filter="lowrank"),
                max_iters=12, tol=1e-8)
    np.testing.assert_array_equal(np.asarray(r_auto.logliks),
                                  np.asarray(r_exp.logliks))
    np.testing.assert_array_equal(np.asarray(r_auto.params.Lam),
                                  np.asarray(r_exp.params.Lam))


def test_fit_auto_explicit_filter_wins_over_lowrank_plan(tmp_path,
                                                         monkeypatch):
    p, Y, _, _ = _panel(seed=23, N_=16, T_=40, k_=2)
    Ys = (Y - Y.mean(0)) / Y.std(0)
    _seed_widek(tmp_path / "r", 16, 40, 2, 12,
                {"chunked": 1.5, "fused": 2.0, "lowrank": 0.4})
    monkeypatch.setenv("DFM_RUNS", str(tmp_path / "r"))
    r = fit(DynamicFactorModel(n_factors=2), Ys,
            backend=TPUBackend(dtype=jnp.float64, filter="info"),
            max_iters=12, tol=1e-8, auto=True)
    assert r.filter == "info"       # explicit knob beats the plan


# -- registry wiring --------------------------------------------------------

def test_kscale_metrics_registered_with_directions_and_floors():
    for k in ("kscale_speedup_k10", "kscale_speedup_k25",
              "kscale_speedup_k50", "kscale_speedup_k100"):
        assert k in obs_store._BENCH_NUMERIC_KEYS
        assert not obs_store.lower_is_better(k)
    assert obs_store.lower_is_better("kscale_calib_err")
    assert obs_store.noise_floor("kscale_calib_err") == pytest.approx(0.02)
    assert obs_store.lower_is_better("kscale_mf_m25_wall_s")
    assert obs_store.noise_floor("kscale_mf_m25_wall_s") > 0
    rec = obs_store.record_from_bench_json(
        {"metric": "kscale_speedup_k50", "value": 2.5,
         "kscale_calib_err": 0.01, "kscale_mf_m25_wall_s": 0.3})
    assert rec["metrics"]["kscale_speedup_k50"] == 2.5
    assert rec["metrics"]["kscale_calib_err"] == 0.01

"""Request-scoped tracing (obs/trace request spans — ISSUE 19).

The operative contracts of the end-to-end latency waterfall:

- TELESCOPING: ``finish_request`` names adjacent deltas of ONE monotonic
  clock — the stages sum to the measured e2e at float fuzz, by
  construction, for every subset of stamps (daemon path, lone-session
  path, dedup short-circuit).
- ZERO-OVERHEAD OFF: with no tracer and no explicit span, serving answers
  are bit-identical to a traced twin — the span plumbing adds clock reads
  only when someone is watching.
- PROPAGATION: a trace born at submit reaches the request event with the
  same trace_id at every seam — lone session, fleet bucket, daemon
  handle(); the query event carries the same id so waterfall and device
  telemetry join.
- CROSS-PROCESS CONTINUITY: "trace" rides the daemon journal, so kill-9
  replay and --takeover delta replay re-emit request events with the
  ORIGINAL trace_ids stamped ``replay=true``; a duplicate request id is
  answered with its own two-stage waterfall flagged ``dedup=true`` and
  counted in ``status()["dedup_hits"]``.
- TAIL EXEMPLARS: the e2e histogram keeps the worst exemplar-carrying
  trace_id and ``render_prom`` attaches it to the 0.99 quantile in
  OpenMetrics exemplar syntax — a p99 alert resolves to a request trace.
"""

import json
import math
import threading

import numpy as np
import pytest

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.api import TPUBackend
from dfm_tpu.daemon import DaemonClient, DFMDaemon, make_listener
from dfm_tpu.obs.metrics import Ledger, MetricsRegistry, record_event
from dfm_tpu.obs.report import summarize, to_chrome
from dfm_tpu.obs.trace import (Tracer, activate, current_request,
                               finish_request, new_trace_id, request_clock,
                               request_span, set_ambient)
from dfm_tpu.utils import dgp

BE = TPUBackend(filter="info")
R = 2                                    # rows per query


# ---------------------------------------------------------------------------
# the waterfall itself (no jax)
# ---------------------------------------------------------------------------

def test_finish_request_full_waterfall_telescopes():
    t0 = request_clock()
    trace = {"id": "abc123", "t_send": t0, "t_admit": t0 + 0.001,
             "t_batch": t0 + 0.003, "t_tick0": t0 + 0.004,
             "t_launch": t0 + 0.010, "t_read": t0 + 0.050,
             "t_ack": t0 + 0.051}
    ev = finish_request(trace, tenant="t7", session="f1")
    assert ev["trace_id"] == "abc123"
    assert ev["tenant"] == "t7" and ev["session"] == "f1"
    assert set(ev["stages"]) == {"client_send", "queue_wait", "batch_form",
                                 "dispatch", "d2h", "ack"}
    # Adjacent deltas of one clock telescope: residual is float fuzz,
    # nowhere near the 1 ms acceptance budget.
    residual = abs(sum(ev["stages"].values()) - ev["e2e"])
    assert residual < 1e-9
    assert ev["e2e"] == pytest.approx(0.051)
    assert ev["stages"]["d2h"] == pytest.approx(0.040)
    assert "replay" not in ev and "dedup" not in ev


def test_finish_request_partial_stamps_and_flags():
    # Lone-session path: no daemon, no batch former — queue_wait ends at
    # t_tick0 and there is no batch_form stage.
    t0 = 100.0
    sess = {"id": "x", "t_send": t0, "t_admit": t0 + 1, "t_tick0": t0 + 2,
            "t_launch": t0 + 3, "t_read": t0 + 4, "t_ack": t0 + 5}
    ev = finish_request(sess)
    assert set(ev["stages"]) == {"client_send", "queue_wait", "dispatch",
                                 "d2h", "ack"}
    assert sum(ev["stages"].values()) == pytest.approx(ev["e2e"])
    # Dedup short-circuit: two stamps, one stage, flags carried.
    dup = {"id": "y", "t_send": t0, "t_admit": t0 + 0.5,
           "t_ack": t0 + 0.6, "replay": True}
    ev2 = finish_request(dup, dedup=True)
    assert ev2["dedup"] is True and ev2["replay"] is True
    assert ev2["e2e"] == pytest.approx(0.6)
    assert sum(ev2["stages"].values()) == pytest.approx(ev2["e2e"])


def test_trace_ids_and_request_span_context():
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64 and all(len(i) == 16 for i in ids)
    assert current_request() is None
    with request_span() as tr:
        assert current_request() is tr
        assert tr["id"] and "t_send" in tr
        with request_span({"id": "outer9", "t_send": 1.0}) as tr2:
            assert current_request() is tr2 and tr2["id"] == "outer9"
        assert current_request() is tr
    assert current_request() is None


def test_request_metrics_counters_and_prom_exemplar():
    reg, led = MetricsRegistry(), Ledger()
    for i, (e2e, tid) in enumerate([(0.010, "fast01"), (0.500, "slow99")]):
        record_event(reg, led, {
            "kind": "request", "t": float(i), "trace_id": tid,
            "tenant": "t0", "e2e": e2e,
            "stages": {"queue_wait": e2e / 2, "dispatch": e2e / 2},
            **({"replay": True} if i == 0 else {})})
    record_event(reg, led, {"kind": "request", "t": 2.0, "trace_id": "d",
                            "tenant": "t0", "e2e": 0.001,
                            "stages": {"ack": 0.001}, "dedup": True})
    assert reg.counter("requests_total", tenant="t0").value == 3
    assert reg.counter("replayed_requests_total", tenant="t0").value == 1
    assert reg.counter("dedup_hits_total", tenant="t0").value == 1
    # The worst exemplar-carrying observation wins the exemplar slot and
    # rides the 0.99 quantile line in OpenMetrics syntax.
    h = reg.histogram("request_e2e_ms", tenant="t0")
    assert h.exemplar is not None and h.exemplar[1] == "slow99"
    prom = reg.render_prom()
    line = [ln for ln in prom.splitlines()
            if ln.startswith("dfm_request_e2e_ms{")
            and 'quantile="0.99"' in ln]
    assert len(line) == 1 and '# {trace_id="slow99"} 500' in line[0]
    assert reg.histogram("request_stage_ms", stage="dispatch").count == 2


# ---------------------------------------------------------------------------
# report: the requests section + chrome flow events (no jax)
# ---------------------------------------------------------------------------

def _req_event(t, tid, tenant, stages, **extra):
    return {"t": t, "kind": "request", "trace_id": tid, "tenant": tenant,
            "stages": stages, "e2e": sum(stages.values()), **extra}


def test_report_requests_section(tmp_path):
    tr = str(tmp_path / "trace.jsonl")
    evs = [
        _req_event(1.0, "aa", "t0", {"queue_wait": 0.002, "dispatch": 0.08,
                                     "d2h": 0.01, "ack": 0.001}),
        _req_event(2.0, "bb", "t0", {"queue_wait": 0.5, "dispatch": 0.09,
                                     "d2h": 0.01, "ack": 0.001}),
        _req_event(3.0, "cc", "t1", {"queue_wait": 0.001, "dispatch": 0.07,
                                     "d2h": 0.01, "ack": 0.001},
                   replay=True),
        _req_event(4.0, "dd", "t1", {"ack": 0.001}, dedup=True),
    ]
    with open(tr, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    rq = summarize(tr)["requests"]
    assert rq["n_requests"] == 4
    assert rq["replayed"] == 1 and rq["dedup"] == 1
    assert rq["waterfall_residual_max_s"] < 1e-9
    # Attribution: queue_wait dominates total stage time (the 0.5 s
    # outlier), and the tail exemplar names that request.
    shares = {s: d["share"] for s, d in rq["per_stage"].items()}
    assert max(shares, key=shares.get) == "queue_wait"
    assert abs(sum(shares.values()) - 1.0) < 1e-9
    assert rq["tail_exemplars"][0]["trace_id"] == "bb"
    assert set(rq["per_tenant"]) == {"t0", "t1"}
    assert rq["per_tenant"]["t0"]["n"] == 2
    # Empty traces keep the section with stable keys (dashboards).
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    rq0 = summarize(empty)["requests"]
    assert rq0["n_requests"] == 0 and rq0["tail_exemplars"] == []


def test_chrome_export_request_flows(tmp_path):
    evs = [
        {"t": 1.0, "kind": "query", "session": "s1", "tenant": "t0",
         "wall": 0.05, "trace_id": "aa"},
        _req_event(1.06, "aa", "t0", {"queue_wait": 0.01, "dispatch": 0.04,
                                      "d2h": 0.005, "ack": 0.005}),
    ]
    trace = to_chrome(evs)
    tevs = trace["traceEvents"]
    flows = [e for e in tevs if e.get("ph") in ("s", "t", "f")]
    # One flow per trace_id: start at the request span, a step at the
    # query instant, a finish at the ack — all sharing one flow id.
    assert {e["ph"] for e in flows} == {"s", "t", "f"}
    assert len({e["id"] for e in flows}) == 1
    spans = [e for e in tevs if e.get("ph") == "X"
             and "request" in str(e.get("name", ""))]
    names = {e["name"] for e in tevs if e.get("ph") == "X"}
    assert any("aa" in str(e.get("name")) for e in spans)
    assert {"queue_wait", "dispatch", "d2h", "ack"} <= names


# ---------------------------------------------------------------------------
# serving seams: session / fleet (tiny panels, fake mesh CPU)
# ---------------------------------------------------------------------------

def _tenant(N, T, k, seed, extra=40 * R):
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T + extra, rng)
    res = fit(DynamicFactorModel(n_factors=k), Y[:T], max_iters=6,
              backend=BE, telemetry=False)
    return res, Y[:T], Y[T:]


@pytest.fixture(scope="module")
def tiny():
    return _tenant(6, 26, 2, 411)


def test_session_waterfall_and_untraced_bit_identity(tiny):
    res, Y0, stream = tiny
    # Traced twin: every update answers with a request event whose
    # stages telescope to the measured e2e.
    tr = Tracer()
    with activate(tr):
        s1 = open_session(res, Y0, max_update_rows=R, max_iters=3, tol=0.0,
                          capacity=Y0.shape[0] + 6 * R)
        u1 = [s1.update(stream[i * R:(i + 1) * R]) for i in range(3)]
        s1.close()
    reqs = [e for e in tr.events if e["kind"] == "request"]
    quer = [e for e in tr.events if e["kind"] == "query"]
    assert len(reqs) == 3 and len(quer) == 3
    for rev, qev in zip(reqs, quer):
        assert abs(sum(rev["stages"].values()) - rev["e2e"]) < 1e-3
        assert rev["trace_id"] == qev["trace_id"] != ""
        assert {"dispatch", "d2h", "ack"} <= set(rev["stages"])
    # Untraced twin: no tracer, no explicit span -> zero request events
    # and bit-identical answers (the off path takes no clock reads that
    # could perturb anything numeric).
    with activate(None):
        s2 = open_session(res, Y0, max_update_rows=R, max_iters=3, tol=0.0,
                          capacity=Y0.shape[0] + 6 * R)
        u2 = [s2.update(stream[i * R:(i + 1) * R]) for i in range(3)]
        s2.close()
    for a, b in zip(u1, u2):
        np.testing.assert_array_equal(a.nowcast, b.nowcast)
        np.testing.assert_array_equal(a.forecasts["y"], b.forecasts["y"])


def test_session_explicit_span_without_tracer(tiny):
    # An explicit request_span makes an untraced session still finish the
    # span (to the live plane), and the caller sees the stamps.
    res, Y0, stream = tiny
    with activate(None):
        s = open_session(res, Y0, max_update_rows=R, max_iters=2, tol=0.0,
                         capacity=Y0.shape[0] + 2 * R)
        with request_span() as span:
            s.update(stream[:R])
        s.close()
    assert "t_ack" in span and span["t_ack"] >= span["t_send"]


def test_fleet_request_propagation_and_replay_flag(tiny):
    res, Y0, stream = tiny
    fl = open_fleet([res], [Y0], tenants=["t0"], max_update_rows=R,
                    max_iters=3, tol=0.0,
                    capacity=[Y0.shape[0] + 8 * R], backend=BE)
    tr = Tracer()
    with activate(tr):
        # Explicit span (the daemon replay path): original id + replay
        # flag must survive into the request event.
        fl.submit("t0", stream[:R],
                  trace={"id": "replayed01", "t_send": request_clock(),
                         "replay": True})
        fl.drain()
        # Ambient-tracer birth: no explicit span, id minted at submit.
        fl.submit("t0", stream[R:2 * R])
        fl.drain()
    fl.close()
    reqs = [e for e in tr.events if e["kind"] == "request"]
    assert len(reqs) == 2
    assert reqs[0]["trace_id"] == "replayed01"
    assert reqs[0].get("replay") is True
    assert reqs[1]["trace_id"] and "replay" not in reqs[1]
    for rev in reqs:
        assert abs(sum(rev["stages"].values()) - rev["e2e"]) < 1e-3


# ---------------------------------------------------------------------------
# daemon: continuity across dedup, kill-9 replay, and takeover
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dwork(tmp_path_factory, tiny):
    work = tmp_path_factory.mktemp("reqtrace")
    res, Y0, _ = tiny
    boot = open_fleet([res], [Y0], tenants=["t0"], max_update_rows=R,
                      max_iters=3, tol=0.0,
                      capacity=[Y0.shape[0] + 30 * R], backend=BE)
    snap = str(work / "snap")
    boot.snapshot_all(snap)
    boot.close()
    return work, snap


def _submit(daemon, rows, rid, tid):
    return daemon.handle({"op": "submit", "tenant": "t0",
                          "rows": None if rows is None else rows.tolist(),
                          "id": rid,
                          "trace": {"id": tid, "t_send": request_clock()}})


def test_daemon_dedup_waterfall_and_kill9_replay_continuity(dwork, tiny):
    work, snap = dwork
    _, _, stream = tiny
    journal = str(work / "j1.jsonl")
    tr = Tracer()
    d1 = DFMDaemon.recover(snap, journal, backend=BE)
    with activate(tr):
        try:
            sent = []
            for q in range(2):
                tid = new_trace_id()
                r = _submit(d1, stream[q * R:(q + 1) * R], f"rq{q}", tid)
                assert r.get("ok"), r
                # The ack carries the span id end-to-end.
                assert r["trace_id"] == tid
                sent.append(tid)
            # Duplicate id: answered from cache with a two-stage dedup
            # waterfall under a FRESH span, counted in status().
            dup_tid = new_trace_id()
            dup = _submit(d1, stream[:R], "rq0", dup_tid)
            assert dup.get("duplicate") is True
            assert dup["trace_id"] == dup_tid
            assert d1.status()["dedup_hits"] == 1
        finally:
            d1._journal.close()      # crash-sim: abandon, no fleet close
    reqs = {e["trace_id"]: e for e in tr.events if e["kind"] == "request"}
    assert set(reqs) == set(sent) | {dup_tid}
    assert reqs[dup_tid].get("dedup") is True
    assert not any(e.get("replay") for e in reqs.values())
    for rev in reqs.values():
        assert abs(sum(rev["stages"].values()) - rev["e2e"]) < 1e-3
    # Kill-9 recovery: journal replay re-serves both submits under their
    # ORIGINAL trace_ids, stamped replay=true — the waterfall stream is
    # continuous across the process boundary.
    tr2 = Tracer()
    with activate(tr2):
        d2 = DFMDaemon.recover(snap, journal, backend=BE)
        d2.close()
    replayed = [e for e in tr2.events if e["kind"] == "request"]
    assert [e["trace_id"] for e in replayed] == sent
    assert all(e.get("replay") is True for e in replayed)
    st2 = summarize(list(tr2.events))
    assert st2["requests"]["replayed"] == 2


def test_takeover_trace_continuity(dwork, tiny):
    work, snap = dwork
    _, _, stream = tiny
    journal = str(work / "j2.jsonl")
    addr = str(work / "d.sock")
    pred = DFMDaemon.recover(snap, journal, backend=BE)
    listener = make_listener(addr)
    th = threading.Thread(target=pred.serve_forever, args=(listener,),
                          daemon=True)
    th.start()
    cli = DaemonClient(addr, timeout=120.0)
    # Client-side birth: DaemonClient.submit mints the span; the id comes
    # back on the ack after crossing the socket + queue + fleet tick.
    r1 = cli.submit("t0", stream[:R], req_id="to-0", wait=True)
    assert r1.get("ok") and len(r1.get("trace_id", "")) == 16
    # Blue/green: the successor's journal delta replay re-emits the
    # served request under its original trace_id, replay-stamped.
    tr = Tracer()
    prev = set_ambient(tr)       # takeover warms on this thread; the
    try:                         # successor pump inherits the ambient
        succ, lst2, _gap = DFMDaemon.takeover(addr, snap, journal,
                                              backend=BE)
        th.join(timeout=60)
        th2 = threading.Thread(target=succ.serve_forever, args=(lst2,),
                               daemon=True)
        th2.start()
        r2 = cli.submit("t0", stream[R:2 * R], req_id="to-1", wait=True)
        assert r2.get("ok") and len(r2.get("trace_id", "")) == 16
        cli.shutdown()
        th2.join(timeout=60)
        succ.close()
        pred._journal.close()
    finally:
        set_ambient(prev)
    reqs = [e for e in tr.events if e["kind"] == "request"]
    tids = [e["trace_id"] for e in reqs]
    assert r1["trace_id"] in tids       # replayed under the original id
    assert r2["trace_id"] in tids       # served live by the successor
    rep = next(e for e in reqs if e["trace_id"] == r1["trace_id"])
    assert rep.get("replay") is True
    live = next(e for e in reqs if e["trace_id"] == r2["trace_id"])
    assert "replay" not in live
    assert abs(sum(live["stages"].values()) - live["e2e"]) < 1e-3

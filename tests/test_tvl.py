"""Time-varying-loadings DFM tests (config S4; SURVEY.md section 7.1 M4).

Pins: (1) the batched loading filter/smoother against a hand-written scalar
Kalman oracle; (2) monotone conditional loglik across alternation rounds;
(3) the TVL fit beating a static-loadings fit on a high-drift DGP; (4) masked
operation stays finite.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.models.tv_loadings import (TVLParams, TVLSpec, loading_pass,
                                        tvl_fit)
from dfm_tpu.utils import dgp


def _scalar_loading_oracle(y, f, tau2, r, lam0, p0):
    """k=1, N=1 random-walk loading KF + RTS, plain NumPy."""
    T = len(y)
    lam_f = np.zeros(T)
    P_f = np.zeros(T)
    lam_p = np.zeros(T)
    P_p = np.zeros(T)
    lam, P = lam0, p0
    for t in range(T):
        P_pred = P + tau2
        lam_p[t], P_p[t] = lam, P_pred
        S = f[t] * P_pred * f[t] + r
        K = P_pred * f[t] / S
        lam = lam + K * (y[t] - f[t] * lam)
        P = (1.0 - K * f[t]) * P_pred
        lam_f[t], P_f[t] = lam, P
    lam_s = np.zeros(T)
    P_s = np.zeros(T)
    lam_s[-1], P_s[-1] = lam_f[-1], P_f[-1]
    for t in range(T - 2, -1, -1):
        J = P_f[t] / P_p[t + 1]
        lam_s[t] = lam_f[t] + J * (lam_s[t + 1] - lam_p[t + 1])
        P_s[t] = P_f[t] + J * (P_s[t + 1] - P_p[t + 1]) * J
    return lam_s, P_s


def test_loading_pass_matches_scalar_oracle():
    rng = np.random.default_rng(31)
    T = 40
    f = rng.standard_normal(T)
    lam_true = np.cumsum(0.1 * rng.standard_normal(T)) + 1.0
    y = lam_true * f + 0.3 * rng.standard_normal(T)
    tau2, r = 0.01, 0.09
    lam0 = 1.0
    p = TVLParams(Lam0=jnp.asarray([[lam0]]), tau2=jnp.asarray([tau2]),
                  A=jnp.eye(1), Q=jnp.eye(1), R=jnp.asarray([r]),
                  mu0=jnp.zeros(1), P0=jnp.eye(1))
    lam_sm, P_sm, incr = loading_pass(jnp.asarray(y[:, None]),
                                      jnp.asarray(f[:, None]), p)
    p0_prior = 1e-2 + tau2   # loading_pass's prior variance convention
    lam_ref, P_ref = _scalar_loading_oracle(y, f, tau2, r, lam0, p0_prior)
    np.testing.assert_allclose(np.asarray(lam_sm)[:, 0, 0], lam_ref,
                               atol=1e-8)
    np.testing.assert_allclose(np.asarray(P_sm)[:, 0, 0, 0], P_ref,
                               atol=1e-8)


@pytest.fixture(scope="module")
def tvl_panel():
    rng = np.random.default_rng(32)
    Y, F, Lams, A, R = dgp.simulate_tv_loadings(50, 200, 2, rng,
                                                walk_scale=0.08)
    return Y, F, Lams


def test_tvl_conditional_loglik_monotone(tvl_panel):
    Y, _, _ = tvl_panel
    res = tvl_fit(Y, TVLSpec(n_factors=2, n_rounds=6))
    dll = np.diff(res.logliks)
    assert np.all(dll >= -1e-6 * np.abs(res.logliks[:-1]).max()), res.logliks


def test_tvl_beats_static_on_drifting_loadings(tvl_panel):
    Y, F, Lams = tvl_panel
    true_common = np.einsum("tnk,tk->tn", Lams, F)
    res = tvl_fit(Y, TVLSpec(n_factors=2, n_rounds=10))
    err_tvl = np.mean((res.common - true_common) ** 2)
    r_st = fit(DynamicFactorModel(n_factors=2, standardize=False), Y,
               backend="cpu", max_iters=25)
    static_common = r_st.factors @ r_st.params.Lam.T
    err_st = np.mean((static_common - true_common) ** 2)
    assert err_tvl < 0.9 * err_st, (err_tvl, err_st)
    corr = np.corrcoef(res.common.ravel(), true_common.ravel())[0, 1]
    assert corr > 0.9, corr


def test_tvl_masked_finite():
    rng = np.random.default_rng(33)
    Y, F, Lams, _, _ = dgp.simulate_tv_loadings(25, 80, 2, rng,
                                                walk_scale=0.05)
    W = dgp.random_mask(80, 25, rng, 0.25)
    Ynan = np.where(W > 0, Y, np.nan)
    res = tvl_fit(Ynan, TVLSpec(n_factors=2, n_rounds=4), mask=W)
    assert np.all(np.isfinite(res.logliks))
    assert np.all(np.isfinite(res.common))


def test_tvl_fused_chunk_matches_per_round(tvl_panel):
    """fused_chunk>1 == fused_chunk=1 exactly (x64): the chunked driver's
    stop/replay plumbing must not change the trajectory (CLAUDE.md fused-path
    equivalence rule; the chunk boundary at round 4 of 6 is exercised)."""
    Y, _, _ = tvl_panel
    spec = TVLSpec(n_factors=2, n_rounds=6, tol=0.0)
    r1 = tvl_fit(Y, spec, fused_chunk=1)
    r4 = tvl_fit(Y, spec, fused_chunk=4)
    np.testing.assert_allclose(r4.logliks, r1.logliks, rtol=1e-12)
    np.testing.assert_allclose(r4.loadings, r1.loadings, atol=1e-12)
    np.testing.assert_allclose(r4.factors, r1.factors, atol=1e-12)

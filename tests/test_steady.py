"""Steady-state accelerated filter/smoother == exact (ssm/steady.py).

The acceleration freezes the covariance path after tau steps; on a
well-mixing DGP (spectral radius 0.7) the result is exact to machine
precision, which these tests pin.  Also covers the masked/short-T fallback
and EM-through-ss equivalence.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig, em_fit
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.steady import ss_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(61)
    p = dgp.dfm_params(35, 3, rng)
    Y, _ = dgp.simulate(p, 400, rng)
    return p, Y


def test_ss_matches_exact_filter_smoother(setup):
    p, Y = setup
    pj = JP.from_numpy(p, jnp.float64)
    kf_s = info_filter(jnp.asarray(Y), pj)
    sm_s = rts_smoother(kf_s, pj)
    kf, sm, delta = ss_filter_smoother(jnp.asarray(Y), pj, tau=96)
    assert float(delta) < 1e-12          # covariance path fully converged
    assert abs(float(kf.loglik) - float(kf_s.loglik)) < 1e-9 * abs(
        float(kf_s.loglik))
    np.testing.assert_allclose(np.asarray(kf.x_filt),
                               np.asarray(kf_s.x_filt), atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.x_sm),
                               np.asarray(sm_s.x_sm), atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.P_sm),
                               np.asarray(sm_s.P_sm), atol=1e-10)
    np.testing.assert_allclose(np.asarray(sm.P_lag),
                               np.asarray(sm_s.P_lag), atol=1e-10)


@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 64, 100])
def test_affine_const_prefix_matches_sequential(n):
    """The doubling prefix reproduces x_t = M x_{t-1} + d_t exactly for
    every length class (powers of two, odd, 1)."""
    from dfm_tpu.ops.scan import affine_const_prefix
    rng = np.random.default_rng(n)
    k = 4
    M = rng.normal(size=(k, k)) * 0.3          # rho < 1, like the engines
    d = rng.normal(size=(n, k))
    x0 = rng.normal(size=k)
    got = np.asarray(affine_const_prefix(jnp.asarray(M), jnp.asarray(d),
                                         jnp.asarray(x0)))
    x, want = x0, []
    for t in range(n):
        x = M @ x + d[t]
        want.append(x)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-12)


def test_ss_fallback_short_T(setup):
    p, _ = setup
    rng = np.random.default_rng(62)
    Y, _ = dgp.simulate(p, 50, rng)
    pj = JP.from_numpy(p, jnp.float64)
    kf, sm, delta = ss_filter_smoother(jnp.asarray(Y), pj, tau=96)
    kf_s = info_filter(jnp.asarray(Y), pj)
    assert float(kf.loglik) == float(kf_s.loglik)   # exact fallback


def test_ss_fallback_masked(setup):
    p, Y = setup
    rng = np.random.default_rng(63)
    W = dgp.random_mask(*Y.shape, rng, 0.2)
    pj = JP.from_numpy(p, jnp.float64)
    kf, sm, _ = ss_filter_smoother(jnp.asarray(Y), pj, tau=96,
                                   mask=jnp.asarray(W))
    kf_s = info_filter(jnp.asarray(Y), pj, mask=jnp.asarray(W))
    assert float(kf.loglik) == float(kf_s.loglik)


def test_em_through_ss_matches_info(setup):
    p, Y = setup
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 3)
    pj = JP.from_numpy(p0, jnp.float64)
    _, lls_i, _, _ = em_fit(jnp.asarray(Yz), pj, max_iters=5,
                         cfg=EMConfig(filter="info"))
    _, lls_s, _, _ = em_fit(jnp.asarray(Yz), pj, max_iters=5,
                         cfg=EMConfig(filter="ss"))
    np.testing.assert_allclose(np.asarray(lls_s), np.asarray(lls_i),
                               rtol=1e-10)


def test_ss_diagnostic_flags_slow_mixing():
    """With near-unit-root dynamics and WEAK data (the closed-loop mixing is
    what matters — many informative series converge the covariance fast
    regardless of A), a small tau must be reported as unconverged rather
    than silently returning garbage."""
    rng = np.random.default_rng(64)
    k = 1
    A = 0.9995 * np.eye(k)
    p = cpu_ref.SSMParams(0.05 * np.ones((1, k)), A, 1e-3 * np.eye(k),
                          np.array([100.0]), np.zeros(k),
                          5.0 * np.eye(k))
    Y, _ = dgp.simulate(p, 300, rng)
    pj = JP.from_numpy(p, jnp.float64)
    _, _, delta = ss_filter_smoother(jnp.asarray(Y), pj, tau=8)
    assert float(delta) > 1e-6, float(delta)


def test_ss_delta_surfaced_and_warning(recwarn):
    """ADVICE r1 item 1: the freeze diagnostic is threaded out of e_step
    and warn_ss_delta fires above threshold, stays silent below."""
    import warnings
    import pytest
    from dfm_tpu.estim.em import EMConfig, em_step, em_fit_scan, warn_ss_delta
    rng = np.random.default_rng(81)
    p = dgp.dfm_params(20, 2, rng, spectral_radius=0.95)
    Y, _ = dgp.simulate(p, 200, rng)
    Yz = (Y - Y.mean(0)) / Y.std(0)
    p0 = cpu_ref.pca_init(Yz, 2)
    _, _, delta = em_step(jnp.asarray(Yz), JP.from_numpy(p0),
                          cfg=EMConfig(filter="ss", tau=8))
    assert float(delta) >= 0.0
    _, lls, deltas = em_fit_scan(jnp.asarray(Yz), JP.from_numpy(p0),
                                 n_iters=3, cfg=EMConfig(filter="ss", tau=8))
    assert deltas.shape == (3,)
    with pytest.warns(RuntimeWarning, match="steady-state"):
        warn_ss_delta(1e-2, tau=8)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        warn_ss_delta(1e-6, tau=8)   # below threshold: must not warn


def test_auto_tau_buckets_and_floor(setup):
    """auto_tau: margin x measured mixing, bucketed; lo/hi clamps hold."""
    from dfm_tpu.ssm.steady import auto_tau, riccati_mixing_steps
    p, _ = setup
    mix = riccati_mixing_steps(p)
    assert 1 <= mix < 512
    tau = auto_tau(p)
    assert tau >= 2 * mix and tau in (8, 12, 16, 24, 32, 48, 64, 96, 128,
                                      192)
    assert auto_tau(p, lo=16) >= 16
    assert auto_tau(p, margin=1e6) == 192          # hi clamp
    # ss at the auto tau matches the exact filter (the whole point).
    Y = dgp.simulate(p, 400, np.random.default_rng(3))[0]
    Yj = jnp.asarray(Y)
    pj = JP.from_numpy(p, dtype=Yj.dtype)
    kf_ss, _, _ = ss_filter_smoother(Yj, pj, tau=tau)
    kf_ex = info_filter(Yj, pj)
    np.testing.assert_allclose(float(kf_ss.loglik), float(kf_ex.loglik),
                               rtol=1e-8)


def test_affine_const_prefix_slow_mixing_stable():
    """Near-unit-root M (rho ~ 0.999): the doubling association must not
    lose accuracy relative to the sequential recursion over long spans."""
    from dfm_tpu.ops.scan import affine_const_prefix
    rng = np.random.default_rng(5)
    k, n = 3, 2048
    Q, _ = np.linalg.qr(rng.normal(size=(k, k)))
    M = Q @ np.diag([0.999, 0.99, 0.9]) @ Q.T
    d = rng.normal(size=(n, k))
    x0 = rng.normal(size=k)
    got = np.asarray(affine_const_prefix(jnp.asarray(M), jnp.asarray(d),
                                         jnp.asarray(x0)))
    x = x0
    for t in range(n):
        x = M @ x + d[t]
    # the final state has accumulated ~n combines in both orders
    np.testing.assert_allclose(got[-1], x, rtol=1e-9)
    assert np.isfinite(got).all()

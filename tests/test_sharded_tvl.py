"""Sharded TVL estimation == single-device tvl_fit on the fake mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from dfm_tpu.models.tv_loadings import TVLSpec, tvl_fit
from dfm_tpu.parallel.mesh import make_mesh
from dfm_tpu.parallel.sharded_tvl import sharded_tvl_fit
from dfm_tpu.utils import dgp


def test_sharded_tvl_matches_single_device():
    rng = np.random.default_rng(95)
    Y, F, Lams, _, _ = dgp.simulate_tv_loadings(32, 120, 2, rng,
                                                walk_scale=0.05)
    spec = TVLSpec(n_factors=2, n_rounds=5, tol=0.0)
    r1 = tvl_fit(Y, spec)
    r8 = sharded_tvl_fit(Y, spec, mesh=make_mesh(8), dtype=jnp.float64)
    np.testing.assert_allclose(r8.logliks, r1.logliks, rtol=1e-8)
    np.testing.assert_allclose(r8.loadings, r1.loadings, atol=1e-7)
    np.testing.assert_allclose(r8.factors, r1.factors, atol=1e-7)


def test_sharded_tvl_padding_and_mask():
    rng = np.random.default_rng(96)
    Y, F, Lams, _, _ = dgp.simulate_tv_loadings(30, 90, 2, rng,
                                                walk_scale=0.05)
    W = dgp.random_mask(90, 30, rng, 0.2)
    Ynan = np.where(W > 0, Y, np.nan)
    spec = TVLSpec(n_factors=2, n_rounds=3, tol=0.0)
    r1 = tvl_fit(Ynan, spec, mask=W)
    r7 = sharded_tvl_fit(Ynan, spec, mask=W, mesh=make_mesh(7),
                         dtype=jnp.float64)
    np.testing.assert_allclose(r7.logliks, r1.logliks, rtol=1e-8)
    np.testing.assert_allclose(r7.common, r1.common, atol=1e-6)


def test_sharded_tvl_f32_tolerance():
    """TPU-dtype (f32) sharded run vs the f64 oracle, uneven 7-shard mesh
    (VERDICT r2 item 9 — previously x64-only equivalence evidence)."""
    rng = np.random.default_rng(97)
    Y, F, Lams, _, _ = dgp.simulate_tv_loadings(32, 120, 2, rng,
                                                walk_scale=0.05)
    spec = TVLSpec(n_factors=2, n_rounds=4, tol=0.0)
    r64 = tvl_fit(Y, spec)
    r32 = sharded_tvl_fit(Y, spec, mesh=make_mesh(7), dtype=jnp.float32)
    n_obs = float(Y.size)
    floor = 200 * np.finfo(np.float32).eps * n_obs
    np.testing.assert_allclose(r32.logliks, r64.logliks, atol=floor,
                               rtol=1e-4)
    np.testing.assert_allclose(r32.common, r64.common, atol=5e-3)


def test_sharded_tvl_fused_chunk_matches_unfused():
    """fused_chunk>1 == fused_chunk=1 on the fake mesh (x64 exact)."""
    rng = np.random.default_rng(98)
    Y, F, Lams, _, _ = dgp.simulate_tv_loadings(32, 100, 2, rng,
                                                walk_scale=0.05)
    spec = TVLSpec(n_factors=2, n_rounds=5, tol=0.0)
    r1 = sharded_tvl_fit(Y, spec, mesh=make_mesh(8), dtype=jnp.float64,
                         fused_chunk=1)
    r3 = sharded_tvl_fit(Y, spec, mesh=make_mesh(8), dtype=jnp.float64,
                         fused_chunk=3)
    np.testing.assert_allclose(r3.logliks, r1.logliks, rtol=1e-12)
    np.testing.assert_allclose(r3.loadings, r1.loadings, atol=1e-12)
    np.testing.assert_allclose(r3.common, r1.common, atol=1e-10)

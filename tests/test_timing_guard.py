"""Source-level invariant: timing/telemetry code uses monotonic clocks and
real execution barriers.

Two environment facts this audit encodes (CLAUDE.md "hard-won"):

- ``jax.block_until_ready`` is a NO-OP on the axon PJRT plugin (returns in
  0.1 ms while the program is still running).  Any timing or telemetry code
  that "waits" with it measures dispatch overhead, not execution: the only
  reliable barrier is a device->host transfer (``np.asarray``).
- ``time.time()`` is a wall clock: NTP steps and slews make deltas lie.
  Durations and event timestamps must come from ``time.perf_counter()``
  (the ``obs.trace`` event schema is defined in those terms).

Scope: the ``dfm_tpu`` package and the bench tree (``bench.py`` +
``bench/``) — everything that times programs or emits telemetry.
``__graft_entry__.py`` is deliberately OUT of scope: its two
``block_until_ready`` calls gate correctness checks on the fake CPU mesh
(where the call works) and time nothing.

Same mechanism as ``test_precision_guard``: walk the AST so a violation
fails CI instead of silently shipping bogus numbers.
"""

import ast
import pathlib

import dfm_tpu

PKG_ROOT = pathlib.Path(dfm_tpu.__file__).parent
REPO_ROOT = PKG_ROOT.parent

# relpath -> (max occurrences, reason).  Frozen: a new entry needs a reason
# that is genuinely not a duration/telemetry use.
TIME_TIME_ALLOWLIST = {
    # Unix timestamp stamped into the BENCH_ALL.json artifact
    # ("recorded_unix") — a wall-clock *date*, not a duration.
    "bench/all.py": (1, "recorded_unix artifact timestamp"),
    # Run-registry records carry a wall-clock date (``t_unix``, and the
    # time prefix of ``new_run_id``) so history sorts across processes;
    # all durations in a RunRecord come from perf_counter upstream.
    "dfm_tpu/obs/store.py": (2, "RunRecord t_unix / run_id timestamps"),
}


def _scoped_files():
    files = sorted(PKG_ROOT.rglob("*.py"))
    files += [REPO_ROOT / "bench.py"]
    files += sorted((REPO_ROOT / "bench").rglob("*.py"))
    return files


def _rel(path: pathlib.Path) -> str:
    return str(path.relative_to(REPO_ROOT))


def _is_time_time_call(node: ast.AST) -> bool:
    """Matches ``time.time()`` and bare ``time()`` (from-imports)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return (f.attr == "time" and isinstance(f.value, ast.Name)
                and f.value.id == "time")
    return isinstance(f, ast.Name) and f.id == "time"


def test_no_wall_clock_in_timing_paths():
    hits = {}
    for path in _scoped_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        lines = [n.lineno for n in ast.walk(tree) if _is_time_time_call(n)]
        if lines:
            hits[_rel(path)] = lines
    bad = {}
    for rel, lines in hits.items():
        cap, _reason = TIME_TIME_ALLOWLIST.get(rel, (0, ""))
        if len(lines) > cap:
            bad[rel] = lines
    assert not bad, (
        "time.time() in timing/telemetry scope (wall clocks lie across NTP "
        f"steps; use time.perf_counter): {bad}")


def test_no_block_until_ready_in_timing_paths():
    bad = {}
    for path in _scoped_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        lines = [n.lineno for n in ast.walk(tree)
                 if (isinstance(n, ast.Attribute)
                     and n.attr == "block_until_ready")
                 or (isinstance(n, ast.Name)
                     and n.id == "block_until_ready")]
        if lines:
            bad[_rel(path)] = lines
    assert not bad, (
        "block_until_ready in timing/telemetry scope (a no-op barrier on "
        f"the axon plugin; use a device->host transfer): {bad}")


def test_audit_scope_saw_the_timing_modules():
    # A path refactor must update this audit, not silently skip it.
    rels = {_rel(p) for p in _scoped_files()}
    expected = {"dfm_tpu/obs/trace.py", "dfm_tpu/obs/report.py",
                "dfm_tpu/obs/profile.py", "dfm_tpu/obs/cost.py",
                "dfm_tpu/obs/advise.py", "dfm_tpu/obs/metrics.py",
                "dfm_tpu/obs/slo.py", "dfm_tpu/obs/live.py",
                "dfm_tpu/estim/em.py", "dfm_tpu/estim/fused.py",
                "dfm_tpu/estim/tune.py", "dfm_tpu/robust/guard.py",
                "bench.py", "bench/all.py", "bench/batched.py",
                "bench/tune.py"}
    assert expected <= rels, sorted(expected - rels)


def test_allowlist_is_not_stale():
    rels = {_rel(p) for p in _scoped_files()}
    assert set(TIME_TIME_ALLOWLIST) <= rels, (
        "allowlist names files the audit no longer sees")

"""Serving-grade fault tolerance (ISSUE 10): the unified dispatch guard,
tenant blast-radius isolation, and self-healing sessions, driven
deterministically on the fake CPU mesh.

- ``robust.dispatch.guarded_dispatch`` units: retry + backoff records,
  exhaustion -> ``GuardFailure`` with tenant/session attribution,
  watchdog deadline around a hung d2h, ``policy=None`` passthrough, and
  the ``wrap_dispatch`` fault seam.
- Fused fit: an injected dispatch failure retries to a result EXACTLY
  equal to the clean run; persistent failure degrades to the NumPy
  oracle under ``on_failure="cpu"`` or raises under ``"raise"``.
- Scheduler: a transient mid-bucket failure retries with every tenant's
  result bit-identical to its lone fit; retry exhaustion quarantines the
  bucket and requeues its tenants as lone guarded fits (results still
  match the lone oracle); under ``recover_divergence=True`` a
  NaN-poisoned tenant is evicted ALONE while its bucket-mates keep
  their in-bucket results.
- Sessions: a failed update retries from last-good to the exact clean
  answer; repeated divergence escalates through the repair ladder;
  ``snapshot -> restore -> update`` equals the uninterrupted session
  (x64-exact, f32-tolerance) at the same one-dispatch budget.
- Observability: ``summarize()`` aggregates retries / backoff /
  quarantines / degraded queries per tenant and session; the
  ``serve_degraded_queries`` bench metric stays registered.
"""

import dataclasses

import numpy as np
import pytest

from dfm_tpu import (DynamicFactorModel, Job, fit, fit_jobs, open_session)
from dfm_tpu.api import TPUBackend
from dfm_tpu.backends import cpu_ref
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import summarize, _print_text
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.robust import (FaultInjector, FitHealth, GuardFailure,
                            RobustPolicy)
from dfm_tpu.robust.dispatch import guarded_dispatch
from dfm_tpu.robust.faults import InjectedDispatchError
from dfm_tpu.utils import dgp

MODEL = DynamicFactorModel(n_factors=2, standardize=False)


@pytest.fixture(scope="module")
def panel():
    rng = np.random.default_rng(19)
    p = dgp.dfm_params(N=12, k=2, rng=rng)
    Y, _ = dgp.simulate(p, T=60, rng=rng)
    return Y


def _panel(T, N, k, seed=0):
    rng = np.random.default_rng(seed)
    Y, _ = dgp.simulate(dgp.dfm_params(N, k, rng), T, rng)
    return Y


def _jobs(shapes, seed=0, **kw):
    return [Job(Y=_panel(T, N, k, seed=seed + i),
                model=DynamicFactorModel(n_factors=k), tenant=f"t{i}",
                **kw)
            for i, (T, N, k) in enumerate(shapes)]


def _ref(job, dtype="float64"):
    """Lone-fit oracle, same engine (info filter) as the scheduler."""
    return fit(job.model, job.Y,
               backend=TPUBackend(dtype=dtype, filter="info"),
               max_iters=job.max_iters, tol=job.tol)


def _match(r, ref):
    np.testing.assert_allclose(r.fit.logliks, ref.logliks,
                               rtol=1e-9, atol=1e-7)
    np.testing.assert_allclose(np.asarray(r.fit.params.Lam),
                               np.asarray(ref.params.Lam),
                               rtol=1e-7, atol=1e-8)
    assert r.fit.converged == ref.converged


def quick_policy(**kw):
    kw.setdefault("backoff_base", 1e-6)
    return RobustPolicy(**kw)


# ------------------------------------------- guarded_dispatch units --


def test_guarded_dispatch_retries_then_succeeds():
    calls = []

    def flaky(attempt):
        calls.append(attempt)
        if len(calls) < 3:
            raise RuntimeError(f"tunnel reset #{len(calls)}")
        return "ok"

    h = FitHealth()
    out = guarded_dispatch(flaky, quick_policy(dispatch_retries=3), h,
                           label="unit dispatch", tenant="acme")
    assert out == "ok" and calls == [0, 1, 2]
    assert h.n_dispatch_retries == 2
    assert [e.kind for e in h.events] == ["dispatch_error"] * 2
    assert all(e.action == "retried" and e.tenant == "acme"
               for e in h.events)
    # Exponential backoff is charged to the event that paid it.
    assert h.events[1].backoff_s > h.events[0].backoff_s > 0.0


def test_guarded_dispatch_exhaustion_raises_guardfailure():
    def dead(attempt):
        raise ConnectionError("axon tunnel down")

    h = FitHealth()
    lg = {"called": 0}

    def last_good():
        lg["called"] += 1
        return "LG"

    with pytest.raises(GuardFailure, match=r"session update failed after "
                       r"1 retries \(tenant acme\) \(session s9\)") as ei:
        guarded_dispatch(dead, quick_policy(dispatch_retries=1), h,
                         label="session update", tenant="acme",
                         session="s9", last_good=last_good,
                         lls=[-5.0, -4.0], p_iters=2)
    e = ei.value
    assert lg["called"] == 1 and e.last_good == "LG"
    np.testing.assert_array_equal(e.lls, [-5.0, -4.0])
    assert e.p_iters == 2
    assert e.health is h and h.events[-1].action == "abort"


def test_guarded_dispatch_policy_none_passthrough():
    seen = []
    assert guarded_dispatch(lambda a: seen.append(a) or 42, None) == 42
    assert seen == [0]
    with pytest.raises(ValueError):   # no retry machinery without policy
        guarded_dispatch(lambda a: (_ for _ in ()).throw(ValueError("x")),
                         None)


def test_guarded_dispatch_guardfailure_passes_through_untouched():
    gf = GuardFailure("terminal", FitHealth(), None, [], 0)

    def call(attempt):
        raise gf

    h = FitHealth()
    with pytest.raises(GuardFailure) as ei:
        guarded_dispatch(call, quick_policy(dispatch_retries=5), h)
    assert ei.value is gf and h.n_dispatch_retries == 0


def test_guarded_dispatch_watchdog_recovers_hung_call():
    import time as _time
    calls = []

    def hung_then_fine(attempt):
        calls.append(attempt)
        if attempt == 0:
            _time.sleep(2.0)   # "hung d2h": never lands within deadline
        return "served"

    h = FitHealth()
    out = guarded_dispatch(
        hung_then_fine,
        quick_policy(dispatch_retries=2, dispatch_deadline_s=0.1), h,
        label="fused fit")
    assert out == "served" and calls == [0, 1]
    assert h.n_dispatch_retries == 1
    assert "watchdog" in h.events[0].detail
    assert h.events[0].detail.startswith("TimeoutError")


def test_guarded_dispatch_injector_seam():
    inj = FaultInjector().dispatch_failure(at=0)
    h = FitHealth()
    out = guarded_dispatch(lambda a: "ok",
                           quick_policy(wrap_dispatch=inj.wrap_call), h)
    assert out == "ok"
    # Retries consume NEW call indices, so a one-shot fault clears.
    assert inj.log == [(0, "raise")] and inj.calls == 2
    assert h.n_dispatch_retries == 1


# ------------------------------------------------- fused fit guard --


def test_fused_injected_failure_retries_to_exact_parity(panel):
    b = TPUBackend(fused_chunk=4)
    clean = fit(MODEL, panel, backend=b, fused=True, max_iters=10,
                tol=0.0, robust=False)
    inj = FaultInjector().dispatch_failure(at=0)
    r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), fused=True,
            max_iters=10, tol=0.0,
            robust=quick_policy(wrap_dispatch=inj.wrap_call))
    np.testing.assert_array_equal(r.logliks, clean.logliks)
    np.testing.assert_array_equal(np.asarray(r.params.Lam),
                                  np.asarray(clean.params.Lam))
    assert inj.log == [(0, "raise")]
    assert r.health is not None and r.health.n_dispatch_retries == 1
    assert [e.kind for e in r.health.events] == ["dispatch_error"]


def test_fused_hung_transfer_watchdog_recovers(panel):
    b = TPUBackend(fused_chunk=4)
    clean = fit(MODEL, panel, backend=b, fused=True, max_iters=8,
                tol=0.0, robust=False)
    # The deadline bounds EVERY attempt (including the clean retry's
    # real execution), so it must sit above the program wall but below
    # the injected hang.
    inj = FaultInjector().hung_transfer(at=0, seconds=30.0)
    r = fit(MODEL, panel, backend=TPUBackend(fused_chunk=4), fused=True,
            max_iters=8, tol=0.0,
            robust=quick_policy(wrap_dispatch=inj.wrap_call,
                                dispatch_deadline_s=5.0))
    np.testing.assert_array_equal(r.logliks, clean.logliks)
    assert inj.log[0] == (0, "hang")
    assert any("watchdog" in e.detail for e in r.health.events)


def test_fused_persistent_failure_degrades_to_cpu(panel):
    inj = FaultInjector().dispatch_failure(at=0, count=-1)
    r = fit(MODEL, panel, fused=True, max_iters=6, tol=0.0,
            robust=quick_policy(dispatch_retries=1, on_failure="cpu",
                                wrap_dispatch=inj.wrap_call))
    assert r.health.fallback_backend == "cpu" and not r.health.ok
    assert len(r.logliks) == 6 and np.isfinite(r.logliks).all()
    # The degraded fit IS the oracle fit: same init, same budget.
    ref = fit(MODEL, panel, backend="cpu", max_iters=6, tol=0.0)
    np.testing.assert_allclose(r.logliks, ref.logliks,
                               rtol=1e-9, atol=1e-7)


def test_fused_persistent_failure_raises_by_default(panel):
    inj = FaultInjector().dispatch_failure(at=0, count=-1)
    with pytest.raises(GuardFailure, match="fused fit failed after"):
        fit(MODEL, panel, fused=True, max_iters=4, tol=0.0,
            robust=quick_policy(dispatch_retries=1,
                                wrap_dispatch=inj.wrap_call))


# -------------------------------------- scheduler blast-radius --


def test_sched_midbucket_retry_keeps_bucket_parity():
    jobs = _jobs([(40, 10, 2)] * 3, seed=700, max_iters=10, tol=1e-6)
    inj = FaultInjector().dispatch_failure(at=0)
    stats = {}
    res = fit_jobs(jobs, max_buckets=1, dtype="float64", stats=stats,
                   robust=quick_policy(wrap_dispatch=inj.wrap_call))
    assert inj.log == [(0, "raise")]
    assert stats["n_quarantined"] == 0
    for r, job in zip(res, jobs):
        _match(r, _ref(job))


def test_sched_exhausted_bucket_quarantines_and_requeues():
    jobs = _jobs([(40, 10, 2)] * 3, seed=710, max_iters=8, tol=1e-6)
    inj = FaultInjector().dispatch_failure(at=0)
    stats = {}
    tr = Tracer()
    with activate(tr):
        res = fit_jobs(jobs, max_buckets=1, dtype="float64", stats=stats,
                       robust=quick_policy(dispatch_retries=0,
                                           wrap_dispatch=inj.wrap_call))
    assert stats["n_quarantined"] == 3
    for i, (r, job) in enumerate(zip(res, jobs)):
        # Requeued lone guarded fits still match the lone oracle.
        _match(r, _ref(job))
        h = r.fit.health
        assert h is not None and not h.ok
        ev = h.events[0]
        assert ev.kind == "quarantine" and ev.action == "requeued"
        assert ev.tenant == f"t{i}"
        assert "InjectedDispatchError" in ev.detail
        assert r.pad_waste_frac == 0.0
    # The trace carries the quarantines with tenant attribution.
    s = summarize(tr.events)
    rb = s["robustness"]
    assert rb["quarantines"] == 3
    assert {t for t, pt in rb["per_tenant"].items() if pt["quarantined"]} \
        == {"t0", "t1", "t2"}
    _print_text(s)


def test_sched_nonretryable_failure_propagates():
    """Quarantine only catches the policy's retry_exceptions: a failure
    OUTSIDE that tuple (here the injected error, with retry_exceptions
    narrowed to ConnectionError) propagates instead of quarantining —
    programming errors never masquerade as tenant faults."""
    jobs = _jobs([(40, 10, 2)] * 2, seed=720, max_iters=6, tol=1e-6)
    inj = FaultInjector().dispatch_failure(at=0, count=-1)
    pol = RobustPolicy(backoff_base=1e-6, dispatch_retries=0,
                       retry_exceptions=(ConnectionError,),
                       wrap_dispatch=inj.wrap_call)
    with pytest.raises(InjectedDispatchError):
        fit_jobs(jobs, max_buckets=1, dtype="float64", robust=pol)


def test_sched_nan_tenant_quarantined_under_recover_divergence():
    jobs = _jobs([(40, 12, 2)] * 3, seed=730, max_iters=10, tol=1e-6)
    bad = cpu_ref.pca_init(
        np.asarray(jobs[1].Y) / np.asarray(jobs[1].Y).std(axis=0), 2)
    bad = dataclasses.replace(bad, Lam=np.full_like(bad.Lam, np.nan))
    jobs[1] = Job(Y=jobs[1].Y, model=jobs[1].model, tenant="poisoned",
                  init=bad, max_iters=10, tol=1e-6)
    stats = {}
    res = fit_jobs(jobs, max_buckets=1, dtype="float64", stats=stats,
                   robust=quick_policy(recover_divergence=True))
    assert stats["n_quarantined"] == 1
    # Bucket-mates keep their IN-BUCKET results, identical to lone fits.
    for i in (0, 2):
        _match(res[i], _ref(jobs[i]))
        assert not any(e.kind == "quarantine"
                       for e in res[i].fit.health.events)
    # The poisoned tenant was evicted alone and repaired in its lone
    # refit: finite trajectory, quarantine + repair on the record.
    h = res[1].fit.health
    assert h.events[0].kind == "quarantine"
    assert h.events[0].tenant == "poisoned"
    assert "non-finite" in h.events[0].detail
    assert np.isfinite(np.asarray(res[1].fit.logliks)).all()
    assert not h.ok


def test_sched_nan_tenant_sails_through_by_default():
    """The PR 8 pinned default is unchanged: without
    ``recover_divergence`` a NaN-poisoned tenant runs to its cap
    in-bucket (independent lanes), no quarantine."""
    jobs = _jobs([(40, 12, 2)] * 2, seed=740, max_iters=8, tol=1e-6)
    bad = cpu_ref.pca_init(
        np.asarray(jobs[1].Y) / np.asarray(jobs[1].Y).std(axis=0), 2)
    bad = dataclasses.replace(bad, Lam=np.full_like(bad.Lam, np.nan))
    jobs[1] = Job(Y=jobs[1].Y, model=jobs[1].model, tenant="poisoned",
                  init=bad, max_iters=8, tol=1e-6)
    stats = {}
    res = fit_jobs(jobs, max_buckets=1, dtype="float64", stats=stats)
    assert stats["n_quarantined"] == 0
    assert len(res[1].fit.logliks) == 8
    assert not np.isfinite(np.asarray(res[1].fit.logliks)).all()
    _match(res[0], _ref(jobs[0]))


# ---------------------------------------- self-healing sessions --


def test_session_injected_failure_retries_to_exact_parity(panel):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    inj = FaultInjector().dispatch_failure(at=0)
    kw = dict(capacity=60, max_update_rows=2, max_iters=4, tol=0.0)
    s_clean = open_session(res0, Y0, robust=False, **kw)
    s_guard = open_session(
        res0, Y0, robust=quick_policy(wrap_dispatch=inj.wrap_call), **kw)
    u_c = s_clean.update(panel[40:42])
    u_g = s_guard.update(panel[40:42])
    np.testing.assert_array_equal(u_g.nowcast, u_c.nowcast)
    np.testing.assert_array_equal(u_g.logliks, u_c.logliks)
    np.testing.assert_array_equal(u_g.factors, u_c.factors)
    assert inj.log == [(0, "raise")] and inj.calls == 2
    h = s_guard.health
    assert h.n_dispatch_retries == 1
    assert [e.kind for e in h.events] == ["dispatch_error"]
    assert h.events[0].session == s_guard.session_id
    assert s_clean.health.ok    # the unguarded twin recorded nothing


def test_session_repeated_divergence_escalates_repair(panel):
    b = TPUBackend(fused_chunk=4)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, backend=b, capacity=60,
                        max_update_rows=2, max_iters=8, tol=0.0,
                        robust=quick_policy(chunk_retries=1))
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=1)
    with pytest.warns(RuntimeWarning, match="diverged"):
        u1 = sess.update(panel[40:41])
    assert u1.diverged and "repair_params" not in sess.health.escalations
    with pytest.warns(RuntimeWarning, match="diverged"):
        sess.update(panel[41:42])
    # Second CONSECUTIVE divergence exceeds chunk_retries: the repair
    # ladder projects the resident params and re-uploads.
    assert sess.health.escalations == ["repair_params"]
    acts = [(e.kind, e.action) for e in sess.health.events]
    assert ("divergence", "restored") in acts
    assert ("divergence", "repaired") in acts
    assert all(e.session == sess.session_id for e in sess.health.events)
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=None)
    u3 = sess.update(panel[42:43])   # the session survives, healthy
    assert not u3.diverged and np.isfinite(u3.nowcast).all()


@pytest.mark.parametrize("dtype", ["float64", "float32"],
                         ids=["x64", "f32"])
def test_snapshot_restore_update_matches_uninterrupted(panel, tmp_path,
                                                       dtype):
    b = TPUBackend(dtype=dtype, fused_chunk=4)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, backend=b, capacity=60,
                        max_update_rows=2, max_iters=4, tol=0.0)
    sess.update(panel[40:42])
    path = sess.snapshot(str(tmp_path / "sess.npz"))
    rest = open_session(snapshot=path, backend=b)
    assert rest.t == sess.t == 42
    assert rest.capacity == 60 and rest.remaining == sess.remaining
    u_a = sess.update(panel[42:44])
    u_b = rest.update(panel[42:44])
    assert u_b.t == u_a.t == 44
    if dtype == "float64":
        np.testing.assert_array_equal(u_b.nowcast, u_a.nowcast)
        np.testing.assert_array_equal(u_b.logliks, u_a.logliks)
        np.testing.assert_array_equal(u_b.factors, u_a.factors)
    else:
        np.testing.assert_allclose(u_b.nowcast, u_a.nowcast,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(u_b.logliks, u_a.logliks,
                                   rtol=1e-3, atol=0.5)


def test_restored_session_keeps_one_dispatch_budget(panel, tmp_path):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=8, tol=1e-6)
    sess = open_session(res0, Y0, capacity=60, max_update_rows=2,
                        max_iters=4, tol=0.0)
    sess.update(panel[40:42])   # compiles the one executable
    path = sess.snapshot(str(tmp_path / "sess.npz"))
    rest = open_session(snapshot=path)
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        rest.update(panel[42:44])
    disp = [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "serve_update"]
    # Same shape key in-process: the restored session reuses the
    # compiled executable — one dispatch, no recompile, one barrier.
    assert len(disp) == 1 and not any(e.get("recompile") for e in disp)
    s = summarize(tr.events)
    assert s["blocking_transfers"] <= 1


def test_snapshot_restore_validation(panel, tmp_path):
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6)
    sess = open_session(res0, Y0)
    path = sess.snapshot(str(tmp_path / "sess.npz"))
    with pytest.raises(ValueError, match="cannot be passed"):
        open_session(res0, Y0, snapshot=path)
    with pytest.raises(TypeError, match="open_session needs"):
        open_session()
    # A plain EM checkpoint is not a session snapshot.
    from dfm_tpu.utils.checkpoint import save_checkpoint
    ck = str(tmp_path / "ck.npz")
    save_checkpoint(ck, res0.params, 3, [-1.0], fingerprint="x")
    with pytest.raises(ValueError, match="not a session snapshot"):
        open_session(snapshot=ck)
    # Tampered panel values fail the content fingerprint loudly.
    with np.load(path) as z:
        d = {k: z[k] for k in z.files}
    d["Y_live"] = d["Y_live"] + 1.0
    bad = str(tmp_path / "bad.npz")
    np.savez(bad, **d)
    with pytest.raises(ValueError, match="corrupt"):
        open_session(snapshot=bad)


def test_keep_session_carries_per_fit_robust(panel):
    Y0 = panel[:40]
    r_off = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6,
                keep_session=True, robust=False)
    assert r_off.session._policy is None
    pol = quick_policy()
    r_on = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6,
               keep_session=True, robust=pol)
    assert r_on.session._policy is pol
    r_off.session.close()
    r_on.session.close()


def test_auto_composes_with_robust(panel, tmp_path, monkeypatch):
    monkeypatch.setenv("DFM_RUNS", str(tmp_path / "runs"))
    with pytest.warns(RuntimeWarning):   # empty registry -> default fit
        r = fit(MODEL, panel[:40], auto=True, max_iters=4, tol=0.0,
                robust=quick_policy())
    assert r.health is not None and r.health.ok


# ------------------------------------------------ observability --


def test_summarize_aggregates_session_robustness(panel):
    b = TPUBackend(fused_chunk=4)
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=8, tol=1e-6)
    inj = FaultInjector().dispatch_failure(at=0)
    tr = Tracer()
    with activate(tr):
        sess = open_session(
            res0, Y0, backend=b, capacity=60, max_update_rows=2,
            max_iters=8, tol=0.0,
            robust=quick_policy(chunk_retries=0,
                                wrap_dispatch=inj.wrap_call))
        sess.update(panel[40:41])        # injected failure -> one retry
        sess._opts = dataclasses.replace(sess._opts, fault_chunk=1)
        with pytest.warns(RuntimeWarning, match="diverged"):
            sess.update(panel[41:42])    # diverged -> degraded + repaired
    s = summarize(tr.events)
    rb = s["robustness"]
    assert rb["dispatch_retries"] == 1
    assert rb["backoff_s_total"] > 0.0
    assert rb["degraded_queries"] == 1
    assert rb["recovered_divergences"] >= 1
    ps = rb["per_session"][sess.session_id]
    assert ps["retries"] == 1 and ps["degraded_queries"] == 1
    assert ps["recovered_divergences"] >= 1
    _print_text(s)   # the text report renders the robustness section


def test_clean_trace_has_zeroed_robustness_section(panel):
    # Schema v1 (ISSUE 12): the robustness section is always present
    # with stable keys; a clean trace reports all-zero counters.
    Y0 = panel[:40]
    res0 = fit(MODEL, Y0, fused=True, max_iters=6, tol=1e-6)
    tr = Tracer()
    with activate(tr):
        sess = open_session(res0, Y0, capacity=60, max_update_rows=2,
                            max_iters=4, tol=0.0)
        sess.update(panel[40:42])
    rb = summarize(tr.events)["robustness"]
    assert rb["dispatch_retries"] == 0 and rb["quarantines"] == 0
    assert rb["degraded_queries"] == 0 and rb["backoff_s_total"] == 0.0
    assert rb["per_tenant"] == {} and rb["per_session"] == {}


def test_degraded_queries_metric_registered():
    from dfm_tpu.obs import store
    assert "serve_degraded_queries" in store._BENCH_NUMERIC_KEYS
    assert store.lower_is_better("serve_degraded_queries")
    assert store.noise_floor("serve_degraded_queries") == 0

"""Fleet serving (dfm_tpu/fleet/ + the serve/batched fleet core).

The operative contracts of ``open_fleet`` (ISSUE 11), verified on the
fake 8-device CPU mesh (conftest):

- PER-TENANT PARITY: lane b of a fleet tick answers exactly what the
  same tenant's lone ``NowcastSession`` would at the same budget —
  x64 nowcasts/factors/forecasts pin to ~1e-10 across ragged mixed-row
  ticks AND a tick the tenant sits out (its lane frozen bit-inert);
  an f32 variant holds to f32 tolerance; ``backend="sharded"`` splits
  the bucket batch axis over the mesh and matches the single-device
  fleet.
- SCATTER-APPEND INERTNESS (satellite): the in-graph ragged row scatter
  touches ONLY the [t, t+n) x [:N] target region — pad rows/columns
  stay exactly zero per padded axis (T, N, k), the live prefix equals
  the host shadow bit-for-bit, and an inactive-tenant tick leaves the
  lane's panel AND params bit-unchanged.  Cross-padding numerics agree
  to fp-reduction tolerance (XLA reassociates across shapes).
- ONE-EXECUTABLE BUDGET: a traced fleet pays 1 serve_update first-call
  per bucket, 0 recompiles after warmup across varying active sets /
  row counts, and exactly one blocking d2h per tick; ``summarize()``
  gains the fleet section (occupancy, queue waits, queries/dispatch).
- QUARANTINE: a tenant diverging past ``policy.chunk_retries`` ticks is
  evicted to a lone guarded session; bucket-mates stay BIT-IDENTICAL
  to a fault-free twin fleet and the evicted tenant's next query heals.
- PLANNING: ``plan_admission`` / ``plan_capacity_classes`` /
  ``obs.advise --fleet`` are jax-free and deterministic; the fleet
  bench metrics stay registered in the observatory.
"""

import dataclasses
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dfm_tpu import (DynamicFactorModel, SessionFleet, fit, open_fleet,
                     open_session)
from dfm_tpu.api import TPUBackend
from dfm_tpu.estim.batched import unstack_params
from dfm_tpu.fleet import fleet_pad_waste, plan_admission
from dfm_tpu.fleet.buffers import FleetBucket
from dfm_tpu.obs.advise import advise_fleet
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import _print_text, summarize
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.robust import RobustPolicy
from dfm_tpu.robust.health import FitHealth
from dfm_tpu.sched.buckets import plan_capacity_classes
from dfm_tpu.serve.batched import FleetOptions, _fleet_impl
from dfm_tpu.utils import dgp

# Default-engine pins run info explicitly so the lone-session parity
# reference is deterministic (the auto heuristic would pick dense at
# these small N, which fleet buckets map to the info twins).  The fleet
# core routes any engine — per-engine parity is pinned in the ENGINE
# ROUTING section below against lone SAME-engine sessions.
BE = TPUBackend(filter="info")
_PF = ("Lam", "A", "Q", "R", "mu0", "P0")


def _tenant(N, T, k, seed, extra=10, backend=BE):
    rng = np.random.default_rng(seed)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T + extra, rng)
    res = fit(DynamicFactorModel(n_factors=k), Y[:T], max_iters=8,
              backend=backend, telemetry=False)
    return res, Y[:T], Y[T:]


@pytest.fixture(scope="module")
def trio():
    """Three tenants, two shapes — one bucket pads, one is exact."""
    return [_tenant(10, 40, 2, 21), _tenant(12, 44, 2, 22),
            _tenant(12, 44, 2, 23)]


def _open(trio_, **kw):
    kw.setdefault("capacity", 56)
    kw.setdefault("max_update_rows", 3)
    kw.setdefault("max_iters", 4)
    kw.setdefault("tol", 0.0)
    kw.setdefault("backend", BE)
    kw.setdefault("max_classes", 1)
    return open_fleet([t[0] for t in trio_], [t[1] for t in trio_], **kw)


def _lone(res, Y, **kw):
    kw.setdefault("capacity", 56)
    kw.setdefault("max_update_rows", 3)
    kw.setdefault("max_iters", 4)
    kw.setdefault("tol", 0.0)
    kw.setdefault("backend", BE)
    return open_session(res, Y, **kw)


def _assert_matches(u, ref, tol=1e-9, atol=1e-10, ll_rtol=1e-7):
    assert u.t == ref.t and u.n_iters == ref.n_iters
    assert u.converged == ref.converged and u.diverged == ref.diverged
    np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=tol, atol=atol)
    np.testing.assert_allclose(u.factors, ref.factors, rtol=tol, atol=atol)
    np.testing.assert_allclose(u.forecasts["y"], ref.forecasts["y"],
                               rtol=tol, atol=atol)
    np.testing.assert_allclose(u.forecasts["f"], ref.forecasts["f"],
                               rtol=tol, atol=atol)
    if ref.forecasts["di"] is not None:
        np.testing.assert_allclose(u.forecasts["di"], ref.forecasts["di"],
                                   rtol=tol, atol=atol)
    # Logliks differ by summation ORDER only (bucket T_cap/N pad terms
    # are exactly zero but reassociate): fp-reduction tolerance.
    np.testing.assert_allclose(u.logliks, ref.logliks, rtol=ll_rtol,
                               atol=1e-6)


# ------------------------------------------------------------- parity --

def test_fleet_matches_lone_sessions_ragged_and_inactive(trio):
    """The acceptance pin: every tenant's fleet answer IS its lone
    session's, across ragged mixed-row ticks, a tick it sits out, and
    the query after (the frozen lane resumed exactly where it was)."""
    fl = _open(trio)
    assert fl.n_buckets == 1 and sorted(fl.tenants) == ["t0", "t1", "t2"]
    lone = [_lone(t[0], t[1]) for t in trio]
    bk = fl._buckets[0]

    # Tick 1: all three active with DIFFERENT row counts (one executable).
    ns1 = (1, 3, 2)
    for i, n in enumerate(ns1):
        fl.submit(f"t{i}", trio[i][2][:n])
    out1 = fl.drain()
    for i, n in enumerate(ns1):
        _assert_matches(out1[f"t{i}"][0], lone[i].update(trio[i][2][:n]))
        assert fl.tenant_length(f"t{i}") == trio[i][1].shape[0] + n

    # Tick 2: t1 sits out — its lane must be frozen BIT-inert.
    p1_before = unstack_params(bk.p)[1]
    Y1_before = np.asarray(bk.Ybuf[1])
    fl.submit("t0", trio[0][2][1:3])
    fl.submit("t2", trio[2][2][2:3])
    out2 = fl.drain()
    _assert_matches(out2["t0"][0], lone[0].update(trio[0][2][1:3]))
    _assert_matches(out2["t2"][0], lone[2].update(trio[2][2][2:3]))
    p1_after = unstack_params(bk.p)[1]
    for f in _PF:
        np.testing.assert_array_equal(np.asarray(getattr(p1_after, f)),
                                      np.asarray(getattr(p1_before, f)),
                                      err_msg=f"inactive lane params {f}")
    np.testing.assert_array_equal(np.asarray(bk.Ybuf[1]), Y1_before,
                                  err_msg="inactive lane panel")

    # Tick 3: t1 comes back — still pins to its (uninterrupted) lone
    # session, proving the inactive tick changed nothing downstream.
    fl.submit("t1", trio[1][2][3:5])
    out3 = fl.drain()
    _assert_matches(out3["t1"][0], lone[1].update(trio[1][2][3:5]))
    fl.close()
    with pytest.raises(RuntimeError, match="closed"):
        fl.submit("t0", trio[0][2][:1])


def test_fleet_pure_reforecast_query(trio):
    """``submit(tenant, None)`` re-runs warm EM + forecast with NO
    append — same answer as the lone session's ``update(None)``.
    (Same bucket shape/statics as the parity test: executable reused.)"""
    fl = _open(trio)
    lone0 = _lone(trio[0][0], trio[0][1])
    fl.submit("t0", trio[0][2][:2])
    lone0.update(trio[0][2][:2])
    fl.drain()
    fl.submit("t0", None)
    with pytest.raises(ValueError, match="mask requires rows"):
        fl.submit("t0", None, mask=np.ones((1, 10)))
    u = fl.drain()["t0"][0]
    ref = lone0.update(None)
    assert u.t == ref.t == 42    # nothing appended
    _assert_matches(u, ref)
    assert fl.tenant_length("t0") == 42
    fl.close()


def test_fleet_matches_lone_sessions_f32(trio):
    # Same-shape tenants on purpose: one f32 fit/serve executable pair
    # covers both lanes (the cross-shape seams are pinned in x64 above).
    b32 = TPUBackend(dtype=jnp.float32, filter="info")
    tens = [_tenant(12, 44, 2, 31, backend=b32),
            _tenant(12, 44, 2, 32, backend=b32)]
    fl = _open(tens, backend=b32, max_iters=3)
    lone = [_lone(t[0], t[1], backend=b32, max_iters=3) for t in tens]
    for i, n in enumerate((2, 1)):
        fl.submit(f"t{i}", tens[i][2][:n])
    out = fl.drain()
    for i, n in enumerate((2, 1)):
        u, ref = out[f"t{i}"][0], lone[i].update(tens[i][2][:n])
        assert u.n_iters == ref.n_iters
        np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=5e-3,
                                   atol=5e-3)
        np.testing.assert_allclose(u.factors, ref.factors, rtol=5e-3,
                                   atol=5e-3)
    fl.close()


def test_sharded_fleet_matches_single_device(trio):
    """backend="sharded" splits the bucket batch axis over the fake mesh
    (filler lanes pad to a multiple of the device count) and must match
    the single-device fleet to reduction tolerance."""
    outs = []
    for backend in (BE, "sharded"):
        fl = _open(trio, backend=backend)
        for tick in ((1, 2, 2), (2, 0, 1)):
            for i, n in enumerate(tick):
                if n:
                    off = 3 if tick[0] == 2 else 0
                    fl.submit(f"t{i}", trio[i][2][off:off + n])
            outs_tick = fl.drain()
        outs.append(outs_tick)
        fl.close()
    for t in ("t0", "t2"):
        a, b = outs[0][t][0], outs[1][t][0]
        np.testing.assert_allclose(a.nowcast, b.nowcast, rtol=1e-9,
                                   atol=1e-10)
        np.testing.assert_allclose(a.forecasts["y"], b.forecasts["y"],
                                   rtol=1e-9, atol=1e-10)
        assert a.n_iters == b.n_iters


# ----------------------------------------------------- engine routing --

ENGINES = [("pit_qr", 0), ("lowrank", 2)]


@pytest.fixture(scope="module")
def eng_pair():
    """Two small same-shape tenants for the routed-engine pins.  The
    pit_qr executables carry a log-depth combine tree whose CPU-mesh
    compile cost grows quickly with the padded length; the parity
    contract is shape-independent, so these pins run a small capacity
    (ragged two-shape bucketing is pinned engine-free above)."""
    return [_tenant(8, 24, 2, 43), _tenant(8, 24, 2, 44)]


@pytest.mark.parametrize("eng,rk", ENGINES)
def test_fleet_engine_matches_lone_same_engine(eng_pair, eng, rk):
    """Per-engine parity: a pit_qr/lowrank-routed bucket answers what
    each tenant's lone SAME-engine session would.  The vmapped engine
    pair reorders one dot_general per step vs the lone pair (XLA batched
    lowering), so the x64 pin is near-machine-eps rather than bit-exact;
    the info engine is pinned truly bit-identical below."""
    kw = dict(capacity=28, max_update_rows=3, max_iters=4, tol=0.0)
    fl = open_fleet([t[0] for t in eng_pair], [t[1] for t in eng_pair],
                    filter=eng, rank=rk, max_classes=1, **kw)
    want_rk = rk if eng == "lowrank" else 0
    for c in fl.classes:
        assert c["filter"] == eng and c["rank"] == want_rk
    lone = [open_session(t[0], t[1], filter=eng, rank=rk, **kw)
            for t in eng_pair]
    for i, n in enumerate((1, 3)):
        fl.submit(f"t{i}", eng_pair[i][2][:n])
    out = fl.drain()
    for i, n in enumerate((1, 3)):
        _assert_matches(out[f"t{i}"][0], lone[i].update(eng_pair[i][2][:n]),
                        tol=1e-9, atol=1e-10)
    for s in lone:
        s.close()
    fl.close()


def test_fleet_engine_matches_lone_f32():
    b32 = TPUBackend(dtype=jnp.float32, filter="lowrank", rank=2)
    tens = [_tenant(10, 32, 2, 33, backend=b32),
            _tenant(10, 32, 2, 34, backend=b32)]
    fl = _open(tens, backend=b32, capacity=40, max_iters=3,
               filter="lowrank", rank=2)
    lone = [_lone(t[0], t[1], backend=b32, capacity=40, max_iters=3,
                  filter="lowrank", rank=2) for t in tens]
    for i, n in enumerate((2, 1)):
        fl.submit(f"t{i}", tens[i][2][:n])
    out = fl.drain()
    for i, n in enumerate((2, 1)):
        u, ref = out[f"t{i}"][0], lone[i].update(tens[i][2][:n])
        assert u.n_iters == ref.n_iters
        np.testing.assert_allclose(u.nowcast, ref.nowcast, rtol=5e-3,
                                   atol=5e-3)
        np.testing.assert_allclose(u.factors, ref.factors, rtol=5e-3,
                                   atol=5e-3)
    fl.close()


def test_fleet_info_explicit_bit_identical_to_default(trio):
    """filter="info" routes through the byte-identical hand-batched
    filter/smoother twins the pre-routing fleet always ran: explicit
    info vs the default (inherited) engine is BIT-identical."""
    outs = []
    for kw in ({}, {"filter": "info"}):
        fl = _open(trio, **kw)
        assert all(c["filter"] == "info" and c["rank"] == 0
                   for c in fl.classes)
        for i, n in enumerate((2, 1, 3)):
            fl.submit(f"t{i}", trio[i][2][:n])
        outs.append(fl.drain())
        fl.close()
    for t in ("t0", "t1", "t2"):
        a, b = outs[0][t][0], outs[1][t][0]
        np.testing.assert_array_equal(a.nowcast, b.nowcast)
        np.testing.assert_array_equal(a.factors, b.factors)
        np.testing.assert_array_equal(a.forecasts["y"], b.forecasts["y"])
        np.testing.assert_array_equal(a.logliks, b.logliks)


def test_fleet_engine_inherits_fit_filter():
    """No filter= needed: a pit_qr fit serves through pit_qr buckets
    (FitResult.filter inheritance); non-routable engines map to info;
    unknown names raise."""
    bq = TPUBackend(filter="pit_qr")
    res, Y0, _ = _tenant(8, 24, 2, 41, backend=bq)
    assert res.filter == "pit_qr"
    fl = open_fleet([res], [Y0], capacity=32, max_iters=2, tol=0.0,
                    backend=bq)
    assert fl.classes[0]["filter"] == "pit_qr"
    fl.close()
    rd, Yd, _ = _tenant(8, 24, 2, 42, backend=TPUBackend(filter="dense"))
    fl = open_fleet([rd], [Yd], capacity=32, max_iters=2, tol=0.0)
    assert fl.classes[0]["filter"] == "info"
    fl.close()
    with pytest.raises(ValueError, match="unknown fleet filter"):
        open_fleet([rd], [Yd], filter="dense")


def test_choose_engine_evidence_gate():
    """The PR 15 evidence gate carried into serving: an engine whose
    residual scale was never profiled is not an "auto" candidate even
    when its structural prior is cheaper."""
    from dfm_tpu.fleet.admission import choose_engine

    class _M:
        pit_qr_calibrated = False
        lowrank_calibrated = False

        def iter_s(self, N, T, k, filt="seq"):
            return {"seq": 1.0, "pit_qr": 0.2, "lowrank": 0.1}[filt]

    m = _M()
    assert choose_engine((56, 12, 2), 4, model=m) == "info"
    m.pit_qr_calibrated = True
    assert choose_engine((56, 12, 2), 4, model=m) == "pit_qr"
    m.lowrank_calibrated = True
    assert choose_engine((56, 12, 8), 4, rank=2, model=m) == "lowrank"
    # rank >= k: the downdate cannot drop work — next-best engine wins.
    assert choose_engine((56, 12, 2), 4, rank=2, model=m) == "pit_qr"


def test_fleet_auto_engine_empty_registry_is_info(trio, tmp_path):
    """filter="auto" with nothing profiled keeps every gate closed: the
    fleet compiles exactly the info engine."""
    fl = _open(trio, filter="auto", runs=str(tmp_path / "empty_runs"))
    assert all(c["filter"] == "info" for c in fl.classes)
    fl.close()


def test_fleet_bands_and_coverage(trio):
    """Rank-r conservative bands as first-class outputs: nowcast_sd /
    forecast_sd ride the existing d2h; the NEXT query scores realized
    rows against the previous 90% bands (host-side, zero dispatches)."""
    fl = _open(trio)
    N = trio[0][1].shape[1]
    fl.submit("t0", trio[0][2][:2])
    u1 = fl.drain()["t0"][0]
    assert u1.nowcast_sd.shape == (N,) and (u1.nowcast_sd > 0).all()
    assert u1.forecast_sd.shape == u1.forecasts["y"].shape
    assert u1.coverage is None          # nothing was predicted before
    fl.submit("t0", trio[0][2][2:3])
    u2 = fl.drain()["t0"][0]
    rows = trio[0][2][2:3]
    hit = (np.abs(rows[0] - u1.forecasts["y"][0])
           <= 1.6448536269514722 * u1.forecast_sd[0])
    assert u2.coverage == pytest.approx(float(np.mean(hit)))
    fl.close()


def test_fleet_snapshot_roundtrip_engine(trio, tmp_path):
    """snapshot_all → restore_fleet round-trips the engine + rank per
    tenant (manifest back-compat: missing keys restore as info)."""
    from dfm_tpu.fleet.driver import restore_fleet
    fl = _open(trio, filter="lowrank", rank=2)
    fl.submit("t0", trio[0][2][:1])
    fl.drain()
    d = str(tmp_path / "snap")
    fl.snapshot_all(d)
    fl.close()
    fl2 = restore_fleet(d, backend=BE)
    assert all(c["filter"] == "lowrank" and c["rank"] == 2
               for c in fl2.classes)
    fl2.close()
    # Pre-engine manifest (no filter/rank keys): defaults to info.
    import json as _json
    mpath = tmp_path / "snap" / "manifest.json"
    man = _json.loads(mpath.read_text())
    for ten in man["tenants"]:
        ten.pop("filter", None)
        ten.pop("rank", None)
    mpath.write_text(_json.dumps(man))
    fl3 = restore_fleet(d, backend=BE)
    assert all(c["filter"] == "info" and c["rank"] == 0
               for c in fl3.classes)
    fl3.close()


# ------------------------------------- scatter-append padding seams --

def _tick_direct(bk, rows, n_new, active=True):
    """Drive ONE lane of ``_fleet_impl`` directly (the satellite-3
    property harness: full control over dims and activity)."""
    B, r_max = bk.B, bk.r_max
    T_cap, N_max, _k = bk.dims
    slot = bk.slots[0]
    rows_b = np.zeros((B, r_max, N_max))
    rmask_b = np.zeros((B, r_max, N_max))
    if n_new:
        W = np.isfinite(rows).astype(float)
        rz = slot.std.transform(rows) if slot.std is not None else rows
        rz = np.where(W > 0, np.nan_to_num(rz), 0.0)
        rows_b[0, :n_new, :slot.N] = rz
        rmask_b[0, :n_new, :slot.N] = W
    return _fleet_impl(
        bk.Ybuf, bk.Wbuf, jnp.asarray(rows_b, bk.dt),
        jnp.asarray(rmask_b, bk.dt),
        jnp.asarray([n_new], np.int32), jnp.asarray([0], np.int32),
        jnp.asarray([slot.t], np.int32),
        bk.p, jnp.asarray([0.0], bk.acc),
        jnp.asarray([bk.floor_for(slot, slot.t + n_new)], bk.acc),
        jnp.asarray([slot.max_iters], np.int32), jnp.asarray([active]),
        cfg=bk.cfg, max_iters=bk.max_iters, opts=bk.opts)


@pytest.mark.parametrize("pad", [(6, 0, 0), (0, 3, 0), (0, 0, 1)],
                         ids=["T", "N", "k"])
def test_scatter_append_inert_across_padding_seams(trio, pad):
    """Per padded axis: the ragged scatter lands ONLY on the target
    region (pad rows/cols exactly zero, live prefix == host shadow
    bit-for-bit) and the tick's answers match the unpadded bucket."""
    res, Y0, stream = trio[0]          # (40, 10), k=2
    ent = ("a", res, Y0, None, 46, 3, 0.0)
    dims1 = (46 + pad[0], 10 + pad[1], 2 + pad[2])
    bk0 = FleetBucket([ent], (46, 10, 2), r_max=2, backend=BE,
                      opts=FleetOptions())
    bk1 = FleetBucket([ent], dims1, r_max=2, backend=BE,
                      opts=FleetOptions())
    out0 = _tick_direct(bk0, stream[:2], 2)
    out1 = _tick_direct(bk1, stream[:2], 2)

    # The scatter-append itself is EXACT: live prefix == host shadow,
    # appended rows land at [40:42) x [:10], everything else stays 0.
    Yb = np.asarray(out1["Ybuf"])
    np.testing.assert_array_equal(Yb[0, :40, :10], bk1.Yhost[0, :40, :10])
    slot = bk1.slots[0]
    rz = slot.std.transform(stream[:2]) if slot.std is not None \
        else stream[:2]
    np.testing.assert_array_equal(Yb[0, 40:42, :10], rz)
    assert not Yb[0, 42:, :].any(), "T-pad rows written"
    assert not Yb[0, :, 10:].any(), "N-pad columns written"
    Wb = np.asarray(out1["Wbuf"])
    assert not Wb[0, 42:, :].any() and not Wb[0, :, 10:].any()

    # Downstream numerics agree across the seam to fp-reduction
    # tolerance (XLA reassociates the exactly-zero pad terms).
    assert int(out1["n_iters"][0]) == int(out0["n_iters"][0])
    assert int(out1["status"][0]) == int(out0["status"][0])
    for key, a_sl, b_sl in (
            ("nowcast", np.s_[0, :10], np.s_[0, :10]),
            ("y_fore", np.s_[0, :, :10], np.s_[0, :, :10]),
            ("f_fore", np.s_[0, :, :2], np.s_[0, :, :2]),
            ("x_sm", np.s_[0, :42, :2], np.s_[0, :42, :2]),
            ("lls", np.s_[0, :3], np.s_[0, :3])):
        np.testing.assert_allclose(np.asarray(out1[key])[b_sl],
                                   np.asarray(out0[key])[a_sl],
                                   rtol=1e-9, atol=1e-10, err_msg=key)


def test_inactive_tick_is_bit_inert(trio):
    """A tick the tenant sits out changes NOTHING in its lane: panel,
    mask and params all bit-identical (act=False freezes + the zero
    scatter lands on already-zero pad)."""
    res, Y0, _stream = trio[0]
    bk = FleetBucket([("a", res, Y0, None, 46, 3, 0.0)], (46, 10, 2),
                     r_max=2, backend=BE, opts=FleetOptions())
    Y_before = np.asarray(bk.Ybuf)
    W_before = np.asarray(bk.Wbuf)
    p_before = unstack_params(bk.p)[0]
    out = _tick_direct(bk, None, 0, active=False)
    np.testing.assert_array_equal(np.asarray(out["Ybuf"]), Y_before)
    np.testing.assert_array_equal(np.asarray(out["Wbuf"]), W_before)
    p_after = unstack_params(out["p"])[0]
    for f in _PF:
        np.testing.assert_array_equal(np.asarray(getattr(p_after, f)),
                                      np.asarray(getattr(p_before, f)),
                                      err_msg=f)
    assert int(out["n_iters"][0]) == 0


# ----------------------------------------------- one-executable budget --

def test_fleet_trace_budget_and_report_section(trio):
    """Warmup + 3 ticks with varying active sets / row counts: ONE
    serve_update executable (0 recompiles after warmup), exactly one
    blocking d2h per tick, and the summarize() fleet section."""
    tr = Tracer(detector=RecompileDetector())
    with activate(tr):
        fl = _open(trio)
        for tick in ((2, 1, 1), (1, 3, 0), (0, 1, 2), (1, 0, 0)):
            for i, n in enumerate(tick):
                if n:
                    fl.submit(f"t{i}", trio[i][2][:n])
            fl.drain()
        fl.close()
    disp = [e for e in tr.events if e.get("kind") == "dispatch"
            and e.get("program") == "serve_update"]
    assert len(disp) == 4
    assert sum(1 for e in disp if e.get("first_call")) == 1
    assert sum(1 for e in disp if e.get("recompile")) == 0
    assert all(e.get("barrier") and e.get("batch") == 3 for e in disp)

    s = summarize(tr.events)
    assert s["blocking_transfers"] == 4          # exactly one per tick
    fs = s["fleet"]
    assert fs["n_ticks"] == 4 and fs["n_buckets"] == 1
    assert fs["n_queries"] == 8
    assert fs["queries_per_dispatch"] == pytest.approx(8 / 4)
    assert 0 < fs["occupancy_mean"] <= 1
    assert fs["per_bucket"]["0"]["ticks"] == 4
    for t in ("t0", "t1", "t2"):
        assert fs["per_tenant"][t]["queue_wait_s"]["p99"] >= 0
        # Engine stamp + realized band coverage ride the query events
        # (t0/t1/t2 all answered >= 2 queries, so coverage resolved).
        assert fs["per_tenant"][t]["engine"] == "info"
        assert 0.0 <= fs["per_tenant"][t]["forecast_coverage"] <= 1.0
    q = s["queries"]
    assert q["recompiles_after_warmup"] == 0
    assert q["per_session"][fl.fleet_id]["queries"] == 8
    _print_text(s)    # the text report renders the fleet stanza


def test_summarize_without_ticks_emits_empty_stable_fleet_section():
    # Schema v1 (ISSUE 12): the fleet section is always present with
    # stable keys; a tickless trace reports zeros/None, not absence.
    s = summarize([{"kind": "dispatch", "program": "x", "key": "k",
                    "t": 0.0, "dur": 0.01, "barrier": True}])
    fs = s["fleet"]
    assert fs["n_ticks"] == 0 and fs["n_queries"] == 0
    assert fs["queries_per_dispatch"] is None
    assert fs["per_bucket"] == {} and fs["per_tenant"] == {}


# ------------------------------------------------------- quarantine --

def test_divergent_tenant_quarantined_bucket_mates_bit_identical(trio):
    """The chaos pin: a deterministically-poisoned tenant is evicted to
    a lone guarded session after policy.chunk_retries diverged ticks;
    its bucket-mates' answers are BIT-IDENTICAL to a fault-free twin
    fleet, and the evicted tenant's next query heals."""
    def run(fleet, n_ticks, start=0):
        outs = []
        for t in range(start, start + n_ticks):
            for i, name in enumerate(fleet.tenants):
                fleet.submit(name, trio[i][2][2 * t:2 * t + 2])
            outs.append(fleet.drain())
        return outs

    clean = _open(trio)
    clean_out = run(clean, 2)
    clean.close()

    fl = _open(trio,
               robust=RobustPolicy(chunk_retries=0, backoff_base=1e-6))
    bk = fl._buckets[0]
    bk.opts = dataclasses.replace(bk.opts, fault_tenant=1, fault_iter=1)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        faulted = run(fl, 2)
    bk.opts = dataclasses.replace(bk.opts, fault_tenant=None)

    assert fl.quarantined() == ["t1"]
    assert any(e.kind == "quarantine" and e.tenant == "t1"
               for e in fl.health.events)
    for t in range(2):
        for name in ("t0", "t2"):
            a, c = faulted[t][name][0], clean_out[t][name][0]
            assert np.array_equal(a.nowcast, c.nowcast), (name, t)
            assert np.array_equal(a.forecasts["y"], c.forecasts["y"])
            assert np.array_equal(a.factors, c.factors)

    # The evicted tenant keeps serving — on its lone guarded session.
    fl.submit("t1", trio[1][2][4:6])
    u = fl.drain()["t1"][0]
    assert np.isfinite(u.nowcast).all() and not u.diverged
    assert u.t == trio[1][1].shape[0] + 6
    fl.close()


def test_guarded_dispatch_tenants_fanout():
    """One bucket dispatch serves many tenants: a retry is recorded
    per-tenant (first emitted, rest replayed), and the singular/plural
    attribution kwargs are mutually exclusive."""
    pol = RobustPolicy(dispatch_retries=1, backoff_base=1e-6)
    h = FitHealth(engine="test")
    calls = []

    def flaky(attempt):
        calls.append(attempt)
        if attempt == 0:
            raise RuntimeError("injected")
        return 42

    from dfm_tpu.robust.dispatch import guarded_dispatch
    assert guarded_dispatch(flaky, pol, h, label="tick",
                            tenants=["a", "b"]) == 42
    assert calls == [0, 1] and h.n_dispatch_retries == 1
    evs = [e for e in h.events if e.kind == "dispatch_error"]
    assert sorted(e.tenant for e in evs) == ["a", "b"]
    with pytest.raises(ValueError, match="not both"):
        guarded_dispatch(flaky, pol, h, tenant="a", tenants=["b"])


# --------------------------------------------- admission / planning --

def test_plan_admission_deterministic_and_partitioned():
    shapes = [(60, 10, 2), (60, 10, 2), (80, 14, 2), (80, 14, 3)]
    iters = [4, 4, 4, 4]
    classes = plan_admission(shapes, iters, max_classes=3)
    seen = sorted(i for c in classes for i in c.members)
    assert seen == [0, 1, 2, 3]          # every tenant in exactly one
    for c in classes:
        for i in c.members:              # dims dominate every member
            assert all(d >= s for d, s in zip(c.dims, shapes[i]))
    assert classes == plan_admission(shapes, iters, max_classes=3)
    w = fleet_pad_waste(shapes, iters, classes)
    assert 0.0 <= w < 1.0

    # Estimation-flag groups can NEVER share a class: a frozen-A tenant
    # next to an estimated-A one needs max_classes >= the group count.
    keys = [(True, True, True), (True, True, True),
            (False, True, True), (False, True, True)]
    cs = plan_admission(shapes, iters, keys, max_classes=2)
    for c in cs:
        assert len({keys[i] for i in c.members}) == 1
    with pytest.raises(ValueError, match="max_classes"):
        plan_admission(shapes, iters, keys, max_classes=1)


def test_plan_capacity_classes_is_one_dispatch_per_tick():
    shapes = [(50, 10, 2)] * 3 + [(90, 20, 2)] * 2
    plan = plan_capacity_classes(shapes, [5] * 5, max_classes=2)
    assert 1 <= len(plan.buckets) <= 2
    assert sorted(j for b in plan.buckets for j in b.jobs) == list(range(5))
    assert plan == plan_capacity_classes(shapes, [5] * 5, max_classes=2)


def test_advise_fleet_deterministic(tmp_path):
    shapes = [(10, 60, 2)] * 3 + [(20, 90, 2)] * 2
    a = advise_fleet(shapes, tick_iters=5, runs=str(tmp_path))
    b = advise_fleet(shapes, tick_iters=5, runs=str(tmp_path))
    assert a == b
    assert a["layouts"][0]["rank"] == 1
    assert [l["rank"] for l in a["layouts"]] == \
        list(range(1, len(a["layouts"]) + 1))
    for l in a["layouts"]:
        names = sorted(t for c in l["classes"] for t in c["tenants"])
        assert names == list(range(5))
        assert l["predicted_tick_wall_s"] > 0
        # Engine-annotated layouts: every class carries the evidence-
        # gated engine choice — "info" on an uncalibrated registry.
        assert all(c["filter"] == "info" for c in l["classes"])
    assert a["calibrated"] is False      # empty registry -> priors only


def test_advise_fleet_cli(capsys, tmp_path):
    from dfm_tpu.obs.advise import main
    assert main(["--fleet", "10,60,2x2;20,90,2", "--runs",
                 str(tmp_path)]) == 0
    out = capsys.readouterr()
    assert "advise fleet of 3 tenants" in out.out
    assert "PRIORS ONLY" in out.out
    assert "no profile records in the registry" in out.err
    assert main(["--fleet", "bogus"]) == 2


# ------------------------------------------------------ host guards --

def test_open_fleet_validation(trio):
    res, Y0, _ = trio[0]
    with pytest.raises(ValueError, match="at least one"):
        open_fleet([], [])
    with pytest.raises(ValueError, match="panels"):
        open_fleet([res], [])
    with pytest.raises(TypeError, match="FitResult"):
        open_fleet(["nope"], [Y0])
    with pytest.raises(ValueError, match="UNIQUE"):
        open_fleet([res, res], [Y0, Y0], tenants=["a", "a"])
    with pytest.raises(ValueError, match="fused device programs"):
        open_fleet([res], [Y0], backend="cpu")
    with pytest.raises(ValueError, match="capacity"):
        open_fleet([res], [Y0], capacity=10)
    with pytest.raises(ValueError, match="N=10"):
        open_fleet([res], [Y0[:, :4]])
    with pytest.raises(ValueError, match="one value per"):
        open_fleet([res], [Y0], max_iters=[3, 4])


def test_submit_validation_touches_nothing(trio):
    # t0 capped at 43; bucket dims match the parity test's executable.
    fl = _open(trio, capacity=[43, 56, 56])
    res, Y0, stream = trio[0]
    with pytest.raises(KeyError, match="unknown tenant"):
        fl.submit("nope", stream[:1])
    with pytest.raises(ValueError, match="max_update_rows"):
        fl.submit("t0", stream[:4])
    with pytest.raises(ValueError, match="rows must be"):
        fl.submit("t0", np.zeros((1, 3)))
    assert fl.submit("t0", stream[:2]) == 1       # 40 -> 42 queued
    with pytest.raises(ValueError, match="capacity overflow"):
        fl.submit("t0", stream[2:4])              # projected 44 > 43
    assert fl.pending == 1
    out = fl.drain()
    assert out["t0"][0].t == 42 and fl.pending == 0
    assert "SessionFleet" in repr(fl)
    fl.close()
    assert "closed" in repr(fl)


# ------------------------------------------------------ obs plumbing --

def test_fleet_metrics_registered_in_store():
    from dfm_tpu.obs import store
    for k in ("fleet_qps", "fleet_p99_ms", "fleet_pad_waste_frac"):
        assert k in store._BENCH_NUMERIC_KEYS
    assert not store.lower_is_better("fleet_qps")
    assert store.lower_is_better("fleet_p99_ms")
    assert store.lower_is_better("fleet_pad_waste_frac")
    assert store.noise_floor("fleet_p99_ms") == 2.0
    assert store.noise_floor("fleet_pad_waste_frac") == 0.02

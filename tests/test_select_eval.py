"""Factor selection, targeted predictors, OOS eval, checkpoint/resume,
observability (SURVEY.md R7-R9 + section 5 subsystems)."""

import json
import os

import numpy as np
import pytest

from dfm_tpu.api import DynamicFactorModel, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.evaluate import oos_evaluate
from dfm_tpu.estim.select import (bai_ng_ic, lasso_path, select_n_factors,
                                  targeted_predictors)
from dfm_tpu.utils import dgp
from dfm_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from dfm_tpu.utils.obs import JsonlLogger


def test_bai_ng_recovers_true_k():
    rng = np.random.default_rng(71)
    for k_true in (2, 4):
        p = dgp.dfm_params(80, k_true, rng, noise_scale=0.3)
        Y, _ = dgp.simulate(p, 250, rng)
        Y = (Y - Y.mean(0)) / Y.std(0)
        res = bai_ng_ic(Y, k_max=10)
        assert res.k_icp2 == k_true, (k_true, res.k_icp2)
        assert select_n_factors(Y, 10, "icp2") == k_true
        # V(k) must be decreasing in k
        assert np.all(np.diff(res.V) <= 1e-12)


def test_lasso_soft_thresholds_orthogonal_design():
    rng = np.random.default_rng(72)
    T, N = 400, 5
    X = rng.standard_normal((T, N))
    X, _ = np.linalg.qr(X)          # orthonormal columns
    X *= np.sqrt(T)                 # standardize scale: X'X/T = I
    beta = np.array([3.0, -2.0, 0.5, 0.0, 0.0])
    y = X @ beta
    lam = 1.0
    b = lasso_path(X, y, lam)
    expect = np.sign(beta) * np.maximum(np.abs(beta) - lam, 0.0)
    np.testing.assert_allclose(b, expect, atol=1e-6)


def test_targeted_predictors_finds_relevant_series():
    rng = np.random.default_rng(73)
    T, N = 300, 40
    X = rng.standard_normal((T, N))
    # target_{t+1} depends on series 3 and 17 only
    target = np.zeros(T)
    target[1:] = 2.0 * X[:-1, 3] - 1.5 * X[:-1, 17]
    target += 0.1 * rng.standard_normal(T)
    idx = targeted_predictors(X, target, horizon=1, n_keep=5)
    assert 3 in idx and 17 in idx


def test_oos_evaluate_beats_naive_on_persistent_factors():
    rng = np.random.default_rng(74)
    p = dgp.dfm_params(20, 2, rng, noise_scale=0.3, spectral_radius=0.9)
    Y, _ = dgp.simulate(p, 260, rng)
    model = DynamicFactorModel(n_factors=2)
    res = oos_evaluate(model, Y, horizon=1, n_windows=8, max_iters=10)
    assert res.errors.shape[1] == 20
    assert np.all(np.isfinite(res.rmse))
    # Factor forecasts should beat the unconditional-mean benchmark on
    # average for a persistent, low-noise DGP.
    assert res.rmse.mean() < res.rmse_mean.mean(), \
        (res.rmse.mean(), res.rmse_mean.mean())


def test_checkpoint_roundtrip(tmp_path):
    rng = np.random.default_rng(75)
    p = dgp.dfm_params(10, 2, rng)
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, p, 7, [1.0, 2.0])
    loaded = load_checkpoint(path)
    assert loaded is not None
    q, it, lls, converged = loaded
    assert it == 7 and converged is False
    np.testing.assert_allclose(q.Lam, p.Lam)
    np.testing.assert_allclose(lls, [1.0, 2.0])
    assert load_checkpoint(str(tmp_path / "missing.npz")) is None


def test_fit_checkpoint_resume(tmp_path):
    rng = np.random.default_rng(76)
    p = dgp.dfm_params(15, 2, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    model = DynamicFactorModel(n_factors=2)
    path = str(tmp_path / "em.npz")
    r1 = fit(model, Y, backend="cpu", max_iters=5, tol=0.0,
             checkpoint_path=path)
    assert os.path.exists(path)
    # Resuming with a larger budget warm-starts from the checkpoint: the
    # first loglik of the resumed run must be >= the last loglik of the
    # first run (EM monotone), and only the remaining iterations run.
    r2 = fit(model, Y, backend="cpu", max_iters=8, tol=0.0,
             checkpoint_path=path)
    assert r2.logliks[0] >= r1.logliks[-1] - 1e-8
    assert r2.n_iters == 3


def test_checkpoint_fingerprint_rejects_foreign_data(tmp_path):
    """A checkpoint from different data with the same (N, k) must not be
    used as a warm start (ADVICE r1 item 2)."""
    rng = np.random.default_rng(78)
    p = dgp.dfm_params(15, 2, rng)
    Ya, _ = dgp.simulate(p, 80, rng)
    Yb, _ = dgp.simulate(p, 80, rng)      # same shape, different panel
    model = DynamicFactorModel(n_factors=2)
    path = str(tmp_path / "em.npz")
    fit(model, Ya, backend="cpu", max_iters=5, tol=0.0,
        checkpoint_path=path)
    fresh = fit(model, Yb, backend="cpu", max_iters=3, tol=0.0)
    resumed = fit(model, Yb, backend="cpu", max_iters=3, tol=0.0,
                  checkpoint_path=path)
    # Fingerprint mismatch -> cold start: identical first loglik to the
    # checkpoint-free run (same PCA init), and the full iteration budget.
    assert resumed.logliks[0] == fresh.logliks[0]
    assert resumed.n_iters == 3


def test_checkpoint_resume_iteration_budget(tmp_path):
    """Resume subtracts completed iterations instead of re-running the full
    max_iters (ADVICE r1 item 2), including through the fused-chunk TPU
    driver whose checkpoints are labeled with the params' true iteration
    (ADVICE r1 item 3)."""
    from dfm_tpu.api import TPUBackend
    rng = np.random.default_rng(79)
    p = dgp.dfm_params(15, 2, rng)
    Y, _ = dgp.simulate(p, 80, rng)
    model = DynamicFactorModel(n_factors=2)
    path = str(tmp_path / "em.npz")
    fit(model, Y, backend=TPUBackend(fused_chunk=4), max_iters=5, tol=0.0,
        checkpoint_path=path)
    ck = load_checkpoint(path)
    assert ck is not None and ck[1] == 5      # 5 completed iterations
    r2 = fit(model, Y, backend=TPUBackend(fused_chunk=4), max_iters=7,
             tol=0.0, checkpoint_path=path)
    assert r2.n_iters == 2                    # 7 - 5 remaining


def test_checkpoint_rerun_does_not_exceed_budget(tmp_path):
    """Re-running an already-complete fit returns the checkpointed state
    instead of creeping one extra iteration per invocation."""
    rng = np.random.default_rng(82)
    p = dgp.dfm_params(12, 2, rng)
    Y, _ = dgp.simulate(p, 60, rng)
    model = DynamicFactorModel(n_factors=2)
    path = str(tmp_path / "em.npz")
    r1 = fit(model, Y, backend="cpu", max_iters=4, tol=0.0,
             checkpoint_path=path)
    it1 = load_checkpoint(path)[1]
    r2 = fit(model, Y, backend="cpu", max_iters=4, tol=0.0,
             checkpoint_path=path)
    assert load_checkpoint(path)[1] == it1 == 4
    assert r2.loglik == r1.loglik


def test_run_em_loop_reports_divergence():
    from dfm_tpu.estim.em import run_em_loop
    seq = [0.0, 1.0, 0.5]                     # real drop at iter 2

    def step(it):
        return seq[it], None

    lls, converged, state = run_em_loop(step, 10, tol=0.0,
                                        noise_floor=1e-6)
    assert state == "diverged" and not converged and len(lls) == 3


def test_jsonl_logger(tmp_path):
    rng = np.random.default_rng(77)
    p = dgp.dfm_params(12, 2, rng)
    Y, _ = dgp.simulate(p, 60, rng)
    path = str(tmp_path / "log.jsonl")
    logger = JsonlLogger(path, extra={"run": "t"})
    fit(DynamicFactorModel(n_factors=2), Y, backend="cpu", max_iters=4,
        tol=0.0, callback=logger)
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 4
    assert recs[1]["dloglik"] >= 0.0     # EM monotone
    assert recs[0]["run"] == "t"

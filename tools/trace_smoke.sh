#!/usr/bin/env bash
# One-command request-tracing smoke (ISSUE 19).  Leg 1 serves socket
# queries through a traced daemon and asserts EVERY answered request has
# a latency waterfall whose stages sum to the measured e2e within 1 ms,
# then checks `obs.report` prints the per-stage p99 attribution table.
# Leg 2 forces an SLO breach and follows the tail: the p99 line of
# `render_prom()` must carry an OpenMetrics exemplar trace_id, the
# breach must flight-dump, and the dump must resolve that trace_id back
# to a full request event.  Leg 3 runs traced-vs-untraced twin sessions:
# answers bit-identical, span-plumbing overhead (best-of-N warm walls)
# under the gate.  The quick way to answer "can I follow one slow
# request through the whole stack" without the real chip.
#
# Usage (from the repo root):
#   tools/trace_smoke.sh [workdir]           # default: a fresh mktemp -d
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-$(mktemp -d /tmp/dfm_trace_smoke.XXXXXX)}"
export DFM_SMOKE_WORK="$WORK"
mkdir -p "$WORK"

set +e
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" JAX_ENABLE_X64=1 \
DFM_RUNS= DFM_FLIGHT_DIR="$WORK/flight" DFM_FLIGHT_MIN_INTERVAL_S=0 \
python - <<'PY'
import json
import os
import subprocess
import sys
import threading
import time

import jax
jax.config.update("jax_enable_x64", True)
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.daemon import DaemonClient, DFMDaemon, make_listener
from dfm_tpu.obs.live import plane, reset_plane, set_slo
from dfm_tpu.obs.report import summarize
from dfm_tpu.obs.slo import SLOConfig
from dfm_tpu.obs.trace import Tracer, activate, set_ambient
from dfm_tpu.utils import dgp

WORK = os.environ["DFM_SMOKE_WORK"]
SNAP = os.path.join(WORK, "snap")
JOURNAL = os.path.join(WORK, "journal.jsonl")
ADDR = os.path.join(WORK, "daemon.sock")
TRACE = os.path.join(WORK, "trace.jsonl")
R = 2                                    # rows per query

# -- bootstrap: one tiny tenant, fitted + snapshotted -------------------
rng = np.random.default_rng(190)
p_true = dgp.dfm_params(8, 2, rng)
Y, _ = dgp.simulate(p_true, 36 + 60 * R, rng)
res = fit(DynamicFactorModel(n_factors=2), Y[:36], max_iters=6,
          telemetry=False)
Y0, stream = Y[:36], Y[36:]
boot = open_fleet([res], [Y0], tenants=["t0"],
                  capacity=[36 + 62 * R], max_update_rows=R,
                  max_iters=4, tol=0.0)
boot.snapshot_all(SNAP)
boot.close()
cursor = [0]


def next_rows():
    rows = stream[cursor[0]:cursor[0] + R]
    cursor[0] += R
    return rows

# -- leg 1: traced daemon -> every answered request has a waterfall -----
tracer = Tracer(TRACE)
prev_amb = set_ambient(tracer)           # the daemon pump is another
daemon = DFMDaemon.recover(SNAP, JOURNAL)  # thread: ambient, not a ctx
listener = make_listener(ADDR)
th = threading.Thread(target=daemon.serve_forever, args=(listener,),
                      daemon=True)
th.start()
cli = DaemonClient(ADDR, timeout=300.0)
acked = {}
for q in range(6):
    t0 = time.perf_counter()
    resp = cli.submit("t0", next_rows(), req_id=f"l1-{q}", wait=True)
    wall = time.perf_counter() - t0
    assert resp.get("ok"), resp
    tid = resp.get("trace_id", "")
    assert tid, f"answered request q{q} carries no trace_id: {resp}"
    acked[tid] = wall
# One duplicate: answered from cache with its own (dedup) waterfall.
dup = cli.submit("t0", stream[:R], req_id="l1-0", wait=True)
assert dup.get("duplicate") is True and dup.get("trace_id"), dup
acked[dup["trace_id"]] = None
assert daemon.status()["dedup_hits"] == 1

reqs = {e["trace_id"]: e for e in tracer.events
        if e.get("kind") == "request"}
missing = set(acked) - set(reqs)
assert not missing, f"answered requests with no waterfall: {missing}"
worst = 0.0
for tid, wall in acked.items():
    ev = reqs[tid]
    resid = abs(sum(ev["stages"].values()) - ev["e2e"])
    worst = max(worst, resid)
    assert resid <= 1e-3, (tid, resid, ev)
    if wall is not None:                 # span e2e inside the client wall
        assert ev["e2e"] <= wall + 1e-3, (tid, ev["e2e"], wall)
assert reqs[dup["trace_id"]].get("dedup") is True
print(f"leg1: {len(acked)} answered requests, every waterfall sums to "
      f"e2e (worst residual {1e3 * worst:.4f} ms, budget 1 ms)",
      flush=True)

cli.shutdown()
th.join(timeout=60)
daemon.close()
set_ambient(prev_amb)
tracer.close()

rq = summarize(TRACE)["requests"]
assert rq["n_requests"] == len(acked) and rq["dedup"] == 1, rq
assert rq["waterfall_residual_max_s"] <= 1e-3, rq
for st in ("queue_wait", "dispatch", "d2h", "ack"):
    assert st in rq["per_stage"], (st, sorted(rq["per_stage"]))
out = subprocess.run(
    [sys.executable, "-m", "dfm_tpu.obs.report", TRACE],
    capture_output=True, text=True, check=True).stdout
assert "requests:" in out and "dispatch" in out and "share" in out, out
attn = [ln for ln in out.splitlines() if "stage" in ln and "p99" in ln]
assert attn, f"no per-stage p99 attribution table in report:\n{out}"
print("leg1 PASS: obs.report prints the per-stage p99 attribution "
      "table", flush=True)

# -- leg 2: forced SLO breach -> exemplar + flight dump -> trace --------
reset_plane()
set_slo(SLOConfig(p99_ms=1e-6, min_events=3, window=3600.0))
sess = open_session(res, Y0, max_update_rows=R, max_iters=3, tol=0.0,
                    capacity=Y0.shape[0] + 10 * R)
tr2 = Tracer()
with activate(tr2):
    for q in range(5):                   # every query violates the SLO
        sess.update(next_rows())
sess.close()
set_slo(None)
assert plane().flight_dumps >= 1, "SLO breach never flight-dumped"
prom = plane().registry.render_prom()
ex_lines = [ln for ln in prom.splitlines()
            if "dfm_request_e2e_ms{" in ln and 'quantile="0.99"' in ln
            and "trace_id=" in ln]
assert ex_lines, f"no OpenMetrics exemplar on the e2e p99:\n{prom}"
ex_tid = ex_lines[0].split('trace_id="')[1].split('"')[0]
dumps = sorted(os.path.join(WORK, "flight", f)
               for f in os.listdir(os.path.join(WORK, "flight")))
hit = None
for path in dumps:
    with open(path) as f:
        for line in f:
            ev = json.loads(line)
            if ev.get("kind") == "request" and ev.get("trace_id") == ex_tid:
                hit = ev
assert hit is not None, (f"exemplar {ex_tid} not resolvable in flight "
                         f"dumps {dumps}")
assert abs(sum(hit["stages"].values()) - hit["e2e"]) <= 1e-3
burn = [e for e in tr2.events if e.get("kind") == "health"
        and e.get("event") == "slo_burn" and e.get("action") == "fired"]
assert burn and burn[0].get("trace_id"), burn
print(f"leg2 PASS: breach -> prom exemplar {ex_tid} -> flight dump "
      f"resolves to the full waterfall", flush=True)

# -- leg 3: traced vs untraced twins: bit-identical + overhead gate -----
N_WARM, N_MEAS = 2, 6
lo = cursor[0]


def run(traced):
    walls, upds = [], []
    ctx = activate(Tracer() if traced else None)
    with ctx:
        s = open_session(res, Y0, max_update_rows=R, max_iters=3, tol=0.0,
                         capacity=Y0.shape[0] + (lo + (N_WARM + N_MEAS + 1)
                                                 * R))
        for i in range(N_WARM + N_MEAS):
            rows = stream[lo + i * R:lo + (i + 1) * R]
            t0 = time.perf_counter()
            u = s.update(rows)
            if i >= N_WARM:
                walls.append(time.perf_counter() - t0)
            upds.append(u)
        s.close()
    return walls, upds


tw, tu = run(traced=True)
uw, uu = run(traced=False)
for a, b in zip(tu, uu):
    assert np.array_equal(a.nowcast, b.nowcast)
    assert np.array_equal(a.forecasts["y"], b.forecasts["y"])
overhead = 100.0 * (min(tw) - min(uw)) / min(uw)
gate = float(os.environ.get("DFM_SMOKE_TRACE_OVERHEAD_MAX", "30"))
assert overhead <= gate, (f"tracing overhead {overhead:+.1f}% over the "
                          f"{gate:.0f}% smoke gate")
print(f"leg3 PASS: traced == untraced bit-exact; overhead "
      f"{overhead:+.1f}% (best-of-{N_MEAS}, gate {gate:.0f}%)",
      flush=True)
print("TRACE SMOKE PASS", flush=True)
PY
rc=$?
set -e
if [ "$rc" -ne 0 ]; then
    echo "--- trace smoke workdir kept: $WORK ---" >&2
    exit "$rc"
fi
rm -rf "$WORK"
exit $rc

#!/usr/bin/env bash
# One-command fleet-serving check, two legs:
#
#   1. Throughput + budget: run the smoke-size bench.fleet Poisson load
#      and assert the ISSUE 11 acceptance bar from its ONE JSON line —
#      >= 3x aggregate queries/sec vs the loop-over-lone-sessions
#      baseline, 0 serve_update recompiles after warmup (one executable
#      per bucket serves every active set / row count), and <= 1
#      blocking d2h transfer per tick.
#   2. Chaos: inject a deterministic divergence into ONE tenant's lane
#      (the FleetOptions fault seam), assert it is quarantined to a lone
#      guarded session while its bucket-mates stay BIT-IDENTICAL to a
#      fault-free twin fleet, and that the evicted tenant's next query
#      still answers (healed on the lone session).
#   3. Engine: a lowrank-routed fleet bucket (filter="lowrank", rank=r
#      with r < k, the genuinely approximate regime) compiles ONE rank-r
#      serve_update executable (0 recompiles after warmup, <= 1 blocking
#      d2h per tick) and answers every tenant like its lone same-engine
#      session — which tests/test_serve.py pins to a lone same-engine
#      fused fit, so the cold-fit anchor is transitive.  One warm EM
#      iteration per query keeps the approximate E-step out of the
#      divergence guard's rollback path (rollback choice is threshold-
#      sensitive and not a cross-path parity contract).
#
# Usage (from the repo root):
#   tools/fleet_smoke.sh
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time;
# DFM_RUNS is cleared so the smoke run never pollutes the registry.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "--- fleet smoke: bench.fleet Poisson load ---"
OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" DFM_RUNS= \
      DFM_BENCH_FLEET_MIX="${DFM_BENCH_FLEET_MIX:-12,40,2x4;16,48,2x4}" \
      DFM_BENCH_ROUNDS="${DFM_BENCH_ROUNDS:-5}" \
      DFM_BENCH_SERVE_ITERS="${DFM_BENCH_SERVE_ITERS:-3}" \
      DFM_BENCH_ITERS="${DFM_BENCH_ITERS:-20}" \
      DFM_BENCH_FLEET_WIDEK_MIX="${DFM_BENCH_FLEET_WIDEK_MIX:-60,80,50x1}" \
      DFM_BENCH_WIDEK_ROUNDS="${DFM_BENCH_WIDEK_ROUNDS:-1}" \
      python -m bench.fleet)
echo "$OUT"

printf '%s' "$OUT" | python -c '
import json, sys
d = json.loads(sys.stdin.readline())
sp = d["speedup_vs_lone_sessions"]
rc = d["recompiles_after_warmup"]
bt = d["fleet_blocking_transfers_per_tick"]
qpd = d["queries_per_dispatch"]
assert d["n_tenants"] >= 8, \
    f"fleet smoke FAILED: needs B>=8 tenants, got {d['n_tenants']}"
assert sp >= 3.0, \
    f"fleet smoke FAILED: {sp}x vs lone sessions (bar: >= 3x)"
assert rc == 0, \
    f"fleet smoke FAILED: {rc} serve_update recompiles after warmup"
assert bt <= 1.0, \
    f"fleet smoke FAILED: {bt} blocking transfers per tick (bar: <= 1)"
print(f"fleet smoke OK: {sp}x vs lone sessions, "
      f"{qpd} queries/dispatch, {bt} blocking "
      f"transfer(s)/tick, 0 recompiles after warmup")'

echo "--- fleet smoke: quarantine chaos leg ---"
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" DFM_RUNS= python - <<'PY'
import dataclasses
import warnings

import numpy as np

import jax
jax.config.update("jax_enable_x64", True)   # bit-identical twin asserts

from dfm_tpu import DynamicFactorModel, TPUBackend, fit, open_fleet
from dfm_tpu.robust import RobustPolicy
from dfm_tpu.utils import dgp

be = TPUBackend(filter="info")
ress, Ys, streams = [], [], []
for i in range(4):
    rg = np.random.default_rng(400 + i)
    Yi, _ = dgp.simulate(dgp.dfm_params(12, 2, rg), 46, rg)
    ress.append(fit(DynamicFactorModel(n_factors=2), Yi[:40],
                    max_iters=15, backend=be, telemetry=False))
    Ys.append(Yi[:40])
    streams.append(Yi[40:])

kw = dict(capacity=52, max_update_rows=2, max_iters=4, tol=0.0,
          backend=be, max_classes=1)


def run(fleet, n_ticks):
    outs = []
    for t in range(n_ticks):
        for i, name in enumerate(fleet.tenants):
            fleet.submit(name, streams[i][2 * t:2 * t + 2])
        outs.append(fleet.drain())
    return outs


clean = run(open_fleet(ress, Ys, **kw), 2)

fleet = open_fleet(ress, Ys, robust=RobustPolicy(chunk_retries=0),
                   **kw)
bk = fleet._buckets[0]
bk.opts = dataclasses.replace(bk.opts, fault_tenant=1, fault_iter=1)
with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    faulted = run(fleet, 2)
bk.opts = dataclasses.replace(bk.opts, fault_tenant=None)

assert fleet.quarantined() == ["t1"], \
    f"fleet chaos FAILED: expected ['t1'] quarantined, " \
    f"got {fleet.quarantined()}"
for t in range(2):
    for name in ("t0", "t2", "t3"):
        a = faulted[t][name][0]
        c = clean[t][name][0]
        assert np.array_equal(a.nowcast, c.nowcast) \
            and np.array_equal(a.forecasts["y"], c.forecasts["y"]), \
            f"fleet chaos FAILED: bucket-mate {name} perturbed at tick {t}"
print("chaos: t1 quarantined; 3 bucket-mates BIT-IDENTICAL to the "
      "fault-free twin across 2 ticks")

# The evicted tenant's next query answers on its lone guarded session.
fleet.submit("t1", streams[1][4:6])
upd = fleet.drain()["t1"][0]
assert np.isfinite(upd.nowcast).all() and not upd.diverged, \
    "fleet chaos FAILED: evicted tenant's query did not heal"
fleet.close()
print(f"chaos: post-quarantine t1 query healed on its lone session "
      f"(t={upd.t})")
PY

echo "--- fleet smoke: lowrank engine leg ---"
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" DFM_RUNS= python - <<'PY'
import tempfile
import warnings

import numpy as np

import jax
jax.config.update("jax_enable_x64", True)   # tight engine-parity asserts

from dfm_tpu import (DynamicFactorModel, TPUBackend, fit, open_fleet,
                     open_session)
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.report import summarize
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

# A fleet bucket routed through the rank-r downdate engine at r < k
# (the genuinely approximate regime): one lowrank serve_update
# executable serves every tick, the serving budgets hold, and each
# tenant's answer matches its LONE same-engine session query-for-query
# (the vmapped engine pair reassociates ~1 ulp/dot vs the lone pair —
# fp tolerance, not exactness).  ONE warm iteration per query: the
# approximate E-step is non-monotone, and multi-iteration tol=0.0
# serving can trip the divergence guard on a borderline dip — the
# rollback point is threshold-chosen, hence ulp-sensitive, and
# fleet-vs-lone parity through a rollback is deliberately NOT a
# contract.
K, RANK, ITERS, TICKS = 6, 2, 1, 3
be = TPUBackend(filter="lowrank", rank=RANK)
model = DynamicFactorModel(n_factors=K)
ress, Ys, streams = [], [], []
for i in range(3):
    rg = np.random.default_rng(170 + i)
    Yi, _ = dgp.simulate(dgp.dfm_params(20, K, rg), 46, rg)
    ress.append(fit(model, Yi[:40], max_iters=10, backend=be,
                    fused=True, telemetry=False))
    Ys.append(Yi[:40]); streams.append(Yi[40:])

kw = dict(capacity=52, max_update_rows=2, max_iters=ITERS, tol=0.0,
          backend=be)
trace = tempfile.mktemp(suffix=".jsonl")
tr = Tracer(path=trace, detector=RecompileDetector())
with activate(tr), warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    fl = open_fleet(ress, Ys, max_classes=1, filter="lowrank",
                    rank=RANK, **kw)
    assert all(c["filter"] == "lowrank" and c["rank"] == RANK
               for c in fl.classes), fl.classes
    outs = []
    for t in range(TICKS):
        for i, name in enumerate(fl.tenants):
            fl.submit(name, streams[i][2 * t:2 * t + 2])
        outs.append(fl.drain())
    names = fl.tenants
    fl.close()
tr.close()

with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    lone = [open_session(ress[i], Ys[i], filter="lowrank", rank=RANK,
                         **kw) for i in range(3)]
    for t in range(TICKS):
        for i, name in enumerate(names):
            u = outs[t][name][0]
            ref = lone[i].update(streams[i][2 * t:2 * t + 2])
            assert u.n_iters == ref.n_iters
            np.testing.assert_allclose(u.nowcast, ref.nowcast,
                                       rtol=1e-9, atol=1e-10)
            np.testing.assert_allclose(u.forecasts["y"],
                                       ref.forecasts["y"],
                                       rtol=1e-9, atol=1e-10)
            assert u.nowcast_sd is not None and np.all(u.nowcast_sd > 0), \
                f"engine leg FAILED: {name} missing conservative bands"
    for s in lone:
        s.close()

s = summarize(tr.events)
q, fs = s["queries"], s["fleet"]
assert q["recompiles_after_warmup"] == 0, \
    f"engine leg FAILED: {q['recompiles_after_warmup']} recompiles"
assert s["blocking_transfers"] <= TICKS, \
    f"engine leg FAILED: {s['blocking_transfers']} d2h for {TICKS} ticks"
assert all(fs["per_tenant"][n]["engine"] == "lowrank" for n in names), \
    "engine leg FAILED: report did not stamp the lowrank engine"
print(f"engine leg: lowrank(rank={RANK}) fleet == lone same-engine "
      f"sessions across {TICKS} ticks x {len(names)} tenants; "
      f"{s['blocking_transfers']} d2h, 0 recompiles after warmup, "
      "bands present")
PY

echo "fleet smoke: all gates passed"

#!/usr/bin/env bash
# One-command fault-tolerance soak: drive deterministic injected faults
# through all three serving layers — the fused one-shot fit, a scheduler
# bucket, and a streaming session — and assert every layer heals to the
# CLEAN answer, on the record.  Covers (under one trace):
#
#   1. fused fit: injected dispatch failure -> retry -> EXACT parity
#      with the clean run, plus a hung-transfer recovery under the
#      watchdog deadline;
#   2. fit_jobs: retry exhaustion quarantines the bucket, every tenant
#      is requeued as a lone guarded fit matching its lone oracle; a
#      NaN-poisoned tenant is evicted ALONE under recover_divergence;
#   3. session: injected failure retries from last-good to the exact
#      clean nowcast; snapshot -> restore -> update matches the
#      uninterrupted session; a craterd chunk degrades (and repairs)
#      without killing the session.
#
# The trace gate then asserts the robustness section of the report:
# retries/quarantines/degraded queries all present, and the session
# budget holds (<= 1 blocking d2h per query, 0 recompiles after warmup).
#
# Usage (from the repo root):
#   tools/chaos_smoke.sh [trace_path]        # default /tmp/dfm_chaos.jsonl
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_chaos.jsonl}"
rm -f "$TRACE"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - "$TRACE" <<'PY'
import dataclasses
import os
import sys
import tempfile
import warnings

import numpy as np

import jax
jax.config.update("jax_enable_x64", True)   # the parity asserts are f64

from dfm_tpu import (DynamicFactorModel, Job, fit, fit_jobs, open_session)
from dfm_tpu.api import TPUBackend
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.robust import FaultInjector, RobustPolicy
from dfm_tpu.utils import dgp

MODEL = DynamicFactorModel(n_factors=2, standardize=False)
rng = np.random.default_rng(23)
Y, _ = dgp.simulate(dgp.dfm_params(14, 2, rng), 66, rng)
Y0, stream = Y[:56], Y[56:]


def pol(**kw):
    kw.setdefault("backoff_base", 1e-6)
    return RobustPolicy(**kw)


tr = Tracer(path=sys.argv[1], detector=RecompileDetector())
with activate(tr):
    # -- 1. fused fit: injected failure -> retry -> exact parity -------
    b = TPUBackend(fused_chunk=4)
    clean = fit(MODEL, Y0, backend=b, fused=True, max_iters=10, tol=0.0,
                robust=False)
    inj = FaultInjector().dispatch_failure(at=0)
    r = fit(MODEL, Y0, backend=TPUBackend(fused_chunk=4), fused=True,
            max_iters=10, tol=0.0, robust=pol(wrap_dispatch=inj.wrap_call))
    assert np.array_equal(r.logliks, clean.logliks), \
        "chaos FAILED: fused retry diverged from the clean trajectory"
    assert r.health.n_dispatch_retries == 1
    print("fused: 1 injected failure -> 1 retry -> exact parity")

    inj = FaultInjector().hung_transfer(at=0, seconds=30.0)
    r = fit(MODEL, Y0, backend=TPUBackend(fused_chunk=4), fused=True,
            max_iters=10, tol=0.0,
            robust=pol(wrap_dispatch=inj.wrap_call,
                       dispatch_deadline_s=5.0))
    assert np.array_equal(r.logliks, clean.logliks), \
        "chaos FAILED: watchdog recovery diverged from the clean run"
    assert any("watchdog" in e.detail for e in r.health.events)
    print("fused: hung transfer -> watchdog deadline -> retry -> parity")

    # -- 2. scheduler: quarantine + NaN blast radius -------------------
    def jobs3(seed, poison=None):
        js = []
        for i in range(3):
            rg = np.random.default_rng(seed + i)
            Yj, _ = dgp.simulate(dgp.dfm_params(10, 2, rg), 40, rg)
            js.append(Job(Y=Yj, model=DynamicFactorModel(n_factors=2),
                          tenant=f"t{i}", max_iters=8, tol=1e-6))
        if poison is not None:
            from dfm_tpu.backends import cpu_ref
            bad = cpu_ref.pca_init(
                np.asarray(js[poison].Y)
                / np.asarray(js[poison].Y).std(axis=0), 2)
            bad = dataclasses.replace(
                bad, Lam=np.full_like(bad.Lam, np.nan))
            js[poison] = dataclasses.replace(js[poison], init=bad,
                                             tenant="poisoned")
        return js

    def ref(job):
        return fit(job.model, job.Y,
                   backend=TPUBackend(dtype="float64", filter="info"),
                   max_iters=job.max_iters, tol=job.tol)

    js = jobs3(900)
    inj = FaultInjector().dispatch_failure(at=0)
    stats = {}
    res = fit_jobs(js, max_buckets=1, dtype="float64", stats=stats,
                   robust=pol(dispatch_retries=0,
                              wrap_dispatch=inj.wrap_call))
    assert stats["n_quarantined"] == 3, \
        f"chaos FAILED: expected 3 quarantined, got {stats}"
    for rr, job in zip(res, js):
        assert np.allclose(rr.fit.logliks, ref(job).logliks,
                           rtol=1e-9, atol=1e-7), \
            "chaos FAILED: requeued tenant diverged from its lone oracle"
        assert rr.fit.health.events[0].kind == "quarantine"
    print("sched: exhausted bucket -> 3 tenants quarantined -> requeued "
          "lone fits match their oracles")

    js = jobs3(910, poison=1)
    stats = {}
    res = fit_jobs(js, max_buckets=1, dtype="float64", stats=stats,
                   robust=pol(recover_divergence=True))
    assert stats["n_quarantined"] == 1
    assert np.isfinite(np.asarray(res[1].fit.logliks)).all(), \
        "chaos FAILED: poisoned tenant not repaired in its lone refit"
    for i in (0, 2):
        assert np.allclose(res[i].fit.logliks, ref(js[i]).logliks,
                           rtol=1e-9, atol=1e-7), \
            "chaos FAILED: NaN quarantine perturbed a bucket-mate"
    print("sched: NaN tenant evicted alone + repaired; mates untouched")

    # -- 3. session: retry parity, degrade/repair, snapshot/restore ----
    b = TPUBackend(fused_chunk=4)
    res0 = fit(MODEL, Y0, backend=b, fused=True, max_iters=10, tol=1e-6)
    kw = dict(capacity=80, max_update_rows=2, max_iters=8, tol=0.0)
    s_clean = open_session(res0, Y0, backend=b, robust=False, **kw)
    inj = FaultInjector().dispatch_failure(at=0)
    sess = open_session(res0, Y0, backend=b,
                        robust=pol(chunk_retries=0,
                                   wrap_dispatch=inj.wrap_call), **kw)
    u_c = s_clean.update(stream[:2])
    u_g = sess.update(stream[:2])
    assert np.array_equal(u_g.nowcast, u_c.nowcast), \
        "chaos FAILED: session retry diverged from the clean update"
    assert sess.health.n_dispatch_retries == 1
    print("session: injected failure -> retry from last-good -> exact "
          "clean nowcast")

    snap = os.path.join(tempfile.mkdtemp(), "sess.npz")
    sess.snapshot(snap)
    rest = open_session(snapshot=snap, backend=b)
    u_a = sess.update(stream[2:4])
    u_b = rest.update(stream[2:4])
    assert np.array_equal(u_b.nowcast, u_a.nowcast), \
        "chaos FAILED: restored session diverged from the uninterrupted one"
    print(f"session: snapshot -> restore -> update matches uninterrupted "
          f"(t={u_b.t})")

    # Crater chunk 1's logliks on device (the fused fault seam; a static
    # change, so it deliberately compiles one extra executable).
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        u_d = sess.update(stream[4:5])
    assert u_d.diverged, "chaos FAILED: cratered chunk not flagged"
    sess._opts = dataclasses.replace(sess._opts, fault_chunk=None)
    u_ok = sess.update(stream[5:6])
    assert not u_ok.diverged and np.isfinite(u_ok.nowcast).all(), \
        "chaos FAILED: session did not survive the divergence"
    print("session: cratered chunk -> degraded query -> session survives")
tr.close()
PY

echo "--- chaos smoke gate ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"
python -m dfm_tpu.obs.report "$TRACE" --json | python -c '
import json, sys
s = json.load(sys.stdin)
rb = s.get("robustness") or {}
q = s.get("queries") or {}
assert rb.get("dispatch_retries", 0) >= 3, \
    f"chaos smoke FAILED: retries not aggregated ({rb})"
assert rb.get("quarantines", 0) == 4, \
    f"chaos smoke FAILED: expected 4 quarantines, got {rb}"
assert rb.get("degraded_queries", 0) >= 1, \
    f"chaos smoke FAILED: degraded query not aggregated ({rb})"
assert rb.get("per_tenant") and rb.get("per_session"), \
    f"chaos smoke FAILED: per-tenant/session attribution missing ({rb})"
n = q.get("n_queries", 0)
# The healthy serve path compiles once; the ONE extra executable is the
# deliberate fault-seam toggle (a static change), nothing else.
assert q.get("recompiles_after_warmup", 99) <= 1, \
    f"chaos smoke FAILED: serve recompiles after warmup ({q})"
print("chaos smoke OK: %d retries, %d quarantines, %d degraded, "
      "%d session queries" % (rb["dispatch_retries"], rb["quarantines"],
                              rb["degraded_queries"], n))'

#!/usr/bin/env bash
# One-command dispatch-free-fit check: run a traced warm fused refit
# (fit(fused=True) then fit(warm_start=..., fused=True) on the same
# backend) and assert the warm fit stayed within the ISSUE 6 budget of
# <= 2 blocking transfers, read back from the trace via the report CLI.
# The quick way to answer "is the fused path still one program end to
# end" without touching the real chip.
#
# Usage (from the repo root):
#   tools/fused_smoke.sh [trace_path]        # default /tmp/dfm_fused.jsonl
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time;
# export JAX_PLATFORMS= (empty) to smoke the default backend instead.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_fused.jsonl}"
rm -f "$TRACE"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - "$TRACE" <<'PY'
import sys

import numpy as np

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

rng = np.random.default_rng(0)
p_true = dgp.dfm_params(30, 2, rng)
Y, _ = dgp.simulate(p_true, 80, rng)

model = DynamicFactorModel(n_factors=2)
b = TPUBackend(filter="info")
cold = fit(model, Y, backend=b, max_iters=24, tol=1e-6, fused=True)
print(f"cold fused fit: {cold.n_iters} iters, "
      f"converged={bool(cold.converged)}, "
      f"loglik={float(cold.logliks[-1]):.4f}")

# Trace ONLY the warm refit: same backend + same panel object means the
# device buffers are reused and the whole fit is one barrier'd program.
tr = Tracer(path=sys.argv[1], detector=RecompileDetector())
with activate(tr):
    warm = fit(model, Y, backend=b, max_iters=24, tol=1e-6, fused=True,
               warm_start=cold)
    warm.factors  # consume the in-program smooth (cache read)
tr.close()
print(f"warm fused refit: {warm.n_iters} iters, "
      f"nowcast[:3]={np.round(warm.nowcast[:3], 3).tolist()}")
PY

echo "--- fused smoke gate ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"
python -m dfm_tpu.obs.report "$TRACE" --json | python -c '
import json, sys
s = json.load(sys.stdin)
bt = s.get("blocking_transfers", 99)
fi = s.get("fused_iterations", 0)
assert bt <= 2, f"fused smoke FAILED: {bt} blocking transfers (budget 2)"
assert fi > 0, "fused smoke FAILED: no fused dispatch span in the trace"
print(f"fused smoke OK: {bt} blocking transfer(s), "
      f"{fi} fused iteration(s) in one program")'

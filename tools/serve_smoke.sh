#!/usr/bin/env bash
# One-command streaming-session check: open a NowcastSession from a cold
# fused fit, push 3 ragged updates through it under a recompile-detecting
# tracer, and assert the ISSUE 9 warm-query budget from the trace via the
# report CLI: exactly ONE serve_update executable (zero recompiles after
# warmup) and <= 1 blocking d2h transfer per query.  The quick way to
# answer "is a warm update still one program" without the real chip.
#
# Usage (from the repo root):
#   tools/serve_smoke.sh [trace_path]        # default /tmp/dfm_serve.jsonl
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time;
# export JAX_PLATFORMS= (empty) to smoke the default backend instead.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_serve.jsonl}"
rm -f "$TRACE"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - "$TRACE" <<'PY'
import sys

import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_session
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

rng = np.random.default_rng(0)
p_true = dgp.dfm_params(30, 2, rng)
Y, _ = dgp.simulate(p_true, 86, rng)
Y0, stream = Y[:80], Y[80:]

model = DynamicFactorModel(n_factors=2)
res = fit(model, Y0, max_iters=24, tol=1e-6, fused=True)
print(f"cold fused fit: {res.n_iters} iters, "
      f"converged={bool(res.converged)}")

# Trace the session lifecycle: update 1 compiles the one serve_update
# executable; updates 2-3 (different row counts -> ragged padding, same
# padded shape) must reuse it with one d2h barrier each.
tr = Tracer(path=sys.argv[1], detector=RecompileDetector())
with activate(tr):
    sess = open_session(res, Y0, capacity=120, max_update_rows=3,
                        max_iters=5, tol=0.0)
    for rows in (stream[:2], stream[2:5], stream[5:6]):
        u = sess.update(rows)
        print(f"update -> t={u.t}, nowcast[:3]="
              f"{np.round(u.nowcast[:3], 3).tolist()}")
tr.close()
PY

echo "--- serve smoke gate ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"
python -m dfm_tpu.obs.report "$TRACE" --json | python -c '
import json, sys
s = json.load(sys.stdin)
p = s.get("programs", {}).get("serve_update", {})
q = s.get("queries") or {}
n = q.get("n_queries", 0)
bt = s.get("blocking_transfers", 99)
rc = q.get("recompiles_after_warmup", 99)
d = p.get("dispatches")
assert n == 3, f"serve smoke FAILED: expected 3 query events, got {n}"
assert d == 3, f"serve smoke FAILED: serve_update dispatches {d}"
assert rc == 0, f"serve smoke FAILED: {rc} recompiles after warmup"
assert bt <= n, f"serve smoke FAILED: {bt} blocking transfers for {n} queries"
print(f"serve smoke OK: {n} queries, {bt} blocking transfer(s) "
      f"(<= 1/query), 0 recompiles after warmup")'

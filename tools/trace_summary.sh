#!/usr/bin/env bash
# One-command observability check: run a smoke-size traced fit under
# DFM_TRACE and summarize the trace with the report CLI.  The quick way to
# answer "how many programs did a fit dispatch, did anything recompile,
# and what did the convergence curve do" without touching the real chip.
#
# Usage (from the repo root):
#   tools/trace_summary.sh [trace_path]          # default /tmp/dfm_trace.jsonl
#   DFM_TRACE_COST=1 tools/trace_summary.sh      # add static flops/bytes
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time; export
# JAX_PLATFORMS= (empty) to trace the default backend instead.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_trace.jsonl}"
rm -f "$TRACE"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" DFM_TRACE="$TRACE" python - <<'PY'
import numpy as np
from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.backends import cpu_ref
from dfm_tpu.utils import dgp

rng = np.random.default_rng(0)
p_true = dgp.dfm_params(30, 2, rng)
Y, _ = dgp.simulate(p_true, 80, rng)
Y = (Y - Y.mean(0)) / Y.std(0)
r = fit(DynamicFactorModel(n_factors=2), Y,
        backend=TPUBackend(filter="info"), max_iters=24, tol=1e-6)
print(f"smoke fit: {r.n_iters} iters, converged={bool(r.converged)}, "
      f"loglik={float(r.logliks[-1]):.4f}")
PY

echo "--- trace summary ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"

#!/usr/bin/env bash
# One-command parallel-in-time check: QR-factor filter/smoother parity
# vs the sequential scan (single-device AND time-sharded across the fake
# 8-device mesh) -> a smoke-size bench.longt sweep (pit_qr must not lose
# to the sequential scan at the longest smoke T) -> a seeded-registry
# advisor selection (fit(auto=True) applies the pit_qr plan and matches
# the explicit filter= knob bit for bit).  The quick answer to "does
# parallel-in-time still win at long T, and does the advisor know".
#
# Usage (from the repo root):
#   tools/pit_smoke.sh
#
# JAX_PLATFORMS defaults to cpu; the mesh legs force the 8-device fake
# host platform in a fresh process (env var BEFORE jax import).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "--- pit_qr parity (single-device + time-sharded, fake 8-dev mesh) ---" >&2
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PY'
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

from dfm_tpu.parallel import pit_qr_time_sharded
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.parallel_filter import pit_qr_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp

rng = np.random.default_rng(13)
p = dgp.dfm_params(33, 3, rng)
for T in (96, 97):                     # divisible / non-divisible by 8
    Y, _ = dgp.simulate(p, T, rng)
    pj = JP.from_numpy(p, jnp.float64)
    Yj = jnp.asarray(Y)
    mask = jnp.asarray(dgp.random_mask(*Y.shape, rng, 0.3))
    kf_s = info_filter(Yj, pj, mask=mask)
    kf_q, sm_q = pit_qr_filter_smoother(Yj, pj, mask=mask)
    dll = abs(float(kf_q.loglik - kf_s.loglik) / float(kf_s.loglik))
    assert dll < 1e-9, f"pit_qr vs sequential loglik drift {dll} (T={T})"
    kf_t, sm_t = pit_qr_time_sharded(Yj, pj, mask=mask)
    dtl = abs(float(kf_t.loglik - kf_q.loglik) / float(kf_q.loglik))
    dxs = float(jnp.abs(sm_t.x_sm - sm_q.x_sm).max())
    assert dtl < 1e-10 and dxs < 1e-10, \
        f"time-sharded drift loglik={dtl} x_sm={dxs} (T={T})"
    print(f"T={T}: pit_qr==seq (dll {dll:.1e}), "
          f"time-sharded==single (dll {dtl:.1e}, dx_sm {dxs:.1e})")
print("parity OK")
PY

echo "--- bench.longt smoke sweep ---" >&2
OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
      DFM_BENCH_TSWEEP="${DFM_BENCH_TSWEEP:-128,512}" \
      DFM_BENCH_ITERS="${DFM_BENCH_ITERS:-8}" \
      DFM_BENCH_REPS="${DFM_BENCH_REPS:-3}" \
      DFM_RUNS= python -m bench.longt)
echo "$OUT"
printf '%s' "$OUT" | python -c '
import json, sys
d = json.loads(sys.stdin.readline())
spd = d["value"]
ratio = d["pit_qr_noise_ratio"]
assert spd >= 1.0, (
    f"pit smoke FAILED: pit_qr {spd}x sequential at the longest smoke T")
assert ratio <= 3.0, (
    f"pit smoke FAILED: f32 noise ratio {ratio} vs sequential")
print(f"longt smoke OK: pit_qr {spd}x sequential, "
      f"f32 noise ratio {ratio}")'

echo "--- advisor picks pit_qr from a profiled registry ---" >&2
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - <<'PY'
import os
import tempfile

import numpy as np

with tempfile.TemporaryDirectory() as d:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
    from dfm_tpu.obs.advise import advise
    from dfm_tpu.obs.profile import profile_shape
    from dfm_tpu.obs.store import RunStore

    N, T, K, ITERS = 24, 600, 2, 12
    recs, _ = profile_shape(N, T, K, iters=ITERS, repeats=3,
                            variants=("chunked", "pit_qr"),
                            capture_costs=False)
    store = RunStore(d)
    for r in recs:
        store.append(r)
    res = advise(N, T, K, max_iters=ITERS, runs=d)
    top = res["plans"][0]
    print(f"top plan at T={T}: {top['engine']}+{top['filter']} "
          f"(anchored={top['anchored']}, "
          f"{top['predicted_wall_s']:.3f}s predicted)")
    assert top["filter"] == "pit_qr", (
        f"pit smoke FAILED: advisor kept {top} at the profiled long-T "
        f"shape")

    rng = np.random.default_rng(0)
    from dfm_tpu.utils import dgp
    p_true = dgp.dfm_params(N, K, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    os.environ["DFM_RUNS"] = d
    r_auto = fit(DynamicFactorModel(n_factors=K), Y,
                 backend=TPUBackend(), max_iters=ITERS, tol=0.0,
                 auto=True)
    del os.environ["DFM_RUNS"]
    assert r_auto.filter == "pit_qr", r_auto.filter
    # Re-run with the plan's knobs passed explicitly: must be bit-equal.
    a = r_auto.advice
    kw = {}
    if a["engine"] == "fused":
        kw["fused"] = True
    elif int(a.get("depth") or 1) > 1 or a.get("bucket"):
        from dfm_tpu.pipeline import PipelineConfig
        kw["pipeline"] = PipelineConfig(depth=int(a["depth"]),
                                        bucket=bool(a.get("bucket")))
    r_exp = fit(DynamicFactorModel(n_factors=K), Y,
                backend=TPUBackend(filter="pit_qr",
                                   fused_chunk=int(a["fused_chunk"])),
                max_iters=ITERS, tol=0.0, **kw)
    assert np.array_equal(np.asarray(r_auto.logliks),
                          np.asarray(r_exp.logliks)), \
        "pit smoke FAILED: auto fit != explicit filter=pit_qr fit"
    print("fit(auto=True) applied pit_qr, bit-identical to the knob")
PY

echo "pit smoke OK"

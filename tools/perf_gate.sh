#!/usr/bin/env bash
# One-command perf gate: smoke-size bench -> run-registry append ->
# cross-run regression check.  Exit 0 when the new run is within tolerance
# of history, 1 on a perf/convergence regression, 2 on usage errors —
# wire it straight into CI.
#
# Usage (from the repo root):
#   tools/perf_gate.sh                      # gate vs best-of-history
#   tools/perf_gate.sh --against <run|file> # gate vs an explicit baseline
#   DFM_BENCH_N=500 ... tools/perf_gate.sh  # different smoke shape
#
# The registry lives in .dfm_runs/ (override with DFM_RUNS).  History is
# seeded from the checked-in BENCH_*.json artifacts on first use;
# note the gate only compares runs with the SAME config fingerprint (shape,
# metric, device class), so the smoke-size gate accumulates its own smoke
# history — the first smoke run records a baseline, later ones are gated.
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

RUNS="${DFM_RUNS:-.dfm_runs}"
export DFM_RUNS="$RUNS"

# Seed history from the checked-in bench artifacts (idempotent).
python -m dfm_tpu.obs.store backfill --runs "$RUNS" >/dev/null

# Smoke-size by default: tiny panel, enough fused iters to get a stable
# sustained rate without real-device minutes.
OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
      DFM_BENCH_N="${DFM_BENCH_N:-200}" \
      DFM_BENCH_T="${DFM_BENCH_T:-100}" \
      DFM_BENCH_K="${DFM_BENCH_K:-4}" \
      DFM_BENCH_ITERS="${DFM_BENCH_ITERS:-30}" \
      DFM_BENCH_CPU_TIMING_ITERS="${DFM_BENCH_CPU_TIMING_ITERS:-2}" \
      python bench.py)
echo "$OUT"

RUN_ID=$(printf '%s' "$OUT" | python -c \
    'import json,sys; print(json.loads(sys.stdin.readline())["run_id"])')

# The pipelined-dispatch, fused-fit, and advisor metrics must be present
# in the bench line (and therefore in the recorded run, where obs.regress
# gates them: the e2e/fused rates as higher-is-better; blocking_transfers,
# dispatches_per_fit, p99_dispatch_ms and advice_rel_err as lower-is-
# better — the last two with their own noise floors, see obs/store.py).
printf '%s' "$OUT" | python -c '
import json, sys
d = json.loads(sys.stdin.readline())
missing = [k for k in ("e2e_warm_fit_iters_per_sec", "blocking_transfers",
                       "e2e_fused_fit_iters_per_sec", "dispatches_per_fit",
                       "p99_dispatch_ms", "advice_rel_err")
           if d.get(k) is None]
sys.exit(f"perf_gate: bench line missing {missing}" if missing else 0)'

# The multi-tenant scheduler metrics (bench.mixed / tools/mixed_smoke.sh)
# must stay registered in the observatory with their directions + noise
# floors, or recorded mixed runs silently stop being gated.
python -c '
from dfm_tpu.obs import store
need = ("aggregate_mixed_iters_per_sec", "pad_waste_frac",
        "scheduler_overhead_ms")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in ("pad_waste_frac", "scheduler_overhead_ms"):
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert not store.lower_is_better("aggregate_mixed_iters_per_sec")'

# The streaming-session metrics (bench.serve / tools/serve_smoke.sh) must
# stay registered too: query walls are lower-is-better with the ms noise
# floor; blocking transfers per query is an exact count (floor 0).
python -c '
from dfm_tpu.obs import store
need = ("serve_p50_ms", "serve_p99_ms",
        "serve_blocking_transfers_per_query")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in need:
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("serve_p50_ms") > 0, \
    "perf_gate: serve walls lost their ms noise floor"'

# The fault-tolerance health metric (bench.serve / tools/chaos_smoke.sh)
# must stay registered: degraded-mode queries are an exact count (noise
# floor 0) gated lower-is-better — a serving path quietly leaning on the
# repair ladder is a regression even when latency holds.
python -c '
from dfm_tpu.obs import store
assert "serve_degraded_queries" in store._BENCH_NUMERIC_KEYS, \
    "perf_gate: obs.store not recording serve_degraded_queries"
assert store.lower_is_better("serve_degraded_queries"), \
    "perf_gate: serve_degraded_queries lost its lower-is-better marker"
assert store.noise_floor("serve_degraded_queries") == 0, \
    "perf_gate: serve_degraded_queries must gate exactly (count metric)"'

# The fleet-serving metrics (bench.fleet / tools/fleet_smoke.sh) must stay
# registered: aggregate queries/sec gates higher-is-better; the p99 query
# latency rides the ms noise floor and the admission plan's pad waste the
# pad_waste floor, both lower-is-better.
python -c '
from dfm_tpu.obs import store
need = ("fleet_qps", "fleet_p99_ms", "fleet_pad_waste_frac")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
assert not store.lower_is_better("fleet_qps"), \
    "perf_gate: fleet_qps must gate higher-is-better"
for k in ("fleet_p99_ms", "fleet_pad_waste_frac"):
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("fleet_p99_ms") > 0, \
    "perf_gate: fleet_p99_ms lost its ms noise floor"
assert store.noise_floor("fleet_pad_waste_frac") > 0, \
    "perf_gate: fleet_pad_waste_frac lost its pad_waste noise floor"'

# The live telemetry plane metrics (bench.fleet / tools/live_smoke.sh)
# must stay registered: SLO error-budget burn and flight-recorder dumps
# both gate lower-is-better (~0 healthy) with their own noise floors.
python -c '
from dfm_tpu.obs import store
need = ("fleet_slo_burn_rate", "flight_dumps")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in need:
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("fleet_slo_burn_rate") > 0, \
    "perf_gate: fleet_slo_burn_rate lost its noise floor"
assert store.noise_floor("flight_dumps") > 0, \
    "perf_gate: flight_dumps lost its noise floor"'

# The long-T time-parallel metrics (bench.longt / tools/pit_smoke.sh)
# must stay registered: the per-T pit_qr speedups gate higher-is-better
# (the T=1000 crossover is the headline contract); the f32 noise ratio
# vs the sequential scan gates lower-is-better with its own floor.
python -c '
from dfm_tpu.obs import store
need = ("pit_qr_speedup_t300", "pit_qr_speedup_t1000",
        "pit_qr_speedup_t4000", "pit_qr_noise_ratio")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in need[:3]:
    assert not store.lower_is_better(k), \
        f"perf_gate: {k} must gate higher-is-better"
assert store.lower_is_better("pit_qr_noise_ratio"), \
    "perf_gate: pit_qr_noise_ratio lost its lower-is-better marker"
assert store.noise_floor("pit_qr_noise_ratio") > 0, \
    "perf_gate: pit_qr_noise_ratio lost its noise floor"'

# The unbounded-stream metrics (bench.stream / tools/stream_smoke.sh)
# must stay registered: ring-session throughput gates higher-is-better;
# the p99 query wall and warm/cold re-admission walls ride the ms noise
# floor, evictions/query its own whole-row floor (all lower-is-better).
python -c '
from dfm_tpu.obs import store
need = ("stream_qps", "stream_p99_ms", "evictions_per_query",
        "readmission_ms", "stream_blocking_transfers_per_query")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
assert not store.lower_is_better("stream_qps"), \
    "perf_gate: stream_qps must gate higher-is-better"
for k in need[1:]:
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("stream_p99_ms") > 0, \
    "perf_gate: stream_p99_ms lost its ms noise floor"
assert store.noise_floor("readmission_ms") > 0, \
    "perf_gate: readmission_ms lost its ms noise floor"
assert store.noise_floor("evictions_per_query") > 0, \
    "perf_gate: evictions_per_query lost its noise floor"'

# The wide-k state-axis metrics (bench.kscale / tools/kscale_smoke.sh)
# must stay registered: the per-k rank-r speedups gate higher-is-better
# (k=50 is the headline contract); the 90%-band coverage error and the
# MF m~25 fit wall gate lower-is-better with their own noise floors.
python -c '
from dfm_tpu.obs import store
need = ("kscale_speedup_k10", "kscale_speedup_k25", "kscale_speedup_k50",
        "kscale_speedup_k100", "kscale_calib_err", "kscale_mf_m25_wall_s")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in need[:4]:
    assert not store.lower_is_better(k), \
        f"perf_gate: {k} must gate higher-is-better"
for k in ("kscale_calib_err", "kscale_mf_m25_wall_s"):
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("kscale_calib_err") > 0, \
    "perf_gate: kscale_calib_err lost its noise floor"
assert store.noise_floor("kscale_mf_m25_wall_s") > 0, \
    "perf_gate: kscale_mf_m25_wall_s lost its wall noise floor"'

# The serving-daemon metrics (bench.daemon / tools/daemon_smoke.sh) must
# stay registered: socket throughput gates higher-is-better; the p99
# query wall and handoff gap ride the ms noise floor; shed_rate has its
# own fraction floor; dropped_queries gates EXACTLY at zero — any client
# request that got no answer breaks the zero-downtime contract.
python -c '
from dfm_tpu.obs import store
need = ("daemon_qps", "daemon_p99_ms", "daemon_shed_rate",
        "daemon_handoff_gap_ms", "daemon_dropped_queries")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
assert not store.lower_is_better("daemon_qps"), \
    "perf_gate: daemon_qps must gate higher-is-better"
for k in need[1:]:
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
assert store.noise_floor("daemon_p99_ms") > 0, \
    "perf_gate: daemon_p99_ms lost its ms noise floor"
assert store.noise_floor("daemon_handoff_gap_ms") > 0, \
    "perf_gate: daemon_handoff_gap_ms lost its ms noise floor"
assert store.noise_floor("daemon_shed_rate") > 0, \
    "perf_gate: daemon_shed_rate lost its noise floor"
assert store.noise_floor("daemon_dropped_queries") == 0, \
    "perf_gate: daemon_dropped_queries must gate exactly (zero-downtime)"'

# The engine-complete serving metrics (bench.fleet wide-k leg +
# bench.stream pit_qr ring leg) must stay registered: both are
# engine-vs-forced-info-twin speedup ratios gating higher-is-better
# (the regress gate's relative band absorbs twin-ratio timing jitter).
python -c '
from dfm_tpu.obs import store
need = ("fleet_widek_speedup", "stream_pit_speedup")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in need:
    assert not store.lower_is_better(k), \
        f"perf_gate: {k} must gate higher-is-better"'

# The closed-loop maintenance metrics (bench.drift / tools/drift_smoke.sh)
# must stay registered: the managed-vs-frozen held-out gain gates
# higher-is-better (the loop must keep buying forecast quality);
# detection lag, pre-break false-fire rate and the managed/frozen
# serving-p99 ratio gate lower-is-better with their own noise floors.
python -c '
from dfm_tpu.obs import store
need = ("managed_vs_frozen_heldout_gain", "drift_detection_lag_updates",
        "drift_swaps_total", "drift_false_positive_rate",
        "drift_p99_ratio")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
assert not store.lower_is_better("managed_vs_frozen_heldout_gain"), \
    "perf_gate: managed_vs_frozen_heldout_gain must gate higher-is-better"
for k in ("drift_detection_lag_updates", "drift_false_positive_rate",
          "drift_p99_ratio"):
    assert store.lower_is_better(k), \
        f"perf_gate: {k} lost its lower-is-better marker"
    assert store.noise_floor(k) > 0, \
        f"perf_gate: {k} lost its noise floor"
assert store._backfill_kind("BENCH_drift.json") == "bench_drift", \
    "perf_gate: store backfill no longer imports BENCH_drift.json"'

# The request-tracing tax (bench.serve + bench.daemon /
# tools/trace_smoke.sh) must stay registered: trace_overhead_pct is the
# best-of-N traced-vs-untraced warm-wall delta in percent, gated
# lower-is-better with its own 5-point noise floor (tiny smoke walls
# jitter a few percent run-to-run; a real span-plumbing regression is
# tens of points).
python -c '
from dfm_tpu.obs import store
assert "trace_overhead_pct" in store._BENCH_NUMERIC_KEYS, \
    "perf_gate: obs.store not recording trace_overhead_pct"
assert store.lower_is_better("trace_overhead_pct"), \
    "perf_gate: trace_overhead_pct lost its lower-is-better marker"
assert store.noise_floor("trace_overhead_pct") >= 5.0, \
    "perf_gate: trace_overhead_pct lost its percent noise floor"'

# The differentiable-tuning metrics (bench.tune / tools/tune_smoke.sh)
# must stay registered: the grad-search-vs-grid-sweep wall ratio and the
# held-out MSE gain gate higher-is-better; tune_dispatches is the
# dispatch-budget contract itself — lower-is-better with floor 0 (one
# extra blocking d2h through the tunnel IS the regression).
python -c '
from dfm_tpu.obs import store
need = ("tune_speedup_vs_grid", "tune_heldout_gain", "tune_dispatches")
missing = [k for k in need if k not in store._BENCH_NUMERIC_KEYS]
assert not missing, f"perf_gate: obs.store not recording {missing}"
for k in ("tune_speedup_vs_grid", "tune_heldout_gain"):
    assert not store.lower_is_better(k), \
        f"perf_gate: {k} must gate higher-is-better"
assert store.lower_is_better("tune_dispatches"), \
    "perf_gate: tune_dispatches lost its lower-is-better marker"
assert store.noise_floor("tune_dispatches") == 0, \
    "perf_gate: tune_dispatches must gate exactly (dispatch budget)"
assert store._backfill_kind("BENCH_tune.json") == "bench_tune", \
    "perf_gate: store backfill no longer imports BENCH_tune.json"'

echo "--- perf gate (run $RUN_ID vs ${*:-history}) ---" >&2
python -m dfm_tpu.obs.regress "$RUN_ID" --runs "$RUNS" "$@"

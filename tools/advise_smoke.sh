#!/usr/bin/env bash
# One-command advisor check: profile a smoke shape into a scratch
# registry -> rank plans with the calibrated model -> run a traced
# fit(auto=True) -> assert the advice event exists and the predicted
# wall landed within 50% of the realized one.  The quick answer to "is
# the measurement-to-decision loop still closed".
#
# Usage (from the repo root):
#   tools/advise_smoke.sh [trace_path]       # default /tmp/dfm_advise.jsonl
#
# The profile registry is a scratch dir (/tmp/dfm_advise_runs, wiped at
# start) so the run is self-contained and deterministic; JAX_PLATFORMS
# defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_advise.jsonl}"
RUNS="${DFM_ADVISE_RUNS:-/tmp/dfm_advise_runs}"
rm -f "$TRACE"
rm -rf "$RUNS"
export DFM_RUNS="$RUNS"

SHAPE="60,80,2"
ITERS=24

echo "--- profile $SHAPE -> $RUNS ---" >&2
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
    python -m dfm_tpu.obs.profile --shape "$SHAPE" --iters "$ITERS" \
    --repeats 3

echo "--- advise $SHAPE ---" >&2
python -m dfm_tpu.obs.advise --shape "$SHAPE" --max-iters "$ITERS"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - "$TRACE" "$ITERS" <<'PY'
import sys

import numpy as np

from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
from dfm_tpu.utils import dgp

iters = int(sys.argv[2])
rng = np.random.default_rng(0)
p_true = dgp.dfm_params(60, 2, rng)
Y, _ = dgp.simulate(p_true, 80, rng)

model = DynamicFactorModel(n_factors=2)
b = TPUBackend()
# Warm-up pass compiles whatever plan the advisor picks; the traced pass
# is then a warm fit, comparable to the profiler's warm medians.
fit(model, Y, backend=b, max_iters=iters, tol=0.0, auto=True)
r = fit(model, Y, backend=b, max_iters=iters, tol=0.0, auto=True,
        telemetry=sys.argv[1])
a = r.advice or {}
print(f"auto fit: engine={a.get('engine')} "
      f"predicted={a.get('predicted_wall_s', float('nan')):.3f}s "
      f"realized={a.get('realized_wall_s', float('nan')):.3f}s "
      f"rel_err={a.get('rel_err', float('nan')):.2f}")
PY

echo "--- advise smoke gate ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"
python -m dfm_tpu.obs.report "$TRACE" --json | python -c '
import json, sys
s = json.load(sys.stdin)
a = s.get("advice")
assert a, "advise smoke FAILED: no advice event in the trace"
rel = a.get("rel_err")
assert rel is not None and rel < 0.5, (
    f"advise smoke FAILED: prediction error {rel} >= 50%")
dp = s.get("dispatch_percentiles_ms")
assert dp and dp.get("p99") is not None, (
    "advise smoke FAILED: no dispatch percentiles in the summary")
engine, p99 = a.get("engine"), dp["p99"]
print(f"advise smoke OK: {engine} plan, prediction error "
      f"{100 * rel:.0f}%, p99 dispatch {p99:.2f} ms")'

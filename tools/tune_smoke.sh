#!/usr/bin/env bash
# One-command differentiable-tuning check: the CV sweep leg (G hyper
# points as ONE fused batched program + one scoring program, 2 blocking
# d2h), the gradient-search leg (the whole search — inner EM, in-graph
# held-out loss, Adam over log hypers — as ONE jitted program, 1 d2h,
# dispatch budget asserted from the trace via obs.report --json), and a
# smoke-size bench.tune run (grad search must beat the G-lone-fit grid
# sweep >= 3x with <= 2 dispatches).  The quick answer to "does gradient
# tuning still replace the grid, on budget, and does the trace prove it".
#
# Usage (from the repo root):
#   tools/tune_smoke.sh
#
# JAX_PLATFORMS defaults to cpu (the axon CPU fallback); shapes are
# smoke-size via the DFM_BENCH_* knobs below.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "--- CV sweep leg: G lanes, ONE fused program, 2 d2h ---" >&2
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - <<'PY'
import numpy as np

from dfm_tpu.backends import cpu_ref
from dfm_tpu.estim.em import EMConfig
from dfm_tpu.estim.tune import DEFAULT_GRID, TuneOptions, tune_fit
from dfm_tpu.utils import dgp

rng = np.random.default_rng(7)
Y_raw, _ = dgp.simulate(dgp.dfm_params(12, 2, rng), 72, rng)
Y = (Y_raw - Y_raw.mean(0)) / Y_raw.std(0)
W = dgp.random_mask(72, 12, rng, 0.1)
p0 = cpu_ref.pca_init(Y * W, 2)
rec = tune_fit(Y, W, p0, EMConfig(filter="info"),
               TuneOptions(method="sweep", em_iters=4))
assert rec["dispatches"] == 2, \
    f"tune smoke FAILED: sweep used {rec['dispatches']} d2h (budget 2)"
assert len(rec["cv"]) == len(DEFAULT_GRID), rec["cv"]
assert rec["heldout_after"] <= rec["heldout_before"] + 1e-12, \
    f"tune smoke FAILED: sweep made held-out worse ({rec})"
print(f"sweep OK: {len(rec['cv'])} lanes in 2 d2h, best "
      f"q={rec['q_scale']:.3g} r={rec['r_scale']:.3g}, held-out "
      f"{rec['heldout_before']:.4g} -> {rec['heldout_after']:.4g}")
PY

echo "--- grad leg: fit(tune=) end-to-end, budget from the trace ---" >&2
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - <<'PY'
import json
import subprocess
import sys
import tempfile

import numpy as np

from dfm_tpu import DynamicFactorModel, fit
from dfm_tpu.estim.tune import TuneOptions
from dfm_tpu.utils import dgp

rng = np.random.default_rng(8)
Y, _ = dgp.simulate(dgp.dfm_params(12, 2, rng), 72, rng)
trace = tempfile.mktemp(suffix=".jsonl")
res = fit(DynamicFactorModel(n_factors=2), Y, max_iters=6, tol=0.0,
          tune=TuneOptions(method="grad", steps=5, em_iters=4),
          telemetry=trace)
assert res.tune is not None and res.tune["method"] == "grad"
assert res.tune["heldout_after"] <= res.tune["heldout_before"] + 1e-12, \
    f"tune smoke FAILED: grad search made held-out worse ({res.tune})"
# The dispatch budget, proven from the trace the fit wrote:
out = subprocess.run(
    [sys.executable, "-m", "dfm_tpu.obs.report", trace, "--json"],
    capture_output=True, text=True, check=True).stdout
s = json.loads(out)
tu = s["tune"]
assert tu["dispatches"] <= 2, \
    f"tune smoke FAILED: search cost {tu['dispatches']} blocking d2h"
assert tu["q_scale"] == res.tune["q_scale"], (tu, res.tune)
print(f"grad OK: q={tu['q_scale']:.3g} r={tu['r_scale']:.3g} in "
      f"{tu['dispatches']} d2h (budget 2), held-out "
      f"{tu['heldout_before']:.4g} -> {tu['heldout_after']:.4g}")
PY

echo "--- bench.tune smoke (grad vs G-lone-fit grid) ---" >&2
OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
      DFM_BENCH_N="${DFM_BENCH_N:-12}" \
      DFM_BENCH_T="${DFM_BENCH_T:-60}" \
      DFM_BENCH_TUNE_STEPS="${DFM_BENCH_TUNE_STEPS:-5}" \
      DFM_BENCH_TUNE_EM_ITERS="${DFM_BENCH_TUNE_EM_ITERS:-3}" \
      DFM_BENCH_REPS="${DFM_BENCH_REPS:-3}" \
      DFM_RUNS= python -m bench.tune)
echo "$OUT"
printf '%s' "$OUT" | python -c '
import json, sys
d = json.loads(sys.stdin.readline())
spd = d["tune_speedup_vs_grid"]
nd = d["tune_dispatches"]
assert spd >= 3.0, (
    f"tune smoke FAILED: grad search only {spd}x the grid sweep")
assert nd <= 2, (
    f"tune smoke FAILED: tune_dispatches {nd} over the 2-d2h budget")
print(f"bench smoke OK: {spd}x vs grid, {nd} blocking d2h")'

echo "tune smoke OK"

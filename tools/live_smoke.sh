#!/usr/bin/env bash
# One-command live-telemetry-plane check (ISSUE 12), no real chip needed:
#
#   leg 1  metrics-plane bit-identity: the SAME session workload run with
#          DFM_METRICS=0 and =1 must produce byte-identical nowcasts AND
#          the same dispatch count (the plane reuses timestamps the trace
#          layer already takes — zero extra dispatches, off-path inert);
#   leg 2  untraced seams + surfaces: with NO tracer active the session
#          still feeds the plane — the per-tenant ledger reconciles with
#          the queries served, the snapshot file renders through
#          `python -m dfm_tpu.obs.live` in both text and prom modes;
#   leg 3  SLO burn -> flight recorder: an impossible latency objective
#          (p99 < 1 ns) must fire the burn-rate gate deterministically,
#          dump the flight ring to JSONL, and that dump must read back
#          through `python -m dfm_tpu.obs.report`.
#
# Usage (from the repo root): tools/live_smoke.sh
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

TMP=$(mktemp -d /tmp/dfm_live.XXXXXX)
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS="${JAX_PLATFORMS-cpu}"
export DFM_RUNS=    # never append smoke runs to the observatory

# --- leg 1: bit-identity + equal dispatch count, plane off vs on --------
run_workload() {
  DFM_METRICS="$1" python - <<'PY'
import hashlib
import json

import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_session
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

rng = np.random.default_rng(7)
p_true = dgp.dfm_params(24, 2, rng)
Y, _ = dgp.simulate(p_true, 66, rng)
Y0, stream = Y[:60], Y[60:]

res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=16, tol=1e-6,
          fused=True)
h = hashlib.sha256()
tr = Tracer(detector=RecompileDetector())
with activate(tr):
    sess = open_session(res, Y0, capacity=90, max_update_rows=2,
                        max_iters=4, tol=0.0)
    for rows in (stream[:2], stream[2:4], stream[4:6]):
        u = sess.update(rows)
        h.update(np.asarray(u.nowcast, np.float64).tobytes())
        h.update(np.asarray(u.forecasts["y"], np.float64).tobytes())
print(json.dumps({"sha": h.hexdigest(),
                  "dispatches": tr.summary()["dispatches"]}))
PY
}
OFF=$(run_workload 0 | tail -n 1)
ON=$(run_workload 1 | tail -n 1)
echo "plane off: $OFF"
echo "plane on:  $ON"
[ "$OFF" = "$ON" ] || {
  echo "live smoke FAILED: metrics plane changed results or dispatches" >&2
  exit 1
}
echo "leg 1 OK: plane on/off bit-identical, equal dispatch count"

# --- leg 2: untraced seams feed the ledger + snapshot/prom surfaces -----
SNAP="$TMP/live_snapshot.json"
DFM_METRICS_SNAPSHOT="$SNAP" DFM_METRICS_INTERVAL_S=0 python - <<'PY'
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_session
from dfm_tpu.obs.live import plane
from dfm_tpu.utils import dgp

rng = np.random.default_rng(7)
p_true = dgp.dfm_params(24, 2, rng)
Y, _ = dgp.simulate(p_true, 66, rng)
Y0, stream = Y[:60], Y[60:]

res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=16, tol=1e-6,
          fused=True)
# NO tracer: the untraced seam fallbacks must still meter every query.
sess = open_session(res, Y0, capacity=90, max_update_rows=2,
                    max_iters=4, tol=0.0)
for rows in (stream[:2], stream[2:4], stream[4:6]):
    sess.update(rows)
acct = sess.accounting()
assert len(acct) == 1, f"expected one ledger tenant, got {acct}"
row = next(iter(acct.values()))
assert row["queries"] == 3, f"ledger missed queries: {row}"
assert row["em_iters"] == 3 * 4, f"ledger missed EM iters: {row}"
assert row["device_ms"] > 0 and row["est_flops"] > 0, row
st = plane().status()
assert st["enabled"] and st["n_series"] > 0, st
assert plane().write_snapshot() is not None
print(f"untraced session metered: {row['queries']} queries, "
      f"{row['em_iters']} EM iters, {row['device_ms']:.2f} device-ms, "
      f"{st['n_series']} live series")
PY
python -m dfm_tpu.obs.live snapshot --file "$SNAP" > "$TMP/snap.txt"
head -n 6 "$TMP/snap.txt"
python -m dfm_tpu.obs.live prom --file "$SNAP" > "$TMP/prom.txt"
grep -q "dfm_queries_total" "$TMP/prom.txt" || {
  echo "live smoke FAILED: prom rendering lost dfm_queries_total" >&2
  exit 1
}
echo "leg 2 OK: ledger reconciles, snapshot + prom surfaces render"

# --- leg 3: SLO burn fires -> flight recorder dumps -> report reads it --
FLIGHT="$TMP/flight"
DFM_FLIGHT_DIR="$FLIGHT" DFM_FLIGHT_MIN_INTERVAL_S=0 python - <<'PY'
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_session
from dfm_tpu.obs.live import plane, set_slo
from dfm_tpu.obs.slo import SLOConfig
from dfm_tpu.utils import dgp

rng = np.random.default_rng(7)
p_true = dgp.dfm_params(24, 2, rng)
Y, _ = dgp.simulate(p_true, 84, rng)
Y0, stream = Y[:60], Y[60:]

res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=16, tol=1e-6,
          fused=True)
# Impossible objective: every query is over budget, so the burn rate
# must cross fire_at deterministically once min_events accumulate.
set_slo(SLOConfig(p99_ms=1e-6, window=1e9, min_events=10))
sess = open_session(res, Y0, capacity=120, max_update_rows=2,
                    max_iters=3, tol=0.0)
for i in range(12):
    sess.update(stream[2 * i:2 * i + 2])
st = plane().status()
assert st["slo"]["n_fired"] >= 1, f"SLO never fired: {st['slo']}"
assert st["slo"]["burn_rate_max"] > 1.0, st["slo"]
assert st["flight_dumps"] >= 1, f"no flight dump: {st}"
assert plane().health_events, "no slo_burn HealthEvent recorded"
assert plane().health_events[0].kind == "slo_burn"
print(f"SLO fired {st['slo']['n_fired']}x "
      f"(burn max {st['slo']['burn_rate_max']:.1f}), "
      f"{st['flight_dumps']} flight dump(s)")
PY
DUMP=$(ls "$FLIGHT"/flight-*.jsonl | head -n 1)
python -m dfm_tpu.obs.report "$DUMP" --json > "$TMP/flight.json"
python - "$TMP/flight.json" <<'PY'
import json
import sys

s = json.load(open(sys.argv[1]))
assert s["schema_version"] == 1, s.get("schema_version")
n = s["n_events"]
assert n >= 10, f"flight dump too small: {n}"
q = s["queries"]
assert q["n_queries"] >= 10, q
assert "slo_burn" in (s.get("health_kinds") or []), s.get("health_kinds")
print(f"flight dump readable: {n} events, {q['n_queries']} queries, "
      f"slo_burn recorded")
PY
echo "leg 3 OK: SLO burn -> flight dump -> obs.report round-trip"

echo "live smoke OK"

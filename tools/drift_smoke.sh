#!/usr/bin/env bash
# One-command closed-loop-maintenance check (ISSUE 18), no real chip:
#
#   leg 1  off-path bit-identity: the SAME session workload run with
#          DFM_DRIFT=0 and =1 must produce byte-identical nowcasts AND
#          the same dispatch count — the detector is host arithmetic on
#          signals the query path already computes;
#   leg 2  detection + budgets: the bench.drift soak on a simulated
#          regime break must fire within the lag budget, swap through
#          the background refit with ZERO serve_update recompiles,
#          keep the managed/frozen serving-p99 ratio <= 1.05, buy a
#          positive held-out quality gain, and stay false-positive-free
#          on the healthy pre-break regime;
#   leg 3  hot-swap exactness: after fleet.swap_params the tenant's next
#          answers must be bit-equal to a lone session opened cold on
#          the swapped params (info engine — the swap installs EXACTLY
#          the refit params, nothing else moves);
#   leg 4  decision trail: a traced maintenance pass must round-trip
#          through `python -m dfm_tpu.obs.report` — the always-present
#          maintenance section carries the per-tenant trigger/refit/
#          swap rows and the text renderer prints them.
#
# Usage (from the repo root): tools/drift_smoke.sh
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

TMP=$(mktemp -d /tmp/dfm_drift.XXXXXX)
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS="${JAX_PLATFORMS-cpu}"
export DFM_RUNS=    # never append smoke runs to the observatory

LAG_BUDGET="${DFM_DRIFT_LAG_BUDGET:-8}"

# --- leg 1: bit-identity + equal dispatch count, detector off vs on -----
run_workload() {
  DFM_DRIFT="$1" python - <<'PY'
import hashlib
import json

import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_session
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

rng = np.random.default_rng(7)
p_true = dgp.dfm_params(24, 2, rng)
Y, _ = dgp.simulate(p_true, 66, rng)
Y0, stream = Y[:60], Y[60:]

res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=16, tol=1e-6,
          fused=True)
h = hashlib.sha256()
tr = Tracer(detector=RecompileDetector())
with activate(tr):
    sess = open_session(res, Y0, capacity=90, max_update_rows=2,
                        max_iters=4, tol=0.0)
    for rows in (stream[:2], stream[2:4], stream[4:6]):
        u = sess.update(rows)
        h.update(np.asarray(u.nowcast, np.float64).tobytes())
        h.update(np.asarray(u.forecasts["y"], np.float64).tobytes())
print(json.dumps({"sha": h.hexdigest(),
                  "dispatches": tr.summary()["dispatches"]}))
PY
}
OFF=$(run_workload 0 | tail -n 1)
ON=$(run_workload 1 | tail -n 1)
echo "drift off: $OFF"
echo "drift on:  $ON"
[ "$OFF" = "$ON" ] || {
  echo "drift smoke FAILED: detector changed results or dispatches" >&2
  exit 1
}
echo "leg 1 OK: DFM_DRIFT=0/1 bit-identical, equal dispatch count"

# --- leg 2: break -> fire within budget -> refit+swap, budgets hold -----
BENCH=$(DFM_BENCH_N="${DFM_BENCH_N:-8}" \
        DFM_BENCH_DRIFT_T0="${DFM_BENCH_DRIFT_T0:-60}" \
        DFM_BENCH_DRIFT_PRE="${DFM_BENCH_DRIFT_PRE:-18}" \
        DFM_BENCH_DRIFT_POST="${DFM_BENCH_DRIFT_POST:-24}" \
        DFM_BENCH_ITERS="${DFM_BENCH_ITERS:-15}" \
        DFM_BENCH_DRIFT_REFIT_ITERS="${DFM_BENCH_DRIFT_REFIT_ITERS:-25}" \
        DFM_BENCH_SERVE_ITERS="${DFM_BENCH_SERVE_ITERS:-1}" \
        python -m bench.drift)
echo "$BENCH"
BENCH_JSON="$BENCH" python - "$LAG_BUDGET" <<'PY'
import json
import os
import sys

d = json.loads(os.environ["BENCH_JSON"].strip().splitlines()[-1])
budget = int(sys.argv[1])
lag = d["drift_detection_lag_updates"]
assert lag <= budget, f"detection lag {lag} > budget {budget}"
assert d["drift_swaps_total"] >= 1, "maintenance never swapped"
assert d["recompiles_after_warmup"] == 0, \
    f"refit+swap recompiled the serving tick: {d}"
assert d["managed_vs_frozen_heldout_gain"] > 0, \
    f"maintenance bought no quality: {d['managed_vs_frozen_heldout_gain']}"
# p99 at smoke sizes is the max of ~40 few-ms CPU-fallback walls and
# host scheduler jitter alone moves it several ms — apply a 5 ms
# absolute floor before failing the 1.05 ratio bound.  On the real
# chip (60-100 ms dispatch walls) that floor is <10% and the recorded
# run is gated via obs.regress (0.10 p99_ratio noise floor).
ratio_ok = (d["drift_p99_ratio"] <= 1.05
            or d["managed_p99_ms"] - d["frozen_p99_ms"] <= 5.0)
assert ratio_ok, \
    f"maintenance taxed the serving path: p99 ratio {d['drift_p99_ratio']}" \
    f" ({d['frozen_p99_ms']} -> {d['managed_p99_ms']} ms)"
assert d["drift_false_positive_rate"] <= 0.2, \
    f"detector fired on the healthy regime: {d['drift_false_positive_rate']}"
print(f"soak: fired {lag} update(s) after the break "
      f"(budget {budget}), {d['drift_swaps_total']} swap(s), "
      f"gain {d['managed_vs_frozen_heldout_gain']:+.4g}, "
      f"p99 ratio {d['drift_p99_ratio']:.3f}, 0 recompiles")
PY
echo "leg 2 OK: detection within budget, swap recompile-free, budgets hold"

# --- leg 3: hot swap == cold open on the swapped params (bit-exact) -----
python - <<'PY'
import dataclasses

import jax
jax.config.update("jax_enable_x64", True)
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.utils import dgp

rng = np.random.default_rng(31)
p_true = dgp.dfm_params(10, 2, rng)
Y, _ = dgp.simulate(p_true, 66, rng)
Y0, stream = Y[:60], Y[60:]
model = DynamicFactorModel(n_factors=2)


def fleet_answer(r, swap=None):
    fl = open_fleet([r], [Y0], tenants=["t0"], capacity=70,
                    max_update_rows=2, max_iters=3, tol=0.0)
    if swap is not None:
        fl.swap_params("t0", swap)
    fl.submit("t0", stream[:2])
    u = fl.drain()["t0"][-1]
    fl.close()
    return u


with jax.default_matmul_precision("highest"):
    res = fit(model, Y0, max_iters=8, tol=0.0, fused=True)
    res2 = fit(model, Y0, max_iters=24, tol=0.0, fused=True)  # "refit"
    assert not np.allclose(res.params.Lam, res2.params.Lam)
    res_sw = dataclasses.replace(res, params=res2.params)

    # Contract 1 (bit-exact): a hot swap serves EXACTLY what a fleet
    # opened cold on the swapped params serves — the swap installs the
    # refit params and nothing else moves.
    a = fleet_answer(res, swap=res2.params)
    b = fleet_answer(res_sw)
    assert np.array_equal(np.asarray(a.nowcast), np.asarray(b.nowcast)), \
        "post-swap nowcast != cold open on swapped params"
    for key in a.forecasts:
        assert np.array_equal(np.asarray(a.forecasts[key]),
                              np.asarray(b.forecasts[key])), key

    # Contract 2 (documented parity pin): the swapped tenant matches a
    # LONE session cold-opened on the swapped params to the fleet-vs-
    # lone tolerance (vmapped batched linalg reassociates ~1 ulp/dot).
    sess = open_session(res_sw, Y0, capacity=70, max_update_rows=2,
                        max_iters=3, tol=0.0)
    c = sess.update(stream[:2])
    sess.close()
    np.testing.assert_allclose(np.asarray(a.nowcast),
                               np.asarray(c.nowcast),
                               rtol=0, atol=1e-8)

    # Contract 3: a no-op swap (unchanged params) is bit-identical.
    d = fleet_answer(res)
    e = fleet_answer(res, swap=res.params.copy())
    assert np.array_equal(np.asarray(d.nowcast), np.asarray(e.nowcast)), \
        "no-op swap changed answers"
print("hot swap bit-equal to cold open; lone-session parity; "
      "no-op swap bit-identical")
PY
echo "leg 3 OK: hot swap installs exactly the refit params"

# --- leg 4: decision trail round-trips through obs.report ---------------
TRACE="$TMP/maint.jsonl"
DFM_TRACE="$TRACE" DFM_DRIFT=1 python - <<'PY'
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_fleet
from dfm_tpu.fleet import MaintenancePolicy, run_maintenance
from dfm_tpu.utils import dgp

rng = np.random.default_rng(33)
p_true = dgp.dfm_params(10, 2, rng)
Y, _ = dgp.simulate(p_true, 64, rng)
Y0, stream = Y[:60], Y[60:]

res = fit(DynamicFactorModel(n_factors=2), Y0, max_iters=6, tol=0.0,
          fused=True)
fl = open_fleet([res], [Y0], tenants=["t0"], capacity=70,
                max_update_rows=2, max_iters=3, tol=0.0)
fl.submit("t0", stream[:2])
fl.drain()
recs = run_maintenance(fl, ["t0"],
                       policy=MaintenancePolicy(max_iters=20))
fl.close()
assert len(recs) == 1 and recs[0].action in ("swap", "skip"), recs
print(f"maintenance pass: {recs[0].action} "
      f"(delta {recs[0].quality_delta:+.4g})")
PY
python -m dfm_tpu.obs.report "$TRACE" --json > "$TMP/report.json"
python - "$TMP/report.json" <<'PY'
import json
import sys

s = json.load(open(sys.argv[1]))
mt = s["maintenance"]
assert mt["triggers"] == 1 and mt["refits"] == 1, mt
assert mt["swaps"] + mt["skips"] == 1, mt
row = mt["per_tenant"]["t0"]
assert row["refits"] == 1 and row["action"] in ("swap", "skip"), row
assert row["engine"], row
print(f"report maintenance section: {mt['triggers']} trigger, "
      f"{mt['refits']} refit, action={row['action']}")
PY
python -m dfm_tpu.obs.report "$TRACE" > "$TMP/report.txt"
grep -q "maintenance:" "$TMP/report.txt" || {
  echo "drift smoke FAILED: text report lost the maintenance stanza" >&2
  exit 1
}
echo "leg 4 OK: decision trail round-trips through obs.report"

echo "drift smoke OK"

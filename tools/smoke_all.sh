#!/usr/bin/env bash
# Run every one-command smoke check in tools/ (plus the perf gate) and
# print a pass/fail summary — the single entry point for "is every
# subsystem still healthy" before a commit or after an environment
# change.  Each check runs in sequence (nproc == 1: parallel runs would
# contaminate each other's timing legs — CLAUDE.md) with its own log
# under ${SMOKE_LOG_DIR:-/tmp/dfm_smoke_logs}; the summary names each
# failing check and its log so no scrollback archaeology is needed.
#
# Usage (from the repo root):
#   tools/smoke_all.sh              # every *_smoke.sh + perf_gate.sh
#   tools/smoke_all.sh serve fleet  # just tools/serve_smoke.sh + tools/fleet_smoke.sh
#
# Exit 0 when everything passes, 1 otherwise.  Individual checks keep
# their own env knobs (DFM_BENCH_*, JAX_PLATFORMS, ...).
set -uo pipefail
cd "$(dirname "$0")/.."

LOG_DIR="${SMOKE_LOG_DIR:-/tmp/dfm_smoke_logs}"
mkdir -p "$LOG_DIR"

if [ "$#" -gt 0 ]; then
    checks=()
    for name in "$@"; do
        checks+=("tools/${name%_smoke.sh}_smoke.sh")
    done
else
    checks=(tools/*_smoke.sh)
    checks+=(tools/perf_gate.sh)
fi

pass=() fail=()
for check in "${checks[@]}"; do
    name=$(basename "$check" .sh)
    log="$LOG_DIR/$name.log"
    printf '=== %-18s ' "$name"
    t0=$SECONDS
    if bash "$check" >"$log" 2>&1; then
        printf 'PASS  (%3ds)\n' "$((SECONDS - t0))"
        pass+=("$name")
    else
        printf 'FAIL  (%3ds)  log: %s\n' "$((SECONDS - t0))" "$log"
        fail+=("$name")
    fi
done

echo
echo "--- smoke summary: ${#pass[@]} passed, ${#fail[@]} failed ---"
if [ "${#fail[@]}" -gt 0 ]; then
    for name in "${fail[@]}"; do
        echo "FAILED: $name  ($LOG_DIR/$name.log; last lines below)"
        tail -5 "$LOG_DIR/$name.log" | sed 's/^/    /'
    done
    exit 1
fi
echo "all smoke checks OK"

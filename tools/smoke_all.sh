#!/usr/bin/env bash
# Run every one-command smoke check in tools/ (plus the perf gate) and
# print a pass/fail summary — the single entry point for "is every
# subsystem still healthy" before a commit or after an environment
# change.  Each check runs in sequence (nproc == 1: parallel runs would
# contaminate each other's timing legs — CLAUDE.md) with its own log
# under ${SMOKE_LOG_DIR:-/tmp/dfm_smoke_logs}; the summary names each
# failing check and its log so no scrollback archaeology is needed.
#
# Usage (from the repo root):
#   tools/smoke_all.sh              # every *_smoke.sh + perf_gate.sh
#   tools/smoke_all.sh serve fleet  # just tools/serve_smoke.sh + tools/fleet_smoke.sh
#
# Exit 0 when everything passes, 1 otherwise.  Individual checks keep
# their own env knobs (DFM_BENCH_*, JAX_PLATFORMS, ...).
set -uo pipefail
cd "$(dirname "$0")/.."

LOG_DIR="${SMOKE_LOG_DIR:-/tmp/dfm_smoke_logs}"
mkdir -p "$LOG_DIR"

if [ "$#" -gt 0 ]; then
    checks=()
    for name in "$@"; do
        checks+=("tools/${name%_smoke.sh}_smoke.sh")
    done
else
    checks=(tools/*_smoke.sh)
    checks+=(tools/perf_gate.sh)
fi

pass=() fail=() names=() stats=() walls=()
total=0
for check in "${checks[@]}"; do
    name=$(basename "$check" .sh)
    log="$LOG_DIR/$name.log"
    printf '=== %-18s ' "$name"
    t0=$SECONDS
    if bash "$check" >"$log" 2>&1; then
        dt=$((SECONDS - t0))
        printf 'PASS  (%3ds)\n' "$dt"
        pass+=("$name"); stats+=("PASS")
    else
        dt=$((SECONDS - t0))
        printf 'FAIL  (%3ds)  log: %s\n' "$dt" "$log"
        fail+=("$name"); stats+=("FAIL")
    fi
    names+=("$name"); walls+=("$dt"); total=$((total + dt))
done

# Per-smoke wall-clock recap: the slow checks are where smoke time goes,
# and the table survives in scrollback after the inline lines are gone.
echo
printf -- '--- smoke summary: %d passed, %d failed (total %ds) ---\n' \
    "${#pass[@]}" "${#fail[@]}" "$total"
for i in "${!names[@]}"; do
    printf '  %-18s %s  %4ds\n' "${names[$i]}" "${stats[$i]}" "${walls[$i]}"
done
if [ "${#fail[@]}" -gt 0 ]; then
    echo "failing: ${fail[*]}"
    for name in "${fail[@]}"; do
        echo "FAILED: $name  ($LOG_DIR/$name.log; last lines below)"
        tail -5 "$LOG_DIR/$name.log" | sed 's/^/    /'
    done
    exit 1
fi
echo "all smoke checks OK"

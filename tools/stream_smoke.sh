#!/usr/bin/env bash
# One-command unbounded-stream soak (ISSUE 14): run a ring-buffer session
# far past its capacity on the fake mesh and assert the constant-memory
# contract from the trace via the report CLI — flat host+device buffer
# footprint, ZERO recompiles after warmup (the in-graph eviction roll
# rides the one serve_update executable), and <= 1 blocking d2h per
# query.  A second leg opens a fleet with more registered tenants than
# resident HBM lanes, churns the hot set through warm AND cold tiers,
# and asserts every paged-out tenant heals BIT-EXACT against an all-hot
# twin after re-admission.  A third leg rides the ring on the square-root
# parallel-in-time engine (filter="pit_qr"): eviction + warm EM + bands
# through the PIT scans, pinned to a cold same-engine fused fit of the
# trailing window, engine surviving a snapshot/restore round-trip.  The
# quick way to answer "can this serve an infinite stream at constant
# memory" without the real chip.
#
# Usage (from the repo root):
#   tools/stream_smoke.sh [trace_path]       # default /tmp/dfm_stream.jsonl
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time;
# export JAX_PLATFORMS= (empty) to smoke the default backend instead.
set -euo pipefail
cd "$(dirname "$0")/.."

TRACE="${1:-/tmp/dfm_stream.jsonl}"
rm -f "$TRACE"

JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - "$TRACE" <<'PY'
import sys
import tempfile

import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_fleet, open_session
from dfm_tpu.obs.cost import RecompileDetector
from dfm_tpu.obs.trace import Tracer, activate
from dfm_tpu.utils import dgp

# -- leg 1: ring-session soak at queries >> capacity --------------------
rng = np.random.default_rng(14)
p_true = dgp.dfm_params(20, 2, rng)
CAP, ROWS, QUERIES = 40, 2, 30          # 62 rows streamed past a full panel
Y, _ = dgp.simulate(p_true, CAP + (QUERIES + 1) * ROWS, rng)
Y0, stream = Y[:CAP], Y[CAP:]

model = DynamicFactorModel(n_factors=2)
res = fit(model, Y0, max_iters=24, tol=1e-6, fused=True)
print(f"cold fused fit: {res.n_iters} iters, "
      f"converged={bool(res.converged)}")

tr = Tracer(path=sys.argv[1], detector=RecompileDetector())
with activate(tr):
    # The panel starts FULL, so every update evicts `ROWS` oldest rows
    # in-graph while appending — the buffer never grows.
    sess = open_session(res, Y0, capacity=CAP, max_update_rows=ROWS,
                        max_iters=4, tol=0.0, ring=True)
    assert sess.remaining is None, "ring session must report unbounded"
    sess.update(stream[:ROWS])                      # compile + warm
    dev_shape = sess._Ybuf.shape
    host_bytes = sess._Yhost.nbytes + sess._Whost.nbytes
    for i in range(1, QUERIES + 1):
        u = sess.update(stream[i * ROWS:(i + 1) * ROWS])
    assert sess._Ybuf.shape == dev_shape, "device buffer grew"
    assert sess._Yhost.nbytes + sess._Whost.nbytes == host_bytes, \
        "host shadow grew"
    assert sess.t == CAP and sess.n_evicted == (QUERIES + 1) * ROWS, \
        (sess.t, sess.n_evicted)
    print(f"ring soak: {QUERIES + 1} queries streamed "
          f"{sess.total_rows - CAP} rows past capacity={CAP}; "
          f"evicted {sess.n_evicted}, footprint flat, "
          f"nowcast[:3]={np.round(u.nowcast[:3], 3).tolist()}")
    sess.close()
tr.close()

# -- leg 2: tiering churn heals bit-exact -------------------------------
# 4 tenants on 2 resident lanes: every submit past the hot set pages a
# warm tenant in and demotes the LRU hot one.  The all-hot twin fleet
# (resident=None) never pages; answers must agree to the BIT.
rng2 = np.random.default_rng(15)
tenants, panels, streams = [], [], []
for i in range(4):
    pt = dgp.dfm_params(10, 2, rng2)
    Yt, _ = dgp.simulate(pt, 46, rng2)
    r = fit(DynamicFactorModel(n_factors=2), Yt[:40], max_iters=8,
            telemetry=False)
    tenants.append(r); panels.append(Yt[:40]); streams.append(Yt[40:])

kw = dict(capacity=48, max_update_rows=2, max_iters=3, tol=0.0,
          max_classes=1)
fl = open_fleet(tenants, panels, resident=2, **kw)
tw = open_fleet(tenants, panels, **kw)
n_paged = 0
for rnd in range(2):
    for i in range(4):
        name, rows = f"t{i}", streams[i][2 * rnd:2 * rnd + 2]
        paged = fl.tier(name) != "hot"
        fl.submit(name, rows); tw.submit(name, rows)
        a, b = fl.drain()[name][0], tw.drain()[name][0]
        assert np.array_equal(a.nowcast, b.nowcast) \
            and np.array_equal(a.forecasts["y"], b.forecasts["y"]), \
            f"{name} diverged from all-hot twin after paging"
        n_paged += paged
assert n_paged > 0, "tiering leg never paged a tenant in"

# Cold tier round-trip: spill one tenant to disk, thaw + re-admit, and
# the next answer still matches the never-evicted twin bit-exactly.
with tempfile.NamedTemporaryFile(suffix=".npz") as f:
    fl.evict("t0", tier="cold", path=f.name)
    assert fl.tier("t0") == "cold"
    fl.submit("t0", None); tw.submit("t0", None)
    a, b = fl.drain()["t0"][0], tw.drain()["t0"][0]
    assert np.array_equal(a.nowcast, b.nowcast), \
        "cold spill/thaw round-trip diverged"
fl.close(); tw.close()
print(f"tiering: 4 tenants on 2 lanes, {n_paged} re-admissions + one "
      "cold round-trip, all bit-exact vs the all-hot twin")

# -- leg 3: pit_qr ring session -----------------------------------------
# The square-root parallel-in-time engine behind the same ring seam:
# in-graph eviction + warm EM + forecasts through the PIT combine tree,
# pinned to a cold same-engine fused fit of the trailing window (the
# combine tree reassociates across capacity padding — fp tolerance, not
# exactness), engine + ring surviving a snapshot/restore round-trip.
import os

import jax
jax.config.update("jax_enable_x64", True)

from dfm_tpu import TPUBackend

bq = TPUBackend(filter="pit_qr")
rng3 = np.random.default_rng(16)
Yq, _ = dgp.simulate(dgp.dfm_params(12, 2, rng3), 48, rng3)
# standardize=False: the session freezes scaling stats at open, so the
# trailing-window cold-fit pin is exact only without re-standardization
# (same convention as tests/test_stream.py).
mq = DynamicFactorModel(n_factors=2, standardize=False)
resq = fit(mq, Yq[:40], max_iters=10,
           backend=bq, fused=True, telemetry=False)
assert resq.filter == "pit_qr", resq.filter
# The panel opens FULL, so the first update already evicts: its answer
# is pinned against a cold same-engine fused fit of the trailing
# window from the same start params at the same budget.
sq = open_session(resq, Yq[:40], capacity=40, max_update_rows=4,
                  max_iters=4, tol=0.0, backend=bq, ring=True)
assert sq.filter == "pit_qr", sq.filter
uq = sq.update(Yq[40:44])                 # evicts 4 oldest rows in-graph
assert sq.n_evicted == 4, sq.n_evicted
refq = fit(mq, Yq[4:44], backend=bq,
           fused=True, max_iters=4, tol=0.0, init=resq.params)
assert uq.n_iters == refq.n_iters
np.testing.assert_allclose(uq.nowcast, refq.nowcast, rtol=1e-8, atol=1e-8)
np.testing.assert_allclose(uq.forecasts["y"], refq.forecasts["y"],
                           rtol=1e-8, atol=1e-8)
assert uq.nowcast_sd is not None and np.all(uq.nowcast_sd > 0), \
    "pit leg FAILED: missing observation-space bands"

snap = tempfile.mktemp(suffix=".npz")
sq.snapshot(snap)
sq.close()
from dfm_tpu import open_session as _reopen
sr = _reopen(snapshot=snap, backend=bq)
assert sr.filter == "pit_qr" and sr.ring, (sr.filter, sr.ring)
ur = sr.update(Yq[44:48])
assert np.isfinite(ur.nowcast).all() and sr.n_evicted == 8
sr.close(); os.unlink(snap)
print("pit_qr ring leg: eviction + warm EM pinned to the trailing-window "
      "same-engine cold fit; engine + ring survived snapshot/restore")
PY

echo "--- stream smoke gate ($TRACE) ---"
python -m dfm_tpu.obs.report "$TRACE"
python -m dfm_tpu.obs.report "$TRACE" --json | python -c '
import json, sys
s = json.load(sys.stdin)
q = s.get("queries") or {}
n = q.get("n_queries", 0)
ev = q.get("rows_evicted", 0)
bt = s.get("blocking_transfers", 99)
rc = q.get("recompiles_after_warmup", 99)
assert n == 31, f"stream smoke FAILED: expected 31 query events, got {n}"
assert ev == 62, f"stream smoke FAILED: expected 62 evicted rows, got {ev}"
assert rc == 0, f"stream smoke FAILED: {rc} recompiles after warmup"
assert bt <= n, f"stream smoke FAILED: {bt} blocking transfers for {n} queries"
print(f"stream smoke OK: {n} queries evicted {ev} rows in-graph, "
      f"{bt} blocking transfer(s) (<= 1/query), 0 recompiles after warmup")'

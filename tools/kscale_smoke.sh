#!/usr/bin/env bash
# One-command wide-k check: rank-r lowrank filter/smoother parity vs the
# NumPy f64 oracle AND (at r = k) vs the exact info-form path -> a
# smoke-size bench.kscale sweep (rank-r must not lose to exact at the
# widest smoke k, calibration error must be finite, and the MF m~25
# augmented shape must complete a rank-r fit) -> a seeded-registry
# advisor selection (fit(auto=True) applies the lowrank plan and matches
# the explicit filter= knob bit for bit).  The quick answer to "does
# rank-r still win at wide k, are its bands honest, and does the advisor
# know".
#
# Usage (from the repo root):
#   tools/kscale_smoke.sh
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "--- lowrank parity (oracle + r=k exactness) ---" >&2
JAX_PLATFORMS=cpu python - <<'PY'
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

from dfm_tpu.backends import cpu_ref
from dfm_tpu.ssm.info_filter import info_filter
from dfm_tpu.ssm.kalman import rts_smoother
from dfm_tpu.ssm.lowrank_filter import lowrank_filter_smoother
from dfm_tpu.ssm.params import SSMParams as JP
from dfm_tpu.utils import dgp

rng = np.random.default_rng(3)
p = dgp.dfm_params(25, 6, rng)
Y, _ = dgp.simulate(p, 80, rng)
mask = dgp.random_mask(*Y.shape, rng, 0.25)
pj = JP.from_numpy(p, jnp.float64)
Yj = jnp.asarray(Y)

kf_j, sm_j = lowrank_filter_smoother(Yj, pj, mask=jnp.asarray(mask), rank=3)
kf_n = cpu_ref.kalman_filter_lowrank(Y, p, mask=mask, rank=3)
sm_n = cpu_ref.rts_smoother_lowrank(kf_n, p, rank=3)
dll = abs(float(kf_j.loglik) - kf_n.loglik)
dx = float(jnp.abs(sm_j.x_sm - sm_n.x_sm).max())
assert dll < 1e-8 and dx < 1e-10, \
    f"kscale smoke FAILED: oracle drift dll={dll} dx_sm={dx}"
print(f"rank-3 vs NumPy oracle: dll {dll:.1e}, dx_sm {dx:.1e}")

kf_e = info_filter(Yj, pj)
sm_e = rts_smoother(kf_e, pj)
kf_f, sm_f = lowrank_filter_smoother(Yj, pj, rank=6)
dll = abs(float(kf_f.loglik - kf_e.loglik) / float(kf_e.loglik))
dx = float(jnp.abs(sm_f.x_sm - sm_e.x_sm).max())
assert dll < 1e-9 and dx < 1e-8, \
    f"kscale smoke FAILED: r=k drift dll={dll} dx_sm={dx}"
print(f"r=k vs exact info path: dll {dll:.1e}, dx_sm {dx:.1e}")
print("parity OK")
PY

echo "--- bench.kscale smoke sweep ---" >&2
OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
      DFM_BENCH_N="${DFM_BENCH_N:-80}" \
      DFM_BENCH_T="${DFM_BENCH_T:-120}" \
      DFM_BENCH_KSWEEP="${DFM_BENCH_KSWEEP:-12,50}" \
      DFM_BENCH_ITERS="${DFM_BENCH_ITERS:-6}" \
      DFM_BENCH_REPS="${DFM_BENCH_REPS:-2}" \
      DFM_BENCH_MF_T="${DFM_BENCH_MF_T:-30}" \
      DFM_RUNS= python -m bench.kscale)
echo "$OUT"
printf '%s' "$OUT" | python -c '
import json, math, sys
d = json.loads(sys.stdin.readline())
spd = d["value"]
err = d["kscale_calib_err"]
assert spd >= 1.0, (
    f"kscale smoke FAILED: lowrank {spd}x exact at the widest smoke k")
assert math.isfinite(err) and err <= 0.10, (
    f"kscale smoke FAILED: calibration error {err}")
mf_wall = d.get("kscale_mf_m25_wall_s")
assert mf_wall is not None, (
    "kscale smoke FAILED: MF m~25 rank-r leg missing")
m = d["kscale_mf_state_dim"]
print(f"kscale smoke OK: lowrank {spd}x exact, calib err {err}, "
      f"MF m={m} fit {mf_wall}s")'

echo "--- advisor picks lowrank from a profiled wide-k registry ---" >&2
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" python - <<'PY'
import os
import tempfile

import numpy as np

with tempfile.TemporaryDirectory() as d:
    import jax
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dfm_tpu.api import DynamicFactorModel, TPUBackend, fit
    from dfm_tpu.obs.advise import advise
    from dfm_tpu.obs.profile import profile_shape
    from dfm_tpu.obs.store import RunStore

    N, T, K, ITERS = 80, 120, 50, 8
    recs, _ = profile_shape(N, T, K, iters=ITERS, repeats=3,
                            variants=("chunked", "lowrank"),
                            capture_costs=False)
    store = RunStore(d)
    for r in recs:
        store.append(r)
    res = advise(N, T, K, max_iters=ITERS, runs=d)
    top = res["plans"][0]
    print(f"top plan at k={K}: {top['engine']}+{top['filter']} "
          f"(anchored={top['anchored']}, "
          f"{top['predicted_wall_s']:.3f}s predicted)")
    assert top["filter"] == "lowrank", (
        f"kscale smoke FAILED: advisor kept {top} at the profiled "
        f"wide-k shape")

    rng = np.random.default_rng(0)
    from dfm_tpu.utils import dgp
    p_true = dgp.dfm_params(N, K, rng)
    Y, _ = dgp.simulate(p_true, T, rng)
    os.environ["DFM_RUNS"] = d
    r_auto = fit(DynamicFactorModel(n_factors=K), Y,
                 backend=TPUBackend(), max_iters=ITERS, tol=0.0,
                 auto=True)
    del os.environ["DFM_RUNS"]
    assert r_auto.filter == "lowrank", r_auto.filter
    # Re-run with the plan's knobs passed explicitly: must be bit-equal.
    a = r_auto.advice
    kw = {}
    if a["engine"] == "fused":
        kw["fused"] = True
    elif int(a.get("depth") or 1) > 1 or a.get("bucket"):
        from dfm_tpu.pipeline import PipelineConfig
        kw["pipeline"] = PipelineConfig(depth=int(a["depth"]),
                                        bucket=bool(a.get("bucket")))
    r_exp = fit(DynamicFactorModel(n_factors=K), Y,
                backend=TPUBackend(filter="lowrank",
                                   fused_chunk=int(a["fused_chunk"])),
                max_iters=ITERS, tol=0.0, **kw)
    assert np.array_equal(np.asarray(r_auto.logliks),
                          np.asarray(r_exp.logliks)), \
        "kscale smoke FAILED: auto fit != explicit filter=lowrank fit"
    print("fit(auto=True) applied lowrank, bit-identical to the knob")
PY

echo "kscale smoke OK"

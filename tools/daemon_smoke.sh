#!/usr/bin/env bash
# One-command serving-daemon soak (ISSUE 16): real processes, real
# signals.  Leg 1 boots a daemon from a fleet snapshot, serves socket
# queries, SIGKILLs it mid-soak, restarts it, and asserts the restarted
# daemon REPLAYS its journal to answers bit-equal to an uninterrupted
# in-driver twin fleet — and that client idempotency ids still dedup
# across the crash.  Leg 2 runs a blue/green handoff under live load
# (`--takeover`): a successor process warms from the snapshot + journal,
# takes the listening socket from the predecessor via SCM_RIGHTS, and
# the driver asserts ZERO dropped queries, bit-equal answers after the
# swap, and a recorded handoff (gap_ms) in the successor's trace via
# obs.report.  The quick way to answer "does the front door survive
# kill -9 and deploys" without the real chip.
#
# Usage (from the repo root):
#   tools/daemon_smoke.sh [workdir]          # default: a fresh mktemp -d
#
# JAX_PLATFORMS defaults to cpu so this never burns real-device time.
set -euo pipefail
cd "$(dirname "$0")/.."

WORK="${1:-$(mktemp -d /tmp/dfm_daemon_smoke.XXXXXX)}"
export DFM_SMOKE_WORK="$WORK"
mkdir -p "$WORK"

set +e
JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" JAX_ENABLE_X64=1 \
DFM_RUNS= python - <<'PY'
import os
import signal
import subprocess
import sys
import threading
import time

import jax
jax.config.update("jax_enable_x64", True)
import numpy as np

from dfm_tpu import DynamicFactorModel, fit, open_fleet
from dfm_tpu.daemon import DaemonClient
from dfm_tpu.obs.report import summarize
from dfm_tpu.utils import dgp

WORK = os.environ["DFM_SMOKE_WORK"]
SNAP = os.path.join(WORK, "snap")
JOURNAL = os.path.join(WORK, "journal.jsonl")
ADDR = os.path.join(WORK, "daemon.sock")
R = 2                                    # rows per query

# -- bootstrap: two tenants, one snapshot, one uninterrupted twin -------
tens = []
for i, (N, T, k) in enumerate([(8, 36, 2), (10, 40, 2)]):
    rng = np.random.default_rng(160 + i)
    p_true = dgp.dfm_params(N, k, rng)
    Y, _ = dgp.simulate(p_true, T + 40 * R, rng)
    res = fit(DynamicFactorModel(n_factors=k), Y[:T], max_iters=6,
              telemetry=False)
    tens.append((res, Y[:T], Y[T:]))

caps = [t[1].shape[0] + 42 * R for t in tens]
twin = open_fleet([t[0] for t in tens], [t[1] for t in tens],
                  capacity=caps, max_update_rows=R, max_iters=4, tol=0.0)
names = list(twin.tenants)
boot = open_fleet([t[0] for t in tens], [t[1] for t in tens],
                  tenants=names, capacity=caps, max_update_rows=R,
                  max_iters=4, tol=0.0)
boot.snapshot_all(SNAP)
boot.close()
print(f"bootstrap: snapshot of {names} written", flush=True)

cursor = [0] * len(names)


def next_rows(i):
    rows = tens[i][2][cursor[i]:cursor[i] + R]
    cursor[i] += R
    return rows


def twin_answer(i, rows):
    twin.submit(names[i], rows)
    return twin.drain()[names[i]][0]


def check(i, resp, upd, where):
    assert resp.get("ok"), (where, resp)
    assert np.array_equal(np.asarray(resp["nowcast"]), upd.nowcast), where
    assert np.array_equal(np.asarray(resp["forecast_y"]),
                          upd.forecasts["y"]), where


def spawn(tag, extra, trace=None):
    env = dict(os.environ)
    if trace:
        env["DFM_TRACE"] = trace
    err = open(os.path.join(WORK, f"{tag}.err"), "w")
    return subprocess.Popen(
        [sys.executable, "-m", "dfm_tpu.daemon", "--snapshot-dir", SNAP,
         "--journal", JOURNAL, "--snapshot-every", "0"] + extra,
        env=env, stderr=err, text=True)

# -- leg 1: SIGKILL mid-soak -> restart replays to bit-equal ------------
p1 = spawn("p1", ["--listen", ADDR])
cli = DaemonClient(ADDR, timeout=300.0)
assert cli.ping()["pong"]
last_id = None
for q in range(3):
    i = q % len(names)
    rows = next_rows(i)
    last_id = f"leg1-{q}"
    resp = cli.submit(names[i], rows, req_id=last_id)
    check(i, resp, twin_answer(i, rows), f"pre-kill q{q}")
p1.kill()                                # SIGKILL: no drain, no snapshot
p1.wait()
print("leg1: daemon SIGKILLed after 3 answered queries", flush=True)

p2 = spawn("p2", ["--listen", ADDR],
           trace=os.path.join(WORK, "t2.jsonl"))
cli = DaemonClient(ADDR, timeout=300.0)
# The journal survived the kill: a duplicate of an already-served id is
# answered as a duplicate, never re-applied...
dup = cli.submit(names[0], tens[0][2][:R], req_id=last_id)
while dup.get("backpressure"):
    time.sleep(0.2); dup = cli.submit(names[0], tens[0][2][:R],
                                      req_id=last_id)
assert dup.get("duplicate") is True, dup
# ...and fresh queries answer bit-equal to the uninterrupted twin: the
# restarted daemon replayed its journal into the restored snapshot.
for q in range(3):
    i = q % len(names)
    rows = next_rows(i)
    resp = cli.submit(names[i], rows, req_id=f"leg1b-{q}", wait=True)
    check(i, resp, twin_answer(i, rows), f"post-restart q{q}")
print("leg1 PASS: kill -9 -> restart -> journal replay bit-equal "
      "+ dedup survives", flush=True)

# -- leg 2: blue/green handoff under live load --------------------------
stop = threading.Event()
live_log = []                            # (tenant_idx, rows) in order
err_box = []


def hammer():
    # rows=None: pure re-forecasts still run warm EM (state advances
    # every query, so bit-parity across the swap stays a strict check)
    # without consuming append capacity — the successor's warm-up can
    # take minutes and the load must be sustainable for all of it.
    hc = DaemonClient(ADDR, timeout=300.0)
    q = 0
    while not stop.is_set():
        i = q % len(names)
        try:
            resp = hc.submit(names[i], None, req_id=f"ho-{q}", wait=True)
            assert resp.get("ok"), resp
            live_log.append((i, resp))
        except Exception as e:           # any drop fails the leg
            err_box.append(e)
            return
        q += 1
        time.sleep(0.05)


hth = threading.Thread(target=hammer)
hth.start()
time.sleep(0.3)                          # load in flight before the swap
t3 = os.path.join(WORK, "t3.jsonl")
p3 = spawn("p3", ["--takeover", ADDR], trace=t3)
rc2 = p2.wait(timeout=300)               # predecessor drains and exits
assert rc2 == 0, f"predecessor exited rc={rc2}"
time.sleep(1.0)                          # successor serves under load
stop.set()
hth.join(timeout=120)
assert not err_box, f"dropped query during handoff: {err_box[0]}"
assert live_log, "hammer never completed a query"
# Replay the hammer's exact request sequence into the twin: every answer
# across the swap must be bit-equal (successor == uninterrupted).  An
# ack lost in the swap surfaces as a duplicate-flagged retry answer —
# the state change happened exactly once (apply it to the twin; the
# NEXT answers prove parity) but the cached answer may be elided.
n_dup = 0
for i, resp in live_log:
    upd = twin_answer(i, None)
    if resp.get("duplicate"):
        n_dup += 1
        continue
    check(i, resp, upd, "handoff-load")
assert n_dup <= 2, f"{n_dup} duplicate answers: more than one swap?"
post = cli.submit(names[1], next_rows(1), req_id="post-swap", wait=True)
check(1, post, twin_answer(1, tens[1][2][cursor[1] - R:cursor[1]]),
      "post-swap")
print(f"leg2: {len(live_log)} queries served across the swap, 0 dropped,"
      " all bit-equal", flush=True)

cli.shutdown()
rc3 = p3.wait(timeout=120)
assert rc3 == 0, f"successor exited rc={rc3}"
with open(os.path.join(WORK, "p3.err")) as f:
    gap_line = [l for l in f.read().splitlines() if "took over" in l]
assert gap_line, "successor never reported the takeover"
print(f"  {gap_line[0]}", flush=True)

# The successor's trace carries the handoff + its gap: obs.report's
# daemon section is the operator's view of the swap.
s = summarize(t3)
dm = s["daemon"]
assert dm["n_handoffs"] >= 1, dm
assert dm["n_replays"] >= 1, dm
assert dm["handoff_gap_ms"], dm
assert dm["n_requests"] > 0 and dm["queue_depth"], dm
print(f"leg2 PASS: report daemon section: {dm['n_handoffs']} handoff, "
      f"gap p99 {dm['handoff_gap_ms']['p99']:.1f} ms, "
      f"{dm['n_requests']} requests", flush=True)
twin.close()
print("DAEMON SMOKE PASS", flush=True)
PY
rc=$?
set -e
if [ "$rc" -ne 0 ]; then
    echo "--- daemon stderr tails ($WORK) ---" >&2
    tail -n 40 "$WORK"/*.err >&2 || true
    exit "$rc"
fi
rm -rf "$WORK"
exit $rc

#!/usr/bin/env bash
# One-command multi-tenant-scheduler check: run the mixed-shape job-mix
# bench (bench.mixed — fit_jobs vs loop-over-fits) on the fake 8-device
# mesh, assert the scheduler metrics are present and the aggregate
# speedup clears the 3x bar, then gate the recorded run against history
# via obs.regress.  The quick answer to "is shape-bucketed batching
# still paying for itself".
#
# Usage (from the repo root):
#   tools/mixed_smoke.sh                     # gate vs best-of-history
#   DFM_BENCH_SCHED_BACKEND=sharded \
#     DFM_MIXED_MIN_SPEEDUP=0 tools/mixed_smoke.sh   # mesh-sharded leg
#
# The registry lives in .dfm_runs/ (override with DFM_RUNS) — the first
# smoke run records a baseline, later ones are gated.  JAX_PLATFORMS
# defaults to cpu so this never burns real-device time; the fake mesh
# makes the sharded scheduler backend available without real chips.
set -euo pipefail
cd "$(dirname "$0")/.."

RUNS="${DFM_RUNS:-.dfm_runs}"
export DFM_RUNS="$RUNS"
MIN_SPEEDUP="${DFM_MIXED_MIN_SPEEDUP:-3.0}"

# Seed history from the checked-in bench artifacts (idempotent).
python -m dfm_tpu.obs.store backfill --runs "$RUNS" >/dev/null

OUT=$(JAX_PLATFORMS="${JAX_PLATFORMS-cpu}" \
      XLA_FLAGS="${XLA_FLAGS---xla_force_host_platform_device_count=8}" \
      python -m bench.mixed)
echo "$OUT"

RUN_ID=$(printf '%s' "$OUT" | python -c \
    'import json,sys; print(json.loads(sys.stdin.readline())["run_id"])')

# The scheduler metrics must be present in the bench line (and therefore
# in the recorded run, where obs.regress gates them: the aggregate rate
# as higher-is-better; pad_waste_frac and scheduler_overhead_ms as
# lower-is-better with their own noise floors, see obs/store.py) — and
# the batched programs must actually beat the loop-over-fits baseline.
printf '%s' "$OUT" | MIN_SPEEDUP="$MIN_SPEEDUP" python -c '
import json, os, sys
d = json.loads(sys.stdin.readline())
missing = [k for k in ("aggregate_mixed_iters_per_sec", "pad_waste_frac",
                       "scheduler_overhead_ms", "speedup_vs_looped")
           if d.get(k) is None]
assert not missing, f"mixed smoke FAILED: bench line missing {missing}"
need = float(os.environ["MIN_SPEEDUP"])
got = float(d["speedup_vs_looped"])
assert got >= need, (
    f"mixed smoke FAILED: scheduler speedup {got}x < {need}x vs looped")
print("mixed smoke OK: %d jobs in %d buckets, %.2fx vs looped, "
      "pad waste %.1f%%, overhead %.1f ms"
      % (d["n_jobs"], d["n_buckets"], got,
         100 * d["pad_waste_frac"], d["scheduler_overhead_ms"]))'

echo "--- mixed gate (run $RUN_ID vs ${*:-history}) ---" >&2
python -m dfm_tpu.obs.regress "$RUN_ID" --runs "$RUNS" "$@"

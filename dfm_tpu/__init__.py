"""TPU-native dynamic-factor-model framework.

A from-scratch JAX/XLA reimplementation of the capability surface of
``joidegn/DynamicFactorModels.jl`` (see SURVEY.md): PCA + EM estimation of
static/AR(1)/mixed-frequency/time-varying-loadings/stochastic-volatility
dynamic factor models behind a ``fit(model, data, backend=...)`` dispatch
seam, with a NumPy float64 reference backend and a TPU-first execution path
(``lax.scan`` Kalman recursions, information-form sharded EM over a device
mesh).
"""

from .api import (DynamicFactorModel, FitResult, fit, fit_jobs, forecast,
                  Backend, CPUBackend, TPUBackend, ShardedBackend,
                  register_backend, get_backend)
from .estim.select import (bai_ng_ic, select_n_factors, select_n_factors_em,
                           targeted_predictors)
from .estim.evaluate import oos_evaluate
from .estim.batched import DFMBatchSpec, BatchFitResult, fit_many
from .sched import Job, JobResult
from .serve import NowcastSession, SessionUpdate, open_session
from .fleet import SessionFleet, open_fleet, restore_fleet

__version__ = "0.1.0"

__all__ = [
    "DynamicFactorModel", "FitResult", "fit", "forecast",
    "Backend", "CPUBackend", "TPUBackend", "ShardedBackend",
    "register_backend", "get_backend",
    "bai_ng_ic", "select_n_factors", "select_n_factors_em",
    "targeted_predictors", "oos_evaluate",
    "DFMBatchSpec", "BatchFitResult", "fit_many",
    "fit_jobs", "Job", "JobResult",
    "NowcastSession", "SessionUpdate", "open_session",
    "SessionFleet", "open_fleet", "restore_fleet",
    "__version__",
]

"""Distributed layer (SURVEY.md L3): series-sharded execution over a 1-D
device mesh with psum collectives — the TPU-native equivalent of the
reference's (nonexistent) multi-process story, per BASELINE.json:5."""

from .mesh import SERIES_AXIS, make_mesh, pad_panel, unpad_rows
from .sharded import (ShardedEM, sharded_em_step, sharded_em_scan,
                      sharded_em_fit, sharded_filter_smoother)
from .time_sharded import (TIME_AXIS, make_time_mesh, pit_qr_time_sharded,
                           pit_qr_filter_time_sharded)
from .batched import (BATCH_AXIS, make_batch_mesh, run_batched_em_sharded,
                      batched_smooth_sharded)
from .sharded_mf import sharded_mf_fit
from .sharded_sv import sharded_sv_filter
from .sharded_tvl import sharded_tvl_fit

__all__ = [
    "SERIES_AXIS", "make_mesh", "pad_panel", "unpad_rows",
    "TIME_AXIS", "make_time_mesh", "pit_qr_time_sharded",
    "pit_qr_filter_time_sharded",
    "ShardedEM", "sharded_em_step", "sharded_em_scan", "sharded_em_fit",
    "sharded_filter_smoother", "sharded_mf_fit", "sharded_sv_filter",
    "sharded_tvl_fit",
    "BATCH_AXIS", "make_batch_mesh", "run_batched_em_sharded",
    "batched_smooth_sharded",
]

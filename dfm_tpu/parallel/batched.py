"""Batch-axis sharding for the fused multi-fit EM engine.

The batched problems of ``estim.batched`` are INDEPENDENT — no collective
ever crosses problem boundaries — so sharding is embarrassingly simple: a
1-D mesh over a ``"batch"`` axis, ``shard_map`` around the same pure chunk
core the single-device path jits, and batch padding (copies of problem 0,
frozen from the start via the PADDED carry state) when B is not a multiple
of the device count.  Each device runs B/D full EM problems; the host
driver, convergence logic, health records, and robust retry seam are all
shared with ``estim.batched.run_batched_em`` via its ``scan_impl`` /
``state0`` hooks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..estim.batched import (PADDED, _em_chunk_core, _smooth_core,
                             run_batched_em)
from .mesh import shard_map

__all__ = ["BATCH_AXIS", "make_batch_mesh", "run_batched_em_sharded",
           "batched_smooth_sharded"]

BATCH_AXIS = "batch"


def make_batch_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)} "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=K)")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (BATCH_AXIS,))


def _pad_batch(Y, p0, n_shards: int, hetero=None):
    """Pad the batch axis to a multiple of n_shards with copies of problem
    0 (data AND params — a valid problem, so no NaN risk; the driver
    freezes the pads via the PADDED state and the caller slices them off).
    A ``hetero`` bundle pads the same way: every leaf leads with B, and
    pad rows never act (PADDED problems are frozen from the start)."""
    B = Y.shape[0]
    n_pad = (-B) % n_shards
    if n_pad == 0:
        return Y, p0, hetero, 0
    rep = lambda x: jnp.concatenate(
        [x, jnp.repeat(x[:1], n_pad, axis=0)], axis=0)
    hp = (None if hetero is None
          else jax.tree_util.tree_map(rep, hetero))
    return rep(Y), jax.tree_util.tree_map(rep, p0), hp, n_pad


@partial(jax.jit, static_argnames=("cfg", "n_iters", "mesh"))
def _sharded_chunk_impl(Y, carry, tol, noise_floor, cfg, n_iters, mesh,
                        hetero=None):
    """shard_map'd twin of ``estim.batched._em_chunk_impl``: the same pure
    chunk core, batch axis split over the mesh, NO collectives (the
    problems are independent; specs are pytree prefixes, so P("batch")
    covers every SSMParams leaf).  ``hetero`` (mixed-shape bucket mode)
    shards with the same prefix spec — every ``Hetero`` leaf leads with B
    — in a separate trace so the default program stays untouched."""
    Pb = P(BATCH_AXIS)
    if hetero is None:
        body = lambda Yb, c, t, nf: _em_chunk_core(Yb, c, t, nf, cfg,
                                                   n_iters)
        return shard_map(
            body, mesh=mesh,
            in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P()),
            out_specs=((Pb, Pb, Pb, Pb, Pb), P(None, BATCH_AXIS)),
        )(Y, carry, tol, noise_floor)
    body = lambda Yb, c, t, nf, h: _em_chunk_core(Yb, c, t, nf, cfg,
                                                  n_iters, hetero=h)
    return shard_map(
        body, mesh=mesh,
        in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), Pb),
        out_specs=((Pb, Pb, Pb, Pb, Pb), P(None, BATCH_AXIS)),
    )(Y, carry, tol, noise_floor, hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters", "mesh"))
def _sharded_chunk_metrics_impl(Y, carry, tol, noise_floor, cfg, n_iters,
                                mesh, hetero=None):
    """Metrics twin of ``_sharded_chunk_impl``: the chunk core with its
    per-iteration (B, 3) metrics block scanned out.  Both scan outputs are
    time-major with the batch on axis 1, hence the P(None, "batch") specs;
    still no collectives (the per-problem max param-update is local to each
    problem's shard)."""
    Pb = P(BATCH_AXIS)
    if hetero is None:
        body = lambda Yb, c, t, nf: _em_chunk_core(Yb, c, t, nf, cfg,
                                                   n_iters,
                                                   with_metrics=True)
        return shard_map(
            body, mesh=mesh,
            in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P()),
            out_specs=((Pb, Pb, Pb, Pb, Pb),
                       (P(None, BATCH_AXIS), P(None, BATCH_AXIS))),
        )(Y, carry, tol, noise_floor)
    body = lambda Yb, c, t, nf, h: _em_chunk_core(
        Yb, c, t, nf, cfg, n_iters, with_metrics=True, hetero=h)
    return shard_map(
        body, mesh=mesh,
        in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), Pb),
        out_specs=((Pb, Pb, Pb, Pb, Pb),
                   (P(None, BATCH_AXIS), P(None, BATCH_AXIS))),
    )(Y, carry, tol, noise_floor, hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters", "mesh"))
def _sharded_chunk_capped_impl(Y, carry, tol, noise_floor, n_active, cfg,
                               n_iters, mesh, hetero=None):
    """Bucketed twin of ``_sharded_chunk_impl``: STATIC ``n_iters`` fused
    length, TRACED ``n_active`` cap (replicated scalar, P() spec) — one
    executable per mesh size serves every tail-chunk length."""
    Pb = P(BATCH_AXIS)
    if hetero is None:
        body = lambda Yb, c, t, nf, na: _em_chunk_core(Yb, c, t, nf, cfg,
                                                       n_iters, n_active=na)
        return shard_map(
            body, mesh=mesh,
            in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), P()),
            out_specs=((Pb, Pb, Pb, Pb, Pb), P(None, BATCH_AXIS)),
        )(Y, carry, tol, noise_floor, n_active)
    body = lambda Yb, c, t, nf, na, h: _em_chunk_core(
        Yb, c, t, nf, cfg, n_iters, n_active=na, hetero=h)
    return shard_map(
        body, mesh=mesh,
        in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), P(), Pb),
        out_specs=((Pb, Pb, Pb, Pb, Pb), P(None, BATCH_AXIS)),
    )(Y, carry, tol, noise_floor, n_active, hetero)


@partial(jax.jit, static_argnames=("cfg", "n_iters", "mesh"))
def _sharded_chunk_capped_metrics_impl(Y, carry, tol, noise_floor, n_active,
                                       cfg, n_iters, mesh, hetero=None):
    Pb = P(BATCH_AXIS)
    if hetero is None:
        body = lambda Yb, c, t, nf, na: _em_chunk_core(
            Yb, c, t, nf, cfg, n_iters, with_metrics=True, n_active=na)
        return shard_map(
            body, mesh=mesh,
            in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), P()),
            out_specs=((Pb, Pb, Pb, Pb, Pb),
                       (P(None, BATCH_AXIS), P(None, BATCH_AXIS))),
        )(Y, carry, tol, noise_floor, n_active)
    body = lambda Yb, c, t, nf, na, h: _em_chunk_core(
        Yb, c, t, nf, cfg, n_iters, with_metrics=True, n_active=na,
        hetero=h)
    return shard_map(
        body, mesh=mesh,
        in_specs=(Pb, (Pb, Pb, Pb, Pb, Pb), P(), P(), P(), Pb),
        out_specs=((Pb, Pb, Pb, Pb, Pb),
                   (P(None, BATCH_AXIS), P(None, BATCH_AXIS))),
    )(Y, carry, tol, noise_floor, n_active, hetero)


def run_batched_em_sharded(Y, p0, cfg, max_iters: int, tol: float,
                           fused_chunk: int = 8,
                           n_devices: Optional[int] = None, policy=None,
                           with_metrics: bool = False, pipeline=None,
                           hetero=None):
    """Sharded batched-EM driver: same contract as ``run_batched_em``
    (params, per-problem traces, converged, p_iters, healths — plus the
    metrics block when ``with_metrics``), with the batch axis laid across
    the mesh so B also scales across chips.  ``pipeline`` passes through
    to the shared driver with this module's capped twins, so speculative
    issue and bucketed reuse work identically here.  ``hetero`` (a
    ``Hetero`` bundle) rides the same batch padding as Y/p0 — pad rows
    are PADDED-frozen copies of problem 0 — and the shared driver routes
    it into the hetero branch of the twins."""
    mesh = make_batch_mesh(n_devices)
    D = mesh.devices.size
    B = Y.shape[0]
    Yp, pp, hp, n_pad = _pad_batch(jnp.asarray(Y), p0, D, hetero=hetero)
    state0 = np.concatenate([np.zeros(B, np.int32),
                             np.full(n_pad, PADDED, np.int32)])
    impl = partial(_sharded_chunk_impl, mesh=mesh)
    impl_m = partial(_sharded_chunk_metrics_impl, mesh=mesh)
    impl_c = partial(_sharded_chunk_capped_impl, mesh=mesh)
    impl_cm = partial(_sharded_chunk_capped_metrics_impl, mesh=mesh)
    # Telemetry identity for the shared driver's dispatch spans: the
    # sharded twin is a DIFFERENT logical program (its own compile cache
    # entry per device count), so it gets its own name and a key carrying
    # the mesh size.
    for f in (impl, impl_m, impl_c, impl_cm):
        f.trace_name = "sharded_batched_em_chunk"
        f.trace_key = f"mesh{D}"
        f.trace_engine = "sharded_batched_em"
    out = run_batched_em(
        Yp, pp, cfg, max_iters, tol, fused_chunk=fused_chunk, policy=policy,
        scan_impl=impl, state0=state0, with_metrics=with_metrics,
        scan_impl_metrics=impl_m, pipeline=pipeline,
        scan_impl_capped=impl_c, scan_impl_capped_metrics=impl_cm,
        hetero=hp)
    if with_metrics:
        p, lls_list, conv, p_iters, healths, metrics = out
    else:
        p, lls_list, conv, p_iters, healths = out
        metrics = None
    if n_pad:
        p = jax.tree_util.tree_map(lambda x: x[:B], p)
        lls_list, conv = lls_list[:B], conv[:B]
        p_iters, healths = p_iters[:B], healths[:B]
        if metrics is not None:
            metrics = metrics[:, :B]
    if with_metrics:
        return p, lls_list, conv, p_iters, healths, metrics
    return p, lls_list, conv, p_iters, healths


@partial(jax.jit, static_argnames=("mesh",))
def _sharded_smooth_impl(Y, p, mesh, hetero=None):
    Pb = P(BATCH_AXIS)
    if hetero is None:
        return shard_map(_smooth_core, mesh=mesh, in_specs=(Pb, Pb),
                         out_specs=(Pb, Pb))(Y, p)
    body = lambda Yb, pb, h: _smooth_core(Yb, pb, hetero=h)
    return shard_map(body, mesh=mesh, in_specs=(Pb, Pb, Pb),
                     out_specs=(Pb, Pb))(Y, p, hetero)


def batched_smooth_sharded(Y, p, n_devices: Optional[int] = None,
                           hetero=None):
    """Batched filter+smoother with the batch axis across the mesh."""
    mesh = make_batch_mesh(n_devices)
    D = mesh.devices.size
    Yp, pp, hp, n_pad = _pad_batch(jnp.asarray(Y), p, D, hetero=hetero)
    x_sm, P_sm = _sharded_smooth_impl(Yp, pp, mesh, hp)
    if n_pad:
        B = Y.shape[0]
        x_sm, P_sm = x_sm[:B], P_sm[:B]
    return x_sm, P_sm

"""Series-sharded time-varying-loadings estimation.

The TVL model shards even better than the plain DFM: the B-step's N
independent loading chains and the R/tau2 updates are entirely shard-local
(each device scans its own (n_local, k) random-walk chains), so per round
the ONLY communication is the psum of the A-step's k-sized observation
reductions — while the dominant compute, the (N, k, k) loading-covariance
scans, splits N-ways.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..estim.em import run_em_loop, noise_floor_for
from ..models.tv_loadings import (TVLParams, TVLResult, TVLSpec,
                                  tvl_round_core)
from .mesh import SERIES_AXIS, make_mesh

__all__ = ["sharded_tvl_fit"]


def _psum_tree(tree):
    return jax.tree.map(lambda x: lax.psum(x, SERIES_AXIS), tree)


@partial(jax.jit, static_argnames=("mesh", "spec"))
def _sharded_tvl_round_impl(Y, W, Lam_t, Lam0, tau2, R, A, Q, mu0, P0,
                            mesh: Mesh, spec: TVLSpec):
    def body(Y_s, W_s, Lam_t_s, Lam0_s, tau2_s, R_s, A, Q, mu0, P0):
        p_s = TVLParams(Lam0_s, tau2_s, A, Q, R_s, mu0, P0)
        Lam_t_new, p_new, ll, F = tvl_round_core(
            Y_s, W_s, Lam_t_s, p_s, spec, reduce_tree=_psum_tree)
        return (Lam_t_new, p_new.Lam0, p_new.tau2, p_new.R,
                p_new.A, p_new.Q, ll, F)

    col = P(None, SERIES_AXIS)
    mapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(col, col, P(None, SERIES_AXIS, None),
                  P(SERIES_AXIS, None), P(SERIES_AXIS), P(SERIES_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(None, SERIES_AXIS, None), P(SERIES_AXIS, None),
                   P(SERIES_AXIS), P(SERIES_AXIS), P(), P(), P(), P()),
        check_vma=False)
    return mapped(Y, W, Lam_t, Lam0, tau2, R, A, Q, mu0, P0)


def sharded_tvl_fit(Y: np.ndarray, spec: TVLSpec,
                    mask: Optional[np.ndarray] = None,
                    mesh: Optional[Mesh] = None,
                    dtype=jnp.float32, callback=None,
                    init: Optional[TVLParams] = None) -> TVLResult:
    """Multi-device ``tvl_fit``; mirrors its contract."""
    from ..backends.cpu_ref import pca_init
    from ..utils.data import build_mask
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    k = spec.n_factors
    mesh = mesh if mesh is not None else make_mesh()
    D = int(mesh.devices.size)

    W = build_mask(Y)
    if mask is not None:
        W = W * np.asarray(mask, np.float64)
    Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
    if init is None:
        any_missing = bool((W == 0).any())
        p0 = pca_init(Yz, k, mask=W if any_missing else None)
        init = TVLParams(
            Lam0=jnp.asarray(p0.Lam), tau2=jnp.full((N,), 1e-4),
            A=jnp.asarray(p0.A), Q=jnp.asarray(p0.Q), R=jnp.asarray(p0.R),
            mu0=jnp.asarray(p0.mu0), P0=jnp.asarray(p0.P0))

    pad = (-N) % D
    Np = N + pad
    if pad:
        Yz = np.concatenate([Yz, np.zeros((T, pad))], axis=1)
        W = np.concatenate([W, np.zeros((T, pad))], axis=1)
    Lam0 = np.concatenate(
        [np.asarray(init.Lam0, np.float64), np.zeros((pad, k))], axis=0)
    tau2 = np.concatenate(
        [np.asarray(init.tau2, np.float64), np.full(pad, 1e-4)])
    R = np.concatenate([np.asarray(init.R, np.float64), np.ones(pad)])

    state = {
        "Y": jnp.asarray(Yz, dtype), "W": jnp.asarray(W, dtype),
        "Lam_t": jnp.broadcast_to(jnp.asarray(Lam0, dtype), (T, Np, k)),
        "Lam0": jnp.asarray(Lam0, dtype), "tau2": jnp.asarray(tau2, dtype),
        "R": jnp.asarray(R, dtype),
        "A": jnp.asarray(init.A, dtype), "Q": jnp.asarray(init.Q, dtype),
        "mu0": jnp.asarray(init.mu0, dtype),
        "P0": jnp.asarray(init.P0, dtype), "F": None,
    }

    prev = dict(state)
    prev2 = dict(state)

    def step(it):
        prev2.update(prev)
        prev.update(state)
        out = _sharded_tvl_round_impl(
            state["Y"], state["W"], state["Lam_t"], state["Lam0"],
            state["tau2"], state["R"], state["A"], state["Q"],
            state["mu0"], state["P0"], mesh, spec)
        (state["Lam_t"], state["Lam0"], state["tau2"], state["R"],
         state["A"], state["Q"], ll, state["F"]) = out
        return ll, None

    # True-f32 matmul products, as in tvl_fit (bf16 default is unusable).
    with jax.default_matmul_precision("highest"):
        lls, converged, em_state = run_em_loop(
            step, spec.n_rounds, spec.tol, callback,
            noise_floor=noise_floor_for(dtype, state["Y"].size))
    if em_state == "diverged":
        # Drop at round j <- bad update in j-1: the state entering j-1 is
        # the last pre-drop one (its successor if that one predates F).
        best = prev2 if prev2.get("F") is not None else prev
        if best.get("F") is not None:
            state.update(best)

    Lam_t = np.asarray(state["Lam_t"], np.float64)[:, :N]
    F = np.asarray(state["F"], np.float64)
    common = np.einsum("tnk,tk->tn", Lam_t, F)
    p_final = TVLParams(
        Lam0=jnp.asarray(np.asarray(state["Lam0"], np.float64)[:N]),
        tau2=jnp.asarray(np.asarray(state["tau2"], np.float64)[:N]),
        A=jnp.asarray(np.asarray(state["A"], np.float64)),
        Q=jnp.asarray(np.asarray(state["Q"], np.float64)),
        R=jnp.asarray(np.asarray(state["R"], np.float64)[:N]),
        mu0=jnp.asarray(np.asarray(state["mu0"], np.float64)),
        P0=jnp.asarray(np.asarray(state["P0"], np.float64)))
    return TVLResult(params=p_final, loadings=Lam_t, factors=F,
                     logliks=np.asarray(lls), common=common,
                     converged=converged, spec=spec)

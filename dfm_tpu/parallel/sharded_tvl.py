"""Series-sharded time-varying-loadings estimation.

The TVL model shards even better than the plain DFM: the B-step's N
independent loading chains and the R/tau2 updates are entirely shard-local
(each device scans its own (n_local, k) random-walk chains), so per round
the ONLY communication is the psum of the A-step's k-sized observation
reductions — while the dominant compute, the (N, k, k) loading-covariance
scans, splits N-ways.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..estim.em import run_em_chunked, noise_floor_for
from ..models.tv_loadings import (TVLParams, TVLResult, TVLSpec,
                                  factor_pass_tv, tvl_round_core)
from .mesh import shard_map, SERIES_AXIS, make_mesh

__all__ = ["sharded_tvl_fit"]


def _psum_tree(tree):
    return jax.tree.map(lambda x: lax.psum(x, SERIES_AXIS), tree)


@partial(jax.jit, static_argnames=("mesh", "spec", "n_rounds"))
def _sharded_tvl_scan_impl(Y, W, carry, mu0, P0, mesh: Mesh, spec: TVLSpec,
                           n_rounds: int):
    """n alternation rounds fused into ONE XLA program: ``lax.scan`` over the
    shard_map body (the TVL analog of ``sharded._sharded_em_scan_impl``;
    VERDICT r4 item 2).  ``carry`` is the sharded (Lam_t, Lam0, tau2, R, A, Q)
    round state; returns (carry', logliks (n,))."""
    def body(Y_s, W_s, Lam_t_s, Lam0_s, tau2_s, R_s, A, Q, mu0, P0):
        def it(c, _):
            Lam_c, Lam0_c, tau2_c, R_c, A_c, Q_c = c
            p_c = TVLParams(Lam0_c, tau2_c, A_c, Q_c, R_c, mu0, P0)
            Lam_new, p_new, ll, _ = tvl_round_core(
                Y_s, W_s, Lam_c, p_c, spec, reduce_tree=_psum_tree)
            return (Lam_new, p_new.Lam0, p_new.tau2, p_new.R,
                    p_new.A, p_new.Q), ll

        c0 = (Lam_t_s, Lam0_s, tau2_s, R_s, A, Q)
        c_f, lls = lax.scan(it, c0, None, length=n_rounds)
        return c_f + (lls,)

    col = P(None, SERIES_AXIS)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(col, col, P(None, SERIES_AXIS, None),
                  P(SERIES_AXIS, None), P(SERIES_AXIS), P(SERIES_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(None, SERIES_AXIS, None), P(SERIES_AXIS, None),
                   P(SERIES_AXIS), P(SERIES_AXIS), P(), P(), P()))
    out = mapped(Y, W, *carry, mu0, P0)
    return out[:6], out[6]


@partial(jax.jit, static_argnames=("mesh",))
def _sharded_tvl_factors_impl(Y, W, Lam_t, Lam0, tau2, R, A, Q, mu0, P0,
                              mesh: Mesh):
    """Factor path at fixed (Lam_t, params) — the reporting pass (A-step
    only, no B-step/M-step work; the sharded analog of
    ``tv_loadings._tvl_factors``)."""
    def body(Y_s, W_s, Lam_t_s, Lam0_s, tau2_s, R_s, A, Q, mu0, P0):
        p_s = TVLParams(Lam0_s, tau2_s, A, Q, R_s, mu0, P0)
        _, sm = factor_pass_tv(Y_s, Lam_t_s, p_s, mask=W_s,
                               reduce_tree=_psum_tree)
        return sm.x_sm

    col = P(None, SERIES_AXIS)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(col, col, P(None, SERIES_AXIS, None),
                  P(SERIES_AXIS, None), P(SERIES_AXIS), P(SERIES_AXIS),
                  P(), P(), P(), P()),
        out_specs=P())
    return mapped(Y, W, Lam_t, Lam0, tau2, R, A, Q, mu0, P0)


def sharded_tvl_fit(Y: np.ndarray, spec: TVLSpec,
                    mask: Optional[np.ndarray] = None,
                    mesh: Optional[Mesh] = None,
                    dtype=jnp.float32, callback=None,
                    init: Optional[TVLParams] = None,
                    fused_chunk: int = 8) -> TVLResult:
    """Multi-device ``tvl_fit``; mirrors its contract, including the fused
    ``fused_chunk``-round chunks (one XLA dispatch per chunk)."""
    from ..backends.cpu_ref import pca_init
    from ..utils.data import build_mask
    Y = np.asarray(Y, np.float64)
    T, N = Y.shape
    k = spec.n_factors
    mesh = mesh if mesh is not None else make_mesh()
    D = int(mesh.devices.size)

    W = build_mask(Y)
    if mask is not None:
        W = W * np.asarray(mask, np.float64)
    Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
    if init is None:
        any_missing = bool((W == 0).any())
        p0 = pca_init(Yz, k, mask=W if any_missing else None)
        init = TVLParams(
            Lam0=jnp.asarray(p0.Lam), tau2=jnp.full((N,), 1e-4),
            A=jnp.asarray(p0.A), Q=jnp.asarray(p0.Q), R=jnp.asarray(p0.R),
            mu0=jnp.asarray(p0.mu0), P0=jnp.asarray(p0.P0))

    pad = (-N) % D
    Np = N + pad
    if pad:
        Yz = np.concatenate([Yz, np.zeros((T, pad))], axis=1)
        W = np.concatenate([W, np.zeros((T, pad))], axis=1)
    Lam0 = np.concatenate(
        [np.asarray(init.Lam0, np.float64), np.zeros((pad, k))], axis=0)
    tau2 = np.concatenate(
        [np.asarray(init.tau2, np.float64), np.full(pad, 1e-4)])
    R = np.concatenate([np.asarray(init.R, np.float64), np.ones(pad)])

    Yj = jnp.asarray(Yz, dtype)
    Wj = jnp.asarray(W, dtype)
    mu0j = jnp.asarray(init.mu0, dtype)
    P0j = jnp.asarray(init.P0, dtype)
    carry = (jnp.broadcast_to(jnp.asarray(Lam0, dtype), (T, Np, k)),
             jnp.asarray(Lam0, dtype), jnp.asarray(tau2, dtype),
             jnp.asarray(R, dtype), jnp.asarray(init.A, dtype),
             jnp.asarray(init.Q, dtype))

    def unpad_params(c):
        """Chunk-entry carry -> unpadded TVLParams (tvl_fit's callback
        contract)."""
        return TVLParams(
            Lam0=jnp.asarray(np.asarray(c[1], np.float64)[:N]),
            tau2=jnp.asarray(np.asarray(c[2], np.float64)[:N]),
            A=jnp.asarray(np.asarray(c[4], np.float64)),
            Q=jnp.asarray(np.asarray(c[5], np.float64)),
            R=jnp.asarray(np.asarray(c[3], np.float64)[:N]),
            mu0=jnp.asarray(np.asarray(mu0j, np.float64)),
            P0=jnp.asarray(np.asarray(P0j, np.float64)))

    cb = None
    if callback is not None:
        cache: dict = {}

        def cb(it, ll, entry, **kw):
            # One host transfer per chunk: run_em_chunked re-passes the same
            # chunk-entry object for every iteration of a chunk.
            key = id(entry)
            if key not in cache:
                cache.clear()
                cache[key] = unpad_params(entry)
            callback(it, ll, cache[key], **kw)
        cb.wants_params_iter = getattr(callback, "wants_params_iter", False)

    # True-f32 matmul products, as in tvl_fit (bf16 default is unusable).
    with jax.default_matmul_precision("highest"):
        def scan_fn(c, n):
            c_new, lls = _sharded_tvl_scan_impl(Yj, Wj, c, mu0j, P0j,
                                                mesh, spec, n)
            return c_new, lls, None

        floor = noise_floor_for(dtype, Yj.size)
        carry, lls, converged, _ = run_em_chunked(
            scan_fn, carry, spec.n_rounds, spec.tol,
            floor, cb, fused_chunk)

        # Final A-pass at the final state (factors consistent with the
        # returned loadings/params — same semantics as tvl_fit).
        F = _sharded_tvl_factors_impl(Yj, Wj, *carry, mu0j, P0j, mesh)
    F = np.asarray(F, np.float64)

    Lam_t = np.asarray(carry[0], np.float64)[:, :N]
    common = np.einsum("tnk,tk->tn", Lam_t, F)
    from ..robust.health import health_from_trace
    return TVLResult(params=unpad_params(carry), loadings=Lam_t, factors=F,
                     logliks=np.asarray(lls), common=common,
                     converged=converged, spec=spec,
                     health=health_from_trace(lls, floor))

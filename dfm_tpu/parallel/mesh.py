"""Device-mesh construction and series-axis padding helpers.

The framework's one distributed axis is the cross-section: the N series of the
panel are sharded over a 1-D ``jax.sharding.Mesh`` axis named ``"series"``
(SURVEY.md section 2.3).  Time stays sequential (scan) and the k-dim state is
replicated, so a 1-D mesh is the whole topology — on real hardware it lays the
series blocks across ICI neighbors and every collective is a single psum ring.

Padding: shard_map needs N divisible by the mesh size.  Padded series are
given zero loadings, unit variance, zero data and a zero mask row, so they
contribute exactly nothing to any reduction (b, C, c2, n, ldR, M-step sums) —
equivalence with the unpadded run is a unit test.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["SERIES_AXIS", "make_mesh", "pad_panel", "unpad_rows", "shard_map"]

SERIES_AXIS = "series"


def shard_map(body, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; older releases
    only have ``jax.experimental.shard_map.shard_map(..., check_rep=...)``.
    Replication checking is off either way: the per-shard bodies reduce with
    explicit psums and several outputs are only replicated post-collective,
    which the static checker cannot prove.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)} "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=K)")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (SERIES_AXIS,))


def pad_panel(Y: np.ndarray, mask: Optional[np.ndarray], Lam: np.ndarray,
              R: np.ndarray, n_shards: int
              ) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray,
                         np.ndarray, int]:
    """Pad the series axis of (Y, mask, Lam, R) to a multiple of n_shards.

    Returns (Y, mask, Lam, R, n_pad).  If padding is added and mask was None,
    a mask is materialized (ones for real series, zeros for pads) so the
    padded series drop out of every reduction.
    """
    T, N = Y.shape
    n_pad = (-N) % n_shards
    if n_pad == 0:
        return Y, mask, Lam, R, 0
    k = Lam.shape[1]
    Yp = np.concatenate([Y, np.zeros((T, n_pad), Y.dtype)], axis=1)
    if mask is None:
        mask = np.ones((T, N), Y.dtype)
    Wp = np.concatenate([mask, np.zeros((T, n_pad), mask.dtype)], axis=1)
    Lp = np.concatenate([Lam, np.zeros((n_pad, k), Lam.dtype)], axis=0)
    Rp = np.concatenate([R, np.ones(n_pad, R.dtype)], axis=0)
    return Yp, Wp, Lp, Rp, n_pad


def unpad_rows(x: np.ndarray, n_pad: int) -> np.ndarray:
    """Drop trailing padded rows (series axis is axis 0 for Lam/R)."""
    return x[: x.shape[0] - n_pad] if n_pad else x

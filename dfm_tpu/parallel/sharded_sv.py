"""Series-sharded Rao-Blackwellized particle filter for the SV-DFM.

Config S5 (BASELINE.json:11) is defined at 10 000 series — the cross-section,
not the particle cloud, is where the memory and FLOPs grow, so the series
axis is the one sharded (same 1-D ``"series"`` mesh as the plain DFM).

Layout per device: its own columns of the panel ``Y (T, n_local)``, rows of
``Lam (n_local, k)`` and ``R (n_local,)``; the particle cloud (x, P, h, logW)
is REPLICATED — every device propagates the identical M particles from the
identical PRNG key, so no particle state ever crosses the network.  The only
collectives are:

  - once, before the scan: psum of the k-sized stats C = Lam'R^{-1}Lam and
    B = Y R^{-1} Lam (the ``"expanded"`` weight path needs nothing else —
    ZERO in-scan collectives);
  - per step, in the default ``"residual"`` weight path: psum of the
    per-particle residual reductions c2 (M,) and u = Lam'R^{-1}v (M, k) —
    an O(M k) payload independent of N.

The scan body is the SAME function the single-device filter runs
(``models.sv._rbpf_scan``) with the reduction hook bound to psum, so matched
PRNG keys give matching particle paths and resampling decisions up to psum
rounding — asserted against the single-device filter in
``tests/test_sharded_sv.py`` on the fake 8-device mesh.

Padded series (N not divisible by the mesh) get Lam = 0, R = 1, Y = 0: their
residual is identically zero, so they drop out of every reduction; the
particle-independent loglik constant is assembled host-side from the UNPADDED
R, exactly as in ``sv_filter``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.sv import (SVSpec, SVResult, _rbpf_scan, _as_sigma_vec,
                         _host_lls)
from ..ssm.params import SSMParams
from .mesh import shard_map, SERIES_AXIS, make_mesh, pad_panel

__all__ = ["sharded_sv_filter"]


@partial(jax.jit, static_argnames=("mesh", "k", "M", "ess_frac", "residual",
                                   "store_paths"))
def _sharded_sv_impl(Y, Lam, R, A, mu0, P0, h_center, sigma_h, h0_scale, key,
                     mesh: Mesh, k: int, M: int, ess_frac: float,
                     residual: bool, store_paths: bool):
    def body(Y_s, Lam_s, R_s, A, mu0, P0, h_center, sigma_h, h0_scale, key):
        def psum(x):
            return lax.psum(x, SERIES_AXIS)

        G0 = Lam_s * (1.0 / R_s)[:, None]
        C = psum(Lam_s.T @ G0)                        # global (k, k)
        B = psum(Y_s @ G0)                            # global (T, k)
        return _rbpf_scan(Y_s, Lam_s, R_s, C, B, A, mu0, P0, h_center,
                          sigma_h, h0_scale, key, k=k, M=M,
                          ess_frac=ess_frac, residual=residual,
                          store_paths=store_paths, reduce_fn=psum)

    rep = P()
    # _rbpf_scan always returns a 7-tuple; the last two entries are None
    # when store_paths=False (leafless subtrees — any spec matches).
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(SERIES_AXIS, None), P(SERIES_AXIS),
                  rep, rep, rep, rep, rep, rep, rep),
        out_specs=(rep,) * 7)
    return mapped(Y, Lam, R, A, mu0, P0, h_center, sigma_h, h0_scale, key)


def sharded_sv_filter(Y, p: SSMParams, spec: SVSpec,
                      key: Optional[jax.Array] = None,
                      h_center: Optional[jax.Array] = None,
                      sigma_h=None, store_paths: bool = True,
                      mesh: Optional[Mesh] = None) -> SVResult:
    """Multi-device ``sv_filter``; mirrors its contract (see ``models.sv``).

    Pads the series axis to the mesh size automatically; the returned
    ``SVResult`` is in the same units as the single-device filter.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    mesh = mesh if mesh is not None else make_mesh()
    dtype = Y.dtype if hasattr(Y, "dtype") else jnp.float32
    p = p.astype(dtype)
    if h_center is None:
        h_center = jnp.log(jnp.clip(jnp.diagonal(p.Q), 1e-8, None))
    sig = _as_sigma_vec(spec.sigma_h if sigma_h is None else sigma_h,
                        spec.n_factors, dtype)
    h0s = jnp.asarray(spec.h0_scale, dtype)

    R_unpadded = np.asarray(p.R, np.float64)
    n_pad = (-Y.shape[1]) % int(mesh.devices.size)
    if n_pad:
        Yp, _, Lp, Rp, _ = pad_panel(np.asarray(Y, np.float64), None,
                                     np.asarray(p.Lam, np.float64),
                                     R_unpadded, int(mesh.devices.size))
    else:
        # No padding: consume the caller's arrays as-is.  Repeated filter
        # passes (particle-EM E-steps, the S5 pass timing) hand a DEVICE-
        # resident panel here, and the unconditional np.asarray above paid
        # a device->host->device round trip of the 40 MB panel per call —
        # measured 2.4 -> 0.31 passes/sec at the S5 shape on a 1-shard
        # mesh (the whole r4 "sharded SV is slower" artifact).
        Yp, Lp, Rp = Y, p.Lam, p.R
    # True-f32 matmul products, matching sv_filter (bf16 default distorts
    # the particle weights at large N).
    with jax.default_matmul_precision("highest"):
        ll_rel, f_mean, h_mean, ess, n_rs, h_hist, logw_hist = \
            _sharded_sv_impl(
                jnp.asarray(Yp, dtype), jnp.asarray(Lp, dtype),
                jnp.asarray(Rp, dtype), p.A, p.mu0, p.P0,
                jnp.asarray(h_center, dtype), sig, h0s, key, mesh,
                k=spec.n_factors, M=spec.n_particles,
                ess_frac=spec.ess_frac,
                residual=spec.quad_form == "residual",
                store_paths=store_paths)
    # Shared host float64 assembly, from the UNPADDED panel/R (padded series
    # contribute nothing in-scan by design).
    lls = _host_lls(ll_rel, Y, R_unpadded,
                    residual=spec.quad_form == "residual")
    return SVResult(loglik=np.sum(lls), f_mean=f_mean, h_mean=h_mean,
                    ess=ess, n_resamples=n_rs, h_particles=h_hist,
                    logw=logw_hist, lls=lls)

"""Series-sharded mixed-frequency EM (shard_map + psum over both blocks).

Same layout as ``parallel.sharded`` extended to the S3 model: monthly and
quarterly series are padded and sharded SEPARATELY over the 1-D ``"series"``
mesh axis (each shard owns a contiguous slice of both blocks, so the
constrained M-step's monthly/quarterly split stays shard-local), the
augmented-state k x k scans are replicated, and the only communication per
EM iteration is the psum of the info-form observation statistics plus the
loglik residual terms — identical comm volume to the plain sharded EM even
though the state is 5x wider (the stats are m-sized, m = n_lags * k).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..estim.em import run_em_chunked
from ..models.mixed_freq import (MFParams, MFResult, MixedFreqSpec,
                                 augment, mf_em_core, mf_pca_init)
from .mesh import shard_map, SERIES_AXIS, make_mesh

__all__ = ["sharded_mf_fit"]


def _psum_tree(tree):
    return jax.tree.map(lambda x: lax.psum(x, SERIES_AXIS), tree)


def _pad_block(Y, W, Lam, R, n_shards):
    """Pad one frequency block's series axis to a multiple of n_shards."""
    T, n = Y.shape
    pad = (-n) % n_shards
    if pad == 0:
        return Y, W, Lam, R, 0
    k = Lam.shape[1]
    return (np.concatenate([Y, np.zeros((T, pad))], axis=1),
            np.concatenate([W, np.zeros((T, pad))], axis=1),
            np.concatenate([Lam, np.zeros((pad, k))], axis=0),
            np.concatenate([R, np.ones(pad)], axis=0), pad)


@partial(jax.jit, static_argnames=("mesh", "spec_local"))
def _sharded_mf_step_impl(Ym, Wm, Yq, Wq, Lam_m, Lam_q, Rm, Rq,
                          A, Q, mu0, P0, mesh: Mesh,
                          spec_local: MixedFreqSpec):
    def body(Ym_s, Wm_s, Yq_s, Wq_s, Lm_s, Lq_s, Rm_s, Rq_s, A, Q, mu0, P0):
        Y_s = jnp.concatenate([Ym_s, Yq_s], axis=1)
        W_s = jnp.concatenate([Wm_s, Wq_s], axis=1)
        p_s = MFParams(Lm_s, Lq_s, A, Q,
                       jnp.concatenate([Rm_s, Rq_s]), mu0, P0)
        p_new, ll, sm = mf_em_core(Y_s, W_s, p_s, spec_local,
                                   reduce_tree=_psum_tree)
        nm = spec_local.n_monthly
        return (p_new.Lam_m, p_new.Lam_q, p_new.R[:nm], p_new.R[nm:],
                p_new.A, p_new.Q, p_new.mu0, p_new.P0, ll,
                sm.x_sm, sm.P_sm)

    col = P(None, SERIES_AXIS)
    row = P(SERIES_AXIS, None)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(col, col, col, col, row, row, P(SERIES_AXIS),
                  P(SERIES_AXIS), P(), P(), P(), P()),
        out_specs=(row, row, P(SERIES_AXIS), P(SERIES_AXIS),
                   P(), P(), P(), P(), P(), P(), P()))
    return mapped(Ym, Wm, Yq, Wq, Lam_m, Lam_q, Rm, Rq, A, Q, mu0, P0)


@partial(jax.jit, static_argnames=("mesh", "spec_local", "n_iters"))
def _sharded_mf_scan_impl(Ym, Wm, Yq, Wq, params, mesh: Mesh,
                          spec_local: MixedFreqSpec, n_iters: int):
    """n constrained EM iterations fused into ONE XLA program: ``lax.scan``
    over the shard_map body (the MF analog of ``sharded._sharded_em_scan_impl``
    — one program dispatch per CHUNK instead of per iteration, the difference
    between ~10-15 and hundreds of iters/sec through a ~60-100 ms-per-dispatch
    tunnel; VERDICT r4 item 2).  ``params`` is the sharded
    (Lam_m, Lam_q, Rm, Rq, A, Q, mu0, P0) tuple; returns (params', lls (n,)).
    """
    def body(Ym_s, Wm_s, Yq_s, Wq_s, Lm_s, Lq_s, Rm_s, Rq_s, A, Q, mu0, P0):
        Y_s = jnp.concatenate([Ym_s, Yq_s], axis=1)
        W_s = jnp.concatenate([Wm_s, Wq_s], axis=1)
        nm = spec_local.n_monthly

        def it(carry, _):
            Lm_c, Lq_c, Rm_c, Rq_c, A_c, Q_c, mu0_c, P0_c = carry
            p_c = MFParams(Lm_c, Lq_c, A_c, Q_c,
                           jnp.concatenate([Rm_c, Rq_c]), mu0_c, P0_c)
            p_new, ll, _ = mf_em_core(Y_s, W_s, p_c, spec_local,
                                      reduce_tree=_psum_tree)
            return (p_new.Lam_m, p_new.Lam_q, p_new.R[:nm], p_new.R[nm:],
                    p_new.A, p_new.Q, p_new.mu0, p_new.P0), ll

        carry0 = (Lm_s, Lq_s, Rm_s, Rq_s, A, Q, mu0, P0)
        carry, lls = lax.scan(it, carry0, None, length=n_iters)
        return carry + (lls,)

    col = P(None, SERIES_AXIS)
    row = P(SERIES_AXIS, None)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(col, col, col, col, row, row, P(SERIES_AXIS),
                  P(SERIES_AXIS), P(), P(), P(), P()),
        out_specs=(row, row, P(SERIES_AXIS), P(SERIES_AXIS),
                   P(), P(), P(), P(), P()))
    out = mapped(Ym, Wm, Yq, Wq, *params)
    return out[:8], out[8]


def sharded_mf_fit(Y: np.ndarray, spec: MixedFreqSpec,
                   mask: Optional[np.ndarray] = None,
                   mesh: Optional[Mesh] = None,
                   max_iters: int = 50, tol: float = 1e-6,
                   dtype=jnp.float32, standardize: bool = True,
                   init: Optional[MFParams] = None,
                   callback=None, fused_chunk: int = 8) -> MFResult:
    """Multi-device ``mf_fit``; mirrors its contract (standardize -> masked
    PCA warm start -> constrained EM -> smooth), sharded over series.

    ``fused_chunk`` EM iterations run as ONE XLA program between host
    round-trips (``estim.em.run_em_chunked`` — same stop/replay semantics as
    every other fused driver; callbacks receive chunk-entry params).  Set 1
    for one dispatch per iteration and exact per-iteration callbacks."""
    from ..utils.data import build_mask, standardize as _std
    Y = np.asarray(Y, np.float64)
    T = Y.shape[0]
    Nm, Nq = spec.n_monthly, spec.n_quarterly
    W = build_mask(Y, mask)
    std = None
    if standardize:
        Y, std = _std(Y, mask=W)
    if init is None:
        init = mf_pca_init(Y, W, spec)
    mesh = mesh if mesh is not None else make_mesh()
    D = int(mesh.devices.size)
    Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)

    Ym, Wm, Lm, Rm, pad_m = _pad_block(
        Yz[:, :Nm], W[:, :Nm], np.asarray(init.Lam_m, np.float64),
        np.asarray(init.R[:Nm], np.float64), D)
    Yq, Wq, Lq, Rq, pad_q = _pad_block(
        Yz[:, Nm:], W[:, Nm:], np.asarray(init.Lam_q, np.float64),
        np.asarray(init.R[Nm:], np.float64), D)
    spec_pad = dataclasses.replace(spec, n_monthly=Nm + pad_m,
                                   n_quarterly=Nq + pad_q)
    spec_local = dataclasses.replace(
        spec, n_monthly=(Nm + pad_m) // D, n_quarterly=(Nq + pad_q) // D)

    Ymj, Wmj, Yqj, Wqj = (jnp.asarray(a, dtype) for a in (Ym, Wm, Yq, Wq))
    params = tuple(jnp.asarray(a, dtype) for a in
                   (Lm, Lq, Rm, Rq, init.A, init.Q, init.mu0, init.P0))

    def mk_params(pt):
        Lm_, Lq_, Rm_, Rq_, A_, Q_, mu0_, P0_ = (
            np.asarray(a, np.float64) for a in pt)
        return MFParams(Lam_m=jnp.asarray(Lm_[:Nm]),
                        Lam_q=jnp.asarray(Lq_[:Nq]),
                        A=jnp.asarray(A_), Q=jnp.asarray(Q_),
                        R=jnp.asarray(np.concatenate([Rm_[:Nm], Rq_[:Nq]])),
                        mu0=jnp.asarray(mu0_), P0=jnp.asarray(P0_))

    cb = None
    if callback is not None:
        cache: dict = {}

        def cb(it, ll, p_entry, **kw):
            # One host transfer per chunk: run_em_chunked re-passes the same
            # chunk-entry object for every iteration of a chunk.
            key = id(p_entry)
            if key not in cache:
                cache.clear()
                cache[key] = mk_params(p_entry)
            callback(it, ll, cache[key], **kw)
        cb.wants_params_iter = getattr(callback, "wants_params_iter", False)

    from ..estim.em import noise_floor_for
    # True-f32 matmul products, as in mf_fit (bf16 default is unusable for
    # the augmented-state stats — see mixed_freq.mf_em_core).
    with jax.default_matmul_precision("highest"):
        def scan_fn(pt, n):
            pt_new, lls = _sharded_mf_scan_impl(
                Ymj, Wmj, Yqj, Wqj, pt, mesh, spec_local, n)
            return pt_new, lls, None

        floor = noise_floor_for(dtype, Y.size)
        params, lls, converged, _ = run_em_chunked(
            scan_fn, params, max_iters, tol, floor, cb, fused_chunk)

        # The fused chunks never materialize smoothers; run one E-pass at
        # the final params for the reported factors/nowcast.
        out = _sharded_mf_step_impl(Ymj, Wmj, Yqj, Wqj, *params,
                                    mesh, spec_local)
    x_sm = np.asarray(out[9], np.float64)
    P_sm = np.asarray(out[10], np.float64)
    k = spec.n_factors
    p_final = mk_params(params)
    aug = augment(p_final, spec)
    common = x_sm @ np.asarray(aug.Lam, np.float64).T
    if std is not None:
        common = std.inverse(common)
    from ..robust.health import health_from_trace
    return MFResult(params=p_final, logliks=np.asarray(lls),
                    factors=x_sm[:, :k], factor_cov=P_sm[:, :k, :k],
                    nowcast=common, converged=converged, spec=spec,
                    state_T=x_sm[-1], state_cov_T=P_sm[-1],
                    standardizer=std,
                    health=health_from_trace(lls, floor))

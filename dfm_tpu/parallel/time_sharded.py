"""Time-sharded square-root parallel-in-time filtering/smoothing.

The series-sharded EM (``parallel.sharded``) splits the CROSS-SECTION and
replicates the time recursion on every device.  At long T the recursion
itself is the cost, so this module splits the TIME axis instead: each of
the D devices builds the square-root (QR-factor) associative elements for
its own T/D-slab (``ssm.parallel_filter.qr_generic_elements``), runs the
local blocked prefix scan, and the shards are stitched with ONE log-depth
cross-device combine of the D boundary elements:

  1. local inclusive prefix products per shard (``ops.scan.blocked_scan``,
     ~2 sqrt(T/D) sequential depth);
  2. ``all_gather`` of the D per-shard TOTAL products (a few (k, k)
     factors each — the only cross-device payload);
  3. a replicated Hillis-Steele doubling over the gathered totals
     (log2(D) batched combines) gives every shard the exclusive prefix of
     everything before it, and one more batched combine folds that offset
     into the local prefixes.

The offset element's (b, U) IS the previous shard's last filtered
posterior, so each shard recovers its own predicted moments (first slot
from the offset, the rest locally) and its local log-likelihood pieces;
the total loglik is one psum.  The smoother runs the same machinery in
reverse (suffix products; the boundary (x_pred, Lp) of the NEXT shard
arrives by ppermute — the last shard receives zeros, which degenerate
exactly into the anchor element).

Padding: T is padded up to a multiple of D with zero-mask rows.  A fully
unobserved step contributes C_t = 0, n_t = 0 stats, so its loglik pieces
vanish and smoothing through it is the identity correction — trailing pad
rows are exactly inert (they sit AFTER every real row in the prefix
order) and are dropped on exit.  Equivalence with the single-device
``pit_qr_filter_smoother`` is pinned by ``tests/test_time_sharded.py``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map
from ..ops.linalg import (matmul_vpu, matvec_vpu, tria, psd_factor,
                          chol_unrolled, chol_solve_unrolled, psd_cholesky,
                          chol_solve, chol_logdet, QR_UNROLL_K_MAX)
from ..ssm.info_filter import (obs_stats, loglik_terms_local,
                               loglik_from_terms)
from ..ssm.parallel_filter import (qr_generic_elements, qr_init_posterior,
                                   qr_combine_filter, qr_combine_smoother,
                                   _gram)
from ..ssm.params import SSMParams, FilterResult, SmootherResult
from ..ops.scan import blocked_scan

__all__ = ["TIME_AXIS", "make_time_mesh", "pit_qr_time_sharded",
           "pit_qr_filter_time_sharded"]

TIME_AXIS = "time"


def make_time_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices, axis "time"."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)} "
                f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count=K)")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (TIME_AXIS,))


def _filter_identity(k, dtype):
    """Identity of the filtering semigroup: A=I, b=0, U=0, eta=0, Z=0.

    Exact through ``qr_combine_filter`` up to an orthogonal right-factor
    on U/Z (grams — the only thing downstream consumes — are preserved).
    """
    I_k = jnp.eye(k, dtype=dtype)
    z_kk = jnp.zeros((k, k), dtype)
    z_k = jnp.zeros((k,), dtype)
    return (I_k, z_k, z_kk, z_k, z_kk)


def _smoother_identity(k, dtype):
    """Identity of the smoothing semigroup (as the LATER argument):
    E=I, g=0, D=0."""
    return (jnp.eye(k, dtype=dtype), jnp.zeros((k,), dtype),
            jnp.zeros((k, k), dtype))


def _exclusive_doubling(combine, totals, identity):
    """Exclusive prefix of (D, ...) leaves under ``combine`` (arg order:
    earlier, later) via Hillis-Steele doubling — log2(D) batched combines.
    Slot s receives totals[0] o ... o totals[s-1]; slot 0 the identity.
    """
    D = totals[0].shape[0]
    idb = tuple(jnp.broadcast_to(i, (1,) + i.shape) for i in identity)
    # Shift the identity in: x = [id, t_0, ..., t_{D-2}].
    x = tuple(jnp.concatenate([i, t[:-1]], axis=0)
              for i, t in zip(idb, totals))
    d = 1
    while d < D:
        pad = tuple(jnp.broadcast_to(i, (d,) + i.shape[1:]) for i in idb)
        shifted = tuple(jnp.concatenate([p, xi[:-d]], axis=0)
                        for p, xi in zip(pad, x))
        x = combine(shifted, x)
        d *= 2
    return x


def _bcast(e, L):
    """Broadcast a single element's leaves to a leading (L,) batch axis."""
    return tuple(jnp.broadcast_to(x, (L,) + x.shape) for x in e)


def _take(e, i):
    return tuple(x[i] for x in e)


@partial(jax.jit, static_argnames=("mesh", "has_mask", "scan_impl"))
def _pit_qr_time_sharded_impl(Y, mask, p, mesh, has_mask,
                              scan_impl="blocked"):
    k = p.A.shape[0]
    dtype = Y.dtype
    nsh = mesh.devices.size          # static: ppermute perms need ints

    def body(Y_loc, W_loc, p):
        A, Q, mu0, P0 = p.A, p.Q, p.mu0, p.P0
        idx = lax.axis_index(TIME_AXIS)
        is0 = idx == 0
        L = Y_loc.shape[0]
        m_loc = W_loc if has_mask else None
        stats = obs_stats(Y_loc, p.Lam, p.R, mask=m_loc)
        C_loc = stats.C
        if C_loc.ndim == 2:
            C_loc = jnp.broadcast_to(C_loc, (L, k, k))

        # --- local elements; prior correction on shard 0's slot 0 only ---
        elems = qr_generic_elements(stats, A, Q)
        b0, U0 = qr_init_posterior(C_loc[0], stats.b[0], mu0, P0)
        t0 = (jnp.zeros((k, k), dtype), b0, U0, jnp.zeros((k,), dtype),
              jnp.zeros((k, k), dtype))
        e0 = tuple(jnp.where(is0, a, b[0]) for a, b in zip(t0, elems))
        elems = tuple(b.at[0].set(a) for a, b in zip(e0, elems))

        # --- local prefix + one log-depth cross-device boundary combine ---
        if scan_impl == "blocked":
            pref = blocked_scan(qr_combine_filter, elems)
        else:
            pref = lax.associative_scan(qr_combine_filter, elems)
        totals = tuple(x[-1] for x in pref)
        gathered = tuple(lax.all_gather(x, TIME_AXIS) for x in totals)
        offs = _exclusive_doubling(qr_combine_filter, gathered,
                                   _filter_identity(k, dtype))
        off = _take(offs, idx)
        folded = qr_combine_filter(_bcast(off, L), pref)
        # Shard 0's offset is the identity — keep its local prefix bit-
        # exact instead of re-orthogonalizing through the combine.
        glob = tuple(jnp.where(is0, a, b) for a, b in zip(pref, folded))

        x_f, U_f = glob[1], glob[2]
        P_f = _gram(U_f)

        # --- predicted moments: slot 0 from the offset's (b, U) (= the
        # previous shard's last filtered posterior); shard 0 from the
        # prior.  Never a re-factorization of a rounded covariance. ---
        Lq = psd_factor(Q)
        AU = matmul_vpu(jnp.broadcast_to(A, (L - 1, k, k)), U_f[:-1])
        Lp_tail = tria(jnp.concatenate(
            [AU, jnp.broadcast_to(Lq, (L - 1, k, k))], axis=-1))
        Lp0_first = tria(jnp.concatenate([A @ off[2], Lq], axis=-1))
        Lp_first = jnp.where(is0, psd_factor(P0), Lp0_first)
        Lp = jnp.concatenate([Lp_first[None], Lp_tail], axis=0)
        P_pred = _gram(Lp)
        xp_first = jnp.where(is0, mu0, A @ off[1])
        x_pred = jnp.concatenate([xp_first[None], x_f[:-1] @ A.T], axis=0)

        # --- local loglik pieces; ONE psum for the total ---
        LpT_C = matmul_vpu(jnp.swapaxes(Lp, -1, -2), C_loc)
        G = jnp.eye(k, dtype=dtype)[None] + matmul_vpu(LpT_C, Lp)
        chol = chol_unrolled if k <= QR_UNROLL_K_MAX else \
            (lambda M: psd_cholesky(M, jitter=0.0))
        logdetG = chol_logdet(chol(G))
        quad_R, U = loglik_terms_local(Y_loc, p.Lam, p.R, x_pred, m_loc)
        ll = lax.psum(loglik_from_terms(stats, logdetG, P_f, quad_R, U),
                      TIME_AXIS)

        # --- smoother: boundary (x_pred, Lp) of the NEXT shard arrives by
        # ppermute; the last shard has no successor — its received factor
        # is replaced with I and the slot's gain forced to J = 0, which
        # degenerates the element into the anchor (E = 0, g = x_f,
        # D ~ U_f). ---
        is_last = idx == nsh - 1
        perm = [(s + 1, s) for s in range(nsh - 1)]
        xp_next = lax.ppermute(x_pred[0], TIME_AXIS, perm)
        Lp_next_first = lax.ppermute(Lp[0], TIME_AXIS, perm)
        Lp_next_first = jnp.where(is_last, jnp.eye(k, dtype=dtype),
                                  Lp_next_first)
        Lp_next = jnp.concatenate([Lp[1:], Lp_next_first[None]], axis=0)
        xpn = jnp.concatenate([x_pred[1:], xp_next[None]], axis=0)

        chol_slv = chol_solve_unrolled if k <= QR_UNROLL_K_MAX else chol_solve
        APf = matmul_vpu(jnp.broadcast_to(A, (L, k, k)), P_f)
        J = jnp.swapaxes(chol_slv(Lp_next, APf), -1, -2)      # (L, k, k)
        J = J.at[-1].set(jnp.where(is_last, jnp.zeros((k, k), dtype),
                                   J[-1]))
        E = J
        g = x_f - jnp.einsum("tkl,tl->tk", J, xpn)
        ImJA = jnp.broadcast_to(jnp.eye(k, dtype=dtype), (L, k, k)) \
            - matmul_vpu(J, jnp.broadcast_to(A, (L, k, k)))
        D_el = tria(jnp.concatenate(
            [matmul_vpu(ImJA, U_f),
             matmul_vpu(J, jnp.broadcast_to(Lq, (L, k, k)))], axis=-1))
        selems = (E, g, D_el)

        if scan_impl == "blocked":
            suf = blocked_scan(qr_combine_smoother, selems, reverse=True)
        else:
            suf = lax.associative_scan(qr_combine_smoother, selems,
                                       reverse=True)
        stot = tuple(x[0] for x in suf)
        sgath = tuple(lax.all_gather(x, TIME_AXIS) for x in stot)
        # Suffix offsets: flip to make it a prefix problem (leftmost =
        # latest shard; the smoothing combine takes (later, earlier)).
        sflip = tuple(jnp.flip(x, axis=0) for x in sgath)
        soffs_f = _exclusive_doubling(
            lambda a, b: qr_combine_smoother(a, b), sflip,
            _smoother_identity(k, dtype))
        soffs = tuple(jnp.flip(x, axis=0) for x in soffs_f)
        soff = _take(soffs, idx)
        sfolded = qr_combine_smoother(_bcast(soff, L), suf)
        sglob = tuple(jnp.where(is_last, a, b) for a, b in zip(suf, sfolded))

        x_sm, D_sm = sglob[1], sglob[2]
        P_sm = _gram(D_sm)
        # Lag covariance P_{t,t-1|T} = P_sm[t] J[t-1]': J[t-1] is local for
        # slots >= 1; slot 0 needs the PREVIOUS shard's last J — ship it
        # forward (shard 0's slot 0 is zeroed, same as single-device).
        perm_fwd = [(s, s + 1) for s in range(nsh - 1)]
        J_prev = lax.ppermute(J[-1], TIME_AXIS, perm_fwd)
        J_shift = jnp.concatenate([J_prev[None], J[:-1]], axis=0)
        P_lag = jnp.einsum("tij,tkj->tik", P_sm, J_shift)
        P_lag = jnp.where(is0, P_lag.at[0].set(jnp.zeros((k, k), dtype)),
                          P_lag)
        return x_pred, P_pred, x_f, P_f, ll, x_sm, P_sm, P_lag

    t_spec = P(TIME_AXIS)
    rep = P()
    out_specs = (t_spec, t_spec, t_spec, t_spec, rep,
                 t_spec, t_spec, t_spec)
    p_specs = jax.tree_util.tree_map(lambda _: rep, p)
    return shard_map(body, mesh=mesh,
                     in_specs=(t_spec, t_spec, p_specs),
                     out_specs=out_specs)(Y, mask, p)


def pit_qr_time_sharded(Y, p: SSMParams, mask=None,
                        n_devices: Optional[int] = None,
                        mesh: Optional[Mesh] = None,
                        scan_impl: str = "blocked"):
    """Time-sharded square-root PIT filter + smoother.

    Returns ``(FilterResult, SmootherResult)`` with the same contract as
    ``ssm.parallel_filter.pit_qr_filter_smoother`` (exact loglik, moments
    to fp tolerance).  T is padded to a multiple of the mesh size with
    zero-mask rows (exactly inert — module docstring) and unpadded on
    exit.
    """
    if mesh is None:
        mesh = make_time_mesh(n_devices)
    D = mesh.devices.size
    Y = jnp.asarray(Y)
    p = p.astype(Y.dtype)
    T, N = Y.shape
    n_pad = (-T) % D
    W = mask
    if W is None:
        W = jnp.ones((T, N), Y.dtype)
    else:
        W = jnp.asarray(W, Y.dtype)
    if n_pad:
        Y = jnp.concatenate([Y, jnp.zeros((n_pad, N), Y.dtype)], axis=0)
        W = jnp.concatenate([W, jnp.zeros((n_pad, N), Y.dtype)], axis=0)
    has_mask = bool(mask is not None or n_pad)
    xp, Pp, xf, Pf, ll, x_sm, P_sm, P_lag = _pit_qr_time_sharded_impl(
        Y, W, p, mesh, has_mask, scan_impl)
    if n_pad:
        xp, Pp, xf, Pf = (a[:T] for a in (xp, Pp, xf, Pf))
        x_sm, P_sm, P_lag = (a[:T] for a in (x_sm, P_sm, P_lag))
    return (FilterResult(xp, Pp, xf, Pf, ll),
            SmootherResult(x_sm, P_sm, P_lag))


def pit_qr_filter_time_sharded(Y, p: SSMParams, mask=None,
                               n_devices: Optional[int] = None,
                               mesh: Optional[Mesh] = None,
                               scan_impl: str = "blocked") -> FilterResult:
    """Filter-only entry (same stitched program; smoother outputs dropped
    by XLA dead-code elimination when unused)."""
    return pit_qr_time_sharded(Y, p, mask=mask, n_devices=n_devices,
                               mesh=mesh, scan_impl=scan_impl)[0]

"""Series-sharded EM: ``shard_map`` over the mesh, ``psum`` for the E-step.

The distributed design of SURVEY.md sections 2.3/3.1 made concrete.  The panel
``Y (T, N)``, loadings rows ``Lam (N, k)`` and noise diag ``R (N,)`` are
sharded over the 1-D ``"series"`` mesh axis; ``A, Q, mu0, P0`` and the whole
k-dimensional time recursion are replicated.  Per EM iteration the only
communication is ONE psum of the k-sized observation statistics
(``ssm.info_filter.ObsStats`` — b, C, c2, n, ldR), after which:

  - every device runs the identical k x k filter + RTS scan (replicated);
  - the M-step loading/noise rows are computed locally (each series' row
    depends only on its own data column + replicated moments — no collective;
    this is where BASELINE.json:5's "sufficient-statistic reductions as psum
    collectives" lands: the reductions Lam' R^{-1} y_t etc. ARE the psum'd
    ObsStats, and S_yf stays shard-local by construction);
  - A, Q, mu0, P0 updates are recomputed identically everywhere.

Per-step comm volume is O(k^2) regardless of N — the layout scales the
cross-section purely through ICI-local einsums.

Equivalence with the single-device path (same loglik sequence and params to fp
tolerance) is asserted in ``tests/test_sharding.py`` on a fake 8-device CPU
mesh (SURVEY.md section 4.2.4).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import dataclasses

from ..obs.trace import current_tracer, shape_key
from ..estim.em import (EMConfig, cfg_hypers, moments, moment_sums,
                        mstep_rows, mstep_dynamics, mstep_dynamics_sums,
                        run_em_loop)
from ..ssm.info_filter import (ObsStats, obs_stats, info_scan, quad_expanded,
                               quad_local, u_from_stats, loglik_from_terms)
from ..ssm.kalman import rts_smoother
from ..ssm.params import SSMParams, FilterResult
from .mesh import shard_map, SERIES_AXIS, make_mesh, pad_panel, unpad_rows

__all__ = ["sharded_em_step", "sharded_em_fit", "sharded_em_scan",
           "sharded_filter_smoother", "ShardedEM"]


def _psum_stats(stats: ObsStats) -> ObsStats:
    return ObsStats(*(lax.psum(x, SERIES_AXIS) for x in stats))


def _shard_filter_smoother(Y_s, mask_s, p_s: SSMParams,
                           cfg: EMConfig = EMConfig(filter="info"),
                           gate_s=None, sumsq_s=None):
    """Per-device body: local stats -> psum -> replicated k x k scans.

    The loglik quadratic is reduced in a second psum of the per-shard
    residual terms (see info_filter module docstring's float32 note).
    ``cfg.filter == "ss"`` routes the replicated part through the
    steady-state engine (``ssm.steady.ss_from_stats`` — the single-chip
    headline speed path, now available under sharding): only the k-sized
    stats psum and the loglik psum touch the network, so the sequential
    depth stays ~3*tau + O(sqrt(T)) regardless of T or N.  Masked or short
    panels fall back to the exact info scan (same rule as
    ``ss_filter_smoother``; both branches resolve at trace time).

    ``gate_s`` (local (n,) {0,1}, 0 = padded series) marks mesh-divisibility
    padding on UNMASKED panels.  Padded series carry Lam = 0, R = 1, Y = 0,
    so they already contribute nothing to any reduction; the gate only fixes
    the observation COUNT in the loglik constant (and lets the M-step keep
    the pads pinned, see ``_shard_em_step``) — it must NOT become a mask,
    which would force the masked (T,k,k) stats path and knock out the ss
    engine for every padded unmasked panel.

    Returns (kf, sm, delta) with delta the ss freeze diagnostic (0 exact).
    """
    T = Y_s.shape[0]
    use_ss = (cfg.filter == "ss" and mask_s is None and T > 2 * cfg.tau + 4)
    stats_loc = obs_stats(Y_s, p_s.Lam, p_s.R, mask=mask_s)
    stats = _psum_stats(stats_loc)
    if gate_s is not None and mask_s is None:
        n_real = lax.psum(jnp.sum(gate_s), SERIES_AXIS)
        stats = stats._replace(n=jnp.full_like(stats.n, n_real))
    if use_ss:
        from ..ssm.steady import ss_from_stats
        xp, Pp, xf, Pf, logdetG, sm, delta = ss_from_stats(
            stats, p_s, T, cfg.tau)
    else:
        xp, Pp, xf, Pf, logdetG = info_scan(stats, p_s.A, p_s.Q,
                                            p_s.mu0, p_s.P0)
        delta = jnp.zeros((), Y_s.dtype)
    # Panel pass only for the quadratic; U = b - C x_pred is k-sized and
    # psums exactly like the residual form (linear in the local stats).
    # The expanded quadratic is used exactly when the single-device driver
    # uses it (ss engine active + f64 assembly available) so sharded and
    # single-device trajectories stay comparable form-for-form.
    from ..ops.precision import accum_dtype
    if (use_ss and sumsq_s is not None
            and accum_dtype(Y_s.dtype) != Y_s.dtype):
        # Expanded form from the LOCAL stats (every piece is a local series
        # sum, so the psum'd total equals the global expansion).
        quad_R = quad_expanded(sumsq_s, 1.0 / p_s.R, stats_loc, xp)
    else:
        quad_R, _ = quad_local(Y_s, p_s.Lam, p_s.R, xp, mask_s)
    quad_R = lax.psum(quad_R, SERIES_AXIS)
    U = lax.psum(u_from_stats(stats_loc, xp), SERIES_AXIS)
    kf = FilterResult(xp, Pp, xf, Pf,
                      loglik_from_terms(stats, logdetG, Pf, quad_R, U))
    if not use_ss:
        sm = rts_smoother(kf, p_s)
    return kf, sm, delta


def _shard_em_step(Y_s, mask_s, p_s: SSMParams, cfg: EMConfig, gate_s=None,
                   Ysq_s=None, sumsq_s=None):
    kf, sm, delta = _shard_filter_smoother(Y_s, mask_s, p_s, cfg, gate_s,
                                           sumsq_s=sumsq_s)
    # Tuned hypers (fit(tune=...)): the ridge/scales are replicated
    # statics, so the shard-local rows need no extra collective.
    hy = cfg_hypers(cfg)
    ridge = None if hy is None else hy[2]
    if mask_s is None:
        S_ff, S_lag, S_cur, S_cross = moment_sums(sm)
        Lam_s, R_s = mstep_rows(Y_s, None, sm.x_sm, None, None, S_ff,
                                cfg.r_floor, Ysq=Ysq_s, lam_ridge=ridge)
        A, Q, mu0, P0 = mstep_dynamics_sums(sm, S_lag, S_cur, S_cross,
                                            p_s, cfg)
    else:
        EffT, cross = moments(sm)
        S_ff = EffT.sum(0)
        Lam_s, R_s = mstep_rows(Y_s, mask_s, sm.x_sm, EffT, sm.P_sm, S_ff,
                                cfg.r_floor, lam_ridge=ridge)
        A, Q, mu0, P0 = mstep_dynamics(sm, EffT, cross, p_s, cfg)
    if hy is not None:
        Q = hy[0] * Q
        R_s = jnp.maximum(hy[1] * R_s, cfg.r_floor)
    if gate_s is not None and mask_s is None:
        # Keep the pads at their neutral (Lam=0, R=1): the unmasked M-step
        # would otherwise drive a pad's R to r_floor (its residual is 0),
        # poisoning ldR = sum log R in the next iteration's loglik.
        Lam_s = gate_s[:, None] * Lam_s
        R_s = jnp.where(gate_s > 0, R_s, jnp.ones_like(R_s))
    return SSMParams(Lam_s, A, Q, R_s, mu0, P0), kf.loglik, delta


def _param_specs():
    return SSMParams(Lam=P(SERIES_AXIS, None), A=P(), Q=P(),
                     R=P(SERIES_AXIS), mu0=P(), P0=P())


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate"))
def _sharded_em_step_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                          cfg: EMConfig, has_mask: bool, has_gate: bool):
    def body(Y_s, mask_s, gate_s, p_s):
        sumsq_s = None if has_mask else Y_s * Y_s
        Ysq_s = None if has_mask else jnp.sum(sumsq_s, axis=0)
        p_new, ll, delta = _shard_em_step(
            Y_s, mask_s if has_mask else None, p_s, cfg,
            gate_s if has_gate else None, Ysq_s, sumsq_s)
        return p_new, ll, delta

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs()),
        out_specs=(_param_specs(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)  # placeholder; body ignores it when !has_mask
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate",
                                   "n_iters"))
def _sharded_em_scan_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                          cfg: EMConfig, has_mask: bool, has_gate: bool,
                          n_iters: int):
    """n EM iterations fused into ONE XLA program: ``lax.scan`` over the
    shard_map body (VERDICT r2 item 3 — the sharded analog of
    ``em_fit_scan``).  The per-iteration psums sit inside the scan, so a
    multi-device fit pays program-dispatch cost once per CHUNK instead of
    once per iteration (~60-100 ms/dispatch on tunneled devices,
    docs/PERF.md item 4 — the difference between ~10 and ~400 iters/sec)."""
    def body(Y_s, mask_s, gate_s, p_s):
        m = mask_s if has_mask else None
        g = gate_s if has_gate else None
        # Iteration-invariant panel passes, hoisted out of the fused loop.
        sumsq_s = None if has_mask else Y_s * Y_s
        Ysq_s = None if has_mask else jnp.sum(sumsq_s, axis=0)

        def it(p_c, _):
            p_new, ll, delta = _shard_em_step(Y_s, m, p_c, cfg, g, Ysq_s,
                                              sumsq_s)
            return p_new, (ll, delta)

        p_f, (lls, deltas) = lax.scan(it, p_s, None, length=n_iters)
        return p_f, lls, deltas

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs()),
        out_specs=(_param_specs(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate",
                                   "n_bucket"))
def _sharded_em_scan_active_impl(Y, mask, gate, p: SSMParams, n_active,
                                 mesh: Mesh, cfg: EMConfig, has_mask: bool,
                                 has_gate: bool, n_bucket: int):
    """Bucketed twin of ``_sharded_em_scan_impl``: STATIC ``n_bucket`` fused
    length, TRACED ``n_active`` cap — iterations at index >= n_active hold
    the replicated param carry via where-selects (see
    ``estim.em._em_scan_core_active``), so one executable serves every
    tail-chunk/replay length.  ``n_active`` is a replicated scalar; the
    freeze select needs no collective."""
    def body(Y_s, mask_s, gate_s, p_s, n_active_r):
        m = mask_s if has_mask else None
        g = gate_s if has_gate else None
        sumsq_s = None if has_mask else Y_s * Y_s
        Ysq_s = None if has_mask else jnp.sum(sumsq_s, axis=0)

        def it(p_c, j):
            p_new, ll, delta = _shard_em_step(Y_s, m, p_c, cfg, g, Ysq_s,
                                              sumsq_s)
            live = j < n_active_r
            p_out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, a, b), p_new, p_c)
            return p_out, (ll, delta)

        p_f, (lls, deltas) = lax.scan(it, p_s, jnp.arange(n_bucket))
        return p_f, lls, deltas

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs(), P()),
        out_specs=(_param_specs(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p, n_active)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate",
                                   "n_bucket"))
def _sharded_em_scan_active_metrics_impl(Y, mask, gate, p: SSMParams,
                                         n_active, mesh: Mesh, cfg: EMConfig,
                                         has_mask: bool, has_gate: bool,
                                         n_bucket: int):
    """Metrics twin of ``_sharded_em_scan_active_impl`` (same per-iteration
    (n, 3) row contract as ``_sharded_em_scan_metrics_impl``)."""
    def body(Y_s, mask_s, gate_s, p_s, n_active_r):
        m = mask_s if has_mask else None
        g = gate_s if has_gate else None
        sumsq_s = None if has_mask else Y_s * Y_s
        Ysq_s = None if has_mask else jnp.sum(sumsq_s, axis=0)

        def it(carry, j):
            p_c, ll_prev = carry
            p_new, ll, delta = _shard_em_step(Y_s, m, p_c, cfg, g, Ysq_s,
                                              sumsq_s)
            leaves = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda a, b: jnp.max(jnp.abs(a - b)), p_new, p_c))
            dparam = lax.pmax(jnp.max(jnp.stack(leaves)), SERIES_AXIS)
            ll64 = jnp.asarray(ll, jnp.float64)
            row = jnp.stack([ll64, ll64 - ll_prev,
                             jnp.asarray(dparam, jnp.float64)])
            live = j < n_active_r
            p_out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, a, b), p_new, p_c)
            ll_out = jnp.where(live, ll64, ll_prev)
            return (p_out, ll_out), (ll, delta, row)

        ll0 = jnp.asarray(jnp.nan, jnp.float64)
        (p_f, _), (lls, deltas, metrics) = lax.scan(
            it, (p_s, ll0), jnp.arange(n_bucket))
        return p_f, lls, deltas, metrics

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs(), P()),
        out_specs=(_param_specs(), P(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p, n_active)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate",
                                   "n_iters"))
def _sharded_em_scan_metrics_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                                  cfg: EMConfig, has_mask: bool,
                                  has_gate: bool, n_iters: int):
    """Metrics twin of ``_sharded_em_scan_impl``: same fused chunk plus a
    per-iteration (n, 3) [loglik, delta, max param-update] block (the
    sharded analog of ``estim.em._em_scan_core_metrics``).  Lam/R rows are
    shard-local, so the update norm is a local max + ``pmax`` over the mesh
    axis (one extra k-free collective per iteration).  Kept as a separate
    program so the default chunk stays byte-identical to the metrics-free
    path."""
    def body(Y_s, mask_s, gate_s, p_s):
        m = mask_s if has_mask else None
        g = gate_s if has_gate else None
        sumsq_s = None if has_mask else Y_s * Y_s
        Ysq_s = None if has_mask else jnp.sum(sumsq_s, axis=0)

        def it(carry, _):
            p_c, ll_prev = carry
            p_new, ll, delta = _shard_em_step(Y_s, m, p_c, cfg, g, Ysq_s,
                                              sumsq_s)
            leaves = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda a, b: jnp.max(jnp.abs(a - b)), p_new, p_c))
            dparam = lax.pmax(jnp.max(jnp.stack(leaves)), SERIES_AXIS)
            ll64 = jnp.asarray(ll, jnp.float64)
            row = jnp.stack([ll64, ll64 - ll_prev,
                             jnp.asarray(dparam, jnp.float64)])
            return (p_new, ll64), (ll, delta, row)

        ll0 = jnp.asarray(jnp.nan, jnp.float64)
        (p_f, _), (lls, deltas, metrics) = lax.scan(
            it, (p_s, ll0), None, length=n_iters)
        return p_f, lls, deltas, metrics

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs()),
        out_specs=(_param_specs(), P(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate"))
def _sharded_em_step_checked_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                                  cfg: EMConfig, has_mask: bool,
                                  has_gate: bool):
    """Debug-mode sharded EM step: checkify float checks AROUND the
    shard_map program (composes — a poisoned shard raises a located error
    through the psum; tested on the fake mesh).  See ``EMConfig.debug``."""
    from jax.experimental import checkify

    def f(Y, mask, gate, p):
        return _sharded_em_step_impl(Y, mask, gate, p, mesh, cfg,
                                     has_mask, has_gate)

    return checkify.checkify(f, errors=checkify.float_checks)(
        Y, mask, gate, p)


@partial(jax.jit, static_argnames=("mesh", "cfg", "has_mask", "has_gate",
                                   "n_iters"))
def _sharded_em_scan_checked_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                                  cfg: EMConfig, has_mask: bool,
                                  has_gate: bool, n_iters: int):
    """Debug-mode fused sharded chunk: the checkify error state threads
    through the iteration scan, so the raised error locates the first bad
    op across ALL fused iterations (sharded analog of
    ``estim.em._em_fit_scan_checked_impl``)."""
    from jax.experimental import checkify

    def f(Y, mask, gate, p):
        return _sharded_em_scan_impl(Y, mask, gate, p, mesh, cfg,
                                     has_mask, has_gate, n_iters)

    return checkify.checkify(f, errors=checkify.float_checks)(
        Y, mask, gate, p)


@partial(jax.jit, static_argnames=("mesh", "has_mask", "has_gate"))
def _sharded_smooth_impl(Y, mask, gate, p: SSMParams, mesh: Mesh,
                         has_mask: bool, has_gate: bool):
    def body(Y_s, mask_s, gate_s, p_s):
        kf, sm, _ = _shard_filter_smoother(
            Y_s, mask_s if has_mask else None, p_s,
            gate_s=gate_s if has_gate else None)
        return sm.x_sm, sm.P_sm, kf.loglik

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SERIES_AXIS), P(None, SERIES_AXIS),
                  P(SERIES_AXIS), _param_specs()),
        out_specs=(P(), P(), P()))
    if mask is None:
        mask = jnp.ones_like(Y)
    if gate is None:
        gate = jnp.ones((Y.shape[1],), Y.dtype)
    return mapped(Y, mask, gate, p)


class ShardedEM:
    """Driver wrapping padding + device placement + the jitted sharded step.

    Holds the padded device arrays across iterations so the Python convergence
    loop only moves the scalar loglik host-side each iteration.
    """

    def __init__(self, Y: np.ndarray, p0, mask: Optional[np.ndarray] = None,
                 mesh: Optional[Mesh] = None, dtype=jnp.float32,
                 cfg: EMConfig = EMConfig(), Y_dev=None):
        """``Y_dev``: an already-on-device copy of ``Y`` (e.g. the
        device-init panel cache) — reused instead of a fresh host->device
        transfer when no padding or mask forces a host-side rewrite."""
        self.mesh = mesh if mesh is not None else make_mesh()
        n_shards = self.mesh.devices.size
        Lam0 = np.asarray(p0.Lam)
        R0 = np.asarray(p0.R)
        # Decide device-copy reuse BEFORE touching Y's values: when the
        # cached device panel applies (no padding, no mask, right shape and
        # dtype), Y may itself BE a device array (api.fit's device-side
        # prep) and np.asarray(Y) would pay a ~0.7 s device->host transfer
        # just to rebuild what we already hold.
        use_dev = (Y_dev is not None and mask is None
                   and (-Y.shape[1]) % n_shards == 0
                   and Y_dev.dtype == jnp.dtype(dtype)
                   and Y_dev.shape == Y.shape)
        if use_dev:
            Yp, Wp, Lp, Rp, self.n_pad = Y, mask, Lam0, R0, 0
        else:
            Yp, Wp, Lp, Rp, self.n_pad = pad_panel(
                np.asarray(Y, np.float64), mask, Lam0, R0, n_shards)
        # A REAL mask (user-supplied / NaN pattern) selects the masked code
        # paths; mesh-divisibility padding alone does NOT — it is handled by
        # the row gate so unmasked panels keep the cheap time-invariant
        # stats and the ss engine (see _shard_filter_smoother).
        self.has_mask = mask is not None
        self.has_gate = self.n_pad > 0 and not self.has_mask
        # "info" and "ss" are the sharded E-step implementations; anything
        # else (dense/pit/auto) maps to the exact info scan.
        if cfg.filter != "ss":
            cfg = dataclasses.replace(cfg, filter="info")
        self.cfg = cfg
        self.Y = Y_dev if use_dev else jnp.asarray(Yp, dtype)
        tr = current_tracer()
        if tr is not None and not use_dev:
            tr.emit("transfer", direction="h2d", what="panel",
                    key=shape_key(self.Y),
                    bytes=int(self.Y.size * self.Y.dtype.itemsize))
        self.mask = jnp.asarray(Wp, dtype) if self.has_mask else None
        self.gate = (jnp.asarray(
            np.concatenate([np.ones(Y.shape[1]), np.zeros(self.n_pad)]),
            dtype) if self.has_gate else None)
        self.p = SSMParams(
            Lam=jnp.asarray(Lp, dtype), A=jnp.asarray(p0.A, dtype),
            Q=jnp.asarray(p0.Q, dtype), R=jnp.asarray(Rp, dtype),
            mu0=jnp.asarray(p0.mu0, dtype), P0=jnp.asarray(p0.P0, dtype))

    def step(self):
        """One EM iteration; returns loglik at the entering params.

        With ``cfg.debug`` the step is checkified (located error on the
        first NaN/inf any primitive produces, shard_map included)."""
        args = (self.Y, self.mask, self.gate, self.p, self.mesh, self.cfg,
                self.has_mask, self.has_gate)
        if self.cfg.debug:
            err, out = _sharded_em_step_checked_impl(*args)
            err.throw()
            self.p, ll, self.last_delta = out
            return ll
        tr = current_tracer()
        if tr is None:
            self.p, ll, self.last_delta = _sharded_em_step_impl(*args)
            return ll
        with tr.dispatch("sharded_em_step", self._trace_key()):
            self.p, ll, self.last_delta = _sharded_em_step_impl(*args)
        return ll

    def run_scan(self, p: SSMParams, n_iters: int, with_metrics: bool = False,
                 n_active=None):
        """n fused EM iterations from ``p`` (does NOT update ``self.p``).

        Returns (params, logliks (n,), ss_deltas (n,)) — the sharded analog
        of ``estim.em.em_fit_scan``, one XLA dispatch total.  With
        ``cfg.debug`` the whole fused chunk is checkified.
        ``with_metrics`` appends a per-iteration (n, 3) metrics block
        (loglik, delta, max param-update) via the metrics twin program;
        the debug path has no metrics twin and returns ``None`` for it.
        ``n_active`` (bucketed mode): ``n_iters`` becomes the static bucket
        length and ``n_active`` the traced count of advancing iterations —
        see ``estim.em.em_fit_scan``; callers slice outputs ``[:n_active]``.
        """
        if n_active is not None:
            if self.cfg.debug:
                raise ValueError(
                    "bucketed scans (n_active=) have no debug/checkify "
                    "twin — run debug fits unbucketed")
            impl = (_sharded_em_scan_active_metrics_impl if with_metrics
                    else _sharded_em_scan_active_impl)
            args = (self.Y, self.mask, self.gate, p,
                    jnp.asarray(n_active, jnp.int32), self.mesh, self.cfg,
                    self.has_mask, self.has_gate, n_iters)
            tr = current_tracer()
            if tr is None:
                return impl(*args)
            with tr.dispatch("sharded_em_chunk",
                             shape_key(self._trace_key(),
                                       f"iters{n_iters}b"),
                             n_iters=n_iters, bucket=n_iters):
                return impl(*args)
        args = (self.Y, self.mask, self.gate, p, self.mesh, self.cfg,
                self.has_mask, self.has_gate, n_iters)
        if self.cfg.debug:
            err, out = _sharded_em_scan_checked_impl(*args)
            err.throw()
            return out + (None,) if with_metrics else out
        impl = (_sharded_em_scan_metrics_impl if with_metrics
                else _sharded_em_scan_impl)
        tr = current_tracer()
        if tr is None:
            return impl(*args)
        # Suppressed when a chunk driver's barrier'd span is already open;
        # direct callers (dryrun) get the async-dispatch record.
        with tr.dispatch("sharded_em_chunk",
                         shape_key(self._trace_key(), f"iters{n_iters}"),
                         n_iters=n_iters):
            return impl(*args)

    def _trace_key(self) -> str:
        return shape_key(self.Y, self.cfg.filter,
                         f"mesh{self.mesh.devices.size}")

    def smooth(self):
        tr = current_tracer()
        if tr is None:
            return _sharded_smooth_impl(
                self.Y, self.mask, self.gate, self.p, self.mesh,
                self.has_mask, self.has_gate)
        with tr.dispatch("sharded_smooth", self._trace_key()):
            return _sharded_smooth_impl(
                self.Y, self.mask, self.gate, self.p, self.mesh,
                self.has_mask, self.has_gate)

    def params_numpy(self, p: Optional[SSMParams] = None):
        """Unpadded float64 copy of ``p`` (default: current params)."""
        from ..backends.cpu_ref import SSMParams as NpParams
        p = self.p if p is None else p
        return NpParams(
            Lam=unpad_rows(np.asarray(p.Lam, np.float64), self.n_pad),
            A=np.asarray(p.A, np.float64), Q=np.asarray(p.Q, np.float64),
            R=unpad_rows(np.asarray(p.R, np.float64), self.n_pad),
            mu0=np.asarray(p.mu0, np.float64),
            P0=np.asarray(p.P0, np.float64))

    def params_device(self, p_np) -> SSMParams:
        """Inverse of ``params_numpy``: re-pad a host params pytree and put
        it back on the device (zero loading rows / unit variances for the
        padded series — the same no-contribution contract as ``pad_panel``).
        The robustness guard uses this to restore or repair params between
        fused chunks."""
        dt = self.Y.dtype
        Lam = np.asarray(p_np.Lam, np.float64)
        R = np.asarray(p_np.R, np.float64)
        if self.n_pad:
            k = Lam.shape[1]
            Lam = np.concatenate([Lam, np.zeros((self.n_pad, k))], axis=0)
            R = np.concatenate([R, np.ones(self.n_pad)], axis=0)
        return SSMParams(
            Lam=jnp.asarray(Lam, dt), A=jnp.asarray(p_np.A, dt),
            Q=jnp.asarray(p_np.Q, dt), R=jnp.asarray(R, dt),
            mu0=jnp.asarray(p_np.mu0, dt), P0=jnp.asarray(p_np.P0, dt))


def _sharded_cfg(cfg: EMConfig) -> EMConfig:
    return cfg if cfg.filter == "ss" else dataclasses.replace(cfg,
                                                              filter="info")


def sharded_em_step(Y, p, mask=None, mesh=None, cfg: EMConfig = EMConfig()):
    """Functional one-shot sharded EM step (shapes must already divide).

    Returns (params, loglik, ss_delta)."""
    mesh = mesh if mesh is not None else make_mesh()
    return _sharded_em_step_impl(Y, mask, None, p, mesh, _sharded_cfg(cfg),
                                 mask is not None, False)


def sharded_em_scan(Y, p, n_iters: int, mask=None, mesh=None,
                    cfg: EMConfig = EMConfig()):
    """n fused sharded EM iterations in one XLA program (shapes must already
    divide the mesh).  Returns (params, logliks (n,), ss_deltas (n,))."""
    mesh = mesh if mesh is not None else make_mesh()
    return _sharded_em_scan_impl(Y, mask, None, p, mesh, _sharded_cfg(cfg),
                                 mask is not None, False, n_iters)


def sharded_filter_smoother(Y, p, mask=None, mesh=None):
    mesh = mesh if mesh is not None else make_mesh()
    return _sharded_smooth_impl(Y, mask, None, p, mesh, mask is not None,
                                False)


def sharded_em_fit(Y, p0, mask=None, mesh=None, cfg: EMConfig = EMConfig(),
                   max_iters: int = 50, tol: float = 1e-6, dtype=jnp.float32,
                   callback=None, Y_dev=None,
                   matmul_precision: str = "highest"):
    """EM driver over the mesh; mirrors ``estim.em.em_fit``'s contract,
    including the callback receiving the (unpadded) params the loglik was
    evaluated at.  Returns (params, logliks, converged, driver).
    ``Y_dev``: see ``ShardedEM``.

    ``matmul_precision`` defaults to "highest" like every standalone fit
    driver: the MXU's bf16 input rounding at the default setting costs
    ~1e-4 relative loglik — outside the 1e-5 oracle contract (docs/PERF.md
    item 2).  ``ShardedBackend`` already wraps this call in its own
    precision context; direct callers get the same protection here.
    """
    import jax
    with jax.default_matmul_precision(matmul_precision):
        return _sharded_em_fit_body(Y, p0, mask, mesh, cfg, max_iters, tol,
                                    dtype, callback, Y_dev)


def _sharded_em_fit_body(Y, p0, mask, mesh, cfg, max_iters, tol, dtype,
                         callback, Y_dev):
    drv = ShardedEM(Y, p0, mask=mask, mesh=mesh, dtype=dtype, cfg=cfg,
                    Y_dev=Y_dev)

    entering = prev_entering = drv.p
    max_delta = 0.0

    def step(it):
        nonlocal entering, prev_entering, max_delta
        prev_entering = entering
        entering = drv.p
        ll = drv.step()
        if drv.cfg.filter == "ss":
            max_delta = max(max_delta, float(drv.last_delta))
        # Only materialize host params when someone is listening.
        cb_params = (drv.params_numpy(entering)
                     if callback is not None else None)
        return ll, cb_params

    from ..estim.em import cfg_hypers, noise_floor_for, warn_ss_delta
    lls, converged, em_state = run_em_loop(
        step, max_iters, tol, callback,
        noise_floor=noise_floor_for(drv.Y.dtype, drv.Y.size,
                                    mult=drv.cfg.noise_floor_mult),
        monotone=cfg_hypers(drv.cfg) is None)
    if drv.cfg.filter == "ss":
        warn_ss_delta(max_delta, drv.cfg.tau)
    drv.p_iters = len(lls)
    if em_state == "diverged":
        # The drop at iteration j was caused by the update in j-1: hand back
        # the params entering j-1 (the last pre-drop loglik's params).
        drv.p = prev_entering
        drv.p_iters = max(len(lls) - 2, 0)
    return drv.params_numpy(), np.asarray(lls), converged, drv

"""Panel data preparation (reference component R2, SURVEY.md section 2.1).

Column standardization to mean 0 / variance 1 before factor extraction, with
mask/NaN awareness, plus lag-matrix helpers for factor-augmented regressions.
All NumPy: data prep happens once on host, the device path starts afterwards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Standardizer:
    """Per-series affine transform y -> (y - mean) / scale and its inverse."""

    mean: np.ndarray   # (N,)
    scale: np.ndarray  # (N,)

    def transform(self, Y: np.ndarray) -> np.ndarray:
        return (Y - self.mean) / self.scale

    def inverse(self, Z: np.ndarray) -> np.ndarray:
        return Z * self.scale + self.mean


def standardize(Y: np.ndarray, mask: Optional[np.ndarray] = None
                ) -> Tuple[np.ndarray, Standardizer]:
    """Standardize each series over its *observed* entries.

    NaNs in ``Y`` are treated as missing regardless of ``mask``.  Returns the
    standardized panel (missing entries left as NaN) and the transform.
    """
    Y = np.asarray(Y, dtype=np.float64)
    obs = np.isfinite(Y)
    if mask is not None:
        obs &= np.asarray(mask) > 0
    W = obs.astype(np.float64)
    counts = np.maximum(W.sum(0), 1.0)
    Yz = np.where(obs, Y, 0.0)
    mean = Yz.sum(0) / counts
    var = (W * (Yz - mean) ** 2).sum(0) / np.maximum(counts - 1.0, 1.0)
    scale = np.sqrt(np.maximum(var, 1e-12))
    Z = np.where(obs, (Y - mean) / scale, np.nan)
    return Z, Standardizer(mean, scale)


def standardize_onepass(Y: np.ndarray, out_dtype=np.float64
                        ) -> Tuple[np.ndarray, Standardizer]:
    """One-pass standardize for FULLY-OBSERVED panels, emitting ``out_dtype``.

    The mask-aware ``standardize`` makes two f64 passes over the panel plus
    an f64 output it then casts — ~0.55 s of the 2.2 s warm fit on a 40 MB
    panel (docs/PERF.md fixed-cost table).  Here mean and variance come from
    a single fused pass (sum and sum-of-squares accumulated in f64), and the
    output is written directly in the backend's compute dtype, so an f32
    backend never materializes the f64 intermediate.

    Same ddof-1 / 1e-12 variance-floor semantics as ``standardize``.  The
    shifted-moment variance cancels for data offset ~1e7 * sd from zero
    (sum-of-squares rounding); panels that extreme should be de-meaned
    upstream — economic panels are nowhere near it.
    """
    Y = np.asarray(Y)
    T = Y.shape[0]
    s1 = Y.sum(axis=0, dtype=np.float64)
    s2 = np.einsum("ti,ti->i", Y, Y, dtype=np.float64)
    mean = s1 / T
    var = (s2 - T * mean * mean) / max(T - 1.0, 1.0)
    scale = np.sqrt(np.maximum(var, 1e-12))
    inv = (1.0 / scale).astype(out_dtype)
    Z = (Y.astype(out_dtype, copy=False) - mean.astype(out_dtype)) * inv
    return Z, Standardizer(mean, scale)


def validate_panel(Y: np.ndarray, mask: Optional[np.ndarray] = None,
                   check_variance: bool = True) -> None:
    """Reject panels that poison standardization/EM downstream.

    Raises ``ValueError`` naming the offending column indices when a series
    has NO observed entries (its mean/scale are undefined — the zero-fill
    would fabricate data) or, with ``check_variance``, when an observed
    series is constant (scale hits the 1e-12 floor and the standardized
    column explodes to ~1e6-magnitude values that dominate the PCA init).
    """
    Y = np.asarray(Y, dtype=np.float64)
    obs = np.isfinite(Y)
    if mask is not None:
        obs &= np.asarray(mask) > 0
    counts = obs.sum(0)
    dead = np.flatnonzero(counts == 0)
    if dead.size:
        raise ValueError(
            f"column(s) {dead.tolist()} have no observed entries "
            "(all-NaN / fully masked); drop them before fitting")
    if not check_variance:
        return
    W = obs.astype(np.float64)
    Yz = np.where(obs, Y, 0.0)
    mean = Yz.sum(0) / np.maximum(counts, 1.0)
    var = (W * (Yz - mean) ** 2).sum(0) / np.maximum(counts - 1.0, 1.0)
    flat = np.flatnonzero((counts > 1) & (var < 1e-12))
    if flat.size:
        raise ValueError(
            f"column(s) {flat.tolist()} have zero variance over their "
            "observed entries; standardization would divide by ~0 — drop "
            "or de-constant them before fitting")


def build_mask(Y: np.ndarray, mask: Optional[np.ndarray] = None) -> np.ndarray:
    """{0,1} observation mask from explicit mask and/or NaN pattern."""
    obs = np.isfinite(np.asarray(Y, dtype=np.float64))
    if mask is not None:
        obs &= np.asarray(mask) > 0
    return obs.astype(np.float64)


def lag_matrix(x: np.ndarray, lags: int) -> np.ndarray:
    """Stack [x_{t-1}, ..., x_{t-lags}] rows for t = lags..T-1.

    x: (T,) or (T, d).  Returns (T - lags, lags * d)."""
    x = np.asarray(x, dtype=np.float64)
    if x.ndim == 1:
        x = x[:, None]
    T, d = x.shape
    cols = [x[lags - j - 1:T - j - 1] for j in range(lags)]
    return np.concatenate(cols, axis=1)

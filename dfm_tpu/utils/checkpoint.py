"""EM checkpoint/resume (SURVEY.md section 5, checkpoint row).

EM state is a small pytree (Lam, A, Q, R, mu0, P0) plus the loglik history
and iteration counter — ``numpy.savez`` is the right tool (orbax would be
overkill for kilobytes of dense arrays; no sharded state ever needs saving
because params are replicated or trivially gatherable).  ``api.fit`` wires
this up via ``checkpoint_path`` / ``checkpoint_every`` and resumes
automatically from a compatible checkpoint.

Checkpoints carry a data/model fingerprint (hash of the panel bytes, mask
pattern and model config — ADVICE r1 item 2) so a checkpoint from a
different dataset that happens to share (N, k) is never silently used as a
warm start; the stored ``iter`` counts the EM iterations the params embody,
letting ``fit`` resume with the remaining budget instead of starting the
iteration count over.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import Optional, Tuple

import numpy as np

from ..backends.cpu_ref import SSMParams

__all__ = ["save_checkpoint", "load_checkpoint", "data_fingerprint",
           "warm_fingerprint", "panel_fingerprint", "panel_mismatch",
           "SNAPSHOT_SCHEMA_VERSION", "check_schema_version",
           "fsync_dir"]

_FIELDS = ("Lam", "A", "Q", "R", "mu0", "P0")

# Stamped into every npz this module writes.  Bump when the on-disk
# layout changes incompatibly; readers refuse FUTURE versions loudly
# (check_schema_version) instead of surfacing a format drift as an
# opaque KeyError deep in restore.
SNAPSHOT_SCHEMA_VERSION = 1


def check_schema_version(z, path: str) -> None:
    """Refuse snapshots written by a future schema, naming both versions.

    ``z`` is an open ``np.load`` handle (or any mapping with ``in`` /
    ``__getitem__``).  Files WITHOUT a stamp (pre-versioning) are
    accepted — they predate the scheme and their layout is version 1.
    Raises ``ValueError`` so callers that normally swallow corrupt files
    must re-raise it explicitly (a version refusal is actionable, a torn
    file is not)."""
    if "schema_version" not in z:
        return
    found = int(np.asarray(z["schema_version"]))
    if found > SNAPSHOT_SCHEMA_VERSION:
        raise ValueError(
            f"snapshot {path!r} carries schema_version={found}, but this "
            f"build reads schema_version<={SNAPSHOT_SCHEMA_VERSION}; it was "
            "written by a newer dfm_tpu — upgrade this process (or re-write "
            "the snapshot with the older build) instead of guessing at the "
            "layout")


def fsync_dir(d: str) -> None:
    """Best-effort fsync of a directory entry (makes a rename durable)."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def data_fingerprint(Y: np.ndarray, mask, model) -> str:
    """Stable hash of (panel bytes, mask pattern, model config)."""
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(np.asarray(Y, np.float64)).tobytes())
    if mask is not None:
        h.update(np.ascontiguousarray(
            np.asarray(mask, np.uint8)).tobytes())
    h.update(repr(model).encode())
    return h.hexdigest()


def warm_fingerprint(shape, model, has_missing: bool) -> str:
    """STRUCTURAL fingerprint for ``fit(warm_start=...)`` validation.

    Deliberately value-free (panel shape + model config + missing-data
    presence, NOT data bytes): warm-refitting on *updated values* of the
    same panel shape is the intended serving flow — recompiles only come
    from structural change, which is exactly what this hash captures.
    Contrast ``data_fingerprint`` (checkpoint/resume), which must reject
    different *data*."""
    h = hashlib.sha1()
    h.update(repr((tuple(int(d) for d in shape), repr(model),
                   bool(has_missing))).encode())
    return h.hexdigest()


def panel_fingerprint(Y: np.ndarray, mask=None) -> str:
    """CONTENT fingerprint of one (panel, mask) pair.

    Value-sensitive, model-free: two host copies of the same data hash
    equal, so the fused warm-refit device-panel cache can survive a
    ``Y.copy()`` between fits (the serving flow ``warm_fingerprint``
    deliberately ignores values for).  NaN patterns hash via the f64
    byte image (all payloads normalized by the asarray cast)."""
    Y = np.ascontiguousarray(np.asarray(Y, np.float64))
    h = hashlib.sha1()
    h.update(repr(Y.shape).encode())
    h.update(Y.tobytes())
    if mask is not None:
        h.update(b"mask")
        h.update(np.ascontiguousarray(np.asarray(mask, np.uint8)).tobytes())
    return h.hexdigest()


def panel_mismatch(Y_a, mask_a, Y_b, mask_b) -> Optional[str]:
    """Name the first differing field between two (panel, mask) pairs.

    Returns None when they are content-equal (NaNs compare equal — both
    encode "missing"), else a short human-readable reason — "panel shape",
    "panel dtype", "mask presence", "mask pattern", or "panel values" —
    used by the fused warm-refit cache to say WHY a re-upload happened."""
    A, B = np.asarray(Y_a), np.asarray(Y_b)
    if A.shape != B.shape:
        return f"panel shape ({A.shape} vs {B.shape})"
    if A.dtype != B.dtype:
        return f"panel dtype ({A.dtype} vs {B.dtype})"
    if (mask_a is None) != (mask_b is None):
        return "mask presence (one fit passed mask=, the other did not)"
    if mask_a is not None and not np.array_equal(np.asarray(mask_a),
                                                 np.asarray(mask_b)):
        return "mask pattern"
    if not np.array_equal(A, B, equal_nan=A.dtype.kind == "f"):
        return "panel values"
    return None


def save_checkpoint(path: str, params, it: int, logliks,
                    fingerprint: Optional[str] = None,
                    converged: bool = False,
                    extra: Optional[dict] = None) -> None:
    """Atomic durable write (tmp + fsync + rename) of EM state.

    ``extra``: additional arrays merged into the npz under their own keys
    (the serve-session snapshot stores its live panel + config here);
    ``load_checkpoint`` reads only the EM fields and ignores extras, so
    a session snapshot is ALSO a valid warm-start checkpoint.

    The tmp file is fsync'd before the rename and the directory entry
    after it, so a crash at ANY point leaves either the old snapshot or
    the new one — never a truncated npz.  Every file is stamped with
    ``schema_version`` (see ``check_schema_version``)."""
    arrays = {f: np.asarray(getattr(params, f), np.float64) for f in _FIELDS}
    arrays["iter"] = np.asarray(it)
    arrays["logliks"] = np.asarray(logliks, np.float64)
    arrays["converged"] = np.asarray(bool(converged))
    if fingerprint is not None:
        arrays["fingerprint"] = np.asarray(fingerprint)
    for k, v in (extra or {}).items():
        if k in arrays:
            raise ValueError(f"extra key {k!r} collides with an EM "
                             f"checkpoint field")
        arrays[k] = np.asarray(v)
    arrays.setdefault("schema_version", np.asarray(SNAPSHOT_SCHEMA_VERSION))
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str, fingerprint: Optional[str] = None,
                    on_mismatch: str = "ignore"
                    ) -> Optional[Tuple[SSMParams, int, np.ndarray, bool]]:
    """Returns (params, completed_iters, logliks, converged) or None if
    absent, unreadable, or fingerprint-mismatched.  When a fingerprint is
    expected, a checkpoint WITHOUT one (pre-fingerprint file) is also
    rejected — accepting it would silently warm-start from possibly-foreign
    params, the exact failure the fingerprint exists to prevent.

    ``on_mismatch``: "ignore" returns None on a fingerprint mismatch —
    ``fit`` uses it so foreign data cold-starts with the full iteration
    budget; "raise" raises ``ValueError`` instead, for callers who need
    pointing an existing checkpoint at CHANGED data to fail loudly rather
    than refit from scratch and overwrite the old state."""
    if on_mismatch not in ("ignore", "raise"):
        raise ValueError(f"on_mismatch must be 'ignore' or 'raise'; "
                         f"got {on_mismatch!r}")
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            check_schema_version(z, path)   # future-version refusal: loud
            matches = (fingerprint is None
                       or ("fingerprint" in z
                           and str(z["fingerprint"]) == fingerprint))
            if matches:
                params = SSMParams(*(z[f] for f in _FIELDS))
                converged = bool(z["converged"]) if "converged" in z else False
                out = (params, int(z["iter"]), np.asarray(z["logliks"]),
                       converged)
            else:
                out = None
    except ValueError:
        raise              # schema_version from the future — actionable
    except Exception:
        return None        # unreadable/corrupt file: caller starts fresh
    if out is None and on_mismatch == "raise":
        raise _fingerprint_error(path)
    return out


def _fingerprint_error(path: str) -> ValueError:
    return ValueError(
        f"checkpoint {path!r} was written for different data / mask / "
        "model (fingerprint mismatch); resuming would either warm-start "
        "from foreign params or silently overwrite the old run — delete "
        "the file or use a different checkpoint_path")

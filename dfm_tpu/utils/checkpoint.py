"""EM checkpoint/resume (SURVEY.md section 5, checkpoint row).

EM state is a small pytree (Lam, A, Q, R, mu0, P0) plus the loglik history
and iteration counter — ``numpy.savez`` is the right tool (orbax would be
overkill for kilobytes of dense arrays; no sharded state ever needs saving
because params are replicated or trivially gatherable).  ``api.fit`` wires
this up via ``checkpoint_path`` / ``checkpoint_every`` and resumes
automatically from a compatible checkpoint.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional, Tuple

import numpy as np

from ..backends.cpu_ref import SSMParams

__all__ = ["save_checkpoint", "load_checkpoint"]

_FIELDS = ("Lam", "A", "Q", "R", "mu0", "P0")


def save_checkpoint(path: str, params, it: int, logliks) -> None:
    """Atomic write (tmp + rename) of EM state."""
    arrays = {f: np.asarray(getattr(params, f), np.float64) for f in _FIELDS}
    arrays["iter"] = np.asarray(it)
    arrays["logliks"] = np.asarray(logliks, np.float64)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str) -> Optional[Tuple[SSMParams, int, np.ndarray]]:
    """Returns (params, next_iter, logliks) or None if absent/unreadable."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            params = SSMParams(*(z[f] for f in _FIELDS))
            return params, int(z["iter"]), np.asarray(z["logliks"])
    except Exception:
        return None

"""Observability helpers (SURVEY.md section 5: metrics/logging/tracing rows).

``JsonlLogger`` is a fit-callback that appends per-iteration records
(iter, loglik, dloglik, secs, iters/sec) to a JSONL file — the sink the
bench harness consumes.  ``profile_trace`` wraps ``jax.profiler.trace`` for
Perfetto dumps and degrades to a no-op where the profiler is unavailable
(the axon PJRT plugin does not support every profiler hook).
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Optional

__all__ = ["JsonlLogger", "profile_trace"]


class JsonlLogger:
    """Per-iteration EM record sink: pass as ``fit(..., callback=logger)``."""

    def __init__(self, path: str, extra: Optional[dict] = None):
        self.path = path
        self.extra = extra or {}
        self._t_prev = time.perf_counter()
        self._ll_prev = None

    def __call__(self, it: int, loglik: float, params=None) -> None:
        now = time.perf_counter()
        secs = now - self._t_prev
        self._t_prev = now
        rec = {
            "iter": int(it),
            "loglik": float(loglik),
            "dloglik": (None if self._ll_prev is None
                        else float(loglik) - self._ll_prev),
            "secs": secs,
            "iters_per_sec": (1.0 / secs) if secs > 0 else None,
            **self.extra,
        }
        self._ll_prev = float(loglik)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """``with profile_trace("/tmp/trace"):`` — Perfetto trace if possible."""
    if not log_dir:
        yield
        return
    import jax
    try:
        with jax.profiler.trace(log_dir):
            yield
    except Exception:
        yield

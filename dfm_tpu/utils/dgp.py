"""Factor-model data-generating processes for tests and benchmarks.

NumPy analog of the reference's ``factor_model_DGP`` (SURVEY.md R10 / section
3.3): draw loadings, simulate a stable factor VAR(1) path, add idiosyncratic
noise.  Deterministic given the seed; used by the
simulate -> estimate -> recover test spine (SURVEY.md section 4.2.3) and by the
benchmark configs S1-S5 (BASELINE.json:6-12).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..backends.cpu_ref import SSMParams, _solve_discrete_lyapunov_or_eye


def stable_var1(k: int, rng: np.random.Generator,
                spectral_radius: float = 0.7) -> np.ndarray:
    """Random k x k transition with spectral radius scaled to the target."""
    A = rng.standard_normal((k, k))
    ev = np.max(np.abs(np.linalg.eigvals(A)))
    return A * (spectral_radius / max(ev, 1e-12))


def dfm_params(N: int, k: int, rng: np.random.Generator,
               static: bool = False,
               noise_scale: float = 1.0,
               spectral_radius: float = 0.7) -> SSMParams:
    """Draw a random, identifiable-ish parameter set."""
    Lam = rng.standard_normal((N, k))
    if static:
        A = np.zeros((k, k))
        Q = np.eye(k)
    else:
        A = stable_var1(k, rng, spectral_radius)
        Q = np.eye(k)
    R = noise_scale * (0.5 + rng.random(N))      # heteroskedastic diag
    mu0 = np.zeros(k)
    P0 = _solve_discrete_lyapunov_or_eye(A, Q)
    return SSMParams(Lam, A, Q, R, mu0, P0)


def simulate(p: SSMParams, T: int, rng: np.random.Generator
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Simulate (Y (T,N), F (T,k)) from the state-space model."""
    N, k = p.Lam.shape
    Lq = np.linalg.cholesky(p.Q + 1e-12 * np.eye(k))
    L0 = np.linalg.cholesky(p.P0 + 1e-12 * np.eye(k))
    F = np.zeros((T, k))
    f = p.mu0 + L0 @ rng.standard_normal(k)
    for t in range(T):
        if t > 0:
            f = p.A @ F[t - 1] + Lq @ rng.standard_normal(k)
        F[t] = f
    E = rng.standard_normal((T, N)) * np.sqrt(p.R)
    Y = F @ p.Lam.T + E
    return Y, F


def random_mask(T: int, N: int, rng: np.random.Generator,
                frac_missing: float = 0.1) -> np.ndarray:
    """{0,1} observation mask with i.i.d. missingness."""
    return (rng.random((T, N)) >= frac_missing).astype(np.float64)


def mixed_freq_mask(T: int, N: int, n_quarterly: int) -> np.ndarray:
    """Monthly/quarterly mask: last ``n_quarterly`` series observed every 3rd
    period only (months 3, 6, ... -> indices 2, 5, ...), per the
    Mariano-Murasawa setup of SURVEY.md section 3.4."""
    mask = np.ones((T, N))
    q = np.zeros(T)
    q[2::3] = 1.0
    mask[:, N - n_quarterly:] = q[:, None]
    return mask


def simulate_mixed_freq(n_monthly: int, n_quarterly: int, T: int, k: int,
                        rng: np.random.Generator,
                        weights=(1.0, 2.0, 3.0, 2.0, 1.0),
                        noise_scale: float = 1.0):
    """Mixed-frequency DGP (config S3, BASELINE.json:9; SURVEY.md section 3.4).

    Monthly series load on f_t; quarterly series load on the Mariano-Murasawa
    weighted lag combination g_t = sum_j w_j f_{t-j} (w = [1,2,3,2,1]/3) and
    are observed only at months 3, 6, ... (indices 2, 5, ...).

    Returns (Y (T, Nm+Nq) with NaN at unobserved, mask, F (T, k), truth dict).
    """
    wv = np.asarray(weights, np.float64) / 3.0
    L = len(wv)
    A = stable_var1(k, rng)
    F = np.zeros((T + L - 1, k))
    f = rng.standard_normal(k)
    for t in range(T + L - 1):
        if t > 0:
            f = A @ F[t - 1] + rng.standard_normal(k)
        F[t] = f
    Fw = F[L - 1:]                                 # aligned current factor
    G = sum(wv[j] * F[L - 1 - j: L - 1 - j + T] for j in range(L))
    Lam_m = rng.standard_normal((n_monthly, k))
    Lam_q = rng.standard_normal((n_quarterly, k))
    R = noise_scale * (0.5 + rng.random(n_monthly + n_quarterly))
    Ym = Fw @ Lam_m.T + rng.standard_normal((T, n_monthly)) * np.sqrt(
        R[:n_monthly])
    Yq = G @ Lam_q.T + rng.standard_normal((T, n_quarterly)) * np.sqrt(
        R[n_monthly:])
    Y = np.concatenate([Ym, Yq], axis=1)
    mask = mixed_freq_mask(T, n_monthly + n_quarterly, n_quarterly)
    Y = np.where(mask > 0, Y, np.nan)
    truth = {"Lam_m": Lam_m, "Lam_q": Lam_q, "A": A, "R": R, "G": G}
    return Y, mask, Fw, truth


def simulate_tv_loadings(N: int, T: int, k: int, rng: np.random.Generator,
                         walk_scale: float = 0.02,
                         noise_scale: float = 1.0):
    """Random-walk-loadings DGP (config S4, BASELINE.json:10).

    lam_{i,t} = lam_{i,t-1} + walk_scale * xi,  y_t = Lam_t f_t + eps.
    Returns (Y, F, Lams (T,N,k), A (k,k), R (N,))."""
    A = stable_var1(k, rng)
    F = np.zeros((T, k))
    f = rng.standard_normal(k)
    for t in range(T):
        if t > 0:
            f = A @ F[t - 1] + rng.standard_normal(k)
        F[t] = f
    Lam0 = rng.standard_normal((N, k))
    steps = walk_scale * rng.standard_normal((T, N, k))
    steps[0] = 0.0
    Lams = Lam0[None] + np.cumsum(steps, axis=0)
    R = noise_scale * (0.5 + rng.random(N))
    Y = np.einsum("tnk,tk->tn", Lams, F) + rng.standard_normal((T, N)) * np.sqrt(R)
    return Y, F, Lams, A, R


def simulate_sv(N: int, T: int, k: int, rng: np.random.Generator,
                vol_walk_scale: float = 0.05):
    """Stochastic-volatility DGP (config S5, BASELINE.json:11).

    Factor innovation log-variances follow random walks:
        h_t = h_{t-1} + vol_walk_scale * xi,   Q_t = diag(exp(h_t)).
    Returns (Y, F, H (T,k), params-without-SV for RBPF init)."""
    A = stable_var1(k, rng)
    Lam = rng.standard_normal((N, k))
    R = 0.5 + rng.random(N)
    H = np.cumsum(np.r_[np.zeros((1, k)),
                        vol_walk_scale * rng.standard_normal((T - 1, k))], axis=0)
    F = np.zeros((T, k))
    f = rng.standard_normal(k)
    for t in range(T):
        if t > 0:
            f = A @ F[t - 1] + np.exp(0.5 * H[t]) * rng.standard_normal(k)
        F[t] = f
    Y = F @ Lam.T + rng.standard_normal((T, N)) * np.sqrt(R)
    p = SSMParams(Lam, A, np.eye(k), R, np.zeros(k), np.eye(k))
    return Y, F, H, p

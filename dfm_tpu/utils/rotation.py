"""Factor-space alignment utilities (SURVEY.md section 4.2.3).

Factor models are identified only up to an invertible k x k rotation; raw
loadings/factors from two fits are not comparable entrywise.  These helpers
produce the least-squares alignment map and rotation-invariant comparison
metrics, used by recovery tests and available to users comparing fits.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["procrustes", "align_factors", "factor_r2", "trace_r2"]


def procrustes(F_hat: np.ndarray, F_ref: np.ndarray) -> np.ndarray:
    """Orthogonal Procrustes: rotation O minimizing ||F_hat O - F_ref||_F."""
    U, _, Vt = np.linalg.svd(np.asarray(F_hat).T @ np.asarray(F_ref),
                             full_matrices=False)
    return U @ Vt


def align_factors(F_hat: np.ndarray, F_ref: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """General least-squares alignment (rotation + scale): returns
    (F_hat @ B, B) with B = argmin ||F_hat B - F_ref||."""
    F_hat = np.asarray(F_hat, np.float64)
    F_ref = np.asarray(F_ref, np.float64)
    B, *_ = np.linalg.lstsq(F_hat, F_ref, rcond=None)
    return F_hat @ B, B


def factor_r2(F_hat: np.ndarray, F_ref: np.ndarray) -> np.ndarray:
    """Per-reference-factor R^2 of the aligned estimate (1 = recovered)."""
    aligned, _ = align_factors(F_hat, F_ref)
    resid = F_ref - aligned
    return 1.0 - resid.var(axis=0) / np.maximum(F_ref.var(axis=0), 1e-300)


def trace_r2(F_hat: np.ndarray, F_ref: np.ndarray) -> float:
    """Trace R^2 (canonical-correlation style summary in [0, 1])."""
    aligned, _ = align_factors(F_hat, F_ref)
    num = np.sum((F_ref - aligned) ** 2)
    den = np.sum((F_ref - F_ref.mean(0)) ** 2)
    return float(1.0 - num / max(den, 1e-300))

"""Cross-run perf/convergence regression gate (jax-free CLI).

Diffs a candidate run against either an explicit baseline
(``--against <run_id|file>``) or the registry's best-of-history for the
same config fingerprint.  Thresholds are noise-aware: the historical
baseline per metric is the *median of the best N* recorded values
(axon-tunnel walls drift run to run; a single lucky best would
over-trigger), and the tolerance band is relative (default 30% — wide
enough for tunnel jitter, far inside the 2x-slowdown gate the acceptance
criteria require).

Exit codes: 0 = no regression, 1 = perf or convergence regression,
2 = usage error (unknown run, unreadable file, empty registry).

::

    python -m dfm_tpu.obs.regress [candidate] [--against <run|file>]
        [--runs DIR] [--tol 0.30] [--loglik-rtol 1e-3] [--best-n 5]
        [--json]

``candidate`` defaults to the latest recorded run; it may also be a
run_id or a path to a JSON file (a RunRecord or a raw ``bench.py``
output line).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

from .store import (RunStore, lower_is_better, noise_floor,
                    record_from_bench_json, runs_dir)

DEFAULT_TOL = 0.30
DEFAULT_LOGLIK_RTOL = 1e-3


class UsageError(Exception):
    pass


def _load_record(spec: str, store: Optional[RunStore]) -> Dict[str, Any]:
    """Resolve a run_id-or-path spec to a RunRecord dict."""
    if os.path.exists(spec):
        try:
            with open(spec) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise UsageError("cannot read %s: %s" % (spec, e))
        if isinstance(obj, dict) and "metrics" in obj and "run_id" in obj:
            return obj
        if isinstance(obj, dict) and "parsed" in obj:   # BENCH_r* wrapper
            obj = obj["parsed"]
        if isinstance(obj, dict) and "metric" in obj:   # raw bench line
            return record_from_bench_json(obj, source=spec)
        raise UsageError("%s is not a RunRecord or bench JSON" % spec)
    if store is None:
        raise UsageError("no runs dir and %s is not a file" % spec)
    rec = store.get(spec)
    if rec is None:
        raise UsageError("run %r not found in %s" % (spec, store.file))
    return rec


def record_from_trace_summary(summary: Dict[str, Any], *,
                              source: str = "trace") -> Dict[str, Any]:
    """Adapt an ``obs.report.summarize`` dict into a pseudo-RunRecord so
    two traces (or a trace and a recorded run) diff through the same gate
    (``obs.report --diff``)."""
    metrics: Dict[str, float] = {}
    for k in ("amortized_ms_per_iter", "wall_s"):
        v = summary.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[k] = float(v)
    for k, v in (summary.get("phases") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[k] = float(v)
    # Latency-percentile + advisor-drift metrics (PR 7): both
    # lower-is-better with their own noise floors (store.py).
    p99 = (summary.get("dispatch_percentiles_ms") or {}).get("p99")
    if isinstance(p99, (int, float)) and not isinstance(p99, bool):
        metrics["p99_dispatch_ms"] = float(p99)
    rel = (summary.get("advice") or {}).get("rel_err")
    if isinstance(rel, (int, float)) and not isinstance(rel, bool):
        metrics["advice_rel_err"] = float(rel)
    rec: Dict[str, Any] = {
        "run_id": source, "kind": "trace", "source": source,
        "config": {"kind": "trace"}, "fingerprint": "kind=trace",
        "metrics": metrics,
        "dispatches": summary.get("dispatches"),
        "recompiles": summary.get("recompiles"),
    }
    conv = summary.get("convergence") or {}
    ll = conv.get("loglik_last")
    if isinstance(ll, (int, float)) and not isinstance(ll, bool):
        rec["loglik"] = float(ll)
    return rec


def compare(cand: Dict[str, Any], baselines: Dict[str, float],
            base_loglik: Optional[float], *, tol: float = DEFAULT_TOL,
            loglik_rtol: float = DEFAULT_LOGLIK_RTOL,
            baseline_label: str = "history") -> Dict[str, Any]:
    """Diff candidate metrics against per-metric baseline values.

    A perf regression is a candidate worse than baseline by more than
    ``tol`` relative (direction per :func:`store.lower_is_better`); a
    convergence regression is a final loglik *below* baseline by more
    than ``loglik_rtol`` relative."""
    checks: List[Dict[str, Any]] = []
    for metric, base in sorted(baselines.items()):
        c = cand.get("metrics", {}).get(metric)
        if c is None or base is None:
            continue
        lower = lower_is_better(metric)
        ratio = (c / base) if base else float("inf")
        ok = ratio <= 1.0 + tol if lower else ratio >= 1.0 - tol
        sub_noise = False
        if not ok and lower and abs(c - base) <= noise_floor(metric):
            ok = sub_noise = True      # out of band but below unit floor
        checks.append({"metric": metric, "candidate": c, "baseline": base,
                       "ratio": ratio, "tol": tol,
                       "direction": "lower" if lower else "higher",
                       "sub_noise": sub_noise, "ok": bool(ok)})
    ll_check = None
    c_ll = cand.get("loglik")
    if c_ll is not None and base_loglik is not None:
        rel = (c_ll - base_loglik) / max(1.0, abs(base_loglik))
        ll_check = {"candidate": c_ll, "baseline": base_loglik,
                    "rel": rel, "rtol": loglik_rtol,
                    "ok": bool(rel >= -loglik_rtol)}
    ok = all(c["ok"] for c in checks) and (ll_check is None
                                           or ll_check["ok"])
    return {"candidate": cand.get("run_id"),
            "fingerprint": cand.get("fingerprint"),
            "baseline": baseline_label, "checks": checks,
            "loglik": ll_check, "n_checked": len(checks), "ok": bool(ok)}


def diff_against_history(cand: Dict[str, Any], store: RunStore, *,
                         tol: float = DEFAULT_TOL,
                         loglik_rtol: float = DEFAULT_LOGLIK_RTOL,
                         best_n: int = 5) -> Dict[str, Any]:
    fp = cand.get("fingerprint")
    baselines = {}
    for metric in cand.get("metrics", {}):
        b = store.baseline(fp, metric, best_n=best_n,
                           exclude_run=cand.get("run_id"))
        if b is not None:
            baselines[metric] = b
    base_ll = store.baseline_loglik(fp, exclude_run=cand.get("run_id"))
    return compare(cand, baselines, base_ll, tol=tol,
                   loglik_rtol=loglik_rtol,
                   baseline_label="best-of-history(n=%d)" % best_n)


def diff_records(cand: Dict[str, Any], base: Dict[str, Any], *,
                 tol: float = DEFAULT_TOL,
                 loglik_rtol: float = DEFAULT_LOGLIK_RTOL
                 ) -> Dict[str, Any]:
    return compare(cand, dict(base.get("metrics", {})),
                   base.get("loglik"), tol=tol, loglik_rtol=loglik_rtol,
                   baseline_label=base.get("run_id") or "baseline")


def print_diff(d: Dict[str, Any], file=None) -> None:
    file = file or sys.stdout
    print("regress: candidate %s vs %s"
          % (d.get("candidate"), d.get("baseline")), file=file)
    for c in d["checks"]:
        arrow = "<=" if c["direction"] == "lower" else ">="
        print("  [%s] %-42s %.4g vs %.4g (ratio %.3f, need %s %.2f)%s"
              % ("ok" if c["ok"] else "REGRESSION", c["metric"],
                 c["candidate"], c["baseline"], c["ratio"], arrow,
                 1.0 + c["tol"] if c["direction"] == "lower"
                 else 1.0 - c["tol"],
                 " [sub-noise]" if c.get("sub_noise") else ""), file=file)
    ll = d.get("loglik")
    if ll is not None:
        print("  [%s] %-42s %.6g vs %.6g (rel %.3g, floor -%.1g)"
              % ("ok" if ll["ok"] else "REGRESSION", "final loglik",
                 ll["candidate"], ll["baseline"], ll["rel"], ll["rtol"]),
              file=file)
    if not d["checks"] and ll is None:
        print("  (no comparable metrics — nothing gated)", file=file)
    print("regress: %s" % ("OK" if d["ok"] else "REGRESSION"), file=file)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.regress",
        description="Perf/convergence regression gate (jax-free).")
    ap.add_argument("candidate", nargs="?", default=None,
                    help="run_id or JSON file (default: latest run)")
    ap.add_argument("--against", default=None,
                    help="baseline run_id or JSON file "
                         "(default: best-of-history)")
    ap.add_argument("--runs", default=None)
    ap.add_argument("--tol", type=float, default=DEFAULT_TOL)
    ap.add_argument("--loglik-rtol", type=float,
                    default=DEFAULT_LOGLIK_RTOL)
    ap.add_argument("--best-n", type=int, default=5)
    ap.add_argument("--json", action="store_true")
    a = ap.parse_args(argv)

    d = runs_dir(a.runs)
    store = RunStore(d) if d is not None else None
    try:
        if a.candidate is None:
            if store is None:
                raise UsageError("no candidate given and no runs dir")
            cand = store.latest()
            if cand is None:
                raise UsageError("registry %s is empty" % store.file)
        else:
            cand = _load_record(a.candidate, store)
        if a.against is not None:
            base = _load_record(a.against, store)
            diff = diff_records(cand, base, tol=a.tol,
                                loglik_rtol=a.loglik_rtol)
        else:
            if store is None:
                raise UsageError("no --against and no runs dir")
            diff = diff_against_history(cand, store, tol=a.tol,
                                        loglik_rtol=a.loglik_rtol,
                                        best_n=a.best_n)
    except UsageError as e:
        print("error: %s" % e, file=sys.stderr)
        return 2
    if a.json:
        print(json.dumps(diff))
    else:
        print_diff(diff)
    return 0 if diff["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Process-local structured tracing for dispatch/compile/convergence events.

The performance contract of this framework lives on events nothing records:
each program launch through the axon tunnel costs ~60-100 ms, a silent
recompile from shape churn costs seconds, and convergence decisions ride on
f32 loglik deltas vs ``noise_floor_for``.  The ``Tracer`` turns those into a
structured event stream — an in-memory list and, optionally, a JSONL file
(one event per line, flushed eagerly so a crashed fit still leaves a trace).

Event schema (every event):
    ``t``     monotonic ``time.perf_counter()`` seconds (NOT wall clock —
              only deltas within one trace are meaningful)
    ``kind``  one of:
      ``fit``       one per ``api.fit`` call: engine, N/T/k, wall, n_iters
      ``dispatch``  one per program launch: ``program`` (logical name),
                    ``key`` (shape signature), ``dur`` (seconds to return —
                    with ``barrier=true`` this includes the device→host
                    transfer, i.e. true execution wall; otherwise it is
                    async-dispatch overhead only), ``first_call`` (first
                    launch of this program+key in the process: wall time is
                    the compile proxy — the tunnel exposes no other),
                    ``recompile`` (same program, second distinct key),
                    optional ``n_iters``, ``error``
      ``transfer``  explicit device→host or host→device movement
      ``chunk``     per fused-EM chunk: engine, iter range, logliks, deltas
                    vs the noise floor
      ``freeze``    batched engine per-problem state transition
                    (converged/diverged)
      ``health``    a ``robust.health.HealthEvent``, timestamped
      ``cost``      static XLA cost model for a program (opt-in)
      ``span``      generic timed region (``name``, ``dur``)
      ``request``   one per answered serving request: ``trace_id``, the
                    per-stage latency waterfall (``stages``: adjacent
                    deltas of ONE monotonic clock, telescoping exactly to
                    ``e2e``), optional ``replay``/``dedup`` flags

The full kind inventory lives in ``EVENT_KINDS`` — ``summarize()`` and the
live plane route on these strings, so a typo'd kind silently vanishes from
every report.  ``tests/test_trace_schema.py`` AST-audits every
``emit(kind)``/``{"kind": ...}`` literal in the package against it.

Activation: ``fit(telemetry=...)`` pushes a tracer for the duration of the
fit; ``DFM_TRACE=<path>`` makes a process-ambient file tracer that
instrumented code picks up when no explicit tracer is active.  With neither,
``current_tracer()`` is None and every instrumentation site reduces to one
``is None`` check — no event objects, no clock reads, no host syncs.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import IO, List, Optional, Union

from .cost import RecompileDetector, global_detector

__all__ = ["Tracer", "current_tracer", "activate", "fit_tracer",
           "shape_key", "EVENT_KINDS", "new_trace_id", "request_clock",
           "current_request", "request_span", "born_request",
           "finish_request", "set_ambient"]

# Closed schema of event kinds the obs stack routes on.  summarize() /
# LivePlane.record_event / to_chrome all branch on these strings; a kind
# not in this set is an event NOTHING will ever aggregate.  Extending the
# schema means adding the kind here AND teaching obs/metrics.record_event
# + obs/report what to do with it (tests/test_trace_schema.py enforces
# membership for every literal in the package).
EVENT_KINDS = frozenset({
    "fit", "dispatch", "transfer", "chunk", "freeze", "health", "cost",
    "span", "query", "tick", "tenant", "page", "daemon", "maintenance",
    "compile_cache", "advice", "panel_reupload", "fused_fallback",
    "request", "tune",
})


def _json_default(o):
    # numpy scalars/arrays and anything else non-JSON: best-effort coercion.
    for attr in ("item", "tolist"):
        f = getattr(o, attr, None)
        if f is not None:
            try:
                return f()
            except Exception:
                break
    return repr(o)


def shape_key(*parts) -> str:
    """Canonical shape-signature string for dispatch/cost events.

    Accepts ints, strings, dtypes, arrays (contributes ``NxTx..xdtype``).
    Include every static argument that forces a distinct executable —
    notably ``n_iters`` of a fused chunk: a tail chunk of a different
    length IS a new program to XLA, and should show up as a recompile.
    """
    toks = []
    for p in parts:
        shp = getattr(p, "shape", None)
        if shp is not None:
            dt = getattr(p, "dtype", "")
            toks.append("x".join(str(d) for d in shp) + (f"x{dt}" if dt else ""))
        else:
            toks.append(str(p))
    return "/".join(toks)


class Tracer:
    """Collects events in memory and (optionally) appends them to a JSONL file.

    Parameters
    ----------
    path:
        JSONL output file, or None for in-memory only.
    capture_costs:
        Capture static XLA program costs (``obs.cost.program_cost``) at
        instrumented lower points.  Defaults to ``DFM_TRACE_COST=1``.
        Off by default: lower+compile is itself compile-scale work.
    detector:
        Recompile detector; defaults to the process-local singleton so
        "first_call" / "recompile" reflect the process's real compile
        cache, not this tracer's lifetime.  Tests inject a fresh one.
    max_bytes:
        Size-capped rotation for the JSONL file: when the file exceeds
        this many bytes after a write, it is rotated shift-style
        (``path`` -> ``path.1`` -> ... -> ``path.<keep>``, oldest
        dropped) and a fresh file is opened.  Off (None) by default — a
        soak sets ``DFM_TRACE_MAX_MB`` and ``obs.report`` accepts the
        rotated files in order.  Rotation caps the FILE only; the
        in-memory ``events`` list semantics are unchanged.
    keep:
        How many rotated-out files to retain (default 3).
    """

    def __init__(self, path: Optional[str] = None,
                 capture_costs: Optional[bool] = None,
                 detector: Optional[RecompileDetector] = None,
                 max_bytes: Optional[int] = None, keep: int = 3):
        self.path = path
        self.events: List[dict] = []
        self.capture_costs = (os.environ.get("DFM_TRACE_COST") == "1"
                              if capture_costs is None else capture_costs)
        self._detector = detector if detector is not None else global_detector()
        self._fh: Optional[IO[str]] = None
        self._lock = threading.Lock()
        self._depth = 0          # dispatch-span reentrancy (see dispatch())
        self._costed = set()     # (program, key) pairs already cost-captured
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.keep = max(1, int(keep))
        self.rotations = 0
        if path:
            self._fh = open(path, "a", encoding="utf-8")

    # -- event sinks -----------------------------------------------------

    def emit(self, kind: str, *, t: Optional[float] = None, **payload) -> dict:
        ev = {"t": time.perf_counter() if t is None else t, "kind": kind}
        ev.update(payload)
        with self._lock:
            self.events.append(ev)
            if self._fh is not None:
                self._fh.write(json.dumps(ev, default=_json_default) + "\n")
                self._fh.flush()
                if (self.max_bytes is not None
                        and self._fh.tell() > self.max_bytes):
                    self._rotate_locked()
        # Feed the always-on live plane AFTER releasing the (non-reentrant)
        # lock: the plane may mirror slo_burn events back through this
        # tracer, and its own reentrancy guard drops those echoes.  Lazy
        # import (sys.modules hit after the first call) so ``python -m
        # dfm_tpu.obs.live`` doesn't double-import its own module.
        from . import live as _live
        _live.observe(ev)
        return ev

    def _rotate_locked(self) -> None:
        """Shift-rotate the JSONL file (caller holds ``self._lock``)."""
        self._fh.close()
        last = f"{self.path}.{self.keep}"
        if os.path.exists(last):
            os.remove(last)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._fh = open(self.path, "a", encoding="utf-8")
        self.rotations += 1

    @contextmanager
    def dispatch(self, program: str, key: str, *, barrier: bool = False,
                 n_iters: Optional[int] = None, **payload):
        """Span around one program launch (plus its result transfer when the
        caller transfers inside the block — pass ``barrier=True`` then, so
        the report can tell true execution wall from async-launch overhead).

        Reentrancy: the OUTERMOST active dispatch span owns the record.
        Driver loops (``run_em_chunked``, the guard's ``_dispatch``, the
        batched engine) wrap the low-level callables, which carry their own
        spans for direct use (bench, dryrun) — suppressing nested spans
        keeps each physical launch counted exactly once.

        The owning span yields a mutable dict merged into the event at
        exit, so values only known after the d2h read (e.g. the realized
        iteration count of a fused while-loop fit) can be recorded:
        ``with tr.dispatch(...) as rec: ...; rec["n_iters"] = n``.
        Suppressed (nested) spans yield None.
        """
        if self._depth > 0:
            yield None
            return
        self._depth += 1
        status = self._detector.note(program, key)
        t0 = time.perf_counter()
        err = None
        extra: dict = {}
        try:
            yield extra
        except BaseException as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._depth -= 1
            ev = {"program": program, "key": key,
                  "dur": time.perf_counter() - t0, "barrier": bool(barrier),
                  "first_call": status != "cached",
                  "recompile": status == "recompile"}
            if n_iters is not None:
                ev["n_iters"] = int(n_iters)
            if err is not None:
                ev["error"] = err
            ev.update(payload)
            ev.update(extra)
            self.emit("dispatch", t=t0, **ev)

    @contextmanager
    def span(self, name: str, **payload):
        t0 = time.perf_counter()
        try:
            yield None
        finally:
            self.emit("span", t=t0, name=name,
                      dur=time.perf_counter() - t0, **payload)

    def maybe_cost(self, program: str, key: str, jitted, *args, **kwargs):
        """Capture the static cost of ``jitted`` at this signature, once per
        (program, key), when cost capture is on.  Never raises."""
        if not self.capture_costs or (program, key) in self._costed:
            return
        self._costed.add((program, key))
        from .cost import program_cost
        c = program_cost(jitted, *args, **kwargs)
        if c:
            self.emit("cost", program=program, key=key, **c)

    # -- lifecycle -------------------------------------------------------

    def summary(self) -> dict:
        from .report import summarize
        return summarize(self.events)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- activation ----------------------------------------------------------
#
# A thread-local stack of active tracers; instrumented code asks
# current_tracer() and does nothing when it returns None.  The bottom of the
# stack is lazily seeded from DFM_TRACE so `DFM_TRACE=t.jsonl python
# bench.py` traces without code changes.  Pushing None masks the ambient
# tracer (fit(telemetry=False)).

_tls = threading.local()
_ENV_SENTINEL = object()
_env_tracer: Union[object, None, Tracer] = _ENV_SENTINEL


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _ambient() -> Optional[Tracer]:
    global _env_tracer
    if _env_tracer is _ENV_SENTINEL:
        path = os.environ.get("DFM_TRACE")
        if path:
            mb = os.environ.get("DFM_TRACE_MAX_MB")
            max_bytes = int(float(mb) * 1e6) if mb else None
            _env_tracer = Tracer(path, max_bytes=max_bytes)
        else:
            _env_tracer = None
    return _env_tracer


def current_tracer() -> Optional[Tracer]:
    """The active tracer, or None (the zero-overhead answer)."""
    st = _stack()
    if st:
        return st[-1]
    return _ambient()


@contextmanager
def activate(tracer: Optional[Tracer]):
    """Make ``tracer`` current for the block; ``activate(None)`` suppresses
    any ambient DFM_TRACE tracer (telemetry hard-off)."""
    st = _stack()
    st.append(tracer)
    try:
        yield tracer
    finally:
        st.pop()


def fit_tracer(telemetry) -> tuple:
    """Resolve ``fit(telemetry=...)`` to ``(tracer, owned)``.

    - None: inherit whatever is current (possibly DFM_TRACE); not owned.
    - False: telemetry hard-off (tracer None pushed over ambient).
    - True: fresh in-memory tracer; owned (summary attached to the result).
    - str / PathLike: fresh file tracer; owned (closed after the fit).
    - Tracer: use as-is; not owned (caller controls lifetime/close).
    """
    if telemetry is None:
        return current_tracer(), False
    if telemetry is False:
        return None, False
    if telemetry is True:
        return Tracer(), True
    if isinstance(telemetry, Tracer):
        return telemetry, False
    return Tracer(os.fspath(telemetry)), True


def set_ambient(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install ``tracer`` as the process-ambient tracer (the one every
    thread's ``current_tracer()`` falls back to) and return the previous
    ambient.  ``activate()`` is thread-local — a daemon's pump thread never
    sees a tracer the benchmark pushed on the main thread; this is the
    cross-thread knob.  ``set_ambient(None)`` restores the untraced default
    (and masks any ``DFM_TRACE`` seed until the process restarts)."""
    global _env_tracer
    prev = _env_tracer
    _env_tracer = tracer
    return None if prev is _ENV_SENTINEL else prev


# -- request-scoped spans -------------------------------------------------
#
# A request trace is a plain mutable dict born where a serving request is
# born (DaemonClient.submit / fleet.submit / session.update) and carried BY
# REFERENCE through the queue, the tick, and the ack.  Each seam writes one
# absolute timestamp from request_clock() into it; the finisher turns the
# telescoping adjacent deltas into the "request" event's stage waterfall —
# the stages sum to the measured e2e EXACTLY because every boundary is a
# single reading of a single clock.  request_clock() is CLOCK_MONOTONIC:
# system-wide per host (unlike a perf_counter epoch, which on some
# platforms is per-process), so stamps survive the daemon's cross-process
# seams — kill-9 journal replay and --takeover handoff — the same way the
# handoff's t_stop does.  (On Linux perf_counter IS CLOCK_MONOTONIC, which
# is what lets request stamps and ordinary event ``t`` values share one
# timeline in obs.report --chrome.)

_request_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "dfm_request", default=None)


def new_trace_id() -> str:
    """A fresh 16-hex request id (collision-safe at fleet scale, short
    enough to read in a waterfall)."""
    return uuid.uuid4().hex[:16]


def request_clock() -> float:
    """The one clock every request stamp uses (see module comment)."""
    return time.clock_gettime(time.CLOCK_MONOTONIC)


def current_request() -> Optional[dict]:
    """The request trace dict in flight on this context, or None."""
    return _request_ctx.get()


@contextmanager
def request_span(trace: Optional[dict] = None, *, replay: bool = False):
    """Bind a request trace dict for the block (contextvar — survives
    threads only via explicit propagation, which the daemon/fleet do by
    carrying the dict itself).  With ``trace=None`` a fresh context is
    born: ``{"id": new_trace_id(), "t_send": request_clock()}``.
    ``replay=True`` stamps the context so every downstream span and the
    final waterfall carry ``replay: true`` (journal-replay requests must
    never be mistaken for live traffic)."""
    if trace is None:
        trace = {"id": new_trace_id(), "t_send": request_clock()}
    if replay:
        trace["replay"] = True
    tok = _request_ctx.set(trace)
    try:
        yield trace
    finally:
        _request_ctx.reset(tok)


def born_request(trace: Optional[dict] = None) -> dict:
    """Resolve the request context for a serving entry point: the dict
    passed explicitly (daemon → fleet), else the one bound by an enclosing
    ``request_span``, else a fresh birth."""
    if trace is not None:
        return trace
    cur = _request_ctx.get()
    if cur is not None:
        return cur
    return {"id": new_trace_id(), "t_send": request_clock()}


def finish_request(trace: dict, *, tenant: str = "", session: str = "",
                   **payload) -> dict:
    """Turn a stamped request trace into the ``request`` event payload.

    Stages are the adjacent deltas of whatever boundary stamps the trace
    accumulated, in pipeline order — absent seams simply contribute no
    stage, so a lone ``session.update`` waterfall has three stages while a
    daemon round-trip has six.  By construction
    ``sum(stages.values()) == e2e`` to float precision.
    """
    order = ("t_send", "t_admit", "t_batch", "t_tick0", "t_launch",
             "t_read", "t_ack")
    # Stage name keyed by the boundary that ENDS it; t_tick0 is "the tick
    # picked this request up", so the stage before it is queue_wait unless
    # the daemon stamped batch extraction (then it splits into queue_wait
    # + batch_form).
    stage_of = {"t_admit": "client_send", "t_batch": "queue_wait",
                "t_tick0": "queue_wait", "t_launch": "dispatch",
                "t_read": "d2h", "t_ack": "ack"}
    present = [k for k in order if k in trace]
    stages = {}
    for a, b in zip(present, present[1:]):
        name = ("batch_form" if (b == "t_tick0" and a == "t_batch")
                else stage_of[b])
        stages[name] = float(trace[b]) - float(trace[a])
    ev = {"trace_id": trace.get("id", ""), "stages": stages,
          "e2e": (float(trace[present[-1]]) - float(trace[present[0]])
                  if len(present) > 1 else 0.0)}
    if tenant:
        ev["tenant"] = str(tenant)
    if session:
        ev["session"] = str(session)
    if trace.get("replay"):
        ev["replay"] = True
    ev.update(payload)
    return ev

"""Auto-tuning advisor: ranked fit plans from the calibrated cost model
(``python -m dfm_tpu.obs.advise --shape N,T,K`` — jax-free CLI).

Given a panel shape, enumerate the candidate execution plans the fit
drivers expose (fused while-loop vs chunked EM, ``fused_chunk`` size,
pipeline depth, tail bucketing), predict each plan's wall with the
``obs.cost`` model calibrated from the profile records in the run
registry (``obs.profile``), and rank them.  ``fit(auto=True)`` applies
the top plan and emits an ``advice`` trace event with predicted vs
realized wall, which ``obs.regress`` gates as ``advice_rel_err`` — the
model drifts, the gate fires, you re-profile.

With an empty registry the CLI still ranks (device priors, flagged
``calibrated: false``); ``fit(auto=True)`` instead falls back to the
default knobs with a warning — auto-tuning never runs on pure priors.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

__all__ = ["advise", "candidate_plans", "main"]


def candidate_plans(chunk: int = 8) -> List[dict]:
    """The plan grid: every knob combination the advisor considers.
    Kept small and structured — each row maps 1:1 onto fit() knobs
    (``fused=``/``pipeline=``/backend ``fused_chunk``)."""
    return [
        {"engine": "fused", "fused_chunk": chunk, "depth": 1,
         "bucket": False},
        {"engine": "fused", "fused_chunk": 2 * chunk, "depth": 1,
         "bucket": False},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 1,
         "bucket": False},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 2,
         "bucket": False},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 2,
         "bucket": True},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 4,
         "bucket": True},
    ]


def advise(N: int, T: int, k: int, *, max_iters: int = 50, chunk: int = 8,
           runs: Optional[str] = None,
           device: Optional[str] = None) -> dict:
    """Rank candidate plans for shape (N, T, k); deterministic given a
    fixed profile registry.  ``runs=None`` resolves the ambient registry
    (``DFM_RUNS`` / ``.dfm_runs``); reading never creates anything."""
    from .cost import fit_cost_model
    from .store import RunStore, runs_dir

    d = runs_dir(runs)
    profiles: List[dict] = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    model = fit_cost_model(profiles, device=device)

    plans = []
    for cand in candidate_plans(chunk):
        pred = model.predict(N, T, k, max_iters, engine=cand["engine"],
                             chunk=cand["fused_chunk"],
                             depth=cand["depth"], bucket=cand["bucket"])
        plans.append({**cand, **pred})
    # Deterministic rank: predicted wall, then the stable knob tuple.
    plans.sort(key=lambda p: (p["predicted_wall_s"], p["engine"],
                              p["depth"], p["fused_chunk"], p["bucket"]))
    for i, p in enumerate(plans):
        p["rank"] = i + 1
    return {"shape": {"N": int(N), "T": int(T), "k": int(k)},
            "max_iters": int(max_iters), "device": model.device,
            "calibrated": model.calibrated,
            "n_profiles": model.n_profiles, "plans": plans,
            "model": model.to_dict()}


def _plan_str(p: dict) -> str:
    if p["engine"] == "fused":
        return f"fused (chunk={p['fused_chunk']})"
    s = f"chunked (chunk={p['fused_chunk']}, depth={p['depth']}"
    return s + (", bucket)" if p["bucket"] else ")")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.advise",
        description="Rank fit plans for a shape via the calibrated cost "
                    "model (profiles from the run registry).")
    ap.add_argument("--shape", required=True, metavar="N,T,K")
    ap.add_argument("--max-iters", type=int, default=50)
    ap.add_argument("--chunk", type=int, default=8,
                    help="base fused_chunk for the plan grid")
    ap.add_argument("--runs", default=None,
                    help="registry dir (default: DFM_RUNS or .dfm_runs)")
    ap.add_argument("--device", default=None,
                    help="device class to calibrate for (tpu/cpu; "
                         "default: the latest profile's)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    try:
        N, T, k = (int(x) for x in args.shape.split(","))
    except ValueError:
        print(f"error: --shape wants N,T,K, got {args.shape!r}",
              file=sys.stderr)
        return 2
    res = advise(N, T, k, max_iters=args.max_iters, chunk=args.chunk,
                 runs=args.runs, device=args.device)
    if not res["calibrated"]:
        print("warning: no profile records in the registry — predictions "
              "use device priors only; run `python -m dfm_tpu.obs.profile "
              f"--shape {args.shape}` to calibrate", file=sys.stderr)
    if args.json:
        json.dump(res, sys.stdout, indent=2, default=str)
        print()
        return 0
    sh = res["shape"]
    cal = ("calibrated from %d profile(s)" % res["n_profiles"]
           if res["calibrated"] else "PRIORS ONLY")
    print(f"advise N={sh['N']} T={sh['T']} k={sh['k']} "
          f"max_iters={res['max_iters']} [{res['device']}, {cal}]")
    for p in res["plans"]:
        mark = " (measured anchor)" if p.get("anchored") else ""
        print(f"  #{p['rank']}: {_plan_str(p):34s} "
              f"predicted {p['predicted_wall_s']:.3f}s{mark}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

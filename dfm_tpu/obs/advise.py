"""Auto-tuning advisor: ranked fit plans from the calibrated cost model
(``python -m dfm_tpu.obs.advise --shape N,T,K`` — jax-free CLI).

Given a panel shape, enumerate the candidate execution plans the fit
drivers expose (fused while-loop vs chunked EM, ``fused_chunk`` size,
pipeline depth, tail bucketing), predict each plan's wall with the
``obs.cost`` model calibrated from the profile records in the run
registry (``obs.profile``), and rank them.  ``fit(auto=True)`` applies
the top plan and emits an ``advice`` trace event with predicted vs
realized wall, which ``obs.regress`` gates as ``advice_rel_err`` — the
model drifts, the gate fires, you re-profile.

With an empty registry the CLI still ranks (device priors, flagged
``calibrated: false``); ``fit(auto=True)`` instead falls back to the
default knobs with a warning — auto-tuning never runs on pure priors.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

__all__ = ["advise", "advise_fleet", "advise_jobs", "candidate_plans",
           "main"]


def candidate_plans(chunk: int = 8) -> List[dict]:
    """The plan grid: every knob combination the advisor considers.
    Kept small and structured — each row maps 1:1 onto fit() knobs
    (``fused=``/``pipeline=``/backend ``fused_chunk``/``filter=``);
    ``filter`` is the time-scan engine (``seq`` = sequential scan,
    ``pit_qr`` = parallel-in-time QR — the long-T log-depth play,
    ``lowrank`` = rank-r computation-aware downdate — the wide-k play:
    only r x r linalg in the scans)."""
    return [
        {"engine": "fused", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "seq"},
        {"engine": "fused", "fused_chunk": 2 * chunk, "depth": 1,
         "bucket": False, "filter": "seq"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "seq"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 2,
         "bucket": False, "filter": "seq"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 2,
         "bucket": True, "filter": "seq"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 4,
         "bucket": True, "filter": "seq"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "pit_qr"},
        {"engine": "fused", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "pit_qr"},
        {"engine": "chunked", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "lowrank"},
        {"engine": "fused", "fused_chunk": chunk, "depth": 1,
         "bucket": False, "filter": "lowrank"},
    ]


def advise(N: int, T: int, k: int, *, max_iters: int = 50, chunk: int = 8,
           runs: Optional[str] = None,
           device: Optional[str] = None) -> dict:
    """Rank candidate plans for shape (N, T, k); deterministic given a
    fixed profile registry.  ``runs=None`` resolves the ambient registry
    (``DFM_RUNS`` / ``.dfm_runs``); reading never creates anything."""
    from .cost import fit_cost_model
    from .store import RunStore, runs_dir

    d = runs_dir(runs)
    profiles: List[dict] = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    model = fit_cost_model(profiles, device=device)

    plans = []
    for cand in candidate_plans(chunk):
        pred = model.predict(N, T, k, max_iters, engine=cand["engine"],
                             chunk=cand["fused_chunk"],
                             depth=cand["depth"], bucket=cand["bucket"],
                             filter=cand.get("filter", "seq"))
        plans.append({**cand, **pred})
    # Evidence gate: an engine-switch plan (pit_qr / lowrank) whose
    # family has NO measured profiles may never undercut the best
    # measured plan at this shape — its prediction is pure structural
    # prior, and acting on it forces a fresh compile of an engine nobody
    # timed (the one cost the model can't see).  Clamp such plans to the
    # best anchored wall; the tie-break below then keeps the measured
    # plan on top.  A profiled family (calibrated scale or any measured
    # wall) competes on its numbers, anywhere in shape space.
    anchored = [p["predicted_wall_s"] for p in plans if p.get("anchored")]
    if anchored:
        floor = min(anchored)
        for p in plans:
            flt = p.get("filter", "seq")
            if (flt != "seq" and not p.get("anchored")
                    and not getattr(model, f"{flt}_calibrated", False)
                    and p["predicted_wall_s"] < floor):
                p["predicted_wall_s"] = floor
                p["evidence_clamped"] = True
    # Deterministic rank: predicted wall, then the stable knob tuple.
    # Ties prefer the sequential scan FIRST (equal predictions keep the
    # default engine — and a clamped engine-switch plan tied at the
    # anchored floor must lose to the measured plan), then the engine.
    plans.sort(key=lambda p: (p["predicted_wall_s"],
                              p.get("filter", "seq") != "seq",
                              p["engine"],
                              p.get("filter", "seq"), p["depth"],
                              p["fused_chunk"], p["bucket"]))
    for i, p in enumerate(plans):
        p["rank"] = i + 1
    return {"shape": {"N": int(N), "T": int(T), "k": int(k)},
            "max_iters": int(max_iters), "device": model.device,
            "calibrated": model.calibrated,
            "n_profiles": model.n_profiles, "plans": plans,
            "model": model.to_dict()}


def advise_jobs(shapes, *, max_iters: int = 50, chunk: int = 8,
                runs: Optional[str] = None,
                device: Optional[str] = None) -> dict:
    """Rank bucket LAYOUTS for a mixed-shape job mix (the scheduler's
    planning problem — see ``sched.buckets``): for each candidate bucket
    count, run the cost-model DP and predict the mix's aggregate wall.
    ``shapes`` is a list of (N, T, k) triples, one per job.  Deterministic
    given a fixed profile registry: ties prefer fewer executables, then
    the smaller bucket-dims tuple.  Each bucket carries the evidence-gated
    ``filter`` engine ``fleet.admission.choose_engine`` would route it to
    (always "info" on an uncalibrated registry)."""
    from ..fleet.admission import choose_engine
    from ..sched.buckets import plan_buckets
    from .cost import fit_cost_model
    from .store import RunStore, runs_dir

    d = runs_dir(runs)
    profiles: List[dict] = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    model = fit_cost_model(profiles, device=device)

    tnk = [(int(T), int(N), int(k)) for (N, T, k) in shapes]
    iters = [int(max_iters)] * len(tnk)
    layouts, seen = [], set()
    for mb in range(1, min(len(tnk), 4) + 1):
        plan = plan_buckets(tnk, iters, max_buckets=mb, model=model,
                            chunk=chunk)
        sig = tuple(sorted((b.dims, b.jobs) for b in plan.buckets))
        if sig in seen:     # a larger budget the DP declined to use
            continue
        seen.add(sig)
        layouts.append({
            "max_buckets": mb, "n_buckets": len(plan.buckets),
            "buckets": [{"dims": {"T": b.dims[0], "N": b.dims[1],
                                  "k": b.dims[2]},
                         "jobs": list(b.jobs), "cap": b.cap,
                         "filter": choose_engine(b.dims, int(max_iters),
                                                 model=model)}
                        for b in plan.buckets],
            "pad_waste_frac": plan.pad_waste_frac,
            "predicted_wall_s": plan.predicted_wall_s})
    layouts.sort(key=lambda l: (l["predicted_wall_s"], l["n_buckets"],
                                tuple(tuple(b["dims"].values())
                                      for b in l["buckets"])))
    for i, l in enumerate(layouts):
        l["rank"] = i + 1
    return {"jobs": [{"N": N, "T": T, "k": k} for (N, T, k) in shapes],
            "max_iters": int(max_iters), "device": model.device,
            "calibrated": model.calibrated,
            "n_profiles": model.n_profiles, "layouts": layouts,
            "model": model.to_dict()}


def advise_fleet(shapes, *, tick_iters: int = 5,
                 runs: Optional[str] = None,
                 device: Optional[str] = None) -> dict:
    """Rank capacity-CLASS layouts for a serving fleet (the
    ``fleet.open_fleet`` admission problem — see
    ``sched.plan_capacity_classes``): each class is one resident batched
    buffer costing ONE fused ``serve_update`` dispatch per tick, so the
    sweep trades per-tick padded-EM waste against extra executables +
    dispatches.  ``shapes`` is a list of per-tenant (N, T_capacity, k)
    triples; ``tick_iters`` the per-tick warm-EM budget.  Deterministic
    given a fixed profile registry: ties prefer fewer classes, then the
    smaller class-dims tuple.  Each class carries the evidence-gated
    ``filter`` engine ``fleet.admission.choose_engine`` would route it to
    (what ``open_fleet(filter="auto")`` compiles; always "info" on an
    uncalibrated registry)."""
    from ..fleet.admission import choose_engine
    from ..sched.buckets import plan_capacity_classes
    from .cost import fit_cost_model
    from .store import RunStore, runs_dir

    d = runs_dir(runs)
    profiles: List[dict] = []
    if d is not None:
        profiles = [r for r in RunStore(d).load()
                    if r.get("kind") == "profile"]
    model = fit_cost_model(profiles, device=device)

    tnk = [(int(T), int(N), int(k)) for (N, T, k) in shapes]
    iters = [int(tick_iters)] * len(tnk)
    layouts, seen = [], set()
    for mc in range(1, min(len(tnk), 4) + 1):
        plan = plan_capacity_classes(tnk, iters, max_classes=mc,
                                     model=model)
        sig = tuple(sorted((b.dims, b.jobs) for b in plan.buckets))
        if sig in seen:     # a larger budget the DP declined to use
            continue
        seen.add(sig)
        layouts.append({
            "max_classes": mc, "n_classes": len(plan.buckets),
            "classes": [{"dims": {"T": b.dims[0], "N": b.dims[1],
                                  "k": b.dims[2]},
                         "tenants": list(b.jobs),
                         "filter": choose_engine(b.dims, int(tick_iters),
                                                 model=model)}
                        for b in plan.buckets],
            "pad_waste_frac": plan.pad_waste_frac,
            "predicted_tick_wall_s": plan.predicted_wall_s})
    layouts.sort(key=lambda l: (l["predicted_tick_wall_s"], l["n_classes"],
                                tuple(tuple(c["dims"].values())
                                      for c in l["classes"])))
    for i, l in enumerate(layouts):
        l["rank"] = i + 1
    return {"tenants": [{"N": N, "T": T, "k": k} for (N, T, k) in shapes],
            "tick_iters": int(tick_iters), "device": model.device,
            "calibrated": model.calibrated,
            "n_profiles": model.n_profiles, "layouts": layouts,
            "model": model.to_dict()}


def _parse_jobs(spec: str):
    """``N,T,K[xC]`` triples joined by ``;`` — e.g. ``20,60,2;26,80,2x3``
    is one (20, 60, 2) job plus three (26, 80, 2) jobs."""
    shapes = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        mult = 1
        if "x" in part.rsplit(",", 1)[-1]:
            part, m = part.rsplit("x", 1)
            mult = int(m)
        N, T, k = (int(x) for x in part.split(","))
        shapes.extend([(N, T, k)] * mult)
    if not shapes:
        raise ValueError("empty job spec")
    return shapes


def _plan_str(p: dict) -> str:
    eng = p["engine"]
    if p.get("filter", "seq") != "seq":
        eng += f"+{p['filter']}"
    if p["engine"] == "fused":
        return f"{eng} (chunk={p['fused_chunk']})"
    s = f"{eng} (chunk={p['fused_chunk']}, depth={p['depth']}"
    return s + (", bucket)" if p["bucket"] else ")")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dfm_tpu.obs.advise",
        description="Rank fit plans for a shape via the calibrated cost "
                    "model (profiles from the run registry).")
    what = ap.add_mutually_exclusive_group(required=True)
    what.add_argument("--shape", metavar="N,T,K")
    what.add_argument("--jobs", metavar="N,T,K[xC];...",
                      help="rank bucket layouts for a mixed-shape job mix "
                           "(the sched.submit planning problem) instead of "
                           "single-fit plans")
    what.add_argument("--fleet", metavar="N,T,K[xC];...",
                      help="rank serving capacity-class layouts for a "
                           "tenant mix (T = per-tenant panel capacity; "
                           "the fleet.open_fleet admission problem)")
    ap.add_argument("--max-iters", type=int, default=50)
    ap.add_argument("--chunk", type=int, default=8,
                    help="base fused_chunk for the plan grid")
    ap.add_argument("--tick-iters", type=int, default=5,
                    help="per-tick warm-EM budget for --fleet layouts")
    ap.add_argument("--runs", default=None,
                    help="registry dir (default: DFM_RUNS or .dfm_runs)")
    ap.add_argument("--device", default=None,
                    help="device class to calibrate for (tpu/cpu; "
                         "default: the latest profile's)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.fleet is not None:
        try:
            shapes = _parse_jobs(args.fleet)
        except ValueError:
            print(f"error: --fleet wants N,T,K[xC] triples joined by "
                  f"';', got {args.fleet!r}", file=sys.stderr)
            return 2
        res = advise_fleet(shapes, tick_iters=args.tick_iters,
                           runs=args.runs, device=args.device)
        if not res["calibrated"]:
            big = max(shapes)
            print("warning: no profile records in the registry — "
                  "predictions use device priors only; run `python -m "
                  "dfm_tpu.obs.profile --shape "
                  f"{big[0]},{big[1]},{big[2]}` to calibrate",
                  file=sys.stderr)
        if args.json:
            json.dump(res, sys.stdout, indent=2, default=str)
            print()
            return 0
        cal = ("calibrated from %d profile(s)" % res["n_profiles"]
               if res["calibrated"] else "PRIORS ONLY")
        print(f"advise fleet of {len(res['tenants'])} tenants "
              f"tick_iters={res['tick_iters']} [{res['device']}, {cal}]")
        for l in res["layouts"]:
            dims = " + ".join(
                f"({c['dims']['T']},{c['dims']['N']},{c['dims']['k']})"
                f"x{len(c['tenants'])}"
                + ("" if c.get("filter", "info") == "info"
                   else f"[{c['filter']}]")
                for c in l["classes"])
            print(f"  #{l['rank']}: {l['n_classes']} class"
                  f"{'es' if l['n_classes'] != 1 else ''} {dims:40s} "
                  f"predicted tick {l['predicted_tick_wall_s']:.3f}s, "
                  f"pad waste {100 * l['pad_waste_frac']:.1f}%")
        return 0
    if args.jobs is not None:
        try:
            shapes = _parse_jobs(args.jobs)
        except ValueError:
            print(f"error: --jobs wants N,T,K[xC] triples joined by ';', "
                  f"got {args.jobs!r}", file=sys.stderr)
            return 2
        res = advise_jobs(shapes, max_iters=args.max_iters,
                          chunk=args.chunk, runs=args.runs,
                          device=args.device)
        if not res["calibrated"]:
            big = max(shapes)
            print("warning: no profile records in the registry — "
                  "predictions use device priors only; run `python -m "
                  "dfm_tpu.obs.profile --shape "
                  f"{big[0]},{big[1]},{big[2]}` to calibrate",
                  file=sys.stderr)
        if args.json:
            json.dump(res, sys.stdout, indent=2, default=str)
            print()
            return 0
        cal = ("calibrated from %d profile(s)" % res["n_profiles"]
               if res["calibrated"] else "PRIORS ONLY")
        print(f"advise {len(res['jobs'])} jobs "
              f"max_iters={res['max_iters']} [{res['device']}, {cal}]")
        for l in res["layouts"]:
            dims = " + ".join(
                f"({b['dims']['T']},{b['dims']['N']},{b['dims']['k']})"
                f"x{len(b['jobs'])}"
                + ("" if b.get("filter", "info") == "info"
                   else f"[{b['filter']}]")
                for b in l["buckets"])
            print(f"  #{l['rank']}: {l['n_buckets']} bucket"
                  f"{'s' if l['n_buckets'] != 1 else ''} {dims:40s} "
                  f"predicted {l['predicted_wall_s']:.3f}s, "
                  f"pad waste {100 * l['pad_waste_frac']:.1f}%")
        return 0
    try:
        N, T, k = (int(x) for x in args.shape.split(","))
    except ValueError:
        print(f"error: --shape wants N,T,K, got {args.shape!r}",
              file=sys.stderr)
        return 2
    res = advise(N, T, k, max_iters=args.max_iters, chunk=args.chunk,
                 runs=args.runs, device=args.device)
    if not res["calibrated"]:
        print("warning: no profile records in the registry — predictions "
              "use device priors only; run `python -m dfm_tpu.obs.profile "
              f"--shape {args.shape}` to calibrate", file=sys.stderr)
    if args.json:
        json.dump(res, sys.stdout, indent=2, default=str)
        print()
        return 0
    sh = res["shape"]
    cal = ("calibrated from %d profile(s)" % res["n_profiles"]
           if res["calibrated"] else "PRIORS ONLY")
    print(f"advise N={sh['N']} T={sh['T']} k={sh['k']} "
          f"max_iters={res['max_iters']} [{res['device']}, {cal}]")
    for p in res["plans"]:
        mark = " (measured anchor)" if p.get("anchored") else ""
        print(f"  #{p['rank']}: {_plan_str(p):34s} "
              f"predicted {p['predicted_wall_s']:.3f}s{mark}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

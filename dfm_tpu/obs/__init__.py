"""Telemetry: dispatch/compile tracing, convergence telemetry, cost reports.

Zero-overhead when off: every instrumentation site is gated on
``current_tracer() is None``.  Activate with ``fit(telemetry=...)`` or
``DFM_TRACE=<path>``; summarize with ``python -m dfm_tpu.obs.report``.

Perf observatory (PR 4): ``obs.store`` is the persistent run registry
(``DFM_RUNS``), ``obs.regress`` the cross-run regression gate —
``python -m dfm_tpu.obs.regress`` / ``report --diff``.

Self-calibrating cost observatory (PR 7): ``obs.profile`` measures
per-variant program profiles into the registry
(``python -m dfm_tpu.obs.profile --shape N,T,K``), ``obs.cost`` fits the
calibrated cost model from them, and ``obs.advise`` ranks execution
plans (``python -m dfm_tpu.obs.advise --shape N,T,K``) — applied by
``fit(auto=True)``, drift-gated via the ``advice`` trace event.

Live serving telemetry plane (PR 12): ``obs.metrics`` (process-local
jax-free counters/gauges/streaming-quantile histograms + per-tenant
``Ledger``), ``obs.slo`` (error-budget burn-rate monitor + latency
anomaly detector), ``obs.live`` (the always-on singleton fed by every
tracer emit AND every untraced serving seam; flight recorder ring).
Inspect live: ``python -m dfm_tpu.obs.live [snapshot|prom]``;
disable: ``DFM_METRICS=0``.
"""

from .cost import (RecompileDetector, global_detector, program_cost,
                   reset_global_detector)
from .metrics import Histogram, Ledger, MetricsRegistry, record_event
from .slo import SLOConfig
from .trace import Tracer, activate, current_tracer, fit_tracer, shape_key

# Live-plane surface, PEP 562-lazy: ``python -m dfm_tpu.obs.live`` first
# imports this package, and an eager ``from .live import ...`` here would
# put the module in sys.modules before runpy executes it (RuntimeWarning
# + two module objects).  Same policy as the lazy ``summarize`` below.
_LIVE_NAMES = ("accounting", "observe", "plane", "reset_plane", "set_slo",
               "status")


def __getattr__(name):
    if name in _LIVE_NAMES:
        from . import live
        return getattr(live, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def summarize(events_or_path):
    """Aggregate an event stream (lazy import: keeps ``python -m
    dfm_tpu.obs.report`` from double-importing its own module via the
    package, and the package import free of report's argparse)."""
    from .report import summarize as _summarize
    return _summarize(events_or_path)


def run_store(path=None):
    """Open the run registry (lazy import, same policy as ``summarize``):
    ``RunStore`` at ``path`` or the resolved ``runs_dir()``; None when
    recording is disabled and no path is given."""
    from .store import RunStore, runs_dir
    d = path or runs_dir()
    return RunStore(d) if d is not None else None


__all__ = [
    "Tracer", "activate", "current_tracer", "fit_tracer", "shape_key",
    "RecompileDetector", "global_detector", "reset_global_detector",
    "program_cost", "summarize", "run_store",
    "Histogram", "Ledger", "MetricsRegistry", "record_event",
    "SLOConfig", "plane", "observe", "reset_plane", "set_slo",
    "accounting", "status",
]
